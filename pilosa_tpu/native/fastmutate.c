/* fastmutate: one-crossing per-op mutate for the roaring write path.
 *
 * The per-op SetBit serving shape runs container mutate + WAL record
 * build through a single CPython-extension call — where the previous
 * architecture either paid ~15-25 us of interpreted numpy per op or a
 * ctypes boundary whose per-call overhead was measured a loss at
 * container sizes (storage/native.py rationale; VERDICT r5 #1 names
 * ctypes the blocker and a real C-API extension the fix).
 *
 * This is NOT a parallel data structure: the functions operate on the
 * live pilosa_tpu.storage.roaring.Bitmap object graph (keys list,
 * Container slots, numpy buffers) under the GIL, preserving every
 * invariant the Python implementation maintains — version counter,
 * serialization-table dirty set, copy-on-write guards, the n<=4096
 * array rule, run-buffer non-adjacency. Anything unusual (new
 * container, mapped/COW-stale bitmap words, odd dtypes) BAILS by
 * returning None and the caller re-runs the op through the pure-Python
 * path, so behavior is bit-for-bit identical by construction (pinned
 * by tests/test_write_path.py's randomized differential).
 *
 * Entry points (module pilosa_fastmutate):
 *   setbit(bitmap, pos)   -> None (bail) | False (no change)
 *                            | bytes (13-byte WAL add record)
 *   clearbit(bitmap, pos) -> None | False | bytes (remove record)
 *
 * The returned bytes are the marshaled op record (type, u64 LE value,
 * FNV-1a32 of the first 9 bytes — roaring.Op.marshal), so Python only
 * appends them to the group-commit WAL. All three container kinds are
 * handled: sorted-u32 array (copy-insert/delete into a fresh buffer),
 * u64[1024] bitmap (in-place word set/clear when the COW epoch allows),
 * and wire-form u16 run buffers (interval extend/merge/split/trim,
 * always a fresh buffer — run buffers are never mutated in place).
 * Representation conversions at the 4096/2047 thresholds call back
 * into Container._maybe_convert (rare, and the Python logic is the
 * single source of truth for them).
 */

#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <numpy/arrayobject.h>
#include <stdint.h>
#include <string.h>

#define ARRAY_MAX_SIZE 4096
#define RUN_MAX_SIZE 2047
#define OP_ADD 0
#define OP_REMOVE 1

static PyObject *s_keys, *s_containers, *s_version, *s_table,
    *s_table_dirty, *s_cow_epoch, *s_array, *s_bitmap, *s_runs, *s_n,
    *s_mapped, *s_cow, *s_maybe_convert;

/* ---- small helpers -------------------------------------------------------- */

static PyObject* wal_record(int typ, uint64_t pos) {
    PyObject* b = PyBytes_FromStringAndSize(NULL, 13);
    if (!b) return NULL;
    uint8_t* rec = (uint8_t*)PyBytes_AS_STRING(b);
    rec[0] = (uint8_t)typ;
    memcpy(rec + 1, &pos, 8); /* little-endian host (loader-gated) */
    uint32_t h = 2166136261u;
    for (int i = 0; i < 9; i++) h = (h ^ rec[i]) * 16777619u;
    memcpy(rec + 9, &h, 4);
    return b;
}

/* attr as int64; -1 with error set on failure */
static int get_i64(PyObject* o, PyObject* name, int64_t* out) {
    PyObject* v = PyObject_GetAttr(o, name);
    if (!v) return -1;
    *out = PyLong_AsLongLong(v);
    Py_DECREF(v);
    if (*out == -1 && PyErr_Occurred()) return -1;
    return 0;
}

static int set_i64(PyObject* o, PyObject* name, int64_t v) {
    PyObject* pv = PyLong_FromLongLong(v);
    if (!pv) return -1;
    int rc = PyObject_SetAttr(o, name, pv);
    Py_DECREF(pv);
    return rc;
}

static int bump_version(PyObject* bm) {
    int64_t v;
    if (get_i64(bm, s_version, &v) < 0) return -1;
    return set_i64(bm, s_version, v + 1);
}

/* Mirror of Bitmap._add/_remove's table upkeep: point mutations park
 * their container key in _table_dirty for bulk patching. */
static int note_dirty(PyObject* bm, uint64_t key) {
    PyObject* table = PyObject_GetAttr(bm, s_table);
    if (!table) return -1;
    int is_none = (table == Py_None);
    Py_DECREF(table);
    if (is_none) return 0;
    PyObject* dirty = PyObject_GetAttr(bm, s_table_dirty);
    if (!dirty) return -1;
    PyObject* k = PyLong_FromUnsignedLongLong(key);
    if (!k) { Py_DECREF(dirty); return -1; }
    int rc = PySet_Add(dirty, k);
    Py_DECREF(k);
    Py_DECREF(dirty);
    return rc;
}

static int call_maybe_convert(PyObject* c) {
    PyObject* r = PyObject_CallMethodNoArgs(c, s_maybe_convert);
    if (!r) return -1;
    Py_DECREF(r);
    return 0;
}

/* usable 1-d C-contiguous aligned numpy array of the given type, or
 * NULL (no error set) when the buffer is anything else — caller bails */
static PyArrayObject* usable(PyObject* o, int typenum) {
    if (!PyArray_Check(o)) return NULL;
    PyArrayObject* a = (PyArrayObject*)o;
    if (PyArray_TYPE(a) != typenum || PyArray_NDIM(a) != 1
        || !PyArray_ISCARRAY_RO(a))
        return NULL;
    return a;
}

/* ---- per-kind mutate ------------------------------------------------------ */
/* Each returns: 0 = no change, 1 = changed, 2 = bail, -1 = error.  */

static int mutate_array(PyObject* c, PyArrayObject* arr, uint16_t v,
                        int is_set) {
    int64_t n = PyArray_DIM(arr, 0);
    const uint32_t* data = (const uint32_t*)PyArray_DATA(arr);
    int64_t lo = 0, hi = n;
    while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (data[mid] < v) lo = mid + 1; else hi = mid;
    }
    int present = lo < n && data[lo] == v;
    if (is_set ? present : !present) return 0;
    npy_intp dims[1] = { is_set ? n + 1 : n - 1 };
    PyObject* grown = PyArray_SimpleNew(1, dims, NPY_UINT32);
    if (!grown) return -1;
    uint32_t* out = (uint32_t*)PyArray_DATA((PyArrayObject*)grown);
    if (is_set) {
        memcpy(out, data, lo * 4);
        out[lo] = v;
        memcpy(out + lo + 1, data + lo, (n - lo) * 4);
    } else {
        memcpy(out, data, lo * 4);
        memcpy(out + lo, data + lo + 1, (n - lo - 1) * 4);
    }
    int rc = PyObject_SetAttr(c, s_array, grown);
    Py_DECREF(grown);
    if (rc < 0) return -1;
    if (PyObject_SetAttr(c, s_mapped, Py_False) < 0) return -1;
    int64_t new_n = is_set ? n + 1 : n - 1;
    if (set_i64(c, s_n, new_n) < 0) return -1;
    if (is_set && new_n > ARRAY_MAX_SIZE && call_maybe_convert(c) < 0)
        return -1;
    return 1;
}

static int mutate_bitmap(PyObject* bm, PyObject* c, PyArrayObject* words,
                         uint16_t v, int is_set) {
    /* In-place word mutation is only safe when the buffer is neither
     * mmap-backed nor captured by a frozen snapshot — otherwise bail
     * and let Python's _guard_inplace copy first. */
    PyObject* mapped = PyObject_GetAttr(c, s_mapped);
    if (!mapped) return -1;
    int is_mapped = PyObject_IsTrue(mapped);
    Py_DECREF(mapped);
    if (is_mapped) return 2;
    int64_t cow, epoch;
    if (get_i64(c, s_cow, &cow) < 0
        || get_i64(bm, s_cow_epoch, &epoch) < 0) return -1;
    if (cow != epoch) return 2;
    if (PyArray_DIM(words, 0) != 1024) return 2;
    uint64_t* w = (uint64_t*)PyArray_DATA(words);
    uint64_t bit = 1ULL << (v & 63);
    int64_t n;
    if (is_set) {
        if (w[v >> 6] & bit) return 0;
        w[v >> 6] |= bit;
        if (get_i64(c, s_n, &n) < 0 || set_i64(c, s_n, n + 1) < 0)
            return -1;
        return 1;
    }
    if (!(w[v >> 6] & bit)) return 0;
    w[v >> 6] &= ~bit;
    if (get_i64(c, s_n, &n) < 0 || set_i64(c, s_n, n - 1) < 0) return -1;
    if (n - 1 <= ARRAY_MAX_SIZE && call_maybe_convert(c) < 0) return -1;
    return 1;
}

/* Build a fresh run buffer (run buffers are never mutated in place —
 * that keeps mmap'd and frozen captures safe with no COW tokens). */
static int store_runs(PyObject* c, const uint16_t* runs, int64_t n_runs,
                      int64_t delta_n) {
    npy_intp dims[1] = { 1 + 2 * n_runs };
    PyObject* buf = PyArray_SimpleNew(1, dims, NPY_UINT16);
    if (!buf) return -1;
    uint16_t* out = (uint16_t*)PyArray_DATA((PyArrayObject*)buf);
    out[0] = (uint16_t)n_runs;
    memcpy(out + 1, runs, n_runs * 4);
    int rc = PyObject_SetAttr(c, s_runs, buf);
    Py_DECREF(buf);
    if (rc < 0) return -1;
    if (PyObject_SetAttr(c, s_mapped, Py_False) < 0) return -1;
    int64_t n;
    if (get_i64(c, s_n, &n) < 0 || set_i64(c, s_n, n + delta_n) < 0)
        return -1;
    if (n_runs > RUN_MAX_SIZE && call_maybe_convert(c) < 0) return -1;
    return 1;
}

static int mutate_runs(PyObject* c, PyArrayObject* rbuf, uint16_t v,
                       int is_set) {
    int64_t len = PyArray_DIM(rbuf, 0);
    const uint16_t* b = (const uint16_t*)PyArray_DATA(rbuf);
    if (len < 1) return 2;
    int64_t R = b[0];
    if (len != 1 + 2 * R) return 2; /* malformed: let Python raise */
    /* i = last run whose start <= v (searchsorted right - 1) */
    int64_t lo = 0, hi = R;
    while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (b[1 + 2 * mid] <= v) lo = mid + 1; else hi = mid;
    }
    int64_t i = lo - 1;
    uint32_t start_i = 0, end_i = 0; /* end exclusive */
    if (i >= 0) {
        start_i = b[1 + 2 * i];
        end_i = start_i + b[2 + 2 * i] + 1;
    }
    /* scratch: worst case R+1 runs of (start, len-1) pairs */
    uint16_t stack[2 * 64 + 2];
    uint16_t* scratch = stack;
    PyObject* heap = NULL;
    if (2 * (R + 1) > (int64_t)(sizeof(stack) / sizeof(stack[0]))) {
        heap = PyBytes_FromStringAndSize(NULL, (R + 1) * 4);
        if (!heap) return -1;
        scratch = (uint16_t*)PyBytes_AS_STRING(heap);
    }
    int rc;
    if (is_set) {
        if (i >= 0 && v < end_i) { Py_XDECREF(heap); return 0; }
        int join_prev = i >= 0 && (uint32_t)v == end_i;
        int join_next = i + 1 < R && (uint32_t)v + 1 == b[1 + 2 * (i + 1)];
        int64_t out_R;
        memcpy(scratch, b + 1, R * 4);
        if (join_prev && join_next) {
            /* merge runs i and i+1 across v */
            uint32_t next_start = b[1 + 2 * (i + 1)];
            uint32_t next_len1 = b[2 + 2 * (i + 1)];
            /* merged covers start_i .. next_start+next_len1, so its
             * len-1 is (next_start - start_i) + next_len1 */
            scratch[2 * i + 1] =
                (uint16_t)((next_start - start_i) + next_len1);
            memmove(scratch + 2 * (i + 1), scratch + 2 * (i + 2),
                    (R - i - 2) * 4);
            out_R = R - 1;
        } else if (join_prev) {
            scratch[2 * i + 1] = (uint16_t)(b[2 + 2 * i] + 1);
            out_R = R;
        } else if (join_next) {
            scratch[2 * (i + 1)] = (uint16_t)(v);
            scratch[2 * (i + 1) + 1] = (uint16_t)(b[2 + 2 * (i + 1)] + 1);
            out_R = R;
        } else {
            memmove(scratch + 2 * (i + 2), scratch + 2 * (i + 1),
                    (R - i - 1) * 4);
            scratch[2 * (i + 1)] = v;
            scratch[2 * (i + 1) + 1] = 0;
            out_R = R + 1;
        }
        rc = store_runs(c, scratch, out_R, +1);
    } else {
        if (i < 0 || v >= end_i) { Py_XDECREF(heap); return 0; }
        int64_t out_R;
        memcpy(scratch, b + 1, R * 4);
        if (end_i - start_i == 1) {
            memmove(scratch + 2 * i, scratch + 2 * (i + 1),
                    (R - i - 1) * 4);
            out_R = R - 1;
        } else if (v == start_i) {
            scratch[2 * i] = (uint16_t)(start_i + 1);
            scratch[2 * i + 1] = (uint16_t)(b[2 + 2 * i] - 1);
            out_R = R;
        } else if ((uint32_t)v == end_i - 1) {
            scratch[2 * i + 1] = (uint16_t)(b[2 + 2 * i] - 1);
            out_R = R;
        } else {
            memmove(scratch + 2 * (i + 2), scratch + 2 * (i + 1),
                    (R - i - 1) * 4);
            scratch[2 * i + 1] = (uint16_t)(v - start_i - 1);
            scratch[2 * (i + 1)] = (uint16_t)(v + 1);
            scratch[2 * (i + 1) + 1] = (uint16_t)(end_i - v - 2);
            out_R = R + 1;
        }
        rc = store_runs(c, scratch, out_R, -1);
    }
    Py_XDECREF(heap);
    return rc;
}

/* ---- the one crossing ----------------------------------------------------- */

static PyObject* mutate(PyObject* bm, uint64_t pos, int is_set) {
    uint64_t key = pos >> 16;
    uint16_t v = (uint16_t)(pos & 0xFFFF);

    PyObject* keys = PyObject_GetAttr(bm, s_keys);
    if (!keys) return NULL;
    if (!PyList_CheckExact(keys)) { Py_DECREF(keys); Py_RETURN_NONE; }
    Py_ssize_t nk = PyList_GET_SIZE(keys);
    Py_ssize_t lo = 0, hi = nk;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) >> 1;
        uint64_t kv = PyLong_AsUnsignedLongLong(PyList_GET_ITEM(keys, mid));
        if (kv == (uint64_t)-1 && PyErr_Occurred()) {
            Py_DECREF(keys);
            return NULL;
        }
        if (kv < key) lo = mid + 1; else hi = mid;
    }
    int found = 0;
    if (lo < nk) {
        uint64_t kv = PyLong_AsUnsignedLongLong(PyList_GET_ITEM(keys, lo));
        if (kv == (uint64_t)-1 && PyErr_Occurred()) {
            Py_DECREF(keys);
            return NULL;
        }
        found = kv == key;
    }
    Py_DECREF(keys);
    if (!found) {
        if (is_set) Py_RETURN_NONE; /* new container: Python creates it */
        /* remove against an absent container: a no-op, but _remove
         * bumps the version before discovering that — mirror it */
        if (bump_version(bm) < 0) return NULL;
        Py_RETURN_FALSE;
    }

    PyObject* containers = PyObject_GetAttr(bm, s_containers);
    if (!containers) return NULL;
    if (!PyList_CheckExact(containers) || lo >= PyList_GET_SIZE(containers)) {
        Py_DECREF(containers);
        Py_RETURN_NONE;
    }
    PyObject* c = PyList_GET_ITEM(containers, lo);
    Py_INCREF(c);
    Py_DECREF(containers);

    /* classify the container kind; bail on any unusual buffer */
    PyObject* runs_o = PyObject_GetAttr(c, s_runs);
    if (!runs_o) { Py_DECREF(c); return NULL; }
    PyObject* bitmap_o = NULL;
    PyObject* array_o = NULL;
    int rc = 2;
    if (runs_o != Py_None) {
        PyArrayObject* rbuf = usable(runs_o, NPY_UINT16);
        if (rbuf) {
            if (bump_version(bm) < 0 || note_dirty(bm, key) < 0)
                rc = -1;
            else
                rc = mutate_runs(c, rbuf, v, is_set);
        }
    } else {
        bitmap_o = PyObject_GetAttr(c, s_bitmap);
        if (!bitmap_o) { Py_DECREF(runs_o); Py_DECREF(c); return NULL; }
        if (bitmap_o != Py_None) {
            PyArrayObject* words = usable(bitmap_o, NPY_UINT64);
            if (words) {
                /* safety pre-check happens inside (bails BEFORE any
                 * side effect so the Python fallback replays cleanly) */
                PyObject* mapped = PyObject_GetAttr(c, s_mapped);
                if (!mapped) rc = -1;
                else {
                    int m = PyObject_IsTrue(mapped);
                    Py_DECREF(mapped);
                    int64_t cow = 0, epoch = 0;
                    if (m < 0 || get_i64(c, s_cow, &cow) < 0
                        || get_i64(bm, s_cow_epoch, &epoch) < 0)
                        rc = -1;
                    else if (m || cow != epoch)
                        rc = 2; /* COW copy needed: Python path */
                    else if (bump_version(bm) < 0
                             || note_dirty(bm, key) < 0)
                        rc = -1;
                    else
                        rc = mutate_bitmap(bm, c, words, v, is_set);
                }
            }
        } else {
            array_o = PyObject_GetAttr(c, s_array);
            if (!array_o) {
                Py_DECREF(runs_o);
                Py_DECREF(c);
                return NULL;
            }
            PyArrayObject* arr = usable(array_o, NPY_UINT32);
            if (arr) {
                if (bump_version(bm) < 0 || note_dirty(bm, key) < 0)
                    rc = -1;
                else
                    rc = mutate_array(c, arr, v, is_set);
            }
        }
    }
    Py_DECREF(runs_o);
    Py_XDECREF(bitmap_o);
    Py_XDECREF(array_o);
    Py_DECREF(c);
    if (rc < 0) return NULL;
    if (rc == 2) Py_RETURN_NONE;
    if (rc == 0) Py_RETURN_FALSE;
    return wal_record(is_set ? OP_ADD : OP_REMOVE, pos);
}

static PyObject* py_setbit(PyObject* self, PyObject* const* args,
                           Py_ssize_t nargs) {
    (void)self;
    if (nargs != 2) {
        PyErr_SetString(PyExc_TypeError, "setbit(bitmap, pos)");
        return NULL;
    }
    uint64_t pos = PyLong_AsUnsignedLongLong(args[1]);
    if (pos == (uint64_t)-1 && PyErr_Occurred()) return NULL;
    return mutate(args[0], pos, 1);
}

static PyObject* py_clearbit(PyObject* self, PyObject* const* args,
                             Py_ssize_t nargs) {
    (void)self;
    if (nargs != 2) {
        PyErr_SetString(PyExc_TypeError, "clearbit(bitmap, pos)");
        return NULL;
    }
    uint64_t pos = PyLong_AsUnsignedLongLong(args[1]);
    if (pos == (uint64_t)-1 && PyErr_Occurred()) return NULL;
    return mutate(args[0], pos, 0);
}

/* Batch WAL-record build for the bulk-import lane: 13-byte checksummed
 * records for a whole position vector in one crossing, GIL RELEASED —
 * concurrent wire-import threads build their blobs in parallel while
 * another thread applies (the numpy _wal_blob fallback held the GIL
 * for its nine u32 vector passes). */
static PyObject* py_wal_records(PyObject* self, PyObject* const* args,
                                Py_ssize_t nargs) {
    (void)self;
    if (nargs != 2) {
        PyErr_SetString(PyExc_TypeError, "wal_records(values, typ)");
        return NULL;
    }
    PyArrayObject* a = usable(args[0], NPY_UINT64);
    if (!a) {
        PyErr_SetString(PyExc_TypeError,
                        "wal_records: need 1-d C-contiguous u64 array");
        return NULL;
    }
    long typ = PyLong_AsLong(args[1]);
    if (typ == -1 && PyErr_Occurred()) return NULL;
    npy_intp n = PyArray_DIM(a, 0);
    PyObject* b = PyBytes_FromStringAndSize(NULL, n * 13);
    if (!b) return NULL;
    uint8_t* out = (uint8_t*)PyBytes_AS_STRING(b);
    const uint64_t* vals = (const uint64_t*)PyArray_DATA(a);
    Py_BEGIN_ALLOW_THREADS
    for (npy_intp i = 0; i < n; i++) {
        uint8_t* rec = out + i * 13;
        rec[0] = (uint8_t)typ;
        uint64_t pos = vals[i];
        memcpy(rec + 1, &pos, 8); /* little-endian host (loader-gated) */
        uint32_t h = 2166136261u;
        for (int j = 0; j < 9; j++) h = (h ^ rec[j]) * 16777619u;
        memcpy(rec + 9, &h, 4);
    }
    Py_END_ALLOW_THREADS
    return b;
}

static PyMethodDef methods[] = {
    {"setbit", (PyCFunction)(void*)py_setbit, METH_FASTCALL,
     "setbit(bitmap, pos) -> None (bail) | False | 13-byte WAL record"},
    {"clearbit", (PyCFunction)(void*)py_clearbit, METH_FASTCALL,
     "clearbit(bitmap, pos) -> None (bail) | False | 13-byte WAL record"},
    {"wal_records", (PyCFunction)(void*)py_wal_records, METH_FASTCALL,
     "wal_records(u64 values, typ) -> marshaled 13-byte op records"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "pilosa_fastmutate",
    "One-crossing roaring point mutations (see fastmutate.c)", -1,
    methods, NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit_pilosa_fastmutate(void) {
    import_array();
#define INTERN(var, name) \
    if (!(var = PyUnicode_InternFromString(name))) return NULL
    INTERN(s_keys, "keys");
    INTERN(s_containers, "containers");
    INTERN(s_version, "version");
    INTERN(s_table, "_table");
    INTERN(s_table_dirty, "_table_dirty");
    INTERN(s_cow_epoch, "_cow_epoch");
    INTERN(s_array, "array");
    INTERN(s_bitmap, "bitmap");
    INTERN(s_runs, "runs");
    INTERN(s_n, "n");
    INTERN(s_mapped, "mapped");
    INTERN(s_cow, "cow");
    INTERN(s_maybe_convert, "_maybe_convert");
#undef INTERN
    return PyModule_Create(&moduledef);
}
