// Host-side native bit kernels for pilosa_tpu.
//
// The reference's only native component is roaring/assembly_amd64.s — POPCNT
// loops fused with AND/OR/XOR/ANDNOT over u64 slices, plus sorted-array set
// ops in Go. On TPU the hot path moves to XLA/Pallas (pilosa_tpu/ops/); this
// library is the CPU-side equivalent for storage maintenance, import packing,
// and the no-TPU fallback, so none of those paths are Python-loop-bound.
//
// Built as a plain shared library (extern "C"), loaded via ctypes
// (pilosa_tpu/storage/native.py). g++ -O3 -march=native autovectorizes the
// popcount loops with __builtin_popcountll.

#include <cstdint>
#include <cstring>

extern "C" {

// ---- fused popcount + bitwise op over u64 words ----------------------------

uint64_t popcnt_and(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] & b[i]);
    return total;
}

uint64_t popcnt_or(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] | b[i]);
    return total;
}

uint64_t popcnt_xor(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] ^ b[i]);
    return total;
}

uint64_t popcnt_andnot(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] & ~b[i]);
    return total;
}

uint64_t popcnt(const uint64_t* a, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i]);
    return total;
}

// ---- sorted u32 array set ops ----------------------------------------------
// Standard two-pointer merges; out must have room for the worst case
// (min(na,nb) for intersect, na+nb for union, na for difference).

int64_t intersect_sorted_u32(const uint32_t* a, int64_t na,
                             const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) i++;
        else if (a[i] > b[j]) j++;
        else { out[k++] = a[i]; i++; j++; }
    }
    return k;
}

int64_t intersection_count_sorted_u32(const uint32_t* a, int64_t na,
                                      const uint32_t* b, int64_t nb) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) i++;
        else if (a[i] > b[j]) j++;
        else { k++; i++; j++; }
    }
    return k;
}

int64_t union_sorted_u32(const uint32_t* a, int64_t na,
                         const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) out[k++] = b[j++];
        else { out[k++] = a[i]; i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

int64_t difference_sorted_u32(const uint32_t* a, int64_t na,
                              const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) j++;
        else { i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    return k;
}

// ---- packing: u64 bit positions -> dense u32 word matrix -------------------
// Scatter set-bit positions into a row-major uint32 word buffer of
// words_per_row words per row: pos -> words[row * words_per_row + col/32].
// Positions are fragment-local: pos = row * slice_width + col.

void pack_positions_u32(const uint64_t* positions, int64_t n,
                        uint64_t slice_width, int64_t words_per_row,
                        uint32_t* words) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t pos = positions[i];
        uint64_t row = pos / slice_width;
        uint64_t col = pos % slice_width;
        words[row * words_per_row + (col >> 5)] |= (1u << (col & 31));
    }
}

// Unpack one row of u32 words into sorted column ids; returns count.
int64_t unpack_words_u32(const uint32_t* words, int64_t n_words,
                         uint64_t* out) {
    int64_t k = 0;
    for (int64_t i = 0; i < n_words; i++) {
        uint32_t w = words[i];
        while (w) {
            int bit = __builtin_ctz(w);
            out[k++] = (uint64_t)i * 32 + bit;
            w &= w - 1;
        }
    }
    return k;
}

}  // extern "C"
