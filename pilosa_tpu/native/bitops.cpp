// Host-side native bit kernels for pilosa_tpu.
//
// The reference's only native component is roaring/assembly_amd64.s — POPCNT
// loops fused with AND/OR/XOR/ANDNOT over u64 slices, plus sorted-array set
// ops in Go. On TPU the hot path moves to XLA/Pallas (pilosa_tpu/ops/); this
// library is the CPU-side equivalent for storage maintenance, import packing,
// and the no-TPU fallback, so none of those paths are Python-loop-bound.
//
// Built as a plain shared library (extern "C"), loaded via ctypes
// (pilosa_tpu/storage/native.py). g++ -O3 -march=native autovectorizes the
// popcount loops with __builtin_popcountll.

#include <cstdint>
#include <cstring>

extern "C" {

// ---- fused popcount + bitwise op over u64 words ----------------------------

uint64_t popcnt_and(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] & b[i]);
    return total;
}

uint64_t popcnt_or(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] | b[i]);
    return total;
}

uint64_t popcnt_xor(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] ^ b[i]);
    return total;
}

uint64_t popcnt_andnot(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] & ~b[i]);
    return total;
}

uint64_t popcnt(const uint64_t* a, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i]);
    return total;
}

// ---- sorted u32 array set ops ----------------------------------------------
// Standard two-pointer merges; out must have room for the worst case
// (min(na,nb) for intersect, na+nb for union, na for difference).

int64_t intersect_sorted_u32(const uint32_t* a, int64_t na,
                             const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) i++;
        else if (a[i] > b[j]) j++;
        else { out[k++] = a[i]; i++; j++; }
    }
    return k;
}

int64_t intersection_count_sorted_u32(const uint32_t* a, int64_t na,
                                      const uint32_t* b, int64_t nb) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) i++;
        else if (a[i] > b[j]) j++;
        else { k++; i++; j++; }
    }
    return k;
}

int64_t union_sorted_u32(const uint32_t* a, int64_t na,
                         const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) out[k++] = b[j++];
        else { out[k++] = a[i]; i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

int64_t difference_sorted_u32(const uint32_t* a, int64_t na,
                              const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) j++;
        else { i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    return k;
}

// ---- packing: u64 bit positions -> dense u32 word matrix -------------------
// Scatter set-bit positions into a row-major uint32 word buffer of
// words_per_row words per row: pos -> words[row * words_per_row + col/32].
// Positions are fragment-local: pos = row * slice_width + col.

void pack_positions_u32(const uint64_t* positions, int64_t n,
                        uint64_t slice_width, int64_t words_per_row,
                        uint32_t* words) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t pos = positions[i];
        uint64_t row = pos / slice_width;
        uint64_t col = pos % slice_width;
        words[row * words_per_row + (col >> 5)] |= (1u << (col & 31));
    }
}

// Unpack one row of u32 words into sorted column ids; returns count.
int64_t unpack_words_u32(const uint32_t* words, int64_t n_words,
                         uint64_t* out) {
    int64_t k = 0;
    for (int64_t i = 0; i < n_words; i++) {
        uint32_t w = words[i];
        while (w) {
            int bit = __builtin_ctz(w);
            out[k++] = (uint64_t)i * 32 + bit;
            w &= w - 1;
        }
    }
    return k;
}

}  // extern "C"

// ---- native write-path micro-engine ----------------------------------------
// The measured host denominator for the SetBit path (the reference's is
// fragment.go:369-459 driven by ctl/bench.go:71-102; no Go toolchain in
// this image, so this is the C++ stand-in, as popcnt_and is for reads).
// Faithful shape: per op — locate the container (pos>>16), sorted-array
// insert or bitmap set with array->bitmap conversion at 4096, append a
// 13-byte op record to the data file with one unbuffered write(), and
// after every max_op_n ops rewrite a snapshot of all containers to a
// temp file, fsync, and rename over the data file (the same durability
// cadence the Python fragment and the reference both pay).

#include <cstdio>
#include <cstdlib>
#include <fcntl.h>
#include <unistd.h>

namespace {

struct WContainer {
    uint16_t* array;     // sorted u16 values, or null when bitmap
    uint64_t* bitmap;    // u64[1024], or null when array
    int32_t n;
    int32_t cap;
};

const int32_t kArrayMax = 4096;
const int32_t kBitmapWords = 1024;

bool wcontainer_add(WContainer* c, uint16_t v) {
    if (c->bitmap) {
        uint64_t bit = 1ULL << (v & 63);
        if (c->bitmap[v >> 6] & bit) return false;
        c->bitmap[v >> 6] |= bit;
        c->n++;
        return true;
    }
    // binary search
    int32_t lo = 0, hi = c->n;
    while (lo < hi) {
        int32_t mid = (lo + hi) / 2;
        if (c->array[mid] < v) lo = mid + 1; else hi = mid;
    }
    if (lo < c->n && c->array[lo] == v) return false;
    if (c->n + 1 > kArrayMax) {  // convert then set
        uint64_t* bm = (uint64_t*)calloc(kBitmapWords, 8);
        for (int32_t i = 0; i < c->n; i++)
            bm[c->array[i] >> 6] |= 1ULL << (c->array[i] & 63);
        free(c->array);
        c->array = nullptr;
        c->bitmap = bm;
        return wcontainer_add(c, v);
    }
    if (c->n == c->cap) {
        c->cap = c->cap ? c->cap * 2 : 8;
        c->array = (uint16_t*)realloc(c->array, c->cap * 2);
    }
    memmove(c->array + lo + 1, c->array + lo, (c->n - lo) * 2);
    c->array[lo] = v;
    c->n++;
    return true;
}

}  // namespace

// Runs n_ops SetBit ops (64-bit fragment positions) against a data file
// at `path` with WAL append per op and a snapshot rewrite every
// max_op_n ops. Returns ops actually changed (idempotent re-sets don't
// append), or -1 on IO error. Elapsed time is the caller's job.
extern "C" int64_t bench_setbit(const char* path, const uint64_t* positions,
                     int64_t n_ops, int64_t max_op_n) {
    int64_t max_key = 0;
    for (int64_t i = 0; i < n_ops; i++)
        if ((int64_t)(positions[i] >> 16) > max_key)
            max_key = positions[i] >> 16;
    WContainer* conts = (WContainer*)calloc(max_key + 1,
                                            sizeof(WContainer));
    int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) { free(conts); return -1; }

    unsigned char rec[13];
    int64_t changed = 0, op_n = 0;
    char tmp_path[4096];
    snprintf(tmp_path, sizeof tmp_path, "%s.snapshotting", path);

    for (int64_t i = 0; i < n_ops; i++) {
        uint64_t pos = positions[i];
        WContainer* c = &conts[pos >> 16];
        if (!wcontainer_add(c, (uint16_t)(pos & 0xFFFF))) continue;
        changed++;
        // 13-byte op record: type(1) + value(8) + checksum(4) — the
        // same record size the storage WAL appends per mutation.
        rec[0] = 0;
        memcpy(rec + 1, &pos, 8);
        uint32_t sum = (uint32_t)(pos ^ (pos >> 32)) * 2654435761u;
        memcpy(rec + 9, &sum, 4);
        if (write(fd, rec, 13) != 13) { close(fd); free(conts); return -1; }
        if (++op_n > max_op_n) {
            // snapshot: rewrite every live container, fsync, rename.
            int sfd = open(tmp_path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
            if (sfd < 0) { close(fd); free(conts); return -1; }
            for (int64_t k = 0; k <= max_key; k++) {
                WContainer* cc = &conts[k];
                if (cc->n == 0) continue;
                if (cc->bitmap) {
                    if (write(sfd, cc->bitmap, kBitmapWords * 8) < 0)
                        { close(sfd); close(fd); free(conts); return -1; }
                } else {
                    if (write(sfd, cc->array, cc->n * 2) < 0)
                        { close(sfd); close(fd); free(conts); return -1; }
                }
            }
            fsync(sfd);
            close(sfd);
            if (rename(tmp_path, path) != 0)
                { close(fd); free(conts); return -1; }
            close(fd);
            fd = open(path, O_WRONLY | O_APPEND, 0644);
            if (fd < 0) { free(conts); return -1; }
            op_n = 0;
        }
    }
    close(fd);
    for (int64_t k = 0; k <= max_key; k++) {
        free(conts[k].array);
        free(conts[k].bitmap);
    }
    free(conts);
    return changed;
}
