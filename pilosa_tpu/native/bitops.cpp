// Host-side native bit kernels for pilosa_tpu.
//
// The reference's only native component is roaring/assembly_amd64.s — POPCNT
// loops fused with AND/OR/XOR/ANDNOT over u64 slices, plus sorted-array set
// ops in Go. On TPU the hot path moves to XLA/Pallas (pilosa_tpu/ops/); this
// library is the CPU-side equivalent for storage maintenance, import packing,
// and the no-TPU fallback, so none of those paths are Python-loop-bound.
//
// Built as a plain shared library (extern "C"), loaded via ctypes
// (pilosa_tpu/storage/native.py). g++ -O3 -march=native autovectorizes the
// popcount loops with __builtin_popcountll.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---- fused popcount + bitwise op over u64 words ----------------------------

uint64_t popcnt_and(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] & b[i]);
    return total;
}

uint64_t popcnt_or(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] | b[i]);
    return total;
}

uint64_t popcnt_xor(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] ^ b[i]);
    return total;
}

uint64_t popcnt_andnot(const uint64_t* a, const uint64_t* b, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i] & ~b[i]);
    return total;
}

uint64_t popcnt(const uint64_t* a, int64_t n) {
    uint64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += __builtin_popcountll(a[i]);
    return total;
}

// ---- sorted u32 array set ops ----------------------------------------------
// Standard two-pointer merges; out must have room for the worst case
// (min(na,nb) for intersect, na+nb for union, na for difference).

int64_t intersect_sorted_u32(const uint32_t* a, int64_t na,
                             const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) i++;
        else if (a[i] > b[j]) j++;
        else { out[k++] = a[i]; i++; j++; }
    }
    return k;
}

int64_t intersection_count_sorted_u32(const uint32_t* a, int64_t na,
                                      const uint32_t* b, int64_t nb) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) i++;
        else if (a[i] > b[j]) j++;
        else { k++; i++; j++; }
    }
    return k;
}

int64_t union_sorted_u32(const uint32_t* a, int64_t na,
                         const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) out[k++] = b[j++];
        else { out[k++] = a[i]; i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

int64_t difference_sorted_u32(const uint32_t* a, int64_t na,
                              const uint32_t* b, int64_t nb, uint32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) j++;
        else { i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    return k;
}

// ---- packing: u64 bit positions -> dense u32 word matrix -------------------
// Scatter set-bit positions into a row-major uint32 word buffer of
// words_per_row words per row: pos -> words[row * words_per_row + col/32].
// Positions are fragment-local: pos = row * slice_width + col.

void pack_positions_u32(const uint64_t* positions, int64_t n,
                        uint64_t slice_width, int64_t words_per_row,
                        uint32_t* words) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t pos = positions[i];
        uint64_t row = pos / slice_width;
        uint64_t col = pos % slice_width;
        words[row * words_per_row + (col >> 5)] |= (1u << (col & 31));
    }
}

// Unpack one row of u32 words into sorted column ids; returns count.
int64_t unpack_words_u32(const uint32_t* words, int64_t n_words,
                         uint64_t* out) {
    int64_t k = 0;
    for (int64_t i = 0; i < n_words; i++) {
        uint32_t w = words[i];
        while (w) {
            int bit = __builtin_ctz(w);
            out[k++] = (uint64_t)i * 32 + bit;
            w &= w - 1;
        }
    }
    return k;
}

// ---- whole-bitmap intersection count ---------------------------------------
// One crossing for an entire two-level intersection count: zip both
// bitmaps' container tables (sorted keys + per-container type/ptr/n)
// and dispatch per pair kind — the reference's intersectionCount
// container dispatch (roaring.go:1192-1268) with the Python walk
// removed. Tables are the serialization tables the batch engine
// already maintains (roaring._SerTable).

extern "C" int64_t bitmap_intersection_count(
        int64_t na, const uint64_t* keys_a, const uint8_t* types_a,
        const uint64_t* ptrs_a, const int64_t* ns_a,
        int64_t nb, const uint64_t* keys_b, const uint8_t* types_b,
        const uint64_t* ptrs_b, const int64_t* ns_b) {
    int64_t i = 0, j = 0;
    int64_t total = 0;
    while (i < na && j < nb) {
        if (keys_a[i] < keys_b[j]) { i++; continue; }
        if (keys_a[i] > keys_b[j]) { j++; continue; }
        if (ns_a[i] && ns_b[j]) {
            bool bm_a = types_a[i] != 0, bm_b = types_b[j] != 0;
            if (!bm_a && !bm_b) {
                total += intersection_count_sorted_u32(
                    (const uint32_t*)ptrs_a[i], ns_a[i],
                    (const uint32_t*)ptrs_b[j], ns_b[j]);
            } else if (bm_a && bm_b) {
                total += (int64_t)popcnt_and(
                    (const uint64_t*)ptrs_a[i],
                    (const uint64_t*)ptrs_b[j], 1024);
            } else {
                const uint32_t* arr = (const uint32_t*)(
                    bm_a ? ptrs_b[j] : ptrs_a[i]);
                int64_t n_arr = bm_a ? ns_b[j] : ns_a[i];
                const uint64_t* bm = (const uint64_t*)(
                    bm_a ? ptrs_a[i] : ptrs_b[j]);
                for (int64_t t = 0; t < n_arr; t++) {
                    uint32_t v = arr[t];
                    total += (bm[v >> 6] >> (v & 63)) & 1ULL;
                }
            }
        }
        i++;
        j++;
    }
    return total;
}

// ---- batched write engine ---------------------------------------------------
// ONE crossing per mutation batch: container merges, changed-value
// detection, and WAL record construction all happen here, so the serving
// write path runs at compiled speed with ctypes overhead amortized over
// the whole batch (per-op ctypes was measured a loss; see
// storage/native.py). The reference's equivalent per-op loop is
// fragment.go:369-459; this is its batch-grouped native form.
//
// Group layout (caller = roaring.Bitmap.apply_batch): one group per
// touched container, in key order. types[g]: 0 = array container
// (sorted u32 values at arr_ptrs[g], count arr_ns[g]); 1 = bitmap
// container (u64[1024] at arr_ptrs[g], mutated IN PLACE — caller
// guarantees copy-on-write happened); 2 = run container (wire-form
// u16 buffer [numRuns, start, len-1, ...] at arr_ptrs[g], cardinality
// arr_ns[g]) — decoded to sorted values here and merged through the
// array path, i.e. the engine transparently upgrades runs (output is
// array or bitmap; roaring.Bitmap.optimize() re-compresses later).
// chunk values are sorted, unique, < 65536.
//
// Outputs per group:
//   out_kind[g]: 0 = merged array written at out_vals[out_offsets[g]]
//                1 = converted to bitmap at out_bitmaps[out_bm_idx[g]*1024]
//                2 = existing bitmap mutated in place
//   out_ns[g]:   new container cardinality
// Changed (newly set / newly cleared) global positions (keys[g]<<16 | v)
// are appended to `changed`; when wal_op_type >= 0 a 13-byte WAL record
// (type, u64 LE value, FNV-1a32 of the first 9 bytes) per changed value
// is appended to `wal`. Returns total changed count.

namespace {

const int64_t kWordsPerContainer = 1024;  // u64 words per bitmap container

inline void wal_record(uint8_t* rec, uint8_t typ, uint64_t pos) {
    rec[0] = typ;
    memcpy(rec + 1, &pos, 8);
    uint32_t h = 2166136261u;
    for (int i = 0; i < 9; i++) h = (h ^ rec[i]) * 16777619u;
    memcpy(rec + 9, &h, 4);
}

// Expand a wire-form run buffer into sorted u32 values; returns count.
int64_t decode_runs_u32(const uint16_t* runs, uint32_t* out) {
    int64_t n_runs = runs[0];
    int64_t k = 0;
    for (int64_t i = 0; i < n_runs; i++) {
        uint32_t start = runs[1 + 2 * i];
        uint32_t len = (uint32_t)runs[2 + 2 * i] + 1;
        for (uint32_t v = 0; v < len; v++) out[k++] = start + v;
    }
    return k;
}

}  // namespace

extern "C" int64_t batch_add(
        int64_t n_groups, const uint64_t* keys, const uint8_t* types,
        const uint64_t* arr_ptrs, const int64_t* arr_ns,
        const uint32_t* chunk_vals, const int64_t* chunk_starts,
        uint32_t* out_vals, int64_t* out_offsets, int64_t* out_ns,
        uint8_t* out_kind, uint64_t* out_bitmaps, int64_t* out_bm_idx,
        uint64_t* changed, uint8_t* wal, int64_t wal_op_type) {
    int64_t n_changed = 0, out_off = 0, bm_count = 0;
    for (int64_t g = 0; g < n_groups; g++) {
        const uint32_t* b = chunk_vals + chunk_starts[g];
        int64_t nb = chunk_starts[g + 1] - chunk_starts[g];
        uint64_t base = keys[g] << 16;
        int64_t before_changed = n_changed;
        if (types[g] == 1) {  // bitmap container, in-place
            uint64_t* bm = (uint64_t*)arr_ptrs[g];
            int64_t n = arr_ns[g];
            for (int64_t i = 0; i < nb; i++) {
                uint32_t v = b[i];
                uint64_t bit = 1ULL << (v & 63);
                if (bm[v >> 6] & bit) continue;
                bm[v >> 6] |= bit;
                n++;
                changed[n_changed++] = base | v;
            }
            out_kind[g] = 2;
            out_ns[g] = n;
            out_bm_idx[g] = -1;
            out_offsets[g] = -1;
        } else {  // array/run container: two-pointer union into out_vals
            const uint32_t* a = (const uint32_t*)arr_ptrs[g];
            int64_t na = arr_ns[g];
            uint32_t* decoded = nullptr;
            if (types[g] == 2) {  // run: decode, then merge as array
                decoded = (uint32_t*)malloc((na ? na : 1) * 4);
                na = decode_runs_u32((const uint16_t*)arr_ptrs[g],
                                     decoded);
                a = decoded;
            }
            uint32_t* out = out_vals + out_off;
            int64_t i = 0, j = 0, k = 0;
            while (i < na && j < nb) {
                if (a[i] < b[j]) out[k++] = a[i++];
                else if (a[i] > b[j]) out[k++] = b[j++];
                else { out[k++] = a[i]; i++; j++; }
            }
            while (i < na) out[k++] = a[i++];
            while (j < nb) out[k++] = b[j++];
            // changed = chunk values not present in the existing array
            // (second pass keeps the union loop branch-light).
            i = 0; j = 0;
            while (j < nb) {
                while (i < na && a[i] < b[j]) i++;
                if (i >= na || a[i] != b[j]) changed[n_changed++] = base | b[j];
                j++;
            }
            if (k > 4096) {  // convert to bitmap container
                uint64_t* bm = out_bitmaps + bm_count * kWordsPerContainer;
                memset(bm, 0, kWordsPerContainer * 8);
                for (int64_t t = 0; t < k; t++)
                    bm[out[t] >> 6] |= 1ULL << (out[t] & 63);
                out_kind[g] = 1;
                out_bm_idx[g] = bm_count++;
                out_offsets[g] = -1;
            } else {
                out_kind[g] = 0;
                out_offsets[g] = out_off;
                out_bm_idx[g] = -1;
                out_off += k;
            }
            out_ns[g] = k;
            free(decoded);
        }
        if (wal_op_type >= 0) {
            for (int64_t t = before_changed; t < n_changed; t++)
                wal_record(wal + t * 13, (uint8_t)wal_op_type, changed[t]);
        }
    }
    return n_changed;
}

// Batched remove. Same group layout as batch_add (run groups decode and
// go through the array path). Array groups write the difference to
// out_vals (kind 0). Bitmap groups clear in place; if the result drops
// to <=4096 values it is UNPACKED to an array in out_vals (kind 0) to
// restore the serialization invariant, else kind 2.
extern "C" int64_t batch_remove(
        int64_t n_groups, const uint64_t* keys, const uint8_t* types,
        const uint64_t* arr_ptrs, const int64_t* arr_ns,
        const uint32_t* chunk_vals, const int64_t* chunk_starts,
        uint32_t* out_vals, int64_t* out_offsets, int64_t* out_ns,
        uint8_t* out_kind, uint64_t* changed, uint8_t* wal,
        int64_t wal_op_type) {
    int64_t n_changed = 0, out_off = 0;
    for (int64_t g = 0; g < n_groups; g++) {
        const uint32_t* b = chunk_vals + chunk_starts[g];
        int64_t nb = chunk_starts[g + 1] - chunk_starts[g];
        uint64_t base = keys[g] << 16;
        int64_t before_changed = n_changed;
        if (types[g] == 1) {
            uint64_t* bm = (uint64_t*)arr_ptrs[g];
            int64_t n = arr_ns[g];
            for (int64_t i = 0; i < nb; i++) {
                uint32_t v = b[i];
                uint64_t bit = 1ULL << (v & 63);
                if (!(bm[v >> 6] & bit)) continue;
                bm[v >> 6] &= ~bit;
                n--;
                changed[n_changed++] = base | v;
            }
            if (n <= 4096) {  // unpack to array (serialization invariant)
                uint32_t* out = out_vals + out_off;
                int64_t k = 0;
                for (int64_t w = 0; w < kWordsPerContainer; w++) {
                    uint64_t word = bm[w];
                    while (word) {
                        int bit = __builtin_ctzll(word);
                        out[k++] = (uint32_t)(w * 64 + bit);
                        word &= word - 1;
                    }
                }
                out_kind[g] = 0;
                out_offsets[g] = out_off;
                out_off += k;
            } else {
                out_kind[g] = 2;
                out_offsets[g] = -1;
            }
            out_ns[g] = n;
        } else {
            const uint32_t* a = (const uint32_t*)arr_ptrs[g];
            int64_t na = arr_ns[g];
            uint32_t* decoded = nullptr;
            if (types[g] == 2) {
                decoded = (uint32_t*)malloc((na ? na : 1) * 4);
                na = decode_runs_u32((const uint16_t*)arr_ptrs[g],
                                     decoded);
                a = decoded;
            }
            uint32_t* out = out_vals + out_off;
            int64_t i = 0, j = 0, k = 0;
            while (i < na) {
                while (j < nb && b[j] < a[i]) j++;
                if (j < nb && b[j] == a[i]) {
                    changed[n_changed++] = base | a[i];
                    i++;
                } else {
                    out[k++] = a[i++];
                }
            }
            out_kind[g] = 0;
            out_offsets[g] = out_off;
            out_ns[g] = k;
            out_off += k;
            free(decoded);
        }
        if (wal_op_type >= 0) {
            for (int64_t t = before_changed; t < n_changed; t++)
                wal_record(wal + t * 13, (uint8_t)wal_op_type, changed[t]);
        }
    }
    return n_changed;
}

}  // extern "C"

// ---- native snapshot writer -------------------------------------------------
// Serializes a whole roaring snapshot (cookie/keyN/headers/offsets/container
// blocks — the reference format, roaring.go:475-533) straight from a table of
// container buffer pointers, using writev batches that point INTO the
// container buffers (zero copy, no GIL held during the call). The table is
// maintained incrementally by the batched write path, so the MAX_OP_N
// snapshot cadence stops costing O(all containers) of Python per rewrite.

#include <cstdlib>
#include <sys/uio.h>
#include <unistd.h>

namespace {

bool writev_full(int fd, struct iovec* iov, int n) {
    while (n > 0) {
        ssize_t w = writev(fd, iov, n);
        if (w < 0) return false;
        while (n > 0 && (size_t)w >= iov[0].iov_len) {
            w -= iov[0].iov_len;
            iov++;
            n--;
        }
        if (n > 0) {  // partial iovec
            iov[0].iov_base = (uint8_t*)iov[0].iov_base + w;
            iov[0].iov_len -= w;
        }
    }
    return true;
}

}  // namespace

extern "C" int64_t write_snapshot_fd(
        int fd, int64_t n_cont, const uint64_t* keys, const int64_t* ns,
        const uint8_t* types, const uint64_t* ptrs) {
    int64_t live = 0, body = 0;
    for (int64_t i = 0; i < n_cont; i++) {
        if (ns[i] == 0) continue;
        live++;
        body += types[i] ? kWordsPerContainer * 8 : ns[i] * 4;
    }
    int64_t head_len = 8 + live * 12 + live * 4;
    uint8_t* head = (uint8_t*)malloc(head_len ? head_len : 1);
    if (!head) return -1;
    uint32_t cookie = 12346, nl = (uint32_t)live;
    memcpy(head, &cookie, 4);
    memcpy(head + 4, &nl, 4);
    uint8_t* hp = head + 8;
    uint32_t* offp = (uint32_t*)(head + 8 + live * 12);
    uint32_t off = (uint32_t)head_len;
    for (int64_t i = 0; i < n_cont; i++) {
        if (ns[i] == 0) continue;
        memcpy(hp, &keys[i], 8);
        uint32_t nm1 = (uint32_t)(ns[i] - 1);
        memcpy(hp + 8, &nm1, 4);
        hp += 12;
        *offp++ = off;
        off += types[i] ? kWordsPerContainer * 8 : (uint32_t)(ns[i] * 4);
    }
    struct iovec hv = {head, (size_t)head_len};
    if (!writev_full(fd, &hv, 1)) { free(head); return -1; }
    free(head);
    // Container blocks via writev, IOV_MAX-sized batches, zero copy.
    const int kBatch = 1024;
    struct iovec iov[kBatch];
    int in = 0;
    for (int64_t i = 0; i < n_cont; i++) {
        if (ns[i] == 0) continue;
        iov[in].iov_base = (void*)ptrs[i];
        iov[in].iov_len = types[i] ? kWordsPerContainer * 8 : ns[i] * 4;
        if (++in == kBatch) {
            if (!writev_full(fd, iov, in)) return -1;
            in = 0;
        }
    }
    if (in && !writev_full(fd, iov, in)) return -1;
    return head_len + body;
}

// ---- native write-path micro-engine ----------------------------------------
// The measured host denominator for the SetBit path (the reference's is
// fragment.go:369-459 driven by ctl/bench.go:71-102; no Go toolchain in
// this image, so this is the C++ stand-in, as popcnt_and is for reads).
// Faithful shape: per op — locate the container (pos>>16), sorted-array
// insert or bitmap set with array->bitmap conversion at 4096, append a
// 13-byte op record to the data file with one unbuffered write(), and
// after every max_op_n ops rewrite a snapshot of all containers to a
// temp file, fsync, and rename over the data file (the same durability
// cadence the Python fragment and the reference both pay).

#include <cstdio>
#include <cstdlib>
#include <fcntl.h>
#include <unistd.h>

namespace {

struct WContainer {
    uint16_t* array;     // sorted u16 values, or null when bitmap
    uint64_t* bitmap;    // u64[1024], or null when array
    int32_t n;
    int32_t cap;
};

const int32_t kArrayMax = 4096;
const int32_t kBitmapWords = 1024;

bool wcontainer_add(WContainer* c, uint16_t v) {
    if (c->bitmap) {
        uint64_t bit = 1ULL << (v & 63);
        if (c->bitmap[v >> 6] & bit) return false;
        c->bitmap[v >> 6] |= bit;
        c->n++;
        return true;
    }
    // binary search
    int32_t lo = 0, hi = c->n;
    while (lo < hi) {
        int32_t mid = (lo + hi) / 2;
        if (c->array[mid] < v) lo = mid + 1; else hi = mid;
    }
    if (lo < c->n && c->array[lo] == v) return false;
    if (c->n + 1 > kArrayMax) {  // convert then set
        uint64_t* bm = (uint64_t*)calloc(kBitmapWords, 8);
        for (int32_t i = 0; i < c->n; i++)
            bm[c->array[i] >> 6] |= 1ULL << (c->array[i] & 63);
        free(c->array);
        c->array = nullptr;
        c->bitmap = bm;
        return wcontainer_add(c, v);
    }
    if (c->n == c->cap) {
        c->cap = c->cap ? c->cap * 2 : 8;
        c->array = (uint16_t*)realloc(c->array, c->cap * 2);
    }
    memmove(c->array + lo + 1, c->array + lo, (c->n - lo) * 2);
    c->array[lo] = v;
    c->n++;
    return true;
}

}  // namespace

// Runs n_ops SetBit ops (64-bit fragment positions) against a data file
// at `path` with WAL append per op and a snapshot rewrite every
// max_op_n ops. Returns ops actually changed (idempotent re-sets don't
// append), or -1 on IO error. Elapsed time is the caller's job.
extern "C" int64_t bench_setbit(const char* path, const uint64_t* positions,
                     int64_t n_ops, int64_t max_op_n) {
    int64_t max_key = 0;
    for (int64_t i = 0; i < n_ops; i++)
        if ((int64_t)(positions[i] >> 16) > max_key)
            max_key = positions[i] >> 16;
    WContainer* conts = (WContainer*)calloc(max_key + 1,
                                            sizeof(WContainer));
    int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) { free(conts); return -1; }

    unsigned char rec[13];
    int64_t changed = 0, op_n = 0;
    char tmp_path[4096];
    snprintf(tmp_path, sizeof tmp_path, "%s.snapshotting", path);

    for (int64_t i = 0; i < n_ops; i++) {
        uint64_t pos = positions[i];
        WContainer* c = &conts[pos >> 16];
        if (!wcontainer_add(c, (uint16_t)(pos & 0xFFFF))) continue;
        changed++;
        // 13-byte op record: type(1) + value(8) + checksum(4) — the
        // same record size the storage WAL appends per mutation.
        rec[0] = 0;
        memcpy(rec + 1, &pos, 8);
        uint32_t sum = (uint32_t)(pos ^ (pos >> 32)) * 2654435761u;
        memcpy(rec + 9, &sum, 4);
        if (write(fd, rec, 13) != 13) { close(fd); free(conts); return -1; }
        if (++op_n > max_op_n) {
            // snapshot: rewrite every live container, fsync, rename.
            int sfd = open(tmp_path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
            if (sfd < 0) { close(fd); free(conts); return -1; }
            for (int64_t k = 0; k <= max_key; k++) {
                WContainer* cc = &conts[k];
                if (cc->n == 0) continue;
                if (cc->bitmap) {
                    if (write(sfd, cc->bitmap, kBitmapWords * 8) < 0)
                        { close(sfd); close(fd); free(conts); return -1; }
                } else {
                    if (write(sfd, cc->array, cc->n * 2) < 0)
                        { close(sfd); close(fd); free(conts); return -1; }
                }
            }
            fsync(sfd);
            close(sfd);
            if (rename(tmp_path, path) != 0)
                { close(fd); free(conts); return -1; }
            close(fd);
            fd = open(path, O_WRONLY | O_APPEND, 0644);
            if (fd < 0) { free(conts); return -1; }
            op_n = 0;
        }
    }
    close(fd);
    for (int64_t k = 0; k <= max_key; k++) {
        free(conts[k].array);
        free(conts[k].bitmap);
    }
    free(conts);
    return changed;
}

// Parse a "digits,digits\n"* byte buffer into u64 row/col arrays in one
// pass (the CSV import fast lane; ~6x numpy's general text parser).
// Strict: exactly two fields per line, CRLF tolerated, any other shape
// (blank line, third field, non-digit, value past 2^64-1) returns -1
// and the caller falls back to the exact per-row Python path that owns
// the error messages. Returns the number of parsed pairs.
extern "C" int64_t parse_csv_u64_pairs(
        const uint8_t* buf, int64_t n, uint64_t* rows, uint64_t* cols,
        int64_t max_pairs) {
    int64_t out = 0;
    int64_t i = 0;
    while (i < n) {
        if (out >= max_pairs) return -1;
        for (int field = 0; field < 2; field++) {
            if (i >= n || buf[i] < '0' || buf[i] > '9') return -1;
            unsigned __int128 v = 0;
            int digits = 0;
            while (i < n && buf[i] >= '0' && buf[i] <= '9') {
                v = v * 10 + (uint64_t)(buf[i] - '0');
                if (++digits > 20) return -1;
                i++;
            }
            if (v > (unsigned __int128)UINT64_MAX) return -1;
            if (field == 0) {
                if (i >= n || buf[i] != ',') return -1;
                i++;
                rows[out] = (uint64_t)v;
            } else {
                cols[out] = (uint64_t)v;
            }
        }
        out++;
        if (i < n) {
            if (buf[i] == '\r') i++;
            if (i >= n || buf[i] != '\n') return -1;
            i++;
        }
    }
    return out;
}
