"""Injected logger threaded through Server/Holder/Fragment/Syncer/Gossip.

Reference: the Go build passes a ``LogOutput io.Writer`` down the same
chain — server/server.go:123-131 opens ``--log-path`` (stderr when
empty), holder.go:360 and fragment.go:329 expose ``logger()`` accessors,
and fragment.go:1012-1020 wraps snapshots in a duration ``track()``.
Here the equivalent is one small thread-safe Logger object with Go
``log.Printf`` semantics; components receive it as a constructor
argument and default to the silent NOP so library use stays quiet.
"""

from __future__ import annotations

import sys
import threading
import time


class Logger:
    """Thread-safe line logger. ``printf`` mirrors Go's log.Printf:
    a %-format string plus args, one timestamped line per call."""

    def __init__(self, stream=None):
        self._stream = stream          # None → silent (the NOP)
        self._owns_stream = False
        self._mu = threading.Lock()

    @classmethod
    def open(cls, path: str) -> "Logger":
        """A logger for ``--log-path``: append to ``path``, or stderr
        when the path is empty (server/server.go:123-131)."""
        if not path:
            return cls(sys.stderr)
        lg = cls(open(path, "a", encoding="utf-8"))
        lg._owns_stream = True
        return lg

    def printf(self, fmt: str, *args) -> None:
        if self._stream is None:
            return
        msg = (fmt % args) if args else fmt
        line = time.strftime("%Y/%m/%d %H:%M:%S ") + msg + "\n"
        with self._mu:
            stream = self._stream  # close() may have nulled it post-check
            if stream is None:
                return
            try:
                stream.write(line)
                stream.flush()
            except (OSError, ValueError):
                pass  # a full disk / closed stream must not kill serving

    def track(self, fmt: str, *args):
        """Context manager logging "<msg> took <dur>" on exit — the
        reference's snapshot timer (fragment.go:1012-1020)."""
        return _Track(self, (fmt % args) if args else fmt)

    def close(self) -> None:
        if self._owns_stream and self._stream is not None:
            with self._mu:
                try:
                    self._stream.close()
                except OSError:
                    pass
                self._stream = None


class _Track:
    def __init__(self, logger: Logger, msg: str):
        self.logger = logger
        self.msg = msg

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.logger.printf("%s took %.6fs", self.msg,
                           time.monotonic() - self._start)
        return False


NOP = Logger(None)
