"""StatsD/DataDog stats backend.

Reference: datadog/datadog.go — a StatsClient speaking the dogstatsd wire
protocol over UDP (datadog.go:38-115). The datadog-go dependency is a thin
formatter around a UDP socket, so this module emits the protocol directly:

    metric.name:value|TYPE|@rate|#tag1:v1,tag2

Types: ``c`` count, ``g`` gauge, ``h`` histogram, ``s`` set, ``ms`` timing
(timings arrive in nanoseconds per the StatsClient contract and are sent
as milliseconds, matching datadog.go:105-113). ``with_tags`` children
accumulate tags hierarchically exactly like the reference's WithTags
(datadog.go:63-75). Sends are fire-and-forget UDP: a missing agent
costs nothing and drops silently, so the hot path never blocks.
"""

from __future__ import annotations

import copy
import socket
from typing import Optional

from .stats import StatsClient

DEFAULT_ADDR = "127.0.0.1:8125"   # dogstatsd agent default (datadog.go:30)


class StatsDStatsClient(StatsClient):
    """dogstatsd-protocol emitter (datadog/datadog.go:38-115)."""

    def __init__(self, addr: str = DEFAULT_ADDR, prefix: str = "pilosa.",
                 tags: Optional[list[str]] = None, _sock=None):
        host, _, port = addr.rpartition(":")
        self._dest = (host or "127.0.0.1", int(port))
        self.prefix = prefix
        self.tags = list(tags or [])
        self._sock = _sock or socket.socket(socket.AF_INET,
                                            socket.SOCK_DGRAM)

    def with_tags(self, *tags: str) -> "StatsDStatsClient":
        child = copy.copy(self)   # children share the socket and dest
        child.tags = sorted(set(self.tags) | set(tags))
        return child

    # -- emitters -----------------------------------------------------------

    def count(self, name: str, value: int = 1) -> None:
        self._send(name, f"{value}|c")

    def gauge(self, name: str, value: float) -> None:
        self._send(name, f"{_num(value)}|g")

    def histogram(self, name: str, value: float) -> None:
        self._send(name, f"{_num(value)}|h")

    def set(self, name: str, value: str) -> None:
        self._send(name, f"{value}|s")

    def timing(self, name: str, value_ns: float) -> None:
        # StatsClient carries nanoseconds; dogstatsd timers take ms
        # (datadog.go:105-113 converts with time.Duration.Seconds()*1000).
        self._send(name, f"{_num(value_ns / 1e6)}|ms")

    def _send(self, name: str, payload: str) -> None:
        msg = f"{self.prefix}{name}:{payload}"
        if self.tags:
            msg += "|#" + ",".join(self.tags)
        try:
            self._sock.sendto(msg.encode(), self._dest)
        except OSError:
            pass   # agent down: drop, never block the caller

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _num(v: float) -> str:
    """Render floats compactly: integral values without the trailing .0."""
    return str(int(v)) if float(v).is_integer() else repr(float(v))
