"""Small stream helpers shared by storage and the HTTP layer."""

from __future__ import annotations


class CappedReader:
    """File-like reader limited to the first n bytes.

    Two users with the same need: fragment backup streams exactly the
    size captured under lock even if the WAL grows after (tar headers
    carry a fixed size), and the WSGI request body has no EOF of its own
    (reading past Content-Length blocks on the live socket).
    """

    def __init__(self, f, n: int):
        self.f = f
        self.remaining = n

    def read(self, size: int = -1) -> bytes:
        if self.remaining <= 0:
            return b""
        if size < 0 or size > self.remaining:
            size = self.remaining
        out = self.f.read(size)
        self.remaining -= len(out)
        return out
