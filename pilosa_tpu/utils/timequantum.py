"""Time quantum engine: time-view naming and range covers.

Reference: time.go. A frame with a time quantum writes each timestamped bit
to one extra view per quantum unit (Y/M/D/H, e.g. ``standard_2017``,
``standard_201701``); a Range query unions the *minimal* set of views
covering [start, end), computed by walking up from fine to coarse units and
back down (time.go:95-167 — semantics preserved exactly, including the
GTE-boundary rules of nextYearGTE/nextMonthGTE/nextDayGTE).
"""

from __future__ import annotations

import datetime as dt

from ..errors import PilosaError

VALID_QUANTUMS = frozenset(
    ["Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""])


def parse_time_quantum(v: str) -> str:
    q = v.upper()
    if q not in VALID_QUANTUMS:
        raise PilosaError(f"invalid time quantum: {v!r}")
    return q


_UNIT_FMT = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}


def view_by_time_unit(name: str, t: dt.datetime, unit: str) -> str:
    fmt = _UNIT_FMT.get(unit)
    if fmt is None:
        return ""
    return f"{name}_{t.strftime(fmt)}"


def views_by_time(name: str, t: dt.datetime, quantum: str) -> list[str]:
    """All per-unit view names a timestamped bit lands in (time.go:81-92)."""
    out = []
    for unit in quantum:
        v = view_by_time_unit(name, t, unit)
        if v:
            out.append(v)
    return out


def _add_months(t: dt.datetime, n: int) -> dt.datetime:
    # Matches Go's AddDate normalization: overflowing days roll forward
    # (Jan 30 + 1mo = "Feb 30" → Mar 1/2). The GTE probes call this from
    # mid-month dates, so the overflow case is reachable.
    month0 = t.month - 1 + n
    year = t.year + month0 // 12
    month = month0 % 12 + 1
    base = dt.datetime(year, month, 1, t.hour, t.minute, t.second,
                       t.microsecond)
    return base + dt.timedelta(days=t.day - 1)


def _add_years(t: dt.datetime, n: int) -> dt.datetime:
    # Feb 29 + 1y = "Feb 29 non-leap" → Mar 1, per Go AddDate normalization.
    return _add_months(t, 12 * n)


def _next_year_gte(t: dt.datetime, end: dt.datetime) -> bool:
    nxt = _add_years(t, 1)
    return nxt.year == end.year or end > nxt


def _next_month_gte(t: dt.datetime, end: dt.datetime) -> bool:
    nxt = _add_months(t, 1)
    return (nxt.year, nxt.month) == (end.year, end.month) or end > nxt


def _next_day_gte(t: dt.datetime, end: dt.datetime) -> bool:
    nxt = t + dt.timedelta(days=1)
    return ((nxt.year, nxt.month, nxt.day)
            == (end.year, end.month, end.day)) or end > nxt


def views_by_time_range(name: str, start: dt.datetime, end: dt.datetime,
                        quantum: str) -> list[str]:
    """Minimal view cover of [start, end) (time.go:95-167)."""
    t = start
    has_y, has_m = "Y" in quantum, "M" in quantum
    has_d, has_h = "D" in quantum, "H" in quantum
    results: list[str] = []

    # Walk up from the smallest units to the largest.
    if has_h or has_d or has_m:
        while t < end:
            if has_h:
                if not _next_day_gte(t, end):
                    break
                if t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t += dt.timedelta(hours=1)
                    continue
            if has_d:
                if not _next_month_gte(t, end):
                    break
                if t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t += dt.timedelta(days=1)
                    continue
            if has_m:
                if not _next_year_gte(t, end):
                    break
                if t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_months(t, 1)
                    continue
            break

    # Walk back down from the largest units to the smallest.
    while t < end:
        if has_y and _next_year_gte(t, end):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _add_years(t, 1)
        elif has_m and _next_month_gte(t, end):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_months(t, 1)
        elif has_d and _next_day_gte(t, end):
            results.append(view_by_time_unit(name, t, "D"))
            t += dt.timedelta(days=1)
        elif has_h:
            results.append(view_by_time_unit(name, t, "H"))
            t += dt.timedelta(hours=1)
        else:
            break

    return results
