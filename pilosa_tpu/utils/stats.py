"""Stats clients (reference stats.go): a minimal metrics abstraction with
tag-scoped children, a no-op default, an expvar-style in-process collector
(surfaced at /debug/vars by the HTTP layer), and a fan-out multiplexer."""

from __future__ import annotations

import threading
from typing import Iterable, Optional


class StatsClient:
    """Interface (reference stats.go:33-54)."""

    def with_tags(self, *tags: str) -> "StatsClient":
        return self

    def count(self, name: str, value: int = 1) -> None:
        pass

    def gauge(self, name: str, value: float) -> None:
        pass

    def histogram(self, name: str, value: float) -> None:
        pass

    def set(self, name: str, value: str) -> None:
        pass

    def timing(self, name: str, value_ns: float) -> None:
        pass


class NopStatsClient(StatsClient):
    pass


NOP = NopStatsClient()


class ExpvarStatsClient(StatsClient):
    """In-process counters keyed by tag-qualified names; JSON-able for
    /debug/vars (reference stats.go:70-130)."""

    def __init__(self, _root: Optional[dict] = None,
                 _prefix: str = "", _lock=None):
        self._root = _root if _root is not None else {}
        self._prefix = _prefix
        self._lock = _lock or threading.Lock()

    def with_tags(self, *tags: str) -> "ExpvarStatsClient":
        prefix = ",".join(filter(None, [self._prefix, *sorted(tags)]))
        return ExpvarStatsClient(self._root, prefix, self._lock)

    def _key(self, name: str) -> str:
        return f"{self._prefix}.{name}" if self._prefix else name

    def count(self, name: str, value: int = 1) -> None:
        with self._lock:
            k = self._key(name)
            self._root[k] = self._root.get(k, 0) + value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._root[self._key(name)] = value

    def histogram(self, name: str, value: float) -> None:
        # Aggregate count/sum/min/max/last per key: the old
        # last-write-wins gauge meant /debug/vars showed whichever
        # sample landed last, not a distribution — a 10 s outlier in a
        # thousand 1 ms timings was invisible (or was ALL you saw).
        with self._lock:
            k = self._key(name)
            cur = self._root.get(k)
            if not isinstance(cur, dict) or "count" not in cur:
                cur = self._root[k] = {"count": 0, "sum": 0.0,
                                       "min": value, "max": value,
                                       "last": value}
            cur["count"] += 1
            cur["sum"] += value
            if value < cur["min"]:
                cur["min"] = value
            if value > cur["max"]:
                cur["max"] = value
            cur["last"] = value

    def set(self, name: str, value: str) -> None:
        with self._lock:
            self._root[self._key(name)] = value

    def timing(self, name: str, value_ns: float) -> None:
        self.histogram(name, value_ns)

    def snapshot(self) -> dict:
        with self._lock:
            # Histogram entries are mutable dicts: copy them so a
            # caller's snapshot can't tear against live updates.
            return {k: dict(v) if isinstance(v, dict) else v
                    for k, v in self._root.items()}


class MultiStatsClient(StatsClient):
    """Fan-out to several clients (reference stats.go:133-185)."""

    def __init__(self, clients: Iterable[StatsClient]):
        self._clients = list(clients)

    def with_tags(self, *tags: str) -> "MultiStatsClient":
        return MultiStatsClient(c.with_tags(*tags) for c in self._clients)

    def count(self, name: str, value: int = 1) -> None:
        for c in self._clients:
            c.count(name, value)

    def gauge(self, name: str, value: float) -> None:
        for c in self._clients:
            c.gauge(name, value)

    def histogram(self, name: str, value: float) -> None:
        for c in self._clients:
            c.histogram(name, value)

    def set(self, name: str, value: str) -> None:
        for c in self._clients:
            c.set(name, value)

    def timing(self, name: str, value_ns: float) -> None:
        for c in self._clients:
            c.timing(name, value_ns)

    def snapshot(self) -> dict:
        """Merged snapshot of every child that has one (the expvar
        child, behind /debug/vars) — composing the registry bridge in
        must not silently blank the expvar page."""
        out: dict = {}
        for c in self._clients:
            snap = getattr(c, "snapshot", None)
            if callable(snap):
                out.update(snap())
        return out
