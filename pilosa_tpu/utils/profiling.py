"""Profiling: sampling CPU profiles and thread dumps.

Reference: Go pprof mounted at ``/debug/pprof`` (handler.go:30,99) plus
the ``--profile.cpu`` / ``--profile.cpu-time`` server flags
(cmd/server.go:47-62,99-100). Go's pprof is a statistical sampler of all
goroutine stacks; the Python-host equivalent here samples
``sys._current_frames()`` across all threads on a fixed interval and
aggregates collapsed stacks (flamegraph-compatible ``a;b;c count``
lines). The device side needs no custom hooks — JAX's own profiler and
XLA dump flags cover TPU kernels; this module profiles the CPU host path
(parsing, routing, roaring maintenance) that surrounds them.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter


def collect_sample(skip_threads: tuple[int, ...] = ()) -> list[str]:
    """One collapsed stack per live thread, innermost frame last."""
    out = []
    for tid, frame in sys._current_frames().items():
        if tid in skip_threads:
            continue
        stack = []
        f = frame
        while f is not None:
            code = f.f_code
            stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
            f = f.f_back
        out.append(";".join(reversed(stack)))
    return out


def sample_profile(seconds: float, interval: float = 0.005) -> str:
    """Sample all thread stacks for ``seconds``; return collapsed-stack
    counts sorted by weight (the pprof-profile equivalent)."""
    counts: Counter[str] = Counter()
    me = threading.get_ident()
    deadline = time.monotonic() + seconds
    n = 0
    while time.monotonic() < deadline:
        for stack in collect_sample(skip_threads=(me,)):
            counts[stack] += 1
        n += 1
        time.sleep(interval)
    lines = [f"# cpu profile: {n} samples over {seconds:g}s "
             f"@ {interval * 1000:g}ms"]
    for stack, c in counts.most_common():
        lines.append(f"{stack} {c}")
    return "\n".join(lines) + "\n"


def thread_dump() -> str:
    """Stack trace of every live thread (the pprof-goroutine
    equivalent)."""
    frames = sys._current_frames()
    lines = []
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        daemon = " daemon" if t.daemon else ""
        lines.append(f"thread {t.name} (id {t.ident}{daemon}):")
        if frame is not None:
            lines.extend(line.rstrip() for line in
                         traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines)


class CPUProfiler:
    """Background sampler for the ``--profile.cpu`` server flag: starts
    on open, writes the collapsed-stack report at stop (or after
    ``duration`` seconds, whichever comes first)."""

    def __init__(self, path: str, duration: float = 30.0,
                 interval: float = 0.005):
        self.path = path
        self.duration = duration
        self.interval = interval
        self._counts: Counter[str] = Counter()
        self._samples = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="cpu-profiler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        me = threading.get_ident()
        deadline = time.monotonic() + self.duration
        while not self._stop.is_set() and time.monotonic() < deadline:
            for stack in collect_sample(skip_threads=(me,)):
                self._counts[stack] += 1
            self._samples += 1
            time.sleep(self.interval)
        self._write()

    def _write(self) -> None:
        lines = [f"# cpu profile: {self._samples} samples "
                 f"@ {self.interval * 1000:g}ms"]
        for stack, c in self._counts.most_common():
            lines.append(f"{stack} {c}")
        with open(self.path, "w") as f:
            f.write("\n".join(lines) + "\n")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
