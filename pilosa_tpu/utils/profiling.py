"""Profiling: sampling CPU profiles and thread dumps.

Reference: Go pprof mounted at ``/debug/pprof`` (handler.go:30,99) plus
the ``--profile.cpu`` / ``--profile.cpu-time`` server flags
(cmd/server.go:47-62,99-100). Go's pprof is a statistical sampler of all
goroutine stacks; the Python-host equivalent here samples
``sys._current_frames()`` across all threads on a fixed interval and
aggregates collapsed stacks (flamegraph-compatible ``a;b;c count``
lines). The device side needs no custom hooks — JAX's own profiler and
XLA dump flags cover TPU kernels; this module profiles the CPU host path
(parsing, routing, roaring maintenance) that surrounds them.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter


# A thread whose innermost Python frame is one of these is blocked in an
# idle primitive (lock/event wait, selector poll), not burning CPU. Go's
# pprof samples on-CPU time via SIGPROF; Python has no per-thread
# equivalent, so this wall-clock sampler drops known-idle leaves instead
# and reports how many it dropped.
_IDLE_LEAVES = {
    ("threading.py", "wait"),
    ("threading.py", "_wait_for_tstate_lock"),
    ("threading.py", "join"),
    ("selectors.py", "select"),
    ("socketserver.py", "serve_forever"),
    ("connection.py", "poll"),
}


def _is_idle_leaf(frame) -> bool:
    code = frame.f_code
    return (code.co_filename.rsplit("/", 1)[-1],
            code.co_name) in _IDLE_LEAVES


# Heap profiling via tracemalloc (the reference gets /debug/pprof/heap
# free from net/http/pprof, handler.go:30,99). tracemalloc costs ~2× on
# allocations while tracing, so arming is explicit and removable
# without a restart — and, since this round, arm/disarm are separate
# MUTATING operations (POST on the endpoint) while the report is a
# pure read (GET): a monitoring system GETing the heap endpoint must
# never toggle interpreter-wide allocation tracing as a side effect.


def heap_start() -> str:
    """Arm tracemalloc (idempotent). One frame per allocation is
    recorded: the report groups by source line and never reads deeper
    frames."""
    import tracemalloc
    if tracemalloc.is_tracing():
        return "tracemalloc already tracing.\n"
    tracemalloc.start(1)
    return ("tracemalloc started. Allocations are now traced; GET the "
            "endpoint for the report, POST ?op=stop to disarm (tracing "
            "costs ~2x on allocation-heavy paths).\n")


def heap_stop() -> str:
    """Disarm tracemalloc (idempotent)."""
    import tracemalloc
    if tracemalloc.is_tracing():
        tracemalloc.stop()
        return "tracemalloc stopped; allocation tracing disarmed.\n"
    return "tracemalloc was not tracing.\n"


def heap_report(top_n: int = 30) -> str:
    """Allocation-site report — a pure read; arming state is
    untouched."""
    import tracemalloc
    if not tracemalloc.is_tracing():
        return ("tracemalloc is not tracing. POST "
                "/debug/pprof/heap?op=start to arm it, then GET for "
                "the report.\n")
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")
    total = sum(s.size for s in stats)
    lines = [f"traced memory: {total / (1 << 20):.1f} MiB in "
             f"{sum(s.count for s in stats)} blocks "
             f"(top {min(top_n, len(stats))} sites)\n"]
    for s in stats[:top_n]:
        fr = s.traceback[0]
        lines.append(f"{s.size / 1024:10.1f} KiB {s.count:8d} blocks  "
                     f"{fr.filename.rsplit('/', 1)[-1]}:{fr.lineno}\n")
    return "".join(lines)


def collect_sample(skip_threads: tuple[int, ...] = (),
                   include_idle: bool = True) -> list[str]:
    """One collapsed stack per live thread, innermost frame last."""
    out = []
    for tid, frame in sys._current_frames().items():
        if tid in skip_threads:
            continue
        if not include_idle and _is_idle_leaf(frame):
            continue
        stack = []
        f = frame
        while f is not None:
            code = f.f_code
            stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
            f = f.f_back
        out.append(";".join(reversed(stack)))
    return out


def _sample_loop(seconds: float, interval: float,
                 stop: threading.Event | None = None
                 ) -> tuple[Counter, int, int]:
    """Shared sampler: returns (stack counts, #samples, #idle dropped)."""
    counts: Counter[str] = Counter()
    me = threading.get_ident()
    deadline = time.monotonic() + seconds
    n = idle = 0
    while time.monotonic() < deadline and (stop is None
                                           or not stop.is_set()):
        frames = sys._current_frames()
        for tid, frame in frames.items():
            if tid == me:
                continue
            if _is_idle_leaf(frame):
                idle += 1
                continue
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            counts[";".join(reversed(stack))] += 1
        n += 1
        time.sleep(interval)
    return counts, n, idle


def _format_report(counts: Counter, samples: int, idle: int,
                   interval: float) -> str:
    lines = [f"# cpu profile (wall-clock sampler, idle leaves dropped): "
             f"{samples} samples, {idle} idle stacks dropped "
             f"@ {interval * 1000:g}ms"]
    for stack, c in counts.most_common():
        lines.append(f"{stack} {c}")
    return "\n".join(lines) + "\n"


def sample_profile(seconds: float, interval: float = 0.005) -> str:
    """Sample all thread stacks for ``seconds``; return collapsed-stack
    counts sorted by weight (the pprof-profile equivalent)."""
    counts, n, idle = _sample_loop(seconds, interval)
    return _format_report(counts, n, idle, interval)


def thread_dump() -> str:
    """Stack trace of every live thread (the pprof-goroutine
    equivalent)."""
    frames = sys._current_frames()
    lines = []
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        daemon = " daemon" if t.daemon else ""
        lines.append(f"thread {t.name} (id {t.ident}{daemon}):")
        if frame is not None:
            lines.extend(line.rstrip() for line in
                         traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines)


class CPUProfiler:
    """Background sampler for the ``--profile.cpu`` server flag: starts
    on open, writes the collapsed-stack report at stop (or after
    ``duration`` seconds, whichever comes first)."""

    def __init__(self, path: str, duration: float = 30.0,
                 interval: float = 0.005):
        self.path = path
        self.duration = duration
        self.interval = interval
        self._counts: Counter[str] = Counter()
        self._samples = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="cpu-profiler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        self._counts, self._samples, idle = _sample_loop(
            self.duration, self.interval, stop=self._stop)
        with open(self.path, "w") as f:
            f.write(_format_report(self._counts, self._samples, idle,
                                   self.interval))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
