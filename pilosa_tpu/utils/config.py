"""Configuration: TOML file + PILOSA_* environment + flags.

Reference: config.go (schema at config.go:34-57, defaults :59-71) and
cmd/root.go:99-153 (viper merge priority: flags > env > file). The same
priority holds here: load() starts from defaults, overlays the TOML
file, then ``PILOSA_*`` environment variables, and the CLI overlays
explicit flags last.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

try:
    import tomllib  # Python 3.11+
except ModuleNotFoundError:  # pragma: no cover - version-dependent
    try:
        import tomli as tomllib  # the 3.10 backport, if installed
    except ModuleNotFoundError:
        # No TOML parser on this interpreter: everything except
        # --config (defaults, env, flags) still works — fail only if a
        # config FILE is actually requested, not at import time (the
        # unconditional import broke every CLI/server entry point on
        # 3.10 containers).
        tomllib = None

DEFAULT_HOST = "localhost"
DEFAULT_PORT = "10101"
DEFAULT_CLUSTER_TYPE = "static"
DEFAULT_REPLICA_N = 1
DEFAULT_POLLING_INTERVAL = 60.0
DEFAULT_ANTI_ENTROPY_INTERVAL = 600.0
DEFAULT_INTERNAL_PORT = "14000"   # gossip port (config.go:25-31)


def parse_duration(v) -> float:
    """Go-style duration string ("10m", "1h30m", "45s") → seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    units = {"ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0,
             "h": 3600.0}
    total = 0.0
    matched = False
    for num, unit in re.findall(r"([0-9.]+)(ns|us|ms|s|m|h)", str(v)):
        total += float(num) * units[unit]
        matched = True
    if not matched:
        raise ValueError(f"invalid duration: {v!r}")
    return total


@dataclass
class ClusterConfig:
    replica_n: int = DEFAULT_REPLICA_N
    type: str = DEFAULT_CLUSTER_TYPE          # static | http | gossip
    hosts: list[str] = field(default_factory=list)
    internal_hosts: list[str] = field(default_factory=list)
    polling_interval: float = DEFAULT_POLLING_INTERVAL
    internal_port: str = DEFAULT_INTERNAL_PORT  # gossip bind port
    gossip_seed: str = ""                       # seed "host:port" to join
    gossip_secret: str = ""                     # HMAC key for gossip frames
    # Staleness bound (seconds) on the coordinator generation map
    # (cluster.generations): remote-slice cache keys stop trusting a
    # peer's tokens this long after the last exchange with it. Writes
    # routed through this coordinator invalidate on their own response
    # — the bound only governs out-of-band writes (docs/DISTRIBUTED.md).
    gen_staleness: float = 2.0
    # Elastic resize (cluster.resize; docs/CLUSTER_RESIZE.md):
    # ``resize_pace`` (seconds) breathes between streamed blocks so a
    # migration never saturates a serving node; ``resize_grace``
    # (seconds) keeps the previous epoch's owners write-accepting
    # after finalize so straggler coordinators' union-writes don't
    # bounce.
    resize_pace: float = 0.0
    resize_grace: float = 30.0


# Query lifecycle defaults (sched subsystem; docs/SCHEDULING.md).
DEFAULT_QUERY_CONCURRENCY = 16
DEFAULT_QUERY_QUEUE_DEPTH = 64


# Executor cache defaults (docs/DISTRIBUTED.md): the materialized
# bitmap-result residency bounds and the coordinator hot-query cache.
DEFAULT_RESULT_CACHE_ENTRIES = 8
DEFAULT_RESULT_CACHE_BITS = 32 << 20
DEFAULT_CLUSTER_CACHE_ENTRIES = 64


@dataclass
class QueryConfig:
    """[query] section: the sched subsystem's knobs. concurrency/
    queue_depth bound the admission controller (overflow answers 429);
    default_timeout (seconds, 0 = none) applies when a request carries
    neither ?timeout= nor X-Pilosa-Deadline; slow_threshold (seconds,
    0 = disabled) arms the slow-query log. result_cache_entries/_bits
    bound the executor's materialized-result residency cache;
    cluster_cache_entries bounds the coordinator hot-query result
    cache (0 disables either)."""
    concurrency: int = DEFAULT_QUERY_CONCURRENCY
    queue_depth: int = DEFAULT_QUERY_QUEUE_DEPTH
    default_timeout: float = 0.0
    slow_threshold: float = 0.0
    result_cache_entries: int = DEFAULT_RESULT_CACHE_ENTRIES
    result_cache_bits: int = DEFAULT_RESULT_CACHE_BITS
    cluster_cache_entries: int = DEFAULT_CLUSTER_CACHE_ENTRIES


# -- [tenants]: per-tenant QoS (sched.tenants; docs/SCHEDULING.md) -----------
# One sub-table per tenant (tenant = index). The ``default`` entry is
# MANDATORY whenever the table is present: it is what unknown tenants
# (new indexes, forwarded legs with no header) schedule under, so a
# table without it would silently drop them on the floor.

_TENANT_KEYS = ("weight", "concurrency", "queue-depth",
                "max-container-ops", "max-device-bytes", "max-wall",
                "cache-share")

DEFAULT_TENANT = "default"


def validate_tenant_entry(name: str, entry) -> dict:
    """One ``[tenants.<name>]`` table → normalized snake_case dict.
    Fails LOUDLY (ValueError) on unknown keys, non-positive weights,
    or out-of-range shares — a half-parsed QoS table that silently
    drops a ceiling is an isolation hole, not a default."""
    if not isinstance(entry, dict):
        raise ValueError(f"[tenants.{name}]: expected a table,"
                         f" got {type(entry).__name__}")
    unknown = sorted(set(entry) - set(_TENANT_KEYS))
    if unknown:
        raise ValueError(
            f"[tenants.{name}]: unknown key(s) {', '.join(unknown)}"
            f" (valid: {', '.join(_TENANT_KEYS)})")
    out: dict = {}
    if "weight" in entry:
        w = float(entry["weight"])
        if w <= 0:
            raise ValueError(
                f"[tenants.{name}]: weight must be positive, got {w}")
        out["weight"] = w
    for key, attr in (("concurrency", "concurrency"),
                      ("queue-depth", "queue_depth"),
                      ("max-container-ops", "max_container_ops"),
                      ("max-device-bytes", "max_device_bytes")):
        if key in entry:
            v = int(entry[key])
            if v < 0:
                raise ValueError(f"[tenants.{name}]: {key} must be"
                                 f" >= 0 (0 = unlimited), got {v}")
            out[attr] = v
    if "max-wall" in entry:
        v = parse_duration(entry["max-wall"])
        if v < 0:
            raise ValueError(f"[tenants.{name}]: max-wall must be"
                             f" >= 0 (0 = unlimited), got {v}")
        out["max_wall_s"] = v
    if "cache-share" in entry:
        v = float(entry["cache-share"])
        if not 0.0 < v <= 1.0:
            raise ValueError(
                f"[tenants.{name}]: cache-share must be in (0, 1],"
                f" got {v}")
        out["cache_share"] = v
    return out


def parse_tenant_table(table) -> dict[str, dict]:
    """The whole ``[tenants]`` TOML table → {name: normalized dict}.
    A present-but-defaultless table fails loudly."""
    if not isinstance(table, dict):
        raise ValueError("[tenants]: expected a table of tables")
    out = {str(name): validate_tenant_entry(str(name), entry)
           for name, entry in table.items()}
    if out and DEFAULT_TENANT not in out:
        raise ValueError(
            "[tenants]: a 'default' entry is required — it is what"
            " unknown tenants schedule and account under")
    return out


def parse_tenants(raw: str) -> dict[str, dict]:
    """Compact env/flag form of the tenant table (PILOSA_TENANTS /
    --tenants), same key vocabulary as the TOML::

        default:weight=4,concurrency=8;bulk:weight=1,max-wall=2s

    ``;`` separates tenants, ``name:`` starts one, ``,``-separated
    ``key=value`` pairs follow. Same loud validation as the table."""
    table: dict = {}
    for part in str(raw).split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, body = part.partition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"invalid tenant spec {part!r}: expected"
                f" name:key=value[,key=value...]")
        entry: dict = {}
        for kv in body.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, eq, v = kv.partition("=")
            if not eq:
                raise ValueError(
                    f"invalid tenant spec {part!r}: {kv!r} is not"
                    f" key=value")
            entry[k.strip()] = v.strip()
        table[name] = entry
    return parse_tenant_table(table)


@dataclass
class TenantsConfig:
    """[tenants] section (sched.tenants; docs/SCHEDULING.md): the
    per-tenant QoS table — weight (second-level stride share within
    each lane), concurrency / queue-depth (per-tenant slot cap and
    queue quota; overflow 429s only that tenant), max-container-ops /
    max-device-bytes / max-wall (slow-query kill ceilings over the
    live cost ledger; 0 = unlimited), cache-share (fraction of the
    result-cache budgets one tenant may occupy). ``table`` maps
    tenant name → normalized entry; empty = every tenant rides the
    built-in default policy."""
    table: dict = field(default_factory=dict)


@dataclass
class MetricsConfig:
    """[metrics] section (obs subsystem): ``enabled`` gates the
    /metrics endpoint, the StatsClient→registry bridge, and the
    runtime collector; ``runtime_interval`` (seconds) paces the
    collector's background sampling; ``accounting`` gates the
    per-query cost ledger (obs.accounting — on by default, plain-int
    increments). ``federate_timeout``/``federate_fanout`` bound the
    cluster-federation fan-out (obs.federate): per-peer scrape
    deadline and max parallel legs."""
    enabled: bool = True
    runtime_interval: float = 10.0
    accounting: bool = True
    federate_timeout: float = 2.0
    federate_fanout: int = 8


def parse_resolutions(raw: str) -> tuple[tuple[float, int], ...]:
    """``"10s:360,1m:720,15m:672"`` → ((10.0, 360), ...) — the metric
    history's (step, ring-capacity) ladder. The store hard-depends on
    finest-first ordering (resolutions[0] drives the sampling guard
    and every window walk assumes steps grow with index), so this IS
    the validation gate: steps must be strictly ascending and every
    capacity positive — a misconfigured ladder fails loudly at load
    instead of serving garbage history to a blinded sentinel."""
    out = []
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        step_s, _, cap = part.partition(":")
        step, points = parse_duration(step_s), int(cap)
        if step <= 0 or points <= 0:
            raise ValueError(
                f"invalid history resolution {part!r}: step and"
                f" capacity must be positive")
        if out and step <= out[-1][0]:
            raise ValueError(
                f"history resolutions must be strictly ascending"
                f" (finest first): {raw!r}")
        out.append((step, points))
    if not out:
        raise ValueError(f"invalid history resolutions: {raw!r}")
    return tuple(out)


@dataclass
class HistoryConfig:
    """[history] section (obs.history): the embedded on-disk metric
    history. ``resolutions`` is the step:capacity ladder (finest
    first); ``segment_bytes`` × ``segments`` bound each resolution's
    disk ring; ``max_series`` caps the in-memory series count."""
    enabled: bool = True
    resolutions: str = "10s:360,1m:720,15m:672"
    segment_bytes: int = 1 << 20
    segments: int = 8
    max_series: int = 4096


@dataclass
class SentinelConfig:
    """[sentinel] section (obs.sentinel): the regression sentinel.
    ``interval`` paces evaluation; a robust-z rule fires when the
    recent ``window`` median sits ``zscore`` MAD-scaled deviations
    past the trailing ``baseline`` median AND at least ``min_ratio``
    times it; ``manifest`` points at a committed benchmarks/
    MANIFEST.json whose envelope (× ``manifest_tolerance``) live
    medians must stay inside; ``retrip`` rate-limits re-fires per
    series."""
    enabled: bool = True
    interval: float = 30.0
    window: float = 120.0
    baseline: float = 3600.0
    zscore: float = 6.0
    min_points: int = 5
    min_ratio: float = 1.5
    retrip: float = 300.0
    manifest: str = ""
    manifest_tolerance: float = 5.0


@dataclass
class ProfileConfig:
    """[profile] section (obs subsystem): the ALWAYS-ON low-Hz
    continuous wall profiler behind ``GET /debug/pprof/flame``
    (obs.profile). ``continuous`` turns it off entirely; ``hz`` is the
    sampling rate (default 10 — microseconds of work per tick);
    ``ring`` bounds the retained sample count."""
    continuous: bool = True
    hz: float = 10.0
    ring: int = 8192


@dataclass
class SLOConfig:
    """[slo] section (obs subsystem): the latency objective the
    rolling burn rates (obs.slo.SLOTracker) are computed against —
    fraction ``target`` of queries must finish within ``objective``
    seconds."""
    objective: float = 0.25
    target: float = 0.99


@dataclass
class FaultConfig:
    """[fault] section (fault subsystem; docs/FAULT_TOLERANCE.md):
    ``enabled`` gates peer health tracking + circuit breakers;
    ``breaker_threshold`` consecutive transport failures trip a peer's
    breaker open; the open window backs off exponentially from
    ``breaker_backoff`` up to ``breaker_backoff_cap`` with full
    jitter; ``hedge`` (seconds, 0 = off) arms hedged reads — a second
    replica leg fires when the first exceeds max(hedge, the peer's
    p95-ish latency estimate). ``failpoints`` maps injection sites to
    spec strings ([fault.failpoints] in TOML, PILOSA_FAULT_<SITE> in
    the environment); ``seed`` (PILOSA_FAULT_SEED) makes probabilistic
    failpoint schedules replay deterministically."""
    enabled: bool = True
    breaker_threshold: int = 3
    breaker_backoff: float = 0.5
    breaker_backoff_cap: float = 30.0
    hedge: float = 0.0
    failpoints: dict = field(default_factory=dict)
    seed: int = 0


@dataclass
class TraceConfig:
    """[trace] section (obs subsystem): ``enabled`` keeps EVERY
    query's trace (off by default; ``?trace=1`` opts in per request
    either way); ``max_traces``/``max_spans`` bound the per-node ring.

    Tail sampling (on by default — docs/OBSERVABILITY.md): ``tail``
    gives every query the span buffer and keeps the interesting ones
    at query end (slow / errored / deadline / cancelled / partial /
    shed / breaker / failpoint / 1-in-``head_n`` head sample);
    ``slow_floor`` floors the histogram-derived slow threshold. Kept
    traces persist to a disk segment ring under the data dir bounded
    by ``disk_segment_bytes`` × ``disk_segments`` (the retention
    knobs), browsable via /debug/traces?source=disk."""
    enabled: bool = False
    max_traces: int = 64
    max_spans: int = 512
    tail: bool = True
    head_n: int = 1000
    slow_floor: float = 0.1
    disk_segment_bytes: int = 1 << 20
    disk_segments: int = 8


@dataclass
class BlackboxConfig:
    """[blackbox] section (obs.blackbox): the flight recorder.
    ``interval`` paces the periodic whole-system snapshot;
    ``segment_bytes`` × ``segments`` bound the on-disk ring;
    ``dumps`` bounds the retained full-dump files."""
    enabled: bool = True
    interval: float = 10.0
    segment_bytes: int = 256 << 10
    segments: int = 4
    dumps: int = 4


@dataclass
class WatchdogConfig:
    """[watchdog] section (obs.watchdog): the stall watchdog.
    ``interval`` paces the detectors; ``wal_stall`` is the WAL
    dirty-age threshold, ``deadline_grace`` the past-deadline grace
    for running legs, ``gossip_silence`` the membership-silence bound,
    ``queue_stall`` the no-grant-while-queued bound; ``resize_stall``
    the no-progress bound on an elastic resize this node coordinates;
    ``scrub_stall`` the no-progress bound on an in-flight storage
    scrub pass (storage.scrub); ``tier_stall`` the no-progress bound
    while the tier working-set manager has pending work
    (tier.manager); ``backup_stall`` the no-progress bound on an
    in-flight cluster backup this node coordinates (backup
    coordinator); ``retrip`` rate-limits repeat trips per cause
    (0 on any threshold disables that detector)."""
    enabled: bool = True
    interval: float = 1.0
    wal_stall: float = 5.0
    deadline_grace: float = 5.0
    gossip_silence: float = 60.0
    queue_stall: float = 10.0
    resize_stall: float = 60.0
    scrub_stall: float = 300.0
    tier_stall: float = 120.0
    backup_stall: float = 120.0
    retrip: float = 60.0


@dataclass
class ScrubConfig:
    """[scrub] section (storage.scrub): the background storage-
    integrity scrubber. ``interval`` is the pause between passes;
    ``pace`` the sleep between fragments WITHIN a pass (serving
    traffic owns the disk — the scrub breathes); ``repair`` gates the
    automatic replica re-stream of quarantined fragments
    (server.repair); ``repair_rescan`` its rescan/retry cadence."""
    enabled: bool = True
    interval: float = 600.0
    pace: float = 0.01
    repair: bool = True
    repair_rescan: float = 15.0


@dataclass
class TierConfig:
    """[tier] section (tier.manager): the tiered-storage working-set
    manager. ``resident_budget`` is the byte budget for the resident
    (hot + faulted-cold) set — 0 disables watermark eviction;
    ``high_watermark``/``low_watermark`` are the fractions of that
    budget where eviction starts and stops; ``idle`` the no-touch age
    before an open fragment becomes a demotion candidate;
    ``blob_idle`` the additional cold age before a demoted fragment
    is pushed off local disk into the blob store; ``cold_dir`` roots
    the blob staging area and the local-dir blob backend (defaults to
    ``<data-dir>/_tier``); ``blob`` selects the blob backend
    (``""`` = no blob tier, ``dir`` = the local-dir backend standing
    in for object storage); ``interval`` paces the manager loop;
    ``prefetch_interval`` the history-driven prefetcher cadence
    (0 = off); ``pace`` the sleep between per-fragment transitions
    within one pass (serving traffic owns the disk)."""
    enabled: bool = False
    resident_budget: int = 0
    high_watermark: float = 0.9
    low_watermark: float = 0.7
    idle: float = 300.0
    blob_idle: float = 3600.0
    cold_dir: str = ""
    blob: str = ""
    interval: float = 10.0
    prefetch_interval: float = 0.0
    pace: float = 0.01


@dataclass
class CaptureConfig:
    """[capture] section (obs.capture): the workload-capture plane —
    every served query/import appends a replayable record to an
    on-disk segment ring under ``<data>/capture/``. ``mode`` is
    ``off`` | ``sampled`` | ``full``: off is a nop-cost path, sampled
    (the default) records every write/import plus 1-in-``sample-n``
    reads, full records everything. ``segment-bytes`` × ``segments``
    bound the ring (the byte budget). ``redact`` is a comma-separated
    tenant list ("*" = all) whose PQL string/numeric literals are
    replaced with ``?`` before recording."""
    mode: str = "sampled"
    sample_n: int = 16
    segment_bytes: int = 1 << 20
    segments: int = 8
    redact: str = ""


@dataclass
class BackupConfig:
    """[backup] section (backup package): the disaster-recovery
    archive. ``archive`` selects the archive blob backend (same spec
    grammar as ``tier.blob``: ``""`` = no archive, ``dir:<path>`` =
    the local-dir backend standing in for object storage; bare
    ``dir`` roots it at ``<data-dir>/_archive``); ``wal_interval``
    paces the continuous WAL-segment archiver flush (the
    point-in-time-recovery granularity is bounded by it);
    ``keep_fulls`` is the retention floor — GC keeps the newest N
    full backups plus every incremental and WAL segment any of them
    depend on."""
    archive: str = ""
    wal_interval: float = 2.0
    keep_fulls: int = 2


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() not in ("0", "false", "no", "off", "")


@dataclass
class Config:
    data_dir: str = "~/.pilosa"
    host: str = f"{DEFAULT_HOST}:{DEFAULT_PORT}"
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    query: QueryConfig = field(default_factory=QueryConfig)
    tenants: TenantsConfig = field(default_factory=TenantsConfig)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    history: HistoryConfig = field(default_factory=HistoryConfig)
    sentinel: SentinelConfig = field(default_factory=SentinelConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)
    blackbox: BlackboxConfig = field(default_factory=BlackboxConfig)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    scrub: ScrubConfig = field(default_factory=ScrubConfig)
    tier: TierConfig = field(default_factory=TierConfig)
    capture: CaptureConfig = field(default_factory=CaptureConfig)
    backup: BackupConfig = field(default_factory=BackupConfig)
    profile: ProfileConfig = field(default_factory=ProfileConfig)
    slo: SLOConfig = field(default_factory=SLOConfig)
    fault: FaultConfig = field(default_factory=FaultConfig)
    anti_entropy_interval: float = DEFAULT_ANTI_ENTROPY_INTERVAL
    log_path: str = ""
    # Accepted and persisted but inert, exactly like the reference at
    # this vintage: config.go:48-50 declares [plugins] path and
    # cmd/server.go:96 flags it, but nothing ever loads a plugin.
    plugins_path: str = ""

    def to_toml(self) -> str:
        hosts = ", ".join(f'"{h}"' for h in self.cluster.hosts)
        internal = ", ".join(f'"{h}"' for h in self.cluster.internal_hosts)
        failpoints = "".join(
            f'"{site}" = "{spec}"\n'
            for site, spec in sorted(self.fault.failpoints.items()))
        if failpoints:
            failpoints = "\n[fault.failpoints]\n" + failpoints
        toml_keys = {"weight": "weight", "concurrency": "concurrency",
                     "queue_depth": "queue-depth",
                     "max_container_ops": "max-container-ops",
                     "max_device_bytes": "max-device-bytes",
                     "max_wall_s": "max-wall",
                     "cache_share": "cache-share"}
        tenants = ""
        for name, entry in sorted(self.tenants.table.items()):
            tenants += f"\n[tenants.{name}]\n"
            for attr, key in toml_keys.items():
                if attr in entry:
                    v = entry[attr]
                    tenants += (f'{key} = "{v}s"\n'
                                if key == "max-wall" else
                                f"{key} = {v}\n")

        def dur(v: float) -> str:
            # Sub-second values must survive the round trip ("0.5s"
            # parses back to 0.5; int-truncation would write "0s",
            # silently disabling the knob).
            return f"{int(v)}s" if v == int(v) else f"{v}s"
        return f"""data-dir = "{self.data_dir}"
host = "{self.host}"
log-path = "{self.log_path}"

[cluster]
replicas = {self.cluster.replica_n}
type = "{self.cluster.type}"
hosts = [{hosts}]
internal-hosts = [{internal}]
polling-interval = "{int(self.cluster.polling_interval)}s"
internal-port = "{self.cluster.internal_port}"
gossip-seed = "{self.cluster.gossip_seed}"
gossip-secret = "{self.cluster.gossip_secret}"
gen-staleness = "{dur(self.cluster.gen_staleness)}"
resize-pace = "{dur(self.cluster.resize_pace)}"
resize-grace = "{dur(self.cluster.resize_grace)}"

[query]
concurrency = {self.query.concurrency}
queue-depth = {self.query.queue_depth}
default-timeout = "{dur(self.query.default_timeout)}"
slow-threshold = "{dur(self.query.slow_threshold)}"
result-cache-entries = {self.query.result_cache_entries}
result-cache-bits = {self.query.result_cache_bits}
cluster-cache-entries = {self.query.cluster_cache_entries}
{tenants}
[metrics]
enabled = {str(self.metrics.enabled).lower()}
runtime-interval = "{dur(self.metrics.runtime_interval)}"
accounting = {str(self.metrics.accounting).lower()}
federate-timeout = "{dur(self.metrics.federate_timeout)}"
federate-fanout = {self.metrics.federate_fanout}

[history]
enabled = {str(self.history.enabled).lower()}
resolutions = "{self.history.resolutions}"
segment-bytes = {self.history.segment_bytes}
segments = {self.history.segments}
max-series = {self.history.max_series}

[sentinel]
enabled = {str(self.sentinel.enabled).lower()}
interval = "{dur(self.sentinel.interval)}"
window = "{dur(self.sentinel.window)}"
baseline = "{dur(self.sentinel.baseline)}"
zscore = {self.sentinel.zscore}
min-points = {self.sentinel.min_points}
min-ratio = {self.sentinel.min_ratio}
retrip = "{dur(self.sentinel.retrip)}"
manifest = "{self.sentinel.manifest}"
manifest-tolerance = {self.sentinel.manifest_tolerance}

[trace]
enabled = {str(self.trace.enabled).lower()}
max-traces = {self.trace.max_traces}
max-spans = {self.trace.max_spans}
tail = {str(self.trace.tail).lower()}
head-n = {self.trace.head_n}
slow-floor = "{dur(self.trace.slow_floor)}"
disk-segment-bytes = {self.trace.disk_segment_bytes}
disk-segments = {self.trace.disk_segments}

[blackbox]
enabled = {str(self.blackbox.enabled).lower()}
interval = "{dur(self.blackbox.interval)}"
segment-bytes = {self.blackbox.segment_bytes}
segments = {self.blackbox.segments}
dumps = {self.blackbox.dumps}

[watchdog]
enabled = {str(self.watchdog.enabled).lower()}
interval = "{dur(self.watchdog.interval)}"
wal-stall = "{dur(self.watchdog.wal_stall)}"
deadline-grace = "{dur(self.watchdog.deadline_grace)}"
gossip-silence = "{dur(self.watchdog.gossip_silence)}"
queue-stall = "{dur(self.watchdog.queue_stall)}"
resize-stall = "{dur(self.watchdog.resize_stall)}"
scrub-stall = "{dur(self.watchdog.scrub_stall)}"
tier-stall = "{dur(self.watchdog.tier_stall)}"
backup-stall = "{dur(self.watchdog.backup_stall)}"
retrip = "{dur(self.watchdog.retrip)}"

[scrub]
enabled = {str(self.scrub.enabled).lower()}
interval = "{dur(self.scrub.interval)}"
pace = "{dur(self.scrub.pace)}"
repair = {str(self.scrub.repair).lower()}
repair-rescan = "{dur(self.scrub.repair_rescan)}"

[tier]
enabled = {str(self.tier.enabled).lower()}
resident-budget = {self.tier.resident_budget}
high-watermark = {self.tier.high_watermark}
low-watermark = {self.tier.low_watermark}
idle = "{dur(self.tier.idle)}"
blob-idle = "{dur(self.tier.blob_idle)}"
cold-dir = "{self.tier.cold_dir}"
blob = "{self.tier.blob}"
interval = "{dur(self.tier.interval)}"
prefetch-interval = "{dur(self.tier.prefetch_interval)}"
pace = "{dur(self.tier.pace)}"

[capture]
mode = "{self.capture.mode}"
sample-n = {self.capture.sample_n}
segment-bytes = {self.capture.segment_bytes}
segments = {self.capture.segments}
redact = "{self.capture.redact}"

[backup]
archive = "{self.backup.archive}"
wal-interval = "{dur(self.backup.wal_interval)}"
keep-fulls = {self.backup.keep_fulls}

[profile]
continuous = {str(self.profile.continuous).lower()}
hz = {self.profile.hz}
ring = {self.profile.ring}

[slo]
objective = "{dur(self.slo.objective)}"
target = {self.slo.target}

[fault]
enabled = {str(self.fault.enabled).lower()}
breaker-threshold = {self.fault.breaker_threshold}
breaker-backoff = "{dur(self.fault.breaker_backoff)}"
breaker-backoff-cap = "{dur(self.fault.breaker_backoff_cap)}"
hedge = "{dur(self.fault.hedge)}"
seed = {self.fault.seed}
{failpoints}
[plugins]
path = "{self.plugins_path}"

[anti-entropy]
interval = "{int(self.anti_entropy_interval)}s"
"""


def load(path: str = "", env: dict | None = None) -> Config:
    """Defaults ← TOML file ← PILOSA_* env (cmd/root.go:99-153)."""
    cfg = Config()
    if path:
        if tomllib is None:
            raise RuntimeError(
                "config file given but no TOML parser is available"
                " (needs Python 3.11+ tomllib or the tomli package)")
        with open(path, "rb") as f:
            data = tomllib.load(f)
        cfg.data_dir = data.get("data-dir", cfg.data_dir)
        cfg.host = data.get("host", cfg.host)
        cfg.log_path = data.get("log-path", cfg.log_path)
        cl = data.get("cluster", {})
        cfg.cluster.replica_n = int(cl.get("replicas",
                                           cfg.cluster.replica_n))
        cfg.cluster.type = cl.get("type", cfg.cluster.type)
        cfg.cluster.hosts = list(cl.get("hosts", cfg.cluster.hosts))
        cfg.cluster.internal_hosts = list(
            cl.get("internal-hosts", cfg.cluster.internal_hosts))
        if "polling-interval" in cl:
            cfg.cluster.polling_interval = parse_duration(
                cl["polling-interval"])
        cfg.cluster.internal_port = str(cl.get("internal-port",
                                               cfg.cluster.internal_port))
        cfg.cluster.gossip_seed = cl.get("gossip-seed",
                                         cfg.cluster.gossip_seed)
        cfg.cluster.gossip_secret = cl.get("gossip-secret",
                                           cfg.cluster.gossip_secret)
        if "gen-staleness" in cl:
            cfg.cluster.gen_staleness = parse_duration(
                cl["gen-staleness"])
        if "resize-pace" in cl:
            cfg.cluster.resize_pace = parse_duration(cl["resize-pace"])
        if "resize-grace" in cl:
            cfg.cluster.resize_grace = parse_duration(
                cl["resize-grace"])
        ae = data.get("anti-entropy", {})
        if "interval" in ae:
            cfg.anti_entropy_interval = parse_duration(ae["interval"])
        q = data.get("query", {})
        cfg.query.concurrency = int(q.get("concurrency",
                                          cfg.query.concurrency))
        cfg.query.queue_depth = int(q.get("queue-depth",
                                          cfg.query.queue_depth))
        if "default-timeout" in q:
            cfg.query.default_timeout = parse_duration(
                q["default-timeout"])
        if "slow-threshold" in q:
            cfg.query.slow_threshold = parse_duration(
                q["slow-threshold"])
        cfg.query.result_cache_entries = int(q.get(
            "result-cache-entries", cfg.query.result_cache_entries))
        cfg.query.result_cache_bits = int(q.get(
            "result-cache-bits", cfg.query.result_cache_bits))
        cfg.query.cluster_cache_entries = int(q.get(
            "cluster-cache-entries", cfg.query.cluster_cache_entries))
        if "tenants" in data:
            cfg.tenants.table = parse_tenant_table(data["tenants"])
        m = data.get("metrics", {})
        if "enabled" in m:
            cfg.metrics.enabled = _parse_bool(m["enabled"])
        if "runtime-interval" in m:
            cfg.metrics.runtime_interval = parse_duration(
                m["runtime-interval"])
        if "accounting" in m:
            cfg.metrics.accounting = _parse_bool(m["accounting"])
        if "federate-timeout" in m:
            cfg.metrics.federate_timeout = parse_duration(
                m["federate-timeout"])
        if "federate-fanout" in m:
            cfg.metrics.federate_fanout = int(m["federate-fanout"])
        hs = data.get("history", {})
        if "enabled" in hs:
            cfg.history.enabled = _parse_bool(hs["enabled"])
        if "resolutions" in hs:
            parse_resolutions(hs["resolutions"])  # validate at load
            cfg.history.resolutions = str(hs["resolutions"])
        if "segment-bytes" in hs:
            cfg.history.segment_bytes = int(hs["segment-bytes"])
        if "segments" in hs:
            cfg.history.segments = int(hs["segments"])
        if "max-series" in hs:
            cfg.history.max_series = int(hs["max-series"])
        sn = data.get("sentinel", {})
        if "enabled" in sn:
            cfg.sentinel.enabled = _parse_bool(sn["enabled"])
        for key, attr in (("interval", "interval"),
                          ("window", "window"),
                          ("baseline", "baseline"),
                          ("retrip", "retrip")):
            if key in sn:
                setattr(cfg.sentinel, attr, parse_duration(sn[key]))
        if "zscore" in sn:
            cfg.sentinel.zscore = float(sn["zscore"])
        if "min-points" in sn:
            cfg.sentinel.min_points = int(sn["min-points"])
        if "min-ratio" in sn:
            cfg.sentinel.min_ratio = float(sn["min-ratio"])
        if "manifest" in sn:
            cfg.sentinel.manifest = str(sn["manifest"])
        if "manifest-tolerance" in sn:
            cfg.sentinel.manifest_tolerance = float(
                sn["manifest-tolerance"])
        t = data.get("trace", {})
        if "enabled" in t:
            cfg.trace.enabled = _parse_bool(t["enabled"])
        if "max-traces" in t:
            cfg.trace.max_traces = int(t["max-traces"])
        if "max-spans" in t:
            cfg.trace.max_spans = int(t["max-spans"])
        if "tail" in t:
            cfg.trace.tail = _parse_bool(t["tail"])
        if "head-n" in t:
            cfg.trace.head_n = int(t["head-n"])
        if "slow-floor" in t:
            cfg.trace.slow_floor = parse_duration(t["slow-floor"])
        if "disk-segment-bytes" in t:
            cfg.trace.disk_segment_bytes = int(t["disk-segment-bytes"])
        if "disk-segments" in t:
            cfg.trace.disk_segments = int(t["disk-segments"])
        bb = data.get("blackbox", {})
        if "enabled" in bb:
            cfg.blackbox.enabled = _parse_bool(bb["enabled"])
        if "interval" in bb:
            cfg.blackbox.interval = parse_duration(bb["interval"])
        if "segment-bytes" in bb:
            cfg.blackbox.segment_bytes = int(bb["segment-bytes"])
        if "segments" in bb:
            cfg.blackbox.segments = int(bb["segments"])
        if "dumps" in bb:
            cfg.blackbox.dumps = int(bb["dumps"])
        wd = data.get("watchdog", {})
        if "enabled" in wd:
            cfg.watchdog.enabled = _parse_bool(wd["enabled"])
        for key, attr in (("interval", "interval"),
                          ("wal-stall", "wal_stall"),
                          ("deadline-grace", "deadline_grace"),
                          ("gossip-silence", "gossip_silence"),
                          ("queue-stall", "queue_stall"),
                          ("resize-stall", "resize_stall"),
                          ("scrub-stall", "scrub_stall"),
                          ("tier-stall", "tier_stall"),
                          ("backup-stall", "backup_stall"),
                          ("retrip", "retrip")):
            if key in wd:
                setattr(cfg.watchdog, attr, parse_duration(wd[key]))
        sc = data.get("scrub", {})
        if "enabled" in sc:
            cfg.scrub.enabled = _parse_bool(sc["enabled"])
        if "interval" in sc:
            cfg.scrub.interval = parse_duration(sc["interval"])
        if "pace" in sc:
            cfg.scrub.pace = parse_duration(sc["pace"])
        if "repair" in sc:
            cfg.scrub.repair = _parse_bool(sc["repair"])
        if "repair-rescan" in sc:
            cfg.scrub.repair_rescan = parse_duration(sc["repair-rescan"])
        ti = data.get("tier", {})
        if "enabled" in ti:
            cfg.tier.enabled = _parse_bool(ti["enabled"])
        if "resident-budget" in ti:
            cfg.tier.resident_budget = int(ti["resident-budget"])
        if "high-watermark" in ti:
            cfg.tier.high_watermark = float(ti["high-watermark"])
        if "low-watermark" in ti:
            cfg.tier.low_watermark = float(ti["low-watermark"])
        for key, attr in (("idle", "idle"),
                          ("blob-idle", "blob_idle"),
                          ("interval", "interval"),
                          ("prefetch-interval", "prefetch_interval"),
                          ("pace", "pace")):
            if key in ti:
                setattr(cfg.tier, attr, parse_duration(ti[key]))
        if "cold-dir" in ti:
            cfg.tier.cold_dir = str(ti["cold-dir"])
        if "blob" in ti:
            cfg.tier.blob = str(ti["blob"])
        cp = data.get("capture", {})
        if "mode" in cp:
            cfg.capture.mode = str(cp["mode"])
        if "sample-n" in cp:
            cfg.capture.sample_n = int(cp["sample-n"])
        if "segment-bytes" in cp:
            cfg.capture.segment_bytes = int(cp["segment-bytes"])
        if "segments" in cp:
            cfg.capture.segments = int(cp["segments"])
        if "redact" in cp:
            cfg.capture.redact = str(cp["redact"])
        bu = data.get("backup", {})
        if "archive" in bu:
            cfg.backup.archive = str(bu["archive"])
        if "wal-interval" in bu:
            cfg.backup.wal_interval = parse_duration(bu["wal-interval"])
        if "keep-fulls" in bu:
            cfg.backup.keep_fulls = int(bu["keep-fulls"])
        p = data.get("profile", {})
        if "continuous" in p:
            cfg.profile.continuous = _parse_bool(p["continuous"])
        if "hz" in p:
            cfg.profile.hz = float(p["hz"])
        if "ring" in p:
            cfg.profile.ring = int(p["ring"])
        s = data.get("slo", {})
        if "objective" in s:
            cfg.slo.objective = parse_duration(s["objective"])
        if "target" in s:
            cfg.slo.target = float(s["target"])
        fl = data.get("fault", {})
        if "enabled" in fl:
            cfg.fault.enabled = _parse_bool(fl["enabled"])
        if "breaker-threshold" in fl:
            cfg.fault.breaker_threshold = int(fl["breaker-threshold"])
        if "breaker-backoff" in fl:
            cfg.fault.breaker_backoff = parse_duration(
                fl["breaker-backoff"])
        if "breaker-backoff-cap" in fl:
            cfg.fault.breaker_backoff_cap = parse_duration(
                fl["breaker-backoff-cap"])
        if "hedge" in fl:
            cfg.fault.hedge = parse_duration(fl["hedge"])
        if "seed" in fl:
            cfg.fault.seed = int(fl["seed"])
        for site, spec in (fl.get("failpoints") or {}).items():
            cfg.fault.failpoints[str(site)] = str(spec)
        cfg.plugins_path = data.get("plugins", {}).get(
            "path", cfg.plugins_path)
    env = os.environ if env is None else env
    if env.get("PILOSA_DATA_DIR"):
        cfg.data_dir = env["PILOSA_DATA_DIR"]
    if env.get("PILOSA_HOST"):
        cfg.host = env["PILOSA_HOST"]
    if env.get("PILOSA_CLUSTER_TYPE"):
        cfg.cluster.type = env["PILOSA_CLUSTER_TYPE"]
    if env.get("PILOSA_CLUSTER_HOSTS"):
        cfg.cluster.hosts = [h.strip() for h in
                             env["PILOSA_CLUSTER_HOSTS"].split(",")
                             if h.strip()]
    if env.get("PILOSA_CLUSTER_REPLICAS"):
        cfg.cluster.replica_n = int(env["PILOSA_CLUSTER_REPLICAS"])
    if env.get("PILOSA_CLUSTER_INTERNAL_PORT"):
        cfg.cluster.internal_port = env["PILOSA_CLUSTER_INTERNAL_PORT"]
    if env.get("PILOSA_CLUSTER_GOSSIP_SEED"):
        cfg.cluster.gossip_seed = env["PILOSA_CLUSTER_GOSSIP_SEED"]
    if env.get("PILOSA_CLUSTER_GOSSIP_SECRET"):
        cfg.cluster.gossip_secret = env["PILOSA_CLUSTER_GOSSIP_SECRET"]
    if env.get("PILOSA_CLUSTER_INTERNAL_HOSTS"):
        cfg.cluster.internal_hosts = [
            h.strip() for h in
            env["PILOSA_CLUSTER_INTERNAL_HOSTS"].split(",") if h.strip()]
    if env.get("PILOSA_CLUSTER_POLL_INTERVAL"):
        cfg.cluster.polling_interval = parse_duration(
            env["PILOSA_CLUSTER_POLL_INTERVAL"])
    if env.get("PILOSA_LOG_PATH"):
        cfg.log_path = env["PILOSA_LOG_PATH"]
    if env.get("PILOSA_ANTI_ENTROPY_INTERVAL"):
        cfg.anti_entropy_interval = parse_duration(
            env["PILOSA_ANTI_ENTROPY_INTERVAL"])
    if env.get("PILOSA_QUERY_CONCURRENCY"):
        cfg.query.concurrency = int(env["PILOSA_QUERY_CONCURRENCY"])
    if env.get("PILOSA_QUERY_QUEUE_DEPTH"):
        cfg.query.queue_depth = int(env["PILOSA_QUERY_QUEUE_DEPTH"])
    if env.get("PILOSA_QUERY_DEFAULT_TIMEOUT"):
        cfg.query.default_timeout = parse_duration(
            env["PILOSA_QUERY_DEFAULT_TIMEOUT"])
    if env.get("PILOSA_QUERY_SLOW_THRESHOLD"):
        cfg.query.slow_threshold = parse_duration(
            env["PILOSA_QUERY_SLOW_THRESHOLD"])
    if env.get("PILOSA_QUERY_RESULT_CACHE_ENTRIES"):
        cfg.query.result_cache_entries = int(
            env["PILOSA_QUERY_RESULT_CACHE_ENTRIES"])
    if env.get("PILOSA_QUERY_RESULT_CACHE_BITS"):
        cfg.query.result_cache_bits = int(
            env["PILOSA_QUERY_RESULT_CACHE_BITS"])
    if env.get("PILOSA_QUERY_CLUSTER_CACHE_ENTRIES"):
        cfg.query.cluster_cache_entries = int(
            env["PILOSA_QUERY_CLUSTER_CACHE_ENTRIES"])
    if env.get("PILOSA_TENANTS"):
        cfg.tenants.table = parse_tenants(env["PILOSA_TENANTS"])
    if env.get("PILOSA_CLUSTER_GEN_STALENESS"):
        # Bare numbers accepted too (the executor's direct env read
        # takes them; the two entry points must not diverge).
        raw = env["PILOSA_CLUSTER_GEN_STALENESS"]
        try:
            cfg.cluster.gen_staleness = float(raw)
        except ValueError:
            cfg.cluster.gen_staleness = parse_duration(raw)
    if env.get("PILOSA_CLUSTER_RESIZE_PACE"):
        cfg.cluster.resize_pace = parse_duration(
            env["PILOSA_CLUSTER_RESIZE_PACE"])
    if env.get("PILOSA_CLUSTER_RESIZE_GRACE"):
        cfg.cluster.resize_grace = parse_duration(
            env["PILOSA_CLUSTER_RESIZE_GRACE"])
    if env.get("PILOSA_METRICS_ENABLED"):
        cfg.metrics.enabled = _parse_bool(env["PILOSA_METRICS_ENABLED"])
    if env.get("PILOSA_METRICS_RUNTIME_INTERVAL"):
        cfg.metrics.runtime_interval = parse_duration(
            env["PILOSA_METRICS_RUNTIME_INTERVAL"])
    if env.get("PILOSA_METRICS_ACCOUNTING"):
        cfg.metrics.accounting = _parse_bool(
            env["PILOSA_METRICS_ACCOUNTING"])
    if env.get("PILOSA_METRICS_FEDERATE_TIMEOUT"):
        cfg.metrics.federate_timeout = parse_duration(
            env["PILOSA_METRICS_FEDERATE_TIMEOUT"])
    if env.get("PILOSA_METRICS_FEDERATE_FANOUT"):
        cfg.metrics.federate_fanout = int(
            env["PILOSA_METRICS_FEDERATE_FANOUT"])
    if env.get("PILOSA_HISTORY_ENABLED"):
        cfg.history.enabled = _parse_bool(env["PILOSA_HISTORY_ENABLED"])
    if env.get("PILOSA_HISTORY_RESOLUTIONS"):
        parse_resolutions(env["PILOSA_HISTORY_RESOLUTIONS"])
        cfg.history.resolutions = env["PILOSA_HISTORY_RESOLUTIONS"]
    if env.get("PILOSA_HISTORY_SEGMENT_BYTES"):
        cfg.history.segment_bytes = int(
            env["PILOSA_HISTORY_SEGMENT_BYTES"])
    if env.get("PILOSA_HISTORY_SEGMENTS"):
        cfg.history.segments = int(env["PILOSA_HISTORY_SEGMENTS"])
    if env.get("PILOSA_HISTORY_MAX_SERIES"):
        cfg.history.max_series = int(env["PILOSA_HISTORY_MAX_SERIES"])
    if env.get("PILOSA_SENTINEL_ENABLED"):
        cfg.sentinel.enabled = _parse_bool(
            env["PILOSA_SENTINEL_ENABLED"])
    for env_key_, attr_ in (("PILOSA_SENTINEL_INTERVAL", "interval"),
                            ("PILOSA_SENTINEL_WINDOW", "window"),
                            ("PILOSA_SENTINEL_BASELINE", "baseline"),
                            ("PILOSA_SENTINEL_RETRIP", "retrip")):
        if env.get(env_key_):
            setattr(cfg.sentinel, attr_, parse_duration(env[env_key_]))
    if env.get("PILOSA_SENTINEL_ZSCORE"):
        cfg.sentinel.zscore = float(env["PILOSA_SENTINEL_ZSCORE"])
    if env.get("PILOSA_SENTINEL_MIN_POINTS"):
        cfg.sentinel.min_points = int(env["PILOSA_SENTINEL_MIN_POINTS"])
    if env.get("PILOSA_SENTINEL_MIN_RATIO"):
        cfg.sentinel.min_ratio = float(env["PILOSA_SENTINEL_MIN_RATIO"])
    if env.get("PILOSA_SENTINEL_MANIFEST"):
        cfg.sentinel.manifest = env["PILOSA_SENTINEL_MANIFEST"]
    if env.get("PILOSA_SENTINEL_MANIFEST_TOLERANCE"):
        cfg.sentinel.manifest_tolerance = float(
            env["PILOSA_SENTINEL_MANIFEST_TOLERANCE"])
    if env.get("PILOSA_PROFILE_CONTINUOUS"):
        cfg.profile.continuous = _parse_bool(
            env["PILOSA_PROFILE_CONTINUOUS"])
    if env.get("PILOSA_PROFILE_HZ"):
        cfg.profile.hz = float(env["PILOSA_PROFILE_HZ"])
    if env.get("PILOSA_PROFILE_RING"):
        cfg.profile.ring = int(env["PILOSA_PROFILE_RING"])
    if env.get("PILOSA_SLO_OBJECTIVE"):
        cfg.slo.objective = parse_duration(env["PILOSA_SLO_OBJECTIVE"])
    if env.get("PILOSA_SLO_TARGET"):
        cfg.slo.target = float(env["PILOSA_SLO_TARGET"])
    if env.get("PILOSA_TRACE_ENABLED"):
        cfg.trace.enabled = _parse_bool(env["PILOSA_TRACE_ENABLED"])
    if env.get("PILOSA_TRACE_MAX_TRACES"):
        cfg.trace.max_traces = int(env["PILOSA_TRACE_MAX_TRACES"])
    if env.get("PILOSA_TRACE_MAX_SPANS"):
        cfg.trace.max_spans = int(env["PILOSA_TRACE_MAX_SPANS"])
    if env.get("PILOSA_TRACE_TAIL"):
        cfg.trace.tail = _parse_bool(env["PILOSA_TRACE_TAIL"])
    if env.get("PILOSA_TRACE_HEAD_N"):
        cfg.trace.head_n = int(env["PILOSA_TRACE_HEAD_N"])
    if env.get("PILOSA_TRACE_SLOW_FLOOR"):
        cfg.trace.slow_floor = parse_duration(
            env["PILOSA_TRACE_SLOW_FLOOR"])
    if env.get("PILOSA_TRACE_DISK_SEGMENT_BYTES"):
        cfg.trace.disk_segment_bytes = int(
            env["PILOSA_TRACE_DISK_SEGMENT_BYTES"])
    if env.get("PILOSA_TRACE_DISK_SEGMENTS"):
        cfg.trace.disk_segments = int(env["PILOSA_TRACE_DISK_SEGMENTS"])
    if env.get("PILOSA_BLACKBOX_ENABLED"):
        cfg.blackbox.enabled = _parse_bool(env["PILOSA_BLACKBOX_ENABLED"])
    if env.get("PILOSA_BLACKBOX_INTERVAL"):
        cfg.blackbox.interval = parse_duration(
            env["PILOSA_BLACKBOX_INTERVAL"])
    if env.get("PILOSA_BLACKBOX_SEGMENT_BYTES"):
        cfg.blackbox.segment_bytes = int(
            env["PILOSA_BLACKBOX_SEGMENT_BYTES"])
    if env.get("PILOSA_BLACKBOX_SEGMENTS"):
        cfg.blackbox.segments = int(env["PILOSA_BLACKBOX_SEGMENTS"])
    if env.get("PILOSA_BLACKBOX_DUMPS"):
        cfg.blackbox.dumps = int(env["PILOSA_BLACKBOX_DUMPS"])
    if env.get("PILOSA_WATCHDOG_ENABLED"):
        cfg.watchdog.enabled = _parse_bool(env["PILOSA_WATCHDOG_ENABLED"])
    for env_key_, attr_ in (("PILOSA_WATCHDOG_INTERVAL", "interval"),
                            ("PILOSA_WATCHDOG_WAL_STALL", "wal_stall"),
                            ("PILOSA_WATCHDOG_DEADLINE_GRACE",
                             "deadline_grace"),
                            ("PILOSA_WATCHDOG_GOSSIP_SILENCE",
                             "gossip_silence"),
                            ("PILOSA_WATCHDOG_QUEUE_STALL",
                             "queue_stall"),
                            ("PILOSA_WATCHDOG_RESIZE_STALL",
                             "resize_stall"),
                            ("PILOSA_WATCHDOG_SCRUB_STALL",
                             "scrub_stall"),
                            ("PILOSA_WATCHDOG_TIER_STALL",
                             "tier_stall"),
                            ("PILOSA_WATCHDOG_BACKUP_STALL",
                             "backup_stall"),
                            ("PILOSA_WATCHDOG_RETRIP", "retrip")):
        if env.get(env_key_):
            setattr(cfg.watchdog, attr_, parse_duration(env[env_key_]))
    if env.get("PILOSA_SCRUB_ENABLED"):
        cfg.scrub.enabled = _parse_bool(env["PILOSA_SCRUB_ENABLED"])
    if env.get("PILOSA_SCRUB_INTERVAL"):
        cfg.scrub.interval = parse_duration(env["PILOSA_SCRUB_INTERVAL"])
    if env.get("PILOSA_SCRUB_PACE"):
        cfg.scrub.pace = parse_duration(env["PILOSA_SCRUB_PACE"])
    if env.get("PILOSA_SCRUB_REPAIR"):
        cfg.scrub.repair = _parse_bool(env["PILOSA_SCRUB_REPAIR"])
    if env.get("PILOSA_SCRUB_REPAIR_RESCAN"):
        cfg.scrub.repair_rescan = parse_duration(
            env["PILOSA_SCRUB_REPAIR_RESCAN"])
    if env.get("PILOSA_TIER_ENABLED"):
        cfg.tier.enabled = _parse_bool(env["PILOSA_TIER_ENABLED"])
    if env.get("PILOSA_TIER_RESIDENT_BUDGET"):
        cfg.tier.resident_budget = int(env["PILOSA_TIER_RESIDENT_BUDGET"])
    if env.get("PILOSA_TIER_HIGH_WATERMARK"):
        cfg.tier.high_watermark = float(env["PILOSA_TIER_HIGH_WATERMARK"])
    if env.get("PILOSA_TIER_LOW_WATERMARK"):
        cfg.tier.low_watermark = float(env["PILOSA_TIER_LOW_WATERMARK"])
    for env_key_, attr_ in (("PILOSA_TIER_IDLE", "idle"),
                            ("PILOSA_TIER_BLOB_IDLE", "blob_idle"),
                            ("PILOSA_TIER_INTERVAL", "interval"),
                            ("PILOSA_TIER_PREFETCH_INTERVAL",
                             "prefetch_interval"),
                            ("PILOSA_TIER_PACE", "pace")):
        if env.get(env_key_):
            setattr(cfg.tier, attr_, parse_duration(env[env_key_]))
    if env.get("PILOSA_TIER_COLD_DIR"):
        cfg.tier.cold_dir = env["PILOSA_TIER_COLD_DIR"]
    if env.get("PILOSA_TIER_BLOB"):
        cfg.tier.blob = env["PILOSA_TIER_BLOB"]
    if env.get("PILOSA_CAPTURE_MODE"):
        cfg.capture.mode = env["PILOSA_CAPTURE_MODE"]
    if env.get("PILOSA_CAPTURE_SAMPLE_N"):
        cfg.capture.sample_n = int(env["PILOSA_CAPTURE_SAMPLE_N"])
    if env.get("PILOSA_CAPTURE_SEGMENT_BYTES"):
        cfg.capture.segment_bytes = int(
            env["PILOSA_CAPTURE_SEGMENT_BYTES"])
    if env.get("PILOSA_CAPTURE_SEGMENTS"):
        cfg.capture.segments = int(env["PILOSA_CAPTURE_SEGMENTS"])
    if env.get("PILOSA_CAPTURE_REDACT"):
        cfg.capture.redact = env["PILOSA_CAPTURE_REDACT"]
    if env.get("PILOSA_BACKUP_ARCHIVE"):
        cfg.backup.archive = env["PILOSA_BACKUP_ARCHIVE"]
    if env.get("PILOSA_BACKUP_WAL_INTERVAL"):
        cfg.backup.wal_interval = parse_duration(
            env["PILOSA_BACKUP_WAL_INTERVAL"])
    if env.get("PILOSA_BACKUP_KEEP_FULLS"):
        cfg.backup.keep_fulls = int(env["PILOSA_BACKUP_KEEP_FULLS"])
    if env.get("PILOSA_PLUGINS_PATH"):
        cfg.plugins_path = env["PILOSA_PLUGINS_PATH"]
    if env.get("PILOSA_FAULT_ENABLED"):
        cfg.fault.enabled = _parse_bool(env["PILOSA_FAULT_ENABLED"])
    if env.get("PILOSA_FAULT_BREAKER_THRESHOLD"):
        cfg.fault.breaker_threshold = int(
            env["PILOSA_FAULT_BREAKER_THRESHOLD"])
    if env.get("PILOSA_FAULT_BREAKER_BACKOFF"):
        cfg.fault.breaker_backoff = parse_duration(
            env["PILOSA_FAULT_BREAKER_BACKOFF"])
    if env.get("PILOSA_FAULT_BREAKER_BACKOFF_CAP"):
        cfg.fault.breaker_backoff_cap = parse_duration(
            env["PILOSA_FAULT_BREAKER_BACKOFF_CAP"])
    if env.get("PILOSA_FAULT_HEDGE"):
        cfg.fault.hedge = parse_duration(env["PILOSA_FAULT_HEDGE"])
    if env.get("PILOSA_FAULT_SEED"):
        cfg.fault.seed = int(env["PILOSA_FAULT_SEED"])
    # Failpoint arming: PILOSA_FAULT_<SITE> via the canonical site
    # list + env-key mapping owned by fault.failpoints, so a newly
    # added site cannot silently drift out of env arming and the
    # reserved knobs above never collide (runtime import: failpoints
    # imports parse_duration from here).
    from ..fault.failpoints import SITES as _fp_sites
    from ..fault.failpoints import env_key as _fp_env_key
    for site in _fp_sites:
        if env.get(_fp_env_key(site)):
            cfg.fault.failpoints[site] = env[_fp_env_key(site)]
    return cfg
