

def cache_dir(*parts: str) -> str:
    """The per-machine cache base (PILOSA_TPU_CACHE overrides
    ~/.cache/pilosa_tpu) joined with ``parts`` — one definition for
    the native-lib build dir, cost-model calibrations, and the XLA
    persistent compile cache."""
    import os
    base = os.environ.get("PILOSA_TPU_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "pilosa_tpu")
    return os.path.join(base, *parts)
