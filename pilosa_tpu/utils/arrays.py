"""Shared vectorized array idioms used across the import/storage paths."""

from __future__ import annotations

import numpy as np


def sort_dedupe(values: np.ndarray) -> np.ndarray:
    """Sorted-unique form of ``values``: skips the O(n log n) sort when
    the input is already ordered (bulk lanes feed pre-sorted vectors)
    and dedupes with one linear mask pass — the shared idiom of the
    import/batch-write hot paths."""
    if len(values) > 1 and not bool(np.all(values[:-1] <= values[1:])):
        values = np.sort(values)
    if len(values) > 1:
        keep = np.empty(len(values), dtype=bool)
        keep[0] = True
        np.not_equal(values[1:], values[:-1], out=keep[1:])
        if not keep.all():
            values = values[keep]
    return values


def searchsorted_membership(haystack: np.ndarray,
                            needles: np.ndarray):
    """``(mask, idx)``: which ``needles`` occur in the SORTED
    ``haystack``, plus their searchsorted insertion points. The
    out-of-bounds guard runs before the equality fixup — the subtle
    part of the idiom, kept in one place (it was hand-rolled at three
    bulk-lane call sites)."""
    idx = np.searchsorted(haystack, needles)
    mask = idx < len(haystack)
    if mask.any():
        h = np.flatnonzero(mask)
        mask[h] = haystack[idx[h]] == needles[h]
    return mask, idx


def group_by_key(keys: np.ndarray, *arrays: np.ndarray):
    """Yield ``(key, sub_array, ...)`` groups of ``arrays`` split by
    equal values of ``keys``, via one stable argsort — the vector form
    of a dict-of-lists group-by. Groups come out in ascending key
    order; within a group, elements keep their input order.
    """
    if not len(keys):
        return
    order = np.argsort(keys, kind="stable")
    ks = keys[order]
    arrs = [a[order] for a in arrays]
    bounds = np.flatnonzero(ks[1:] != ks[:-1]) + 1
    for s, e in zip(np.concatenate(([0], bounds)),
                    np.concatenate((bounds, [len(ks)]))):
        yield (int(ks[s]), *(a[s:e] for a in arrs))
