"""Shared vectorized array idioms used across the import/storage paths."""

from __future__ import annotations

import numpy as np


def group_by_key(keys: np.ndarray, *arrays: np.ndarray):
    """Yield ``(key, sub_array, ...)`` groups of ``arrays`` split by
    equal values of ``keys``, via one stable argsort — the vector form
    of a dict-of-lists group-by. Groups come out in ascending key
    order; within a group, elements keep their input order.
    """
    if not len(keys):
        return
    order = np.argsort(keys, kind="stable")
    ks = keys[order]
    arrs = [a[order] for a in arrays]
    bounds = np.flatnonzero(ks[1:] != ks[:-1]) + 1
    for s, e in zip(np.concatenate(([0], bounds)),
                    np.concatenate((bounds, [len(ks)]))):
        yield (int(ks[s]), *(a[s:e] for a in arrs))
