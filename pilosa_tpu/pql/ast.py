"""PQL AST: Query = list of Calls; Call = name + args + children.

Reference: pql/ast.go. ``Call.__str__`` produces the canonical
re-serialization (sorted arg keys, Go-style literal formatting) that is the
wire form used to forward queries to peer nodes (executor.go:1004), so its
output must round-trip through the parser.
"""

from __future__ import annotations

import datetime as dt
from typing import Any, Optional

from ..errors import TIME_FORMAT

# Comparison operators accepted in a BSI field condition, e.g.
# ``Range(frame=f, age >= 20)`` (pql/token.go ASSIGN..BETWEEN set).
CONDITION_OPS = ("==", "!=", "<", "<=", ">", ">=", "><")


class Condition:
    """A ``field OP value`` argument (pilosa 1.0's range syntax): the
    parser stores it under the field name in ``Call.args``, so a call
    carries at most one condition per field. ``op`` is one of
    CONDITION_OPS; ``value`` is an int, except ``><`` (between), whose
    value is a two-int [low, high] list."""

    __slots__ = ("op", "value")

    def __init__(self, op: str, value: Any):
        if op not in CONDITION_OPS:
            raise ValueError(f"invalid condition op: {op!r}")
        self.op = op
        self.value = value

    def __repr__(self):
        return f"Condition({self.op} {self.value!r})"

    def __eq__(self, other):
        return (isinstance(other, Condition) and self.op == other.op
                and self.value == other.value)

    def __hash__(self):
        v = tuple(self.value) if isinstance(self.value, list) else \
            self.value
        return hash((self.op, v))


def _fmt_value(v: Any) -> str:
    if isinstance(v, str):
        return _quote(v)
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, dt.datetime):
        return f'"{v.strftime(TIME_FORMAT)}"'
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_fmt_value(x) for x in v) + "]"
    return str(v)


def _quote(s: str) -> str:
    out = s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    return f'"{out}"'


class Call:
    def __init__(self, name: str = "",
                 args: Optional[dict[str, Any]] = None,
                 children: Optional[list["Call"]] = None):
        self.name = name
        self.args: dict[str, Any] = args or {}
        self.children: list[Call] = children or []

    # -- arg helpers (ast.go:52-89)

    def uint_arg(self, key: str) -> tuple[int, bool]:
        """(value, found); raises on a non-integer value."""
        if key not in self.args:
            return 0, False
        v = self.args[key]
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(
                f"could not convert {v!r} to uint in Call.uint_arg")
        return v & 0xFFFFFFFFFFFFFFFF, True

    def uint_slice_arg(self, key: str) -> tuple[list[int], bool]:
        if key not in self.args:
            return [], False
        v = self.args[key]
        if not isinstance(v, (list, tuple)) or not all(
                isinstance(x, int) and not isinstance(x, bool) for x in v):
            raise ValueError(
                f"unexpected type in Call.uint_slice_arg: {v!r}")
        return [x & 0xFFFFFFFFFFFFFFFF for x in v], True

    def keys(self) -> list[str]:
        return sorted(self.args)

    def clone(self) -> "Call":
        return Call(self.name, dict(self.args),
                    [c.clone() for c in self.children])

    # -- inverse detection (ast.go:174-195)

    def supports_inverse(self) -> bool:
        return self.name == "Bitmap"

    def is_inverse(self, row_label: str, column_label: str) -> bool:
        if not self.supports_inverse():
            return False
        try:
            _, row_ok = self.uint_arg(row_label)
            _, col_ok = self.uint_arg(column_label)
        except ValueError:
            return False
        return not row_ok and col_ok

    # -- canonical serialization (ast.go:121-171)

    def condition_arg(self) -> Optional[tuple[str, "Condition"]]:
        """The (field_name, condition) pair of a BSI range call, or
        None. At most one condition per call is meaningful — the
        first in key order wins (parse keeps keys unique)."""
        for k in self.keys():
            v = self.args[k]
            if isinstance(v, Condition):
                return k, v
        return None

    def __str__(self) -> str:
        parts = [c.__str__() for c in self.children]
        for k in self.keys():
            v = self.args[k]
            if isinstance(v, Condition):
                # Wire form must re-parse on peer nodes (executor.go
                # forwards the canonical serialization).
                parts.append(f"{k} {v.op} {_fmt_value(v.value)}")
            else:
                parts.append(f"{k}={_fmt_value(v)}")
        return f"{self.name or '!UNNAMED'}({', '.join(parts)})"

    def __repr__(self):
        return f"Call({self.__str__()})"

    def __eq__(self, other):
        return (isinstance(other, Call) and self.name == other.name
                and self.args == other.args
                and self.children == other.children)


class Query:
    def __init__(self, calls: Optional[list[Call]] = None):
        self.calls: list[Call] = calls or []

    def write_calls(self) -> list[Call]:
        """Calls that mutate state (ast.go WriteCalls)."""
        return [c for c in self.calls
                if c.name in ("SetBit", "ClearBit", "SetFieldValue",
                              "SetRowAttrs", "SetColumnAttrs")]

    def __str__(self) -> str:
        return "\n".join(str(c) for c in self.calls)

    def __eq__(self, other):
        return isinstance(other, Query) and self.calls == other.calls
