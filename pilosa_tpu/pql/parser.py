"""PQL lexer + recursive-descent parser.

Reference: pql/scanner.go (token rules) and pql/parser.go (grammar):

    query    := call*
    call     := IDENT '(' children? args? ')'
    children := call (',' call)*         # children come before args
    args     := key '=' value (',' ...)  # keys unique
    value    := IDENT(true|false|null|other) | STRING | INTEGER | FLOAT | list
    list     := '[' value (',' value)* ']'

Token rules match the reference scanner exactly: idents start with a letter
and continue with [A-Za-z0-9_\\-.]; numbers allow one leading '-' and one
'.'; strings are single- or double-quoted with \\n, \\\\, \\", \\' escapes.
"""

from __future__ import annotations

import re

from ..errors import PilosaError
from .ast import Call, Condition, Query

EOF = "EOF"
WS = "WS"
IDENT = "IDENT"
STRING = "STRING"
BADSTRING = "BADSTRING"
INTEGER = "INTEGER"
FLOAT = "FLOAT"
EQ = "EQ"
COND = "COND"  # comparison operator of a BSI field condition
COMMA = "COMMA"
LPAREN = "LPAREN"
RPAREN = "RPAREN"
LBRACK = "LBRACK"
RBRACK = "RBRACK"
ILLEGAL = "ILLEGAL"


class ParseError(PilosaError):
    def __init__(self, pos, message):
        self.pos = pos
        super().__init__(f"{message} occurred at line {pos[0]}, char {pos[1]}")


# Token regexes (compiled once; the scanner was the query hot path's
# biggest cost as a char-at-a-time loop — PQL parse was ~55% of SetBit
# service time). Each preserves the reference scanner's rules exactly:
# idents start with a letter and continue [A-Za-z0-9_\-.]; numbers take
# an optional leading '-' and at most one '.'; strings are single- or
# double-quoted with \n \\ \" \' escapes and may not span lines.
_IDENT_RE = re.compile(r"[A-Za-z][A-Za-z0-9_\-.]*")
# [0-9] not \d: the reference's isDigit is ASCII-only, and \d would
# admit Unicode digits that int() then silently converts.
_NUMBER_RE = re.compile(
    r"-(?:[0-9]+(?:\.[0-9]*)?|\.[0-9]*)?|[0-9]+(?:\.[0-9]*)?")
_STRING_RE = re.compile(r"(['\"])((?:\\[n\\\"']|[^\\\n])*?)\1")
_ESCAPE_RE = re.compile(r"\\(.)")
_ESCAPES = {"n": "\n", "\\": "\\", '"': '"', "'": "'"}
_SIMPLE_TOKENS = {"=": EQ, ",": COMMA, "(": LPAREN, ")": RPAREN,
                  "[": LBRACK, "]": RBRACK}


class Scanner:
    def __init__(self, text: str):
        self._s = text
        self._i = 0
        self._line = 0
        self._char = 0

    def _advance(self, j: int) -> None:
        """Consume self._s[self._i:j], updating (line, char)."""
        s, i = self._s, self._i
        nl = s.count("\n", i, j)
        if nl:
            self._line += nl
            self._char = j - (s.rindex("\n", i, j) + 1)
        else:
            self._char += j - i
        self._i = j

    def scan(self):
        s, i = self._s, self._i
        pos = (self._line, self._char)
        if i >= len(s):
            self._i += 1
            return EOF, pos, ""
        ch = s[i]
        if ch.isspace():
            j, n = i + 1, len(s)
            while j < n and s[j].isspace():
                j += 1
            lit = s[i:j]
            self._advance(j)
            # WS positions here are exact even across newlines (the
            # reference's unread() lost the column there); harmless
            # divergence — WS is dropped before parsing.
            return WS, pos, lit
        if "a" <= ch <= "z" or "A" <= ch <= "Z":
            m = _IDENT_RE.match(s, i)
            self._advance(m.end())
            return IDENT, pos, m.group()
        if "0" <= ch <= "9" or ch == "-":
            m = _NUMBER_RE.match(s, i)
            lit = m.group()
            self._advance(m.end())
            return (FLOAT if "." in lit else INTEGER), pos, lit
        if ch == '"' or ch == "'":
            m = _STRING_RE.match(s, i)
            if m is None:  # unterminated / newline / bad escape
                return self._scan_badstring(pos)
            body = m.group(2)
            self._advance(m.end())
            if "\\" in body:
                body = _ESCAPE_RE.sub(
                    lambda mm: _ESCAPES[mm.group(1)], body)
            return STRING, pos, body
        if ch in "<>!=":
            # Comparison operators of the BSI condition syntax
            # (``age >= 20``): two-char forms first, then the single-
            # char ones; '=' alone stays the assignment token.
            two = s[i:i + 2]
            if two in ("==", "!=", "<=", ">=", "><"):
                self._advance(i + 2)
                return COND, pos, two
            if ch in "<>":
                self._advance(i + 1)
                return COND, pos, ch
        self._advance(i + 1)
        return _SIMPLE_TOKENS.get(ch, ILLEGAL), pos, ch

    def _scan_badstring(self, pos):
        """Failure path of the string rule: unterminated input, embedded
        newline, or invalid escape ⇒ BADSTRING with the partial body
        (same consumption as the reference's char loop)."""
        s, n = self._s, len(self._s)
        ending = s[self._i]
        j = self._i + 1
        buf = []
        while True:
            if j >= n:
                self._advance(n)
                self._i = n + 1  # past-EOF bump, as a char read would
                return BADSTRING, pos, "".join(buf)
            ch = s[j]
            if ch == ending:
                # The char loop accepts exactly what _STRING_RE does, so
                # a terminated string can't reach this fallback; if the
                # regex and loop ever diverge, fail loudly.
                raise AssertionError(
                    "string regex / badstring loop divergence")
            if ch == "\n":
                self._advance(j + 1)
                return BADSTRING, pos, "".join(buf)
            if ch == "\\":
                if j + 1 >= n:
                    self._advance(n)
                    self._i = n + 1
                    return BADSTRING, pos, "".join(buf)
                nxt = s[j + 1]
                if nxt in _ESCAPES:
                    buf.append(_ESCAPES[nxt])
                    j += 2
                    continue
                self._advance(j + 2)
                return BADSTRING, pos, "".join(buf)
            buf.append(ch)
            j += 1


class Parser:
    """Recursive-descent parser over a pre-tokenized stream.

    The reference scans lazily with an 8-token unread ring
    (scanner.go:216-263); tokenizing the whole query up front with WS
    dropped gives the same stream semantics while unread becomes an
    index decrement — the token plumbing was the parse hot path's
    remaining cost once the scanner went regex."""

    def __init__(self, text: str):
        sc = Scanner(text)
        toks: list[tuple] = []
        while True:
            item = sc.scan()
            if item[0] == WS:
                continue
            toks.append(item)
            if item[0] == EOF:
                break
        self._toks = toks
        self._pos = 0

    # -- token stream helpers

    def _scan(self):
        p = self._pos
        self._pos = p + 1
        toks = self._toks
        return toks[p] if p < len(toks) else toks[-1]  # EOF repeats

    def _unscan(self, n: int = 1):
        self._pos -= n

    # WS never enters the stream, so the skip forms are the plain ones.
    _scan_skip_ws = _scan
    _unscan_skip_ws = _unscan

    # -- grammar

    def parse(self) -> Query:
        query = Query()
        while True:
            tok, pos, lit = self._scan_skip_ws()
            if tok == EOF:
                return query
            if tok != IDENT:
                raise ParseError(pos, f"expected identifier, found {lit!r}")
            self._unscan()
            query.calls.append(self._parse_call())

    def _parse_call(self) -> Call:
        call = Call()
        tok, pos, lit = self._scan_skip_ws()
        if tok != IDENT:
            raise ParseError(pos, f"expected identifier, found {lit!r}")
        call.name = lit
        tok, pos, lit = self._scan_skip_ws()
        if tok != LPAREN:
            raise ParseError(pos, f"expected left paren, found {lit!r}")
        call.children = self._parse_children()
        call.args = self._parse_args()
        tok, pos, lit = self._scan_skip_ws()
        if tok != RPAREN:
            raise ParseError(pos, f"expected right paren, found {lit!r}")
        return call

    def _parse_children(self) -> list[Call]:
        children = []
        while True:
            tok, pos, lit = self._scan_skip_ws()
            if tok != IDENT:
                self._unscan_skip_ws(1)
                return children
            tok2, pos2, _ = self._scan()
            # A child call needs LPAREN ADJACENT to the ident — the
            # reference checks it with a raw (non-WS-skipping) scan
            # (parser.go:119-126), so "Bitmap (" falls through to args.
            # The WS-free stream keeps that rule via token positions.
            if tok2 != LPAREN or pos2 != (pos[0], pos[1] + len(lit)):
                self._unscan()            # the non-LPAREN token
                self._unscan_skip_ws(1)   # the IDENT
                return children
            self._unscan(2)
            children.append(self._parse_call())
            tok, pos, lit = self._scan_skip_ws()
            if tok == RPAREN:
                self._unscan()
                return children
            if tok != COMMA:
                raise ParseError(
                    pos, f"expected comma or right paren, found {lit!r}")

    def _parse_args(self) -> dict:
        args: dict = {}
        while True:
            tok, pos, lit = self._scan_skip_ws()
            if tok == RPAREN:
                self._unscan()
                return args
            if tok != IDENT:
                raise ParseError(pos, f"expected argument key, found {lit!r}")
            key = lit
            tok, pos, lit = self._scan_skip_ws()
            if tok == COND:
                value = self._parse_condition(lit, pos)
            elif tok == EQ:
                value = self._parse_value()
            else:
                raise ParseError(pos, f"expected equals sign, found {lit!r}")
            if key in args:
                raise ParseError(pos, f"argument key already used: {key}")
            args[key] = value
            tok, pos, lit = self._scan_skip_ws()
            if tok == RPAREN:
                self._unscan()
                return args
            if tok != COMMA:
                raise ParseError(
                    pos, f"expected comma or right paren, found {lit!r}")

    def _parse_condition(self, op: str, pos) -> Condition:
        """``field OP value``: the value must be an integer, except
        ``><`` (between), which takes a two-int [low, high] list."""
        value = self._parse_value()
        if op == "><":
            if (not isinstance(value, list) or len(value) != 2
                    or not all(isinstance(v, int)
                               and not isinstance(v, bool)
                               for v in value)):
                raise ParseError(
                    pos, "between requires a two-integer list")
        elif isinstance(value, bool) or not isinstance(value, int):
            raise ParseError(
                pos, f"condition value must be an integer: {value!r}")
        return Condition(op, value)

    def _parse_value(self, in_list: bool = False):
        tok, pos, lit = self._scan_skip_ws()
        if tok == IDENT:
            if lit == "true":
                return True
            if lit == "false":
                return False
            if lit == "null" and not in_list:
                return None
            return lit
        if tok == STRING:
            return lit
        if tok == INTEGER:
            try:
                v = int(lit)
            except ValueError:
                raise ParseError(pos, f"invalid integer: {lit!r}")
            # int64 bounds, like the reference's strconv.ParseInt(lit,
            # 10, 64) (parser.go:186,243) — larger ids are unparseable
            # there, and letting them through would let one stray
            # SetBit push max_slice past 2^43 and explode every later
            # query's slice enumeration.
            if not -(1 << 63) <= v < 1 << 63:
                raise ParseError(pos, f"invalid integer: {lit!r}")
            return v
        if tok == FLOAT and not in_list:
            try:
                return float(lit)
            except ValueError:
                raise ParseError(pos, f"invalid float: {lit!r}")
        if tok == LBRACK and not in_list:
            return self._parse_list()
        kind = "list" if in_list else "argument"
        raise ParseError(pos, f"invalid {kind} value: {lit!r}")

    def _parse_list(self) -> list:
        values = []
        while True:
            values.append(self._parse_value(in_list=True))
            tok, pos, lit = self._scan_skip_ws()
            if tok == RBRACK:
                return values
            if tok != COMMA:
                raise ParseError(pos, f"expected comma, found {lit!r}")


# Fast path for flat call lists — the serving hot shapes
# (SetBit/ClearBit/Bitmap/TopN streams of key=value args, no children,
# no escapes): one anchored regex per call instead of ~17 scanner
# tokens. Strings are restricted to charset-safe bodies (no quotes,
# escapes, or separators) so the arg split is unambiguous; ANY mismatch
# falls back to the full parser, which keeps exact reference error
# semantics (pql/parser.go:66-260).
_FAST_ARG = (r"[A-Za-z][A-Za-z0-9_\-.]*\s*=\s*"
             r"(?:-?[0-9]+(?![0-9.])|\"[A-Za-z0-9 _\-.:]*\""
             r"|'[A-Za-z0-9 _\-.:]*'"
             r"|\[\s*-?[0-9]+\s*(?:,\s*-?[0-9]+\s*)*\])")
_FAST_CALL_RE = re.compile(
    r"\s*([A-Za-z][A-Za-z0-9_\-.]*)\(\s*(?:(" + _FAST_ARG
    + r"(?:\s*,\s*" + _FAST_ARG + r")*))?\s*\)\s*")
_FAST_ARG_RE = re.compile(
    r"([A-Za-z][A-Za-z0-9_\-.]*)\s*=\s*"
    r"(?:(-?[0-9]+)(?![0-9.])|\"([A-Za-z0-9 _\-.:]*)\""
    r"|'([A-Za-z0-9 _\-.:]*)'"
    r"|\[\s*(-?[0-9]+\s*(?:,\s*-?[0-9]+\s*)*)\])")


# The single point-mutation wire shape — `SetBit(frame="x", rowID=N,
# columnID=M)` with the default labels in canonical order — gets one
# anchored regex and a direct Call build: at production per-op write
# rates the generic fast path's finditer + groups split was a measured
# slice of per-op latency (ISSUE 8). Digit counts bounded so int() is
# always < 2^63; any other shape (custom labels, timestamp, view,
# reordered args) falls through unchanged.
_POINT_MUTATE_RE = re.compile(
    r'\s*(SetBit|ClearBit)\(\s*frame\s*=\s*"([A-Za-z0-9 _\-.:]*)"\s*,'
    r'\s*rowID\s*=\s*([0-9]{1,18})\s*,'
    r'\s*columnID\s*=\s*([0-9]{1,18})\s*\)\s*$')


def _parse_fast(text: str):
    """Query for a flat call list, or None when any call needs the full
    grammar (children, non-integer lists, floats, escapes, bool/null
    idents). Integer lists — the TopN exact-phase forwarding shape —
    stay on the fast path."""
    m = _POINT_MUTATE_RE.match(text)
    if m is not None:
        call = Call(m.group(1), {"frame": m.group(2),
                                 "rowID": int(m.group(3)),
                                 "columnID": int(m.group(4))})
        q = Query()
        q.calls.append(call)
        return q
    query = Query()
    i = 0
    n = len(text)
    while i < n:
        m = _FAST_CALL_RE.match(text, i)
        if m is None:
            return None if text[i:].strip() else query
        call = Call()
        call.name = m.group(1)
        body = m.group(2)
        if body:
            args = call.args
            count = 0
            for am in _FAST_ARG_RE.finditer(body):
                key, intv, dq, sq, lst = am.groups()
                if intv is not None:
                    v = int(intv)
                    if not -(1 << 63) <= v < 1 << 63:
                        return None  # full parser raises the bound error
                    args[key] = v
                elif lst is not None:
                    # Empty lists are a grammar error (the full parser
                    # requires >=1 value), so the regex requires one.
                    vals = [int(x) for x in lst.split(",")]
                    if any(not -(1 << 63) <= v < 1 << 63
                           for v in vals):
                        return None
                    args[key] = vals
                else:
                    args[key] = dq if dq is not None else sq
                count += 1
            if len(args) != count:
                return None  # duplicate key: full parser raises
        query.calls.append(call)
        i = m.end()
    return query


def parse(text: str) -> Query:
    fast = _parse_fast(text)
    if fast is not None:
        return fast
    return Parser(text).parse()
