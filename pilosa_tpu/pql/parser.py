"""PQL lexer + recursive-descent parser.

Reference: pql/scanner.go (token rules) and pql/parser.go (grammar):

    query    := call*
    call     := IDENT '(' children? args? ')'
    children := call (',' call)*         # children come before args
    args     := key '=' value (',' ...)  # keys unique
    value    := IDENT(true|false|null|other) | STRING | INTEGER | FLOAT | list
    list     := '[' value (',' value)* ']'

Token rules match the reference scanner exactly: idents start with a letter
and continue with [A-Za-z0-9_\\-.]; numbers allow one leading '-' and one
'.'; strings are single- or double-quoted with \\n, \\\\, \\", \\' escapes.
"""

from __future__ import annotations

from ..errors import PilosaError
from .ast import Call, Query

EOF = "EOF"
WS = "WS"
IDENT = "IDENT"
STRING = "STRING"
BADSTRING = "BADSTRING"
INTEGER = "INTEGER"
FLOAT = "FLOAT"
EQ = "EQ"
COMMA = "COMMA"
LPAREN = "LPAREN"
RPAREN = "RPAREN"
LBRACK = "LBRACK"
RBRACK = "RBRACK"
ILLEGAL = "ILLEGAL"


class ParseError(PilosaError):
    def __init__(self, pos, message):
        self.pos = pos
        super().__init__(f"{message} occurred at line {pos[0]}, char {pos[1]}")


def _is_letter(ch):
    return "a" <= ch <= "z" or "A" <= ch <= "Z"


def _is_digit(ch):
    return "0" <= ch <= "9"


def _is_ident_char(ch):
    return _is_letter(ch) or _is_digit(ch) or ch in "_-."


class Scanner:
    def __init__(self, text: str):
        self._s = text
        self._i = 0
        self._line = 0
        self._char = 0

    def _read(self) -> str:
        if self._i >= len(self._s):
            self._i += 1
            return ""
        ch = self._s[self._i]
        self._i += 1
        if ch == "\n":
            self._line += 1
            self._char = 0
        else:
            self._char += 1
        return ch

    def _unread(self):
        self._i -= 1
        if 0 <= self._i < len(self._s) and self._s[self._i] == "\n":
            self._line -= 1
        else:
            self._char -= 1

    def scan(self):
        pos = (self._line, self._char)
        ch = self._read()
        if ch == "":
            return EOF, pos, ""
        if ch.isspace():
            self._unread()
            return self._scan_whitespace()
        if _is_letter(ch):
            self._unread()
            return self._scan_ident()
        if _is_digit(ch) or ch == "-":
            self._unread()
            return self._scan_number()
        if ch in "\"'":
            self._unread()
            return self._scan_string()
        simple = {"=": EQ, ",": COMMA, "(": LPAREN, ")": RPAREN,
                  "[": LBRACK, "]": RBRACK}
        return simple.get(ch, ILLEGAL), pos, ch

    def _scan_whitespace(self):
        pos = (self._line, self._char)
        buf = []
        while True:
            ch = self._read()
            if ch == "" or not ch.isspace():
                if ch != "":
                    self._unread()
                break
            buf.append(ch)
        return WS, pos, "".join(buf)

    def _scan_ident(self):
        pos = (self._line, self._char)
        buf = []
        while True:
            ch = self._read()
            if ch == "" or not _is_ident_char(ch):
                if ch != "":
                    self._unread()
                break
            buf.append(ch)
        return IDENT, pos, "".join(buf)

    def _scan_number(self):
        pos = (self._line, self._char)
        tok = INTEGER
        buf = []
        first = True
        seen_dot = False
        while True:
            ch = self._read()
            if not (_is_digit(ch) or (first and ch == "-")
                    or (not seen_dot and ch == ".")):
                if ch != "":
                    self._unread()
                break
            if ch == ".":
                seen_dot = True
                tok = FLOAT
            buf.append(ch)
            first = False
        return tok, pos, "".join(buf)

    def _scan_string(self):
        pos = (self._line, self._char)
        ending = self._read()
        buf = []
        while True:
            ch = self._read()
            if ch == ending:
                break
            if ch in ("\n", ""):
                return BADSTRING, pos, "".join(buf)
            if ch == "\\":
                nxt = self._read()
                if nxt == "n":
                    buf.append("\n")
                elif nxt in ("\\", '"', "'"):
                    buf.append(nxt)
                else:
                    return BADSTRING, pos, "".join(buf)
            else:
                buf.append(ch)
        return STRING, pos, "".join(buf)


class Parser:
    """Recursive-descent parser with an unread token buffer
    (reference scanner.go:216-263 uses an 8-token ring; a list works)."""

    def __init__(self, text: str):
        self._scanner = Scanner(text)
        self._buf: list[tuple] = []   # pushback stack of (tok, pos, lit)
        self._history: list[tuple] = []

    # -- token stream helpers

    def _scan(self):
        if self._buf:
            item = self._buf.pop()
        else:
            item = self._scanner.scan()
        self._history.append(item)
        return item

    def _unscan(self, n: int = 1):
        for _ in range(n):
            self._buf.append(self._history.pop())

    def _scan_skip_ws(self):
        while True:
            item = self._scan()
            if item[0] != WS:
                return item

    def _unscan_skip_ws(self, n: int = 1):
        """Unscan n non-WS tokens (plus any WS between them)."""
        count = 0
        while count < n:
            if not self._history:
                return
            tok = self._history[-1][0]
            self._unscan()
            if tok != WS:
                count += 1

    # -- grammar

    def parse(self) -> Query:
        query = Query()
        while True:
            tok, pos, lit = self._scan_skip_ws()
            if tok == EOF:
                return query
            if tok != IDENT:
                raise ParseError(pos, f"expected identifier, found {lit!r}")
            self._unscan()
            query.calls.append(self._parse_call())

    def _parse_call(self) -> Call:
        call = Call()
        tok, pos, lit = self._scan_skip_ws()
        if tok != IDENT:
            raise ParseError(pos, f"expected identifier, found {lit!r}")
        call.name = lit
        tok, pos, lit = self._scan_skip_ws()
        if tok != LPAREN:
            raise ParseError(pos, f"expected left paren, found {lit!r}")
        call.children = self._parse_children()
        call.args = self._parse_args()
        tok, pos, lit = self._scan_skip_ws()
        if tok != RPAREN:
            raise ParseError(pos, f"expected right paren, found {lit!r}")
        return call

    def _parse_children(self) -> list[Call]:
        children = []
        while True:
            tok, _, _ = self._scan_skip_ws()
            if tok != IDENT:
                self._unscan_skip_ws(1)
                return children
            tok2, _, _ = self._scan()
            if tok2 != LPAREN:
                self._unscan()            # the non-LPAREN token
                self._unscan_skip_ws(1)   # the IDENT
                return children
            self._unscan(2)
            children.append(self._parse_call())
            tok, pos, lit = self._scan_skip_ws()
            if tok == RPAREN:
                self._unscan()
                return children
            if tok != COMMA:
                raise ParseError(
                    pos, f"expected comma or right paren, found {lit!r}")

    def _parse_args(self) -> dict:
        args: dict = {}
        while True:
            tok, pos, lit = self._scan_skip_ws()
            if tok == RPAREN:
                self._unscan()
                return args
            if tok != IDENT:
                raise ParseError(pos, f"expected argument key, found {lit!r}")
            key = lit
            tok, pos, lit = self._scan_skip_ws()
            if tok != EQ:
                raise ParseError(pos, f"expected equals sign, found {lit!r}")
            value = self._parse_value()
            if key in args:
                raise ParseError(pos, f"argument key already used: {key}")
            args[key] = value
            tok, pos, lit = self._scan_skip_ws()
            if tok == RPAREN:
                self._unscan()
                return args
            if tok != COMMA:
                raise ParseError(
                    pos, f"expected comma or right paren, found {lit!r}")

    def _parse_value(self, in_list: bool = False):
        tok, pos, lit = self._scan_skip_ws()
        if tok == IDENT:
            if lit == "true":
                return True
            if lit == "false":
                return False
            if lit == "null" and not in_list:
                return None
            return lit
        if tok == STRING:
            return lit
        if tok == INTEGER:
            try:
                return int(lit)
            except ValueError:
                raise ParseError(pos, f"invalid integer: {lit!r}")
        if tok == FLOAT and not in_list:
            try:
                return float(lit)
            except ValueError:
                raise ParseError(pos, f"invalid float: {lit!r}")
        if tok == LBRACK and not in_list:
            return self._parse_list()
        kind = "list" if in_list else "argument"
        raise ParseError(pos, f"invalid {kind} value: {lit!r}")

    def _parse_list(self) -> list:
        values = []
        while True:
            values.append(self._parse_value(in_list=True))
            tok, pos, lit = self._scan_skip_ws()
            if tok == RBRACK:
                return values
            if tok != COMMA:
                raise ParseError(pos, f"expected comma, found {lit!r}")


def parse(text: str) -> Query:
    return Parser(text).parse()
