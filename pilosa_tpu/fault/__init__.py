"""Fault-tolerance layer: peer health, circuit breakers, failpoints.

The executor has always re-mapped a failed node's slices onto surviving
replicas (executor._map_reduce), and the sched subsystem made dead
peers fail *within budget* — but nothing REMEMBERED a failure between
queries, so every query re-paid the dead peer's RPC timeout before
re-mapping. This package is the memory:

- ``fault.health``   — per-peer EWMA of RPC outcomes + latency, fed by
  every cluster/client call and by gossip liveness transitions.
- ``fault.breaker``  — closed/open/half-open circuit breakers per peer
  with exponential backoff + full jitter on half-open probes.
- ``fault.failpoints`` — named deterministic fault-injection sites
  (rpc.send, rpc.recv, wal.append, snapshot.write, gossip.deliver,
  mesh.dispatch) driving the chaos tests; zero-cost when disarmed.

``FaultManager`` is the per-server composition the executor, client,
syncer, handler, and gossip callback all share. State is PER NODE (two
in-process servers each keep their own view of a peer), while
failpoints are process-global by design — the injection sites live in
module code (roaring, gossip, mesh) with no server handle.
"""

from __future__ import annotations

import threading
from typing import Optional

from .breaker import (STATE_CLOSED, STATE_HALF_OPEN,  # noqa: F401
                      STATE_OPEN, BreakerBoard)
from .health import PeerHealth


class FaultManager:
    """One node's fault-tolerance state: health scores + breakers.

    ``record_rpc`` is the single feed for RPC outcomes (called by
    cluster.client._do for every attempt); ``note_gossip`` folds the
    membership layer's liveness transitions in, so a gossip-declared
    death opens the breaker *before* any query pays a timeout at all.
    """

    def __init__(self, breaker_threshold: int = 3,
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 30.0,
                 hedge_s: float = 0.0,
                 node: str = "", rng=None):
        self.node = node
        self.health = PeerHealth(node=node)
        self.breakers = BreakerBoard(threshold=breaker_threshold,
                                     backoff_base_s=backoff_base_s,
                                     backoff_cap_s=backoff_cap_s,
                                     node=node, rng=rng)
        # Hedged-read floor (seconds); 0 disables hedging. The actual
        # per-peer trigger is max(floor, the peer's p95-ish latency
        # estimate), so a configured 30 ms floor hedges a peer whose
        # EWMA tail says 200 ms at 200 ms, not 30.
        self.hedge_s = hedge_s
        self._mu = threading.Lock()

    # -- feeds ---------------------------------------------------------------

    def record_rpc(self, host: str, ok: bool,
                   latency_s: Optional[float] = None) -> None:
        if not host or host == self.node:
            return
        self.health.record(host, ok, latency_s)
        if ok:
            self.breakers.record_success(host)
        else:
            self.breakers.record_failure(host)

    def note_gossip(self, host: str, state: str) -> None:
        """Fold a membership transition in: ``dead`` opens the breaker
        immediately (no query ever pays the first timeout when gossip
        already knows), ``alive`` re-arms an immediate half-open probe
        so recovery isn't held hostage to the backoff schedule."""
        if not host or host == self.node:
            return
        self.health.note_gossip(host, state)
        if state == "dead":
            self.breakers.force_open(host, reason="gossip dead")
        elif state == "alive":
            self.breakers.note_probe_ready(host)

    # -- consults ------------------------------------------------------------

    def allow(self, host: str) -> bool:
        """May a request go to ``host`` right now? (Closed breaker, or
        a granted half-open probe.) The local node is always allowed.
        SIDE-EFFECTFUL: a lapsed open window transitions to half-open
        and this caller takes the single probe slot — only the layer
        that actually SENDS (cluster.client._do) may call this; pure
        filters must use would_allow()."""
        if not host or host == self.node:
            return True
        return self.breakers.allow(host)

    def would_allow(self, host: str) -> bool:
        """allow() without side effects — for peer filters (the
        anti-entropy syncer) whose own client will gate again when it
        actually sends."""
        if not host or host == self.node:
            return True
        return self.breakers.would_allow(host)

    def order_nodes(self, nodes: list, local: str = "") -> list:
        """Replica owners ordered for placement: breaker-allowed nodes
        first (stable within each class, so equal-health clusters keep
        the jump-hash primary order and its locality), the allowed
        class additionally ranked by quantized health score. Open
        circuits sink to the end but are NOT dropped — when every
        replica of a slice is dark the query still attempts one (the
        attempt doubles as an extra probe)."""
        if len(nodes) < 2:
            return nodes
        local = local or self.node

        def key(n):
            if n.host == local:
                return (0, 0.0)
            if not self.breakers.would_allow(n.host):
                return (2, 0.0)
            if self.breakers.state(n.host) != STATE_CLOSED:
                # Probe-ready (open window lapsed / half-open): rank
                # at the top of the remote class so the slices whose
                # natural order starts with this peer route it the
                # probe. Its health score is STALE by construction —
                # an open circuit gets no samples — and ranking by it
                # would exile a recovered peer forever.
                return (1, -1.0)
            # Quantized so EWMA noise can't shuffle stable placement.
            return (1, -round(self.health.score(n.host), 1))

        return sorted(nodes, key=key)

    def probe_targets(self) -> list[str]:
        """Peers whose breaker wants a half-open probe NOW (open
        window lapsed, no probe in flight). The server's background
        probe loop sends each a cheap /version request — recovery must
        not depend on query traffic happening to rank the returned
        peer first (in many topologies it never does)."""
        return [host for host, st in self.breakers.snapshot().items()
                if st["state"] != STATE_CLOSED
                and self.breakers.would_allow(host)]

    def hedge_delay_s(self, host: str) -> Optional[float]:
        """Seconds to wait on ``host`` before firing a hedge leg, or
        None when hedging is off."""
        if self.hedge_s <= 0 or host == self.node:
            return None
        return max(self.hedge_s, self.health.latency_tail(host))

    # -- exposition ----------------------------------------------------------

    def snapshot(self) -> dict:
        """The /status ``fault`` block: per-peer health + breaker
        state, plus the armed failpoints."""
        from . import failpoints as fp
        out = {
            "peers": self.health.snapshot(),
            "breakers": self.breakers.snapshot(),
            "hedgeS": self.hedge_s,
        }
        if fp.ACTIVE is not None:
            out["failpoints"] = fp.ACTIVE.snapshot()
        return out
