"""Per-peer health: EWMA of RPC outcomes and latency + gossip liveness.

Every cluster/client.py attempt feeds ``record`` (ok/failed + wall
latency); the gossip membership layer feeds ``note_gossip`` on state
transitions. The blended **score** in [0, 1] is what the executor's
replica ordering consumes (fault.FaultManager.order_nodes), and every
update mirrors into the ``pilosa_cluster_peer_health`` gauge so
operators watch degradation instead of discovering it.

EWMA, not windows: a fixed smoothing factor means one dict entry per
peer, updates are O(1) on the RPC hot path, and the score decays
toward the truth at a known rate regardless of traffic shape.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..obs import metrics as obs_metrics

# Smoothing factor per sample: ~10 samples to move 90% of the way.
ALPHA = 0.2
# Latency deviation multiplier for the hedging tail estimate
# (mean + K·mean-abs-deviation ≈ p95 for well-behaved latencies).
_TAIL_K = 3.0


class _Peer:
    __slots__ = ("ok", "lat", "dev", "gossip", "samples", "last_ts",
                 "fails", "oks")

    def __init__(self):
        self.ok = 1.0        # EWMA of outcome (1 success / 0 failure)
        self.lat = 0.0       # EWMA of latency seconds
        self.dev = 0.0       # EWMA of |latency - lat|
        self.gossip = "alive"
        self.samples = 0
        self.last_ts = 0.0
        self.fails = 0       # lifetime counters, for the snapshot
        self.oks = 0


class PeerHealth:
    def __init__(self, node: str = "", alpha: float = ALPHA):
        self.node = node
        self.alpha = alpha
        self._mu = threading.Lock()
        self._peers: dict[str, _Peer] = {}

    def _peer(self, host: str) -> _Peer:
        p = self._peers.get(host)
        if p is None:
            p = self._peers[host] = _Peer()
        return p

    # -- feeds ---------------------------------------------------------------

    def record(self, host: str, ok: bool,
               latency_s: Optional[float] = None) -> None:
        a = self.alpha
        with self._mu:
            p = self._peer(host)
            p.ok += a * ((1.0 if ok else 0.0) - p.ok)
            if ok:
                p.oks += 1
            else:
                p.fails += 1
            if latency_s is not None and ok:
                if p.samples == 0 or p.lat == 0.0:
                    p.lat = latency_s
                else:
                    p.dev += a * (abs(latency_s - p.lat) - p.dev)
                    p.lat += a * (latency_s - p.lat)
            p.samples += 1
            p.last_ts = time.time()
            score = self._score_locked(p)
        obs_metrics.PEER_HEALTH.labels(host).set(round(score, 4))

    def note_gossip(self, host: str, state: str) -> None:
        with self._mu:
            p = self._peer(host)
            p.gossip = state
            if state == "alive" and p.ok < 1.0:
                # A refuted suspicion / rejoin fully forgives the
                # outcome EWMA: the old score describes the old
                # incarnation, and a decayed score would starve the
                # returned peer of the traffic it needs to re-prove
                # itself (the breaker still guards the first probe).
                p.ok = 1.0
            score = self._score_locked(p)
        obs_metrics.PEER_HEALTH.labels(host).set(round(score, 4))

    # -- consults ------------------------------------------------------------

    @staticmethod
    def _score_locked(p: _Peer) -> float:
        if p.gossip == "dead":
            return 0.0
        s = p.ok
        if p.gossip == "suspect":
            s *= 0.5
        return max(0.0, min(1.0, s))

    def score(self, host: str) -> float:
        """Blended health in [0, 1]; unknown peers score 1.0 (innocent
        until an RPC or a rumor says otherwise)."""
        with self._mu:
            p = self._peers.get(host)
            return 1.0 if p is None else self._score_locked(p)

    def latency(self, host: str) -> float:
        with self._mu:
            p = self._peers.get(host)
            return 0.0 if p is None else p.lat

    def latency_tail(self, host: str) -> float:
        """A p95-ish latency estimate (EWMA mean + K·deviation) — the
        hedged-read trigger for this peer; 0.0 when unobserved."""
        with self._mu:
            p = self._peers.get(host)
            if p is None or p.lat == 0.0:
                return 0.0
            return p.lat + _TAIL_K * p.dev

    def snapshot(self) -> dict:
        with self._mu:
            items = list(self._peers.items())
        out = {}
        for host, p in items:
            out[host] = {
                "score": round(self._score_locked(p), 4),
                "okEwma": round(p.ok, 4),
                "latencyMs": round(p.lat * 1e3, 3),
                "latencyTailMs": round(
                    (p.lat + _TAIL_K * p.dev) * 1e3, 3),
                "gossip": p.gossip,
                "samples": p.samples,
                "failures": p.fails,
                "successes": p.oks,
            }
        return out
