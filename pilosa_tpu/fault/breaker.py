"""Per-peer circuit breakers: closed / open / half-open.

The contract the executor and client build on:

- **closed**: requests flow; ``threshold`` CONSECUTIVE transport
  failures trip the breaker open (any completed HTTP exchange —
  whatever its status code — counts as success: the peer is alive).
- **open**: ``allow()`` answers False, so placement skips the peer and
  the client fails fast (CircuitOpenError) instead of paying the dead
  peer's socket timeout. The open window is exponential backoff with
  FULL jitter: ``uniform(0, min(cap, base·2^n))`` after the n-th trip
  (AWS full-jitter — a cluster of coordinators must not probe a
  recovering peer in lockstep).
- **half-open**: once the window lapses, exactly ONE in-flight probe
  is granted; its success closes the breaker (and resets the backoff
  exponent), its failure re-opens with a doubled window.

Transitions mirror into ``pilosa_fault_breaker_state`` /
``pilosa_fault_breaker_transitions_total`` and — when a traced query
drives the transition — a zero-length span on its trace, so a stitched
perfetto view shows WHERE the breaker tripped inside the query.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from ..obs import metrics as obs_metrics
from ..sched import context as sched_context

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

_STATE_GAUGE = {STATE_CLOSED: 0, STATE_HALF_OPEN: 1, STATE_OPEN: 2}


class _Breaker:
    __slots__ = ("state", "failures", "openings", "open_until",
                 "probe_inflight", "probe_granted", "opened_ts",
                 "last_reason")

    def __init__(self):
        self.state = STATE_CLOSED
        self.failures = 0        # consecutive transport failures
        self.openings = 0        # trips since last close (backoff exp)
        self.open_until = 0.0
        self.probe_inflight = False
        self.probe_granted = 0.0  # clock() when the probe was granted
        self.opened_ts = 0.0
        self.last_reason = ""


class BreakerBoard:
    """All of one node's per-peer breakers behind one lock."""

    # Seconds after which a granted-but-unreported half-open probe is
    # considered abandoned and a new probe may be granted. A probe can
    # die without an outcome (its request raised before reaching the
    # wire, the caller was interrupted); without an expiry that lost
    # slot would blacklist the peer FOREVER — every later allow() sees
    # probe_inflight and fails fast, and nothing ever reports back.
    # Sized above the client's 30 s default socket timeout so a
    # legitimately slow probe is never double-granted.
    PROBE_EXPIRY_S = 60.0

    def __init__(self, threshold: int = 3, backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 30.0, node: str = "",
                 rng: Optional[random.Random] = None, clock=None):
        self.threshold = max(1, threshold)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.node = node
        self._rng = rng or random.Random()
        self._clock = clock or time.monotonic
        self._mu = threading.Lock()
        self._peers: dict[str, _Breaker] = {}

    def _peer(self, host: str) -> _Breaker:
        b = self._peers.get(host)
        if b is None:
            b = self._peers[host] = _Breaker()
        return b

    # -- transitions (hold _mu) ----------------------------------------------

    def _transition(self, host: str, b: _Breaker, to: str,
                    reason: str = "") -> None:
        if b.state == to:
            return
        b.state = to
        b.last_reason = reason
        obs_metrics.BREAKER_STATE.labels(host).set(_STATE_GAUGE[to])
        obs_metrics.BREAKER_TRANSITIONS.labels(host, to).inc()
        # Attribute the transition to the query that drove it, when
        # one is bound and traced (zero-length marker span).
        ctx = sched_context.current()
        trace = getattr(ctx, "trace", None) if ctx is not None else None
        if trace is not None:
            trace.add_span(f"breaker_{to}", time.time(), 0.0,
                           tags={"peer": host, "reason": reason})

    def _open(self, host: str, b: _Breaker, reason: str) -> None:
        b.openings += 1
        window = min(self.backoff_cap_s,
                     self.backoff_base_s * (2.0 ** (b.openings - 1)))
        b.open_until = self._clock() + self._rng.uniform(0.0, window)
        b.opened_ts = time.time()
        b.probe_inflight = False
        self._transition(host, b, STATE_OPEN, reason)

    # -- feeds ---------------------------------------------------------------

    def record_success(self, host: str) -> None:
        with self._mu:
            b = self._peers.get(host)
            if b is None:
                return
            b.failures = 0
            b.probe_inflight = False
            if b.state != STATE_CLOSED:
                b.openings = 0
                self._transition(host, b, STATE_CLOSED, "probe ok")

    def record_failure(self, host: str) -> None:
        with self._mu:
            b = self._peer(host)
            b.failures += 1
            if b.state == STATE_HALF_OPEN:
                # The probe failed: re-open with a doubled window.
                self._open(host, b, "probe failed")
            elif (b.state == STATE_CLOSED
                  and b.failures >= self.threshold):
                self._open(host, b,
                           f"{b.failures} consecutive failures")
            elif b.state == STATE_OPEN:
                b.probe_inflight = False

    def force_open(self, host: str, reason: str = "forced") -> None:
        """Open immediately (gossip declared the peer dead) — no
        threshold wait, so not even the FIRST query pays a timeout."""
        with self._mu:
            b = self._peer(host)
            if b.state != STATE_OPEN:
                b.failures = self.threshold
                self._open(host, b, reason)

    def note_probe_ready(self, host: str) -> None:
        """Collapse the open window (gossip says the peer is back):
        the next request becomes the half-open probe right away. A
        HALF_OPEN breaker whose probe never reported back is rescued
        too — the liveness evidence outranks a lost probe slot."""
        with self._mu:
            b = self._peers.get(host)
            if b is None:
                return
            if b.state == STATE_OPEN:
                b.open_until = self._clock()
            elif b.state == STATE_HALF_OPEN:
                b.probe_inflight = False

    # -- consults ------------------------------------------------------------

    def _probe_expired(self, b: _Breaker) -> bool:
        return (b.probe_inflight
                and self._clock() - b.probe_granted
                > self.PROBE_EXPIRY_S)

    def allow(self, host: str) -> bool:
        """May a request go to ``host``? Open→half-open happens here:
        when the window has lapsed, the FIRST caller is granted the
        probe and concurrent callers keep failing fast until the probe
        reports back (or its expiry reclaims an abandoned slot)."""
        with self._mu:
            b = self._peers.get(host)
            if b is None or b.state == STATE_CLOSED:
                return True
            now = self._clock()
            if b.state == STATE_OPEN:
                if now < b.open_until:
                    return False
                self._transition(host, b, STATE_HALF_OPEN,
                                 "backoff elapsed")
                b.probe_inflight = True
                b.probe_granted = now
                return True
            # half-open: one probe at a time
            if b.probe_inflight and not self._probe_expired(b):
                return False
            b.probe_inflight = True
            b.probe_granted = now
            return True

    def would_allow(self, host: str) -> bool:
        """allow() without the side effects (no half-open transition,
        no probe slot taken) — the consult for placement ordering and
        for pure peer FILTERS like the anti-entropy syncer (which must
        never consume the probe its own client is about to need)."""
        with self._mu:
            b = self._peers.get(host)
            if b is None or b.state == STATE_CLOSED:
                return True
            if b.state == STATE_OPEN:
                return self._clock() >= b.open_until
            return not b.probe_inflight or self._probe_expired(b)

    def state(self, host: str) -> str:
        with self._mu:
            b = self._peers.get(host)
            return STATE_CLOSED if b is None else b.state

    def snapshot(self) -> dict:
        now = self._clock()
        with self._mu:
            items = list(self._peers.items())
            out = {}
            for host, b in items:
                out[host] = {
                    "state": b.state,
                    "consecutiveFailures": b.failures,
                    "openings": b.openings,
                    "reopenInS": round(max(0.0, b.open_until - now), 3)
                    if b.state == STATE_OPEN else 0.0,
                    "reason": b.last_reason,
                }
        return out
