"""Failpoints: named, deterministic fault-injection sites.

The chaos-testing contract (docs/FAULT_TOLERANCE.md): production code
carries a handful of NAMED injection points; a disarmed site costs one
module-attribute read and a None check (the same nop-path contract as
ctx.trace — the overhead guard test proves no registry call happens),
and an armed site injects a scripted fault deterministically, so every
chaos failure replays from its logged seed.

Sites (each exercised by at least one test):

==================  =========================================================
``rpc.send``        cluster/client._do, before a request reaches the wire
``rpc.recv``        cluster/client._do, after the response is read
``wal.append``      storage/roaring, around every op-log write (torn-write
                    capable: writes a prefix, then fails — crash mid-append)
``snapshot.write``  storage/fragment, inside the snapshot tmp-file write
``gossip.deliver``  cluster/gossip envelope delivery (drop / delay)
``mesh.dispatch``   parallel/mesh device dispatch gates
``ring.write``      obs/diskring segment appends (trace store +
                    blackbox ring; torn-write capable — crash
                    mid-segment-write)
``resize.stream``   server/syncer FragmentStreamer block pushes during
                    an elastic resize (torn-write capable: a PREFIX of
                    the block's positions lands on the target, then the
                    stream fails — the idempotent block re-diff must
                    converge); partition mode scopes by target host
``storage.read``    storage/fragment, before the data file is read
                    back (open) and before the scrubber re-reads it —
                    corrupt-capable: flips real bits in the on-disk
                    snapshot/mmap bytes, so detection → quarantine →
                    repair is deterministically injectable at every
                    leg (storage-integrity subsystem)
``tier.fault``      storage/fragment, before a cold fragment's
                    container blocks are faulted in on first read
                    (tier working-set manager) — corrupt-capable:
                    flips real bits in the demoted snapshot so the
                    per-block crc check at fault time catches it
``tier.fetch``      tier/manager blob-tier transfers (push + fetch)
                    — error/delay/corrupt legs make cold-fetch
                    failure and torn-promotion deterministically
                    injectable; partition mode scopes by direction
                    (``push`` / ``fetch``)
``backup.push``     backup/archive object puts (fragment blocks, WAL
                    segments, manifests) — fires AFTER the store
                    write, so error mode models a crash with the
                    object durable (resume must skip it), torn mode
                    replaces the object with a prefix (a torn archive
                    object restore admission must catch), corrupt
                    flips real bits of the stored object; partition
                    mode scopes by object key
``restore.fetch``   backup/archive object gets during restore /
                    verify — error makes a fetch fail, corrupt flips
                    stored bits BEFORE the read so digest-verified
                    admission (the PR-15 contract) must reject them,
                    torn raises mid-transfer; partition scopes by key
==================  =========================================================

Spec grammar (one string per site)::

    off                        disarm
    error                      raise FailpointError every hit
    error(0.25)                ... with probability 0.25 (seeded RNG)
    enospc                     raise FailpointError carrying
                               errno.ENOSPC — a full disk at this
                               site (fault.diskfull degradation path)
    enospc(0.25)               ... with probability 0.25
    delay(50ms)                sleep 50 ms, then proceed
    delay(50ms,0.5)            ... with probability 0.5
    torn(7)                    write the first 7 bytes of the record,
                               then raise (wal.append / sites passing
                               ``data`` + ``writer``)
    corrupt                    flip ONE real bit of the site's file
                               (``writer`` at snapshot.write, ``path``
                               at storage.read) at a seeded-random
                               offset, then PROCEED — silent on-disk
                               corruption, exactly the fault the
                               integrity footer exists to catch
    corrupt(3)                 ... flip 3 bits
    partition(hostB)           raise only when the site's ``host``
                               contains "hostB" (one-way partition)
    <mode>*3                   trigger at most 3 times, then auto-disarm

Arming: ``[fault.failpoints]`` TOML, ``PILOSA_FAULT_<SITE>`` env (dots
as underscores: ``PILOSA_FAULT_RPC_SEND=error``), or
``POST /debug/failpoints``. The RNG seeds from ``PILOSA_FAULT_SEED``
(logged at first arm) so probabilistic schedules replay exactly.
"""

from __future__ import annotations

import logging
import os
import random
import re
import threading
import time
from typing import Optional

from ..obs import metrics as obs_metrics
from ..utils.config import parse_duration

# The nop-path flag every injection site checks inline:
#     if failpoints.ACTIVE is not None: failpoints.ACTIVE.hit("rpc.send")
# None whenever no failpoint is armed anywhere — the disarmed cost is
# one module-attribute read, no call, no allocation.
ACTIVE: Optional["Failpoints"] = None

SITES = ("rpc.send", "rpc.recv", "wal.append", "snapshot.write",
         "gossip.deliver", "mesh.dispatch", "ring.write",
         "resize.stream", "storage.read", "tier.fault", "tier.fetch",
         "backup.push", "restore.fetch")


def env_key(site: str) -> str:
    """The ONE site→env-variable mapping (dots as underscores):
    utils.config's load() and arm_from_env both use it, so the env
    contract cannot drift between the two arming paths."""
    return "PILOSA_FAULT_" + site.replace(".", "_").upper()

_LOG = logging.getLogger("pilosa_tpu.fault")

_SPEC_RE = re.compile(
    r"^(?P<mode>[a-z]+)"
    r"(?:\((?P<args>[^)]*)\))?"
    r"(?:\*(?P<count>\d+))?$")

_MODES = ("error", "delay", "torn", "partition", "enospc", "corrupt")


class FailpointError(OSError):
    """An injected fault. Subclasses OSError deliberately: transport
    layers (http.client wrappers, the gossip loops, the device-dispatch
    fallback) already treat OSError as 'the operation failed', so an
    injection exercises exactly the recovery path a real fault would."""


class Failpoint:
    __slots__ = ("site", "mode", "arg", "pct", "remaining", "spec",
                 "hits")

    def __init__(self, site: str, mode: str, arg, pct: float,
                 remaining: Optional[int], spec: str):
        self.site = site
        self.mode = mode
        self.arg = arg
        self.pct = pct
        self.remaining = remaining  # None = unlimited triggers
        self.spec = spec
        self.hits = 0


def parse_spec(site: str, spec: str) -> Optional[Failpoint]:
    """Spec string → Failpoint; None for "off"/empty; ValueError on
    anything malformed (an unparseable injection must fail loudly —
    a chaos test that silently injects nothing proves nothing)."""
    spec = spec.strip()
    if not spec or spec == "off":
        return None
    m = _SPEC_RE.match(spec)
    if m is None or m.group("mode") not in _MODES:
        raise ValueError(f"failpoint {site}: invalid spec {spec!r}")
    mode = m.group("mode")
    raw_args = [a.strip() for a in (m.group("args") or "").split(",")
                if a.strip()]
    count = int(m.group("count")) if m.group("count") else None
    pct = 1.0
    arg = None
    if mode in ("error", "enospc"):
        if len(raw_args) > 1:
            raise ValueError(f"failpoint {site}: {mode} takes at most"
                             f" one argument")
        if raw_args:
            pct = float(raw_args[0])
    elif mode == "delay":
        if not raw_args or len(raw_args) > 2:
            raise ValueError(f"failpoint {site}: delay(duration[,p])")
        arg = parse_duration(raw_args[0])
        if len(raw_args) == 2:
            pct = float(raw_args[1])
    elif mode == "torn":
        if not raw_args or len(raw_args) > 2:
            raise ValueError(f"failpoint {site}: torn(bytes[,p])")
        arg = int(raw_args[0])
        if len(raw_args) == 2:
            pct = float(raw_args[1])
    elif mode == "corrupt":
        if len(raw_args) > 2:
            raise ValueError(f"failpoint {site}: corrupt([bits][,p])")
        arg = int(raw_args[0]) if raw_args else 1
        if arg < 1:
            raise ValueError(f"failpoint {site}: corrupt needs >=1 bit")
        if len(raw_args) == 2:
            pct = float(raw_args[1])
    elif mode == "partition":
        if not raw_args or len(raw_args) > 2:
            raise ValueError(f"failpoint {site}: partition(host[,p])")
        arg = raw_args[0]
        if len(raw_args) == 2:
            pct = float(raw_args[1])
    if not 0.0 <= pct <= 1.0:
        raise ValueError(f"failpoint {site}: probability {pct} outside"
                         f" [0, 1]")
    return Failpoint(site, mode, arg, pct, count, spec)


class Failpoints:
    """The armed-failpoint registry. One process-global instance
    (``default()``) serves every injection site; tests may build their
    own for isolation of the parsing/trigger logic."""

    def __init__(self, seed: Optional[int] = None):
        if seed is None:
            env = os.environ.get("PILOSA_FAULT_SEED", "")
            seed = int(env) if env else random.SystemRandom().randrange(
                1 << 31)
        self.seed = seed
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self._points: dict[str, Failpoint] = {}
        self._seed_logged = False

    # -- arming --------------------------------------------------------------

    def arm(self, site: str, spec: str) -> None:
        if site not in SITES:
            raise ValueError(
                f"unknown failpoint site {site!r} (sites: "
                + ", ".join(SITES) + ")")
        fp = parse_spec(site, spec)
        with self._mu:
            if fp is None:
                self._points.pop(site, None)
            else:
                self._points[site] = fp
                if not self._seed_logged:
                    self._seed_logged = True
                    # The replay contract: every chaos failure report
                    # carries the seed that reproduces its schedule.
                    _LOG.warning(
                        "failpoints armed (PILOSA_FAULT_SEED=%d to"
                        " replay this schedule)", self.seed)
        self._sync_active()

    def disarm(self, site: str) -> None:
        with self._mu:
            self._points.pop(site, None)
        self._sync_active()

    def disarm_all(self) -> None:
        with self._mu:
            self._points.clear()
        self._sync_active()

    def _sync_active(self) -> None:
        """Publish to the process-global ACTIVE hook — DEFAULT registry
        only. A private registry (unit tests isolating trigger logic)
        must neither hijack the production injection sites nor clear a
        schedule the default registry armed."""
        global ACTIVE
        with _default_mu:
            is_default = _default is self
        if not is_default:
            return
        with self._mu:
            armed = bool(self._points)
        ACTIVE = self if armed else None

    # -- the injection hook --------------------------------------------------

    def hit(self, site: str, host: Optional[str] = None,
            writer=None, data: Optional[bytes] = None,
            path: Optional[str] = None,
            span: Optional[tuple] = None) -> None:
        """Evaluate ``site``. Raises FailpointError when the armed mode
        says so; returns silently otherwise. ``host`` scopes partition
        mode; ``writer``+``data`` let torn mode emit a prefix of the
        record before failing; ``writer`` (an open file) or ``path``
        give corrupt mode the bytes to flip. ``span`` (offset, length)
        confines corrupt flips to the byte range the caller is about to
        verify, so detection is deterministic rather than a draw
        against the whole file."""
        with self._mu:
            fp = self._points.get(site)
            if fp is None:
                return
            if fp.mode == "partition" and (
                    host is None or fp.arg not in host):
                return
            if fp.pct < 1.0 and self._rng.random() >= fp.pct:
                return
            fp.hits += 1
            if fp.remaining is not None:
                fp.remaining -= 1
                if fp.remaining <= 0:
                    self._points.pop(site, None)
            mode, arg = fp.mode, fp.arg
        self._sync_active()
        obs_metrics.FAILPOINT_TRIGGERS.labels(site).inc()
        # Tail-sampling cross-link (obs.sampler): a query that hit an
        # armed failpoint is chaos evidence — flag its context so the
        # end-of-query keep decision retains the trace.
        from ..sched import context as sched_context
        ctx = sched_context.current()
        if ctx is not None:
            ctx.note_flag("failpoint")
        if mode == "delay":
            time.sleep(arg)
            return
        if mode == "torn":
            if writer is not None and data:
                writer.write(data[:max(0, min(int(arg), len(data)))])
            raise FailpointError(
                f"failpoint {site}: torn write after {arg} bytes")
        if mode == "corrupt":
            self._corrupt(site, writer=writer, path=path,
                          bits=int(arg or 1), span=span)
            return
        if mode == "enospc":
            # The two-arg OSError form sets .errno, so the catching
            # site's `err.errno == errno.ENOSPC` test sees exactly
            # what a real full disk raises.
            import errno as errno_mod
            raise FailpointError(
                errno_mod.ENOSPC,
                f"failpoint {site}: injected ENOSPC"
                " (no space left on device)")
        # error / partition
        raise FailpointError(f"failpoint {site}: injected"
                             + (f" (partition {arg})"
                                if mode == "partition" else ""))

    def _corrupt(self, site: str, writer, path: Optional[str],
                 bits: int, span: Optional[tuple] = None) -> None:
        """Flip ``bits`` real bits at seeded-random offsets of the
        site's file — silent on-disk corruption, the fault the
        storage-integrity footer (storage.integrity) exists to catch.
        Proceeds (never raises): the point is that NOTHING fails at
        the write, exactly like real bit rot."""
        opened = None
        fd = None
        if writer is not None and hasattr(writer, "fileno"):
            # Snapshot writers are opened "wb" (write-only), so flips
            # reopen the file read-write by name; a nameless writer
            # (BytesIO-backed test double) falls through to its fd.
            try:
                writer.flush()
            except (OSError, ValueError):
                pass
            name = getattr(writer, "name", None)
            if isinstance(name, str) and path is None:
                path = name
            else:
                try:
                    fd = writer.fileno()
                except (OSError, ValueError):
                    fd = None
        if fd is None:
            if path is None:
                return
            try:
                opened = open(path, "r+b")
            except OSError:
                return  # nothing on disk yet: nothing to rot
            fd = opened.fileno()
        try:
            size = os.fstat(fd).st_size
            if size <= 0:
                return
            base, extent = 0, size
            if span is not None:
                base = max(0, min(int(span[0]), size - 1))
                extent = max(1, min(int(span[1]), size - base))
            with self._mu:  # seeded draws stay on the replay schedule
                flips = [(base + self._rng.randrange(extent),
                          self._rng.randrange(8))
                         for _ in range(bits)]
            for off, bit in flips:
                b = os.pread(fd, 1, off)
                if not b:
                    continue
                os.pwrite(fd, bytes([b[0] ^ (1 << bit)]), off)
                _LOG.warning(
                    "failpoint %s: corrupt flipped bit %d of byte %d"
                    " (file size %d)", site, bit, off, size)
        finally:
            if opened is not None:
                opened.close()

    # -- exposition ----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            points = {
                site: {"spec": fp.spec, "hits": fp.hits,
                       "remaining": fp.remaining}
                for site, fp in self._points.items()}
        return {"seed": self.seed, "sites": list(SITES),
                "armed": points}


_default: Optional[Failpoints] = None
_default_mu = threading.Lock()


def default() -> Failpoints:
    global _default
    with _default_mu:
        if _default is None:
            _default = Failpoints()
        return _default


def seed_default(seed: int) -> None:
    """Fix the default registry's RNG seed (the [fault] seed knob).
    Rebuilds the registry, so call before arming anything."""
    global _default, ACTIVE
    with _default_mu:
        _default = Failpoints(seed=seed)
    ACTIVE = None  # the old registry's schedule (if any) is gone


def arm(site: str, spec: str) -> None:
    default().arm(site, spec)


def disarm_all() -> None:
    if _default is not None:
        _default.disarm_all()


def arm_from_env(env=None) -> list[str]:
    """Arm failpoints from ``PILOSA_FAULT_<SITE>`` variables (dots as
    underscores); returns the sites armed. Reserved PILOSA_FAULT_*
    names (SEED, HEDGE, the breaker knobs) are skipped — they belong
    to utils.config."""
    env = os.environ if env is None else env
    armed = []
    for site in SITES:
        val = env.get(env_key(site))
        if val is None:
            continue
        arm(site, val)
        if val.strip() not in ("", "off"):
            armed.append(site)
    return armed


class injected:
    """Context manager for tests: arm on enter, disarm on exit.

    >>> with injected("rpc.send", "error"):
    ...     ...
    """

    def __init__(self, site: str, spec: str):
        self.site = site
        self.spec = spec

    def __enter__(self):
        arm(self.site, self.spec)
        return default()

    def __exit__(self, exc_type, exc, tb):
        default().disarm(self.site)
        return False
