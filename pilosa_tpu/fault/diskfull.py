"""Disk-full graceful degradation: ENOSPC flips the node write-unready.

A full disk used to be a 500 crash-loop: every write query hit the WAL
leader flush, got an OSError, answered 500, and the client retried
into the same wall — while reads (which need no new bytes) were
perfectly servable. This module is the one place that state lives:

- Durable-write sites (``wal.append`` leader flushes,
  ``snapshot.write`` rewrites) call :func:`note_enospc` when their
  OSError is ENOSPC. The node flips **write-unready**: ``/health``
  reports it (load balancers can drain writes), and the HTTP layer
  answers writes with ``507 Insufficient Storage`` + Retry-After
  instead of admitting them into a doomed WAL append. Reads keep
  serving throughout.
- **Auto-recovery**: while unready, :func:`write_ready` probes the
  failing directory (throttled) with a real write; the first probe
  that succeeds — an operator freed space, a retention job pruned —
  clears the state with no restart.
- Observability rings (obs.diskring) deliberately do NOT flip this
  state: diagnostics must never gate serving. They drop-and-count
  (SegmentRing.dropped) on any write failure, ENOSPC included.

Injection: the ``enospc`` failpoint mode (fault.failpoints) raises a
FailpointError carrying ``errno.ENOSPC`` at the existing
``wal.append`` / ``snapshot.write`` / ``ring.write`` sites, so the
whole degrade-and-recover loop is testable on a healthy disk.
"""

from __future__ import annotations

import errno
import os
import threading
import time
from typing import Optional

from ..obs import metrics as obs_metrics

PROBE_INTERVAL_S = 2.0
# What a 507 tells the client to wait: the probe cadence — sooner
# retries cannot observe a recovery the probe hasn't.
RETRY_AFTER_S = 2


def is_enospc(err: BaseException) -> bool:
    return getattr(err, "errno", None) == errno.ENOSPC


class DiskFullState:
    """Process-wide write-readiness latch (one default instance via
    :func:`default`; tests may build their own)."""

    def __init__(self, probe_interval_s: float = PROBE_INTERVAL_S):
        self.probe_interval_s = probe_interval_s
        self._mu = threading.Lock()
        self._unready = False
        self._since = 0.0
        self._site = ""
        self._dir = ""
        self._events: dict[str, int] = {}
        self._last_probe = 0.0
        self._recoveries = 0
        obs_metrics.STORAGE_WRITE_READY.set(1)

    # -- flipping ------------------------------------------------------------

    def note_enospc(self, site: str, path: Optional[str] = None) -> None:
        """A durable-write site hit ENOSPC: flip write-unready and
        remember the directory so the recovery probe targets the
        filesystem that actually filled."""
        d = os.path.dirname(path) if path else ""
        with self._mu:
            self._events[site] = self._events.get(site, 0) + 1
            if not self._unready:
                self._unready = True
                self._since = time.time()
                self._site = site
                self._last_probe = 0.0  # next write_ready() probes
            if d:
                self._dir = d
        obs_metrics.STORAGE_ENOSPC.labels(site).inc()
        obs_metrics.STORAGE_WRITE_READY.set(0)

    def note_if_enospc(self, err: BaseException, site: str,
                       path: Optional[str] = None) -> bool:
        """note_enospc iff ``err`` is an ENOSPC (the one-liner the
        write sites' except-paths call); returns whether it was."""
        if is_enospc(err):
            self.note_enospc(site, path)
            return True
        return False

    def note_write_ok(self) -> None:
        """A durable write SUCCEEDED: clear the latch immediately (the
        cheapest possible recovery signal — real traffic proved the
        disk writable, no probe needed)."""
        with self._mu:
            if not self._unready:
                return
            self._clear_locked()
        obs_metrics.STORAGE_WRITE_READY.set(1)

    def _clear_locked(self) -> None:
        self._unready = False
        self._since = 0.0
        self._site = ""
        self._recoveries += 1

    # -- readiness -----------------------------------------------------------

    def write_ready(self, probe: bool = True) -> bool:
        """True while durable writes should be admitted. While
        unready, a throttled probe write to the failing directory
        auto-recovers the moment space frees."""
        with self._mu:
            if not self._unready:
                return True
            if not probe or not self._dir:
                return False
            now = time.monotonic()
            if now - self._last_probe < self.probe_interval_s:
                return False
            self._last_probe = now
            target = os.path.join(self._dir, ".enospc-probe")
        try:
            with open(target, "w") as f:
                f.write(str(time.time()))
            os.remove(target)
        except OSError:
            return False
        with self._mu:
            if self._unready:
                self._clear_locked()
        obs_metrics.STORAGE_WRITE_READY.set(1)
        return True

    def retry_after_s(self) -> int:
        return RETRY_AFTER_S

    def reset(self) -> None:
        """Test hook: back to pristine (counters included)."""
        with self._mu:
            self._unready = False
            self._since = 0.0
            self._site = ""
            self._dir = ""
            self._events = {}
            self._recoveries = 0
        obs_metrics.STORAGE_WRITE_READY.set(1)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "writeReady": not self._unready,
                "since": self._since or None,
                "site": self._site or None,
                "dir": self._dir or None,
                "events": dict(self._events),
                "recoveries": self._recoveries,
            }


_default: Optional[DiskFullState] = None
_default_mu = threading.Lock()


def default() -> DiskFullState:
    global _default
    with _default_mu:
        if _default is None:
            _default = DiskFullState()
        return _default


def note_if_enospc(err: BaseException, site: str,
                   path: Optional[str] = None) -> bool:
    return default().note_if_enospc(err, site, path)


def write_ready(probe: bool = True) -> bool:
    # Cheap when never tripped: one lock-guarded bool read.
    return _default is None or _default.write_ready(probe=probe)


def note_write_ok() -> None:
    if _default is not None:
        _default.note_write_ok()
