"""CLI entry point: ``python -m pilosa_tpu.cli <verb>``.

Reference: cmd/ (cobra wiring) + ctl/ (command logic). Verbs: server,
import, export, backup, restore, sort, check, inspect, bench, config.
"""

import sys

from .commands import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
