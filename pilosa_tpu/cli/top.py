"""``pilosa-tpu top``: a live terminal dashboard over the fleet
observability plane (docs/OBSERVABILITY.md).

Polls the federation endpoints of any cluster member — the member
does the fan-out, `top` does none of its own:

- ``GET /metrics/cluster?partial=1`` — merged counters/histograms +
  per-node gauges; consecutive scrapes difference into live QPS,
  per-lane p50/p99, WAL fsync rate, compile-cache hit rate;
- ``GET /debug/cluster?partial=1`` — per-node build/breaker/WAL/
  resize/admission columns (missing nodes render as DOWN);
- ``GET /debug/metrics/history?scope=cluster&partial=1`` — the p99
  sparkline over the trailing window, from the on-disk history.

Keybindings (documented in docs/OBSERVABILITY.md): ``q`` quit,
``p`` pause/resume polling, ``n`` toggle the per-node table.
``--once`` renders a single frame and exits (scripts, tests).
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Optional

SPARK = "▁▂▃▄▅▆▇█"


def _get(host: str, path: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return r.read()


def sparkline(values: list[float], width: int = 40) -> str:
    """Unicode sparkline, newest right, scaled to the window max."""
    if not values:
        return ""
    values = values[-width:]
    hi = max(values)
    if hi <= 0:
        return SPARK[0] * len(values)
    return "".join(SPARK[min(len(SPARK) - 1,
                             int(v / hi * (len(SPARK) - 1) + 0.5))]
                   for v in values)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0:
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}PB"


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v < 0.001:
        return f"{v * 1e6:.0f}us"
    if v < 1.0:
        return f"{v * 1e3:.1f}ms"
    return f"{v:.2f}s"


class Snapshot:
    """One polling pass: the parsed federation responses."""

    def __init__(self, host: str, timeout: float = 10.0,
                 history_window: str = "10m"):
        from ..obs.federate import parse_exposition
        self.at = time.time()
        self.families = parse_exposition(
            _get(host, "/metrics/cluster?partial=1",
                 timeout).decode())
        self.cluster = json.loads(
            _get(host, "/debug/cluster?partial=1", timeout))
        try:
            self.history = json.loads(_get(
                host, "/debug/metrics/history?scope=cluster&partial=1"
                      "&family=pilosa_query_duration_seconds"
                      f"&window={history_window}", timeout))
        except Exception:  # noqa: BLE001 - sparkline is optional garnish
            self.history = {"series": []}

    # -- family accessors -----------------------------------------------------

    def samples(self, family: str) -> list[tuple[str, dict, float]]:
        fam = self.families.get(family)
        return list(fam["samples"]) if fam else []

    def total(self, family: str, **match) -> float:
        out = 0.0
        for name, labels, v in self.samples(family):
            if name.endswith(("_bucket", "_sum")):
                continue
            if name.endswith("_count") and not family.endswith("_count"):
                continue
            if all(labels.get(k) == v2 for k, v2 in match.items()):
                out += v
        return out

    def gauge_sum(self, family: str, **match) -> float:
        return self.total(family, **match)

    def hist_components(self, family: str, **match
                        ) -> tuple[dict, float, float]:
        """(bucket le → cumulative count, sum, count) over every
        sample matching the label filter."""
        buckets: dict[str, float] = {}
        total = count = 0.0
        for name, labels, v in self.samples(family):
            if not all(labels.get(k) == v2 for k, v2 in match.items()):
                continue
            if name.endswith("_bucket"):
                le = labels.get("le", "")
                buckets[le] = buckets.get(le, 0.0) + v
            elif name.endswith("_sum"):
                total += v
            elif name.endswith("_count"):
                count += v
        return buckets, total, count


def _quantile(buckets: dict[str, float], q: float) -> Optional[float]:
    """Upper-bound quantile estimate from cumulative le buckets."""
    rows = []
    for le, c in buckets.items():
        try:
            bound = float("inf") if le == "+Inf" else float(le)
        except ValueError:
            continue
        rows.append((bound, c))
    rows.sort()
    if not rows or rows[-1][1] <= 0:
        return None
    want = rows[-1][1] * q
    for bound, c in rows:
        if c >= want:
            return None if bound == float("inf") else bound
    return None


def _delta_hist(cur, prev, family: str, **match
                ) -> tuple[dict, float, float]:
    """Bucket/sum/count deltas between two snapshots (the live
    window); falls back to cumulative when there is no previous."""
    cb, cs, cc = cur.hist_components(family, **match)
    if prev is None:
        return cb, cs, cc
    pb, ps, pc = prev.hist_components(family, **match)
    db = {le: max(0.0, c - pb.get(le, 0.0)) for le, c in cb.items()}
    return db, max(0.0, cs - ps), max(0.0, cc - pc)


def _rate(cur, prev, family: str, **match) -> Optional[float]:
    if prev is None:
        return None
    dt = cur.at - prev.at
    if dt <= 0:
        return None
    return max(0.0, (cur.total(family, **match)
                     - prev.total(family, **match))) / dt


def _lanes() -> tuple:
    from ..sched import LANES
    return LANES


def render(cur: Snapshot, prev: Optional[Snapshot],
           show_nodes: bool = True, paused: bool = False,
           width: int = 78) -> str:
    """One frame of the dashboard as plain text (ANSI-free: the loop
    adds the clear-screen; tests snapshot this)."""
    lines = []
    nodes = cur.cluster.get("nodes") or {}
    missing = cur.cluster.get("missing") or []
    skew = cur.cluster.get("versionSkew")
    title = (f"pilosa-tpu top — {len(nodes)} node"
             f"{'s' if len(nodes) != 1 else ''}")
    if missing:
        title += f" ({len(missing)} unreachable)"
    if skew:
        title += "  [VERSION SKEW]"
    if paused:
        title += "  [paused]"
    clock = time.strftime("%H:%M:%S", time.localtime(cur.at))
    lines.append(title + " " * max(1, width - len(title) - len(clock))
                 + clock)
    lines.append("-" * width)

    # Cluster roll-up row: QPS, latency, admission, WAL, compile, HBM.
    qps = _rate(cur, prev, "pilosa_query_requests_total")
    fsync = _rate(cur, prev, "pilosa_wal_fsync_calls_total")
    hits = _rate(cur, prev, "pilosa_compile_cache_hits_total")
    misses = _rate(cur, prev, "pilosa_compile_cache_misses_total")
    inflight = cur.gauge_sum("pilosa_admission_inflight_queries")
    queued = cur.gauge_sum("pilosa_admission_queue_depth")
    hbm = cur.gauge_sum("pilosa_residency_hbm_bytes", kind="used")
    b, _s, _c = _delta_hist(cur, prev, "pilosa_query_duration_seconds")
    lines.append(
        f"qps {qps:8.1f}/s" if qps is not None else "qps        -  ",)
    lines[-1] += (f"   p50 {_fmt_s(_quantile(b, 0.5)):>8}"
                  f"   p99 {_fmt_s(_quantile(b, 0.99)):>8}"
                  f"   inflight {inflight:.0f}"
                  f"   queued {queued:.0f}")
    row = (f"wal fsync {fsync:6.1f}/s" if fsync is not None
           else "wal fsync     -  ")
    if hits is not None and misses is not None:
        row += f"   compile hit {hits:5.1f}/s miss {misses:5.1f}/s"
    row += f"   hbm {_fmt_bytes(hbm)}"
    lines.append(row)
    lines.append("")

    # Per-lane table (live window when a previous scrape exists).
    lines.append(f"{'LANE':<8}{'QPS':>10}{'SHED/S':>10}{'P50':>10}"
                 f"{'P99':>10}")
    for lane in _lanes():
        lb, _ls, lc = _delta_hist(cur, prev,
                                  "pilosa_query_duration_seconds",
                                  lane=lane)
        lqps = _rate(cur, prev, "pilosa_query_requests_total",
                     lane=lane)
        shed = _rate(cur, prev, "pilosa_admission_rejections_total",
                     lane=lane)
        lines.append(
            f"{lane:<8}"
            + (f"{lqps:>9.1f}/s" if lqps is not None else f"{'-':>10}")
            + (f"{shed:>9.1f}/s" if shed is not None else f"{'-':>10}")
            + f"{_fmt_s(_quantile(lb, 0.5)):>10}"
            + f"{_fmt_s(_quantile(lb, 0.99)):>10}")
    lines.append("")

    # Planner panel: is the cost-based planner helping — CSE cache hit
    # rate, short-circuits per second, and the estimator's tail error
    # (misestimation ratio p99; ~1.0 means estimates track actuals).
    cse_hit = _rate(cur, prev,
                    "pilosa_planner_subresult_cache_events_total",
                    event="hit")
    cse_miss = _rate(cur, prev,
                     "pilosa_planner_subresult_cache_events_total",
                     event="miss")
    sc = _rate(cur, prev, "pilosa_planner_decisions_total",
               outcome="short_circuit")
    mb, _ms, _mc = _delta_hist(cur, prev,
                               "pilosa_planner_misestimation_ratio")
    mis_p99 = _quantile(mb, 0.99)
    if any(v is not None for v in (cse_hit, cse_miss, sc, mis_p99)):
        row = "planner "
        if cse_hit is not None and cse_miss is not None \
                and cse_hit + cse_miss > 0:
            pct = 100.0 * cse_hit / (cse_hit + cse_miss)
            row += f"  cse hit {pct:5.1f}%"
        else:
            row += "  cse hit     -"
        row += (f"   short-circuit {sc:6.1f}/s" if sc is not None
                else "   short-circuit     -")
        row += (f"   misest p99 {mis_p99:6.2f}x" if mis_p99 is not None
                else "   misest p99     -")
        lines.append(row)
        lines.append("")

    # p99 sparkline from the fleet history (mean across nodes/lanes
    # per tick).
    series = [s for s in (cur.history.get("series") or [])
              if s.get("name", "").endswith(":p99")]
    if series:
        by_ts: dict[float, list[float]] = {}
        for s in series:
            for ts, v in s.get("points") or []:
                by_ts.setdefault(round(ts), []).append(v)
        vals = [sum(vs) / len(vs) for _ts, vs in sorted(by_ts.items())]
        win = cur.history.get("windowS") or 0
        lines.append(f"p99 history ({int(win)}s): "
                     + sparkline(vals, width - 24))
        lines.append("")

    # Per-node table.
    if show_nodes:
        lines.append(f"{'NODE':<24}{'STATE':>6}{'VER':>10}{'BRKR':>6}"
                     f"{'WAL':>6}{'INFL':>6}{'RESIZE':>10}")
        for host in sorted(set(nodes) | set(missing)):
            if host in missing:
                lines.append(f"{host:<24}{'DOWN':>6}{'-':>10}{'-':>6}"
                             f"{'-':>6}{'-':>6}{'-':>10}")
                continue
            block = nodes[host] or {}
            ver = str((block.get("build") or {}).get("version",
                                                     ""))[:9]
            breakers = (block.get("fault") or {}).get("breakers") or {}
            n_open = sum(1 for b in breakers.values()
                         if isinstance(b, dict)
                         and b.get("state") == "open")
            wal = block.get("wal") or {}
            wal_col = ("ok" if not wal.get("oldestDirtyAgeS")
                       or wal["oldestDirtyAgeS"] < 1.0 else
                       f"{wal['oldestDirtyAgeS']:.0f}s")
            infl = (block.get("admission") or {}).get("inFlight", 0)
            resize = (block.get("resize") or {}).get("phase", "idle")
            lines.append(f"{host:<24}{'up':>6}{ver:>10}{n_open:>6}"
                         f"{wal_col:>6}{infl:>6}{resize:>10}")
    return "\n".join(lines) + "\n"


def cmd_top(args, stdout, stderr) -> int:
    """The CLI entry point (registered in commands.py)."""
    host = args.host
    interval = max(0.2, float(getattr(args, "interval", 2.0) or 2.0))
    window = getattr(args, "window", "") or "10m"
    try:
        cur = Snapshot(host, history_window=window)
    except Exception as e:  # noqa: BLE001 - CLI-facing error
        print(f"top: cannot reach {host}: {e}", file=stderr)
        return 1
    if getattr(args, "once", False):
        stdout.write(render(cur, None))
        return 0

    import select
    import sys
    prev: Optional[Snapshot] = None
    show_nodes = True
    paused = False
    poll_keys = True   # latched off at stdin EOF (closed pipe)
    # Raw-ish single-key input when stdin is a tty; plain polling
    # otherwise (pipes, tests).
    tty_fd = None
    old_attrs = None
    try:
        import termios
        import tty as tty_mod
        if sys.stdin.isatty():
            tty_fd = sys.stdin.fileno()
            old_attrs = termios.tcgetattr(tty_fd)
            tty_mod.setcbreak(tty_fd)
    except Exception:  # noqa: BLE001 - keys are a convenience
        tty_fd = None
    try:
        while True:
            stdout.write("\x1b[2J\x1b[H")   # clear + home
            stdout.write(render(cur, prev, show_nodes=show_nodes,
                                paused=paused))
            stdout.write("\n[q]uit  [p]ause  [n]odes\n")
            if hasattr(stdout, "flush"):
                stdout.flush()
            deadline = time.monotonic() + interval
            while True:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    break
                if not poll_keys:
                    # Stdin hit EOF (closed pipe): select() reports
                    # an EOF stream always-readable and read('')
                    # would busy-spin — just sleep out the interval.
                    time.sleep(wait)
                    break
                try:
                    ready, _, _ = select.select([sys.stdin], [], [],
                                                wait)
                except (OSError, ValueError):
                    time.sleep(wait)
                    break
                if not ready:
                    break
                key = sys.stdin.read(1)
                if not key:   # EOF mid-session: stop polling keys
                    poll_keys = False
                    continue
                if key in ("q", "Q"):
                    return 0
                if key in ("p", "P"):
                    paused = not paused
                if key in ("n", "N"):
                    show_nodes = not show_nodes
            if paused:
                continue
            try:
                prev, cur = cur, Snapshot(host, history_window=window)
            except Exception as e:  # noqa: BLE001 - keep the last frame
                print(f"top: poll failed: {e}", file=stderr)
    except KeyboardInterrupt:
        return 0
    finally:
        if tty_fd is not None and old_attrs is not None:
            import termios
            termios.tcsetattr(tty_fd, termios.TCSADRAIN, old_attrs)
