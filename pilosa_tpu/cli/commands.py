"""Command logic for the pilosa-tpu CLI.

Reference: ctl/ — one Command per verb: server (ctl: server/server.go),
import (ctl/import.go), export (ctl/export.go), backup/restore
(ctl/backup.go, ctl/restore.go), sort (ctl/sort.go), check
(ctl/check.go), inspect (ctl/inspect.go), bench (ctl/bench.go), config
(ctl/config.go).
"""

from __future__ import annotations

import argparse
import csv
import datetime as dt
import io
import mmap
import os
import random
import re
import sys
import time
from typing import Optional

import numpy as np

from ..errors import TIME_FORMAT, PilosaError

IMPORT_BUFFER_SIZE = 10_000_000  # bits per import batch (ctl/import.go:58)


def _parse_csv_bits(stream, stderr, start_rnum: int = 1):
    """CSV rows → Bit triples, streamed (ctl/import.go:119-180)."""
    from ..cluster.client import Bit
    for rnum, record in enumerate(csv.reader(stream), start_rnum):
        if not record or record[0] == "":
            continue
        if len(record) < 2:
            raise PilosaError(
                f"bad column count on row {rnum}: col={len(record)}")
        # Like the reference's strconv.ParseUint (ctl/import.go): ids
        # are unsigned 64-bit — negatives and overflow are per-row
        # errors, not wrapped or truncated.
        try:
            row_id = int(record[0])
            if not 0 <= row_id < 1 << 64:
                raise ValueError
        except ValueError:
            raise PilosaError(
                f"invalid row id on row {rnum}: {record[0]!r}")
        try:
            col_id = int(record[1])
            if not 0 <= col_id < 1 << 64:
                raise ValueError
        except ValueError:
            raise PilosaError(
                f"invalid column id on row {rnum}: {record[1]!r}")
        ts = 0
        if len(record) > 2 and record[2]:
            try:
                t = dt.datetime.strptime(record[2], TIME_FORMAT)
            except ValueError:
                raise PilosaError(
                    f"invalid timestamp on row {rnum}: {record[2]!r}")
            ts = int(t.replace(tzinfo=dt.timezone.utc).timestamp() * 1e9)
        yield Bit(row_id, col_id, ts)


def _parse_csv_arrays(stream, stderr, chunk_lines: int):
    """CSV → (rows u64, cols u64, ts i64|None) array chunks.

    Fast path: ONE native pass (bitops.cpp parse_csv_u64_pairs,
    ~10 M bits/s) that parses and validates in the same loop — strict
    two-field ``digits,digits`` lines, exact u64 bounds, ParseUint
    semantics; any other shape falls through. Without the native
    toolchain, the fallback is numpy's C CSV parser (np.loadtxt)
    behind a bytes-level gate, since loadtxt is laxer than ParseUint
    (negatives wrap under u64, floats truncate, '#' starts a comment).
    Chunks both parsers reject (timestamps, malformed rows) re-parse
    through _parse_csv_bits, which owns the exact per-row error
    messages (and their absolute row numbers).

    Known limit: chunking is by physical lines, so a quoted CSV field
    containing a newline can straddle a chunk boundary, and row numbers
    count lines rather than csv records. Pilosa's import format is
    numeric ``row,col[,timestamp]`` — quoted multi-line fields are not
    valid input here, so the trade is taken for the 30x parse speed."""

    # Fast-path gate: one C-level bytes.translate pass (digits, comma,
    # newline ONLY — no minus, dot, '#', or blank-line ambiguity can
    # reach loadtxt), ~50x cheaper than the structural regex it
    # replaces, which was 3x the cost of the parse itself. Structure
    # is validated AFTER the parse instead: exactly 2 columns and one
    # row per newline (a blank or 3-field line fails that and
    # re-parses through the exact path).
    def parse_clean(text: str):
        data = text.encode()
        from ..storage import native
        got = native.parse_csv_pairs(data)
        if got is not None:
            return got
        # numpy fallback (no native toolchain): gate, then loadtxt.
        if data.translate(None, b"0123456789,\r\n"):
            return None
        u8 = np.frombuffer(data, np.uint8)
        # Field lengths from separator spacing: >19 digits can exceed
        # 2^64, which loadtxt silently WRAPS under dtype=uint64 (the
        # exact path must reject it per ParseUint instead).
        sep_idx = np.flatnonzero((u8 == 10) | (u8 == 44))
        if len(sep_idx):
            if int(np.diff(sep_idx, prepend=-1).max()) > 20:
                return None
            if len(u8) - 1 - int(sep_idx[-1]) > 19:
                return None
        elif len(u8) > 19:
            return None
        n_lines = int((u8 == 10).sum())
        if len(u8) and u8[-1] != 10:
            n_lines += 1
        try:
            arr = np.loadtxt(io.StringIO(text), delimiter=",",
                             dtype=np.uint64, ndmin=2, comments=None)
        except (ValueError, OverflowError):
            return None  # e.g. an id past 2^64: exact path rejects it
        if arr.shape != (n_lines, 2):
            return None
        return arr[:, 0], arr[:, 1]
    # Read BYTE blocks cut at line boundaries instead of iterating the
    # stream line by line (the per-line loop cost more than the C
    # parse itself at import scale); chunk_lines only bounds the block
    # so memory stays flat. Line numbers for the exact path's error
    # messages come from newline counts.
    rnum = 1
    pending = ""
    block_chars = max(1 << 20, min(chunk_lines * 16, 64 << 20))
    eof = False
    while True:
        # Fill until the buffer is block-sized AND cuttable (a single
        # line longer than the block keeps growing the buffer rather
        # than spinning). Only each newly read block is scanned for a
        # newline — rescanning the accumulated buffer would go
        # quadratic on newline-free input (review finding).
        parts = [pending] if pending else []
        size = len(pending)
        has_nl = "\n" in pending
        while not eof and (size < block_chars or not has_nl):
            block = stream.read(block_chars)
            if not block:
                eof = True
            else:
                parts.append(block)
                size += len(block)
                has_nl = has_nl or "\n" in block
        pending = "".join(parts)
        if not pending:
            return
        if eof:
            chunk, pending = pending, ""
        else:
            cut = pending.rfind("\n")
            chunk, pending = pending[:cut + 1], pending[cut + 1:]
        n_chunk_lines = chunk.count("\n")
        if not chunk.endswith("\n"):
            n_chunk_lines += 1
        parsed = parse_clean(chunk)
        if parsed is not None and len(parsed[0]):
            # Slice to the caller's bits-per-batch bound: minimal-width
            # rows can pack more lines than chunk_lines into one byte
            # block (ctl/import.go:58's buffer contract).
            r_all, c_all = parsed
            for i in range(0, len(r_all), chunk_lines):
                yield (r_all[i:i + chunk_lines],
                       c_all[i:i + chunk_lines], None)
        else:
            bits = list(_parse_csv_bits(iter(chunk.splitlines(True)),
                                        stderr, start_rnum=rnum))
            for i in range(0, len(bits), chunk_lines):
                group = bits[i:i + chunk_lines]
                yield (np.array([b.row_id for b in group],
                                dtype=np.uint64),
                       np.array([b.column_id for b in group],
                                dtype=np.uint64),
                       np.array([b.timestamp for b in group],
                                dtype=np.int64))
        rnum += n_chunk_lines


def load_server_config(args, env=None):
    """Config for the server subcommand with flags > env > file priority
    (reference cmd/root.go:99-153 viper merge; flags cmd/server.go:88-104).
    ``load`` applies defaults ← file ← env; explicit flags overlay last."""
    from ..utils import config as config_mod

    cfg = config_mod.load(args.config or "", env=env)
    if args.data_dir:
        cfg.data_dir = args.data_dir
    if args.bind:
        cfg.host = args.bind
    if getattr(args, "plugins_path", ""):
        cfg.plugins_path = args.plugins_path
    if getattr(args, "log_path", ""):
        cfg.log_path = args.log_path
    if getattr(args, "cluster_hosts", ""):
        cfg.cluster.hosts = [h.strip() for h in
                             args.cluster_hosts.split(",") if h.strip()]
    if getattr(args, "cluster_internal_hosts", ""):
        cfg.cluster.internal_hosts = [
            h.strip() for h in args.cluster_internal_hosts.split(",")
            if h.strip()]
    if getattr(args, "cluster_replicas", None) is not None:
        cfg.cluster.replica_n = args.cluster_replicas
    if getattr(args, "cluster_type", ""):
        cfg.cluster.type = args.cluster_type
    if getattr(args, "cluster_internal_port", ""):
        cfg.cluster.internal_port = args.cluster_internal_port
    if getattr(args, "cluster_gossip_seed", ""):
        cfg.cluster.gossip_seed = args.cluster_gossip_seed
    if getattr(args, "cluster_gossip_secret", ""):
        cfg.cluster.gossip_secret = args.cluster_gossip_secret
    if getattr(args, "cluster_poll_interval", None) is not None:
        cfg.cluster.polling_interval = args.cluster_poll_interval
    if getattr(args, "anti_entropy_interval", None) is not None:
        cfg.anti_entropy_interval = args.anti_entropy_interval
    if getattr(args, "query_concurrency", None) is not None:
        cfg.query.concurrency = args.query_concurrency
    if getattr(args, "query_queue_depth", None) is not None:
        cfg.query.queue_depth = args.query_queue_depth
    if getattr(args, "query_default_timeout", None) is not None:
        cfg.query.default_timeout = args.query_default_timeout
    if getattr(args, "query_slow_threshold", None) is not None:
        cfg.query.slow_threshold = args.query_slow_threshold
    if getattr(args, "query_result_cache_entries", None) is not None:
        cfg.query.result_cache_entries = args.query_result_cache_entries
    if getattr(args, "query_result_cache_bits", None) is not None:
        cfg.query.result_cache_bits = args.query_result_cache_bits
    if getattr(args, "query_cluster_cache_entries", None) is not None:
        cfg.query.cluster_cache_entries = \
            args.query_cluster_cache_entries
    if getattr(args, "tenants", ""):
        from ..utils.config import parse_tenants
        cfg.tenants.table = parse_tenants(args.tenants)
    if getattr(args, "cluster_gen_staleness", None) is not None:
        cfg.cluster.gen_staleness = args.cluster_gen_staleness
    from ..utils.config import _parse_bool
    if getattr(args, "metrics_enabled", None) is not None:
        cfg.metrics.enabled = _parse_bool(args.metrics_enabled)
    if getattr(args, "metrics_runtime_interval", None) is not None:
        cfg.metrics.runtime_interval = args.metrics_runtime_interval
    if getattr(args, "trace_enabled", None) is not None:
        cfg.trace.enabled = _parse_bool(args.trace_enabled)
    if getattr(args, "trace_tail", None) is not None:
        cfg.trace.tail = _parse_bool(args.trace_tail)
    if getattr(args, "blackbox_enabled", None) is not None:
        cfg.blackbox.enabled = _parse_bool(args.blackbox_enabled)
    if getattr(args, "watchdog_enabled", None) is not None:
        cfg.watchdog.enabled = _parse_bool(args.watchdog_enabled)
    if getattr(args, "trace_max_traces", None) is not None:
        cfg.trace.max_traces = args.trace_max_traces
    if getattr(args, "metrics_accounting", None) is not None:
        cfg.metrics.accounting = _parse_bool(args.metrics_accounting)
    if getattr(args, "history_enabled", None) is not None:
        cfg.history.enabled = _parse_bool(args.history_enabled)
    if getattr(args, "sentinel_enabled", None) is not None:
        cfg.sentinel.enabled = _parse_bool(args.sentinel_enabled)
    if getattr(args, "sentinel_manifest", ""):
        cfg.sentinel.manifest = args.sentinel_manifest
    if getattr(args, "profile_continuous", None) is not None:
        cfg.profile.continuous = _parse_bool(args.profile_continuous)
    if getattr(args, "profile_hz", None) is not None:
        cfg.profile.hz = args.profile_hz
    if getattr(args, "slo_objective", None) is not None:
        cfg.slo.objective = args.slo_objective
    if getattr(args, "slo_target", None) is not None:
        cfg.slo.target = args.slo_target
    return cfg


def cmd_server(args, stdout, stderr) -> int:
    from ..cluster.broadcast import HTTPBroadcaster
    from ..cluster.topology import Cluster, Node
    from ..server.server import Server
    from ..utils import logger as logger_mod

    cfg = load_server_config(args)
    import os
    if cfg.log_path:
        logger = logger_mod.Logger.open(os.path.expanduser(cfg.log_path))
    else:
        logger = logger_mod.Logger(stderr)

    cluster = None
    if cfg.cluster.hosts:
        nodes = []
        internal = cfg.cluster.internal_hosts or [""] * len(
            cfg.cluster.hosts)
        for h, ih in zip(cfg.cluster.hosts, internal):
            nodes.append(Node(h, internal_host=ih))
        cluster = Cluster(nodes=nodes, replica_n=cfg.cluster.replica_n)

    broadcast_receiver = None
    gossip_set = None
    if cfg.cluster.type == "gossip":
        from ..cluster.gossip import GossipNodeSet
        bind_host = cfg.host.rpartition(":")[0] or "localhost"
        gossip_set = GossipNodeSet(
            cfg.host, gossip_host=f"{bind_host}:{cfg.cluster.internal_port}",
            seeds=[cfg.cluster.gossip_seed] if cfg.cluster.gossip_seed
            else [],
            secret_key=cfg.cluster.gossip_secret or None, logger=logger)
        if cluster is None:
            cluster = Cluster(nodes=[Node(cfg.host)])
        cluster.node_set = gossip_set
        broadcast_receiver = gossip_set
    server = Server(os.path.expanduser(cfg.data_dir), host=cfg.host,
                    cluster=cluster, broadcast_receiver=broadcast_receiver,
                    anti_entropy_interval=cfg.anti_entropy_interval,
                    polling_interval=cfg.cluster.polling_interval,
                    logger=logger, query_config=cfg.query,
                    metrics_config=cfg.metrics, trace_config=cfg.trace,
                    profile_config=cfg.profile, slo_config=cfg.slo,
                    fault_config=cfg.fault,
                    gen_staleness_s=cfg.cluster.gen_staleness,
                    blackbox_config=cfg.blackbox,
                    watchdog_config=cfg.watchdog,
                    resize_pace_s=cfg.cluster.resize_pace,
                    resize_grace_s=cfg.cluster.resize_grace,
                    history_config=cfg.history,
                    sentinel_config=cfg.sentinel,
                    tenants_config=cfg.tenants,
                    scrub_config=cfg.scrub,
                    tier_config=cfg.tier,
                    capture_config=cfg.capture,
                    backup_config=cfg.backup)
    if gossip_set is not None:
        server.broadcaster = gossip_set
    server.open()
    if cfg.cluster.type == "http":
        server.broadcaster = HTTPBroadcaster(server)
        server.handler.broadcaster = server.broadcaster

    profiler = None
    if getattr(args, "profile_cpu", ""):
        from ..utils.profiling import CPUProfiler
        profiler = CPUProfiler(args.profile_cpu,
                               duration=args.profile_cpu_time)
        profiler.start()
    print(f"pilosa-tpu serving at http://{server.host} "
          f"(data: {cfg.data_dir})", file=stdout, flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        print("shutting down", file=stderr)
        if profiler is not None:
            profiler.stop()
        server.close()
        logger.close()
    return 0


def _parse_csv_field_values(stream, chunk_lines: int):
    """``column,value`` CSV → (cols u64, vals i64) array chunks for the
    BSI field-import lane (values may be negative, so the bit-import
    fast parsers don't apply)."""
    cols: list[int] = []
    vals: list[int] = []
    for rnum, record in enumerate(csv.reader(stream), 1):
        if not record or record[0] == "":
            continue
        if len(record) != 2:
            raise PilosaError(
                f"bad column count on row {rnum}: col={len(record)}")
        try:
            col = int(record[0])
            if not 0 <= col < 1 << 64:
                raise ValueError
        except ValueError:
            raise PilosaError(
                f"invalid column id on row {rnum}: {record[0]!r}")
        try:
            val = int(record[1])
            if not -(1 << 63) <= val < 1 << 63:
                raise ValueError
        except ValueError:
            raise PilosaError(
                f"invalid value on row {rnum}: {record[1]!r}")
        cols.append(col)
        vals.append(val)
        if len(cols) >= chunk_lines:
            yield (np.array(cols, dtype=np.uint64),
                   np.array(vals, dtype=np.int64))
            cols, vals = [], []
    if cols:
        yield (np.array(cols, dtype=np.uint64),
               np.array(vals, dtype=np.int64))


def cmd_import(args, stdout, stderr) -> int:
    from ..cluster.client import Client
    client = Client(args.host)

    def import_stream(stream):
        # One array chunk per IMPORT_BUFFER_SIZE lines so memory stays
        # flat on multi-GB files (ctl/import.go:166-171).
        if getattr(args, "field", ""):
            # BSI value lane: column,value rows into the named field.
            for cols, vals in _parse_csv_field_values(
                    stream, IMPORT_BUFFER_SIZE):
                print(f"importing {len(cols)} values", file=stderr)
                client.import_field_values(args.index, args.frame,
                                           args.field, cols, vals)
            return
        for rows, cols, ts in _parse_csv_arrays(stream, stderr,
                                                IMPORT_BUFFER_SIZE):
            print(f"importing {len(rows)} bits", file=stderr)
            client.import_arrays(args.index, args.frame, rows, cols, ts)

    for path in args.paths:
        print(f"parsing: {path}", file=stderr)
        if path == "-":
            import_stream(sys.stdin)
        else:
            with open(path, newline="") as f:
                import_stream(f)
    return 0


def cmd_export(args, stdout, stderr) -> int:
    from ..cluster.client import Client
    client = Client(args.host)
    max_slice = client.max_slices().get(args.index, 0)
    for slice in range(max_slice + 1):
        client.export_csv_to(stdout, args.index, args.frame,
                             args.view, slice)
    return 0


def _open_cli_archive(spec: str):
    """The archive store behind --archive for offline CLI modes (gc,
    list, restore, check): an explicit ``dir:<path>`` — the CLI has no
    data dir to root a bare ``dir`` under."""
    from ..backup import archive as backup_archive
    if spec == "dir":
        raise PilosaError(
            "--archive needs an explicit path (dir:/path/to/archive)")
    store = backup_archive.open_archive(spec, "")
    if store is None:
        raise PilosaError("--archive required for this mode")
    return store


def cmd_backup(args, stdout, stderr) -> int:
    """Three faces (docs/DISASTER_RECOVERY.md): the legacy frame-view
    tar dump (-i/-f/-o), the cluster-archive backup driven through the
    coordinator (--mode [--wait]), and offline archive maintenance
    against --archive (--list, --gc [--dry-run] [--keep N]
    [--sweep-orphans])."""
    import json as json_mod
    import urllib.request

    if getattr(args, "list", False) or getattr(args, "gc", False):
        from ..backup import archive as backup_archive
        from ..backup import retention as retention_mod
        store = _open_cli_archive(args.archive)
        if args.gc:
            plan = retention_mod.run_gc(
                store, keep_fulls=args.keep, dry_run=args.dry_run,
                sweep_orphans=args.sweep_orphans)
            print(json_mod.dumps(plan, indent=1), file=stdout)
            return 0
        for m in backup_archive.list_backups(store):
            print(f"{m['id']}  {m.get('kind', '?'):11s}"
                  f"  t={m.get('t', 0.0):.3f}"
                  f"  fragments={len(m.get('fragments', []))}"
                  f"  parent={m.get('parent') or '-'}", file=stdout)
        return 0

    if getattr(args, "mode", ""):
        # Cluster-archive backup: POST /backup on any member; it
        # coordinates against its configured [backup] archive.
        req = urllib.request.Request(
            f"http://{args.host}/backup",
            data=json_mod.dumps({"kind": args.mode}).encode(),
            method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            status = json_mod.loads(r.read())
        print(json_mod.dumps(status, indent=1), file=stdout)
        if not args.wait:
            return 0
        deadline = time.time() + 1800
        while time.time() < deadline:
            time.sleep(0.5)
            with urllib.request.urlopen(
                    f"http://{args.host}/backup", timeout=10) as r:
                op = json_mod.loads(r.read()).get("op") or {}
            if op.get("phase") == "done":
                print(json_mod.dumps(op, indent=1), file=stdout)
                return 0
            if op.get("phase") == "failed":
                print(json_mod.dumps(op, indent=1), file=stdout)
                return 1
        print("backup: timed out waiting", file=stderr)
        return 1

    if not (args.index and args.frame and args.output):
        print("backup: either --mode (cluster archive backup),"
              " --archive with --list/--gc, or -i/-f/-o (frame-view"
              " tar)", file=stderr)
        return 1
    from ..cluster.client import Client
    client = Client(args.host)
    with open(args.output, "wb") as f:
        client.backup_to(f, args.index, args.frame, args.view)
    return 0


def cmd_restore(args, stdout, stderr) -> int:
    """Two faces (docs/DISASTER_RECOVERY.md): the legacy frame-view
    tar restore (-i/-f INPUT), and the archive restore (--archive
    [--id ID] [--to-timestamp T] [--verify RECORDS]) that rebuilds a
    cluster of any size with digest-verified admission and optional
    workload-replay verification."""
    import json as json_mod

    if getattr(args, "archive", ""):
        from ..backup import restore as restore_mod
        from ..backup import verify as verify_mod
        from ..utils import logger as logger_mod
        store = _open_cli_archive(args.archive)
        summary = restore_mod.run_restore(
            args.host, store, backup_id=args.id or None,
            to_timestamp=args.to_timestamp,
            logger=logger_mod.Logger(stderr))
        if args.verify:
            from ..obs import replay as obs_replay
            records = obs_replay.load_records(args.verify)
            summary["verify"] = verify_mod.verify_restore(
                args.host, records,
                logger=logger_mod.Logger(stderr))
        print(json_mod.dumps(summary, indent=1), file=stdout)
        if args.verify and (summary["verify"]["mismatches"]
                            or not summary["verify"]["compared"]):
            return 1
        return 0

    if not (args.index and args.frame and args.input):
        print("restore: either --archive (archive restore) or"
              " -i/-f INPUT (frame-view tar)", file=stderr)
        return 1
    from ..cluster.client import Client
    client = Client(args.host)
    with open(args.input, "rb") as f:
        client.restore_from(f, args.index, args.frame, args.view)
    return 0


def cmd_sort(args, stdout, stderr) -> int:
    # Sort CSV rows by fragment bit position (ctl/sort.go:49-106).
    # Key (slice, row*W + col%W) == lexicographic (slice, row, col%W),
    # which lexsort computes without the u64 overflow of row*W.
    from .. import SLICE_WIDTH
    with open(args.path, newline="") as f:
        chunks = list(_parse_csv_arrays(f, stderr, IMPORT_BUFFER_SIZE))
    if not chunks:
        return 0
    rows = np.concatenate([c[0] for c in chunks])
    cols = np.concatenate([c[1] for c in chunks])
    ts = np.concatenate([c[2] if c[2] is not None
                         else np.zeros(len(c[0]), dtype=np.int64)
                         for c in chunks])
    w = np.uint64(SLICE_WIDTH)
    order = np.lexsort((cols % w, rows, cols // w))
    for i in order:
        if ts[i]:
            t = dt.datetime.fromtimestamp(ts[i] / 1e9, dt.timezone.utc)
            stdout.write(f"{rows[i]},{cols[i]},"
                         f"{t.strftime(TIME_FORMAT)}\n")
        else:
            stdout.write(f"{rows[i]},{cols[i]}\n")
    return 0


def _mmap_bitmap(path: str):
    from ..storage import roaring
    with open(path, "rb") as f:
        mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
    return roaring.Bitmap.unmarshal(mm, mapped=True), mm


def _fragment_files(path: str) -> list[str]:
    """Fragment data files under a data dir (or the path itself when
    it IS a file): numeric names inside a ``fragments`` directory —
    the holder layout <index>/<frame>/views/<view>/fragments/<slice>."""
    if not os.path.isdir(path):
        return [path]
    out = []
    for root, _dirs, files in os.walk(path):
        if os.path.basename(root) != "fragments":
            continue
        for name in sorted(files):
            if name.isdigit():
                out.append(os.path.join(root, name))
    return out


def _blob_stubs(path: str) -> list[str]:
    """``<slice>.blob`` stub files under a data dir — fragments whose
    bytes live in the blob tier (pilosa_tpu.tier)."""
    if not os.path.isdir(path):
        return [path] if path.endswith(".blob") else []
    out = []
    for root, _dirs, files in os.walk(path):
        if os.path.basename(root) != "fragments":
            continue
        for name in sorted(files):
            if name.endswith(".blob") and name[:-5].isdigit():
                out.append(os.path.join(root, name))
    return out


def _blob_store_for(stub_path: str):
    """Resolve the blob store a stub's objects live in: the
    PILOSA_TIER_BLOB / PILOSA_TIER_COLD_DIR env settings when present
    (the same knobs the server reads), else the default layout — a
    ``_tier/blob`` dir under an ancestor of the stub (the data dir).
    Returns None when no store can be located."""
    from ..tier import blob as blob_mod
    spec = os.environ.get("PILOSA_TIER_BLOB", "")
    cold = os.environ.get("PILOSA_TIER_COLD_DIR", "")
    if spec.startswith("dir:"):
        return blob_mod.LocalDirBlobStore(spec[len("dir:"):])
    if cold and os.path.isdir(os.path.join(cold, "blob")):
        return blob_mod.LocalDirBlobStore(os.path.join(cold, "blob"))
    probe = os.path.dirname(os.path.abspath(stub_path))
    for _ in range(8):
        root = os.path.join(probe, "_tier", "blob")
        if os.path.isdir(root):
            return blob_mod.LocalDirBlobStore(root)
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None


def _check_deep(args, stdout) -> int:
    """Offline storage scrub (the CLI face of storage.scrub): verify
    every snapshot footer (per-block crc32 table + whole-body digest)
    and WAL-tail FNV checksums under the given data dirs / files, one
    verdict line per fragment; nonzero exit on ANY corruption.
    ``.corrupt`` aside files (quarantine forensics / pending-repair
    sentinels) are reported too. Blob-tier stubs (``<slice>.blob``)
    are walked as well: each fragment's blob objects verify against
    the manifest crcs + reassembled footer digest — cold-tier files
    are ordinary footered snapshots and take the normal lane."""
    import json as _json

    from ..storage import scrub as scrub_mod
    from ..tier import blob as blob_mod
    rc = 0
    n = corrupt = vintage = 0
    for path in args.paths:
        files = _fragment_files(path)
        stubs = _blob_stubs(path)
        if not files and not stubs:
            print(f"{path}: no fragment files found", file=stdout)
        for f in files:
            n += 1
            v = scrub_mod.scrub_file(f)
            if v.get("corrupt"):
                corrupt += 1
                rc = 1
                print(f"{f}: CORRUPT: {v.get('error')}", file=stdout)
            else:
                cov = v.get("coverage")
                if cov != "full":
                    vintage += 1
                extra = ""
                if v.get("walTornBytes"):
                    extra = (f", torn tail {v['walTornBytes']}B"
                             " (trimmed on next open)")
                print(f"{f}: ok ({cov} coverage,"
                      f" {v.get('blocks', 0)} blocks,"
                      f" {v.get('walRecords', 0)} wal records{extra})",
                      file=stdout)
            if os.path.exists(f + ".corrupt"):
                print(f"{f}.corrupt: quarantine forensics present"
                      f" (fragment pending repair)", file=stdout)
        for s in stubs:
            n += 1
            try:
                with open(s, "r", encoding="utf-8") as fh:
                    stub = _json.load(fh)
                prefix = stub["prefix"]
            except (OSError, ValueError, KeyError) as e:
                corrupt += 1
                rc = 1
                print(f"{s}: CORRUPT: unreadable blob stub: {e}",
                      file=stdout)
                continue
            store = _blob_store_for(s)
            if store is None:
                # Stub without a reachable store (remote spec, moved
                # dir): report presence, don't guess at a verdict.
                print(f"{s}: blob stub ({stub.get('size', '?')}B at"
                      f" {prefix}; no local blob store found —"
                      f" skipped)", file=stdout)
                continue
            v = blob_mod.verify_fragment(store, prefix)
            if v.get("corrupt"):
                corrupt += 1
                rc = 1
                print(f"{s}: CORRUPT (blob {prefix}):"
                      f" {v.get('error')}", file=stdout)
            else:
                print(f"{s}: ok (blob tier, {v.get('blocks', 0)}"
                      f" blocks at {prefix})", file=stdout)
    print(f"checked {n} fragments: {corrupt} corrupt,"
          f" {vintage} without footers", file=stdout)
    return rc


def _check_deep_archive(args, stdout) -> int:
    """``check --deep --archive``: the offline-archive face of the
    deep check (docs/DISASTER_RECOVERY.md). Walks every committed
    backup manifest, re-fetches and re-crcs every referenced pool
    object plus the reassembled body digest and footer, and re-crcs
    every archived WAL segment — same verdict-line format as the
    data-dir walk, nonzero exit on ANY corruption."""
    from ..backup import archive as backup_archive
    store = _open_cli_archive(args.archive)
    rc = 0
    n = corrupt = 0
    backups = backup_archive.list_backups(store)
    if not backups:
        print(f"{args.archive}: no committed backups found",
              file=stdout)
    for manifest in backups:
        for name, v in backup_archive.verify_backup(store, manifest):
            n += 1
            if v.get("corrupt"):
                corrupt += 1
                rc = 1
                print(f"{name}: CORRUPT: {v.get('error')}",
                      file=stdout)
            else:
                print(f"{name}: ok ({v.get('coverage')} coverage,"
                      f" {v.get('blocks', 0)} blocks,"
                      f" {v.get('bytes', 0)} bytes)", file=stdout)
    wal_n = 0
    for key, v in backup_archive.verify_wal(store):
        n += 1
        wal_n += 1
        if v.get("corrupt"):
            corrupt += 1
            rc = 1
            print(f"{key}: CORRUPT: {v.get('error')}", file=stdout)
        else:
            print(f"{key}: ok ({v.get('batches', 0)} batches)",
                  file=stdout)
    print(f"checked {len(backups)} backups + {wal_n} wal segments"
          f" ({n} objects): {corrupt} corrupt", file=stdout)
    return rc


def cmd_check(args, stdout, stderr) -> int:
    # Offline consistency check of fragment files (ctl/check.go:46-113).
    # Bitmap.check() validates every container kind, including the run
    # invariants: buffer length vs numRuns, sorted, non-overlapping,
    # non-adjacent intervals, Σ lengths == cardinality.
    # --deep instead runs the offline storage scrub (footer + WAL
    # checksums) and accepts whole data DIRS; with --archive it walks
    # an offline backup archive instead.
    from ..proto import internal_pb2 as pb
    if getattr(args, "deep", False):
        if getattr(args, "archive", ""):
            return _check_deep_archive(args, stdout)
        return _check_deep(args, stdout)
    if not args.paths:
        print("check: paths required (or --deep --archive)",
              file=stderr)
        return 1
    rc = 0
    for path in args.paths:
        if path.endswith(".cache"):
            try:
                with open(path, "rb") as f:
                    pb.Cache.FromString(f.read())
                print(f"{path}: ok", file=stdout)
            except Exception as e:  # noqa: BLE001 - reported per file
                print(f"{path}: {e}", file=stdout)
                rc = 1
            continue
        if path.endswith(".snapshotting"):
            print(f"{path}: snapshot file found (incomplete snapshot)",
                  file=stdout)
            continue
        try:
            bm, mm = _mmap_bitmap(path)
            bm.check()
            bm.unmap()
            print(f"{path}: ok", file=stdout)
        except Exception as e:  # noqa: BLE001 - reported per file
            print(f"{path}: {e}", file=stdout)
            rc = 1
    return rc


def cmd_inspect(args, stdout, stderr) -> int:
    # Container stats dump (ctl/inspect.go:48-105) + per-kind summary
    # (counts, run intervals, resident bytes) for the three container
    # types.
    bm, mm = _mmap_bitmap(args.path)
    stats = bm.container_stats()
    print("== Bitmap Info ==", file=stdout)
    print(f"Containers: {len(bm.containers)}", file=stdout)
    print(f"Operations: {bm.op_n}", file=stdout)
    # Checksum coverage (storage.integrity): whether this snapshot
    # carries the integrity footer, and how much it covers.
    footer = bm.footer
    if footer is not None:
        print(f"Checksums: footer v{footer.version}"
              f" ({footer.block_n} block crc32s,"
              f" {footer.body_len} body bytes covered)", file=stdout)
    else:
        print("Checksums: none (vintage snapshot — scrub blind;"
              " rewritten with a footer on next snapshot)",
              file=stdout)
    print("", file=stdout)
    print("== Container Types ==", file=stdout)
    print(f"{'TYPE':>6} {'COUNT':>8} {'INTERVALS':>10} {'BYTES':>10}",
          file=stdout)
    for kind in ("array", "bitmap", "run"):
        ivals = stats["intervals"].get(kind, 0)
        print(f"{kind:>6} {stats['counts'][kind]:>8}"
              f" {ivals:>10} {stats['bytes'][kind]:>10}", file=stdout)
    print("", file=stdout)
    print("== Containers ==", file=stdout)
    print(f"{'KEY':>12} {'TYPE':>6} {'N':>8} {'RUNS':>6}", file=stdout)
    for key, c in zip(bm.keys, bm.containers):
        n_runs = ((len(c.runs) - 1) >> 1) if c.runs is not None else 0
        print(f"{int(key):>12} {c.kind():>6} {c.n:>8} {n_runs:>6}",
              file=stdout)
    bm.unmap()
    return 0


def cmd_bench(args, stdout, stderr) -> int:
    # Random SetBit throughput through the full HTTP stack
    # (ctl/bench.go:53-102).
    from ..cluster.client import Client
    if args.op != "set-bit":
        print(f"unknown bench op: {args.op!r}", file=stderr)
        return 1
    client = Client(args.host)
    max_row_id, max_column_id = 1000, 100000
    rng = random.Random(0)
    start = time.perf_counter()
    for _ in range(args.n):
        row = rng.randrange(max_row_id)
        col = rng.randrange(max_column_id)
        client.execute_query(
            None, args.index,
            f'SetBit(rowID={row}, frame="{args.frame}", columnID={col})',
            remote=False)
    elapsed = time.perf_counter() - start
    print(f"Executed {args.n} operations in {elapsed:.3f}s "
          f"({args.n / elapsed:0.3f} op/sec)", file=stdout)
    return 0


def cmd_replay(args, stdout, stderr) -> int:
    """Re-issue a captured workload (docs/OBSERVABILITY.md): records
    come from a file (--records) or a live cluster-merged export
    (--from / --host), replay preserves arrival gaps scaled by
    --rate xN, and --shadow BASELINE CANDIDATE switches to the
    digest-comparing differential mode."""
    import json as json_mod

    from ..obs import replay as obs_replay

    if args.records:
        records = obs_replay.load_records(args.records)
    else:
        source = args.from_host or args.host
        records = obs_replay.fetch_records(source, cluster=True)
    if not records:
        print("no capture records to replay", file=stderr)
        return 1
    rate = args.rate.lstrip("xX") or "1"
    try:
        rate = float(rate)
    except ValueError:
        print(f"invalid --rate: {args.rate!r}", file=stderr)
        return 1
    if args.shadow:
        out = obs_replay.shadow(records, args.shadow[0],
                                args.shadow[1],
                                senders=args.senders)
    else:
        out = obs_replay.replay(records, args.host, rate=rate,
                                processes=args.processes,
                                senders=args.senders)
    body = json_mod.dumps(out, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(body + "\n")
    print(body, file=stdout)
    if args.shadow and out["mismatches"]:
        return 1
    return 0


def cmd_config(args, stdout, stderr) -> int:
    from ..utils.config import Config
    stdout.write(Config().to_toml())
    return 0


def cmd_resize(args, stdout, stderr) -> int:
    """Operator face of the online resize (docs/CLUSTER_RESIZE.md):
    POST /cluster/resize on any member to start/abort, GET to watch."""
    import json as json_mod
    import urllib.request

    def get_status():
        with urllib.request.urlopen(
                f"http://{args.host}/cluster/resize", timeout=10) as r:
            return json_mod.loads(r.read())

    def post(body: dict):
        req = urllib.request.Request(
            f"http://{args.host}/cluster/resize",
            data=json_mod.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json_mod.loads(r.read())

    if args.status:
        print(json_mod.dumps(get_status(), indent=1), file=stdout)
        return 0
    if args.abort:
        print(json_mod.dumps(post({"abort": True}), indent=1),
              file=stdout)
        return 0
    body: dict = {}
    if args.hosts:
        body["hosts"] = [h.strip() for h in args.hosts.split(",")
                         if h.strip()]
    elif args.add:
        body["add"] = args.add
    elif args.remove:
        body["remove"] = args.remove
    else:
        print("resize: one of --add/--remove/--hosts/--abort/--status"
              " required", file=stderr)
        return 1
    status = post(body)
    print(json_mod.dumps(status, indent=1), file=stdout)
    if not args.wait:
        return 0
    rid = (status.get("op") or {}).get("id") or status.get("id")
    # Transient poll failures (a node busy streaming, a coordinator
    # restart mid-recovery) keep waiting; only a sustained outage or
    # the overall deadline gives up. An absent op is NOT terminal —
    # journal recovery re-registers it.
    deadline = time.time() + 1800
    misses = 0
    while time.time() < deadline:
        time.sleep(0.5)
        try:
            s = get_status()
        except Exception as e:  # noqa: BLE001 - transient poll error
            misses += 1
            if misses >= 60:
                print(f"resize {rid}: status unreachable: {e}",
                      file=stderr)
                return 1
            continue
        misses = 0
        op = s.get("op") or {}
        phase = op.get("phase", "")
        print(f"resize {rid}: {phase or '(pending)'} "
              f"(slices={op.get('slicesMoved', 0)},"
              f" bytes={op.get('bytesStreamed', 0)})", file=stdout,
              flush=True)
        if phase in ("done", "aborted"):
            return 0 if phase == "done" else 1
    print(f"resize {rid}: wait timed out", file=stderr)
    return 1


def build_parser() -> argparse.ArgumentParser:
    from .. import __version__
    p = argparse.ArgumentParser(
        prog="pilosa-tpu",
        description=f"TPU-native distributed bitmap index"
                    f" (version {__version__})")
    p.add_argument("--version", action="version",
                   version=f"pilosa-tpu {__version__}")
    sub = p.add_subparsers(dest="command", required=True)

    # Full server flag surface (reference cmd/server.go:88-104).
    from ..utils.config import parse_duration
    s = sub.add_parser("server", help="run a pilosa-tpu node")
    s.add_argument("-d", "--data-dir", default="")
    s.add_argument("-b", "--bind", default="",
                   help="host:port to listen on (default localhost:10101)")
    s.add_argument("-c", "--config", default="", help="TOML config file")
    s.add_argument("--log-path", dest="log_path", default="",
                   help="log file path (default stderr)")
    s.add_argument("--cluster.replicas", dest="cluster_replicas",
                   type=int, default=None, metavar="N",
                   help="number of hosts each piece of data is stored on")
    s.add_argument("--cluster.hosts", dest="cluster_hosts", default="",
                   help="comma-separated list of hosts in cluster")
    s.add_argument("--cluster.internal-hosts",
                   dest="cluster_internal_hosts", default="",
                   help="comma-separated internal-communication hosts")
    s.add_argument("--cluster.type", dest="cluster_type", default="",
                   choices=["", "static", "http", "gossip"],
                   help="cluster membership backend")
    s.add_argument("--cluster.internal-port", dest="cluster_internal_port",
                   default="", help="internal state-sharing (gossip) port")
    s.add_argument("--cluster.gossip-secret", dest="cluster_gossip_secret",
                   default="", help="shared HMAC key authenticating gossip"
                   " frames (unset = unauthenticated)")
    s.add_argument("--cluster.gossip-seed", dest="cluster_gossip_seed",
                   default="", help="host:port to seed gossip membership")
    s.add_argument("--cluster.poll-interval", dest="cluster_poll_interval",
                   type=parse_duration, default=None, metavar="DUR",
                   help="max-slice polling interval (e.g. 60s)")
    # Query lifecycle flags (sched subsystem; docs/SCHEDULING.md).
    s.add_argument("--query.concurrency", dest="query_concurrency",
                   type=int, default=None, metavar="N",
                   help="max queries executing concurrently"
                        " (admission cap, default 16)")
    s.add_argument("--query.queue-depth", dest="query_queue_depth",
                   type=int, default=None, metavar="N",
                   help="max queries waiting for a slot before the"
                        " server answers 429 (default 64)")
    s.add_argument("--query.default-timeout",
                   dest="query_default_timeout", type=parse_duration,
                   default=None, metavar="DUR",
                   help="deadline applied to queries that carry no"
                        " ?timeout= or X-Pilosa-Deadline (0 = none)")
    s.add_argument("--query.slow-threshold",
                   dest="query_slow_threshold", type=parse_duration,
                   default=None, metavar="DUR",
                   help="log queries slower than this with per-stage"
                        " timings (0 = disabled)")
    s.add_argument("--query.result-cache-entries",
                   dest="query_result_cache_entries", type=int,
                   default=None, metavar="N",
                   help="materialized-result residency cache entry"
                        " bound (0 disables, default 8)")
    s.add_argument("--query.result-cache-bits",
                   dest="query_result_cache_bits", type=int,
                   default=None, metavar="N",
                   help="materialized-result residency cache total"
                        " cached-bit bound (default 33554432)")
    s.add_argument("--query.cluster-cache-entries",
                   dest="query_cluster_cache_entries", type=int,
                   default=None, metavar="N",
                   help="coordinator hot-query result cache entry"
                        " bound (0 disables, default 64)")
    s.add_argument("--tenants", dest="tenants", default="",
                   metavar="SPEC",
                   help="per-tenant QoS table, compact form:"
                        " 'default:weight=4,concurrency=8;"
                        "bulk:weight=1,max-wall=2s' — same keys as"
                        " the [tenants] TOML table (a 'default'"
                        " entry is required; docs/SCHEDULING.md)")
    s.add_argument("--cluster.gen-staleness",
                   dest="cluster_gen_staleness", type=parse_duration,
                   default=None, metavar="DUR",
                   help="generation-map staleness bound for"
                        " remote-slice cache keys (default 2s)")
    s.add_argument("--anti-entropy.interval", dest="anti_entropy_interval",
                   type=parse_duration, default=None, metavar="DUR",
                   help="anti-entropy sweep interval (e.g. 10m)")
    # Observability flags (obs subsystem; docs/OBSERVABILITY.md).
    s.add_argument("--metrics.enabled", dest="metrics_enabled",
                   default=None, metavar="BOOL",
                   help="serve Prometheus /metrics + feed the registry"
                        " from every stats call site (default true)")
    s.add_argument("--metrics.runtime-interval",
                   dest="metrics_runtime_interval", type=parse_duration,
                   default=None, metavar="DUR",
                   help="runtime collector sampling interval"
                        " (default 10s)")
    s.add_argument("--trace.enabled", dest="trace_enabled",
                   default=None, metavar="BOOL",
                   help="trace every query (default false; any single"
                        " request can opt in with ?trace=1)")
    s.add_argument("--trace.tail", dest="trace_tail",
                   default=None,
                   help="tail-sampled tracing: every query buffers"
                        " spans; slow/errored/faulted ones persist"
                        " (default true)")
    s.add_argument("--blackbox.enabled", dest="blackbox_enabled",
                   default=None,
                   help="blackbox flight recorder (default true)")
    s.add_argument("--history.enabled", dest="history_enabled",
                   default=None,
                   help="on-disk metric history under the data dir"
                        " (default true)")
    s.add_argument("--sentinel.enabled", dest="sentinel_enabled",
                   default=None,
                   help="regression sentinel over the metric history"
                        " (default true)")
    s.add_argument("--sentinel.manifest", dest="sentinel_manifest",
                   default="", metavar="PATH",
                   help="benchmarks/MANIFEST.json whose committed"
                        " envelope live latencies must stay inside")
    s.add_argument("--watchdog.enabled", dest="watchdog_enabled",
                   default=None,
                   help="stall watchdog (default true)")
    s.add_argument("--trace.max-traces", dest="trace_max_traces",
                   type=int, default=None, metavar="N",
                   help="recent traces kept per node for /debug/traces"
                        " (default 64)")
    s.add_argument("--metrics.accounting", dest="metrics_accounting",
                   default=None, metavar="BOOL",
                   help="per-query cost ledgers (?profile=1,"
                        " X-Pilosa-Stats; default true)")
    s.add_argument("--profile.continuous", dest="profile_continuous",
                   default=None, metavar="BOOL",
                   help="always-on low-Hz wall profiler behind"
                        " /debug/pprof/flame (default true)")
    s.add_argument("--profile.hz", dest="profile_hz", type=float,
                   default=None, metavar="HZ",
                   help="continuous-profiler sampling rate"
                        " (default 10)")
    s.add_argument("--slo.objective", dest="slo_objective",
                   type=parse_duration, default=None, metavar="DUR",
                   help="latency objective for burn-rate gauges"
                        " (default 250ms)")
    s.add_argument("--slo.target", dest="slo_target", type=float,
                   default=None, metavar="FRACTION",
                   help="fraction of queries that must meet the"
                        " objective (default 0.99)")
    # Profiling flags (reference cmd/server.go:47-62,99-100).
    s.add_argument("--profile.cpu", dest="profile_cpu", default="",
                   metavar="PATH",
                   help="write a sampled CPU profile to PATH")
    s.add_argument("--plugins.path", dest="plugins_path", default="",
                   help="path to plugin directory (accepted but inert, "
                        "as in the reference at this vintage)")
    s.add_argument("--profile.cpu-time", dest="profile_cpu_time",
                   type=parse_duration, default=30.0, metavar="DUR",
                   help="duration of the CPU profile (default 30s)")
    s.set_defaults(fn=cmd_server)

    def client_cmd(name, help, fn, **extra):
        c = sub.add_parser(name, help=help)
        c.add_argument("--host", default="localhost:10101")
        c.add_argument("-i", "--index", required=extra.get("index", True))
        c.add_argument("-f", "--frame", required=extra.get("frame", True))
        c.set_defaults(fn=fn)
        return c

    c = client_cmd("import", "bulk-import CSV bits", cmd_import)
    c.add_argument("--field", default="",
                   help="import column,value rows into this BSI"
                        " integer field instead of bits")
    c.add_argument("paths", nargs="+", help="CSV files ('-' for stdin)")

    c = client_cmd("export", "export frame as CSV", cmd_export)
    c.add_argument("--view", default="standard")

    c = client_cmd("backup", "cluster backup into the archive, or a"
                             " frame-view tar dump", cmd_backup,
                   index=False, frame=False)
    c.add_argument("--view", default="standard")
    c.add_argument("-o", "--output", default="",
                   help="frame-view tar mode: output file")
    c.add_argument("--mode", default="", choices=["full", "incremental"],
                   help="take a cluster backup of this kind into the"
                        " server's configured [backup] archive")
    c.add_argument("--wait", action="store_true",
                   help="with --mode: poll until the backup settles")
    c.add_argument("--archive", default="",
                   help="offline archive spec (dir:/path) for"
                        " --list/--gc")
    c.add_argument("--list", action="store_true",
                   help="list committed backups in --archive")
    c.add_argument("--gc", action="store_true",
                   help="run archive retention GC against --archive")
    c.add_argument("--keep", type=int, default=2, metavar="N",
                   help="GC: full backups to keep (default 2, min 1)")
    c.add_argument("--dry-run", action="store_true",
                   help="GC: print the plan, delete nothing")
    c.add_argument("--sweep-orphans", action="store_true",
                   help="GC: also delete pool objects no committed"
                        " manifest references (NOT safe while a"
                        " backup is in flight)")

    c = client_cmd("restore", "restore from the backup archive, or a"
                              " frame-view tar", cmd_restore,
                   index=False, frame=False)
    c.add_argument("--view", default="standard")
    c.add_argument("input", nargs="?", default="",
                   help="frame-view tar mode: input file")
    c.add_argument("--archive", default="",
                   help="archive spec (dir:/path): restore the"
                        " cluster at --host from it")
    c.add_argument("--id", default="",
                   help="restore this backup id (default: newest"
                        " usable)")
    c.add_argument("--to-timestamp", dest="to_timestamp",
                   type=float, default=None, metavar="EPOCH",
                   help="point-in-time cut: replay archived WAL only"
                        " up to this unix timestamp")
    c.add_argument("--verify", default="",
                   help="after restoring, replay this captured-"
                        "workload records file and compare result"
                        " digests (nonzero exit on any mismatch)")

    c = sub.add_parser("sort", help="sort CSV by fragment position")
    c.add_argument("path")
    c.set_defaults(fn=cmd_sort)

    c = sub.add_parser("check", help="consistency-check fragment files")
    c.add_argument("paths", nargs="*")
    c.add_argument("--deep", action="store_true",
                   help="offline storage scrub: verify snapshot"
                        " footers (block crc32s + body digest) and"
                        " WAL-tail checksums; accepts data DIRS;"
                        " nonzero exit on corruption")
    c.add_argument("--archive", default="",
                   help="with --deep: walk an offline backup archive"
                        " (dir:/path) instead — re-crc every object"
                        " of every committed backup + WAL segment")
    c.set_defaults(fn=cmd_check)

    c = sub.add_parser("inspect", help="dump container stats of a file")
    c.add_argument("path")
    c.set_defaults(fn=cmd_inspect)

    c = client_cmd("bench", "run benchmarks against a server", cmd_bench)
    c.add_argument("--op", default="", help="benchmark operation"
                                            " (set-bit)")
    c.add_argument("-n", type=int, default=0, help="operation count")

    c = sub.add_parser(
        "top", help="live fleet dashboard over the federation"
                    " endpoints (docs/OBSERVABILITY.md)")
    c.add_argument("--host", default="localhost:10101",
                   help="any cluster member (it federates the fleet)")
    c.add_argument("--interval", type=parse_duration, default=2.0,
                   metavar="DUR", help="poll interval (default 2s)")
    c.add_argument("--window", default="10m", metavar="DUR",
                   help="history window for the sparkline"
                        " (default 10m)")
    c.add_argument("--once", action="store_true",
                   help="render one frame and exit (scripts, tests)")
    from .top import cmd_top
    c.set_defaults(fn=cmd_top)

    c = sub.add_parser(
        "resize", help="drive / inspect an elastic cluster resize")
    c.add_argument("--host", default="localhost:10101",
                   help="any current cluster member (it coordinates)")
    c.add_argument("--add", default="",
                   help="host:port joining the cluster")
    c.add_argument("--remove", default="",
                   help="host:port leaving the cluster")
    c.add_argument("--hosts", default="",
                   help="explicit target membership (comma-separated;"
                        " overrides --add/--remove)")
    c.add_argument("--abort", action="store_true",
                   help="abort the in-flight resize")
    c.add_argument("--status", action="store_true",
                   help="print resize status and exit")
    c.add_argument("--wait", action="store_true",
                   help="poll until the resize settles")
    c.set_defaults(fn=cmd_resize)

    c = sub.add_parser(
        "replay", help="re-issue a captured workload against a"
                       " cluster (docs/OBSERVABILITY.md)")
    c.add_argument("--host", default="localhost:10101",
                   help="replay target (also the default capture"
                        " export source)")
    c.add_argument("--records", default="",
                   help="records file (JSONL or a saved"
                        " /debug/capture/records response); default:"
                        " export live from --from")
    c.add_argument("--from", dest="from_host", default="",
                   help="export the capture stream from this node"
                        " (cluster-merged) instead of a file;"
                        " defaults to --host")
    c.add_argument("--rate", default="x1", metavar="xN",
                   help="arrival-gap compression (x1 = recorded rate,"
                        " x10 = 10x faster)")
    c.add_argument("--processes", type=int, default=1,
                   help="driver processes (open-loop shards)")
    c.add_argument("--senders", type=int, default=32,
                   help="sender threads per process")
    c.add_argument("--shadow", nargs=2,
                   metavar=("BASELINE", "CANDIDATE"),
                   help="differential replay: writes to both in"
                        " order, reads compared by result digest")
    c.add_argument("--out", default="",
                   help="write the summary JSON here as well")
    c.set_defaults(fn=cmd_replay)

    c = sub.add_parser("config", help="print default configuration")
    c.set_defaults(fn=cmd_config)
    return p


def main(argv: Optional[list[str]] = None, stdout=None, stderr=None) -> int:
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args, stdout, stderr)
    except PilosaError as e:
        print(f"error: {e}", file=stderr)
        return 1
