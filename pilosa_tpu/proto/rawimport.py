"""Raw-array import wire format (TPU-native sidecar).

The reference's /import endpoint speaks protobuf (handler.go:896-906),
and so does ours by default — but protobuf varint-decodes every u64
individually, which is the measured bound on bulk-import wire
throughput. Between OUR client and server the id vectors travel as
little-endian u64 arrays instead: encode is a buffer copy, decode is
np.frombuffer views into the request body. Content negotiation keeps
reference parity: the client tries this format once per host and falls
back to protobuf on 415 (so a reference-shaped server still works),
and reference clients never see it because protobuf stays accepted.

Layout (all little-endian):
    magic   4s   b"PRAW"
    version u8   1 | 2
    flags   u8   bit 0: timestamps present (v1)
                 bit 1: positions form (v2)
    idx_len u16, idx utf-8 bytes
    frm_len u16, frame utf-8 bytes
    slice   u64
    n       u64
    pad     0-7 zero bytes so the arrays start 8-byte-aligned (an
            unaligned u64 view forces numpy's per-element slow path —
            measured 10x on the apply)
    v1: rows n x u64, cols n x u64, [ts n x i64 iff flags & 1]
    v2: positions n x u64

Version 2 — the **presorted positions form** (ISSUE 8, the pipelined
import path) — carries slice-local bit positions
(``row*SLICE_WIDTH + col%SLICE_WIDTH``) already sorted and deduped by
the CLIENT: half the wire bytes of v1 (8 vs 16 per bit), and the
server skips its packed-sort entirely (add_many's is-sorted check
passes), so the client-side sort of slice N+1 — np.sort releases the
GIL — genuinely overlaps the server-side apply of slice N. No
timestamp variant: timestamped imports need the per-quantum view
fan-out, which wants (row, col) pairs — they stay on v1. A server
that predates v2 answers 400 "unsupported raw-import version" and the
client drops to v1 for that host (same per-host negotiation idiom as
the 415 protobuf fallback).
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

CONTENT_TYPE = "application/x-pilosa-raw-import"
_MAGIC = b"PRAW"
_HDR = struct.Struct("<4sBB")


def encode(index: str, frame: str, slice: int, rows: np.ndarray,
           cols: np.ndarray, ts_ns: Optional[np.ndarray]) -> bytes:
    idx_b = index.encode()
    frm_b = frame.encode()
    flags = 1 if ts_ns is not None else 0
    hdr_len = _HDR.size + 2 + len(idx_b) + 2 + len(frm_b) + 16
    parts = [
        _HDR.pack(_MAGIC, 1, flags),
        struct.pack("<H", len(idx_b)), idx_b,
        struct.pack("<H", len(frm_b)), frm_b,
        struct.pack("<QQ", slice, len(rows)),
        b"\0" * (-hdr_len % 8),
        np.ascontiguousarray(rows, dtype="<u8").tobytes(),
        np.ascontiguousarray(cols, dtype="<u8").tobytes(),
    ]
    if ts_ns is not None:
        parts.append(np.ascontiguousarray(ts_ns, dtype="<i8").tobytes())
    return b"".join(parts)


def encode_positions(index: str, frame: str, slice: int,
                     positions: np.ndarray) -> bytes:
    """Version-2 body: ``positions`` MUST be sorted-unique slice-local
    u64 positions (the server rejects anything else with 400)."""
    idx_b = index.encode()
    frm_b = frame.encode()
    hdr_len = _HDR.size + 2 + len(idx_b) + 2 + len(frm_b) + 16
    return b"".join([
        _HDR.pack(_MAGIC, 2, 2),
        struct.pack("<H", len(idx_b)), idx_b,
        struct.pack("<H", len(frm_b)), frm_b,
        struct.pack("<QQ", slice, len(positions)),
        b"\0" * (-hdr_len % 8),
        np.ascontiguousarray(positions, dtype="<u8").tobytes(),
    ])


def version_of(body: bytes) -> int:
    """Wire version byte (0 when the body is not raw-import at all)."""
    return body[4] if len(body) >= _HDR.size and body[:4] == _MAGIC \
        else 0


def decode(body: bytes):
    """→ (index, frame, slice, rows u64, cols u64, ts_ns i64|None,
    positions u64|None) — exactly one of (rows, cols) / positions is
    populated, by wire version. Arrays are zero-copy views of
    ``body``. Raises ValueError on any structural mismatch (the
    handler maps it to 400)."""
    if len(body) < _HDR.size or body[:4] != _MAGIC:
        raise ValueError("bad raw-import magic")
    _, version, flags = _HDR.unpack_from(body)
    if version not in (1, 2):
        raise ValueError(f"unsupported raw-import version {version}")
    try:
        off = _HDR.size
        (idx_len,) = struct.unpack_from("<H", body, off)
        off += 2
        index = body[off:off + idx_len].decode()
        off += idx_len
        (frm_len,) = struct.unpack_from("<H", body, off)
        off += 2
        frame = body[off:off + frm_len].decode()
        off += frm_len
        slice, n = struct.unpack_from("<QQ", body, off)
        off += 16
    except (struct.error, UnicodeDecodeError) as e:
        # Truncated-header struct.error is not a ValueError subclass;
        # the contract (and the handler's 400 mapping) is ValueError.
        raise ValueError(f"truncated raw-import header: {e}")
    off += -off % 8  # alignment padding (see layout)
    if version == 2:
        if not flags & 2:
            raise ValueError("raw-import v2 without positions flag")
        if len(body) - off != n * 8:
            raise ValueError("raw-import length mismatch")
        positions = np.frombuffer(body, dtype="<u8", count=n,
                                  offset=off)
        return index, frame, slice, None, None, None, positions
    want = n * 16 + (n * 8 if flags & 1 else 0)
    if len(body) - off != want:
        raise ValueError("raw-import length mismatch")
    rows = np.frombuffer(body, dtype="<u8", count=n, offset=off)
    off += n * 8
    cols = np.frombuffer(body, dtype="<u8", count=n, offset=off)
    off += n * 8
    ts_ns = None
    if flags & 1:
        ts_ns = np.frombuffer(body, dtype="<i8", count=n, offset=off)
    return index, frame, slice, rows, cols, ts_ns, None
