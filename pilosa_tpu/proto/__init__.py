"""Wire types (protobuf). `from pilosa_tpu.proto import internal_pb2`.

The generated module is regenerated from internal.proto on demand if protoc
is available and the source is newer; the checked-in generated file is the
fallback so runtime protoc is not required.
"""

import os
import subprocess

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "internal.proto")
_GEN = os.path.join(_DIR, "internal_pb2.py")


def _regen_if_stale():
    try:
        if (not os.path.exists(_GEN)
                or os.path.getmtime(_GEN) < os.path.getmtime(_SRC)):
            subprocess.run(
                ["protoc", f"--python_out={_DIR}", f"-I{_DIR}", _SRC],
                check=True, capture_output=True)
    except Exception:
        pass  # fall back to whatever generated module exists


_regen_if_stale()

from . import internal_pb2  # noqa: E402
