"""Benchmark of record: Intersect+Count throughput on 1 Gbit rows.

Metric (BASELINE.md): Intersect+Count row-ops/sec on 2^30-bit packed rows.
The device op is the fused count kernel ``sum(popcount(a & b), axis=-1)``
(pilosa_tpu.ops.kernels.op_count, which A/Bs the Pallas kernel against
XLA fusion on TPU) — the TPU replacement for the reference's amd64 POPCNT
assembly loop (roaring/assembly_amd64.s:60-77, `popcntAndSliceAsm`). The
baseline denominator is measured on this machine: the same algorithm
through our C++ host kernel (pilosa_tpu/native/bitops.cpp, `popcnt_and`),
which is the faithful stand-in for the reference's native path (no Go
toolchain in this image — BASELINE.md records that denominators must be
measured, not quoted).

Fail-soft contract: this script ALWAYS prints exactly one JSON line and
exits 0. The device measurement runs in a subprocess with a bounded
timeout and retries (TPU backend init through the tunnel can fail or
hang transiently — round 1 lost its number to an uncaught init error);
if every attempt fails, the line still carries the host-C++ number with
an "error" field instead of crashing.

Methodology: the TPU is reached through a tunnel whose host↔device sync
costs ~65 ms per round trip regardless of payload — so per-call timing
measures the tunnel, not the chip. We instead batch K row pairs per call,
chain N asynchronous dispatches, and sync ONCE on the last output; the
measured window then amortizes one sync over K*N row-ops of real HBM
traffic. Counts are verified against the host kernel before timing.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs: PILOSA_BENCH_BITS (row width, default 2^30, must be < 2^31 —
per-row counts are int32), PILOSA_BENCH_ROWS (K, default 16 — 4 GB of
operands in HBM), PILOSA_BENCH_ITERS (chained dispatches, default 256;
measured asymptote — 512 gains <2%), PILOSA_BENCH_TRIALS (default 3,
median reported), PILOSA_BENCH_DEVICE_TIMEOUT (seconds per device
attempt, default 300 — covers the operand upload through the tunnel),
PILOSA_BENCH_DEVICE_TRIES (default 2).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_MARK = "DEVICE_RESULT:"


def _params():
    bits = int(os.environ.get("PILOSA_BENCH_BITS", str(1 << 30)))
    if bits >= 1 << 31:
        raise SystemExit("PILOSA_BENCH_BITS must be < 2^31 "
                         "(per-row device counts are int32)")
    if bits % 64:
        raise SystemExit("PILOSA_BENCH_BITS must be a multiple of 64")
    return (bits,
            int(os.environ.get("PILOSA_BENCH_ROWS", "16")),
            int(os.environ.get("PILOSA_BENCH_ITERS", "256")),
            int(os.environ.get("PILOSA_BENCH_TRIALS", "3")))


def _rows(bits, k_rows):
    rng = np.random.default_rng(42)
    n_words = bits // 32
    a = rng.integers(0, 2**32, size=(k_rows, n_words), dtype=np.uint32)
    b = rng.integers(0, 2**32, size=(k_rows, n_words), dtype=np.uint32)
    return a, b


def device_worker() -> None:
    """Measure the device kernel; prints one DEVICE_RESULT line.

    Runs in its own process so a hung/broken TPU backend init cannot take
    down the benchmark of record — the parent enforces the timeout.
    """
    t_begin = time.perf_counter()  # budget anchor: the parent's kill
    # deadline started when this process did

    import jax

    from pilosa_tpu.ops.kernels import op_count
    from pilosa_tpu.storage import native

    bits, k_rows, iters, trials = _params()
    a, b = _rows(bits, k_rows)

    da, db = jax.device_put(a), jax.device_put(b)
    got = np.asarray(op_count("and", da, db))  # warmup + verify
    want = [native.popcnt_and(a[i].view(np.uint64), b[i].view(np.uint64))
            for i in range(k_rows)]
    assert got.tolist() == want, (got.tolist(), want)
    del a, b, got, want  # parent holds nothing; don't double RSS here

    # Self-budget against the parent's kill deadline: probe one synced
    # dispatch (an upper bound per chained iter — it includes the sync)
    # and scale the chain down on platforms too slow for the full
    # default workload, so a DEVICE_RESULT always lands in time.
    t0 = time.perf_counter()
    np.asarray(op_count("and", da, db))
    probe_s = time.perf_counter() - t0
    # Budget = what's left of the parent's deadline (minus headroom for
    # the final sync + result print), not a fixed slice — setup (4 GB
    # generation, upload, warmup/verify) already consumed part of it.
    deadline = float(os.environ.get("PILOSA_BENCH_DEVICE_TIMEOUT", "300"))
    budget = max(5.0, 0.8 * deadline - (time.perf_counter() - t_begin))
    iters = max(1, min(iters, int(budget / max(probe_s, 1e-9) / trials)))

    best = []
    t_start = time.perf_counter()
    for _ in range(trials):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = op_count("and", da, db)
        np.asarray(out)  # single sync: flushes the whole chained queue
        best.append((time.perf_counter() - t0) / (k_rows * iters))
        if time.perf_counter() - t_start > budget:
            break  # report what we have instead of being killed
    device_s = sorted(best)[len(best) // 2]
    platform = jax.devices()[0].platform
    print(_MARK + json.dumps({"device_s": device_s, "platform": platform}),
          flush=True)


def main() -> None:
    from pilosa_tpu.storage import native

    bits, k_rows, _, _ = _params()
    a, b = _rows(bits, k_rows)

    # --- host-native baseline (C++ popcount kernel, same rows).
    # Rows are viewed as u64 (bit-identical reinterpret, the kernel's
    # native word) so the timed region is the kernel alone. Median of
    # per-row times over two passes, mirroring the device side's
    # median-of-trials.
    a64, b64 = a.view(np.uint64), b.view(np.uint64)
    native.popcnt_and(a64[0], b64[0])  # warmup: page in + lib load
    host_times = []
    for _ in range(2):
        for i in range(k_rows):
            t0 = time.perf_counter()
            native.popcnt_and(a64[i], b64[i])
            host_times.append(time.perf_counter() - t0)
    host_s = sorted(host_times)[len(host_times) // 2]
    # Pin the denominator: this shared 1-core VM is noisy, and a freshly
    # measured host leg swung vs_baseline 2× between otherwise identical
    # runs. Persist the best (fastest) host measurement across rounds
    # and divide by that; both raw legs are reported alongside.
    host_pinned_s = _pin_host_baseline(bits, k_rows, host_s)
    # The device subprocess regenerates its own operands — drop ours
    # (4 GB at default ROWS) so peak host RSS doesn't double.
    del a, b, a64, b64

    # --- device path, in a bounded subprocess (see module docstring).
    timeout = int(os.environ.get("PILOSA_BENCH_DEVICE_TIMEOUT", "300"))
    tries = int(os.environ.get("PILOSA_BENCH_DEVICE_TRIES", "2"))
    device_s, platform, err = None, None, None
    for attempt in range(tries):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--device-worker"],
                timeout=timeout, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            err = f"device attempt {attempt + 1} timed out after {timeout}s"
            print(err, file=sys.stderr)
            continue
        for line in proc.stdout.splitlines():
            if line.startswith(_MARK):
                res = json.loads(line[len(_MARK):])
                device_s, platform = res["device_s"], res["platform"]
                break
        if device_s is not None:
            break
        err = (f"device attempt {attempt + 1} rc={proc.returncode}: "
               + proc.stderr.strip()[-800:])
        print(err, file=sys.stderr)
        if attempt + 1 < tries:
            time.sleep(5)

    metric = f"intersect_count_{bits // (1 << 20)}Mbit_rows"
    if device_s is not None:
        line = {
            "metric": metric,
            "bits": bits,
            "value": round(1.0 / device_s, 3),
            "unit": "ops/sec",
            # vs_baseline uses the PINNED (best-ever, i.e. fastest) host
            # denominator — conservative on this noisy VM, where a slow
            # host run would otherwise inflate the same-run ratio. Both
            # ratios are published explicitly so the semantics are
            # unambiguous to downstream consumers.
            "vs_baseline": round(host_pinned_s / device_s, 3),
            "vs_baseline_pinned": round(host_pinned_s / device_s, 3),
            "vs_baseline_same_run": round(host_s / device_s, 3),
            "platform": platform,
            "device_ops": round(1.0 / device_s, 3),
            "host_ops_this_run": round(1.0 / host_s, 3),
            "host_ops_pinned": round(1.0 / host_pinned_s, 3),
        }
        # Second clause of the metric of record: TopN(1000) p50 at
        # BASELINE config-3 scale, measured by benchmarks/suite.py
        # (config3_topn1000_end_to_end) and recorded for the artifact.
        try:
            with open(os.path.join(os.path.dirname(_BASELINE_PATH),
                                   "TOPN1000.json")) as f:
                line["topn1000_p50_ms"] = json.load(f)["device_p50_ms"]
        except (OSError, ValueError, KeyError):
            pass
        # Kernel-level Pallas-vs-XLA A/B record (benchmarks/pallas_ab.py)
        # and the write-path legs (suite._write_denominator) — the two
        # round-4 perf-proof artifacts, carried in the line of record.
        try:
            with open(os.path.join(os.path.dirname(_BASELINE_PATH),
                                   "PALLAS_AB.json")) as f:
                ab = json.load(f)
                line["pallas_ab"] = {
                    "pallas_wins": ab["pallas_wins"],
                    "total": ab["total"],
                    "serving_default": "xla"}
        except (OSError, ValueError, KeyError):
            pass
        try:
            with open(os.path.join(os.path.dirname(_BASELINE_PATH),
                                   "WRITEPATH.json")) as f:
                line["write_path"] = json.load(f)
        except (OSError, ValueError, KeyError):
            pass
        # Compile-cache counters from the last suite pass
        # (benchmarks/MANIFEST.json, obs subsystem): hit/miss +
        # compile seconds, so the cold-compile tax (VERDICT r5 weak
        # #2) rides the line of record as a tracked number.
        try:
            with open(os.path.join(os.path.dirname(_BASELINE_PATH),
                                   "MANIFEST.json")) as f:
                manifest = json.load(f)
            cc = manifest.get("compile_cache") or {}
            if "misses" in cc:
                line["compile_cache"] = {
                    "hits": cc["hits"], "misses": cc["misses"],
                    "compile_seconds": cc.get("compileSeconds")}
            # Restart-latency acceptance table (suite.
            # config_compile_stability): first-vs-warm device query
            # per slice config in FRESH processes sharing the
            # persistent XLA cache, plus the (bucket-bound) compile
            # count — the 5.4 s cold-query complaint as a tracked
            # number on the line of record.
            cs = manifest.get("compile_stability") or {}
            if cs:
                line["compile_stability"] = {
                    name: {"first_ms": rec.get("first_ms"),
                           "warm_p50_ms": rec.get("warm_p50_ms"),
                           "compile_count": rec.get("compile_count"),
                           "bucket": rec.get("bucket")}
                    for name, rec in cs.items()}
            # Per-config cost ledgers (obs.accounting via
            # suite.config_query_cost): container-op mix, device
            # bytes, compile ms — the attribution numbers ride the
            # line of record next to the throughput they explain.
            qc = manifest.get("query_cost") or {}
            if qc:
                line["query_cost"] = {
                    name: {"containerOps": sum(
                               (c.get("containerOps") or {}).values()),
                           "deviceBytes": c.get("deviceBytes", 0),
                           "compileMs": c.get("compileMs", 0.0)}
                    for name, c in qc.items()}
            # Run-container mix on the run-heavy workload
            # (suite.config_container_mix): run-op share, resident
            # bytes vs the two-kind baseline, p50 ratio — ROADMAP
            # item 4's acceptance numbers on the line of record.
            cm = manifest.get("container_mix") or {}
            if cm.get("runs"):
                line["container_mix"] = {
                    "run_op_share": cm["runs"].get("run_op_share"),
                    "resident_bytes_ratio": cm.get(
                        "resident_bytes_ratio"),
                    "p50_ratio": cm.get("p50_ratio"),
                    "runs_p50_ms": cm["runs"].get("p50_ms"),
                    "containers": cm["runs"].get("containers")}
            # Distributed fast paths (suite.config_distributed_topn →
            # DISTRIBUTED.json): 2-node TopN pushdown vs fan-out vs
            # single-node, and the generation-validated resident
            # chain — ROADMAP item 3's acceptance numbers on the line
            # of record.
            # Always-on observability overhead (suite.
            # config_obs_overhead): tail sampling + blackbox cadence
            # vs all-off, interleaved A/B — ISSUE 11's ≤2% acceptance
            # bound on the bench-leg p50, on the line of record.
            oo = manifest.get("obs_overhead") or {}
            if oo.get("ratio") is not None:
                line["obs_overhead"] = {
                    "ratio": oo["ratio"],
                    "on_p50_ms": oo.get("on_p50_ms"),
                    "off_p50_ms": oo.get("off_p50_ms"),
                    "target_ratio": oo.get("target_ratio")}
            # Metric-history + sentinel overhead (suite.
            # config_obs_history): whole-registry sampling + rule
            # evaluation vs all-off, interleaved A/B — ISSUE 13's
            # ≤2% acceptance bound, on the line of record.
            oh = manifest.get("obs_history") or {}
            if oh.get("ratio") is not None:
                line["obs_history"] = {
                    "ratio": oh["ratio"],
                    "on_p50_ms": oh.get("on_p50_ms"),
                    "off_p50_ms": oh.get("off_p50_ms"),
                    "target_ratio": oh.get("target_ratio")}
            # Background storage-scrub overhead (suite.
            # config_scrub_overhead): continuous re-verification
            # passes vs off, interleaved A/B — ISSUE 15's ≤2%
            # acceptance bound, on the line of record.
            so = manifest.get("scrub_overhead") or {}
            if so.get("ratio") is not None:
                line["scrub_overhead"] = {
                    "ratio": so["ratio"],
                    "on_p50_ms": so.get("on_p50_ms"),
                    "off_p50_ms": so.get("off_p50_ms"),
                    "target_ratio": so.get("target_ratio")}
            dt = manifest.get("distributed_topn") or {}
            if dt.get("topn_pushdown_p50_ms") is not None:
                line["distributed_topn"] = {
                    "pushdown_p50_ms": dt["topn_pushdown_p50_ms"],
                    "vs_single": dt.get("topn_vs_single"),
                    "vs_fanout": dt.get("topn_vs_fanout"),
                    "chain_hit_p50_ms": dt.get("chain_hit_p50_ms"),
                    "chain_miss_ms": dt.get("chain_miss_ms"),
                    "generations_rtt_ms": dt.get(
                        "generations_rtt_ms")}
            # Elastic resize under load (suite.config_resize →
            # RESIZE.json): resize duration + query p99 inflation
            # during the migration — ROADMAP item 5's acceptance
            # numbers on the line of record.
            rz = manifest.get("resize") or {}
            if rz.get("resize_duration_s") is not None:
                line["resize"] = {
                    "duration_s": rz["resize_duration_s"],
                    "p99_inflation": rz.get("p99_inflation"),
                    "during_p99_ms": rz.get("during_p99_ms"),
                    "baseline_p99_ms": rz.get("baseline_p99_ms"),
                    "bytes_streamed": rz.get("bytes_streamed"),
                    "slices_moved": rz.get("slices_moved"),
                    "zero_wrong_answers": rz.get(
                        "zero_wrong_answers")}
            # Recorded-traffic replay (suite.config_replay →
            # REPLAY.json): offered-vs-achieved open-loop QPS of the
            # scaled captured workload, the self-shadow digest
            # verdict, and the capture-plane overhead guard — ISSUE
            # 19's acceptance numbers on the line of record.
            rp = manifest.get("replay") or {}
            if rp.get("offered_qps") is not None:
                shadow = rp.get("shadow") or {}
                line["replay"] = {
                    "offered_qps": rp["offered_qps"],
                    "achieved_qps": rp.get("achieved_qps"),
                    "shed": rp.get("shed"),
                    "shadow_self_mismatches": (shadow.get("self")
                                               or {}).get("mismatches"),
                    "seeded_fault_detected": (
                        shadow.get("seeded_fault") or {}).get(
                            "detected")}
            co = manifest.get("capture_overhead") or {}
            if co.get("ratio") is not None:
                line["capture_overhead"] = {
                    "ratio": co["ratio"],
                    "on_p50_ms": co.get("on_p50_ms"),
                    "off_p50_ms": co.get("off_p50_ms"),
                    "target_ratio": co.get("target_ratio")}
            # Disaster recovery (suite.config_backup): the
            # backup-while-serving p50 overhead (continuous
            # coordinator passes vs off, interleaved; ISSUE 20's
            # ≤5% bound) and the digest-verified restore wall time
            # into a fresh node, on the line of record.
            bk = manifest.get("backup") or {}
            if bk.get("ratio") is not None:
                line["backup"] = {
                    "ratio": bk["ratio"],
                    "on_p50_ms": bk.get("on_p50_ms"),
                    "off_p50_ms": bk.get("off_p50_ms"),
                    "restore_wall_s": bk.get("restore_wall_s"),
                    "restore_fragments": bk.get("restore_fragments"),
                    "target_ratio": bk.get("target_ratio")}
        except (OSError, ValueError, KeyError):
            pass
        # Serving-quality artifact (sched subsystem): open-loop
        # latency under load vs the admission cap
        # (benchmarks/latency_under_load.py → LATENCY.json).
        try:
            with open(os.path.join(os.path.dirname(_BASELINE_PATH),
                                   "LATENCY.json")) as f:
                lat = json.load(f)
                line["latency_under_load"] = {
                    "below_cap_p99_ms": lat["below_cap"]["p99_ms"],
                    "above_cap_p99_ms": lat["above_cap"]["p99_ms"],
                    "above_cap_rejected": lat["above_cap"]["rejected"]}
        except (OSError, ValueError, KeyError):
            pass
        # Roofline accounting (VERDICT r4 item 4): effective HBM GB/s of
        # THIS run's number (arithmetic, a measurement) + the untunneled
        # v5e-8 projections for configs 4-5 (labeled projections, from
        # recorded kernel times — benchmarks/roofline.py). Only at the
        # canonical 2^30-bit shape: roofline.compute's bytes/op assumes
        # it, and smaller smoke shapes under-amortize the dispatch so
        # their GB/s is not the metric of record (a reduced smoke once
        # overwrote ROOFLINE.json with a wrong-arithmetic number).
        if bits == (1 << 30):
            try:
                from benchmarks import roofline
                roof = roofline.compute(metric_ops_s=line["value"])
                line["effective_hbm_gbps"] = \
                    roof["metric_of_record"]["effective_hbm_gbps"]
                line["hbm_fraction_of_v5e_peak"] = \
                    roof["metric_of_record"]["fraction_of_v5e_peak"]
                roof_path = os.path.join(
                    os.path.dirname(_BASELINE_PATH), "ROOFLINE.json")
                # Headline = the RECENT-RUN MEDIAN, not a historical
                # pin: the old best-run pin only expired after three
                # consecutive runs below 80% of it, so a sustained
                # ≤20% regression reported the stale peak forever
                # (ADVICE r5 #1). The median of the last 5 runs tracks
                # the current level while still shrugging off one
                # congested-slot outlier; the all-time max survives as
                # the separate best_observed field, and this run's raw
                # number always lands in latest_run_ops_per_s.
                try:
                    with open(roof_path) as f:
                        prior = json.load(f)
                except (OSError, ValueError):
                    prior = {}
                prior_best = max(
                    prior.get("metric_of_record", {})
                    .get("ops_per_s", 0),
                    prior.get("best_observed", {}).get("ops_per_s", 0))
                # Only a TPU run may fold into the headline history:
                # the metric of record IS the device number, and one
                # CPU-container pass (ops/s ~590x lower) would poison
                # the recent-run median for the next five real runs
                # (review finding). Non-TPU runs still stamp
                # latest_run_* so the pass is visible.
                fold = line.get("platform") == "tpu"
                recent = list(prior.get("recent_runs") or [])
                if fold:
                    recent = recent[-4:] + [line["value"]]
                # True median (even windows average the middle pair):
                # the upper median would bias the headline high right
                # after a regression, which is what this change exists
                # to stop.
                import statistics
                headline = (float(statistics.median(recent))
                            if recent else line["value"])
                if headline != line["value"]:
                    roof = roofline.compute(metric_ops_s=headline)
                roof["metric_of_record"]["kind"] = \
                    "measurement (median of recent runs)"
                roof["metric_of_record"]["latest_run_ops_per_s"] = \
                    line["value"]
                roof["metric_of_record"]["latest_run_platform"] = \
                    line.get("platform")
                roof["best_observed"] = {
                    "ops_per_s": round(max(prior_best, line["value"])
                                       if fold else prior_best
                                       or line["value"], 3),
                    "note": "historical max across rounds; not the"
                            " headline metric"}
                roof["recent_runs"] = recent
                # roofline.compute() builds the projections fresh with
                # the ASSUMED constants; roofline.py's own main()
                # stamps the measured values next to them — carry the
                # prior file's measured annotations forward instead of
                # erasing them on every bench pass (review finding:
                # this writer reverted the PR-4 'projections carry
                # measured constants' guarantee).
                if prior.get("measured_constants"):
                    roof["measured_constants"] = \
                        prior["measured_constants"]
                for cfg, block in prior.items():
                    if not (isinstance(block, dict)
                            and cfg in roof
                            and isinstance(block.get("assumptions"),
                                           dict)):
                        continue
                    target = roof[cfg].setdefault("assumptions", {})
                    for k, v in block["assumptions"].items():
                        if k.endswith("_measured") \
                                or k == "measured_platform":
                            target[k] = v
                with open(roof_path, "w") as f:
                    json.dump(roof, f, indent=1)
            except Exception:  # noqa: BLE001 - must not kill the line
                pass
        print(json.dumps(line))
    else:
        # Fail-soft: record the host-C++ denominator so the round still
        # has a number, flagged with the device error.
        print(json.dumps({
            "metric": metric,
            "value": round(1.0 / host_s, 3),
            "unit": "ops/sec",
            "vs_baseline": 1.0,
            "platform": "host-cpp-fallback",
            "error": err or "device measurement unavailable",
        }))


_BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "benchmarks", "HOST_BASELINE.json")


def _pin_host_baseline(bits: int, k_rows: int, host_s: float) -> float:
    """Best-of-all-rounds host seconds for this workload shape ON THIS
    MACHINE (the key carries the hostname — a faster rig's measurement
    must not poison vs_baseline for every other rig); updates the
    persisted record when this run's measurement is faster. One shared
    writer for HOST_BASELINE.json lives in benchmarks.pinning."""
    import platform

    from benchmarks.pinning import pin
    return pin(f"bits={bits},rows={k_rows},host={platform.node()}",
               "best_host_s", host_s, lambda new, old: new < old)


if __name__ == "__main__":
    if "--device-worker" in sys.argv[1:]:
        device_worker()
    elif "--latency-under-load" in sys.argv[1:]:
        # Open-loop latency-under-load benchmark (sched subsystem):
        # fixed arrival rates below/above the admission cap, p50/p99 +
        # rejected count into benchmarks/LATENCY.json + MANIFEST.json.
        from benchmarks import latency_under_load
        latency_under_load.main()
    else:
        main()
