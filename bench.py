"""Benchmark of record: single-fragment Intersect+Count on 1 B-bit rows.

Metric (BASELINE.md): Intersect+Count ops/sec on two 2^30-bit packed rows.
The device op is the fused XLA kernel ``sum(popcount(a & b))``
(pilosa_tpu.ops.kernels.op_count_total) — the TPU replacement for the
reference's amd64 POPCNT assembly loop (roaring/assembly_amd64.s:60-77,
`popcntAndSliceAsm`). The baseline denominator is measured on this
machine: the same algorithm through our C++ host kernel
(pilosa_tpu/native/bitops.cpp, `popcnt_and`), which is the faithful
stand-in for the reference's native path (no Go toolchain in this image —
BASELINE.md records that denominators must be measured, not quoted).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs: PILOSA_BENCH_BITS (default 2^30), PILOSA_BENCH_ITERS (20).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import jax

    from pilosa_tpu.ops.kernels import op_count_total
    from pilosa_tpu.storage import native

    bits = int(os.environ.get("PILOSA_BENCH_BITS", str(1 << 30)))
    iters = int(os.environ.get("PILOSA_BENCH_ITERS", "20"))
    n_words = bits // 32

    rng = np.random.default_rng(42)
    a = rng.integers(0, 2**32, size=n_words, dtype=np.uint32)
    b = rng.integers(0, 2**32, size=n_words, dtype=np.uint32)

    # --- device path (TPU if available, else whatever jax defaults to)
    from pilosa_tpu.ops.kernels import _op_count_total_parts
    da, db = jax.device_put(a), jax.device_put(b)
    want = op_count_total("and", da, db)  # warmup: compile + one run
    # Dispatch asynchronously and sync once: measures sustained kernel
    # throughput rather than per-call host↔device round-trip latency.
    t0 = time.perf_counter()
    outs = [_op_count_total_parts("and", da, db) for _ in range(iters)]
    jax.block_until_ready(outs)
    device_s = (time.perf_counter() - t0) / iters
    hi, lo = outs[-1]
    got = (int(hi) << 16) + int(lo)
    assert got == want

    # --- host-native baseline (C++ popcount kernel, same data)
    base_iters = max(1, min(iters, 5))
    native_ok = native.available()
    if native_ok:
        ref = native.popcnt_and(a, b)
        assert ref == want, (ref, want)
        t0 = time.perf_counter()
        for _ in range(base_iters):
            native.popcnt_and(a, b)
        host_s = (time.perf_counter() - t0) / base_iters
    else:  # pure-numpy fallback baseline
        t0 = time.perf_counter()
        for _ in range(base_iters):
            int(np.unpackbits(np.bitwise_and(a, b).view(np.uint8)).sum())
        host_s = (time.perf_counter() - t0) / base_iters

    ops_per_sec = 1.0 / device_s
    print(json.dumps({
        "metric": f"intersect_count_{bits // (1 << 20)}Mbit_rows",
        "value": round(ops_per_sec, 3),
        "unit": "ops/sec",
        "vs_baseline": round(host_s / device_s, 3),
    }))


if __name__ == "__main__":
    main()
