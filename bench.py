"""Benchmark of record: Intersect+Count throughput on 1 Gbit rows.

Metric (BASELINE.md): Intersect+Count row-ops/sec on 2^30-bit packed rows.
The device op is the fused XLA kernel ``sum(popcount(a & b), axis=-1)``
(pilosa_tpu.ops.kernels.op_count_rows) — the TPU replacement for the
reference's amd64 POPCNT assembly loop (roaring/assembly_amd64.s:60-77,
`popcntAndSliceAsm`). The baseline denominator is measured on this
machine: the same algorithm through our C++ host kernel
(pilosa_tpu/native/bitops.cpp, `popcnt_and`), which is the faithful
stand-in for the reference's native path (no Go toolchain in this image —
BASELINE.md records that denominators must be measured, not quoted).

Methodology: the TPU is reached through a tunnel whose host↔device sync
costs ~65 ms per round trip regardless of payload — so per-call timing
measures the tunnel, not the chip. We instead batch K row pairs per call,
chain N asynchronous dispatches, and sync ONCE on the last output; the
measured window then amortizes one sync over K*N row-ops of real HBM
traffic (validated: chained-dispatch and on-device fori_loop agree within
2% at ~550 GB/s sustained on a v5e chip). Counts are verified against the
host kernel before timing.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs: PILOSA_BENCH_BITS (row width, default 2^30),
PILOSA_BENCH_ROWS (K, default 8), PILOSA_BENCH_ITERS (chained dispatches,
default 32), PILOSA_BENCH_TRIALS (default 3, median reported).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import jax

    from pilosa_tpu.ops.kernels import op_count_rows
    from pilosa_tpu.storage import native

    bits = int(os.environ.get("PILOSA_BENCH_BITS", str(1 << 30)))
    k_rows = int(os.environ.get("PILOSA_BENCH_ROWS", "8"))
    iters = int(os.environ.get("PILOSA_BENCH_ITERS", "32"))
    trials = int(os.environ.get("PILOSA_BENCH_TRIALS", "3"))
    n_words = bits // 32

    rng = np.random.default_rng(42)
    a = rng.integers(0, 2**32, size=(k_rows, n_words), dtype=np.uint32)
    b = rng.integers(0, 2**32, size=(k_rows, n_words), dtype=np.uint32)

    # --- host-native baseline (C++ popcount kernel, same rows).
    # Rows are viewed as u64 (bit-identical reinterpret, the kernel's
    # native word) so the timed region is the kernel alone, not a
    # widening copy; popcnt_and itself falls back to np.bitwise_count
    # when the C++ lib is unavailable. Median of per-row times over two
    # passes, mirroring the device side's median-of-trials.
    a64, b64 = a.view(np.uint64), b.view(np.uint64)
    native.popcnt_and(a64[0], b64[0])  # warmup: page in + lib load
    want, host_times = [], []
    for _ in range(2):
        want = []
        for i in range(k_rows):
            t0 = time.perf_counter()
            want.append(native.popcnt_and(a64[i], b64[i]))
            host_times.append(time.perf_counter() - t0)
    host_s = sorted(host_times)[len(host_times) // 2]

    # --- device path (TPU if available, else whatever jax defaults to)
    da, db = jax.device_put(a), jax.device_put(b)
    got = np.asarray(op_count_rows("and", da, db))  # warmup + verify
    assert got.tolist() == want, (got.tolist(), want)

    best = []
    for _ in range(trials):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = op_count_rows("and", da, db)
        np.asarray(out)  # single sync: flushes the whole chained queue
        best.append((time.perf_counter() - t0) / (k_rows * iters))
    device_s = sorted(best)[len(best) // 2]

    ops_per_sec = 1.0 / device_s
    print(json.dumps({
        "metric": f"intersect_count_{bits // (1 << 20)}Mbit_rows",
        "value": round(ops_per_sec, 3),
        "unit": "ops/sec",
        "vs_baseline": round(host_s / device_s, 3),
    }))


if __name__ == "__main__":
    main()
