"""Multi-tenant QoS (ISSUE 14, pilosa_tpu.sched.tenants): per-tenant
weighted lanes / caps / quotas in admission, the slow-query cost-kill
policy with its penalty box, per-tenant cache quotas, the `[tenants]`
config contract, per-tenant SLO burn, the sentinel's tenant rule, and
ENOSPC disk-full graceful degradation (fault.diskfull + the `enospc`
failpoint mode)."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.errors import QueryKilledError
from pilosa_tpu.fault import diskfull as fault_diskfull
from pilosa_tpu.fault import failpoints
from pilosa_tpu.obs import accounting as obs_accounting
from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.obs.slo import HealthChecker, TenantSLOTracker
from pilosa_tpu.sched import (AdmissionController, AdmissionFullError,
                              QueryContext, TenantRegistry)
from pilosa_tpu.server.server import Server
from pilosa_tpu.storage.bitmap import Bitmap
from pilosa_tpu.storage.wal import GroupCommitWal, WalError
from pilosa_tpu.utils.config import (Config, QueryConfig, TenantsConfig,
                                     load, parse_tenant_table,
                                     parse_tenants)

pytestmark = pytest.mark.tenant


@pytest.fixture(autouse=True)
def _clean_global_state():
    """The diskfull latch and failpoint registry are process-global:
    a leaked write-unready flag would 507 every later write test in
    the tier-1 run."""
    yield
    failpoints.disarm_all()
    fault_diskfull.default().reset()


# ---------------------------------------------------------------------------
# [tenants] config contract


class TestTenantConfig:
    def test_table_parses_and_normalizes(self):
        table = parse_tenant_table({
            "default": {"weight": 4, "concurrency": 8,
                        "queue-depth": 16, "max-wall": "2s",
                        "cache-share": 0.5},
            "bulk": {"weight": 1, "max-container-ops": 1000,
                     "max-device-bytes": 1 << 20},
        })
        assert table["default"]["weight"] == 4.0
        assert table["default"]["max_wall_s"] == 2.0
        assert table["default"]["cache_share"] == 0.5
        assert table["bulk"]["max_container_ops"] == 1000

    def test_unknown_key_fails_loudly(self):
        with pytest.raises(ValueError, match="unknown key.*wieght"):
            parse_tenant_table({"default": {"wieght": 4}})

    def test_non_positive_weight_fails_loudly(self):
        with pytest.raises(ValueError, match="weight must be positive"):
            parse_tenant_table({"default": {"weight": 0}})
        with pytest.raises(ValueError, match="weight must be positive"):
            parse_tenant_table({"default": {"weight": -2}})

    def test_missing_default_fails_loudly(self):
        with pytest.raises(ValueError, match="'default' entry"):
            parse_tenant_table({"bulk": {"weight": 1}})

    def test_bad_cache_share_fails_loudly(self):
        with pytest.raises(ValueError, match="cache-share"):
            parse_tenant_table({"default": {"cache-share": 1.5}})

    def test_compact_form_round_trips(self):
        table = parse_tenants(
            "default:weight=4,concurrency=8;"
            "bulk:weight=1,max-wall=500ms,queue-depth=2")
        assert table["default"]["concurrency"] == 8
        assert table["bulk"]["max_wall_s"] == 0.5
        assert table["bulk"]["queue_depth"] == 2

    def test_compact_form_malformed_fails(self):
        with pytest.raises(ValueError):
            parse_tenants("default")  # no colon
        with pytest.raises(ValueError):
            parse_tenants("default:weight")  # no =

    def test_env_plumbing(self):
        cfg = load(env={"PILOSA_TENANTS":
                        "default:weight=2;hot:concurrency=4"})
        assert cfg.tenants.table["default"]["weight"] == 2.0
        assert cfg.tenants.table["hot"]["concurrency"] == 4
        with pytest.raises(ValueError):
            load(env={"PILOSA_TENANTS": "hot:weight=1"})  # no default

    def test_toml_file_and_to_toml_round_trip(self, tmp_path):
        cfg = Config()
        cfg.tenants = TenantsConfig(table=parse_tenants(
            "default:weight=4,cache-share=0.5;"
            "bulk:weight=1,max-wall=2s"))
        p = tmp_path / "c.toml"
        p.write_text(cfg.to_toml())
        got = load(str(p))
        assert got.tenants.table["default"]["weight"] == 4.0
        assert got.tenants.table["default"]["cache_share"] == 0.5
        assert got.tenants.table["bulk"]["max_wall_s"] == 2.0


# ---------------------------------------------------------------------------
# TenantRegistry: resolution, inheritance, penalty box


class TestTenantRegistry:
    def test_unknown_tenant_rides_default_policy(self):
        reg = TenantRegistry({"default": {"weight": 4,
                                          "concurrency": 8}})
        pol = reg.policy("never-seen-index")
        assert pol.weight == 4 and pol.concurrency == 8

    def test_named_tenant_inherits_unset_knobs_from_default(self):
        reg = TenantRegistry({"default": {"weight": 4,
                                          "cache_share": 0.25},
                              "bulk": {"weight": 1}})
        pol = reg.policy("bulk")
        assert pol.weight == 1 and pol.cache_share == 0.25

    def test_penalty_box_demotes_and_recovers(self):
        reg = TenantRegistry({"default": {"weight": 4}},
                             penalty_half_life_s=0.05)
        assert reg.effective_weight("t") == 4.0
        reg.note_kill("t")
        w = reg.effective_weight("t")
        assert w < 4.0  # demoted (~half)
        assert reg.snapshot()["t"]["inPenaltyBox"]
        time.sleep(0.5)  # 10 half-lives: score decays past the floor
        assert reg.effective_weight("t") == 4.0
        assert not reg.snapshot()["t"]["inPenaltyBox"]

    def test_repeat_offender_sinks_further(self):
        reg = TenantRegistry({"default": {"weight": 8}},
                             penalty_half_life_s=60.0)
        reg.note_kill("t")
        one = reg.effective_weight("t")
        reg.note_kill("t")
        two = reg.effective_weight("t")
        assert two < one < 8.0


# ---------------------------------------------------------------------------
# Two-level stride admission


def _drain(ac, slots):
    for s in slots:
        s.release()


class TestTenantAdmission:
    def _grant_order(self, ac, plan, n_grants):
        """Enqueue one waiter per (lane, tenant) in ``plan`` behind a
        gate slot; release serially; return grant order."""
        order, threads = [], []
        gate = ac.acquire("read", tenant="gate")
        mu = threading.Lock()

        def worker(lane, tenant):
            s = ac.acquire(lane, tenant=tenant)
            with mu:
                order.append(tenant)
            s.release()

        for lane, tenant in plan:
            t = threading.Thread(target=worker, args=(lane, tenant))
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and ac.snapshot()["queued"].get("read", 0)
               + ac.snapshot()["queued"].get("write", 0) < len(plan)):
            time.sleep(0.01)
        gate.release()
        for t in threads:
            t.join(timeout=10)
        return order

    def test_weighted_share_between_tenants_within_lane(self):
        reg = TenantRegistry({"default": {"weight": 1},
                              "heavy": {"weight": 3}})
        ac = AdmissionController(concurrency=1, queue_depth=64,
                                 tenants=reg)
        plan = [("read", "heavy")] * 6 + [("read", "light")] * 6
        order = self._grant_order(ac, plan, len(plan))
        # Stride at 3:1 — the first 4 grants hold ~3 heavy to 1
        # light, NOT 6 heavy in a row (FIFO would).
        first4 = order[:4]
        assert first4.count("heavy") == 3 and "light" in first4, order

    def test_aggressor_backlog_cannot_starve_quiet_tenant(self):
        reg = TenantRegistry({"default": {"weight": 1}})
        ac = AdmissionController(concurrency=1, queue_depth=64,
                                 tenants=reg)
        # 10 queued aggressor waiters, 1 quiet: equal weights mean the
        # quiet tenant is granted 2nd, not 11th.
        plan = [("read", "aggr")] * 10 + [("read", "quiet")]
        order = self._grant_order(ac, plan, len(plan))
        assert "quiet" in order[:2], order

    def test_per_tenant_concurrency_cap_queues_at_cap(self):
        reg = TenantRegistry({"default": {"weight": 1},
                              "capped": {"concurrency": 1}})
        ac = AdmissionController(concurrency=4, queue_depth=8,
                                 tenants=reg)
        s1 = ac.acquire("read", tenant="capped")
        got = []

        def second():
            s = ac.acquire("read", tenant="capped")
            got.append(time.monotonic())
            s.release()

        t = threading.Thread(target=second)
        t.start()
        time.sleep(0.15)
        # Capped tenant waits despite 3 free global slots; another
        # tenant sails through them.
        assert not got
        ac.acquire("read", tenant="other").release()
        s1.release()
        t.join(timeout=10)
        assert got  # cap freed -> granted

    def test_queue_quota_429s_only_the_offender(self):
        reg = TenantRegistry({"default": {"weight": 1},
                              "noisy": {"concurrency": 1,
                                        "queue-depth": 1}})
        ac = AdmissionController(concurrency=1, queue_depth=16,
                                 tenants=reg)
        gate = ac.acquire("read", tenant="noisy")  # holds noisy's cap
        t = threading.Thread(
            target=lambda: ac.acquire("read", tenant="noisy").release())
        t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and not ac.snapshot()["queued"]:
            time.sleep(0.01)
        with pytest.raises(AdmissionFullError) as ei:
            ac.acquire("read", tenant="noisy")
        assert ei.value.tenant == "noisy"
        assert ei.value.retry_after_s >= 1
        # The quiet tenant still queues fine (global depth not hit).
        t2 = threading.Thread(
            target=lambda: ac.acquire("read", tenant="quiet").release())
        t2.start()
        time.sleep(0.1)
        snap = ac.snapshot()
        assert snap["tenants"]["quiet"]["queued"] == 1
        assert snap["tenants"]["noisy"]["rejected"] == 1
        gate.release()
        t.join(timeout=10)
        t2.join(timeout=10)

    def test_retry_after_is_per_lane(self):
        """A shed write burst (long write holds) must not inflate the
        Retry-After handed to rejected READ traffic."""
        ac = AdmissionController(concurrency=1, queue_depth=0)
        s = ac.acquire("write")
        s._t0 -= 8.0  # backdate: an 8 s write hold
        s.release()   # write-lane hold EWMA ~= 1.6s
        gate = ac.acquire("write")
        with pytest.raises(AdmissionFullError) as wr:
            ac.acquire("write")
        with pytest.raises(AdmissionFullError) as rd:
            ac.acquire("read")
        assert wr.value.retry_after_s >= 2
        assert rd.value.retry_after_s == 1  # read EWMA untouched
        gate.release()

    def test_snapshot_shape_still_has_lane_totals(self):
        ac = AdmissionController(concurrency=1, queue_depth=4)
        snap = ac.snapshot()
        assert snap["queued"] == {} and snap["rejected"] == 0
        assert "tenants" in snap


# ---------------------------------------------------------------------------
# Slow-query cost-kill policy


class TestCostKillPolicy:
    def _ctx(self, reg, tenant="t", **kw):
        ctx = QueryContext(pql="Count()", index=tenant, tenant=tenant,
                           **kw)
        obs_accounting.attach(ctx, node="n")
        reg.install(ctx)
        return ctx

    def test_container_op_ceiling_kills(self):
        reg = TenantRegistry({"default": {},
                              "t": {"max_container_ops": 5}})
        ctx = self._ctx(reg)
        for _ in range(5):
            ctx.cost.note_container_op("and", "bitmap:bitmap")
        ctx.check()  # at the ceiling: fine
        ctx.cost.note_container_op("and", "bitmap:bitmap")
        with pytest.raises(QueryKilledError, match="cost-policy"):
            ctx.check()
        assert ctx.killed_by == "cost-policy"
        # Every subsequent check raises the KILLED form, from any
        # thread (deterministic 402 mapping).
        with pytest.raises(QueryKilledError):
            ctx.check()

    def test_wall_ceiling_kills(self):
        reg = TenantRegistry({"default": {},
                              "t": {"max_wall_s": 0.01}})
        ctx = self._ctx(reg)
        time.sleep(0.03)
        with pytest.raises(QueryKilledError, match="wall"):
            ctx.check()

    def test_device_bytes_ceiling_kills(self):
        reg = TenantRegistry({"default": {},
                              "t": {"max_device_bytes": 100}})
        ctx = self._ctx(reg)
        ctx.cost.note_device_dispatch(101)
        with pytest.raises(QueryKilledError, match="device bytes"):
            ctx.check()

    def test_kill_broadcasts_and_enters_penalty_box(self):
        reg = TenantRegistry({"default": {},
                              "t": {"max_container_ops": 1}})
        fanned = []
        reg.kill_broadcast = fanned.append
        ctx = self._ctx(reg)
        ctx.cost.note_container_op("or", "array:array")
        ctx.cost.note_container_op("or", "array:array")
        with pytest.raises(QueryKilledError):
            ctx.check()
        assert fanned == [ctx.id]
        snap = reg.snapshot()["t"]
        assert snap["killed"] == 1 and snap["inPenaltyBox"]

    def test_no_ceilings_attaches_nothing(self):
        reg = TenantRegistry({"default": {}})
        ctx = self._ctx(reg)
        assert ctx.cost_policy is None  # zero per-check overhead


# ---------------------------------------------------------------------------
# Per-tenant cache quotas (executor)


class TestCacheQuotas:
    def _bm(self, n):
        bm = Bitmap()
        for i in range(n):
            bm.set_bit(i)
        return bm

    def _executor(self, share=0.5, entries=64, bits=400):
        from pilosa_tpu.executor import Executor
        reg = TenantRegistry({"default": {"cache_share": share}})
        ex = Executor(None, host="a", use_mesh=False, tenants=reg)
        ex._result_cache_entries = entries
        ex._result_cache_bits = bits
        return ex

    def test_aggressor_evicts_its_own_entries_not_quiet_tenants(self):
        ex = self._executor(share=0.5, bits=400)
        ex._result_cache_put(("quiet", "e1", (0,)), self._bm(100))
        ex._result_cache_put(("quiet", "e2", (0,)), self._bm(100))
        # Aggressor floods: its share is 200 bits -> only its own
        # entries churn; the quiet tenant's 200 bits stay put.
        for i in range(10):
            ex._result_cache_put(("aggr", f"e{i}", (0,)),
                                 self._bm(100))
        usage = ex.tenant_cache_usage()
        assert usage["quiet"]["resultBits"] == 200
        assert usage["aggr"]["resultBits"] <= 200

    def test_oversize_single_entry_respects_tenant_budget(self):
        ex = self._executor(share=0.25, bits=400)  # tenant budget 100
        ex._result_cache_put(("t", "big", (0,)), self._bm(150))
        assert ex.tenant_cache_usage() == {}

    def test_cluster_cache_per_tenant_entry_cap(self):
        ex = self._executor(share=0.5)
        ex._cluster_cache_entries = 4  # tenant cap = 2
        pre = {"local": {}, "remote": {}}
        ex._cluster_cache_snapshot = lambda *a: pre
        for i in range(4):
            ex._cluster_cache_store(("aggr", f"q{i}", (0,), 0), "aggr",
                                    [0], [i], pre)
        ex._cluster_cache_store(("quiet", "q", (0,), 0), "quiet",
                                [0], [9], pre)
        usage = ex.tenant_cache_usage()
        assert usage["aggr"]["clusterEntries"] <= 2
        assert usage["quiet"]["clusterEntries"] == 1


# ---------------------------------------------------------------------------
# Per-tenant SLO burn + sentinel rule


class TestTenantSLO:
    def test_per_tenant_burn_rates(self):
        hist = obs_metrics.Registry().histogram(
            "pilosa_test_tenant_seconds", "t", labels=("tenant",))
        tracker = TenantSLOTracker(histogram=hist, objective_s=0.25,
                                   target=0.9)
        tracker.record()  # baseline
        for _ in range(10):
            hist.labels("quiet").observe(0.01)   # all good
            hist.labels("aggr").observe(5.0)     # all bad
        out = tracker.record()
        assert out["quiet"]["burnRates"]["5m"] == 0.0
        # 100% bad over a 10% budget = 10x burn.
        assert out["aggr"]["burnRates"]["5m"] == pytest.approx(10.0)
        assert tracker.last()["aggr"]["requestsTotal"] == 10

    def test_sentinel_tenant_burn_rule_fires(self):
        from pilosa_tpu.obs.sentinel import Sentinel

        from pilosa_tpu.obs.history import series_key

        class _Hist:
            def keys(self, family=""):
                return [series_key("pilosa_tenant_slo_burn_rate_ratio",
                                   {"tenant": "aggr", "window": "5m"}),
                        series_key("pilosa_tenant_slo_burn_rate_ratio",
                                   {"tenant": "quiet", "window": "5m"})]

            def window_values(self, key, start, end):
                return [12.0] * 6 if "aggr" in key else [0.1] * 6

        sen = Sentinel(_Hist(), interval_s=1000, min_points=5,
                       tenant_burn_threshold=10.0, watches=())
        findings = sen.check()
        assert len(findings) == 1
        f = findings[0]
        assert f["rule"] == "tenant_burn"
        assert f["labels"].get("tenant") == "aggr"


# ---------------------------------------------------------------------------
# ENOSPC graceful degradation


class TestEnospc:
    def test_enospc_failpoint_mode_carries_errno(self):
        import errno
        fp = failpoints.parse_spec("wal.append", "enospc*1")
        assert fp.mode == "enospc"
        with failpoints.injected("wal.append", "enospc"):
            with pytest.raises(failpoints.FailpointError) as ei:
                failpoints.default().hit("wal.append")
            assert ei.value.errno == errno.ENOSPC
            assert fault_diskfull.is_enospc(ei.value)

    def test_wal_enospc_flips_unready_and_recovers_on_write(self,
                                                            tmp_path):
        f = open(tmp_path / "w.wal", "ab")
        wal = GroupCommitWal(f, fsync_policy="none")
        wal.append(b"x" * 13)
        with failpoints.injected("wal.append", "enospc*1"):
            with pytest.raises(WalError):
                wal.flush()
        st = fault_diskfull.default()
        assert not st.write_ready(probe=False)
        assert st.snapshot()["events"] == {"wal.append": 1}
        # The batch stayed pending; the next (post-disarm) flush
        # succeeds and THAT clears the latch — real traffic is the
        # cheapest recovery probe.
        wal.flush()
        assert st.write_ready(probe=False)
        wal.close()
        f.close()

    def test_probe_auto_recovery(self, tmp_path):
        st = fault_diskfull.default()
        st.note_enospc("snapshot.write",
                       path=str(tmp_path / "frag" / "0"))
        assert not st.write_ready(probe=False)
        os.makedirs(tmp_path / "frag", exist_ok=True)
        # First probed call recovers (the dir is writable again).
        assert st.write_ready()
        assert st.snapshot()["recoveries"] == 1

    def test_diskring_drops_and_counts_instead_of_raising(self,
                                                          tmp_path):
        from pilosa_tpu.obs.diskring import SegmentRing
        ring = SegmentRing(str(tmp_path / "ring"))
        with failpoints.injected("ring.write", "enospc"):
            assert ring.append({"a": 1}) is False
        assert ring.dropped == 1
        # And it does NOT gate serving: the node stays write-ready.
        assert fault_diskfull.default().write_ready(probe=False)
        assert ring.append({"a": 2}) is True

    def test_health_reports_write_unready(self):
        st = fault_diskfull.default()
        st.note_enospc("wal.append", path="/nonexistent-dir/x")
        hc = HealthChecker()
        ready, checks = hc.check()
        assert not checks["writeReady"]["ok"]
        assert not ready
        st.reset()
        _, checks = hc.check()
        assert checks["writeReady"]["ok"]


# ---------------------------------------------------------------------------
# HTTP integration: tenant-scoped 429, cost-kill 402, ENOSPC 507,
# /debug/tenants


def _post(host, path, body=b"", headers=None):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST", headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.read(), dict(r.headers)


def _get(host, path):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=30) as r:
        return json.loads(r.read())


class _SlowExecutor:
    """Busy-waits (cooperatively checking the query context) for
    queries against ``only`` (default: every index)."""

    def __init__(self, real, seconds=30.0, only=None):
        self._real = real
        self._seconds = seconds
        self._only = only

    def __getattr__(self, name):
        return getattr(self._real, name)

    def execute(self, index, query, slices=None, opt=None, **kw):
        if self._only is None or index == self._only:
            t0 = time.monotonic()
            while time.monotonic() - t0 < self._seconds:
                if opt is not None and opt.ctx is not None:
                    opt.ctx.check()
                time.sleep(0.005)
        return self._real.execute(index, query, slices, opt, **kw)


def _make_server(tmp_path, tenants=None, **qc):
    s = Server(str(tmp_path / "srv"), host="127.0.0.1:0",
               anti_entropy_interval=0, polling_interval=0,
               query_config=QueryConfig(**qc),
               tenants_config=TenantsConfig(
                   table=parse_tenants(tenants) if tenants else {}))
    s.open()
    _post(s.host, "/index/i")
    _post(s.host, "/index/i/frame/f")
    _post(s.host, "/index/i/query",
          b'SetBit(frame="f", rowID=1, columnID=3)')
    return s


class TestTenantHTTP:
    def test_cost_kill_answers_402_with_header(self, tmp_path):
        s = _make_server(tmp_path,
                         tenants="default:weight=1;i:max-wall=150ms")
        try:
            s.handler.executor = _SlowExecutor(s.executor)
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(s.host, "/index/i/query",
                      b'Count(Bitmap(frame="f", rowID=1))')
            assert ei.value.code == 402
            assert ei.value.headers["X-Pilosa-Killed-By"] \
                == "cost-policy"
            assert time.monotonic() - t0 < 10
            assert b"cost-policy" in ei.value.read()
            # Penalty + kill count surface at /debug/tenants; the
            # registry is drained (no leaked slot or entry).
            dbg = _get(s.host, "/debug/tenants")["tenants"]["i"]
            assert dbg["killed"] == 1 and dbg["inPenaltyBox"]
            assert dbg["effectiveWeight"] < dbg["policy"]["weight"]
            assert _get(s.host, "/debug/queries")["queries"] == []
        finally:
            s.close()

    def test_tenant_quota_429_spares_other_tenant(self, tmp_path):
        s = _make_server(
            tmp_path, concurrency=8, queue_depth=64,
            tenants="default:weight=1;i:concurrency=1,queue-depth=1")
        try:
            _post(s.host, "/index/quiet")
            _post(s.host, "/index/quiet/frame/f")
            _post(s.host, "/index/quiet/query",
                  b'SetBit(frame="f", rowID=1, columnID=3)')
            s.handler.executor = _SlowExecutor(s.executor, only="i")

            def swallow():
                try:
                    _post(s.host, "/index/i/query?timeout=5s",
                          b'Bitmap(frame="f", rowID=1)')
                except urllib.error.HTTPError:
                    pass

            threads = [threading.Thread(target=swallow)
                       for _ in range(2)]  # 1 slot + 1 queue seat
            for t in threads:
                t.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                snap = _get(s.host, "/debug/queries")["admission"]
                ten = (snap.get("tenants") or {}).get("i", {})
                if ten.get("inFlight", 0) >= 1 \
                        and ten.get("queued", 0) >= 1:
                    break
                time.sleep(0.02)
            try:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post(s.host, "/index/i/query",
                          b'Bitmap(frame="f", rowID=1)')
                assert ei.value.code == 429
                assert int(ei.value.headers["Retry-After"]) >= 1
                # The OTHER tenant still has the remaining 7 slots.
                st, _, _ = _post(s.host,
                                 "/index/quiet/query?timeout=10s",
                                 b'Count(Bitmap(frame="f", rowID=1))')
                assert st == 200
                dbg = _get(s.host, "/debug/tenants")["tenants"]
                assert dbg["i"]["shed"] >= 1
                assert dbg.get("quiet", {}).get("shed", 0) == 0
            finally:
                for ctx in [s.query_registry.get(q["id"]) for q in
                            s.query_registry.active()]:
                    if ctx is not None:
                        ctx.cancel()
                for t in threads:
                    t.join(timeout=10)
        finally:
            s.close()

    def test_enospc_write_507_read_serving_and_recovery(self,
                                                        tmp_path):
        s = _make_server(tmp_path)
        try:
            st = fault_diskfull.default()
            st.note_enospc("wal.append", path="/nonexistent-dir/x")
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(s.host, "/index/i/query",
                      b'SetBit(frame="f", rowID=2, columnID=4)')
            assert ei.value.code == 507
            assert int(ei.value.headers["Retry-After"]) >= 1
            # Imports (the write lane) shed identically.
            with pytest.raises(urllib.error.HTTPError) as ei2:
                _post(s.host, "/index/i/query",
                      b'SetBit(frame="f", rowID=2, columnID=5)')
            assert ei2.value.code == 507
            # Reads keep serving; /health reports the condition.
            stc, _, _ = _post(s.host, "/index/i/query",
                              b'Count(Bitmap(frame="f", rowID=1))')
            assert stc == 200
            with pytest.raises(urllib.error.HTTPError) as eh:
                urllib.request.urlopen(f"http://{s.host}/health",
                                       timeout=10)
            assert eh.value.code == 503
            body = json.loads(eh.value.read())
            assert body["checks"]["writeReady"]["ok"] is False
            # Space "frees": point the probe at a writable dir; the
            # next write probes, recovers, and lands.
            with st._mu:
                st._dir = str(tmp_path)
                st._last_probe = 0.0
            stw, _, _ = _post(s.host, "/index/i/query",
                              b'SetBit(frame="f", rowID=2, columnID=6)')
            assert stw == 200
            assert _get(s.host, "/debug/tenants")["writeReady"][
                "writeReady"] is True
        finally:
            s.close()

    def test_debug_tenants_shape(self, tmp_path):
        s = _make_server(tmp_path,
                         tenants="default:weight=2,concurrency=8")
        try:
            out = _get(s.host, "/debug/tenants")
            assert "writeReady" in out
            row = out["tenants"]["i"]
            assert row["served"] >= 1  # the fixture's SetBit
            assert row["policy"]["weight"] == 2.0
        finally:
            s.close()

    def test_tenant_metrics_families_emit(self, tmp_path):
        s = _make_server(tmp_path)
        try:
            _post(s.host, "/index/i/query",
                  b'Count(Bitmap(frame="f", rowID=1))')
            with urllib.request.urlopen(f"http://{s.host}/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            assert 'pilosa_tenant_query_requests_total{tenant="i"' \
                in text
            assert "pilosa_tenant_query_duration_seconds" in text
            assert "pilosa_storage_write_ready 1" in text
        finally:
            s.close()
