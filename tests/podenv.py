"""Shared helpers for the multi-process pod tests (test_pod.py,
test_pod_cluster.py) and their child scripts.

One copy of the env contract: children must get stock CPU JAX decided
in the PARENT environment — the axon sitecustomize hook runs at
interpreter start, so in-process overrides are too late (see
.claude/skills/verify/SKILL.md gotchas).
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def cpu_env() -> dict:
    """A child env with the TPU plugin disarmed and CPU JAX selected."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["PILOSA_TPU_MESH_MIN_SLICES"] = "1"
    return env


def pod_env(proc_id: int, jax_port: int, peers: list[str],
            cpu_devices: int = 2) -> dict:
    """cpu_env plus the pod process contract (parallel.multihost/pod)."""
    env = cpu_env()
    env.update({
        "PILOSA_TPU_DIST_COORDINATOR": f"localhost:{jax_port}",
        "PILOSA_TPU_DIST_NUM_PROCS": str(len(peers)),
        "PILOSA_TPU_DIST_PROC_ID": str(proc_id),
        "PILOSA_TPU_DIST_CPU_DEVICES": str(cpu_devices),
        "PILOSA_TPU_POD_PEERS": ",".join(peers),
    })
    return env


class ChildSet:
    """Spawn child processes with log files, kill + close on exit."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.procs: dict[str, subprocess.Popen] = {}
        self._stack = contextlib.ExitStack()

    def spawn(self, name: str, argv: list[str], env: dict,
              pipe: bool = False):
        """pipe=True captures stdout/stderr (for the driver child);
        otherwise output goes to <name>.log — a PIPE nothing drains
        would wedge a long-lived worker on a full buffer."""
        if pipe:
            stdout = stderr = subprocess.PIPE
        else:
            stdout = stderr = self._stack.enter_context(
                open(self.log_path(name), "w"))
        p = subprocess.Popen(argv, env=env, stdout=stdout, stderr=stderr,
                             text=True)
        self.procs[name] = p
        return p

    def log_path(self, name: str):
        return self.tmp_path / f"{name}.log"

    def logs_tail(self, n: int = 2000) -> str:
        out = []
        for name in self.procs:
            path = self.log_path(name)
            if path.exists():
                out.append(f"{name}:\n{path.read_text()[-n:]}")
        return "\n".join(out)

    def cleanup(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()
        self._stack.close()


# ---- helpers for the child scripts themselves --------------------------


def http(method: str, host: str, path: str, body: bytes = b"",
         content_type: str = "application/json") -> bytes:
    req = urllib.request.Request(
        f"http://{host}{path}", data=body, method=method,
        headers={"Content-Type": content_type, "Accept": content_type})
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        raise RuntimeError(
            f"{method} {path}: {e.code}: "
            f"{e.read().decode(errors='replace')[:500]}") from e


def query(host: str, index: str, pql: str):
    raw = http("POST", host, f"/index/{index}/query", pql.encode())
    return json.loads(raw)["results"]


def wait_up(host: str, deadline: float = 120) -> None:
    t0 = time.time()
    while time.time() - t0 < deadline:
        try:
            http("GET", host, "/version")
            return
        except Exception:  # noqa: BLE001 - keep polling until deadline
            time.sleep(0.3)
    raise RuntimeError(f"{host} not up")


def child_main(fn) -> None:
    """Run a child's main() and hard-exit either way: jax.distributed's
    atexit shutdown can hang on dead peers, and the launcher only
    watches rc/stdout."""
    try:
        fn()
    except BaseException:
        import traceback
        traceback.print_exc()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(1)
    os._exit(0)
