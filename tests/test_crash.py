"""Process-level crash durability: SIGKILL a live server mid-write.

The in-process suites cover torn-WAL-tail trims and clean restarts
(test_fragment, test_server soaks); this one kills a REAL server
process with SIGKILL while a write storm is in flight, then proves the
data directory reopens cleanly: `check` passes on every fragment file,
and every acknowledged write is present after restart (the reference's
durability contract — an op acked over HTTP has hit the WAL).

The child runs with the device paths disabled so a SIGKILL can never
wedge the shared TPU tunnel (SKILL.md gotcha).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from podenv import cpu_env, free_port, wait_up

_HERE = os.path.dirname(os.path.abspath(__file__))


def _spawn_server(data_dir, port, log):
    env = cpu_env()
    env["PILOSA_TPU_MESH"] = "0"
    return subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server",
         "-d", str(data_dir), "-b", f"127.0.0.1:{port}"],
        env=env, stdout=log, stderr=log,
        cwd=os.path.dirname(_HERE))


def _query(port, pql, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/index/ci/query", data=pql.encode(),
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())["results"]


def test_sigkill_mid_write_storm_recovers(tmp_path):
    port = free_port()
    data_dir = tmp_path / "data"
    with open(tmp_path / "server.log", "w") as log:
        proc = _spawn_server(data_dir, port, log)
        try:
            wait_up(f"127.0.0.1:{port}")
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/index/ci", data=b"{}",
                method="POST"), timeout=30).read()
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/index/ci/frame/cf", data=b"{}",
                method="POST"), timeout=30).read()

            # Write storm: every acked SetBit is recorded; the kill
            # lands somewhere inside the stream.
            acked = []
            deadline = time.monotonic() + 6.0
            i = 0
            while time.monotonic() < deadline and i < 3000:
                col = (i * 131) % (1 << 20)
                row = i % 40
                _query(port, f'SetBit(frame="cf", rowID={row},'
                             f' columnID={col})')
                acked.append((row, col))
                i += 1
            assert len(acked) > 200, "storm too slow to be meaningful"
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    # Offline integrity: every fragment file must pass check().
    frag_dir = data_dir / "ci" / "cf" / "views" / "standard" / "fragments"
    frags = [str(p) for p in frag_dir.iterdir()
             if p.name.isdigit()] if frag_dir.exists() else []
    assert frags, "no fragment files written before the kill"
    from pilosa_tpu.cli.commands import main as cli_main
    import io
    out = io.StringIO()
    rc = cli_main(["check"] + frags, stdout=out, stderr=out)
    assert rc == 0, f"check failed after SIGKILL:\n{out.getvalue()}"

    # Restart on the same data dir: every acked bit answers.
    with open(tmp_path / "server2.log", "w") as log:
        proc = _spawn_server(data_dir, port, log)
        try:
            wait_up(f"127.0.0.1:{port}")
            want = {}
            for row, col in acked:
                want.setdefault(row, set()).add(col)
            for row, cols in sorted(want.items()):
                got = _query(port, f'Bitmap(frame="cf", rowID={row})')
                bits = set(got[0]["bits"])
                missing = cols - bits
                assert not missing, (row, sorted(missing)[:5])
        finally:
            proc.send_signal(signal.SIGINT)
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


@pytest.mark.chaos
def test_wal_append_torn_at_every_offset_recovers(tmp_path):
    """Failpoint-driven DETERMINISTIC crash-mid-wal.append, group-commit
    form: the ``wal.append`` failpoint now fires at the LEADER's batch
    write (storage.wal), so ``torn(k)`` tears a GROUPED multi-record
    batch at every byte offset — exactly where a crash mid group
    commit would cut the log. The reopen must recover the acked prefix
    (records whose commit barrier returned) plus exactly the complete
    records of the torn batch (written but never acked — at-least-once
    is allowed, loss of acked ops is not), and the fragment must
    accept writes again."""
    from pilosa_tpu.fault import failpoints
    from pilosa_tpu.fault.failpoints import FailpointError
    from pilosa_tpu.storage.fragment import Fragment
    from pilosa_tpu.storage.roaring import OP_SIZE
    from pilosa_tpu.storage.wal import WalError

    batch_cols = [99, 100, 101]  # the torn batch: 3 records, 39 bytes
    try:
        for k in range(OP_SIZE * len(batch_cols)):
            path = str(tmp_path / f"frag{k}")
            f = Fragment(path, "i", "f", "standard", 0)
            f.open()
            acked = []
            for col in range(8):  # acked prefix: barriered below
                f.set_bit(1, col)
                acked.append(col)
            f.wal_barrier()  # the ack point (group-commit contract)
            with failpoints.injected("wal.append", f"torn({k})"):
                # ONE atomic 3-record append (the batched write path)
                # so the torn batch is the same 39 bytes regardless of
                # when a background flush races the barrier.
                import numpy as np
                f.set_bits(np.full(3, 1, dtype=np.uint64),
                           np.array(batch_cols, dtype=np.uint64))
                with pytest.raises((FailpointError, WalError)):
                    f.wal_barrier()  # leader write tears mid-batch
                # Simulate the crash HERE (still torn-armed, so the
                # background flusher cannot quietly retry the batch):
                # mark the dead process's WAL dead and free its flock.
                f._wal.close()
                import fcntl
                fcntl.flock(f._file.fileno(), fcntl.LOCK_UN)
            f2 = Fragment(path, "i", "f", "standard", 0)
            f2.open()
            try:
                # The failed leader truncated back to the durable
                # prefix, so recovery is EXACTLY the acked set — none
                # of the torn batch's records survive at any offset.
                got = sorted(f2.row(1).bits())
                assert got == acked, (
                    f"torn at {k}: {got} != acked {acked}")
                assert f2.set_bit(1, 999), \
                    f"torn at {k}: fragment must accept writes again"
                f2.wal_barrier()
            finally:
                f2.close()
    finally:
        failpoints.disarm_all()


@pytest.mark.chaos
def test_crash_mid_snapshot_write_recovers(tmp_path):
    """Failpoint-driven crash-mid-``snapshot.write``: the async
    MAX_OP_N-triggered snapshot dies mid-serialization, the old
    snapshot+WAL stays the file of record, writes keep flowing, the
    retry lands, and a reopen sees every acked bit."""
    import pilosa_tpu.storage.fragment as fragmod
    from pilosa_tpu.fault import failpoints
    from pilosa_tpu.storage.fragment import Fragment

    old_maxop = fragmod.MAX_OP_N
    fragmod.MAX_OP_N = 20  # force snapshot storms
    path = str(tmp_path / "frag")
    try:
        f = Fragment(path, "i", "f", "standard", 0)
        f.open()
        acked = []
        with failpoints.injected("snapshot.write", "error"):
            for col in range(100):  # many ops → several failed
                f.set_bit(2, col)   # background snapshot attempts
                acked.append(col)
            f._join_snapshot()
        # Disarmed: more writes re-trigger the snapshot, which now
        # lands cleanly.
        for col in range(100, 140):
            f.set_bit(2, col)
            acked.append(col)
        f._join_snapshot()
        assert sorted(f.row(2).bits()) == acked
        f.close()
        f2 = Fragment(path, "i", "f", "standard", 0)
        f2.open()
        try:
            assert sorted(f2.row(2).bits()) == acked, \
                "every acked bit must survive the failed snapshots"
        finally:
            f2.close()
    finally:
        fragmod.MAX_OP_N = old_maxop
        failpoints.disarm_all()


def test_single_fragment_storm_exact_model(tmp_path):
    """Mixed per-op set/clear + batched sets under forced snapshot-storm
    cadence, ops serialized so model order == apply order: the final
    storage must equal the model EXACTLY, live and after reopen. This
    is the single-node half of the 60-min soak's consistency argument —
    when a cluster soak diverges by a bit, this pins whether the
    storage engine (WAL, async snapshot splice, batch engine) can lose
    or invent ops at all (round 5: it could not; the soak event was an
    opposing-op linearization ambiguity across replica fan-outs)."""
    import random
    import threading
    import time

    import numpy as np

    import pilosa_tpu.storage.fragment as fragmod
    from pilosa_tpu.storage.fragment import Fragment

    old_maxop = fragmod.MAX_OP_N
    fragmod.MAX_OP_N = 200
    try:
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        model: dict[int, set] = {}
        mu = threading.Lock()
        stop = threading.Event()
        errs: list = []

        def worker(seed):
            rng = random.Random(seed)
            try:
                while not stop.is_set():
                    r = rng.randrange(16)
                    c = rng.randrange(1 << 18)
                    if rng.random() < 0.85:
                        with mu:
                            f.set_bit(r, c)
                            model.setdefault(r, set()).add(c)
                    else:
                        with mu:
                            f.clear_bit(r, c)
                            model.setdefault(r, set()).discard(c)
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        def batch_worker(seed):
            rng = random.Random(seed)
            try:
                while not stop.is_set():
                    r = rng.randrange(16)
                    cols = np.array(
                        [rng.randrange(1 << 18) for _ in range(100)],
                        dtype=np.uint64)
                    with mu:
                        f.set_bits(np.full(100, r, dtype=np.uint64),
                                   cols)
                        model.setdefault(r, set()).update(cols.tolist())
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(2)]
        threads += [threading.Thread(target=batch_worker, args=(9,))]
        for t in threads:
            t.start()
        time.sleep(8)
        stop.set()
        for t in threads:
            t.join()
        assert not errs, errs

        def rows_equal(frag):
            from pilosa_tpu import SLICE_WIDTH
            for r, want in model.items():
                # offset_range rebases to 0, so values ARE the cols
                pos = frag.storage.offset_range(
                    0, r * SLICE_WIDTH, (r + 1) * SLICE_WIDTH)
                got = set(pos.values().tolist())
                if got != want:
                    return False, r
            return True, None

        ok, bad = rows_equal(f)
        assert ok, f"live mismatch in row {bad}"
        f.close()
        f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f2.open()
        ok, bad = rows_equal(f2)
        assert ok, f"reopen mismatch in row {bad}"
        f2.close()
    finally:
        fragmod.MAX_OP_N = old_maxop
