"""Blackbox flight recorder + stall watchdog (docs/OBSERVABILITY.md):
periodic snapshots into a bounded disk ring, full dumps on demand, and
the four stall detectors — most importantly, a failpoint-wedged WAL
flusher must trip the watchdog and produce a dump that NAMES the
wedged WAL."""

import io
import json
import os
import threading
import time

import pytest

from pilosa_tpu.fault import failpoints
from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.obs.blackbox import Blackbox
from pilosa_tpu.obs.diskring import SegmentRing
from pilosa_tpu.obs.sampler import TailSampler
from pilosa_tpu.obs.trace import Tracer
from pilosa_tpu.obs.watchdog import Watchdog
from pilosa_tpu.sched import (AdmissionController, QueryContext,
                              QueryRegistry)
from pilosa_tpu.storage import wal as storage_wal


# -- blackbox ------------------------------------------------------------------


class TestBlackbox:
    def test_snapshot_ring_and_dump(self, tmp_path):
        state = {"admission": {"queued": {}}, "note": "hello"}
        bb = Blackbox(str(tmp_path / "bb"), state_fn=lambda: state,
                      interval_s=60.0, node="n1")
        for _ in range(3):
            bb.snapshot("periodic")
        recent = list(bb.ring.scan())
        assert len(recent) == 3
        assert recent[0]["note"] == "hello"
        assert recent[0]["node"] == "n1"
        path = bb.dump("api")
        assert path is not None and os.path.exists(path)
        with open(path) as f:
            doc = json.load(f)
        assert doc["cause"] == "api"
        # The dump carries the whole ring (oldest first) plus a fresh
        # "current" snapshot taken at dump time.
        assert len(doc["ring"]) == 4  # 3 periodic + the dump's own
        assert doc["current"]["trigger"] == "dump:api"
        bb.stop()

    def test_dump_files_bounded(self, tmp_path):
        bb = Blackbox(str(tmp_path / "bb"), state_fn=dict,
                      interval_s=60.0, max_dumps=2)
        paths = [bb.dump(f"api") for _ in range(4)]
        assert all(paths)
        assert len(bb.dumps()) == 2  # oldest pruned
        bb.stop()

    def test_state_fn_error_still_snapshots(self, tmp_path):
        def boom():
            raise RuntimeError("collector died")
        bb = Blackbox(str(tmp_path / "bb"), state_fn=boom,
                      interval_s=60.0)
        snap = bb.snapshot("periodic")
        assert "collector died" in snap["stateError"]
        bb.stop()


# -- WAL flusher health --------------------------------------------------------


class TestWalFlusherHealth:
    def test_dirty_age_tracked_and_cleared(self, tmp_path):
        f = open(tmp_path / "wal", "ab")
        wal = storage_wal.GroupCommitWal(f, fsync_policy="none")
        try:
            wal.append(b"x" * storage_wal.OP_SIZE)
            health = storage_wal.flusher_health()
            mine = [w for w in health["wals"]
                    if w["file"] == f.name]
            assert mine and mine[0]["pendingBytes"] > 0
            assert health["oldestDirtyAgeS"] >= 0.0
            wal.barrier()
            health = storage_wal.flusher_health()
            assert not [w for w in health["wals"]
                        if w["file"] == f.name]
        finally:
            wal.close()
            f.close()


# -- watchdog ------------------------------------------------------------------


def _quiet_sampler(tmp_path=None, disk=None):
    return TailSampler(
        disk=disk, head_n=0, slow_floor_s=30.0,
        histogram=obs_metrics.Histogram(
            "pilosa_test_watchdog_latency_seconds", buckets=(64.0,)))


class TestWatchdog:
    def test_wedged_wal_flusher_trips_and_dump_names_wal(
            self, tmp_path):
        """THE acceptance path: arm a delay failpoint on wal.append
        (the leader flush wedges mid-write, exactly like a hung disk),
        let records go dirty, and the watchdog must trip wal_flusher
        and produce a blackbox dump whose WAL section names the wedged
        WAL file with its pending bytes."""
        bb = Blackbox(str(tmp_path / "bb"),
                      state_fn=lambda: {
                          "wal": storage_wal.flusher_health()},
                      interval_s=60.0, node="n1")
        wd = Watchdog(blackbox=bb, wal_stall_s=0.15,
                      deadline_grace_s=0, gossip_silence_s=0,
                      queue_stall_s=0, retrip_s=60.0)
        f = open(tmp_path / "wedged-wal", "ab")
        wal = storage_wal.GroupCommitWal(f, fsync_policy="none")
        before = obs_metrics.WATCHDOG_TRIPS.labels("wal_flusher").value
        try:
            with failpoints.injected("wal.append", "delay(1.5s)*1"):
                wal.append(b"y" * storage_wal.OP_SIZE)
                # A flush attempt wedges in the delayed leader write;
                # run it in a side thread like the background flusher.
                t = threading.Thread(target=lambda: wal.flush(None),
                                     daemon=True)
                t.start()
                deadline = time.time() + 5.0
                fired = []
                while time.time() < deadline and not fired:
                    time.sleep(0.05)
                    fired = [c for c, _ in wd.check()
                             if c == "wal_flusher"]
                assert fired, storage_wal.flusher_health()
                t.join(timeout=10)
        finally:
            wal.close()
            f.close()
        assert obs_metrics.WATCHDOG_TRIPS.labels(
            "wal_flusher").value == before + 1
        dumps = bb.dumps()
        assert dumps, "watchdog trip produced no blackbox dump"
        with open(dumps[-1]) as fh:
            doc = json.load(fh)
        assert doc["cause"] == "watchdog:wal_flusher"
        wal_state = doc["current"]["wal"]
        named = [w["file"] for w in wal_state["wals"]]
        assert str(tmp_path / "wedged-wal") in named, wal_state
        assert wal_state["oldestDirtyAgeS"] > 0.15
        bb.stop()

    def test_stuck_query_trips_and_force_keeps_trace(self, tmp_path):
        registry = QueryRegistry()
        tracer = Tracer(enabled=False)
        disk = SegmentRing(str(tmp_path / "traces"))
        sampler = _quiet_sampler(disk=disk)
        wd = Watchdog(registry=registry, tracer=tracer,
                      sampler=sampler, wal_stall_s=0,
                      deadline_grace_s=0.05, gossip_silence_s=0,
                      queue_stall_s=0, retrip_s=60.0)
        ctx = QueryContext(pql="Count(...)", timeout_s=0.01)
        registry.register(ctx)
        ctx.state = "running"
        trace = tracer.start(ctx, node="n1")
        with trace.span("execute"):
            pass
        time.sleep(0.1)  # now well past deadline + grace
        fired = wd.check()
        assert [c for c, _ in fired] == ["stuck_query"]
        # The in-flight trace was force-kept and persisted.
        assert trace.keep_reason == "watchdog"
        assert any(t["id"] == ctx.id for t in tracer.traces())
        assert any(r["id"] == ctx.id for r in disk.scan())
        registry.finish(ctx)
        disk.close()

    def test_admission_stall_and_gossip_silence(self):
        adm = AdmissionController(concurrency=1, queue_depth=4)
        wd = Watchdog(admission=adm, gossip_age_fn=lambda: 120.0,
                      wal_stall_s=0, deadline_grace_s=0,
                      gossip_silence_s=30.0, queue_stall_s=0.05,
                      retrip_s=60.0)
        slot = adm.acquire("read")
        waiter_in = threading.Event()

        def waiter():
            waiter_in.set()
            s = adm.acquire("read", None)
            s.release()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        waiter_in.wait(1)
        time.sleep(0.15)  # queued, no grant for > queue_stall_s
        causes = {c for c, _ in wd.check()}
        assert causes == {"gossip_silence", "admission_stall"}
        # Rate limit: an immediate re-check does not re-trip.
        assert wd.check() == []
        slot.release()
        t.join(timeout=5)

    def test_quiet_system_never_trips(self):
        wd = Watchdog(admission=AdmissionController(),
                      registry=QueryRegistry(),
                      gossip_age_fn=lambda: None)
        assert wd.check() == []
        snap = wd.snapshot()
        assert snap["trips"] == 0


# -- handler routes ------------------------------------------------------------


def _call(app, method, path, body=b""):
    if "?" in path:
        path, _, qs = path.partition("?")
    else:
        qs = ""
    environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
               "QUERY_STRING": qs, "CONTENT_LENGTH": str(len(body)),
               "wsgi.input": io.BytesIO(body)}
    out = {}

    def start_response(status, hs):
        out["status"] = int(status.split()[0])

    chunks = app(environ, start_response)
    return out["status"], b"".join(chunks)


class TestBlackboxRoutes:
    def test_routes(self, tmp_path):
        from pilosa_tpu.server.handler import Handler
        bb = Blackbox(str(tmp_path / "bb"),
                      state_fn=lambda: {"k": 1}, interval_s=60.0)
        bb.snapshot("periodic")
        wd = Watchdog(blackbox=bb, wal_stall_s=0, deadline_grace_s=0,
                      gossip_silence_s=0, queue_stall_s=0)
        h = Handler(None, None, blackbox=bb, watchdog=wd)
        status, body = _call(h, "GET", "/debug/blackbox")
        assert status == 200
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert doc["recent"][0]["k"] == 1
        assert "watchdog" in doc
        status, body = _call(h, "POST", "/debug/blackbox/dump")
        assert status == 200
        assert os.path.exists(json.loads(body)["dumped"])
        bb.stop()

    def test_routes_without_recorder(self):
        from pilosa_tpu.server.handler import Handler
        h = Handler(None, None)
        status, body = _call(h, "GET", "/debug/blackbox")
        assert status == 200
        assert json.loads(body)["enabled"] is False
        status, _ = _call(h, "POST", "/debug/blackbox/dump")
        assert status == 404
