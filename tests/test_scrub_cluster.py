"""Chaos legs: storage integrity in a REAL 3-node replicas=2 gossip
cluster (ISSUE 15 acceptance).

- ``test_bitflip_restart_detect_quarantine_autorepair``: random bytes
  flipped in one node's fragment data files ON DISK, the node
  restarted — detection at open, quarantine, transparent read
  failover (differential-checked exact answers from every node
  throughout), then AUTOMATIC repair from the replicas, proven by the
  quarantine draining and the repaired node answering exactly from
  its own copy.
- ``test_live_scrub_detects_and_repairs_without_restart``: bytes
  flipped under a RUNNING node's mmap'd fragment, caught by a
  triggered scrub pass (no restart), repaired the same way.

Marked ``slow`` + ``chaos`` + ``scrub`` (multi-process); the fast
failpoint-driven legs run tier-1 in tests/test_scrub.py.
"""

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402

pytestmark = [pytest.mark.slow, pytest.mark.chaos, pytest.mark.scrub]

N_SLICES = 6
N_ROWS = 8


def _post(host, path, body=b"", timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=timeout).read()


def _get_json(host, path, timeout=10):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


def _count(host, row, timeout=30):
    got = json.loads(_post(
        host, "/index/sc/query",
        f'Count(Bitmap(frame="f", rowID={row}))'.encode(),
        timeout=timeout))
    assert "error" not in got, got
    return got["results"][0]


def _metric(host, name):
    total = 0.0
    found = False
    with urllib.request.urlopen(f"http://{host}/metrics",
                                timeout=10) as r:
        for line in r.read().decode().splitlines():
            if line.startswith(name) and not line.startswith("#"):
                total += float(line.rsplit(" ", 1)[1])
                found = True
    return total if found else None


class _Cluster:
    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.ports = {n: free_port() for n in "abc"}
        self.gports = {n: free_port() for n in "abc"}
        self.hosts = {n: f"127.0.0.1:{self.ports[n]}" for n in "abc"}
        self.procs: dict[str, subprocess.Popen] = {}
        self.logs = []
        self.host_list = ",".join(self.hosts[n] for n in "abc")

    def data_dir(self, name):
        return self.tmp_path / name

    def spawn(self, name, seed=""):
        d = self.data_dir(name)
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env["PILOSA_TPU_WARMUP"] = "0"
        env["PILOSA_FAULT_BREAKER_BACKOFF"] = "0.2s"
        env["PILOSA_FAULT_BREAKER_BACKOFF_CAP"] = "1s"
        env["PILOSA_FAULT_SEED"] = "12345"
        # Fast repair cadence; passive scrub passes stay off-cadence
        # (the tests trigger them explicitly).
        env["PILOSA_SCRUB_INTERVAL"] = "600s"
        env["PILOSA_SCRUB_PACE"] = "0s"
        env["PILOSA_SCRUB_REPAIR_RESCAN"] = "0.5s"
        log = open(self.tmp_path / f"{name}.log", "a")
        self.logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", self.hosts[name],
                "--cluster.type", "gossip",
                "--cluster.hosts", self.host_list,
                "--cluster.replicas", "2",
                "--cluster.internal-port", str(self.gports[name]),
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        self.procs[name] = p
        wait_up(self.hosts[name])
        return self.hosts[name]

    def kill(self, name):
        p = self.procs.pop(name)
        p.send_signal(signal.SIGKILL)
        p.wait()

    def fragment_files(self, name):
        out = []
        for root, _dirs, files in os.walk(self.data_dir(name)):
            if os.path.basename(root) != "fragments":
                continue
            for f in files:
                if f.isdigit():
                    out.append(os.path.join(root, f))
        return out

    def close(self):
        for p in self.procs.values():
            try:
                p.send_signal(signal.SIGINT)
            except OSError:
                pass
        for p in self.procs.values():
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for log in self.logs:
            log.close()


@pytest.fixture
def cluster(tmp_path):
    c = _Cluster(tmp_path)
    c.spawn("a")
    c.spawn("b", seed=f"127.0.0.1:{c.gports['a']}")
    c.spawn("c", seed=f"127.0.0.1:{c.gports['a']}")
    yield c
    c.close()


def _seed_data(cluster):
    """Spread bits over N_SLICES so every node owns slices, return the
    row→count model."""
    host_a = cluster.hosts["a"]
    _post(host_a, "/index/sc", b"{}")
    _post(host_a, "/index/sc/frame/f", b"{}")
    rng = random.Random(7)
    model = {r: set() for r in range(N_ROWS)}
    lines = []
    for _ in range(4000):
        r = rng.randrange(N_ROWS)
        col = rng.randrange(N_SLICES * SLICE_WIDTH)
        model[r].add(col)
        lines.append(f'SetBit(frame="f", rowID={r}, columnID={col})')
        if len(lines) >= 500:
            _post(host_a, "/index/sc/query",
                  "\n".join(lines).encode())
            lines = []
    if lines:
        _post(host_a, "/index/sc/query", "\n".join(lines).encode())
    return model


def _differential(hosts, model):
    for h in hosts:
        for row in sorted(model):
            got = _count(h, row)
            assert got == len(model[row]), (h, row, got,
                                            len(model[row]))


def _flip_bytes(path, n, rng):
    size = os.path.getsize(path)
    if size < 16:
        return 0
    with open(path, "r+b") as f:
        for _ in range(n):
            off = rng.randrange(size)
            f.seek(off)
            b = f.read(1)[0]
            f.seek(off)
            f.write(bytes([b ^ (1 << rng.randrange(8))]))
    return n


def _wait_quarantine_drained(host, timeout=90.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        last = _get_json(host, "/debug/integrity")
        if not last["quarantined"]:
            return last
        time.sleep(0.5)
    raise AssertionError(f"quarantine never drained: {last}")


def test_bitflip_restart_detect_quarantine_autorepair(cluster):
    """THE acceptance leg: raw on-disk bit flips on one node are
    detected at reopen, quarantined, served around with zero wrong
    answers, and automatically repaired from the replicas."""
    hosts = [cluster.hosts[n] for n in "abc"]
    model = _seed_data(cluster)
    _differential(hosts, model)

    # Kill B; rot EVERY fragment data file it owns; restart it.
    cluster.kill("b")
    rng = random.Random(99)
    files = cluster.fragment_files("b")
    assert files, "node b owns fragments"
    for f in files:
        _flip_bytes(f, 5, rng)
    host_b = cluster.spawn("b", seed=f"127.0.0.1:{cluster.gports['a']}")

    # Detection: the reopen quarantined at least one fragment (a flip
    # can land in an already-superseded WAL byte, but 5 flips x every
    # file makes zero detections practically impossible).
    integ = _get_json(host_b, "/debug/integrity")
    assert integ["quarantined"], integ
    n_quarantined = len(integ["quarantined"])
    assert _metric(host_b,
                   "pilosa_storage_corruption_detected_total") >= 1

    # Zero wrong answers THROUGHOUT, under CONCURRENT load: a
    # differential checker hammers every node from a background
    # thread across the whole quarantine → repair window; any wrong
    # count or error fails the test.
    stop = threading.Event()
    violations: list = []

    def loadgen():
        rng_l = random.Random(3)
        while not stop.is_set():
            h = hosts[rng_l.randrange(len(hosts))]
            row = rng_l.randrange(N_ROWS)
            try:
                got = _count(h, row)
            except Exception as e:  # noqa: BLE001 - surfaced below
                violations.append((h, row, repr(e)))
                continue
            if got != len(model[row]):
                violations.append((h, row, got, len(model[row])))

    loader = threading.Thread(target=loadgen)
    loader.start()
    try:
        _differential(hosts, model)
        # Automatic repair: the quarantine drains without any
        # operator action and repairs are counted.
        _wait_quarantine_drained(host_b)
    finally:
        stop.set()
        loader.join()
    assert not violations, violations[:5]
    assert _metric(host_b, "pilosa_storage_repairs_total") \
        >= n_quarantined
    _differential(hosts, model)

    # The repaired node's state survives another restart cleanly (no
    # stale quarantine sentinel, no corruption).
    cluster.kill("b")
    host_b = cluster.spawn("b", seed=f"127.0.0.1:{cluster.gports['a']}")
    integ = _get_json(host_b, "/debug/integrity")
    assert not integ["quarantined"], integ
    _differential(hosts, model)


def test_live_scrub_detects_and_repairs_without_restart(cluster):
    """The scrub leg: bytes flipped under a RUNNING node (bit rot on
    disk below a warm mmap) are caught by a scrub pass, quarantined,
    and repaired — no restart involved."""
    hosts = [cluster.hosts[n] for n in "abc"]
    model = _seed_data(cluster)
    host_c = cluster.hosts["c"]

    # A clean triggered pass first: no false positives on live files
    # with concurrent WAL appends.
    out = _get_json(host_c, "/debug/integrity")
    assert not out["quarantined"]
    summary = json.loads(_post(host_c,
                               "/debug/integrity/scrub?sync=1"))
    assert summary["corrupt"] == 0 and summary["fragments"] >= 1

    # Failpoint leg: the `corrupt` mode armed over HTTP rots a real
    # file at the storage.read site of the NEXT scrub re-read —
    # detection, quarantine, and auto-repair all fire from the seeded
    # injection alone.
    _post(host_c, "/debug/failpoints",
          json.dumps({"site": "storage.read",
                      "spec": "corrupt(8)*1"}).encode())
    summary = json.loads(_post(host_c,
                               "/debug/integrity/scrub?sync=1"))
    assert summary["corrupt"] >= 1, summary
    _differential(hosts, model)
    _wait_quarantine_drained(host_c)
    assert _metric(host_c, "pilosa_storage_repairs_total") >= 1
    _differential(hosts, model)

    # Raw-flip leg: rot one on-disk fragment file by hand. Flip many
    # bits INSIDE the file body so at least one lands in a live
    # region regardless of layout.
    rng = random.Random(5)
    files = cluster.fragment_files("c")
    target = max(files, key=os.path.getsize)
    _flip_bytes(target, 16, rng)

    summary = json.loads(_post(host_c,
                               "/debug/integrity/scrub?sync=1"))
    assert summary["corrupt"] >= 1, summary
    assert _metric(host_c,
                   "pilosa_storage_corruption_detected_total") >= 1
    # (No assertion that the quarantine is still VISIBLE here — the
    # repairer wakes on the quarantine hook and can finish the
    # re-stream before the next poll. That speed is the feature.)

    # Exact answers everywhere while quarantined/repairing, then the
    # quarantine fully drained.
    _differential(hosts, model)
    _wait_quarantine_drained(host_c)
    _differential(hosts, model)
    # And the repaired file verifies clean on a fresh pass.
    summary = json.loads(_post(host_c,
                               "/debug/integrity/scrub?sync=1"))
    assert summary["corrupt"] == 0
