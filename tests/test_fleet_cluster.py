"""Fleet observability on a REAL 2-node gossip cluster (ISSUE 13
acceptance): one ``GET /metrics/cluster`` scrape returns both nodes'
merged families in a single coordinator round trip; a SIGSTOPped peer
degrades to a marked partial rollup instead of hanging; the on-disk
metric history survives SIGKILL + restart; build identities ride
gossip so version skew is observable from any member."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.obs import federate  # noqa: E402


def _post(host, path, body=b"", timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=timeout)


def _get(host, path, timeout=15):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


def _get_json(host, path, timeout=15):
    _st, _hd, body = _get(host, path, timeout)
    return json.loads(body)


@pytest.fixture
def cluster(tmp_path):
    """Two gossip-joined nodes with the history sampler on an
    accelerated cadence (0.25 s base resolution) so a short test
    accumulates real multi-tick series. The sentinel is off — this
    leg exercises the history/federation plane, not the rules."""
    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs, logs = {}, []

    def spawn(name, port, internal, seed=""):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env["PILOSA_TPU_WARMUP"] = "0"
        env["PILOSA_METRICS_RUNTIME_INTERVAL"] = "0.25s"
        env["PILOSA_HISTORY_RESOLUTIONS"] = "0.25s:400,1s:200,5s:100"
        env["PILOSA_METRICS_FEDERATE_TIMEOUT"] = "1s"
        env["PILOSA_SENTINEL_ENABLED"] = "0"
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--cluster.type", "gossip",
                "--cluster.hosts", hosts,
                "--cluster.replicas", "1",
                "--cluster.internal-port", str(internal),
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        procs[name] = p
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    host_a = spawn("a", pa, ga)
    host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
    _post(host_a, "/index/fl", b"{}")
    _post(host_a, "/index/fl/frame/f", b"{}")

    import numpy as np

    from pilosa_tpu.cluster.client import Client
    client = Client(host_a)
    cols = np.arange(0, 4 * SLICE_WIDTH,
                     SLICE_WIDTH // 8).astype(np.uint64)
    client.import_arrays("fl", "f", np.ones(len(cols), np.uint64),
                         cols)
    deadline = time.time() + 30
    got = None
    while time.time() < deadline:
        with _post(host_a, "/index/fl/query",
                   b'Count(Bitmap(frame="f", rowID=1))') as r:
            got = json.loads(r.read())["results"][0]
        if got == len(cols):
            break
        time.sleep(0.3)
    assert got == len(cols), got

    yield {"a": host_a, "b": host_b, "procs": procs,
           "respawn_a": lambda: spawn("a", pa, ga,
                                      seed=f"127.0.0.1:{gb}")}

    for p in procs.values():
        try:
            p.send_signal(signal.SIGINT)
        except OSError:
            pass
    for p in procs.values():
        try:
            p.wait(timeout=20)
        except subprocess.TimeoutExpired:
            p.kill()
    for log in logs:
        log.close()


def test_fleet_federation_partial_and_history_survival(cluster):
    host_a, host_b = cluster["a"], cluster["b"]

    # Traffic on BOTH nodes so each registry has its own counts.
    for host in (host_a, host_b):
        for _ in range(5):
            with _post(host, "/index/fl/query",
                       b'Count(Bitmap(frame="f", rowID=1))') as r:
                r.read()
    # A few history ticks at the 0.25s cadence.
    time.sleep(1.5)

    # -- one /metrics/cluster scrape merges both nodes ------------------------
    st, headers, body = _get(host_a, "/metrics/cluster")
    assert st == 200
    assert headers["X-Pilosa-Federated-Nodes"] == "2"
    fams = federate.parse_exposition(body.decode())
    # Counters summed: the cluster-wide query count >= each node's own.
    merged_queries = sum(
        v for _n, _l, v in fams["pilosa_query_requests_total"][
            "samples"])
    per_node = []
    for host in (host_a, host_b):
        _st, _hd, raw = _get(host, "/metrics")
        own = federate.parse_exposition(raw.decode())
        per_node.append(sum(
            v for _n, _l, v in own.get(
                "pilosa_query_requests_total",
                {"samples": []})["samples"]))
    assert merged_queries >= max(per_node)
    assert all(n > 0 for n in per_node)
    # Gauges per-node: the build-info gauge names BOTH nodes.
    build_nodes = {labels.get("node")
                   for _n, labels, _v in fams["pilosa_build_info"][
                       "samples"]}
    assert {host_a, host_b} <= build_nodes, build_nodes
    # Histograms merged: bucket counts from both nodes summed.
    hist_count = sum(
        v for n, _l, v in fams["pilosa_query_duration_seconds"][
            "samples"] if n.endswith("_count"))
    assert hist_count >= 10

    # -- /debug/cluster rollup: builds, epoch, skew, gossip builds ------------
    doc = _get_json(host_a, "/debug/cluster")
    assert set(doc["nodes"]) == {host_a, host_b}
    assert doc["versionSkew"] is False
    assert doc["versions"][host_a] == doc["versions"][host_b] != ""
    for host, block in doc["nodes"].items():
        assert block["build"]["version"]
        assert "wal" in block and "admission" in block
        assert block["resize"]["phase"] == "idle"
    # The gossip build piggyback: each node learned its peer's build
    # identity through push/pull, no HTTP scrape required.
    local_a = _get_json(host_a, "/debug/cluster?local=1")
    assert host_b in (local_a.get("gossipBuilds") or {}), local_a.get(
        "gossipBuilds")

    # -- history federates across the fleet -----------------------------------
    doc = _get_json(
        host_a, "/debug/metrics/history?scope=cluster"
                "&family=pilosa_query_requests_total&window=60s")
    nodes_seen = {s["node"] for s in doc["series"]}
    assert {host_a, host_b} <= nodes_seen, nodes_seen

    # -- SIGSTOPped peer: partial, marked, bounded ----------------------------
    proc_b = cluster["procs"]["b"]
    proc_b.send_signal(signal.SIGSTOP)
    try:
        t0 = time.time()
        try:
            st, headers, body = _get(host_a, "/metrics/cluster",
                                     timeout=30)
        except urllib.error.HTTPError as e:
            st, body = e.code, e.read()
        elapsed = time.time() - t0
        assert st == 503, (st, body[:200])
        assert host_b.encode() in body
        # Bounded by the 1s per-peer federate timeout, not a hang.
        assert elapsed < 15, elapsed
        st, headers, body = _get(host_a, "/metrics/cluster?partial=1",
                                 timeout=30)
        assert st == 200
        assert headers["X-Pilosa-Partial-Nodes"] == host_b
        fams = federate.parse_exposition(body.decode())
        build_nodes = {labels.get("node") for _n, labels, _v in
                       fams["pilosa_build_info"]["samples"]}
        assert host_a in build_nodes and host_b not in build_nodes
        # The rollup degrades the same way.
        doc = _get_json(host_a, "/debug/cluster?partial=1",
                        timeout=30)
        assert doc["missing"] == [host_b]
        assert host_a in doc["nodes"]
    finally:
        proc_b.send_signal(signal.SIGCONT)

    # -- history survives SIGKILL + restart -----------------------------------
    # More ticks, then kill -9: reopen must serve the pre-kill series
    # minus at most the unflushed tail.
    time.sleep(1.0)
    pre_kill = _get_json(
        host_a, "/debug/metrics/history"
                "?family=pilosa_query_requests_total&window=60s")
    pre_points = [tuple(p) for s in pre_kill["series"]
                  for p in s["points"]]
    assert pre_points, pre_kill
    kill_at = time.time()
    proc_a = cluster["procs"]["a"]
    proc_a.kill()
    proc_a.wait(timeout=20)
    host_a = cluster["respawn_a"]()
    # window 90s stays inside the 0.25s*400 base-ring span, so the
    # reopened BASE resolution is what answers (the acceptance shape).
    post = _get_json(
        host_a, "/debug/metrics/history"
                "?family=pilosa_query_requests_total&window=90s")
    post_points = [tuple(p) for s in post["series"]
                   for p in s["points"]]
    survived = [p for p in post_points if p[0] < kill_at]
    # All but the unflushed tail of the pre-kill ticks persisted.
    assert len(survived) >= max(1, len(pre_points) - 3), (
        len(survived), len(pre_points))
