"""Unit tests for parallel.pod's placement and failure semantics —
the parts the 2-process end-to-end test (test_pod.py) can't easily
exercise: partial-broadcast poisoning, divergence detection, and the
max-shard padding for unbalanced slice lists. Pod instances are built
without jax.distributed via Pod._init_state.
"""

import pytest

from pilosa_tpu.errors import PilosaError
from pilosa_tpu.parallel import pod as pod_mod


def make_pod(pid=0, n=2, peers=None, holder=None):
    p = pod_mod.Pod.__new__(pod_mod.Pod)
    p._init_state(holder, pid, n,
                  peers or [f"h{i}:1" for i in range(n)])
    p.timeout = 1.0
    return p


class TestPlacement:
    def test_owner_round_robin(self):
        p = make_pod(n=3)
        assert [p.owner_pid(s) for s in range(6)] == [0, 1, 2, 0, 1, 2]

    def test_owned_filters_and_sorts(self):
        p = make_pod(pid=1, n=2)
        assert p.owned([5, 3, 0, 1, 7]) == [1, 3, 5, 7]

    def test_max_shard_balances_unbalanced_lists(self):
        p = make_pod(n=2)
        # [1,3,5,7] all land on pid 1 — shard length must cover it.
        assert p.max_shard_slices([1, 3, 5, 7]) == 4
        assert p._local_slices([1, 3, 5, 7]) == [-1, -1, -1, -1]
        p1 = make_pod(pid=1, n=2)
        assert p1._local_slices([1, 3, 5, 7]) == [1, 3, 5, 7]
        # Mixed list: pid0 owns 2, pid1 owns 1 → both pad to 2.
        assert p.max_shard_slices([0, 2, 3]) == 2
        assert p._local_slices([0, 2, 3]) == [0, 2]
        assert p1._local_slices([0, 2, 3]) == [3, -1]

    def test_empty(self):
        p = make_pod()
        assert p.max_shard_slices([]) == 0


class TestDispatchFailureSemantics:
    def test_unreachable_worker_before_any_delivery_not_poisoned(self):
        """No worker got the item → nothing entered a collective →
        retrying later is safe (not poisoned)."""
        p = make_pod(n=2)

        def never_delivers(pid, method, path, body, ctype, sent=None):
            raise OSError("connection refused")

        p._request = never_delivers
        with pytest.raises(PilosaError, match="not reachable"):
            p._dispatch({"kind": "count_expr", "index": "i", "expr": [],
                         "leaves": [], "slices": [0, 1]})
        assert not p._poisoned
        with pytest.raises(PilosaError, match="not reachable"):
            p._dispatch({"kind": "count_expr", "index": "i", "expr": [],
                         "leaves": [], "slices": [0, 1]})

    def test_partial_delivery_poisons(self):
        """One worker got the item, another didn't → the delivered one
        is parked in an orphaned collective; the device path must shut
        off for the pod's lifetime."""
        p = make_pod(n=3)

        def one_delivers(pid, method, path, body, ctype, sent=None):
            if pid == 1:
                if sent is not None:
                    sent.set()
                return b'{"total": 0}'
            raise OSError("connection refused")

        p._request = one_delivers
        with pytest.raises(PilosaError, match="disabled"):
            p._dispatch({"kind": "count_expr", "index": "i", "expr": [],
                         "leaves": [], "slices": [0, 1]})
        assert p._poisoned
        with pytest.raises(PilosaError, match="disabled"):
            p._dispatch({"kind": "count_expr", "index": "i", "expr": [],
                         "leaves": [], "slices": [0, 1]})

    def test_collective_failure_poisons(self):
        p = make_pod(n=2)

        def delivers(pid, method, path, body, ctype, sent=None):
            if sent is not None:
                sent.set()
            return b'{"total": 7}'

        p._request = delivers

        def boom(item):
            raise RuntimeError("gloo timeout")

        p.run_item = boom
        with pytest.raises(RuntimeError, match="gloo timeout"):
            p._dispatch({"kind": "count_expr", "index": "i", "expr": [],
                         "leaves": [], "slices": [0]})
        assert p._poisoned

    def test_divergent_worker_result_raises(self):
        p = make_pod(n=2)

        def delivers(pid, method, path, body, ctype, sent=None):
            if sent is not None:
                sent.set()
            return b'{"total": 999}'

        p._request = delivers
        p.run_item = lambda item: {"total": 7}
        with pytest.raises(PilosaError, match="divergence"):
            p._dispatch({"kind": "count_expr", "index": "i", "expr": [],
                         "leaves": [], "slices": [0]})

    def test_agreeing_results_succeed(self):
        p = make_pod(n=2)

        def delivers(pid, method, path, body, ctype, sent=None):
            if sent is not None:
                sent.set()
            return b'{"total": 7}'

        p._request = delivers
        p.run_item = lambda item: {"total": 7}
        out = p._dispatch({"kind": "count_expr", "index": "i", "expr": [],
                           "leaves": [], "slices": [0]})
        assert out == {"total": 7}
        assert not p._poisoned
