"""Time quantum tests — exact expected covers from reference time_test.go."""

import datetime as dt

import pytest

from pilosa_tpu.errors import PilosaError
from pilosa_tpu.utils import timequantum as tq


def T(s):
    return dt.datetime.strptime(s, "%Y-%m-%d %H:%M")


class TestParse:
    def test_valid(self):
        for q in ["Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH",
                  "H", ""]:
            assert tq.parse_time_quantum(q.lower()) == q

    def test_invalid(self):
        with pytest.raises(PilosaError):
            tq.parse_time_quantum("YH")


class TestViewsByTime:
    def test_units(self):
        t = T("2017-01-02 03:00")
        assert tq.views_by_time("std", t, "YMDH") == [
            "std_2017", "std_201701", "std_20170102", "std_2017010203"]


# Expected lists transcribed from reference time_test.go:88-148.
RANGE_CASES = [
    ("Y", "2000-01-01 00:00", "2002-01-01 00:00",
     ["F_2000", "F_2001"]),
    ("YM", "2000-11-01 00:00", "2003-03-01 00:00",
     ["F_200011", "F_200012", "F_2001", "F_2002", "F_200301", "F_200302"]),
    ("YMD", "2000-11-28 00:00", "2003-03-02 00:00",
     ["F_20001128", "F_20001129", "F_20001130", "F_200012", "F_2001",
      "F_2002", "F_200301", "F_200302", "F_20030301"]),
    ("YMDH", "2000-11-28 22:00", "2002-03-01 03:00",
     ["F_2000112822", "F_2000112823", "F_20001129", "F_20001130",
      "F_200012", "F_2001", "F_200201", "F_200202", "F_2002030100",
      "F_2002030101", "F_2002030102"]),
    ("M", "2000-01-01 00:00", "2000-03-01 00:00",
     ["F_200001", "F_200002"]),
    ("MD", "2000-11-29 00:00", "2002-02-03 00:00",
     ["F_20001129", "F_20001130", "F_200012", "F_200101", "F_200102",
      "F_200103", "F_200104", "F_200105", "F_200106", "F_200107",
      "F_200108", "F_200109", "F_200110", "F_200111", "F_200112",
      "F_200201", "F_20020201", "F_20020202"]),
    ("MDH", "2000-11-29 22:00", "2002-03-02 03:00",
     ["F_2000112922", "F_2000112923", "F_20001130", "F_200012", "F_200101",
      "F_200102", "F_200103", "F_200104", "F_200105", "F_200106",
      "F_200107", "F_200108", "F_200109", "F_200110", "F_200111",
      "F_200112", "F_200201", "F_200202", "F_20020301", "F_2002030200",
      "F_2002030201", "F_2002030202"]),
    ("D", "2000-01-01 00:00", "2000-01-04 00:00",
     ["F_20000101", "F_20000102", "F_20000103"]),
    ("H", "2000-01-01 00:00", "2000-01-01 02:00",
     ["F_2000010100", "F_2000010101"]),
]


class TestViewsByTimeRange:
    @pytest.mark.parametrize("q,start,end,want", RANGE_CASES,
                             ids=[c[0] for c in RANGE_CASES])
    def test_cover(self, q, start, end, want):
        assert tq.views_by_time_range("F", T(start), T(end), q) == want

    def test_dh_leap_february(self):
        # the long DH case spanning Feb 2000 (leap year), spot-check shape
        got = tq.views_by_time_range("F", T("2000-01-01 22:00"),
                                     T("2000-03-01 02:00"), "DH")
        assert got[0] == "F_2000010122"
        assert "F_20000229" in got          # leap day present
        assert got[-1] == "F_2000030101"
        assert len(got) == 63  # 2h + 30d + 29d + 2h

    def test_empty_range(self):
        assert tq.views_by_time_range("F", T("2000-01-01 00:00"),
                                      T("2000-01-01 00:00"), "YMDH") == []

    def test_leap_day_start(self):
        # Feb 29 start with Y quantum must normalize like Go AddDate,
        # not raise (code-review regression).
        got = tq.views_by_time_range("F", T("2016-02-29 00:00"),
                                     T("2018-01-01 00:00"), "Y")
        assert got == ["F_2016", "F_2017"]
