"""Device kernel layer tests: pack/unpack round-trips and parity between the
host roaring engine (semantics reference) and the XLA/Pallas kernels."""

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.ops import kernels, packed, pallas_kernels
from pilosa_tpu.storage.roaring import Bitmap


def rand_bitmap(rng, n, hi):
    return Bitmap.from_sorted(
        rng.choice(hi, size=n, replace=False).astype(np.uint64))


class TestPacking:
    def test_pack_dense_container_is_view_equal(self):
        # A dense container must blit: positions 0..65535 → all-ones words.
        b = Bitmap.from_sorted(np.arange(1 << 16, dtype=np.uint64))
        words = packed.pack_bitmap(b, packed.WORDS_PER_SLICE)
        assert np.all(words[:2048] == 0xFFFFFFFF)
        assert np.all(words[2048:] == 0)

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(1)
        b = rand_bitmap(rng, 10000, SLICE_WIDTH)
        words = packed.pack_bitmap(b, packed.WORDS_PER_SLICE)
        back = packed.unpack_to_bitmap(words)
        assert np.array_equal(back.values(), b.values())

    def test_pack_rows_layout(self):
        # storage positions pos = row*SLICE_WIDTH + col (fragment layout)
        storage = Bitmap(0, 31, 32, SLICE_WIDTH + 5, 3 * SLICE_WIDTH - 1)
        m = packed.pack_rows(storage, [0, 1, 2])
        assert m.shape == (3, packed.WORDS_PER_SLICE)
        assert m[0, 0] == (1 | (1 << 31))
        assert m[0, 1] == 1
        assert m[1, 0] == (1 << 5)
        assert m[2, -1] == (1 << 31)

    def test_pack_base_word_window(self):
        b = Bitmap(0, 100 * 32, 100 * 32 + 7)
        words = packed.pack_bitmap(b, 8, base_word=100)
        assert words[0] == (1 | (1 << 7))
        assert np.all(words[1:] == 0)


class TestKernelParity:
    @pytest.mark.parametrize("op,ref", [
        ("and", lambda a, b: a.intersect(b)),
        ("or", lambda a, b: a.union(b)),
        ("andnot", lambda a, b: a.difference(b)),
        ("xor", lambda a, b: a.xor(b)),
    ])
    def test_set_op_matches_roaring(self, op, ref):
        import jax

        from pilosa_tpu.parallel import mesh as mesh_mod
        rng = np.random.default_rng(kernels.OPS.index(op))
        a, b = (rand_bitmap(rng, 5000, SLICE_WIDTH) for _ in range(2))
        aw = packed.pack_bitmap(a, packed.WORDS_PER_SLICE)
        bw = packed.pack_bitmap(b, packed.WORDS_PER_SLICE)
        # The production materializing primitive: the expression
        # evaluator behind mesh.materialize_expr_sharded / count_expr.
        expr = (op, ("leaf", 0), ("leaf", 1))
        got = np.asarray(jax.jit(
            lambda leaves: mesh_mod._eval_expr(expr, leaves))(
                np.stack([aw, bw])))
        want = packed.pack_bitmap(ref(a, b), packed.WORDS_PER_SLICE)
        assert np.array_equal(got, want)
        # counts agree with the host engine too
        count = int(np.asarray(kernels.op_count_rows(op, aw, bw)))
        assert count == ref(a, b).count()

    def test_intersection_count_parity(self):
        rng = np.random.default_rng(9)
        a, b = (rand_bitmap(rng, 20000, SLICE_WIDTH) for _ in range(2))
        aw = packed.pack_bitmap(a, packed.WORDS_PER_SLICE)
        bw = packed.pack_bitmap(b, packed.WORDS_PER_SLICE)
        assert int(np.asarray(kernels.op_count_rows("and", aw, bw))) \
            == a.intersection_count(b)

    def test_row_block_and_topk(self):
        rng = np.random.default_rng(3)
        n_rows = 50
        storage = Bitmap.from_sorted(np.sort(rng.choice(
            n_rows * SLICE_WIDTH, size=100000, replace=False)
            .astype(np.uint64)))
        rows = packed.pack_rows(storage, range(n_rows))
        other = rand_bitmap(rng, 30000, SLICE_WIDTH)
        ow = packed.pack_bitmap(other, packed.WORDS_PER_SLICE)
        counts = np.asarray(kernels.row_block_op_count("and", rows, ow))
        # parity vs host roaring per row
        for r in range(0, n_rows, 7):
            row_bm = storage.offset_range(0, r * SLICE_WIDTH,
                                          (r + 1) * SLICE_WIDTH)
            assert counts[r] == row_bm.intersection_count(other)

    def test_popcount_rows(self):
        rng = np.random.default_rng(4)
        b = rand_bitmap(rng, 12345, SLICE_WIDTH)
        w = packed.pack_bitmap(b, packed.WORDS_PER_SLICE)
        assert int(np.asarray(kernels.popcount_rows(w))) == b.count()
        m = np.stack([w, np.zeros_like(w)])
        assert list(np.asarray(kernels.popcount_rows(m))) == [b.count(), 0]


class TestPallas:
    """Pallas kernels run in interpret mode off-TPU; parity vs XLA path."""

    @pytest.mark.parametrize("op", kernels.OPS)
    def test_pallas_count_parity(self, op):
        rng = np.random.default_rng(11)
        a = rng.integers(0, 1 << 32, (17, 5000), dtype=np.uint32)
        b = rng.integers(0, 1 << 32, (17, 5000), dtype=np.uint32)
        got = np.asarray(pallas_kernels.op_count_rows_pallas(
            op, a, b, interpret=True))
        want = np.asarray(kernels.op_count_rows(op, a, b))
        assert np.array_equal(got, want)

    def test_pallas_1d(self):
        rng = np.random.default_rng(12)
        a = rng.integers(0, 1 << 32, 4096, dtype=np.uint32)
        b = rng.integers(0, 1 << 32, 4096, dtype=np.uint32)
        got = int(np.asarray(pallas_kernels.op_count_rows_pallas(
            "and", a, b, interpret=True)))
        assert got == int(np.bitwise_count(a & b).sum())


class TestCountTotal:
    def test_no_int32_overflow(self):
        # >2^31 total bits must not wrap (code-review regression).
        a = np.full((70000 // 8, 8 * 1024), 0xFFFFFFFF, dtype=np.uint32)
        total = kernels.op_count_total("or", a, a)
        assert total == a.size * 32


class TestSparseWords:
    """Host-side sparse (word idx, word value) extraction — the upload
    payload of the device densify kernel (cold-path sparse uploads)."""

    def _storage(self):
        import numpy as np
        from pilosa_tpu import SLICE_WIDTH
        from pilosa_tpu.storage.roaring import Bitmap
        rng = np.random.default_rng(1)
        st = Bitmap()
        rows = rng.integers(0, 6, 30000).astype(np.uint64)
        cols = rng.integers(0, SLICE_WIDTH, 30000).astype(np.uint64)
        # row 0 also gets a dense run -> bitmap containers
        dense = np.sort(rng.choice(SLICE_WIDTH // 4, 150000,
                                   replace=False)).astype(np.uint64)
        st.add_many(np.unique(np.concatenate(
            [rows * SLICE_WIDTH + cols, dense])))
        return st

    def test_bucket_rows_matches_dense_pack(self):
        import numpy as np
        from pilosa_tpu.ops import packed
        st = self._storage()
        ids = [0, 1, 2, 3, 4, 5]
        dense = packed.pack_rows(st, ids)
        lanes, vals = packed.bucket_rows(st, ids)
        assert lanes.shape == vals.shape
        assert lanes.shape[1] == packed.WORDS_PER_SLICE // 128
        got = np.zeros_like(dense)
        for t in range(len(ids)):
            for s_grp in range(lanes.shape[1]):
                nz = vals[t, s_grp] != 0
                got[t, s_grp * 128 + lanes[t, s_grp][nz]] = \
                    vals[t, s_grp][nz]
        assert (got == dense).all()

    def test_bucket_then_densify_kernel(self):
        import numpy as np
        from pilosa_tpu.ops import packed
        from pilosa_tpu.ops.pallas_kernels import densify_pallas
        st = self._storage()
        ids = [0, 1, 5]
        dense = packed.pack_rows(st, ids)
        lanes, vals = packed.bucket_rows(st, ids)
        got = np.asarray(densify_pallas(
            lanes, vals, packed.WORDS_PER_SLICE, True))
        assert (got == dense).all()

    def test_sparse_words_empty(self):
        from pilosa_tpu.ops import packed
        from pilosa_tpu.storage.roaring import Bitmap
        idx, val = packed.sparse_words(Bitmap(), 32768)
        assert len(idx) == 0 and len(val) == 0
