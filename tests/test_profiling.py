"""Profiling subsystem tests: the sampled CPU profile, thread dump, the
/debug/pprof HTTP surface, and the --profile.cpu background profiler
(reference: net/http/pprof at handler.go:30,99; cmd/server.go:47-62)."""

from __future__ import annotations

import threading
import time

from pilosa_tpu.utils.profiling import (
    CPUProfiler,
    collect_sample,
    sample_profile,
    thread_dump,
)


def busy(stop: threading.Event) -> None:
    while not stop.is_set():
        sum(i * i for i in range(500))


def test_collect_sample_sees_other_threads():
    stop = threading.Event()
    t = threading.Thread(target=busy, args=(stop,), name="busy", daemon=True)
    t.start()
    try:
        stacks = collect_sample(skip_threads=(threading.get_ident(),))
        assert any("busy" in s for s in stacks), stacks
    finally:
        stop.set()
        t.join()


def test_sample_profile_collapsed_stacks():
    stop = threading.Event()
    t = threading.Thread(target=busy, args=(stop,), daemon=True)
    t.start()
    try:
        report = sample_profile(0.2, interval=0.002)
    finally:
        stop.set()
        t.join()
    lines = report.splitlines()
    assert lines[0].startswith("# cpu profile")
    # Collapsed-stack lines end with a sample count; busy() must appear.
    assert any("busy" in line and line.rsplit(" ", 1)[-1].isdigit()
               for line in lines[1:]), report


def test_thread_dump_lists_main_thread():
    dump = thread_dump()
    assert "MainThread" in dump
    assert "test_thread_dump_lists_main_thread" in dump


def test_cpu_profiler_writes_report(tmp_path):
    out = tmp_path / "cpu.prof"
    p = CPUProfiler(str(out), duration=10.0, interval=0.002)
    p.start()
    time.sleep(0.1)
    p.stop()
    text = out.read_text()
    assert text.startswith("# cpu profile")


def test_pprof_http_endpoints(tmp_path):
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.server.handler import Handler

    from test_handler import call

    h = Holder(str(tmp_path / "data"))
    h.open()
    try:
        handler = Handler(h, Executor(h, host="local"), host="local")
        status, _, body = call(handler, "GET", "/debug/pprof/")
        assert status == 200 and b"profile" in body
        status, _, body = call(handler, "GET",
                               "/debug/pprof/profile?seconds=0.1")
        assert status == 200 and body.startswith(b"# cpu profile")
        status, _, body = call(handler, "GET", "/debug/pprof/threads")
        assert status == 200 and b"MainThread" in body
        # Heap: GET is READ-ONLY (a monitoring scrape must not toggle
        # interpreter-wide allocation tracing); POST ?op=start|stop
        # arm/disarm.
        import tracemalloc
        status, _, body = call(handler, "GET", "/debug/pprof/heap")
        assert status == 200 and b"not tracing" in body
        assert not tracemalloc.is_tracing()  # the GET did not arm
        status, _, body = call(handler, "POST",
                               "/debug/pprof/heap?op=start")
        assert status == 200 and b"started" in body
        blob = bytearray(1 << 16)  # some traced allocations
        status, _, body = call(handler, "GET",
                               "/debug/pprof/heap?n=10")
        del blob
        assert status == 200 and b"traced memory" in body
        assert tracemalloc.is_tracing()  # the GET did not disarm
        status, _, body = call(handler, "POST",
                               "/debug/pprof/heap?op=stop")
        assert status == 200 and b"stopped" in body
        assert not tracemalloc.is_tracing()
        status, _, body = call(handler, "POST",
                               "/debug/pprof/heap?op=nope")
        assert status == 400
        # The old mutating GET ?off=1 shim is gone: GET ignores the
        # param and never disarms tracing.
        call(handler, "POST", "/debug/pprof/heap?op=start")
        status, _, body = call(handler, "GET",
                               "/debug/pprof/heap?off=1")
        assert status == 200 and b"DEPRECATED" not in body
        assert tracemalloc.is_tracing()
        call(handler, "POST", "/debug/pprof/heap?op=stop")
    finally:
        h.close()
