"""Streaming discipline on the bulk paths.

Reference: CSV export streams through a csv.Writer over ForEachBit
(handler.go:985-1025) and backup/restore stream through io.Copy
(client.go:463-674). These tests pin the equivalent guarantees: the
export body is a chunk generator, and a >100 MB slice round-trips
through backup/restore with bounded peak RSS (no whole-slice buffers).
"""

import gc
import io
import json
import os
import resource
import urllib.error
import urllib.request

import numpy as np

from pilosa_tpu.cluster.client import Client
from pilosa_tpu.server.server import Server
from pilosa_tpu.storage import roaring


def http_post(host, path, body=b"{}"):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.read()


class TestExportStreams:
    def test_export_body_is_a_chunk_generator(self, tmp_path):
        s = Server(str(tmp_path / "d"), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0)
        s.open()
        try:
            http_post(s.host, "/index/i")
            http_post(s.host, "/index/i/frame/f")
            for col in (3, 70000, 200000):
                http_post(s.host, "/index/i/query",
                          f'SetBit(frame="f", rowID=2, columnID={col})'
                          .encode())
            # Drive the WSGI app directly to observe the body type.
            chunks = s.handler(
                {"REQUEST_METHOD": "GET", "PATH_INFO": "/export",
                 "QUERY_STRING": "index=i&frame=f&view=standard&slice=0",
                 "HTTP_ACCEPT": "text/csv"}, lambda *a: None)
            assert not isinstance(chunks, list)  # generator, not buffer
            body = b"".join(chunks)
            assert body == b"2,3\r\n2,70000\r\n2,200000\r\n"
            # And end-to-end through the streaming client.
            out = io.StringIO()
            Client(s.host).export_csv_to(out, "i", "f", "standard", 0)
            assert out.getvalue() == "2,3\r\n2,70000\r\n2,200000\r\n"
        finally:
            s.close()


def build_big_fragment(path: str, containers: int = 13000) -> int:
    """Craft a >100 MB fragment file cheaply: `containers` dense bitmap
    containers (8 KB each) sharing one word pattern. Returns file size."""
    words = np.full(1024, 0xAAAAAAAAAAAAAAAA, dtype=np.uint64)
    n = int(np.bitwise_count(words).sum())
    bm = roaring.Bitmap()
    for key in range(containers):
        c = bm._container_or_create(key)
        c.array = None
        c.bitmap = words  # shared: write_to only reads it
        c.n = n
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        bm.write_to(f)
    return os.path.getsize(path)


class TestAbortedRestore:
    def test_truncated_restore_leaves_fragment_serving(self, tmp_path):
        """A restore body that dies mid-tar must not leave the fragment
        with storage closed (read_from reopens the old data file)."""
        s = Server(str(tmp_path / "d"), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0)
        s.open()
        try:
            http_post(s.host, "/index/i")
            http_post(s.host, "/index/i/frame/f")
            http_post(s.host, "/index/i/query",
                      b'SetBit(frame="f", rowID=1, columnID=9)')
            # A valid tar prefix, truncated mid-body.
            frag = s.holder.fragment("i", "f", "standard", 0)
            whole = io.BytesIO()
            frag.write_to(whole)
            truncated = whole.getvalue()[:700]  # header + partial data
            req = urllib.request.Request(
                f"http://{s.host}/fragment/data?index=i&frame=f"
                "&view=standard&slice=0", data=truncated, method="POST",
                headers={"Content-Type": "application/octet-stream"})
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("truncated restore must fail")
            except urllib.error.HTTPError as e:
                assert e.code == 500
            # The fragment still answers queries with the old data.
            _, body = http_post(s.host, "/index/i/query",
                                b'Bitmap(frame="f", rowID=1)')
            assert json.loads(body)["results"][0]["bits"] == [9]
            assert frag.set_bit(1, 10)  # and still accepts writes
        finally:
            s.close()


class TestBoundedRSS:
    def test_backup_restore_100mb_slice_bounded_rss(self, tmp_path):
        """Round-trip a >100 MB slice through client backup_to →
        restore_from against a live server in this process; after a warm
        pass, peak RSS must not grow by anything near the slice size
        (the old buffered paths held 100 MB+ several times over)."""
        s = Server(str(tmp_path / "d"), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0)
        s.open()
        try:
            http_post(s.host, "/index/bi")
            http_post(s.host, "/index/bi/frame/bf")
            http_post(s.host, "/index/bi/query",
                      b'SetBit(frame="bf", rowID=0, columnID=0)')
            frag_path = s.holder.fragment("bi", "bf", "standard", 0).path
            s.close()
            size = build_big_fragment(frag_path)
            assert size > 100 * 1024 * 1024, size

            s = Server(str(tmp_path / "d"), host="127.0.0.1:0",
                       anti_entropy_interval=0, polling_interval=0)
            s.open()
            client = Client(s.host)

            def round_trip(n):
                tar_path = tmp_path / f"backup{n}.tar"
                with open(tar_path, "wb") as f:
                    client.backup_to(f, "bi", "bf", "standard")
                assert os.path.getsize(tar_path) > 100 * 1024 * 1024
                with open(tar_path, "rb") as f:
                    client.restore_from(f, "bi", "bf", "standard")

            # Warm TWICE: page cache, pools, lazy imports — and glibc
            # malloc arenas. Each round's HTTP connections spawn fresh
            # server threads whose allocations land on per-thread
            # arenas; with threads left over from earlier tests in the
            # process (e.g. gossip suites) one warm round does not
            # touch every arena the measured round will, and the
            # unwarmed-arena growth (~100 MB) masquerades as a leak.
            round_trip(1)
            round_trip(2)
            gc.collect()
            base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            round_trip(3)
            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            delta_mb = (peak - base) / 1024  # ru_maxrss is KB on linux
            assert delta_mb < 48, f"peak RSS grew {delta_mb:.0f} MB"

            # The data survived the restore byte-exactly.
            frag = s.holder.fragment("bi", "bf", "standard", 0)
            assert frag.storage.count() == 13000 * 32768
        finally:
            s.close()
