"""Fleet observability (ISSUE 13): the on-disk metric history, the
cluster federation merge, the regression sentinel, and their handler
routes — docs/OBSERVABILITY.md is the operator-facing contract.

The chaos legs here drive the ``ring.write`` failpoint through the
HISTORY write site (the acceptance criterion): a torn tick record
costs exactly that tick, reopen serves the pre-kill series minus at
most the unflushed tail."""

import io
import json
import os
import threading
import time

import pytest

from pilosa_tpu.fault import failpoints
from pilosa_tpu.obs import federate
from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.obs.history import (MetricHistory, series_key,
                                    split_key)
from pilosa_tpu.obs.sentinel import Sentinel, robust_z
from pilosa_tpu.obs.trace import Tracer
from pilosa_tpu.server.handler import Handler


def call(app, method, path, body=b"", headers=None):
    if "?" in path:
        path, _, qs = path.partition("?")
    else:
        qs = ""
    environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
               "QUERY_STRING": qs, "CONTENT_LENGTH": str(len(body)),
               "wsgi.input": io.BytesIO(body)}
    for k, v in (headers or {}).items():
        environ["HTTP_" + k.upper().replace("-", "_")] = v
    out = {}

    def start_response(status, hs):
        out["status"] = int(status.split()[0])
        out["headers"] = dict(hs)

    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


RES = ((1.0, 100), (5.0, 40), (25.0, 20))


def _reg_with_families(tag):
    reg = obs_metrics.Registry()
    c = reg.counter(f"pilosa_test_{tag}_events_total", labels=("k",))
    g = reg.gauge(f"pilosa_test_{tag}_depth_value")
    h = reg.histogram(f"pilosa_test_{tag}_lat_seconds",
                      buckets=(0.001, 0.01, 0.1, 1.0))
    return reg, c, g, h


# -- the store -----------------------------------------------------------------


class TestMetricHistory:
    def test_counter_rate_gauge_value_histogram_quantiles(self):
        reg, c, g, h = _reg_with_families("a")
        hist = MetricHistory(resolutions=RES, registry=reg)
        t0 = 1000.0
        for i in range(10):
            c.labels("x").inc(5)
            g.set(i)
            h.observe(0.005)
            h.observe(0.05)
            hist.sample(now=t0 + i)
        out = hist.series("pilosa_test_a_events_total", window_s=60,
                          now=t0 + 10)
        (s,) = out["series"]
        assert s["labels"] == {"k": "x"}
        # 5 increments per 1s tick → rate 5/s (first tick has no
        # previous value, so 9 points).
        assert len(s["points"]) == 9
        assert all(abs(v - 5.0) < 1e-6 for _t, v in s["points"])
        out = hist.series("pilosa_test_a_depth_value", window_s=60,
                          now=t0 + 10)
        assert out["series"][0]["points"][-1][1] == 9.0
        out = hist.series("pilosa_test_a_lat_seconds", window_s=60,
                          now=t0 + 10)
        by_name = {s["name"]: s for s in out["series"]}
        # Two observations per tick, one in each of the first two
        # buckets: p50 = 0.01 bound, p99 = 0.1 bound, rate = 2/s.
        assert by_name["pilosa_test_a_lat_seconds:p50"][
            "points"][-1][1] == pytest.approx(0.01)
        assert by_name["pilosa_test_a_lat_seconds:p99"][
            "points"][-1][1] == pytest.approx(0.1)
        assert by_name["pilosa_test_a_lat_seconds:rate"][
            "points"][-1][1] == pytest.approx(2.0)

    def test_counter_reset_skips_tick_instead_of_negative_rate(self):
        reg, c, _g, _h = _reg_with_families("rst")
        hist = MetricHistory(resolutions=RES, registry=reg)
        child = c.labels("x")
        child.inc(10)
        hist.sample(now=100.0)
        child.inc(10)
        hist.sample(now=101.0)
        child._v = 0.0  # a restart-shaped reset
        hist.sample(now=102.0)
        child.inc(10)
        hist.sample(now=103.0)
        (s,) = hist.series("pilosa_test_rst_events_total",
                           window_s=60, now=104.0)["series"]
        assert all(v >= 0 for _t, v in s["points"]), s["points"]

    def test_base_ring_bounded_and_coarse_aggregates_means(self):
        reg, _c, g, _h = _reg_with_families("b")
        hist = MetricHistory(resolutions=RES, registry=reg)
        t0 = 5000.0
        for i in range(120):  # past the base cap of 100
            g.set(float(i % 10))
            hist.sample(now=t0 + i)
        (s,) = hist.series("pilosa_test_b_depth_value",
                           window_s=99, step_s=0,
                           now=t0 + 120)["series"]
        assert len(s["points"]) <= RES[0][1]
        # Step hint 5s selects the mid ring: bucket means of the
        # 0..9 sawtooth sit strictly inside (0, 9).
        out = hist.series("pilosa_test_b_depth_value", window_s=99,
                          step_s=5.0, now=t0 + 120)
        assert out["stepS"] == 5.0
        (sm,) = out["series"]
        assert sm["points"], sm
        assert all(0.0 < v < 9.0 for _t, v in sm["points"][1:-1])

    def test_resolution_pick_bumps_to_cover_window(self):
        hist = MetricHistory(resolutions=RES)
        assert hist._pick_resolution(30.0, 0.0) == 0
        assert hist._pick_resolution(150.0, 0.0) == 1  # > 1s*100 span
        assert hist._pick_resolution(900.0, 0.0) == 2  # > 5s*40 span
        assert hist._pick_resolution(30.0, 25.0) == 2  # step hint

    def test_series_cap_drops_new_series(self):
        reg = obs_metrics.Registry()
        c = reg.counter("pilosa_test_cap_events_total", labels=("k",))
        hist = MetricHistory(resolutions=RES, registry=reg,
                             max_series=16)
        for i in range(40):
            c.labels(f"k{i}").inc()
        hist.sample(now=100.0)
        for i in range(40):
            c.labels(f"k{i}").inc()
        hist.sample(now=101.0)
        assert len(hist.keys()) <= 16
        assert hist.dropped_series > 0

    def test_label_filter_and_key_round_trip(self):
        key = series_key("pilosa_x_y_total",
                         {"k": 'ho"sti\nle\\', "z": "1"})
        name, labels = split_key(key)
        assert name == "pilosa_x_y_total"
        assert labels == {"k": 'ho"sti\nle\\', "z": "1"}
        reg, c, _g, _h = _reg_with_families("lf")
        hist = MetricHistory(resolutions=RES, registry=reg)
        for k in ("a", "b"):
            c.labels(k).inc()
        hist.sample(now=100.0)
        for k in ("a", "b"):
            c.labels(k).inc()
        hist.sample(now=101.0)
        out = hist.series("pilosa_test_lf_events_total",
                          label_filter={"k": "a"}, window_s=60,
                          now=102.0)
        assert len(out["series"]) == 1
        assert out["series"][0]["labels"] == {"k": "a"}

    def test_resolution_ladder_validated_at_load(self):
        """parse_resolutions is the load-time gate: the store
        hard-depends on a strictly-ascending finest-first ladder, so
        a misordered or degenerate env value fails loudly instead of
        serving garbage history (review finding)."""
        from pilosa_tpu.utils.config import parse_resolutions
        assert parse_resolutions("10s:360,1m:720") == ((10.0, 360),
                                                       (60.0, 720))
        for bad in ("1m:720,10s:360",   # descending
                    "10s:0",            # zero capacity
                    "10s:360,10s:100",  # duplicate step
                    ""):
            with pytest.raises(ValueError):
                parse_resolutions(bad)

    def test_double_sample_same_tick_is_ignored(self):
        reg, _c, g, _h = _reg_with_families("ds")
        hist = MetricHistory(resolutions=RES, registry=reg)
        g.set(1)
        assert hist.sample(now=100.0) > 0
        # The on-demand /status path re-entering inside half a step.
        assert hist.sample(now=100.2) == 0
        assert hist.sample(now=101.0) > 0

    def test_persistence_reopen_serves_series(self, tmp_path):
        reg, c, _g, _h = _reg_with_families("p")
        d = str(tmp_path / "hist")
        hist = MetricHistory(d, resolutions=RES, registry=reg)
        t0 = 100.0
        for i in range(20):
            c.labels("x").inc(3)
            hist.sample(now=t0 + i)
        before = hist.series("pilosa_test_p_events_total",
                             window_s=60, now=t0 + 20)["series"]
        hist.close()
        re = MetricHistory(d, resolutions=RES, registry=reg)
        after = re.series("pilosa_test_p_events_total", window_s=60,
                          now=t0 + 20)["series"]
        assert after == before
        re.close()

    def test_coarse_replay_keeps_bucket_timestamps(self, tmp_path):
        """Coarse flushes persist as [bucket_start, mean] pairs:
        replayed 5s/25s points must carry the SAME timestamps as the
        in-memory ring did (a flush-time stamp would shift every
        coarse point one step late across a restart — review
        finding)."""
        reg, _c, g, _h = _reg_with_families("cr")
        d = str(tmp_path / "hist")
        hist = MetricHistory(d, resolutions=RES, registry=reg)
        t0 = 10000.0
        for i in range(60):   # enough to flush several 5s buckets
            g.set(float(i))
            hist.sample(now=t0 + i)
        before = hist.series("pilosa_test_cr_depth_value",
                             window_s=99, step_s=5.0,
                             now=t0 + 60)["series"]
        hist.close()
        re = MetricHistory(d, resolutions=RES, registry=reg)
        after = re.series("pilosa_test_cr_depth_value", window_s=99,
                          step_s=5.0, now=t0 + 60)["series"]
        assert after == before
        # Bucket-aligned: every coarse timestamp sits on a 5s edge.
        assert all(t % 5.0 == 0 for t, _v in after[0]["points"])
        re.close()

    def test_sigkill_shaped_torn_tail_serves_prefix(self, tmp_path):
        """A half-written tick record on disk (SIGKILL mid-write(2)):
        reopen serves every whole tick and silently skips the torn
        tail — the acceptance shape."""
        reg, c, _g, _h = _reg_with_families("k9")
        d = str(tmp_path / "hist")
        hist = MetricHistory(d, resolutions=RES, registry=reg)
        for i in range(10):
            c.labels("x").inc(2)
            hist.sample(now=100.0 + i)
        hist.close()
        seg_dir = os.path.join(d, "res0")
        seg = sorted(os.listdir(seg_dir))[-1]
        with open(os.path.join(seg_dir, seg), "ab") as f:
            f.write(b'deadbeef {"t": 110.0, "s": {"trunca')
        re = MetricHistory(d, resolutions=RES, registry=reg)
        (s,) = re.series("pilosa_test_k9_events_total", window_s=60,
                         now=110.0)["series"]
        assert len(s["points"]) == 9  # all whole ticks, tail gone
        re.close()

    def test_failpoint_torn_write_at_history_site(self, tmp_path):
        """The chaos acceptance: the ring.write failpoint tears a
        history tick mid-record. That tick's persistence is lost (the
        in-memory ring keeps it), later ticks persist into a fresh
        segment, and reopen serves pre-tear + post-tear ticks."""
        reg, c, _g, _h = _reg_with_families("fp")
        d = str(tmp_path / "hist")
        hist = MetricHistory(d, resolutions=RES, registry=reg)
        for i in range(5):
            c.labels("x").inc(2)
            hist.sample(now=100.0 + i)
        dropped_before = hist.disk[0].dropped
        with failpoints.injected("ring.write", "torn(9)*1"):
            c.labels("x").inc(2)
            hist.sample(now=105.0)
        assert hist.disk[0].dropped == dropped_before + 1
        for i in range(3):
            c.labels("x").inc(2)
            hist.sample(now=106.0 + i)
        hist.close()
        re = MetricHistory(d, resolutions=RES, registry=reg)
        (s,) = re.series("pilosa_test_fp_events_total", window_s=60,
                         now=110.0)["series"]
        ts = [t for t, _v in s["points"]]
        # The torn tick (105) is the at-most-one lost record; ticks
        # before and after it all serve.
        assert 105.0 not in ts
        assert {101.0, 102.0, 103.0, 104.0, 106.0, 107.0,
                108.0} <= set(ts), ts
        re.close()


# -- the federation merge ------------------------------------------------------


class TestFederate:
    def _node_text(self, events=3, depth=5.0, obs=(0.05,)):
        reg = obs_metrics.Registry()
        reg.counter("pilosa_test_m_events_total").inc(events)
        reg.gauge("pilosa_test_m_depth_value").set(depth)
        h = reg.histogram("pilosa_test_m_lat_seconds",
                          buckets=(0.1, 1.0))
        for v in obs:
            h.observe(v)
        return reg.render()

    def test_counters_sum_gauges_pernode_histograms_merge(self):
        per_node = {
            "n1:1": federate.parse_exposition(self._node_text(3, 5.0)),
            "n2:1": federate.parse_exposition(
                self._node_text(4, 7.0, obs=(0.5, 5.0))),
        }
        merged = federate.merge_node_families(per_node)
        text = federate.render_merged(merged)
        fams = federate.parse_exposition(text)
        (_, _, total), = fams["pilosa_test_m_events_total"]["samples"]
        assert total == 7.0
        depths = {labels["node"]: v for _n, labels, v in
                  fams["pilosa_test_m_depth_value"]["samples"]}
        assert depths == {"n1:1": 5.0, "n2:1": 7.0}
        hs = {(n, labels.get("le")): v for n, labels, v in
              fams["pilosa_test_m_lat_seconds"]["samples"]}
        assert hs[("pilosa_test_m_lat_seconds_bucket", "0.1")] == 1.0
        assert hs[("pilosa_test_m_lat_seconds_bucket", "+Inf")] == 3.0
        assert hs[("pilosa_test_m_lat_seconds_count", None)] == 3.0

    def test_merged_output_reparses_with_test_parser(self):
        import sys
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from test_obs import parse_exposition as strict_parse
        per_node = {"a:1": federate.parse_exposition(
            self._node_text())}
        text = federate.render_merged(
            federate.merge_node_families(per_node))
        fams = strict_parse(text)
        assert "pilosa_test_m_events_total" in fams

    def test_help_text_round_trips_without_double_escape(self):
        """parse_exposition unescapes HELP so render_merged's
        re-escape yields the identical wire form per federation hop
        (a still-escaped stored form would double backslashes on
        every hop — review finding)."""
        reg = obs_metrics.Registry()
        reg.counter("pilosa_test_mh_events_total",
                    "back\\slash and\nnewline")
        text = reg.render()
        one_hop = federate.render_merged(federate.merge_node_families(
            {"n1": federate.parse_exposition(text)}))
        two_hop = federate.render_merged(federate.merge_node_families(
            {"n1": federate.parse_exposition(one_hop)}))
        help1 = next(ln for ln in one_hop.splitlines()
                     if ln.startswith("# HELP"))
        help2 = next(ln for ln in two_hop.splitlines()
                     if ln.startswith("# HELP"))
        assert help1 == help2
        assert "back\\\\slash and\\nnewline" in help1

    def test_fan_out_reports_unreachable_peers(self):
        class Node:
            def __init__(self, host):
                self.host = host

        class Cluster:
            nodes = [Node("me:1"), Node("up:1"), Node("down:1")]

        fed = federate.Federator("me:1", cluster=Cluster())

        def fetch(host):
            if host == "down:1":
                raise OSError("connection refused")
            return {"host": host}

        results, missing = fed.fan_out(fetch, lambda: {"host": "me:1"})
        assert set(results) == {"me:1", "up:1"}
        assert missing == ["down:1"]


# -- the sentinel ---------------------------------------------------------------


class _FakeBlackbox:
    def __init__(self):
        self.snaps = []

    def snapshot(self, trigger, extra=None):
        self.snaps.append((trigger, extra))
        return {}


def _hist_with_cliff(tag, baseline_v=0.005, cliff_v=0.5,
                     n_base=100, n_cliff=15):
    reg = obs_metrics.Registry()
    h = reg.histogram(f"pilosa_{tag}_q_seconds",
                      buckets=(0.001, 0.01, 0.1, 1.0))
    hist = MetricHistory(resolutions=((1.0, 4000), (5.0, 50),
                                      (25.0, 20)), registry=reg)
    now = 10000.0
    for _ in range(n_base):
        h.observe(baseline_v)
        hist.sample(now=now)
        now += 1
    for _ in range(n_cliff):
        h.observe(cliff_v)
        hist.sample(now=now)
        now += 1
    return hist, now, f"pilosa_{tag}_q_seconds"


class TestSentinel:
    def test_robust_z_math(self):
        z, rm, bm = robust_z([10.0] * 5, [1.0, 1.1, 0.9, 1.0, 1.05])
        assert rm == 10.0 and bm == pytest.approx(1.0)
        assert z > 50
        z2, _, _ = robust_z([1.0] * 5, [1.0, 1.1, 0.9, 1.0, 1.05])
        assert abs(z2) < 1

    def test_latency_cliff_fires_up_finding(self):
        hist, now, fam = _hist_with_cliff("sent1")
        bb = _FakeBlackbox()
        s = Sentinel(hist, blackbox=bb, window_s=10, baseline_s=200,
                     min_points=3, zscore=4.0,
                     watches=((f"{fam}:p99", "up"),))
        fired = s.check(now=now)
        assert fired and fired[0]["direction"] == "up"
        assert fired[0]["metric"] == f"{fam}:p99"
        # The blackbox snapshot names the regressed metric.
        trigger, extra = bb.snaps[0]
        assert trigger == "sentinel"
        assert extra["sentinel"]["metric"] == f"{fam}:p99"
        # Counter + active gauge raised.
        assert obs_metrics.SENTINEL_FINDINGS.labels(
            f"{fam}:p99", "up").value >= 1
        assert obs_metrics.SENTINEL_ACTIVE.labels(
            f"{fam}:p99", "up").value == 1

    def test_rate_collapse_fires_down_finding(self):
        reg = obs_metrics.Registry()
        c = reg.counter("pilosa_sent2_q_total")
        hist = MetricHistory(resolutions=((1.0, 4000), (5.0, 50),
                                          (25.0, 20)), registry=reg)
        now = 10000.0
        for _ in range(100):
            c.inc(50)
            hist.sample(now=now)
            now += 1
        for _ in range(15):
            c.inc(1)   # the traffic cliff
            hist.sample(now=now)
            now += 1
        s = Sentinel(hist, window_s=10, baseline_s=200, min_points=3,
                     zscore=4.0,
                     watches=(("pilosa_sent2_q_total", "down"),))
        fired = s.check(now=now)
        assert fired and fired[0]["direction"] == "down", fired

    def test_small_shift_below_min_ratio_does_not_fire(self):
        hist, now, fam = _hist_with_cliff("sent3", baseline_v=0.005,
                                          cliff_v=0.007)
        s = Sentinel(hist, window_s=10, baseline_s=200, min_points=3,
                     zscore=4.0, min_ratio=1.5,
                     watches=((f"{fam}:p50", "up"),))
        assert s.check(now=now) == []

    def test_refire_rate_limited_and_recovery_clears_active(self):
        hist, now, fam = _hist_with_cliff("sent4")
        s = Sentinel(hist, window_s=10, baseline_s=200, min_points=3,
                     zscore=4.0, retrip_s=300,
                     watches=((f"{fam}:p99", "up"),))
        assert s.check(now=now)
        assert s.check(now=now + 5) == []     # inside retrip
        # Let the series recover: feed baseline-speed ticks until the
        # recent window is healthy again.
        reg_h = hist.registry.families()[fam]
        for i in range(15):
            reg_h.observe(0.005)
            hist.sample(now=now + 10 + i)
        assert s.check(now=now + 25) == []
        assert obs_metrics.SENTINEL_ACTIVE.labels(
            f"{fam}:p99", "up").value == 0

    def test_manifest_envelope_rule(self, tmp_path):
        reg = obs_metrics.Registry()
        h = reg.histogram("pilosa_query_duration_seconds",
                          labels=("call", "lane", "status"),
                          buckets=(0.001, 0.01, 0.1, 1.0, 10.0))
        hist = MetricHistory(resolutions=((1.0, 400), (5.0, 50),
                                          (25.0, 20)), registry=reg)
        now = 10000.0
        for _ in range(20):
            h.labels("Count", "read", "200").observe(0.5)  # very slow
            hist.sample(now=now)
            now += 1
        manifest = tmp_path / "MANIFEST.json"
        manifest.write_text(json.dumps({"metrics": {
            "latency_below_cap_p99": {"value": 17.7, "unit": "ms"}}}))
        s = Sentinel(hist, window_s=10, baseline_s=200, min_points=3,
                     zscore=1e9,   # silence the z rules
                     manifest_path=str(manifest),
                     manifest_tolerance=5.0, watches=())
        fired = s.check(now=now)
        assert fired, fired
        assert fired[0]["rule"] == "manifest"
        assert fired[0]["manifestKey"] == "latency_below_cap_p99"
        # 0.5s recent median vs 17.7ms * 5 = 88.5ms bound.
        assert fired[0]["recentMedian"] > fired[0]["committed"]

    def test_finding_force_keeps_inflight_trace_as_anomaly(
            self, tmp_path):
        from pilosa_tpu.obs.diskring import SegmentRing
        from pilosa_tpu.obs.sampler import TailSampler
        from pilosa_tpu.sched import QueryContext, QueryRegistry
        hist, now, fam = _hist_with_cliff("sent5")
        tracer = Tracer(enabled=False)
        sampler = TailSampler(disk=SegmentRing(str(tmp_path / "tr")))
        registry = QueryRegistry()
        ctx = QueryContext(pql="Count(...)", index="i", lane="read")
        trace = tracer.start(ctx, node="n1")
        registry.register(ctx)
        try:
            s = Sentinel(hist, registry=registry, tracer=tracer,
                         sampler=sampler, window_s=10, baseline_s=200,
                         min_points=3, zscore=4.0,
                         watches=((f"{fam}:p99", "up"),))
            assert s.check(now=now)
        finally:
            registry.finish(ctx)
        assert trace.keep_reason == "anomaly"
        ring = tracer.traces()
        assert any(t["id"] == ctx.id and t["reason"] == "anomaly"
                   for t in ring), ring
        disk = [r for r in sampler.disk.scan()
                if r.get("id") == ctx.id]
        assert disk and disk[0]["reason"] == "anomaly"
        sampler.disk.close()


# -- handler routes -------------------------------------------------------------


class TestFleetHandler:
    def _handler(self, tmp_path=None, history=None, sentinel=None,
                 federator=None, sampler=None):
        return Handler(None, None, host="local",
                       tracer=Tracer(enabled=False), history=history,
                       sentinel=sentinel, federator=federator,
                       sampler=sampler)

    def test_history_route_params_and_series(self):
        reg, c, _g, _h = _reg_with_families("hr")
        hist = MetricHistory(resolutions=RES, registry=reg)
        t0 = time.time() - 10   # the route queries against wall-clock
        for i in range(5):
            c.labels("x").inc()
            hist.sample(now=t0 + i)
        handler = self._handler(history=hist)
        st, _hd, body = call(
            handler, "GET",
            "/debug/metrics/history?family=pilosa_test_hr_events_total"
            "&window=90s&label=k=x")
        assert st == 200
        doc = json.loads(body)
        assert doc["series"] and doc["series"][0]["labels"] == {
            "k": "x"}
        st, _hd, _body = call(handler, "GET",
                              "/debug/metrics/history?window=bogus")
        assert st == 400
        st, _hd, _body = call(handler, "GET",
                              "/debug/metrics/history?label=bogus")
        assert st == 400
        # No history wired: an empty, marked answer — not a 500.
        st, _hd, body = call(self._handler(), "GET",
                             "/debug/metrics/history")
        assert st == 200
        assert json.loads(body)["enabled"] is False

    def test_metrics_cluster_single_node_marks_gauges(self):
        obs_metrics.HISTORY_SERIES_LIVE.set(3)
        obs_metrics.HISTORY_SAMPLES.inc(0)
        handler = self._handler()
        st, hd, body = call(handler, "GET", "/metrics/cluster")
        assert st == 200
        assert hd["X-Pilosa-Federated-Nodes"] == "1"
        fams = federate.parse_exposition(body.decode())
        # Gauges carry the node label; counters stay plain.
        g = fams.get("pilosa_history_series_live")
        assert g and all(labels.get("node") == "local"
                         for _n, labels, _v in g["samples"])
        c = fams.get("pilosa_history_samples_total")
        assert c and all("node" not in labels
                         for _n, labels, _v in c["samples"])

    def test_partial_contract_503_then_marked(self):
        class Node:
            def __init__(self, host):
                self.host = host

        class Cluster:
            nodes = [Node("local"), Node("gone:1")]

        class DeadClient:
            def metrics_text(self, host=None, deadline_s=None):
                raise OSError("connection refused")

            def debug_cluster_local(self, host=None, deadline_s=None):
                raise OSError("connection refused")

        fed = federate.Federator("local", cluster=Cluster(),
                                 client_for=lambda h: DeadClient())
        handler = self._handler(federator=fed)
        st, _hd, body = call(handler, "GET", "/metrics/cluster")
        assert st == 503 and b"gone:1" in body
        st, hd, _body = call(handler, "GET",
                             "/metrics/cluster?partial=1")
        assert st == 200
        assert hd["X-Pilosa-Partial-Nodes"] == "gone:1"
        st, hd, body = call(handler, "GET",
                            "/debug/cluster?partial=1")
        assert st == 200
        doc = json.loads(body)
        assert doc["missing"] == ["gone:1"]
        assert "local" in doc["nodes"]

    def test_debug_cluster_rollup_and_version_skew(self):
        handler = self._handler()
        st, _hd, body = call(handler, "GET", "/debug/cluster?local=1")
        assert st == 200
        block = json.loads(body)
        assert block["build"]["version"]
        st, _hd, body = call(handler, "GET", "/debug/cluster")
        doc = json.loads(body)
        assert doc["coordinator"] == "local"
        assert doc["versionSkew"] is False
        assert doc["versions"]["local"] == block["build"]["version"]

    def test_sentinel_route(self):
        hist = MetricHistory(resolutions=RES)
        s = Sentinel(hist, interval_s=999)
        handler = self._handler(sentinel=s)
        st, _hd, body = call(handler, "GET", "/debug/sentinel")
        assert st == 200
        doc = json.loads(body)
        assert doc["enabled"] is True and "findings" in doc
        st, _hd, body = call(self._handler(), "GET", "/debug/sentinel")
        assert json.loads(body)["enabled"] is False

    def test_traces_pagination_and_summary(self, tmp_path):
        from pilosa_tpu.obs.diskring import SegmentRing
        from pilosa_tpu.obs.sampler import TailSampler, trace_record
        from pilosa_tpu.obs.trace import Trace
        tracer = Tracer(enabled=False, max_traces=64)
        disk = SegmentRing(str(tmp_path / "tr"))
        sampler = TailSampler(disk=disk)
        for i in range(10):
            t = Trace(f"q{i}", node="n1")
            reason = "slow" if i % 2 else "error"
            tracer.keep(t, reason=reason)
            disk.append(trace_record(t, reason))
        handler = Handler(None, None, host="local", tracer=tracer,
                          sampler=sampler)
        st, _hd, body = call(handler, "GET",
                             "/debug/traces?limit=3&offset=0")
        page1 = json.loads(body)
        st, _hd, body = call(handler, "GET",
                             "/debug/traces?limit=3&offset=3")
        page2 = json.loads(body)
        assert page1["total"] == page2["total"] == 10
        ids1 = [t["id"] for t in page1["traces"]]
        ids2 = [t["id"] for t in page2["traces"]]
        assert len(ids1) == len(ids2) == 3
        assert not set(ids1) & set(ids2)
        # Disk source pages the same way, filtered by reason.
        st, _hd, body = call(
            handler, "GET",
            "/debug/traces?source=disk&reason=slow&limit=2&offset=2")
        doc = json.loads(body)
        assert doc["total"] == 5 and len(doc["traces"]) == 2
        assert all(t["reason"] == "slow" for t in doc["traces"])
        # The reason-count rollup over both stores.
        st, _hd, body = call(handler, "GET", "/debug/traces/summary")
        doc = json.loads(body)
        assert doc["ring"] == {"slow": 5, "error": 5}
        assert doc["disk"] == {"slow": 5, "error": 5}
        disk.close()


# -- sentinel end-to-end: a failpoint latency cliff on a hot path --------------


class TestSentinelEndToEnd:
    def test_injected_latency_cliff_raises_finding_keeps_trace(
            self, tmp_path):
        """The acceptance path: real handler + holder + executor; a
        wal.append failpoint delay turns the write path into a cliff;
        the sentinel (fed by real QUERY_SECONDS observations through
        the history) raises pilosa_sentinel_findings, force-keeps an
        in-flight trace under reason ``anomaly``, and lands a
        blackbox snapshot naming the regressed metric."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.obs.blackbox import Blackbox
        from pilosa_tpu.obs.diskring import SegmentRing
        from pilosa_tpu.obs.sampler import TailSampler

        holder = Holder(str(tmp_path / "data"))
        holder.open()
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        ex = Executor(holder, host="local")
        sampler = TailSampler(
            disk=SegmentRing(str(tmp_path / "traces")),
            head_n=0, slow_floor_s=60.0)
        handler = Handler(holder, ex, host="local",
                          tracer=Tracer(enabled=False),
                          sampler=sampler)
        hist = MetricHistory(resolutions=((1.0, 4000), (5.0, 50),
                                          (25.0, 20)))
        blackbox = Blackbox(str(tmp_path / "bb"),
                            state_fn=lambda: {"ok": True},
                            interval_s=3600, node="local")
        # min_ratio 3: real write timings jitter across adjacent
        # power-of-2 histogram buckets (a 2x "shift"); the injected
        # 60ms cliff is ~64x, so the rule still fires loudly.
        sentinel = Sentinel(
            hist, registry=handler.registry, tracer=handler.tracer,
            sampler=sampler, blackbox=blackbox, interval_s=3600,
            window_s=10, baseline_s=300, min_points=3, zscore=4.0,
            min_ratio=3.0)

        def write(n):
            st, _hd, _b = call(
                handler, "POST", "/index/i/query",
                f'SetBit(rowID=1, frame="f", columnID={n})'.encode())
            assert st == 200

        # Baseline: fast writes, one history tick per (fake) second.
        now = time.time()
        col = 0
        for _ in range(100):
            write(col)
            col += 1
            hist.sample(now=now)
            now += 1
        assert sentinel.check(now=now) == []
        # The cliff: every WAL append pays an injected 60ms delay.
        with failpoints.injected("wal.append", "delay(60ms)"):
            for _ in range(12):
                write(col)
                col += 1
                hist.sample(now=now)
                now += 1
            # One query held in flight across the sentinel pass: the
            # evidence the force-keep must capture.
            release = threading.Event()
            started = threading.Event()

            def slow_query():
                started.set()
                release.wait(10)
                write(10**6)

            t = threading.Thread(target=slow_query)
            # Deterministic in-flight context: register it by hand
            # (the thread itself may not reach the handler before the
            # check below).
            from pilosa_tpu.sched import QueryContext
            ctx = QueryContext(pql="SetBit(...)", index="i",
                               lane="write")
            trace = handler.tracer.start(ctx, node="local")
            handler.registry.register(ctx)
            t.start()
            started.wait(5)
            try:
                fired = sentinel.check(now=now)
            finally:
                release.set()
                t.join(15)
                handler.registry.finish(ctx)
        assert fired, fired
        metrics_hit = {f["metric"] for f in fired}
        assert any(m.startswith("pilosa_query_duration_seconds")
                   for m in metrics_hit), metrics_hit
        # The in-flight trace was force-kept under ``anomaly``, in
        # the ring AND on disk.
        assert trace.keep_reason == "anomaly"
        disk = [r for r in sampler.disk.scan()
                if r.get("id") == ctx.id]
        assert disk and disk[0]["reason"] == "anomaly"
        # The blackbox snapshot names the regressed metric.
        snaps = [r for r in blackbox.ring.scan()
                 if r.get("trigger") == "sentinel"]
        assert snaps, "no sentinel snapshot landed"
        named = {s["sentinel"]["metric"] for s in snaps}
        assert any(m.startswith("pilosa_query_duration_seconds")
                   for m in named), named
        sampler.disk.close()
        hist.close()
        ex.close()
        holder.close()
