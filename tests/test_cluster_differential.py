"""Cluster-level generative differential test: a random stream of
mutations and queries runs against a REAL 2-node gossip cluster
(replicas=2, subprocess servers, HTTP only) and a Python set model.
Every query answered by EITHER node must be model-exact — covering
write fan-out to replicas, query forwarding, the batch/bulk lanes over
the wire, and the raw-import sidecar, none of which the in-process
differential harness touches."""

import json
import os
import signal
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402


def _post(host: str, path: str, body: bytes) -> bytes:
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=30).read()


def _query(host: str, body: str):
    return json.loads(_post(host, "/index/cd/query",
                            body.encode()))["results"]


def test_two_node_cluster_matches_model(tmp_path):
    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs = []
    logs = []

    def spawn(name, port, internal, seed=""):
        d = tmp_path / name
        d.mkdir(exist_ok=True)  # restart reuses the original data dir
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        log = open(tmp_path / f"{name}.log", "a")  # "a": restarts must not truncate the first incarnation's log
        logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--cluster.type", "gossip",
                "--cluster.hosts", hosts,
                "--cluster.replicas", "2",
                "--cluster.internal-port", str(internal),
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        procs.append(p)
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    try:
        host_a = spawn("a", pa, ga)
        host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
        nodes = [host_a, host_b]
        _post(host_a, "/index/cd", b"{}")
        _post(host_a, "/index/cd/frame/f", b"{}")

        from pilosa_tpu.cluster.client import Client
        client = Client(host_a)

        rng = np.random.default_rng(99)
        bits: dict[int, set[int]] = {}
        n_rows, n_cols = 30, 3 * SLICE_WIDTH

        def mset(r, c):
            bits.setdefault(r, set()).add(c)

        for step in range(120):
            kind = int(rng.integers(0, 8))
            node = nodes[int(rng.integers(0, 2))]
            if kind < 3:  # point set via a random node
                r = int(rng.integers(0, n_rows))
                c = int(rng.integers(0, n_cols))
                _query(node, f'SetBit(frame="f", rowID={r},'
                             f' columnID={c})')
                mset(r, c)
            elif kind == 3:  # point clear via a random node
                r = int(rng.integers(0, n_rows))
                c = int(rng.integers(0, n_cols))
                _query(node, f'ClearBit(frame="f", rowID={r},'
                             f' columnID={c})')
                bits.get(r, set()).discard(c)
            elif kind == 4:  # bulk import through the client
                k = int(rng.integers(1, 300))
                rows = rng.integers(0, n_rows, k).astype(np.uint64)
                cols = rng.integers(0, n_cols, k).astype(np.uint64)
                client.import_arrays("cd", "f", rows, cols)
                for r, c in zip(rows.tolist(), cols.tolist()):
                    mset(r, c)
            elif kind == 5:  # Count via BOTH nodes must agree + exact
                r = int(rng.integers(0, n_rows))
                q = f'Count(Bitmap(rowID={r}, frame="f"))'
                got_a = _query(host_a, q)[0]
                got_b = _query(host_b, q)[0]
                want = len(bits.get(r, set()))
                assert got_a == got_b == want, (step, r, got_a,
                                                got_b, want)
            elif kind == 6:  # wide union via a random node
                ids = rng.integers(0, n_rows,
                                   int(rng.integers(2, 10))).tolist()
                q = "Count(Union(" + ", ".join(
                    f'Bitmap(rowID={r}, frame="f")' for r in ids) + "))"
                want = len(set().union(
                    *(bits.get(r, set()) for r in ids)))
                assert _query(node, q)[0] == want, (step, ids)
            else:  # intersect/difference via a random node
                a, b = rng.integers(0, n_rows, 2).tolist()
                sa = bits.get(a, set())
                sb = bits.get(b, set())
                qi = (f'Count(Intersect(Bitmap(rowID={a}, frame="f"),'
                      f' Bitmap(rowID={b}, frame="f")))')
                assert _query(node, qi)[0] == len(sa & sb), (step, a, b)
                qd = (f'Count(Difference(Bitmap(rowID={a}, frame="f"),'
                      f' Bitmap(rowID={b}, frame="f")))')
                assert _query(node, qd)[0] == len(sa - sb), (step, a, b)

        # Export the frame from node B and compare to the model: the
        # full CSV export path (snapshot stream per slice, owner
        # failover) must reproduce every (row, col) exactly.
        import io as _io

        from pilosa_tpu.cluster.client import Client as _C
        exported = set()
        cb = _C(host_b)
        max_slice = max((c // SLICE_WIDTH for s in bits.values()
                         for c in s), default=0)
        for sl in range(max_slice + 1):
            w = _io.StringIO()
            cb.export_csv_to(w, "cd", "f", "standard", sl)
            for line in w.getvalue().splitlines():
                r, c = line.split(",")
                exported.add((int(r), int(c)))
        want_pairs = {(r, c) for r, s in bits.items() for c in s}
        assert exported == want_pairs, (
            len(exported - want_pairs), len(want_pairs - exported))

        # Restart node A and re-verify (the reference's
        # TestMain_Set_Quick cross-checks rows after a restart,
        # server_test.go:42-121): every row must still be model-exact
        # on BOTH nodes — WAL replay + snapshot load + replica state.
        pa_proc = procs[0]
        pa_proc.send_signal(signal.SIGINT)
        pa_proc.wait(timeout=30)
        host_a = spawn("a", pa, ga, seed=f"127.0.0.1:{gb}")
        for r in sorted(bits):
            q = f'Count(Bitmap(rowID={r}, frame="f"))'
            want = len(bits[r])
            assert _query(host_a, q)[0] == want, ("post-restart-a", r)
            assert _query(host_b, q)[0] == want, ("post-restart-b", r)

        # Backup the frame from the cluster, restore into a FRESH
        # single-node server, and re-verify the model there — the tar
        # stream (client.go:463-674 semantics) must carry every bit.
        import io as _io2
        buf = _io2.BytesIO()
        client.backup_to(buf, "cd", "f", "standard")
        pc = free_port()
        hosts_c = f"127.0.0.1:{pc}"
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        logc = open(tmp_path / "c.log", "a")
        logs.append(logc)
        pcproc = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "-d", str(tmp_path / "c"), "-b", hosts_c],
            env=env, stdout=logc, stderr=logc,
            cwd=os.path.dirname(_HERE))
        procs.append(pcproc)
        wait_up(hosts_c)
        _post(hosts_c, "/index/cd", b"{}")
        _post(hosts_c, "/index/cd/frame/f", b"{}")
        cc = Client(hosts_c)
        buf.seek(0)
        cc.restore_from(buf, "cd", "f", "standard")
        for r in sorted(bits):
            got = json.loads(_post(
                hosts_c, "/index/cd/query",
                f'Count(Bitmap(rowID={r}, frame="f"))'.encode()))
            assert got["results"][0] == len(bits[r]), ("restore", r)
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGINT)
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
