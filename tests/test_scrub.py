"""Storage-integrity tests (ISSUE 15): checksummed snapshot footers,
the background scrubber, quarantine, and automatic replica repair.

Tier-1 (fast) legs: footer wire round-trips on BOTH snapshot writers,
vintage-file compatibility, torn-footer reopen, every detection leg
(open / lazy first-read / scrub / the ``corrupt`` failpoint mode),
quarantine gating end to end (executor skip → 503 / partial contract,
409 fragment routes, anti-entropy skip), scrub-vs-concurrent-write
races, the in-process repair cycle against a real 2-node replica set,
the 507 import retry satellite, and the config/CLI/observability
surfaces. The REAL 3-node gossip chaos legs live in
tests/test_scrub_cluster.py (slow).
"""

import io
import json
import os
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.fault import failpoints
from pilosa_tpu.storage import integrity, roaring
from pilosa_tpu.storage import scrub as scrub_mod
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.storage.integrity import (CorruptionError,
                                          QuarantineRegistry)

pytestmark = pytest.mark.scrub


def _mk_bitmap(n=5000, seed=3):
    rng = np.random.default_rng(seed)
    b = roaring.Bitmap()
    b.add_many(rng.choice(1 << 20, size=n, replace=False)
               .astype(np.uint64))
    return b


def _footered_bytes(b):
    buf = io.BytesIO()
    b.write_to(buf, footer=True)
    return buf.getvalue()


# -- footer wire format -------------------------------------------------------


class TestFooter:
    def test_round_trip_and_values_unchanged(self):
        b = _mk_bitmap()
        data = _footered_bytes(b)
        b2 = roaring.Bitmap.unmarshal(data, verify_body=True)
        assert b2.footer is not None
        assert b2.footer.version == integrity.FOOTER_VERSION
        assert (b2.values() == b.values()).all()

    def test_wire_form_is_footer_free_and_body_identical(self):
        """marshal() / the exchange format carries NO footer, and the
        footered file's body is byte-identical to the vintage form —
        the golden-vector compatibility claim."""
        b = _mk_bitmap()
        wire = b.marshal()
        data = _footered_bytes(b)
        assert data[:len(wire)] == wire
        assert len(data) == len(wire) + integrity.footer_len(
            len([c for c in b.containers if c.n]))
        assert roaring.Bitmap.unmarshal(wire).footer is None

    def test_vintage_file_loads_with_no_footer(self):
        b = _mk_bitmap()
        b2 = roaring.Bitmap.unmarshal(b.marshal(), verify_body=True)
        assert b2.footer is None
        assert (b2.values() == b.values()).all()

    def test_empty_bitmap_footer(self):
        data = _footered_bytes(roaring.Bitmap())
        b = roaring.Bitmap.unmarshal(data, verify_body=True)
        assert b.footer is not None and b.footer.block_n == 0
        assert b.count() == 0

    def test_ops_replay_after_footer(self):
        b = _mk_bitmap(100)
        buf = io.BytesIO()
        b.write_to(buf, footer=True)
        buf.write(roaring.Op(roaring.OP_ADD, 12345678).marshal())
        buf.write(roaring.Op(roaring.OP_REMOVE, 12345678).marshal())
        buf.write(roaring.Op(roaring.OP_ADD, 999).marshal())
        b2 = roaring.Bitmap.unmarshal(buf.getvalue(), verify_body=True)
        assert b2.contains(999) and not b2.contains(12345678)
        assert b2.op_n == 3

    def test_runs_cookie_snapshot_gets_footer(self):
        b = roaring.Bitmap()
        b.add_many(np.arange(30000, dtype=np.uint64))
        b.optimize()
        assert any(c.is_run() for c in b.containers)
        data = _footered_bytes(b)
        b2 = roaring.Bitmap.unmarshal(data, verify_body=True)
        assert b2.footer is not None
        assert b2.count() == 30000
        assert not scrub_mod.scrub_buffer(data)["corrupt"]

    def test_frozen_native_writev_path_gets_footer(self, tmp_path):
        b = _mk_bitmap(20000, seed=9)
        frozen = b.freeze()
        p = tmp_path / "snap"
        with open(p, "wb") as f:
            roaring.write_frozen(frozen, f, footer=True)
        raw = p.read_bytes()
        b2 = roaring.Bitmap.unmarshal(raw, verify_body=True)
        assert b2.footer is not None
        assert b2.count() == b.count()
        v = scrub_mod.scrub_buffer(raw)
        assert not v["corrupt"] and v["coverage"] == "full"

    def test_body_flip_detected_at_unmarshal_and_scrub(self):
        b = _mk_bitmap()
        data = bytearray(_footered_bytes(b))
        body_len = roaring.Bitmap.unmarshal(bytes(data)).footer.body_len
        data[body_len - 33] ^= 0x08  # inside a container block
        with pytest.raises(CorruptionError):
            roaring.Bitmap.unmarshal(bytes(data), verify_body=True)
        v = scrub_mod.scrub_buffer(bytes(data))
        assert v["corrupt"] and v["badBlocks"]

    def test_header_flip_detected_without_body_verify(self):
        b = _mk_bitmap()
        data = bytearray(_footered_bytes(b))
        data[9] ^= 0x01  # keyN/header region
        with pytest.raises(ValueError):
            # Either the header crc or the structural parse trips —
            # both are ValueError, both quarantine at the open path.
            roaring.Bitmap.unmarshal(bytes(data))

    def test_footer_flip_is_corruption(self):
        b = _mk_bitmap(50)
        data = bytearray(_footered_bytes(b))
        data[-6] ^= 0x40  # inside the footer
        with pytest.raises(ValueError):
            roaring.Bitmap.unmarshal(bytes(data))

    def test_torn_footer_reads_as_torn_tail(self):
        b = _mk_bitmap(50)
        wire = b.marshal()
        data = _footered_bytes(b)
        torn = data[:len(wire) + 7]  # magic + 3 bytes: truncated at EOF
        b2 = roaring.Bitmap.unmarshal(torn, tolerate_torn_tail=True)
        assert b2.torn_bytes == 7
        assert (b2.values() == b.values()).all()
        with pytest.raises(integrity.TornFooterError):
            roaring.Bitmap.unmarshal(torn)
        v = scrub_mod.scrub_buffer(torn)
        assert not v["corrupt"] and v["walTornBytes"] == 7

    def test_wal_tail_checksum_flip_is_corrupt_in_scrub(self):
        b = _mk_bitmap(50)
        buf = io.BytesIO()
        b.write_to(buf, footer=True)
        buf.write(roaring.Op(roaring.OP_ADD, 1).marshal())
        buf.write(roaring.Op(roaring.OP_ADD, 2).marshal())
        data = bytearray(buf.getvalue())
        data[-20] ^= 0x04  # first wal record's value bytes
        v = scrub_mod.scrub_buffer(bytes(data))
        assert v["corrupt"] and v["walBad"] >= 1

    def test_wal_partial_trailing_record_is_a_tear(self):
        b = _mk_bitmap(50)
        buf = io.BytesIO()
        b.write_to(buf, footer=True)
        buf.write(roaring.Op(roaring.OP_ADD, 1).marshal())
        buf.write(b"\x00\x01\x02")  # 3 bytes of a next record
        v = scrub_mod.scrub_buffer(buf.getvalue())
        assert not v["corrupt"]
        assert v["walRecords"] == 1 and v["walTornBytes"] == 3


# -- the corrupt failpoint mode ----------------------------------------------


class TestCorruptFailpoint:
    def teardown_method(self):
        failpoints.disarm_all()

    def test_spec_parses(self):
        fp = failpoints.parse_spec("storage.read", "corrupt")
        assert fp.mode == "corrupt" and fp.arg == 1
        fp = failpoints.parse_spec("storage.read", "corrupt(3)*2")
        assert fp.arg == 3 and fp.remaining == 2
        with pytest.raises(ValueError):
            failpoints.parse_spec("storage.read", "corrupt(0)")

    def test_flips_exactly_n_bits_and_proceeds(self, tmp_path):
        p = tmp_path / "f"
        p.write_bytes(bytes(1024))
        failpoints.arm("storage.read", "corrupt(3)*1")
        failpoints.default().hit("storage.read", path=str(p))
        after = np.frombuffer(p.read_bytes(), dtype=np.uint8)
        flipped = int(np.unpackbits(after).sum())
        assert 1 <= flipped <= 3  # same-offset re-flips may cancel
        assert failpoints.ACTIVE is None, "*1 auto-disarmed"

    def test_missing_path_is_a_noop(self, tmp_path):
        failpoints.arm("storage.read", "corrupt*1")
        failpoints.default().hit("storage.read",
                                 path=str(tmp_path / "absent"))
        # no exception; the trigger was still consumed


# -- fragment quarantine machinery -------------------------------------------


@pytest.fixture
def frag_dir(tmp_path):
    q = QuarantineRegistry()

    def make(name="0", n_bits=800):
        f = Fragment(str(tmp_path / name), "i", "f", "standard", 0,
                     quarantine=q)
        f.open()
        for i in range(n_bits):
            f.set_bit(3, (i * 7) % SLICE_WIDTH)
        f.snapshot(sync=True)
        return f
    yield q, make
    failpoints.disarm_all()


class TestFragmentQuarantine:
    def test_clean_cycle(self, frag_dir):
        q, make = frag_dir
        f = make()
        assert not f.quarantined
        v = f.verify_on_disk()
        assert not v["corrupt"] and v["coverage"] == "full"
        assert f.storage.footer is not None
        f.close()

    def test_open_detects_raw_flip_resets_and_registers(self, frag_dir):
        q, make = frag_dir
        f = make()
        path, count = f.path, f.row(3).count()
        f.close()
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x10
        open(path, "wb").write(bytes(raw))
        f2 = Fragment(path, "i", "f", "standard", 0, quarantine=q)
        f2.open()
        assert f2.quarantined and q.slice_blocked("i", 0)
        assert os.path.exists(path + ".corrupt")
        # fresh replacement: writes still apply + WAL durable
        assert f2.set_bit(9, 42)
        assert f2.storage.footer is not None
        # sentinel: reopen BEFORE repair stays quarantined
        f2.close()
        f3 = Fragment(path, "i", "f", "standard", 0,
                      quarantine=QuarantineRegistry())
        f3.open()
        assert f3.quarantined, "restart must not serve the near-empty" \
                               " replacement as authoritative"
        f3.clear_quarantine()
        assert not os.path.exists(path + ".corrupt")
        f3.close()
        del count

    def test_lazy_first_read_verify_detects_rot_under_mmap(self,
                                                           frag_dir):
        """Rot landing AFTER a clean open (the mmap-fault scenario):
        the first read re-checks the block crc table and quarantines."""
        q, make = frag_dir
        f = make()
        f.close()
        f = Fragment(f.path, "i", "f", "standard", 0, quarantine=q)
        f.open()  # clean: body digest passes, lazy latch armed
        assert f._verify_pending
        info = f.storage.footer
        off = int(info.offsets[0]) + 2  # inside the first block
        with open(f.path, "r+b") as raw:
            raw.seek(off)
            byte = raw.read(1)[0]
            raw.seek(off)
            raw.write(bytes([byte ^ 0x20]))
        with pytest.raises(CorruptionError):
            f.row(3)
        assert f.quarantined and q.slice_blocked("i", 0)
        f.close()

    def test_scrub_leg_detects_and_quarantines(self, frag_dir):
        q, make = frag_dir
        f = make()
        failpoints.arm("storage.read", "corrupt*1")
        v = f.verify_on_disk()
        assert v["corrupt"] and f.quarantined
        f.close()

    def test_snapshot_write_corrupt_mode_rots_the_file(self, frag_dir):
        """corrupt at snapshot.write flips bits in the JUST-WRITTEN
        snapshot — nothing fails at the write (real bit rot); the
        scrub pass catches it after."""
        q, make = frag_dir
        f = make()
        failpoints.arm("snapshot.write", "corrupt*1")
        f.snapshot(sync=True)
        failpoints.disarm_all()
        v = f.verify_on_disk()
        assert v["corrupt"] and f.quarantined
        f.close()

    def test_scrub_vs_concurrent_writes_no_false_positives(self,
                                                           frag_dir):
        """The race leg: verify_on_disk re-reads the file while a
        writer hammers the WAL — the append-only prefix discipline
        must never misread an in-flight append as corruption."""
        q, make = frag_dir
        f = make()
        stop = threading.Event()
        errors: list = []

        def writer():
            i = 0
            try:
                while not stop.is_set():
                    f.set_bit(5, i % SLICE_WIDTH)
                    i += 1
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(25):
                v = f.verify_on_disk()
                assert not v["corrupt"], v
        finally:
            stop.set()
            t.join()
        assert not errors
        assert not f.quarantined
        f.close()

    def test_reset_for_repair_preserves_first_forensics(self, frag_dir):
        q, make = frag_dir
        f = make()
        f._set_quarantined("test", site="scrub")
        f.reset_for_repair()
        assert f.row_count(3) == 0  # fresh state
        assert f.quarantined  # repairer clears, not reset
        f.close()


# -- scrubber ------------------------------------------------------------------


class TestScrubber:
    def test_pass_detects_and_fires_callback(self, tmp_path):
        from pilosa_tpu.models.holder import Holder
        h = Holder(str(tmp_path))
        h.open()
        idx = h.create_index("i")
        fr = idx.create_frame("f")
        for col in (1, 5, 9):
            fr.set_bit("standard", 2, col)
        frag = h.fragment("i", "f", "standard", 0)
        frag.snapshot(sync=True)
        hits: list = []
        s = scrub_mod.Scrubber(h, interval_s=999, pace_s=0,
                               on_corrupt=hits.append)
        out = s.pass_once()
        assert out["fragments"] >= 1 and out["corrupt"] == 0
        assert s.stall_age() is None
        # rot it, scrub again
        raw = bytearray(open(frag.path, "rb").read())
        raw[40] ^= 0x02
        open(frag.path, "wb").write(bytes(raw))
        out = s.pass_once()
        assert out["corrupt"] == 1
        assert hits and hits[0] is frag
        assert frag.quarantined
        st = s.state()
        assert st["corruptionsFound"] == 1 and st["passes"] == 2
        h.close()

    def test_watchdog_scrub_stall_cause(self):
        from pilosa_tpu.obs.watchdog import Watchdog
        wd = Watchdog(scrub_progress_fn=lambda: 42.0,
                      scrub_stall_s=1.0, wal_stall_s=0,
                      gossip_silence_s=0, queue_stall_s=0,
                      deadline_grace_s=0)
        fired = wd.check()
        assert any(c == "scrub_stall" for c, _ in fired)

    def test_sampler_corruption_keep_reason(self):
        from pilosa_tpu.obs.sampler import TailSampler
        s = TailSampler(head_n=0)
        ctx = types.SimpleNamespace(flags={"corruption"}, lane="read",
                                    elapsed=lambda: 0.0)
        assert s.decide(ctx) == "corruption"


# -- serving-layer gates (single node) ----------------------------------------


def _post(host, path, body=b"", timeout=30, headers=None):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST",
                                 headers=headers or {})
    return urllib.request.urlopen(req, timeout=timeout)


def _query_raw(host, index, pql, qs=""):
    return _post(host, f"/index/{index}/query{qs}", pql.encode())


@pytest.fixture
def solo(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_MESH", "0")
    from pilosa_tpu.server.server import Server
    from pilosa_tpu.utils.config import ScrubConfig
    s = Server(str(tmp_path / "solo"), host="127.0.0.1:0",
               anti_entropy_interval=0, polling_interval=0,
               scrub_config=ScrubConfig(interval=999.0, pace=0.0,
                                        repair=False))
    s.open()
    _post(s.host, "/index/it", b"{}")
    _post(s.host, "/index/it/frame/f", b"{}")
    _query_raw(s.host, "it", 'SetBit(frame="f", rowID=1, columnID=3)')
    _query_raw(s.host, "it", 'SetBit(frame="f", rowID=1, columnID=9)')
    yield s
    failpoints.disarm_all()
    s.close()


class TestServingGates:
    def _quarantine(self, s):
        frag = s.holder.fragment("it", "f", "standard", 0)
        frag._set_quarantined("test corruption", site="scrub")
        return frag

    def test_quarantined_single_node_answers_503_not_wrong(self, solo):
        s = solo
        got = json.loads(_query_raw(
            s.host, "it", 'Count(Bitmap(frame="f", rowID=1))').read())
        assert got["results"][0] == 2
        self._quarantine(s)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _query_raw(s.host, "it",
                       'Count(Bitmap(frame="f", rowID=1))')
        assert ei.value.code == 503

    def test_partial_contract_reports_quarantined_slice(self, solo):
        s = solo
        self._quarantine(s)
        resp = _query_raw(s.host, "it",
                          'Count(Bitmap(frame="f", rowID=1))',
                          qs="?partial=1")
        assert resp.status == 200
        assert resp.headers.get("X-Pilosa-Partial") == "0"
        assert json.loads(resp.read())["results"][0] == 0

    def test_writes_keep_applying_while_quarantined(self, solo):
        s = solo
        frag = self._quarantine(s)
        _query_raw(s.host, "it",
                   'SetBit(frame="f", rowID=7, columnID=1)')
        assert frag.row_count(7) == 1  # WAL-buffered locally

    def test_fragment_routes_409_and_antientropy_skip(self, solo):
        s = solo
        frag = self._quarantine(s)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{s.host}/fragment/blocks?index=it&frame=f"
                f"&view=standard&slice=0", timeout=10)
        assert ei.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{s.host}/fragment/data?index=it&frame=f"
                f"&view=standard&slice=0", timeout=10)
        assert ei.value.code == 409
        # the local syncer never lets the copy vote
        from pilosa_tpu.server.syncer import FragmentSyncer
        calls: list = []

        class _Boom:
            def __init__(self, host):
                calls.append(host)
        FragmentSyncer(frag, s.host, s.cluster,
                       client_factory=_Boom).sync_fragment()
        assert not calls, "quarantined fragment must not sync"

    def test_debug_integrity_and_health_surfaces(self, solo):
        s = solo
        out = json.loads(urllib.request.urlopen(
            f"http://{s.host}/debug/integrity", timeout=10).read())
        assert out["quarantined"] == []
        assert out["coverage"]["footered"] >= 1
        assert "scrub" in out
        frag = self._quarantine(s)
        out = json.loads(urllib.request.urlopen(
            f"http://{s.host}/debug/integrity", timeout=10).read())
        assert out["quarantined"][0]["slice"] == 0
        assert out["quarantined"][0]["reason"] == "test corruption"
        # POST ?sync=1 runs a pass inline (skips quarantined frags)
        out = json.loads(_post(
            s.host, "/debug/integrity/scrub?sync=1").read())
        assert "fragments" in out
        # /health: single node + quarantine = not ready (no replica)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{s.host}/health",
                                   timeout=10)
        assert ei.value.code == 503
        checks = json.loads(ei.value.read())["checks"]
        assert checks["storage"]["ok"] is False
        frag.clear_quarantine()
        ok = json.loads(urllib.request.urlopen(
            f"http://{s.host}/health", timeout=10).read())
        assert ok["checks"]["storage"]["ok"] is True


# -- in-process repair cycle (2 nodes, replicas=2) ----------------------------


@pytest.fixture
def duo(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_MESH", "0")
    from pilosa_tpu.cluster.client import Client
    from pilosa_tpu.cluster.topology import Node
    from pilosa_tpu.server.server import Server
    from pilosa_tpu.utils.config import ScrubConfig
    servers = []

    def make(name):
        s = Server(str(tmp_path / name), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0,
                   scrub_config=ScrubConfig(interval=999.0, pace=0.0,
                                            repair=False))
        s.open()
        servers.append(s)
        return s

    s1, s2 = make("n1"), make("n2")
    for s in servers:
        s.cluster.nodes = [Node(s1.host), Node(s2.host)]
        s.cluster.replica_n = 2
    for h in (s1.host, s2.host):
        _post(h, "/index/rp", b"{}")
        _post(h, "/index/rp/frame/f", b"{}")
    rng = np.random.default_rng(11)
    rows = rng.integers(0, 6, 1500).astype(np.uint64)
    cols = rng.choice(2 * SLICE_WIDTH, size=1500,
                      replace=False).astype(np.uint64)
    Client(s1.host).import_arrays("rp", "f", rows, cols)
    model: dict = {}
    for r, c in zip(rows.tolist(), cols.tolist()):
        model.setdefault(int(r), set()).add(int(c))
    yield (s1, s2), model
    failpoints.disarm_all()
    for s in servers:
        try:
            s.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


class TestRepair:
    def _counts_ok(self, host, model):
        for row in range(6):
            got = json.loads(_query_raw(
                host, "rp",
                f'Count(Bitmap(frame="f", rowID={row}))').read())
            assert got["results"][0] == len(model.get(row, set())), row

    def test_detect_failover_repair_cycle(self, duo):
        (s1, s2), model = duo
        self._counts_ok(s1.host, model)
        frag = s1.holder.fragment("rp", "f", "standard", 0)
        frag.snapshot(sync=True)
        # rot s1's slice-0 copy on disk, scrub-detect it
        raw = bytearray(open(frag.path, "rb").read())
        raw[len(raw) // 3] ^= 0x40
        open(frag.path, "wb").write(bytes(raw))
        v = frag.verify_on_disk()
        assert v["corrupt"] and frag.quarantined

        # reads fail over to s2's replica: every answer still exact
        self._counts_ok(s1.host, model)
        self._counts_ok(s2.host, model)

        # repair re-streams from the replica and un-quarantines
        from pilosa_tpu.server.repair import Repairer
        rep = Repairer(s1.holder, s1.cluster, s1.host,
                       client_factory=s1._client_factory,
                       fault=s1.fault)
        assert rep.repair_fragment(frag) == "repaired"
        assert not frag.quarantined
        assert not s1.holder.quarantine.slice_blocked("rp", 0)
        assert not os.path.exists(frag.path + ".corrupt")
        v = frag.verify_on_disk()
        assert not v["corrupt"]
        # local copy answers exactly again (local fast paths back on)
        self._counts_ok(s1.host, model)
        # and the repaired content equals the replica's, block by block
        f2 = s2.holder.fragment("rp", "f", "standard", 0)
        assert dict(frag.blocks()) == dict(f2.blocks())

    def test_missing_source_fragment_never_counts_as_converged(
            self, duo):
        """Review regression: stream_fragment answers (0, 0) for a
        MISSING source too — a peer that never materialized the
        fragment must NOT let the repairer un-quarantine the fresh
        empty replacement as authoritative (a silent wrong answer)."""
        (s1, s2), model = duo
        frag = s1.holder.fragment("rp", "f", "standard", 0)
        frag._set_quarantined("test", site="scrub")
        # Drop the replica's copy of this exact fragment.
        v2 = s2.holder.index("rp").frame("f").view("standard")
        f2 = v2.fragments.pop(0)
        f2.close()
        from pilosa_tpu.server.repair import Repairer
        rep = Repairer(s1.holder, s1.cluster, s1.host,
                       client_factory=s1._client_factory,
                       fault=s1.fault)
        assert rep.repair_fragment(frag) == "failed"
        assert frag.quarantined, \
            "no source content: must stay quarantined"
        v2.fragments[0] = f2
        f2.open()

    def test_no_replica_outcome(self, duo):
        (s1, s2), model = duo
        frag = s1.holder.fragment("rp", "f", "standard", 0)
        frag._set_quarantined("test", site="scrub")
        from pilosa_tpu.cluster.topology import Node
        from pilosa_tpu.server.repair import Repairer
        s1.cluster.nodes = [Node(s1.host)]  # peers gone
        rep = Repairer(s1.holder, s1.cluster, s1.host,
                       client_factory=s1._client_factory)
        assert rep.repair_fragment(frag) == "no_replica"
        assert frag.quarantined, "stays quarantined: partial contract"

    def test_writes_during_quarantine_survive_repair(self, duo):
        """Acked writes fan to every replica owner, so content written
        WHILE the local copy is quarantined comes home with the
        re-stream."""
        (s1, s2), model = duo
        frag = s1.holder.fragment("rp", "f", "standard", 0)
        frag._set_quarantined("test", site="scrub")
        _query_raw(s1.host, "rp",
                   'SetBit(frame="f", rowID=50, columnID=123)')
        model.setdefault(50, set()).add(123)
        from pilosa_tpu.server.repair import Repairer
        rep = Repairer(s1.holder, s1.cluster, s1.host,
                       client_factory=s1._client_factory,
                       fault=s1.fault)
        assert rep.repair_fragment(frag) == "repaired"
        assert frag.row_count(50) == 1
        self._counts_ok(s1.host, model)


# -- client 507 retry (satellite) ---------------------------------------------


class TestImport507Retry:
    def test_import_retries_507_honoring_retry_after(self, monkeypatch):
        """A mid-import ENOSPC on a peer (PR-14 write-unready) is as
        transient as an admission shed: wait it out like a 429
        instead of failing the import."""
        from pilosa_tpu.cluster.client import Client
        c = Client("peer:1")
        script = [(507, b"full", [("Retry-After", "0.01")]),
                  (507, b"full", [("Retry-After", "0.01")]),
                  (200, b"", [])]
        calls: list = []

        def fake_do(method, path, body=None, headers=None, host=None,
                    idempotent=None, deadline_s=None,
                    headers_out=None):
            status, raw, hs = script[len(calls)]
            calls.append(path)
            if headers_out is not None:
                headers_out.extend(hs)
            return status, raw

        sleeps: list = []
        monkeypatch.setattr(c, "_do", fake_do)
        monkeypatch.setattr(time, "sleep", sleeps.append)
        status, _ = c._do_429("POST", "/import", b"x", {}, None)
        assert status == 200
        assert len(calls) == 3 and len(sleeps) == 2
        assert all(s >= 0.01 for s in sleeps)

    def test_507_bounded_by_budget(self, monkeypatch):
        from pilosa_tpu.cluster.client import Client
        c = Client("peer:1", timeout=0.05)

        def always_507(method, path, body=None, headers=None,
                       host=None, idempotent=None, deadline_s=None,
                       headers_out=None):
            if headers_out is not None:
                headers_out.append(("Retry-After", "100"))
            return 507, b"full"

        monkeypatch.setattr(c, "_do", always_507)
        t0 = time.perf_counter()
        status, _ = c._do_429("POST", "/import", b"x", {}, None)
        assert status == 507
        assert time.perf_counter() - t0 < 1.0


# -- config / CLI --------------------------------------------------------------


class TestConfigSurfaces:
    def test_toml_env_round_trip(self, tmp_path):
        from pilosa_tpu.utils import config as config_mod
        p = tmp_path / "c.toml"
        p.write_text("""
[scrub]
enabled = false
interval = "30s"
pace = "0.5s"
repair = false
repair-rescan = "5s"

[watchdog]
scrub-stall = "45s"
""")
        cfg = config_mod.load(str(p), env={})
        assert cfg.scrub.enabled is False
        assert cfg.scrub.interval == 30.0 and cfg.scrub.pace == 0.5
        assert cfg.scrub.repair is False
        assert cfg.scrub.repair_rescan == 5.0
        assert cfg.watchdog.scrub_stall == 45.0
        cfg2 = config_mod.load("", env={
            "PILOSA_SCRUB_ENABLED": "0",
            "PILOSA_SCRUB_INTERVAL": "12s",
            "PILOSA_SCRUB_PACE": "0.25s",
            "PILOSA_WATCHDOG_SCRUB_STALL": "9s"})
        assert cfg2.scrub.enabled is False
        assert cfg2.scrub.interval == 12.0
        assert cfg2.scrub.pace == 0.25
        assert cfg2.watchdog.scrub_stall == 9.0
        # the default config's to_toml parses back
        out = config_mod.Config().to_toml()
        assert "[scrub]" in out and "scrub-stall" in out

    def test_cli_check_deep_and_inspect(self, tmp_path):
        from pilosa_tpu.cli.commands import main
        # build a mini data-dir shape with one good + one rotten file
        d = tmp_path / "data" / "i" / "f" / "views" / "standard" \
            / "fragments"
        d.mkdir(parents=True)
        good = _mk_bitmap(200, seed=1)
        (d / "0").write_bytes(_footered_bytes(good))
        bad = bytearray(_footered_bytes(_mk_bitmap(200, seed=2)))
        bad[len(bad) // 2] ^= 0x01
        (d / "1").write_bytes(bytes(bad))
        out, err = io.StringIO(), io.StringIO()
        rc = main(["check", "--deep", str(tmp_path / "data")],
                  stdout=out, stderr=err)
        assert rc == 1
        text = out.getvalue()
        assert "CORRUPT" in text and "full coverage" in text
        assert "checked 2 fragments: 1 corrupt" in text
        # clean dir exits 0
        out2 = io.StringIO()
        (d / "1").write_bytes(_footered_bytes(_mk_bitmap(200, seed=2)))
        rc = main(["check", "--deep", str(tmp_path / "data")],
                  stdout=out2, stderr=err)
        assert rc == 0
        # inspect prints coverage
        out3 = io.StringIO()
        rc = main(["inspect", str(d / "0")], stdout=out3, stderr=err)
        assert rc == 0
        assert "Checksums: footer v1" in out3.getvalue()
        # vintage file: coverage "none" but ok
        (d / "0").write_bytes(good.marshal())
        out4 = io.StringIO()
        rc = main(["check", "--deep", str(d / "0")], stdout=out4,
                  stderr=err)
        assert rc == 0
        assert "none coverage" in out4.getvalue()
