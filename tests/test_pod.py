"""End-to-end pod test: a 2-process CPU pod serving PQL as one node.

Boots two whole Server processes joined into one jax.distributed job
(2 procs × 2 virtual CPU devices, gloo collectives), then drives
SetBit/Count/TopN/Bitmap through the coordinator's HTTP API. Counts
reduce with pod-wide psums (parallel.pod + parallel.multihost); bitmap
materialization and the TopN candidate phase ride podLocal HTTP legs.

Style mirror: the reference's multi-process cluster tests
(server/server_test.go:375-496, MustRunMain).
"""

import os
import socket
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _child_env(proc_id: int, jax_port: int, peers: list[str]) -> dict:
    env = dict(os.environ)
    # The axon sitecustomize hook registers the TPU plugin at interpreter
    # start when this var is set — drop it so the children get stock
    # CPU JAX (same trick as __graft_entry__._cpu_mesh_env).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env.update({
        "PILOSA_TPU_DIST_COORDINATOR": f"localhost:{jax_port}",
        "PILOSA_TPU_DIST_NUM_PROCS": "2",
        "PILOSA_TPU_DIST_PROC_ID": str(proc_id),
        "PILOSA_TPU_DIST_CPU_DEVICES": "2",
        "PILOSA_TPU_POD_PEERS": ",".join(peers),
        "PILOSA_TPU_MESH_MIN_SLICES": "1",
    })
    return env


def test_pod_two_process_count_topn(tmp_path):
    jax_port = _free_port()
    peers = [f"localhost:{_free_port()}", f"localhost:{_free_port()}"]
    script = os.path.join(_HERE, "pod_child.py")

    procs = []
    worker_log = tmp_path / "worker.log"
    try:
        for pid in range(2):
            data_dir = tmp_path / f"node{pid}"
            data_dir.mkdir()
            if pid == 0:
                stdout, stderr = subprocess.PIPE, subprocess.PIPE
            else:
                # A file, not a PIPE: nothing drains the long-lived
                # worker, and a full pipe buffer would wedge it.
                stdout = stderr = open(worker_log, "w")
            procs.append(subprocess.Popen(
                [sys.executable, script, str(pid), str(data_dir)],
                env=_child_env(pid, jax_port, peers),
                stdout=stdout, stderr=stderr, text=True))
        out, err = procs[0].communicate(timeout=240)
        assert procs[0].returncode == 0, (
            f"coordinator failed rc={procs[0].returncode}\n"
            f"stdout:\n{out}\nstderr:\n{err[-4000:]}\n"
            f"worker:\n{worker_log.read_text()[-2000:]}")
        assert "POD_TEST_OK" in out, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
