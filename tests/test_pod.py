"""End-to-end pod test: a 2-process CPU pod serving PQL as one node.

Boots two whole Server processes joined into one jax.distributed job
(2 procs × 2 virtual CPU devices, gloo collectives), then drives
SetBit/Count/TopN/Bitmap through the coordinator's HTTP API. Counts
reduce with pod-wide psums (parallel.pod + parallel.multihost); bitmap
materialization and the TopN candidate phase ride podLocal HTTP legs.

Style mirror: the reference's multi-process cluster tests
(server/server_test.go:375-496, MustRunMain).
"""

import os
import sys

from podenv import ChildSet, free_port, pod_env

_HERE = os.path.dirname(os.path.abspath(__file__))


def run_pod(tmp_path, n_procs: int, extra_env: dict | None = None):
    jax_port = free_port()
    peers = [f"localhost:{free_port()}" for _ in range(n_procs)]
    script = os.path.join(_HERE, "pod_child.py")

    children = ChildSet(tmp_path)
    try:
        for pid in range(n_procs):
            data_dir = tmp_path / f"node{pid}"
            data_dir.mkdir()
            env = pod_env(pid, jax_port, peers)
            env.update(extra_env or {})
            children.spawn(
                f"worker{pid}",
                [sys.executable, script, str(pid), str(data_dir)],
                env, pipe=(pid == 0))
        out, err = children.procs["worker0"].communicate(timeout=240)
        assert children.procs["worker0"].returncode == 0, (
            f"coordinator failed"
            f" rc={children.procs['worker0'].returncode}\n"
            f"stdout:\n{out}\nstderr:\n{err[-4000:]}\n"
            f"{children.logs_tail()}")
        assert "POD_TEST_OK" in out, out
    finally:
        children.cleanup()


def test_pod_two_process_count_topn(tmp_path):
    run_pod(tmp_path, 2)


def test_pod_three_process_poisoned_serves_host_path(tmp_path):
    """3 processes: 4 slices land 2/1/1 (owner_pid placement is
    non-trivial), and after a forced partial-dispatch failure the
    poisoned pod must keep serving correct results under concurrent
    load via the host fan-out (pod_child.poison_phase)."""
    run_pod(tmp_path, 3, {"POD_TEST_POISON": "1"})


def test_pod_eight_process_worker_sigkill(tmp_path):
    """8 whole processes (1 virtual device each); worker 7 is SIGKILLed
    between collectives. The coordinator must exit the stalled next
    collective via PILOSA_TPU_POD_TIMEOUT, poison the device path, and
    serve correct host-fan-out results under concurrent load — the
    poison flag's primary real-world trigger, induced by an actual
    death rather than an injected dispatch failure (round-4 verdict
    item 4)."""
    import signal
    import time as time_mod

    n_procs = 8
    jax_port = free_port()
    peers = [f"localhost:{free_port()}" for _ in range(n_procs)]
    script = os.path.join(_HERE, "pod_kill_child.py")
    sentinel = tmp_path / "killed.sentinel"

    children = ChildSet(tmp_path)
    try:
        for pid in range(n_procs):
            data_dir = tmp_path / f"node{pid}"
            data_dir.mkdir()
            env = pod_env(pid, jax_port, peers, cpu_devices=1)
            env["PILOSA_TPU_POD_TIMEOUT"] = "10"
            env["POD_KILL_SENTINEL"] = str(sentinel)
            children.spawn(
                f"worker{pid}",
                [sys.executable, script, str(pid), str(data_dir)],
                env, pipe=(pid == 0))
        coord = children.procs["worker0"]

        # Read coordinator stdout until it says the data is built and
        # the pre-kill collective verified.
        lines = []
        deadline = time_mod.time() + 240
        while time_mod.time() < deadline:
            line = coord.stdout.readline()
            if not line:
                break
            lines.append(line)
            if "READY_FOR_KILL" in line:
                break
        else:
            raise AssertionError("timed out waiting for READY_FOR_KILL")
        assert any("READY_FOR_KILL" in ln for ln in lines), (
            "".join(lines) + children.logs_tail())

        victim = children.procs[f"worker{n_procs - 1}"]
        victim.send_signal(signal.SIGKILL)
        victim.wait()
        sentinel.write_text("killed")

        # communicate() (not sequential reads) so a regression that
        # re-parks the coordinator in the stalled collective fails the
        # test at the timeout instead of wedging it, and a full stderr
        # pipe cannot deadlock the reads.
        out, err = coord.communicate(timeout=240)
        assert coord.returncode == 0, (
            f"coordinator rc={coord.returncode}\nstdout:\n"
            f"{''.join(lines)}{out}\nstderr:\n{err[-4000:]}\n"
            f"{children.logs_tail()}")
        assert "POD_KILL_TEST_OK" in out, out
    finally:
        children.cleanup()
