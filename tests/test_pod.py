"""End-to-end pod test: a 2-process CPU pod serving PQL as one node.

Boots two whole Server processes joined into one jax.distributed job
(2 procs × 2 virtual CPU devices, gloo collectives), then drives
SetBit/Count/TopN/Bitmap through the coordinator's HTTP API. Counts
reduce with pod-wide psums (parallel.pod + parallel.multihost); bitmap
materialization and the TopN candidate phase ride podLocal HTTP legs.

Style mirror: the reference's multi-process cluster tests
(server/server_test.go:375-496, MustRunMain).
"""

import os
import sys

from podenv import ChildSet, free_port, pod_env

_HERE = os.path.dirname(os.path.abspath(__file__))


def run_pod(tmp_path, n_procs: int, extra_env: dict | None = None):
    jax_port = free_port()
    peers = [f"localhost:{free_port()}" for _ in range(n_procs)]
    script = os.path.join(_HERE, "pod_child.py")

    children = ChildSet(tmp_path)
    try:
        for pid in range(n_procs):
            data_dir = tmp_path / f"node{pid}"
            data_dir.mkdir()
            env = pod_env(pid, jax_port, peers)
            env.update(extra_env or {})
            children.spawn(
                f"worker{pid}",
                [sys.executable, script, str(pid), str(data_dir)],
                env, pipe=(pid == 0))
        out, err = children.procs["worker0"].communicate(timeout=240)
        assert children.procs["worker0"].returncode == 0, (
            f"coordinator failed"
            f" rc={children.procs['worker0'].returncode}\n"
            f"stdout:\n{out}\nstderr:\n{err[-4000:]}\n"
            f"{children.logs_tail()}")
        assert "POD_TEST_OK" in out, out
    finally:
        children.cleanup()


def test_pod_two_process_count_topn(tmp_path):
    run_pod(tmp_path, 2)


def test_pod_three_process_poisoned_serves_host_path(tmp_path):
    """3 processes: 4 slices land 2/1/1 (owner_pid placement is
    non-trivial), and after a forced partial-dispatch failure the
    poisoned pod must keep serving correct results under concurrent
    load via the host fan-out (pod_child.poison_phase)."""
    run_pod(tmp_path, 3, {"POD_TEST_POISON": "1"})
