"""Workload capture / replay / shadow tests (ISSUE 19): the canonical
result digest (incl. TopN tie-breaking), PQL redaction, sampling modes,
ring round-trip + torn-tail reopen, paged export, stream merging and
gap-preserving schedules, the handler integration (digest header, slow
log cross-links, /debug/capture routes), the shadow diff catching a
deliberately corrupted candidate over real HTTP, and — additionally
``slow`` — a real 2-node cluster leg with merged export + replay +
zero-self-mismatch shadow."""

import json
import os
import sys
import time

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from pilosa_tpu.executor import Executor  # noqa: E402
from pilosa_tpu.models.holder import Holder  # noqa: E402
from pilosa_tpu.obs import capture as obs_capture  # noqa: E402
from pilosa_tpu.obs import replay as obs_replay  # noqa: E402
from pilosa_tpu.obs.capture import CaptureStore  # noqa: E402
from pilosa_tpu.proto import internal_pb2 as pb  # noqa: E402
from pilosa_tpu.sched.registry import QueryRegistry  # noqa: E402
from pilosa_tpu.server.handler import Handler  # noqa: E402

from test_handler import call  # noqa: E402

pytestmark = pytest.mark.replay


# -- digest canonicalization --------------------------------------------------


class TestResultDigest:
    def test_topn_equal_counts_tie_broken_by_id(self):
        """Two servers may order equal-count TopN pairs differently —
        the canonical digest must not care."""
        a = [[{"id": 7, "count": 3}, {"id": 2, "count": 3},
              {"id": 9, "count": 5}]]
        b = [[{"id": 9, "count": 5}, {"id": 2, "count": 3},
              {"id": 7, "count": 3}]]
        assert obs_capture.result_digest(a) \
            == obs_capture.result_digest(b)
        norm = obs_capture.normalize_result(a[0])
        assert [(e["count"], e["id"]) for e in norm] \
            == [(5, 9), (3, 2), (3, 7)]  # count desc, id asc on ties

    def test_distinct_results_distinct_digests(self):
        d1 = obs_capture.result_digest([{"bits": [1, 2, 3]}])
        d2 = obs_capture.result_digest([{"bits": [1, 2, 4]}])
        assert d1 != d2
        assert len(d1) == 16 and int(d1, 16) >= 0  # 64-bit hex

    def test_dict_key_order_irrelevant(self):
        d1 = obs_capture.result_digest([{"attrs": {}, "bits": [3]}])
        d2 = obs_capture.result_digest([{"bits": [3], "attrs": {}}])
        assert d1 == d2

    def test_pair_lists_normalized_inside_containers(self):
        a = [{"topn": [{"id": 1, "count": 2}, {"id": 0, "count": 2}]}]
        b = [{"topn": [{"id": 0, "count": 2}, {"id": 1, "count": 2}]}]
        assert obs_capture.result_digest(a) \
            == obs_capture.result_digest(b)

    def test_scalars_pass_through(self):
        assert obs_capture.result_digest([True, 42]) \
            != obs_capture.result_digest([True, 43])


# -- redaction ----------------------------------------------------------------


class TestRedaction:
    def test_string_and_numeric_literals_replaced(self):
        pql = 'SetBit(rowID=1, frame="secret-frame", columnID=314159)'
        red = obs_capture.redact_pql(pql)
        assert "secret-frame" not in red and "314159" not in red
        assert red == 'SetBit(rowID=?, frame="?", columnID=?)'

    def test_digits_inside_strings_redact_with_the_string(self):
        assert obs_capture.redact_pql('Bitmap(frame="f2024")') \
            == 'Bitmap(frame="?")'

    def test_call_shape_survives(self):
        red = obs_capture.redact_pql(
            'TopN(frame="f", n=5, field="x")')
        assert red.startswith("TopN(") and "n=?" in red

    def test_redacts_per_tenant_and_wildcard(self, tmp_path):
        s = CaptureStore(str(tmp_path / "c"), mode="full",
                         redact_tenants={"acme"})
        try:
            assert s.redacts("acme") and not s.redacts("other")
        finally:
            s.close()
        s = CaptureStore(str(tmp_path / "c2"), mode="full",
                         redact_tenants={"*"})
        try:
            assert s.redacts("anyone")
        finally:
            s.close()

    def test_add_applies_redaction_for_listed_tenant(self, tmp_path):
        s = CaptureStore(str(tmp_path / "c"), mode="full",
                         redact_tenants={"acme"})
        try:
            s.add("query", 'Bitmap(frame="f", rowID=7)', "i", "acme",
                  "read", "q1", 200, 0.001)
            s.add("query", 'Bitmap(frame="f", rowID=7)', "i", "open",
                  "read", "q2", 200, 0.001)
            recs = s.export()
            assert recs[0]["pql"] == 'Bitmap(frame="?", rowID=?)'
            assert recs[1]["pql"] == 'Bitmap(frame="f", rowID=7)'
        finally:
            s.close()


# -- sampling modes -----------------------------------------------------------


class TestSampling:
    def test_off_is_disabled(self, tmp_path):
        s = CaptureStore(str(tmp_path / "c"), mode="off")
        try:
            assert not s.enabled
            assert not s.should_capture("write")
            assert not s.should_capture("read")
        finally:
            s.close()

    def test_sampled_records_every_write_and_one_in_n_reads(
            self, tmp_path):
        s = CaptureStore(str(tmp_path / "c"), mode="sampled",
                         sample_n=4)
        try:
            assert s.enabled
            assert all(s.should_capture("write") for _ in range(10))
            assert all(s.should_capture("admin") for _ in range(3))
            kept = sum(s.should_capture("read") for _ in range(16))
            assert kept == 4  # deterministic 1-in-4
        finally:
            s.close()

    def test_sample_n_one_keeps_every_read(self, tmp_path):
        s = CaptureStore(str(tmp_path / "c"), mode="sampled",
                         sample_n=1)
        try:
            assert all(s.should_capture("read") for _ in range(5))
        finally:
            s.close()

    def test_full_keeps_everything(self, tmp_path):
        s = CaptureStore(str(tmp_path / "c"), mode="full",
                         sample_n=1000)
        try:
            assert all(s.should_capture("read") for _ in range(5))
        finally:
            s.close()

    def test_invalid_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            CaptureStore(str(tmp_path / "c"), mode="everything")


# -- ring round-trip + torn tail ----------------------------------------------


class TestRoundTrip:
    def test_wire_format_and_monotonic_seq(self, tmp_path):
        s = CaptureStore(str(tmp_path / "c"), mode="full", node="n1")
        try:
            cid = s.add("query", 'Bitmap(frame="f", rowID=1)', "i",
                        "t1", "read", "qid-1", 200, 0.0123,
                        digest="ab" * 8, plan="deadbeefcafe",
                        opts={"timeout": "5s", "partial": True})
            assert cid == 1
            s.add("import", "", "i", "i", "write", "", 200, 0.002,
                  bits=64, slice=3, frame="f")
            recs = s.export()
        finally:
            s.close()
        assert [r["seq"] for r in recs] == [1, 2]
        q = recs[0]
        for key in ("seq", "t", "mono", "kind", "pql", "index",
                    "tenant", "lane", "qid", "plan", "status", "latS",
                    "digest", "node"):
            assert key in q, key
        assert q["kind"] == "query" and q["node"] == "n1"
        assert q["digest"] == "ab" * 8
        assert q["opts"] == {"timeout": "5s", "partial": True}
        imp = recs[1]
        assert imp["kind"] == "import"
        assert (imp["bits"], imp["slice"], imp["frame"]) == (64, 3, "f")

    def test_reopen_resumes_seq(self, tmp_path):
        d = str(tmp_path / "c")
        s = CaptureStore(d, mode="full")
        for i in range(5):
            s.add("query", "Count()", "i", "i", "read", f"q{i}",
                  200, 0.001)
        s.close()
        s = CaptureStore(d, mode="full")
        try:
            cid = s.add("query", "Count()", "i", "i", "read", "q5",
                        200, 0.001)
            assert cid == 6  # cursor resumed past the survivors
        finally:
            s.close()

    def test_torn_tail_skipped_and_seq_stays_monotonic(self, tmp_path):
        """A crash mid-append leaves a torn last line; reopen must
        serve every intact record and keep the cursor monotonic."""
        d = str(tmp_path / "c")
        s = CaptureStore(d, mode="full")
        for i in range(8):
            s.add("query", f"Count(Bitmap(rowID={i}))", "i", "i",
                  "read", f"q{i}", 200, 0.001)
        s.close()
        segs = sorted(f for f in os.listdir(d) if f.endswith(".jsonl"))
        assert segs
        tail = os.path.join(d, segs[-1])
        with open(tail, "rb") as f:
            raw = f.read()
        with open(tail, "wb") as f:
            f.write(raw[:-7])  # tear the last frame mid-line
        s = CaptureStore(d, mode="full")
        try:
            recs = s.export()
            seqs = [r["seq"] for r in recs]
            assert seqs == sorted(seqs)
            assert 7 <= len(recs) < 8  # the torn record is gone
            cid = s.add("query", "Count()", "i", "i", "read", "q8",
                        200, 0.001)
            assert cid > max(seqs)
        finally:
            s.close()


class TestPagedExport:
    @pytest.fixture
    def store(self, tmp_path):
        s = CaptureStore(str(tmp_path / "c"), mode="full")
        for i in range(10):
            s.add("query", f"q{i}", "i", "i", "read", f"id{i}",
                  200, 0.001)
        yield s
        s.close()

    def test_since_limit_pages_oldest_first(self, store):
        page = store.export(since=0, limit=3)
        assert [r["seq"] for r in page] == [1, 2, 3]
        nxt = store.export(since=page[-1]["seq"], limit=100)
        assert [r["seq"] for r in nxt] == [4, 5, 6, 7, 8, 9, 10]

    def test_since_past_end_empty(self, store):
        assert store.export(since=10) == []

    def test_limit_clamped(self, store):
        assert len(store.export(limit=0)) == 1  # floor 1
        assert len(store.export(limit=10**9)) == 10  # ceiling holds

    def test_status_shape(self, store):
        st = store.status()
        assert st["mode"] == "full" and st["seq"] == 10
        assert st["budgetBytes"] == (st["ring"]["segmentBytes"]
                                     * st["ring"]["maxSegments"])


# -- merging + gap-preserving schedules ---------------------------------------


class TestMergeAndSchedule:
    def test_merge_streams_orders_by_wall_then_node_seq(self):
        a = [{"seq": 1, "t": 10.0, "node": "a"},
             {"seq": 2, "t": 30.0, "node": "a"}]
        b = [{"seq": 1, "t": 20.0, "node": "b"},
             {"seq": 2, "t": 10.0, "node": "b"}]
        merged = obs_capture.merge_streams([a, b])
        assert [(r["node"], r["seq"]) for r in merged] \
            == [("a", 1), ("b", 2), ("b", 1), ("a", 2)]

    def test_single_node_offsets_use_monotonic_stamps(self):
        recs = [{"node": "a", "t": 100.0, "mono": 5.0},
                {"node": "a", "t": 100.1, "mono": 5.25},
                {"node": "a", "t": 999.0, "mono": 5.35}]  # wall step
        offs = obs_capture.arrival_offsets(recs)
        assert offs == [0.0, pytest.approx(0.25), pytest.approx(0.35)]

    def test_merged_streams_fall_back_to_wall_clock(self):
        recs = [{"node": "a", "t": 100.0, "mono": 5.0},
                {"node": "b", "t": 100.5, "mono": 900.0}]
        offs = obs_capture.arrival_offsets(recs)
        assert offs == [0.0, pytest.approx(0.5)]

    def test_offsets_never_negative(self):
        recs = [{"node": "a", "t": 100.0, "mono": 5.0},
                {"node": "a", "t": 99.0, "mono": 4.0}]
        assert obs_capture.arrival_offsets(recs)[1] == 0.0

    def test_schedule_rate_compresses_gaps(self):
        recs = [{"node": "a", "t": 0.0, "mono": 0.0},
                {"node": "a", "t": 1.0, "mono": 1.0}]
        assert obs_replay.schedule(recs, rate=4.0)[1] \
            == pytest.approx(0.25)

    def test_replay_shard_preserves_inter_arrival_gaps(self):
        """The open-loop unit: three records 0.12 s apart against a
        dead endpoint (connection refused is instant) must still take
        the full recorded span — sends fire at their offsets, not
        back-to-back."""
        recs = [{"kind": "query", "lane": "read", "index": "i",
                 "pql": "Count()", "node": "a", "t": float(i),
                 "mono": 0.12 * i} for i in range(3)]
        offs = obs_replay.schedule(recs, rate=1.0)
        t0 = time.perf_counter()
        outcomes = obs_replay._replay_shard(
            (recs, offs, "127.0.0.1:9", time.time(), 2))
        elapsed = time.perf_counter() - t0
        assert elapsed >= 0.24  # the recorded span, not instant
        assert len(outcomes) == 3
        assert all(o["status"] == 0 for o in outcomes)  # refused


# -- replay units -------------------------------------------------------------


class TestReplayUnits:
    def test_load_records_jsonl_and_response_doc(self, tmp_path):
        recs = [{"seq": 1, "kind": "query"}, {"seq": 2, "kind": "query"}]
        p1 = tmp_path / "r.jsonl"
        p1.write_text("".join(json.dumps(r) + "\n" for r in recs))
        assert obs_replay.load_records(str(p1)) == recs
        p2 = tmp_path / "r.json"
        p2.write_text(json.dumps({"scope": "cluster", "records": recs}))
        assert obs_replay.load_records(str(p2)) == recs
        p3 = tmp_path / "empty.jsonl"
        p3.write_text("")
        assert obs_replay.load_records(str(p3)) == []

    def test_summarize_lanes_shed_and_percentiles(self):
        outcomes = (
            [{"lane": "read", "status": 200, "latS": 0.01}] * 98
            + [{"lane": "read", "status": 429, "latS": 0.0}] * 2
            + [{"lane": "write", "status": 200, "latS": 0.02}] * 9
            + [{"lane": "write", "status": 500, "latS": 0.0}]
            + [{"lane": "write", "status": -1, "latS": 0.0}])
        s = obs_replay._summarize(outcomes, offered_qps=111.0,
                                  wall_s=1.0)
        assert s["offered"] == 110 and s["skipped_imports"] == 1
        assert s["completed"] == 107 and s["shed"] == 2
        assert s["errors"] == 1
        r = s["lanes"]["read"]
        assert r["sent"] == 100 and r["shed_rate"] == 0.02
        assert r["p50_ms"] == 10.0 and r["p99_ms"] == 10.0
        assert s["lanes"]["write"]["errors"] == 1
        assert s["achieved_qps"] == 107.0

    def test_empty_replay_summary(self):
        s = obs_replay.replay([], "127.0.0.1:9")
        assert s["offered"] == 0 and s["completed"] == 0

    def test_cli_replay_parser(self):
        from pilosa_tpu.cli.commands import build_parser
        args = build_parser().parse_args(
            ["replay", "--records", "r.jsonl", "--rate", "x4",
             "--processes", "2", "--senders", "8",
             "--shadow", "127.0.0.1:1", "127.0.0.1:2",
             "--out", "out.json"])
        assert args.records == "r.jsonl" and args.rate == "x4"
        assert args.processes == 2 and args.senders == 8
        assert args.shadow == ["127.0.0.1:1", "127.0.0.1:2"]
        args = build_parser().parse_args(
            ["replay", "--from", "127.0.0.1:10101"])
        assert args.from_host == "127.0.0.1:10101"
        assert args.rate == "x1" and args.processes == 1


# -- handler integration ------------------------------------------------------


@pytest.fixture
def captured_handler(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    cap = CaptureStore(str(tmp_path / "capture"), mode="full",
                       node="local")
    handler = Handler(
        h, Executor(h, host="local"), host="local", capture=cap,
        registry=QueryRegistry(slow_threshold_s=1e-9))
    yield handler, cap
    cap.close()
    h.close()


class TestHandlerIntegration:
    def _setup_index(self, handler):
        call(handler, "POST", "/index/i", b"{}")
        call(handler, "POST", "/index/i/frame/f", b"{}")

    def test_digest_header_and_capture_record(self, captured_handler):
        handler, cap = captured_handler
        self._setup_index(handler)
        st, hd, body = call(
            handler, "POST", "/index/i/query?timeout=5s",
            b'SetBit(frame="f", rowID=1, columnID=3)')
        assert st == 200
        st, hd, body = call(handler, "POST", "/index/i/query",
                            b'Bitmap(frame="f", rowID=1)')
        assert st == 200
        digest = hd[obs_capture.DIGEST_HEADER]
        # The header IS the canonical digest of the response body.
        assert digest == obs_capture.result_digest(
            json.loads(body)["results"])
        st, _, body = call(handler, "GET",
                           "/debug/capture/records?since=0&limit=10")
        assert st == 200
        recs = json.loads(body)["records"]
        assert [r["kind"] for r in recs] == ["query", "query"]
        assert recs[0]["lane"] == "write"
        assert recs[0]["opts"] == {"timeout": "5s"}
        assert recs[1]["digest"] == digest
        assert recs[1]["qid"]  # the X-Pilosa-Query-Id rode along
        # Planner on by default: the plan fingerprint rides the read.
        assert len(recs[1]["plan"]) == 12

    def test_slow_log_cross_links_digest_and_capture_id(
            self, captured_handler):
        handler, cap = captured_handler
        self._setup_index(handler)
        call(handler, "POST", "/index/i/query",
             b'SetBit(frame="f", rowID=1, columnID=3)')
        st, hd, _ = call(handler, "POST", "/index/i/query",
                         b'Bitmap(frame="f", rowID=1)')
        st, _, body = call(handler, "GET", "/debug/queries/slow")
        assert st == 200
        entry = json.loads(body)["slow"][-1]
        assert entry["resultDigest"] == hd[obs_capture.DIGEST_HEADER]
        assert entry["captureId"] == 2

    def test_no_digest_header_on_errors(self, captured_handler):
        handler, cap = captured_handler
        self._setup_index(handler)
        st, hd, _ = call(handler, "POST", "/index/i/query",
                         b"Bitmap(nope")
        assert st == 400
        assert obs_capture.DIGEST_HEADER not in hd

    def test_import_ack_captured(self, captured_handler):
        handler, cap = captured_handler
        self._setup_index(handler)
        req = pb.ImportRequest(Index="i", Frame="f", Slice=0,
                               RowIDs=[1, 1, 2], ColumnIDs=[3, 4, 5])
        st, _, _ = call(handler, "POST", "/import",
                        req.SerializeToString(),
                        content_type="application/x-protobuf",
                        accept="application/x-protobuf")
        assert st == 200
        recs = cap.export()
        imp = [r for r in recs if r["kind"] == "import"]
        assert len(imp) == 1
        assert imp[0]["bits"] == 3 and imp[0]["lane"] == "write"
        assert imp[0]["frame"] == "f" and imp[0]["slice"] == 0

    def test_capture_status_route(self, captured_handler):
        handler, cap = captured_handler
        self._setup_index(handler)
        call(handler, "POST", "/index/i/query",
             b'SetBit(frame="f", rowID=1, columnID=3)')
        st, _, body = call(handler, "GET", "/debug/capture")
        assert st == 200
        doc = json.loads(body)
        assert doc["enabled"] is True and doc["mode"] == "full"
        assert doc["seq"] == 1 and doc["ring"]["written"] == 1

    def test_records_route_validates_params(self, captured_handler):
        handler, cap = captured_handler
        st, _, _ = call(handler, "GET",
                        "/debug/capture/records?since=nope")
        assert st == 400
        st, _, body = call(handler, "GET", "/debug/capture/records")
        assert st == 200
        doc = json.loads(body)
        assert doc["records"] == [] and doc["next"] == 0

    def test_capture_none_routes_still_answer(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        try:
            handler = Handler(h, Executor(h, host="local"),
                              host="local")
            st, _, body = call(handler, "GET", "/debug/capture")
            assert st == 200
            assert json.loads(body) == {"enabled": False,
                                        "mode": "off"}
            st, hd, _ = call(handler, "GET", "/version")
            assert st == 200
        finally:
            h.close()

    def test_off_mode_writes_nothing(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        cap = CaptureStore(str(tmp_path / "capture"), mode="off")
        try:
            handler = Handler(h, Executor(h, host="local"),
                              host="local", capture=cap)
            call(handler, "POST", "/index/i", b"{}")
            call(handler, "POST", "/index/i/frame/f", b"{}")
            st, hd, _ = call(
                handler, "POST", "/index/i/query",
                b'SetBit(frame="f", rowID=1, columnID=3)')
            assert st == 200
            # The digest header still rides (it is not a capture
            # feature); the ring stays untouched.
            assert obs_capture.DIGEST_HEADER in hd
            assert cap.ring.written == 0 and cap.export() == []
        finally:
            cap.close()
            h.close()


# -- shadow diff over real HTTP -----------------------------------------------


def _start_server(tmp_path, name):
    from pilosa_tpu.server.server import Server
    s = Server(str(tmp_path / name), host="127.0.0.1:0",
               anti_entropy_interval=0, polling_interval=0)
    s.open()
    return s


def _post(host, path, body=b""):
    import urllib.request
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.status, r.read()


class TestShadowDiff:
    def test_self_shadow_clean_then_corrupted_candidate_caught(
            self, tmp_path):
        """Identical write streams to both endpoints → zero
        mismatches; then one extra bit seeded into the candidate only
        is caught with digests + full result dumps."""
        sa = _start_server(tmp_path, "a")
        sb = _start_server(tmp_path, "b")
        try:
            for host in (sa.host, sb.host):
                _post(host, "/index/i", b"{}")
                _post(host, "/index/i/frame/f", b"{}")
            writes = [
                {"seq": i + 1, "kind": "query", "lane": "write",
                 "index": "i", "tenant": "i", "node": "cap",
                 "t": float(i), "mono": float(i),
                 "pql": f'SetBit(frame="f", rowID=1, columnID={c})'}
                for i, c in enumerate((3, 5, 900))]
            reads = [
                {"seq": 10, "kind": "query", "lane": "read",
                 "index": "i", "tenant": "i", "node": "cap",
                 "t": 10.0, "mono": 10.0, "plan": "",
                 "pql": 'Bitmap(frame="f", rowID=1)'},
                {"seq": 11, "kind": "query", "lane": "read",
                 "index": "i", "tenant": "i", "node": "cap",
                 "t": 11.0, "mono": 11.0, "plan": "",
                 "pql": 'Count(Bitmap(frame="f", rowID=1))'},
            ]
            clean = obs_replay.shadow(writes + reads, sa.host, sb.host,
                                      senders=2)
            assert clean["writes_replayed"] == 3
            assert clean["reads_compared"] == 2
            assert clean["mismatches"] == 0
            assert clean["mismatch_rate"] == 0.0

            # Seed the divergence: one bit only the candidate has.
            _post(sb.host, "/index/i/query",
                  b'SetBit(frame="f", rowID=1, columnID=31337)')
            diff = obs_replay.shadow(reads, sa.host, sb.host,
                                     senders=2)
            assert diff["mismatches"] == 2
            assert diff["mismatch_rate"] == 1.0
            assert len(diff["dumps"]) == 2
            for dump in diff["dumps"]:
                assert (dump["baselineDigest"]
                        != dump["candidateDigest"])
                assert "plan" in dump
                assert "31337" not in json.dumps(
                    dump["baselineResults"])
            # Dump completion order is nondeterministic with
            # concurrent senders; the seeded bit shows up in the
            # Bitmap dump, whichever slot it landed in.
            assert any(
                "31337" in json.dumps(d["candidateResults"])
                for d in diff["dumps"])
        finally:
            sb.close()
            sa.close()

    def test_replay_against_live_server(self, tmp_path):
        """Inline (fork-free) replay of a captured stream against a
        real server: every query completes, per-lane stats populate."""
        s = _start_server(tmp_path, "r")
        try:
            _post(s.host, "/index/i", b"{}")
            _post(s.host, "/index/i/frame/f", b"{}")
            recs = []
            for i in range(6):
                lane = "write" if i % 2 == 0 else "read"
                pql = (f'SetBit(frame="f", rowID=1, columnID={i})'
                       if lane == "write"
                       else 'Bitmap(frame="f", rowID=1)')
                recs.append({"seq": i + 1, "kind": "query",
                             "lane": lane, "index": "i", "tenant": "i",
                             "node": "cap", "t": float(i) * 0.01,
                             "mono": float(i) * 0.01, "pql": pql})
            out = obs_replay.replay(recs, s.host, rate=10.0,
                                    processes=1, senders=4)
            assert out["offered"] == 6 and out["completed"] == 6
            assert out["errors"] == 0
            assert set(out["lanes"]) == {"read", "write"}
            assert out["lanes"]["read"]["p99_ms"] > 0
        finally:
            s.close()


# -- the real 2-node leg (slow) -----------------------------------------------


@pytest.mark.slow
class TestTwoNodeCaptureLeg:
    def test_cluster_capture_merge_replay_and_self_shadow(
            self, tmp_path):
        """Full-capture 2-node gossip cluster: traffic served by each
        node lands in that node's ring, ``?scope=cluster`` merges both
        exports in arrival order, the merged stream replays cleanly
        against the cluster, and a shadow between the two members of
        the SAME cluster shows zero mismatches."""
        import signal
        import subprocess

        from podenv import cpu_env, free_port, wait_up

        pa, pb = free_port(), free_port()
        ga, gb = free_port(), free_port()
        hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
        procs, logs = [], []

        def spawn(name, port, internal, seed=""):
            d = tmp_path / name
            d.mkdir(exist_ok=True)
            env = cpu_env()
            env["PILOSA_TPU_MESH"] = "0"
            env["PILOSA_TPU_WARMUP"] = "0"
            env["PILOSA_CAPTURE_MODE"] = "full"
            env["PILOSA_SENTINEL_ENABLED"] = "0"
            log = open(tmp_path / f"{name}.log", "a")
            logs.append(log)
            argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                    "-d", str(d), "-b", f"127.0.0.1:{port}",
                    "--cluster.type", "gossip",
                    "--cluster.hosts", hosts,
                    "--cluster.replicas", "1",
                    "--cluster.internal-port", str(internal),
                    "--anti-entropy.interval", "300s"]
            if seed:
                argv += ["--cluster.gossip-seed", seed]
            p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                                 cwd=os.path.dirname(_HERE))
            procs.append(p)
            wait_up(f"127.0.0.1:{port}")
            return f"127.0.0.1:{port}"

        try:
            host_a = spawn("a", pa, ga)
            host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
            _post(host_a, "/index/cap", b"{}")
            _post(host_a, "/index/cap/frame/f", b"{}")
            # Traffic on BOTH nodes: each captures what it served.
            for i, host in enumerate([host_a, host_b] * 4):
                _post(host, "/index/cap/query",
                      f'SetBit(frame="f", rowID=1, columnID={i})'
                      .encode())
            for host in (host_a, host_b):
                for _ in range(3):
                    _post(host, "/index/cap/query",
                          b'Bitmap(frame="f", rowID=1)')

            # Per-node rings hold only what each node served.
            own_a = obs_replay.fetch_records(host_a)
            own_b = obs_replay.fetch_records(host_b)
            assert len(own_a) == 7 and len(own_b) == 7
            assert {r["node"] for r in own_a} == {host_a}

            # The merged cluster export sees both nodes, in arrival
            # order, and matches a manual merge of the two streams.
            merged = obs_replay.fetch_records(host_a, cluster=True)
            assert len(merged) == 14
            assert {r["node"] for r in merged} == {host_a, host_b}
            ts = [r["t"] for r in merged]
            assert ts == sorted(ts)
            assert merged == obs_capture.merge_streams([own_a, own_b])

            # The merged stream replays cleanly against the cluster.
            out = obs_replay.replay(merged, host_a, rate=50.0,
                                    processes=1, senders=8)
            assert out["completed"] == 14 and out["errors"] == 0

            # Two members of one cluster must agree on every read:
            # zero self-mismatches (writes are replayed into the same
            # cluster twice — SetBit is idempotent).
            shadow = obs_replay.shadow(merged, host_a, host_b,
                                       senders=4)
            assert shadow["reads_compared"] == 6
            assert shadow["mismatches"] == 0
        finally:
            for p in procs:
                try:
                    p.send_signal(signal.SIGINT)
                except OSError:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()
            for log in logs:
                log.close()
