"""Shape-stable global-view program catalogue (parallel.programs):
slice buckets, fused multi-op trees, bucket-bound compile counts, and
the cross-process persistent XLA compile cache (ROADMAP item 1 /
VERDICT weak #2 + #6 acceptance)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.parallel import mesh as mesh_mod
from pilosa_tpu.parallel import programs


def _popcount(a: np.ndarray) -> int:
    return int(np.bitwise_count(a).sum())


class TestSliceBuckets:
    def test_bucket_ladder(self):
        # n_dev × 2^k ladder: every count in (bucket/2, bucket] shares
        # one compiled shape.
        assert programs.slice_bucket(0, 8) == 8
        assert programs.slice_bucket(1, 8) == 8
        assert programs.slice_bucket(8, 8) == 8
        assert programs.slice_bucket(9, 8) == 16
        assert programs.slice_bucket(16, 8) == 16
        assert programs.slice_bucket(17, 8) == 32
        assert programs.slice_bucket(32, 8) == 32
        assert programs.slice_bucket(33, 8) == 64

    def test_bucket_count_is_logarithmic(self):
        buckets = {programs.slice_bucket(n, 8) for n in range(1, 1025)}
        assert len(buckets) == 8  # 8, 16, ..., 1024

    def test_above_largest_bucket_falls_back_to_device_multiple(self):
        bound = mesh_mod.slice_chunk_bound(8)
        big = bound - 3  # above the largest 8×2^k under the bound
        got = programs.slice_bucket(big, 8)
        assert got >= big and got % 8 == 0 and got <= (1 << 15)

    def test_bucket_pad_is_count_identity(self):
        rng = np.random.default_rng(0)
        m = mesh_mod.make_mesh(8)
        leaves = rng.integers(0, 2**32, size=(2, 11, 128),
                              dtype=np.uint32)
        padded = programs.bucket_pad(leaves, 1, 8)
        assert padded.shape[1] == 16
        arrs = [mesh_mod.shard_slices(m, padded[i]) for i in range(2)]
        got = mesh_mod.count_expr_sharded(
            m, ("and", ("leaf", 0), ("leaf", 1)), arrs)
        assert got == _popcount(leaves[0] & leaves[1])


class TestFusedTree:
    def test_counts_and_topn_one_program_one_fetch(self):
        rng = np.random.default_rng(3)
        m = mesh_mod.make_mesh(8)
        S, W, R = 16, 256, 5
        leaves = rng.integers(0, 2**32, size=(3, S, W), dtype=np.uint32)
        rows = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)
        arrs = [mesh_mod.shard_slices(m, leaves[i]) for i in range(3)]
        d_rows = mesh_mod.shard_slices(m, rows)
        exprs = (("and", ("leaf", 0), ("leaf", 1)),
                 ("andnot", ("leaf", 2), ("leaf", 0)))
        counts, topns = mesh_mod.fused_tree_sharded(
            m, exprs, [(("leaf", 1), R)], arrs, [d_rows])
        assert counts == [
            _popcount(leaves[0] & leaves[1]),
            _popcount(leaves[2] & ~leaves[0])]
        assert topns[0] == [_popcount(rows[:, r, :] & leaves[1])
                            for r in range(R)]

    def test_topn_only_tree(self):
        rng = np.random.default_rng(4)
        m = mesh_mod.make_mesh(8)
        S, W, R = 8, 128, 3
        leaves = rng.integers(0, 2**32, size=(1, S, W), dtype=np.uint32)
        rows = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)
        arrs = [mesh_mod.shard_slices(m, leaves[0])]
        counts, topns = mesh_mod.fused_tree_sharded(
            m, (), [(("leaf", 0), R)], arrs,
            [mesh_mod.shard_slices(m, rows)])
        assert counts == []
        assert topns[0] == [_popcount(rows[:, r, :] & leaves[0])
                            for r in range(R)]


class TestTopKProgram:
    def test_lo_sum_carry_does_not_break_order(self):
        """The per-candidate lo-halves sum past 2^16 on dense rows, so
        the in-program lexicographic sort must carry lo's overflow into
        hi first: row A (per-slice counts 65535+65535 = 131070) must
        outrank row B (65536 = hi 1, lo 0) even though B's raw hi is
        larger (review finding)."""
        m = mesh_mod.make_mesh(8)
        S, W = 8, 2048  # 2048 u32 words = 65536 bits per slice
        rows = np.zeros((S, 2, W), dtype=np.uint32)
        rows[0, 0, :] = 0xFFFFFFFF
        rows[1, 0, :] = 0xFFFFFFFF
        rows[0, 0, 0] = 0xFFFFFFFE  # row 0: 65535 + 65535 = 131070
        rows[1, 0, 0] = 0xFFFFFFFE
        rows[0, 1, :] = 0xFFFFFFFF  # row 1: 65536
        counts, idx = mesh_mod.topn_topk_sharded(
            m, None, mesh_mod.shard_slices(m, rows), [], 2)
        assert idx == [0, 1]
        assert counts == [131070, 65536]


class TestExecutorFusedTree:
    """Count+TopN multi-op queries lower into ONE fused device program
    through the executor, and agree with the host path exactly."""

    N_SLICES = 8

    def _fill(self, holder):
        rng = np.random.default_rng(9)
        f = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        for row in range(5):
            cols = (rng.integers(0, SLICE_WIDTH,
                                 size=60 * self.N_SLICES)
                    + np.repeat(np.arange(self.N_SLICES), 60)
                    * SLICE_WIDTH)
            f.import_bits(np.full(len(cols), row, dtype=np.uint64),
                          cols.astype(np.uint64))

    QUERY = ("Count(Intersect(Bitmap(rowID=0, frame=f),"
             " Bitmap(rowID=1, frame=f)))"
             " TopN(Bitmap(rowID=0, frame=f), frame=f, ids=[1, 2, 3])"
             " Count(Union(Bitmap(rowID=2, frame=f),"
             " Bitmap(rowID=3, frame=f)))")

    def test_fused_run_matches_host(self, tmp_path, monkeypatch):
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        holder = Holder(str(tmp_path))
        holder.open()
        try:
            self._fill(holder)
            fast = Executor(holder, host="local", use_mesh=True,
                            mesh_min_slices=1)
            slow = Executor(holder, host="local", use_mesh=False)
            calls = []
            orig = mesh_mod.fused_tree_sharded

            def spy(*a, **kw):
                calls.append(1)
                return orig(*a, **kw)

            monkeypatch.setattr(mesh_mod, "fused_tree_sharded", spy)
            got = fast.execute("i", self.QUERY)
            want = slow.execute("i", self.QUERY)

            def norm(r):
                return [[(p.id, p.count) for p in x]
                        if isinstance(x, list) else x for x in r]

            assert norm(got) == norm(want)
            assert calls == [1], "whole tree must be one dispatch"
            assert fast.device_fallbacks == 0
        finally:
            holder.close()

    def test_filtered_topn_breaks_the_run(self, tmp_path, monkeypatch):
        """threshold>1 keeps its per-kind pruning program — the run
        must fall back per call, still correct."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        holder = Holder(str(tmp_path))
        holder.open()
        try:
            self._fill(holder)
            fast = Executor(holder, host="local", use_mesh=True,
                            mesh_min_slices=1)
            slow = Executor(holder, host="local", use_mesh=False)
            q = ("Count(Bitmap(rowID=0, frame=f))"
                 " TopN(Bitmap(rowID=0, frame=f), frame=f,"
                 " ids=[1, 2], threshold=5)")
            monkeypatch.setattr(
                mesh_mod, "fused_tree_sharded",
                lambda *a, **kw: pytest.fail("filtered TopN fused"))
            got = fast.execute("i", q)
            want = slow.execute("i", q)

            def norm(r):
                return [[(p.id, p.count) for p in x]
                        if isinstance(x, list) else x for x in r]

            assert norm(got) == norm(want)
        finally:
            holder.close()


class TestCompileCountBucketBound:
    """The acceptance gate for ROADMAP item 1(a): growing the slice
    count 8→32 compiles a NEW program only when the count crosses into
    a new bucket — never per slice count. firstCalls counts true XLA
    compilations (shape-keyed, via the jitted cache size), so the
    assertion is on the real cold tax, not the builder-cache shape."""

    def test_count_and_topn_compiles_constant_within_bucket(
            self, tmp_path):
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        holder = Holder(str(tmp_path))
        holder.open()
        try:
            rng = np.random.default_rng(21)
            f = holder.create_index_if_not_exists("i") \
                .create_frame_if_not_exists("f")
            n_slices = 32
            for row in range(3):
                cols = (rng.integers(0, SLICE_WIDTH, size=4 * n_slices)
                        + np.repeat(np.arange(n_slices), 4)
                        * SLICE_WIDTH)
                f.import_bits(np.full(len(cols), row, dtype=np.uint64),
                              cols.astype(np.uint64))
            ex = Executor(holder, host="local", mesh_min_slices=1)
            # A distinctive expression so earlier tests can't have
            # pre-warmed this exact program.
            q = ("Count(Union(Intersect(Bitmap(rowID=0, frame=f),"
                 " Bitmap(rowID=1, frame=f)),"
                 " Difference(Bitmap(rowID=2, frame=f),"
                 " Bitmap(rowID=0, frame=f))))")
            qt = ("TopN(Difference(Bitmap(rowID=1, frame=f),"
                  " Bitmap(rowID=2, frame=f)), frame=f, ids=[0, 2])")
            host = Executor(holder, host="local", use_mesh=False)
            compiles = {}
            for n in (8, 10, 12, 16, 20, 24, 32):
                slices = list(range(n))
                before = mesh_mod.compile_stats()["firstCalls"]
                got = ex.execute("i", q, slices)
                got_t = ex.execute("i", qt, slices)
                compiles[n] = (mesh_mod.compile_stats()["firstCalls"]
                               - before)
                assert got == host.execute("i", q, slices), n
                wt = host.execute("i", qt, slices)
                assert [(p.id, p.count) for p in got_t[0]] == \
                    [(p.id, p.count) for p in wt[0]], n
            assert ex.device_fallbacks == 0
            # 8 → bucket 8 (first touch may compile); 10 → bucket 16
            # (first touch); 12, 16 → SAME bucket: zero new compiles.
            assert compiles[12] == 0, compiles
            assert compiles[16] == 0, compiles
            # 20 → bucket 32 (first touch); 24, 32 → zero again.
            assert compiles[24] == 0, compiles
            assert compiles[32] == 0, compiles
            # And the buckets that did compile each did real work once.
            assert compiles[8] > 0 and compiles[10] > 0
            assert compiles[20] > 0
        finally:
            holder.close()


class TestPersistentCompileCache:
    """Satellite: the on-disk XLA cache must HIT across processes — a
    restarted server re-reads compiled programs instead of re-paying
    the trace+compile (VERDICT weak #2's 5.4 s first query)."""

    CHILD = textwrap.dedent("""
        import os, sys, json
        os.environ["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=8"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, %(repo)r)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from pilosa_tpu.parallel import mesh as mesh_mod
        armed = mesh_mod.arm_compile_cache(None)
        assert armed == %(cache)r, armed
        # Tiny test programs compile fast; drop the persistence
        # threshold so they are cacheable (real serving programs
        # clear the default 0.1 s on their own).
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        import numpy as np
        m = mesh_mod.make_mesh(8)
        slab = mesh_mod.shard_slices(
            m, np.ones((8, 512), dtype=np.uint32))
        got = mesh_mod.count_expr_sharded(
            m, ("and", ("leaf", 0), ("leaf", 1)), [slab, slab])
        assert got == 8 * 512, got  # value 1 per word = 1 bit
        print("STATS " + json.dumps(mesh_mod.compile_stats()))
    """)

    def test_second_process_hits_on_disk_cache(self, tmp_path):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        cache = str(tmp_path / "xla")
        code = self.CHILD % {"repo": repo, "cache": cache}
        env = dict(os.environ)
        env["PILOSA_TPU_COMPILE_CACHE"] = cache

        def run():
            out = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 env=env, timeout=240)
            assert out.returncode == 0, out.stderr[-2000:]
            line = [ln for ln in out.stdout.splitlines()
                    if ln.startswith("STATS ")][0]
            import json
            return json.loads(line[len("STATS "):])

        first = run()
        assert first["persistentMisses"] >= 1, first
        assert first["persistentHits"] == 0, first
        files = set(os.listdir(cache))
        assert files, "first process wrote no cache entries"
        second = run()
        # The counter the satellite asks for: the second process's
        # compile was served from disk — hit, not miss.
        assert second["persistentHits"] >= 1, second
        assert second["persistentMisses"] == 0, second
        assert set(os.listdir(cache)) == files  # nothing re-written

    def test_disabled_by_env_zero(self, monkeypatch):
        monkeypatch.setattr(mesh_mod, "_compile_cache_armed", False)
        monkeypatch.setattr(mesh_mod, "_compile_cache_dir", None)
        monkeypatch.setenv("PILOSA_TPU_COMPILE_CACHE", "0")
        assert mesh_mod.arm_compile_cache("/tmp/never-used") is None
