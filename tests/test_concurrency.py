"""Concurrency smoke: mixed writes and device-batched reads race
through one Executor from many threads.

The reference leans on Go's -race plus mutex-per-object discipline
(fragment/holder/index/frame/view/attr locks — SURVEY §5); here the
same discipline guards numpy/mmap state, plus the device residency
cache's (uid, generation) keys must never serve stale blocks while
writers invalidate them. Every thread's final reads are re-checked
against a single-threaded model after the storm.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    yield h
    h.close()


def test_writers_vs_device_readers(holder):
    frame = holder.create_index_if_not_exists("i") \
        .create_frame_if_not_exists("f")
    n_slices, n_threads, per_thread = 8, 6, 40
    # Pre-seed so reads always see data.
    for s in range(n_slices):
        frame.set_bit("standard", 1, s * SLICE_WIDTH)
        frame.set_bit("standard", 2, s * SLICE_WIDTH)
    ex = Executor(holder, host="local", mesh_min_slices=1,
                  use_mesh=True)

    errs = []
    barrier = threading.Barrier(n_threads)

    def run(tid):
        rng = np.random.default_rng(tid)
        try:
            barrier.wait()
            for k in range(per_thread):
                if tid % 2 == 0:
                    row = int(rng.integers(1, 3))
                    col = int(rng.integers(0, n_slices * SLICE_WIDTH))
                    ex.execute("i", f"SetBit(frame=f, rowID={row},"
                                    f" columnID={col})")
                else:
                    # Rotate through every TopN serving path that
                    # round 4 vectorized (plain rank-array leg, src
                    # candidate arrays, ids refetch) plus Count — all
                    # racing the writers on the same fragments.
                    qs = ("Count(Intersect(Bitmap(frame=f, rowID=1),"
                          " Bitmap(frame=f, rowID=2)))",
                          "TopN(Bitmap(frame=f, rowID=1), frame=f,"
                          " ids=[1, 2])",
                          "TopN(frame=f, n=2)",
                          "TopN(Bitmap(frame=f, rowID=2), frame=f,"
                          " n=2)",
                          # round 5: the materialized-result residency
                          # cache (generation-keyed hits/puts/evictions
                          # racing the writers' invalidating bumps)
                          "Union(Bitmap(frame=f, rowID=1),"
                          " Bitmap(frame=f, rowID=2))",
                          "Difference(Bitmap(frame=f, rowID=1),"
                          " Bitmap(frame=f, rowID=2))")
                    ex.execute("i", qs[k % 6])
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append((tid, repr(e)))

    threads = [threading.Thread(target=run, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    assert ex.device_fallbacks == 0
    # Guard against vacuous success: the storm must actually have run
    # through the device mesh (the residency cache under test).
    assert ex._mesh is not None, "device mesh never engaged"

    # Post-storm: device results must match ground truth exactly (no
    # stale residency entries survive the write generation bumps).
    def truth(row):
        frag_bits = set()
        for s in range(n_slices):
            frag = holder.fragment("i", "f", "standard", s)
            if frag is not None:
                frag_bits |= set(frag.row(row).bits())
        return frag_bits

    t1, t2 = truth(1), truth(2)
    got = ex.execute("i", "Count(Bitmap(frame=f, rowID=1))")[0]
    assert got == len(t1)
    got = ex.execute("i", "Count(Intersect(Bitmap(frame=f, rowID=1),"
                          " Bitmap(frame=f, rowID=2)))")[0]
    assert got == len(t1 & t2)
    pairs = ex.execute("i", "TopN(Bitmap(frame=f, rowID=2), frame=f,"
                            " ids=[1, 2])")[0]
    assert {p.id: p.count for p in pairs} == \
        {1: len(t1 & t2), 2: len(t2)}
    # The result cache must serve FRESH unions post-storm (every write
    # bumped the input fragments' generations, so any cached entry
    # still being served must correspond to the final state).
    got = set(ex.execute("i", "Union(Bitmap(frame=f, rowID=1),"
                              " Bitmap(frame=f, rowID=2))")[0]
              .bits().tolist())
    assert got == (t1 | t2)
    got = set(ex.execute("i", "Union(Bitmap(frame=f, rowID=1),"
                              " Bitmap(frame=f, rowID=2))")[0]
              .bits().tolist())  # repeat: a cache hit, same answer
    assert got == (t1 | t2)


def test_imports_vs_readers_and_writers(holder):
    """Round-5 bulk-import lanes racing point writers and readers on
    the SAME fragments: the packed-sort frame lane, the global array
    merge (container-table rebuilds under the fragment lock), the
    WAL'd small-import lane, and snapshot coalescing — none may tear a
    reader or lose a write. Final state is re-checked against a
    single-threaded model."""
    import queue

    frame = holder.create_index_if_not_exists("imp") \
        .create_frame_if_not_exists("f")
    ex = Executor(holder, host="local", use_mesh=False)
    n_rounds = 6
    errs = []
    applied = queue.Queue()  # (kind, payload) log for the model
    barrier = threading.Barrier(3)

    def importer():
        rng = np.random.default_rng(100)
        try:
            barrier.wait()
            for k in range(n_rounds):
                n = 4000 if k % 2 == 0 else 3  # bulk + small lanes
                rows = rng.integers(0, 50, n).astype(np.uint64)
                cols = rng.integers(0, 2 * SLICE_WIDTH, n) \
                    .astype(np.uint64)
                frame.import_bits(rows, cols)
                applied.put(("import", (rows, cols)))
        except Exception as e:  # noqa: BLE001
            errs.append(("importer", repr(e)))

    def writer():
        rng = np.random.default_rng(200)
        try:
            barrier.wait()
            for _ in range(120):
                row = int(rng.integers(0, 50))
                col = int(rng.integers(0, 2 * SLICE_WIDTH))
                ex.execute("imp", f"SetBit(frame=f, rowID={row},"
                                  f" columnID={col})")
                applied.put(("set", (row, col)))
        except Exception as e:  # noqa: BLE001
            errs.append(("writer", repr(e)))

    def reader():
        try:
            barrier.wait()
            for _ in range(120):
                ex.execute("imp", "Count(Bitmap(frame=f, rowID=7))")
                ex.execute("imp", "TopN(frame=f, n=3)")
        except Exception as e:  # noqa: BLE001
            errs.append(("reader", repr(e)))

    threads = [threading.Thread(target=f)
               for f in (importer, writer, reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs

    # Model: the union of every applied mutation, single-threaded.
    want: set[tuple[int, int]] = set()
    while not applied.empty():
        kind, payload = applied.get()
        if kind == "import":
            rows, cols = payload
            want.update(zip(rows.tolist(), cols.tolist()))
        else:
            want.add(payload)
    for rid in range(50):
        want_n = len({c for (r, c) in want if r == rid})
        got = ex.execute("imp",
                         f"Count(Bitmap(frame=f, rowID={rid}))")[0]
        assert got == want_n, (rid, got, want_n)
