"""Child process for the cluster-of-pods test (tests/test_pod_cluster.py).

Three processes, two cluster nodes: a plain node A and a 2-process pod
whose coordinator B0 is the second cluster node (worker B1 serves only
pod-internal legs). Node A is the test driver: it writes bits through
the full cluster routing (jump-hash owner → HTTP remote leg → pod slice
routing) and checks pod-wide + cluster-wide Count/TopN results.

Usage: python pod_cluster_child.py <role: a|b0|b1> <data_dir>
Env: POD_CLUSTER_A, POD_CLUSTER_B0 — the two cluster hosts; pod procs
additionally carry the PILOSA_TPU_DIST_* / POD_PEERS contract.
"""

import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

from podenv import child_main, http, query, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.cluster.broadcast import StaticNodeSet  # noqa: E402
from pilosa_tpu.cluster.topology import Cluster, Node  # noqa: E402
from pilosa_tpu.server.server import Server  # noqa: E402


def main() -> None:
    role = sys.argv[1]
    data_dir = sys.argv[2]
    host_a = os.environ["POD_CLUSTER_A"]
    host_b0 = os.environ["POD_CLUSTER_B0"]

    my_host = {"a": host_a, "b0": host_b0}.get(role)
    if role == "b1":
        my_host = os.environ["PILOSA_TPU_POD_PEERS"].split(",")[1]

    if role == "b1":
        cluster = None  # single-node self cluster (not a cluster member)
    else:
        nodes = [Node(host_a), Node(host_b0)]
        cluster = Cluster(nodes=nodes, node_set=StaticNodeSet(nodes))

    # Max-slice knowledge crosses cluster nodes via the poll loop
    # (server.go:216-252 equivalent) — keep it fast for the test.
    srv = Server(data_dir, host=my_host, cluster=cluster,
                 anti_entropy_interval=0,
                 polling_interval=0 if role == "b1" else 0.3)
    srv.open()
    print(f"{role} serving on {srv.host}", flush=True)

    if role != "a":
        while True:
            time.sleep(0.5)

    # --- node A drives the test ------------------------------------------
    wait_up(host_b0)
    # Static cluster: create the schema on both cluster nodes (B0's pod
    # broadcaster replicates it to B1).
    for h in (host_a, host_b0):
        http("POST", h, "/index/i", b"{}")
        http("POST", h, "/index/i/frame/f", b"{}")

    def q_retry(pql: str, deadline_s: float = 20.0):
        # A's poll loop may have tripped its circuit breaker for B0
        # while the pod was still initializing (pod mesh setup blocks
        # B0's listener); the breaker's half-open probe / the server's
        # active probe loop close it within a backoff window. Retry
        # through that recovery window — an open circuit at this point
        # is the breaker working as designed, not a test failure.
        deadline = time.time() + deadline_s
        while True:
            try:
                return query(host_a, "i", pql)
            except RuntimeError as e:
                if "circuit open" not in str(e) \
                        or time.time() > deadline:
                    raise
                time.sleep(0.3)

    # Bits across 6 slices, routed by jump hash to A or the pod, and
    # inside the pod by slice % 2 — all through ONE client entry point.
    for s in range(6):
        for j in range(3):
            q_retry(f"SetBit(frame=f, rowID=1,"
                    f" columnID={s * SLICE_WIDTH + j})")
        for j in range(2):
            q_retry(f"SetBit(frame=f, rowID=2,"
                    f" columnID={s * SLICE_WIDTH + j})")

    # Wait for A to adopt the pod's max slice through the poll loop.
    deadline = time.time() + 30
    while time.time() - deadline < 0:
        if query(host_a, "i", "Count(Bitmap(frame=f, rowID=1))")[0] == 18:
            break
        time.sleep(0.3)

    got = query(host_a, "i", "Count(Bitmap(frame=f, rowID=1))")[0]
    assert got == 18, f"Count(row1): {got} != 18"
    got = query(host_a, "i", "Count(Intersect(Bitmap(frame=f, rowID=1),"
                             " Bitmap(frame=f, rowID=2)))")[0]
    assert got == 12, f"Count(Intersect): {got} != 12"

    # Cluster-wide TopN: candidate phase per node (pod host legs on B),
    # exact phase per node (pod collective on B), merged at A.
    pairs = query(host_a, "i", "TopN(frame=f, n=2)")
    got = [(p["id"], p["count"]) for p in pairs[0]]
    assert got == [(1, 18), (2, 12)], got
    pairs = query(host_a, "i",
                  "TopN(Bitmap(frame=f, rowID=1), frame=f, ids=[1, 2])")
    got = [(p["id"], p["count"]) for p in pairs[0]]
    assert got == [(1, 18), (2, 12)], got

    # Bits materialize across both cluster nodes and the pod.
    bits = query(host_a, "i", "Bitmap(frame=f, rowID=2)")[0]["bits"]
    want = sorted(s * SLICE_WIDTH + j for s in range(6) for j in range(2))
    assert bits == want, bits[:8]

    print("POD_CLUSTER_OK", flush=True)
    srv.close()


if __name__ == "__main__":
    child_main(main)
