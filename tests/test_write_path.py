"""The one-crossing write path (ISSUE 8): extension parity + WAL
group-commit ordering.

Two contracts pinned here:

1. The compiled per-op mutate (native/fastmutate.c, loaded by
   storage/native_ext) must be BIT-FOR-BIT equivalent to the pure
   Python paths it shadows — same return values, same resulting
   container state, same marshaled WAL bytes — across all three
   container kinds and the bail/fallback seams. The extension is also
   asserted PRESENT in this environment (the tier-1 gate would
   otherwise silently run the fallback forever); ``PILOSA_TPU_NATIVE_EXT=0``
   is the deliberate escape hatch and skips that assertion.

2. Concurrent writers through the group-committed WAL: whatever
   interleaving the threads land, the op-log must replay to EXACTLY
   the in-memory state at the commit barrier — with group commit on
   (records coalesce through leader flushes) and off (vintage
   write-through), under a crash-style reopen (no orderly close).
"""

import io
import os
import threading
import time

import numpy as np
import pytest

from pilosa_tpu.storage import native_ext, roaring
from pilosa_tpu.storage.fragment import Fragment

EXT_DISABLED = os.environ.get("PILOSA_TPU_NATIVE_EXT", "1") == "0"


def test_extension_loaded():
    """Tier-1 canary: this environment has a toolchain, so the session
    must actually be exercising the compiled crossing — a quiet
    fallback would turn every other test here into fallback-vs-fallback
    and the serving perf claim into fiction."""
    if EXT_DISABLED:
        pytest.skip("PILOSA_TPU_NATIVE_EXT=0 escape hatch")
    assert native_ext.available(), (
        "fastmutate extension failed to build/load — set"
        " PILOSA_TPU_NATIVE_EXT=0 only as a deliberate escape hatch")
    for name in ("setbit", "clearbit", "wal_records"):
        assert hasattr(native_ext.EXT, name)


def _seeded_bitmap(writer=None):
    """One bitmap spanning all three container kinds: key 0 dense
    (bitmap), key 1 sparse (array), key 2 run-form, key 3 array at the
    4096 conversion edge, key 5 run at interval boundaries."""
    b = roaring.Bitmap()
    base = np.uint64(1) << np.uint64(16)
    # key 0: 6000 isolated values — dense enough for the bitmap form,
    # zero adjacency so optimize() can't turn it into runs
    dense = np.arange(0, 12000, 2, dtype=np.uint64)
    sparse = base + np.arange(0, 500, 7, dtype=np.uint64)  # key 1
    runs = np.uint64(2) * base + np.concatenate(
        [np.arange(100, 400, dtype=np.uint64),
         np.arange(1000, 1003, dtype=np.uint64),
         np.arange(9000, 9500, dtype=np.uint64)])
    edge = np.uint64(3) * base + np.arange(4090, dtype=np.uint64)
    bounds = np.uint64(5) * base + np.concatenate(
        [np.arange(0, 50, dtype=np.uint64),
         np.arange(65500, 65536, dtype=np.uint64)])
    b.apply_batch(np.concatenate([dense, sparse, runs, edge, bounds]),
                  wal=False)
    b.optimize()
    assert b.containers[b.keys.index(2)].is_run()
    c0 = b.containers[b.keys.index(0)]
    assert not c0.is_array() and not c0.is_run()
    assert b.containers[b.keys.index(1)].is_array()
    b.op_writer = writer
    return b


def _op_schedule(seed: int, n: int):
    """Mixed add/remove positions biased to hit every container kind,
    conversion edges, run interval splits/joins/trims, absent
    containers, and brand-new containers."""
    rng = np.random.default_rng(seed)
    ops = []
    for _ in range(n):
        kind = rng.integers(0, 7)
        key = int(rng.choice([0, 1, 2, 3, 5, 7, 40]))  # 7/40: absent
        if kind < 2:  # near run/array boundaries
            low = int(rng.choice([0, 1, 99, 100, 399, 400, 401, 999,
                                  1000, 1003, 4089, 4090, 4095, 4096,
                                  8999, 9500, 65499, 65500, 65535]))
        else:
            low = int(rng.integers(0, 1 << 16))
        ops.append((bool(rng.integers(0, 2)),
                    (key << 16) | low))
    return ops


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_point_mutate_differential(seed, monkeypatch):
    """Randomized differential: the same op schedule through the
    extension and the pure-Python path must agree on every return
    value, every WAL byte, and the final state (values, container
    kinds, cardinalities, invariants)."""
    if not native_ext.available() and not EXT_DISABLED:
        pytest.fail("extension unavailable")
    if native_ext.EXT is None:
        pytest.skip("extension not loaded (escape hatch)")

    wal_ext, wal_py = io.BytesIO(), io.BytesIO()
    b_ext = _seeded_bitmap(wal_ext)
    b_py = _seeded_bitmap(wal_py)

    ops = _op_schedule(seed, 4000)
    for i, (is_set, pos) in enumerate(ops):
        r_ext = b_ext.add(pos) if is_set else b_ext.remove(pos)
        monkeypatch.setattr(native_ext, "EXT", None)
        try:
            r_py = b_py.add(pos) if is_set else b_py.remove(pos)
        finally:
            monkeypatch.undo()
        assert r_ext == r_py, (i, is_set, hex(pos))

    assert wal_ext.getvalue() == wal_py.getvalue()
    assert b_ext.op_n == b_py.op_n
    assert np.array_equal(b_ext.values(), b_py.values())
    assert b_ext.keys == b_py.keys
    for c_ext, c_py in zip(b_ext.containers, b_py.containers):
        assert (c_ext.is_array(), c_ext.is_run(), c_ext.n) == \
            (c_py.is_array(), c_py.is_run(), c_py.n)
    b_ext.check()
    b_py.check()


def test_extension_bails_cleanly_on_cow_capture():
    """A frozen capture marks bitmap words copy-on-write; the
    extension must bail (None → Python path copies first) rather than
    scribble on the captured buffer."""
    if native_ext.EXT is None:
        pytest.skip("extension not loaded")
    b = _seeded_bitmap()
    frozen = b.freeze()
    want = b.values().copy()
    for pos in range(6001, 6201, 2):  # key 0: frozen bitmap container
        assert b.add(pos)
    # the capture is untouched
    got = io.BytesIO()
    roaring.write_frozen(frozen, got)
    reloaded = roaring.Bitmap.unmarshal(got.getvalue())
    assert np.array_equal(reloaded.values(), want)
    b.check()


def test_wal_records_byte_identical():
    """The GIL-released batch record builder must emit exactly the
    scalar Op.marshal bytes (same contract test_write_batch pins for
    the numpy builder — this one pins the C path)."""
    if native_ext.EXT is None:
        pytest.skip("extension not loaded")
    vals = np.array([0, 7, 1 << 33, (1 << 63) + 5, (1 << 64) - 1],
                    dtype=np.uint64)
    for typ in (roaring.OP_ADD, roaring.OP_REMOVE):
        blob = native_ext.EXT.wal_records(vals, typ)
        for i, v in enumerate(vals.tolist()):
            assert blob[i * 13:(i + 1) * 13] == \
                roaring.Op(typ, v).marshal()


def _crash_reopen(frag: Fragment) -> Fragment:
    """Abandon ``frag`` the way a crash would (no orderly close — the
    WAL is marked dead so the background flusher can't race, the dead
    process's flock is released) and replay from disk."""
    import fcntl
    if frag._wal is not None:
        frag._wal.close()
    fcntl.flock(frag._file.fileno(), fcntl.LOCK_UN)
    f2 = Fragment(frag.path, frag.index, frag.frame, frag.view,
                  frag.slice)
    f2.open()
    return f2


@pytest.mark.parametrize("group", ["1", "0"])
def test_concurrent_writer_storm_replays_exact(group, tmp_path,
                                               monkeypatch):
    """Multi-thread write storm through one fragment: per-op sets,
    batched sets, and clears from 8 threads over disjoint column
    ranges. After every thread's commit barrier, a crash-style reopen
    must replay the op-log to EXACTLY the set model — with group
    commit on (appends coalesce through leader flushes; sequence order
    is file order) and off (vintage write-through)."""
    monkeypatch.setenv("PILOSA_TPU_WAL_GROUP", group)
    frag = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
    frag.open()
    assert (frag._wal is not None) == (group == "1")

    n_threads, per = 8, 400
    model: dict[int, set] = {t: set() for t in range(n_threads)}
    errs = []
    start = threading.Barrier(n_threads)

    def writer(t: int) -> None:
        # Disjoint 1<<16-wide column lane per thread: every thread's
        # final per-lane state is deterministic regardless of the
        # cross-thread interleaving the storm lands.
        rng = np.random.default_rng(100 + t)
        base = t << 16
        mine = model[t]
        try:
            start.wait()
            for i in range(per):
                col = base + int(rng.integers(0, 3000))
                row = int(rng.integers(0, 4))
                if i % 16 == 15 and mine:
                    r, c = next(iter(mine))
                    frag.clear_bit(r, c)
                    mine.discard((r, c))
                elif i % 7 == 6:
                    cols = base + rng.integers(0, 3000, 40)
                    rows = np.full(40, row, dtype=np.uint64)
                    frag.set_bits(rows, cols.astype(np.uint64))
                    mine.update((row, int(c)) for c in cols)
                else:
                    frag.set_bit(row, col)
                    mine.add((row, col))
            frag.wal_barrier()  # the per-writer ack point
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs, errs

    want = sorted(set().union(*model.values()))
    live = sorted({(r, int(c)) for r in range(4)
                   for c in frag.row(r).bits()})
    assert live == want  # in-memory truth first

    if group == "1":
        assert frag._wal.pending_bytes() == 0
        assert frag._wal.flushes >= 1
    f2 = _crash_reopen(frag)
    try:
        replayed = sorted({(r, int(c)) for r in range(4)
                           for c in f2.row(r).bits()})
        assert replayed == want
    finally:
        f2.close()


class _FailNWritesFile:
    """File wrapper whose first ``n`` write() calls raise — the
    transient-disk-error shape (ENOSPC, torn-write failpoint) the
    dirty-registry invariant must survive."""

    def __init__(self, file, n=1):
        self._file = file
        self.fails_left = n

    def write(self, data):
        if self.fails_left > 0:
            self.fails_left -= 1
            raise OSError(28, "No space left on device")
        return self._file.write(data)

    def __getattr__(self, name):
        return getattr(self._file, name)


def test_flusher_error_then_append_reregisters(tmp_path, monkeypatch):
    """A WalError in the BACKGROUND flusher drops the WAL from the
    dirty registry — but must clear ``_registered`` with it, so the
    owner's next append re-registers and ``barrier_all()`` (the
    serving ack barrier) flushes the records. Leaving the latch set
    made every later write acked-but-volatile until a snapshot."""
    from pilosa_tpu.storage import wal as walmod

    monkeypatch.setenv("PILOSA_TPU_WAL_WINDOW_MS", "1")
    f = open(tmp_path / "wal", "wb", buffering=0)
    try:
        w = walmod.GroupCommitWal(_FailNWritesFile(f, n=1),
                                  fsync_policy=walmod.FSYNC_NONE)
        w.append(b"a" * walmod.OP_SIZE)
        # The background flusher hits the failing write, catches the
        # WalError, and deregisters the WAL.
        deadline = time.time() + 10
        while True:
            with walmod._dirty_mu:
                gone = w not in walmod._dirty
            if gone:
                break
            assert time.time() < deadline, \
                "flusher never processed the failing WAL"
            time.sleep(0.005)
        # The next append must RE-register (the bug: _registered stayed
        # latched True, so the WAL was invisible to barrier_all forever).
        w.append(b"b" * walmod.OP_SIZE)
        with walmod._dirty_mu:
            assert w in walmod._dirty
        walmod.barrier_all()  # disk works again: both records land
        assert w.pending_bytes() == 0
        assert os.path.getsize(tmp_path / "wal") == 2 * walmod.OP_SIZE
        w.close()
    finally:
        f.close()


def test_big_append_registers_before_inline_flush(tmp_path, monkeypatch):
    """An append that crosses _BUF_MAX flushes inline — but must enter
    the dirty registry FIRST: if the inline flush fails (or returns
    early because a racing batch formed mid-write), the pending
    records must still be visible to barrier_all()/the flusher."""
    from pilosa_tpu.storage import wal as walmod

    # Keep the background flusher away from the assertion window.
    monkeypatch.setenv("PILOSA_TPU_WAL_WINDOW_MS", "500")
    f = open(tmp_path / "wal", "wb", buffering=0)
    try:
        w = walmod.GroupCommitWal(_FailNWritesFile(f, n=1),
                                  fsync_policy=walmod.FSYNC_NONE)
        blob = b"c" * (walmod._BUF_MAX + walmod.OP_SIZE)
        with pytest.raises(walmod.WalError):
            w.append(blob)  # inline leader flush hits the bad write
        assert w.pending_bytes() == len(blob)  # batch stayed queued
        with walmod._dirty_mu:
            assert w in walmod._dirty  # barrier_all can still see it
        w.barrier()  # retry succeeds on the recovered disk
        assert w.pending_bytes() == 0
        assert os.path.getsize(tmp_path / "wal") == len(blob)
        w.close()
        with walmod._dirty_mu:
            assert w not in walmod._dirty
    finally:
        f.close()
