"""Logging subsystem + server flag surface.

Reference: the Go build threads an injected log.Logger through every
layer and honors --log-path (server/server.go:123-131, holder.go:360,
fragment.go:1012-1020 snapshot track()); cmd/server.go:88-104 exposes
the full config surface as flags with flags > env > file priority
(cmd/root.go:99-153, proven by cmd/root_test.go).
"""

import urllib.error
import urllib.request

import pytest

from pilosa_tpu.cli.commands import build_parser, load_server_config
from pilosa_tpu.server.server import Server
from pilosa_tpu.utils import logger as logger_mod


def http_post(host, path, body=b""):
    req = urllib.request.Request(
        f"http://{host}{path}", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, r.read()


class TestLogger:
    def test_printf_formats_and_timestamps(self, tmp_path):
        path = tmp_path / "p.log"
        lg = logger_mod.Logger.open(str(path))
        lg.printf("hello %s %d", "world", 7)
        lg.close()
        line = path.read_text().strip()
        assert line.endswith("hello world 7")
        # Go log-style timestamp prefix: YYYY/MM/DD HH:MM:SS
        assert line[4] == "/" and line[7] == "/" and line[10] == " "

    def test_track_logs_duration(self, tmp_path):
        path = tmp_path / "t.log"
        lg = logger_mod.Logger.open(str(path))
        with lg.track("job %s", "x"):
            pass
        lg.close()
        assert "job x took " in path.read_text()

    def test_nop_is_silent(self):
        logger_mod.NOP.printf("never seen %d", 1)  # must not raise

    def test_empty_path_logs_to_stderr(self, capsys):
        lg = logger_mod.Logger.open("")
        lg.printf("to stderr")
        assert "to stderr" in capsys.readouterr().err


class TestServerLogging:
    """--log-path content: the operator gets a record of opens,
    snapshots, anti-entropy, and query errors."""

    def test_log_path_records_lifecycle(self, tmp_path):
        log_path = tmp_path / "pilosa.log"
        logger = logger_mod.Logger.open(str(log_path))
        s = Server(str(tmp_path / "data"), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0,
                   logger=logger)
        s.open()
        try:
            http_post(s.host, "/index/i", b"{}")
            http_post(s.host, "/index/i/frame/f", b"{}")
            http_post(s.host, "/index/i/query",
                      b'SetBit(frame="f", rowID=1, columnID=3)')
            frag = s.holder.fragment("i", "f", "standard", 0)
            frag.snapshot()
            # A handler-level 500 is logged (import to an unowned slice
            # style errors go 400; force a true internal error).
            class Boom:
                def execute(self, *a, **k):
                    raise RuntimeError("kaboom")
            old = s.handler.executor
            s.handler.executor = Boom()
            with pytest.raises(urllib.error.HTTPError):
                http_post(s.host, "/index/i/query", b'Count(Bitmap(frame="f", rowID=1))')
            s.handler.executor = old
        finally:
            s.close()
            logger.close()
        text = log_path.read_text()
        assert "open holder path:" in text
        assert "listening as http://" in text
        assert "fragment: snapshot i/f/standard/0 took " in text
        assert "query error: index=i" in text and "kaboom" in text
        assert "server closing:" in text


class TestFlagPriority:
    """flags > env > file, per key (cmd/root.go:99-153)."""

    # (flag argv pieces, env key/value, toml line(s), getter, per-source
    # expected values: file-only, env-over-file, flag-over-both)
    CASES = [
        (["--data-dir", "/from/flag"], ("PILOSA_DATA_DIR", "/from/env"),
         'data-dir = "/from/file"', lambda c: c.data_dir,
         "/from/file", "/from/env", "/from/flag"),
        (["--bind", "flag:1"], ("PILOSA_HOST", "env:1"),
         'host = "file:1"', lambda c: c.host, "file:1", "env:1", "flag:1"),
        (["--log-path", "/flag.log"], ("PILOSA_LOG_PATH", "/env.log"),
         'log-path = "/file.log"', lambda c: c.log_path,
         "/file.log", "/env.log", "/flag.log"),
        (["--cluster.replicas", "4"], ("PILOSA_CLUSTER_REPLICAS", "3"),
         "[cluster]\nreplicas = 2", lambda c: c.cluster.replica_n, 2, 3, 4),
        (["--cluster.hosts", "f1:1,f2:2"],
         ("PILOSA_CLUSTER_HOSTS", "e1:1,e2:2"),
         '[cluster]\nhosts = ["t1:1", "t2:2"]', lambda c: c.cluster.hosts,
         ["t1:1", "t2:2"], ["e1:1", "e2:2"], ["f1:1", "f2:2"]),
        (["--cluster.internal-hosts", "fi:1"],
         ("PILOSA_CLUSTER_INTERNAL_HOSTS", "ei:1"),
         '[cluster]\ninternal-hosts = ["ti:1"]',
         lambda c: c.cluster.internal_hosts, ["ti:1"], ["ei:1"], ["fi:1"]),
        (["--cluster.type", "gossip"], ("PILOSA_CLUSTER_TYPE", "http"),
         '[cluster]\ntype = "static"', lambda c: c.cluster.type,
         "static", "http", "gossip"),
        (["--cluster.internal-port", "14003"],
         ("PILOSA_CLUSTER_INTERNAL_PORT", "14002"),
         '[cluster]\ninternal-port = "14001"',
         lambda c: c.cluster.internal_port, "14001", "14002", "14003"),
        (["--cluster.gossip-seed", "f:14000"],
         ("PILOSA_CLUSTER_GOSSIP_SEED", "e:14000"),
         '[cluster]\ngossip-seed = "t:14000"',
         lambda c: c.cluster.gossip_seed, "t:14000", "e:14000", "f:14000"),
        (["--cluster.poll-interval", "30s"],
         ("PILOSA_CLUSTER_POLL_INTERVAL", "20s"),
         '[cluster]\npolling-interval = "10s"',
         lambda c: c.cluster.polling_interval, 10.0, 20.0, 30.0),
        (["--anti-entropy.interval", "3m"],
         ("PILOSA_ANTI_ENTROPY_INTERVAL", "2m"),
         '[anti-entropy]\ninterval = "1m"',
         lambda c: c.anti_entropy_interval, 60.0, 120.0, 180.0),
        (["--plugins.path", "/flag/plug"],
         ("PILOSA_PLUGINS_PATH", "/env/plug"),
         '[plugins]\npath = "/file/plug"', lambda c: c.plugins_path,
         "/file/plug", "/env/plug", "/flag/plug"),
    ]

    @pytest.mark.parametrize(
        "flags,envkv,toml,get,want_file,want_env,want_flag",
        CASES, ids=[c[0][0] for c in CASES])
    def test_priority(self, tmp_path, flags, envkv, toml, get,
                      want_file, want_env, want_flag):
        cfg_file = tmp_path / "cfg.toml"
        cfg_file.write_text(toml + "\n")
        parser = build_parser()
        base = ["server", "-c", str(cfg_file)]
        env = {envkv[0]: envkv[1]}

        # file only
        args = parser.parse_args(base)
        assert get(load_server_config(args, env={})) == want_file
        # env beats file
        assert get(load_server_config(args, env=env)) == want_env
        # flag beats both
        args = parser.parse_args(base + flags)
        assert get(load_server_config(args, env=env)) == want_flag
