"""Bulk-import lanes (round 5): the packed-sort frame lane, the
global array-group merge in add_many, the small-import WAL lane, and
snapshot run-coalescing — each checked against the per-op ground truth
(reference import semantics: fragment.go:924-989, frame.go:530-606)."""

import os

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.storage import roaring
from pilosa_tpu.storage.fragment import Fragment


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    yield h
    h.close()


def _frag(tmp_path, name="frag") -> Fragment:
    f = Fragment(os.path.join(str(tmp_path), name), "i", "f",
                 "standard", 0)
    f.open()
    return f


class TestAddManyGlobalMerge:
    def test_matches_per_op_sparse(self):
        rng = np.random.default_rng(3)
        vals = rng.integers(0, 1 << 30, 60_000).astype(np.uint64)
        ref = roaring.Bitmap()
        for v in vals.tolist():
            ref._add(int(v))
        got = roaring.Bitmap()
        got.add_many(vals)
        assert got.marshal() == ref.marshal()

    def test_warm_merge_into_existing_arrays(self):
        rng = np.random.default_rng(4)
        a = rng.integers(0, 1 << 28, 30_000).astype(np.uint64)
        b = rng.integers(0, 1 << 28, 30_000).astype(np.uint64)
        one = roaring.Bitmap()
        one.add_many(np.concatenate([a, b]))
        two = roaring.Bitmap()
        two.add_many(a)
        two.add_many(b)  # >256 existing groups: global merge path
        assert one.marshal() == two.marshal()
        assert two.count() == len(np.unique(np.concatenate([a, b])))

    def test_merge_crossing_array_max_converts(self):
        # A warm merge that pushes containers past ARRAY_MAX_SIZE must
        # convert them (file-format invariant: n>4096 => bitmap block).
        base = np.arange(0, 3000, dtype=np.uint64)
        more = np.arange(2000, 6000, dtype=np.uint64)
        wide_base = np.concatenate(
            [base + np.uint64(k << 16) for k in range(400)])
        wide_more = np.concatenate(
            [more + np.uint64(k << 16) for k in range(400)])
        bm = roaring.Bitmap()
        bm.add_many(wide_base)
        bm.add_many(wide_more)
        c = bm.container(0)
        assert c.bitmap is not None and c.n == 6000
        assert bm.count() == 400 * 6000
        # round-trips through the (coalesced) snapshot writer
        assert roaring.Bitmap.unmarshal(bm.marshal()).count() == bm.count()

    def test_bitmap_targets_or_scatter(self):
        dense = np.arange(0, 60_000, dtype=np.uint64)
        bm = roaring.Bitmap()
        bm.add_many(dense)
        sparse_hits = np.concatenate(
            [dense[::7], np.arange(60_000, 60_500, dtype=np.uint64)])
        added = bm.add_many(sparse_hits)
        assert added == 500
        assert bm.count() == 60_500


class TestRemoveManyGlobal:
    def test_matches_per_op_mixed_kinds(self):
        rng = np.random.default_rng(11)
        base = np.unique(rng.integers(0, 1 << 26, 100_000)
                         .astype(np.uint64))
        dense = np.arange(1 << 26, (1 << 26) + 70_000, dtype=np.uint64)
        allv = np.concatenate([base, dense])
        to_remove = np.concatenate(
            [base[::3], dense[::2],
             rng.integers(0, 1 << 27, 3000).astype(np.uint64)])
        ref = roaring.Bitmap()
        ref.add_many(allv)
        got = roaring.Bitmap()
        got.add_many(allv)
        n_ref = sum(ref._remove(int(v))
                    for v in np.unique(to_remove).tolist())
        n_got = got.remove_many(to_remove)
        assert n_got == n_ref
        assert got.marshal() == ref.marshal()

    def test_max_key_container_no_overflow(self):
        # Regression: span ends derived via (key+1)<<16 wrapped u64 at
        # container key 2^48-1, corrupting the top container's count.
        top = np.uint64(0xFFFFFFFFFFFF0000)
        vals = np.concatenate(
            [np.arange(10, dtype=np.uint64) + top,
             *[np.arange(3, dtype=np.uint64) + np.uint64(k << 16)
               for k in range(300)]])
        bm = roaring.Bitmap()
        bm.add_many(vals)
        ref = roaring.Bitmap()
        ref.add_many(vals)
        to_rm = np.concatenate(
            [np.arange(5, dtype=np.uint64) + top,
             *[np.arange(1, dtype=np.uint64) + np.uint64(k << 16)
               for k in range(300)]])
        n = bm.remove_many(to_rm)
        assert n == sum(ref._remove(int(v)) for v in to_rm.tolist())
        assert bm.marshal() == ref.marshal()
        assert bm.container(0xFFFFFFFFFFFF).n == 5

    def test_emptied_containers_come_out_empty(self):
        # >256 array groups forces the global path; removing every
        # value must leave each container empty (n=0) but present.
        vals = np.concatenate(
            [np.arange(3, dtype=np.uint64) + np.uint64(k << 16)
             for k in range(300)])
        bm = roaring.Bitmap()
        bm.add_many(vals)
        assert bm.remove_many(vals) == len(vals)
        assert bm.count() == 0
        assert bm.container(5) is not None and bm.container(5).n == 0
        # still serializes and round-trips (empty containers skipped)
        assert roaring.Bitmap.unmarshal(bm.marshal()).count() == 0


class TestSnapshotCoalescing:
    def test_mixed_bases_round_trip(self):
        # Containers from one bulk import (shared base), then some
        # point-mutated (fresh buffers — runs must break), then more
        # bulk (second shared base).
        rng = np.random.default_rng(5)
        bm = roaring.Bitmap()
        bm.add_many(rng.integers(0, 1 << 26, 20_000).astype(np.uint64))
        for v in rng.integers(0, 1 << 26, 300).tolist():
            bm._add(int(v))
        bm.add_many(rng.integers(1 << 26, 1 << 27, 20_000)
                    .astype(np.uint64))
        blob = bm.marshal()
        back = roaring.Bitmap.unmarshal(blob)
        assert back.count() == bm.count()
        assert back.marshal() == blob


class TestFragmentImportLanes:
    def test_sparse_import_counts_and_reopen(self, tmp_path):
        rng = np.random.default_rng(6)
        rows = rng.integers(0, 20_000, 200_000).astype(np.uint64)
        cols = rng.integers(0, SLICE_WIDTH, 200_000).astype(np.uint64)
        f = _frag(tmp_path)
        f.import_bits(rows, cols)
        want_total = len(np.unique(rows * np.uint64(SLICE_WIDTH) + cols))
        assert f.storage.count() == want_total
        # row-count cache entries match the count_range ground truth
        for rid in (0, 7, 19_999):
            want = int(np.unique(cols[rows == rid]).size)
            assert f.row_count(rid) == want
            if rid in f._row_counts:
                assert f._row_counts[rid] == want
        f.close()
        f2 = _frag(tmp_path)
        assert f2.storage.count() == want_total
        f2.close()

    def test_small_import_into_large_fragment_is_wal_d(self, tmp_path):
        rng = np.random.default_rng(7)
        f = _frag(tmp_path)
        f.import_bits(rng.integers(0, 30_000, 300_000).astype(np.uint64),
                      rng.integers(0, SLICE_WIDTH, 300_000)
                      .astype(np.uint64))
        op_n_before = f.storage.op_n
        f.import_bits(np.array([11, 11, 500], dtype=np.uint64),
                      np.array([1, 2, 3], dtype=np.uint64))
        # took the WAL lane: op-log grew, no full snapshot forced
        assert f.storage.op_n == op_n_before + 3
        assert f._row_counts.get(11, f.row_count(11)) == f.row_count(11)
        f.close()
        f2 = _frag(tmp_path)
        assert f2.storage.contains(11 * SLICE_WIDTH + 1)
        assert f2.storage.contains(500 * SLICE_WIDTH + 3)
        f2.close()

    def test_import_positions_sorted_lane(self, tmp_path):
        f = _frag(tmp_path)
        pos = np.sort(np.random.default_rng(8)
                      .integers(0, 50 * SLICE_WIDTH, 5000)
                      .astype(np.uint64))
        f.import_positions(pos)
        assert f.storage.count() == len(np.unique(pos))
        assert f.row_count(3) == int(
            np.unique(pos[(pos >= 3 * SLICE_WIDTH)
                          & (pos < 4 * SLICE_WIDTH)]).size)
        f.close()


class TestFramePackedLane:
    def test_packed_equals_per_op(self, holder):
        rng = np.random.default_rng(9)
        rows = rng.integers(0, 500, 30_000).astype(np.uint64)
        cols = rng.integers(0, 1 << 22, 30_000).astype(np.uint64)
        frame = holder.create_index("a").create_frame("f")
        frame.import_bits(rows, cols)
        ref = holder.create_index("b").create_frame("f")
        seen = set()
        for r, c in zip(rows.tolist(), cols.tolist()):
            ref.set_bit("standard", r, c, None)
            seen.add((r, c))
        for rid in (0, 13, 499):
            want = len({c for (r, c) in seen if r == rid})
            total = sum(
                fr.row_count(rid)
                for fr in frame.view("standard").fragments.values())
            assert total == want

    def test_wide_ids_take_fallback(self, holder):
        # rows >= 2^24 exceed the 44-bit pack: generic lane, same result
        frame = holder.create_index("w").create_frame("f")
        rows = np.array([1 << 24, (1 << 24) + 5, 2], dtype=np.uint64)
        cols = np.array([1, SLICE_WIDTH + 2, 3], dtype=np.uint64)
        frame.import_bits(rows, cols)
        frags = frame.view("standard").fragments
        assert sum(f.storage.count() for f in frags.values()) == 3
        assert frags[0].storage.contains(
            (1 << 24) * SLICE_WIDTH + 1)

    def test_inverse_and_time_views(self, holder):
        import datetime as dt
        from pilosa_tpu.models.frame import FrameOptions
        idx = holder.create_index("t")
        frame = idx.create_frame(
            "f", options=FrameOptions(inverse_enabled=True,
                                      time_quantum="YMD"))
        rows = np.array([1, 2, 3], dtype=np.uint64)
        cols = np.array([10, 20, 30], dtype=np.uint64)
        ts = [None, dt.datetime(2026, 7, 30, 12, 0), None]
        frame.import_bits(rows, cols, ts)
        std = frame.view("standard").fragments[0]
        assert std.storage.count() == 3
        inv = frame.view("inverse").fragments[0]
        assert inv.storage.contains(10 * SLICE_WIDTH + 1)
        day = frame.view("standard_20260730")
        assert day is not None
        assert day.fragments[0].storage.contains(2 * SLICE_WIDTH + 20)


class TestBulkLaneFuzz:
    """Randomized interleavings of bulk adds/removes and point ops,
    mirrored against a Python-set model — the bulk lanes must agree
    with per-op semantics on every shape (deterministic seeds)."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_interleaved_ops_match_model(self, seed):
        rng = np.random.default_rng(seed)
        bm = roaring.Bitmap()
        model: set[int] = set()
        # Value universe mixes dense spans, sparse keys, and the
        # max-key container region.
        universes = [
            lambda n: rng.integers(0, 1 << 20, n),          # dense-ish
            lambda n: rng.integers(0, 1 << 34, n),          # sparse
            lambda n: (np.uint64(0xFFFFFFFFFFFF0000)
                       + rng.integers(0, 1 << 14, n).astype(np.uint64)),
        ]
        for step in range(30):
            u = universes[int(rng.integers(0, 3))]
            kind = int(rng.integers(0, 4))
            n = int(rng.integers(1, 5000))
            vals = np.asarray(u(n), dtype=np.uint64)
            if kind == 0:
                added = bm.add_many(vals)
                before = len(model)
                model.update(vals.tolist())
                assert added == len(model) - before
            elif kind == 1:
                removed = bm.remove_many(vals)
                before = len(model)
                model.difference_update(vals.tolist())
                assert removed == before - len(model)
            elif kind == 2:
                v = int(vals[0])
                assert bm._add(v) == (v not in model)
                model.add(v)
            else:
                v = int(vals[0])
                assert bm._remove(v) == (v in model)
                model.discard(v)
            assert bm.count() == len(model), f"step {step}"
        # Final: EXACT value-set equality (a count-preserving
        # wrong-container bug must not pass), then a serialized
        # round-trip of the same.
        want = np.sort(np.fromiter(model, np.uint64, len(model)))
        assert np.array_equal(bm.values(), want)
        back = roaring.Bitmap.unmarshal(bm.marshal())
        assert np.array_equal(back.values(), want)


class TestRawImportWire:
    """The raw-array /import sidecar (proto/rawimport.py): round trip,
    alignment, the 415-fallback negotiation, and the strict error
    matrix (406 before body parse at reference parity; truncated raw
    bodies are 400, never 500)."""

    def test_codec_round_trip_aligned(self):
        from pilosa_tpu.proto import rawimport
        rng = np.random.default_rng(1)
        rows = rng.integers(0, 1 << 40, 1000).astype(np.uint64)
        cols = rng.integers(0, 1 << 40, 1000).astype(np.uint64)
        ts = rng.integers(0, 1 << 50, 1000).astype(np.int64)
        for t in (None, ts):
            body = rawimport.encode("idx", "frm", 7, rows, cols, t)
            i, f, s, r, c, tt, p = rawimport.decode(body)
            assert (i, f, s) == ("idx", "frm", 7)
            assert np.array_equal(r, rows) and np.array_equal(c, cols)
            assert (tt is None) == (t is None)
            assert p is None
            assert r.__array_interface__["data"][0] % 8 == 0

    def test_positions_codec_round_trip(self):
        from pilosa_tpu.proto import rawimport
        posn = np.arange(0, 5000, 3, dtype=np.uint64)
        body = rawimport.encode_positions("idx", "frm", 9, posn)
        assert rawimport.version_of(body) == 2
        i, f, s, r, c, tt, p = rawimport.decode(body)
        assert (i, f, s) == ("idx", "frm", 9)
        assert r is None and c is None and tt is None
        assert np.array_equal(p, posn)
        assert p.__array_interface__["data"][0] % 8 == 0
        with pytest.raises(ValueError):
            rawimport.decode(body[:-3])  # truncated positions

    def test_truncated_bodies_raise_value_error(self):
        from pilosa_tpu.proto import rawimport
        for bad in (b"", b"PRAW", b"PRAW\x01\x00", b"PRAW\x09\x00",
                    b"XXXX\x01\x00" + b"\0" * 64,
                    rawimport.encode("i", "f", 0,
                                     np.arange(4, dtype=np.uint64),
                                     np.arange(4, dtype=np.uint64),
                                     None)[:-3]):
            with pytest.raises(ValueError):
                rawimport.decode(bad)

    def test_server_error_matrix_and_import(self):
        import tempfile
        import urllib.error
        import urllib.request

        from pilosa_tpu.proto import rawimport
        from pilosa_tpu.server.server import Server
        RAW = rawimport.CONTENT_TYPE
        PB = "application/x-protobuf"
        with tempfile.TemporaryDirectory() as d:
            srv = Server(d, host="127.0.0.1:0",
                         anti_entropy_interval=0, polling_interval=0)
            srv.open()
            try:
                def post(path, ct, accept, body):
                    req = urllib.request.Request(
                        f"http://{srv.host}{path}", data=body,
                        method="POST", headers={"Content-Type": ct,
                                                "Accept": accept})
                    try:
                        urllib.request.urlopen(req)
                        return 200
                    except urllib.error.HTTPError as e:
                        return e.code
                assert post("/import", "text/plain", PB, b"x") == 415
                assert post("/import", PB, "application/json",
                            b"garbage") == 406
                assert post("/import", RAW, PB, b"PRAW\x01\x00") == 400
                assert post("/import", RAW, RAW, b"PRAW\x01\x00") == 400
                # real raw import end to end
                post("/index/ri", "application/json", "*/*", b"{}")
                post("/index/ri/frame/f", "application/json", "*/*",
                     b"{}")
                rows = np.array([3, 3, 9], dtype=np.uint64)
                cols = np.array([1, 2, 3], dtype=np.uint64)
                body = rawimport.encode("ri", "f", 0, rows, cols, None)
                assert post("/import", RAW, PB, body) == 200
                q = urllib.request.Request(
                    f"http://{srv.host}/index/ri/query",
                    data=b'Count(Bitmap(rowID=3, frame="f"))',
                    method="POST")
                assert b"[2]" in urllib.request.urlopen(q).read()
                # v2 positions form: sorted lands, unsorted is a 400
                # (the sort is the client's contract)
                from pilosa_tpu import SLICE_WIDTH
                W = np.uint64(SLICE_WIDTH)
                posn = np.uint64(3) * W + np.array(
                    [10, 11, 40], dtype=np.uint64)
                assert post("/import", RAW, PB,
                            rawimport.encode_positions(
                                "ri", "f", 0, posn)) == 200
                assert post("/import", RAW, PB,
                            rawimport.encode_positions(
                                "ri", "f", 0, posn[::-1].copy())) == 400
                q = urllib.request.Request(
                    f"http://{srv.host}/index/ri/query",
                    data=b'Count(Bitmap(rowID=3, frame="f"))',
                    method="POST")
                assert b"[5]" in urllib.request.urlopen(q).read()
            finally:
                srv.close()

    def test_positions_version_negotiation_falls_back(self, monkeypatch):
        """A host that rejects the v2 positions form (400 mentioning
        the version) must be remembered in _no_posn_import and served
        the v1 pair form — the import still lands."""
        import tempfile

        from pilosa_tpu.cluster import client as client_mod
        from pilosa_tpu.proto import rawimport
        from pilosa_tpu.server.server import Server

        real = rawimport.encode_positions

        def bad_version(index, frame, slice, positions):
            body = bytearray(real(index, frame, slice, positions))
            body[4] = 9  # an unknown wire version
            return bytes(body)

        # The client resolves encode_positions through the module at
        # call time, so patching the module attribute reroutes it;
        # the SERVER decodes through the same module but only calls
        # decode(), which stays real.
        monkeypatch.setattr(rawimport, "encode_positions", bad_version)
        with tempfile.TemporaryDirectory() as d:
            srv = Server(d, host="127.0.0.1:0",
                         anti_entropy_interval=0, polling_interval=0)
            srv.open()
            try:
                client = client_mod.Client(srv.host)
                client.create_index("nv")
                client.create_frame("nv", "f")
                rows = np.array([3, 3, 9], dtype=np.uint64)
                cols = np.array([1, 2, 3], dtype=np.uint64)
                client.import_arrays("nv", "f", rows, cols)
                assert srv.host in client._no_posn_import
                import urllib.request
                q = urllib.request.Request(
                    f"http://{srv.host}/index/nv/query",
                    data=b'Count(Bitmap(rowID=3, frame="f"))',
                    method="POST")
                assert b"[2]" in urllib.request.urlopen(q).read()
            finally:
                srv.close()

    def test_positions_form_inverse_frame_falls_back(self):
        """A frame with the inverse view enabled needs (row, col)
        pairs for the transpose; the positions lane must reconstruct
        them server-side and land BOTH views."""
        import tempfile

        from pilosa_tpu.cluster.client import Client
        from pilosa_tpu.server.server import Server
        with tempfile.TemporaryDirectory() as d:
            srv = Server(d, host="127.0.0.1:0",
                         anti_entropy_interval=0, polling_interval=0)
            srv.open()
            try:
                client = Client(srv.host)
                client.create_index("pi")
                client.create_frame("pi", "f",
                                    options={"inverseEnabled": True})
                rows = np.array([1, 1, 2], dtype=np.uint64)
                cols = np.array([5, 9, 5], dtype=np.uint64)
                client.import_arrays("pi", "f", rows, cols)
                import json as json_mod
                import urllib.request
                for pql, want in (
                        (b'Count(Bitmap(rowID=1, frame="f"))', 2),
                        (b'Count(Bitmap(columnID=5, frame="f"))', 2)):
                    q = urllib.request.Request(
                        f"http://{srv.host}/index/pi/query", data=pql,
                        method="POST")
                    got = json_mod.loads(
                        urllib.request.urlopen(q).read())
                    assert got["results"] == [want], (pql, got)
            finally:
                srv.close()
