"""Chaos leg: a REAL 3-node replicas=2 gossip cluster under SIGKILL.

The ISSUE acceptance contract, end to end against real processes:

- the cluster keeps answering CORRECT (differential-checked) queries
  while one node is SIGKILLed mid-load;
- the coordinator's breaker for the dead peer runs the full
  open → half-open → closed cycle across the kill and the restart,
  observed via /metrics;
- once the breaker is open, failover queries complete without paying
  the dead peer's RPC timeout — asserted via the per-query stage
  timings the PR 4 slow log records.

Marked ``slow`` (multi-process, tens of seconds) + ``chaos``; the
fast failpoint-driven chaos tests live in test_fault.py and run in
tier-1.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

N_SLICES = 8


def _post(host, path, body=b"", timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=timeout).read()


def _get(host, path, timeout=10):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return r.read()


def _get_json(host, path, timeout=10):
    return json.loads(_get(host, path, timeout=timeout))


def _count(host, row, timeout=30):
    got = json.loads(_post(
        host, "/index/fc/query",
        f'Count(Bitmap(frame="f", rowID={row}))'.encode(),
        timeout=timeout))
    assert "error" not in got, got
    return got["results"][0]


def _breaker_gauge(host, peer):
    """pilosa_fault_breaker_state{peer="..."} from /metrics, or None
    while the peer has no breaker yet."""
    for line in _get(host, "/metrics").decode().splitlines():
        if line.startswith("pilosa_fault_breaker_state") \
                and f'peer="{peer}"' in line:
            return float(line.rsplit(" ", 1)[1])
    return None


def _transitions(host, peer):
    out = {}
    for line in _get(host, "/metrics").decode().splitlines():
        if line.startswith("pilosa_fault_breaker_transitions_total") \
                and f'peer="{peer}"' in line:
            state = line.split('state="', 1)[1].split('"', 1)[0]
            out[state] = float(line.rsplit(" ", 1)[1])
    return out


class _Cluster:
    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.ports = {n: free_port() for n in "abc"}
        self.gports = {n: free_port() for n in "abc"}
        self.hosts = {n: f"127.0.0.1:{self.ports[n]}" for n in "abc"}
        self.procs: dict[str, subprocess.Popen] = {}
        self.logs = []
        self.host_list = ",".join(self.hosts[n] for n in "abc")

    def spawn(self, name, seed=""):
        d = self.tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env["PILOSA_TPU_WARMUP"] = "0"
        # Fast breaker cadence so the open→half-open→closed cycle fits
        # a test, and a fixed seed so any chaos failure replays.
        env["PILOSA_FAULT_BREAKER_BACKOFF"] = "0.2s"
        env["PILOSA_FAULT_BREAKER_BACKOFF_CAP"] = "1s"
        env["PILOSA_FAULT_SEED"] = "12345"
        log = open(self.tmp_path / f"{name}.log", "a")
        self.logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", self.hosts[name],
                "--cluster.type", "gossip",
                "--cluster.hosts", self.host_list,
                "--cluster.replicas", "2",
                "--cluster.internal-port", str(self.gports[name]),
                "--query.slow-threshold", "1ms",
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        self.procs[name] = p
        wait_up(self.hosts[name])
        return self.hosts[name]

    def close(self):
        for p in self.procs.values():
            try:
                p.send_signal(signal.SIGINT)
            except OSError:
                pass
        for p in self.procs.values():
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for log in self.logs:
            log.close()


@pytest.fixture
def cluster(tmp_path):
    c = _Cluster(tmp_path)
    c.spawn("a")
    c.spawn("b", seed=f"127.0.0.1:{c.gports['a']}")
    c.spawn("c", seed=f"127.0.0.1:{c.gports['a']}")
    yield c
    c.close()


def test_sigkill_failover_breaker_cycle(cluster):
    host_a = cluster.hosts["a"]
    host_c = cluster.hosts["c"]
    _post(host_a, "/index/fc", b"{}")
    _post(host_a, "/index/fc/frame/f", b"{}")

    # Differential model: row -> expected count, spread over N_SLICES
    # so every node owns slices (replicas=2 of 3 nodes: each slice
    # has TWO owners, so any single death leaves a live replica).
    from pilosa_tpu.cluster.client import Client
    import numpy as np
    client = Client(host_a)
    model = {}
    for row in (1, 2):
        cols = np.arange(row, N_SLICES * SLICE_WIDTH,
                         SLICE_WIDTH // 2, dtype=np.uint64)
        client.import_arrays("fc", "f",
                             np.full(len(cols), row, np.uint64), cols)
        model[row] = len(cols)

    # Convergence: the coordinator answers the full count.
    deadline = time.time() + 30
    while time.time() < deadline:
        if all(_count(host_a, r) == n for r, n in model.items()):
            break
        time.sleep(0.3)
    for row, want in model.items():
        assert _count(host_a, row) == want

    # -- SIGKILL node c mid-load ------------------------------------------
    # A steady query storm is in flight while the node dies: every
    # answer, before/during/after, must be the model's (replica
    # failover, never a wrong partial).
    proc_c = cluster.procs.pop("c")
    proc_c.send_signal(signal.SIGKILL)
    proc_c.wait(timeout=30)
    storm_deadline = time.time() + 20
    opened_at = None
    while time.time() < storm_deadline:
        for row, want in model.items():
            got = _count(host_a, row)
            assert got == want, (
                f"row {row}: {got} != {want} with node c dead")
        if opened_at is None and _breaker_gauge(host_a, host_c) == 2:
            opened_at = time.time()
            break
        time.sleep(0.1)
    assert opened_at is not None, (
        "a's breaker for the killed peer never opened; fault block: "
        + json.dumps(_get_json(host_a, "/status").get("fault", {})))

    # -- post-open failovers never pay the dead peer's timeout ------------
    # Wall-clock on the query AND the per-query stage timings (PR 4
    # slow log): with the breaker open, placement skips the dead peer
    # entirely, so execute must run in milliseconds, nowhere near the
    # 30s client timeout the first discovery could have paid.
    for row, want in model.items():
        t0 = time.perf_counter()
        assert _count(host_a, row) == want
        elapsed = time.perf_counter() - t0
        assert elapsed < 2.0, (
            f"post-open failover took {elapsed:.2f}s — paid a dead"
            f" peer timeout?")
    slow = _get_json(host_a, "/debug/queries/slow")["slow"]
    post_open = [q for q in slow
                 if q["pql"].startswith("Count(")
                 and q["startedAt"] >= opened_at - 0.05]
    assert post_open, "slow log (threshold 1ms) must have the queries"
    for q in post_open:
        assert q["stages"].get("execute", 0.0) < 2.0, q
        # And none of their legs touched the dead peer.
        assert all(leg["host"] != host_c for leg in q["legs"]), q

    # -- restart: open → half-open → closed, observed via metrics ---------
    cluster.spawn("c", seed=f"127.0.0.1:{cluster.gports['a']}")
    deadline = time.time() + 30
    closed = False
    while time.time() < deadline:
        for row, want in model.items():  # traffic drives the probe
            assert _count(host_a, row) == want
        if _breaker_gauge(host_a, host_c) == 0:
            closed = True
            break
        time.sleep(0.2)
    assert closed, (
        "breaker never closed after the peer returned; transitions: "
        + json.dumps(_transitions(host_a, host_c)))
    trans = _transitions(host_a, host_c)
    assert trans.get("open", 0) >= 1, trans
    assert trans.get("half_open", 0) >= 1, trans
    assert trans.get("closed", 0) >= 1, trans

    # The full differential model still answers after recovery.
    for row, want in model.items():
        assert _count(host_a, row) == want
