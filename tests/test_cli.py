"""CLI command tests (reference cmd/*_test.go + ctl/*_test.go), driven
in-process against a real server on a random port."""

import io
import os

import pytest

from pilosa_tpu.cli.commands import main
from pilosa_tpu.server.server import Server


@pytest.fixture
def server(tmp_path):
    s = Server(str(tmp_path / "data"), host="127.0.0.1:0",
               anti_entropy_interval=0, polling_interval=0)
    s.open()
    yield s
    s.close()


def run(argv):
    out, err = io.StringIO(), io.StringIO()
    rc = main(argv, stdout=out, stderr=err)
    return rc, out.getvalue(), err.getvalue()


def setup_schema(server, index="i", frame="f"):
    idx = server.holder.create_index_if_not_exists(index)
    idx.create_frame_if_not_exists(frame)


class TestImportExportSort:
    def test_import_then_export(self, server, tmp_path):
        setup_schema(server)
        csv_file = tmp_path / "bits.csv"
        csv_file.write_text("1,10\n1,11\n2,10\n\n")
        rc, out, err = run(["import", "--host", server.host,
                            "-i", "i", "-f", "f", str(csv_file)])
        assert rc == 0, err
        rc, out, err = run(["export", "--host", server.host,
                            "-i", "i", "-f", "f"])
        assert rc == 0
        assert out.splitlines() == ["1,10", "1,11", "2,10"]

    def test_import_with_timestamp(self, server, tmp_path):
        setup_schema(server)
        idx = server.holder.index("i")
        idx.delete_frame("f")
        from pilosa_tpu.models.frame import FrameOptions
        idx.create_frame_if_not_exists("f", FrameOptions(time_quantum="Y"))
        csv_file = tmp_path / "bits.csv"
        csv_file.write_text("1,10,2017-03-04T10:30\n")
        rc, _, err = run(["import", "--host", server.host,
                          "-i", "i", "-f", "f", str(csv_file)])
        assert rc == 0, err
        assert "standard_2017" in server.holder.frame("i", "f").views

    def test_import_multislice_groups(self, server, tmp_path):
        """The vectorized import path must group by slice exactly like
        Bits.GroupBySlice (client.go:1027-1040)."""
        setup_schema(server)
        from pilosa_tpu import SLICE_WIDTH
        csv_file = tmp_path / "m.csv"
        csv_file.write_text(f"1,5\n1,{SLICE_WIDTH + 5}\n"
                            f"7,{2 * SLICE_WIDTH + 3}\n1,6\n")
        rc, _, err = run(["import", "--host", server.host,
                          "-i", "i", "-f", "f", str(csv_file)])
        assert rc == 0, err
        holder = server.holder
        assert holder.fragment("i", "f", "standard", 0).row(1).count() == 2
        assert holder.fragment("i", "f", "standard", 1).row(1).count() == 1
        assert holder.fragment("i", "f", "standard", 2).row(7).count() == 1

    def test_import_rejects_comment_lines(self, server, tmp_path):
        """np.loadtxt silently skips '#' lines; the import pipeline must
        not — the reference parser errors on them (ctl/import.go)."""
        setup_schema(server)
        csv_file = tmp_path / "c.csv"
        csv_file.write_text("1,2\n# not a bit\n3,4\n")
        rc, _, err = run(["import", "--host", server.host,
                          "-i", "i", "-f", "f", str(csv_file)])
        assert rc == 1
        assert "row 2" in err

    @pytest.mark.parametrize("line,what", [
        ("-1,2", "row id"),          # negative: u64 would wrap
        ("1.5,2", "row id"),         # float: loadtxt would truncate
        ("1,2 # note", "column id"),  # inline comment
        (f"{1 << 64},2", "row id"),  # past ParseUint range
    ])
    def test_import_rejects_non_uint_fields(self, server, tmp_path,
                                            line, what):
        """numpy's C parser is laxer than the reference's ParseUint —
        these must all be per-row errors, never wrapped/truncated bits."""
        setup_schema(server)
        csv_file = tmp_path / "bad.csv"
        csv_file.write_text(f"1,2\n{line}\n")
        rc, _, err = run(["import", "--host", server.host,
                          "-i", "i", "-f", "f", str(csv_file)])
        assert rc == 1
        assert f"invalid {what} on row 2" in err

    def test_import_bad_row(self, server, tmp_path):
        setup_schema(server)
        csv_file = tmp_path / "bad.csv"
        csv_file.write_text("notanint,3\n")
        rc, _, err = run(["import", "--host", server.host,
                          "-i", "i", "-f", "f", str(csv_file)])
        assert rc == 1
        assert "invalid row id" in err

    def test_sort(self, tmp_path):
        from pilosa_tpu import SLICE_WIDTH
        csv_file = tmp_path / "s.csv"
        csv_file.write_text(f"5,{SLICE_WIDTH + 1}\n1,7\n0,9\n")
        rc, out, _ = run(["sort", str(csv_file)])
        assert rc == 0
        # Slice 0 rows first (by pos), then slice 1.
        assert out.splitlines() == ["0,9", "1,7", f"5,{SLICE_WIDTH + 1}"]


class TestBackupRestore:
    def test_roundtrip(self, server, tmp_path):
        setup_schema(server)
        server.holder.frame("i", "f").import_bits([1, 2], [3, 4])
        tarball = tmp_path / "backup.tar"
        rc, _, err = run(["backup", "--host", server.host, "-i", "i",
                          "-f", "f", "-o", str(tarball)])
        assert rc == 0, err
        assert tarball.stat().st_size > 0

        # Wipe and restore.
        server.holder.index("i").delete_frame("f")
        setup_schema(server)
        rc, _, err = run(["restore", "--host", server.host, "-i", "i",
                          "-f", "f", str(tarball)])
        assert rc == 0, err
        frag = server.holder.fragment("i", "f", "standard", 0)
        assert frag.row(1).count() == 1
        assert frag.row(2).count() == 1


class TestOffline:
    def test_check_ok_and_corrupt(self, server, tmp_path):
        setup_schema(server)
        frag = server.holder.frame("i", "f")
        frag.set_bit("standard", 1, 2)
        path = server.holder.fragment("i", "f", "standard", 0).path
        rc, out, _ = run(["check", path])
        assert rc == 0
        assert "ok" in out

        bad = tmp_path / "bad"
        bad.write_bytes(b"\x00" * 100)
        rc, out, _ = run(["check", str(bad)])
        assert rc == 1

    def test_inspect(self, server):
        setup_schema(server)
        server.holder.frame("i", "f").set_bit("standard", 0, 5)
        path = server.holder.fragment("i", "f", "standard", 0).path
        rc, out, _ = run(["inspect", path])
        assert rc == 0
        assert "Containers: 1" in out
        assert "array" in out


class TestBenchConfig:
    def test_bench_set_bit(self, server):
        setup_schema(server)
        rc, out, err = run(["bench", "--host", server.host, "-i", "i",
                            "-f", "f", "--op", "set-bit", "-n", "10"])
        assert rc == 0, err
        assert "op/sec" in out

    def test_config_prints_toml(self):
        rc, out, _ = run(["config"])
        assert rc == 0
        assert 'host = "localhost:10101"' in out

    def test_config_load_priority(self, tmp_path, monkeypatch):
        from pilosa_tpu.utils import config as config_mod
        toml = tmp_path / "cfg.toml"
        toml.write_text('data-dir = "/tmp/x"\nhost = "h1:1"\n'
                        '[cluster]\nreplicas = 3\nhosts = ["h1:1","h2:2"]\n'
                        'polling-interval = "30s"\n'
                        '[anti-entropy]\ninterval = "5m"\n')
        cfg = config_mod.load(str(toml), env={})
        assert cfg.data_dir == "/tmp/x"
        assert cfg.cluster.replica_n == 3
        assert cfg.cluster.polling_interval == 30.0
        assert cfg.anti_entropy_interval == 300.0
        # env beats file
        cfg = config_mod.load(str(toml), env={"PILOSA_HOST": "h9:9"})
        assert cfg.host == "h9:9"

    def test_config_parse_plugins(self, tmp_path):
        """[plugins] path parses from TOML and env, and round-trips
        through `pilosa config` output (cmd/server_test.go:86,
        config.go:48-50)."""
        from pilosa_tpu.utils import config as config_mod
        toml = tmp_path / "cfg.toml"
        toml.write_text('[plugins]\npath = "/var/sloth"\n')
        cfg = config_mod.load(str(toml), env={})
        assert cfg.plugins_path == "/var/sloth"
        assert 'path = "/var/sloth"' in cfg.to_toml()
        cfg = config_mod.load(str(toml),
                              env={"PILOSA_PLUGINS_PATH": "/opt/p"})
        assert cfg.plugins_path == "/opt/p"
        # default prints the empty key, like ctl/config.go:58
        rc, out, _ = run(["config"])
        assert rc == 0 and "[plugins]" in out


def test_check_accepts_reference_format_golden_files(capsys):
    """`pilosa check` must validate files in the reference wire format
    (the golden interchange fixtures) — CLI × interchange composition."""
    import glob
    import os

    from pilosa_tpu.cli.commands import main as cli_main
    golden = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "golden", "*.roaring")))
    assert golden
    rc = cli_main(["check", *golden])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count(": ok") == len(golden)
