"""StatsD/dogstatsd backend tests: wire format, tag hierarchy, and the
fire-and-forget failure mode (reference datadog/datadog.go)."""

from __future__ import annotations

import socket

import pytest

from pilosa_tpu.utils.statsd import StatsDStatsClient


@pytest.fixture
def agent():
    """A local UDP 'agent' capturing datagrams."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(2.0)
    yield sock
    sock.close()


def recv(sock) -> str:
    return sock.recvfrom(65536)[0].decode()


def make_client(agent) -> StatsDStatsClient:
    host, port = agent.getsockname()
    return StatsDStatsClient(f"{host}:{port}")


def test_count_wire_format(agent):
    make_client(agent).count("setBit", 3)
    assert recv(agent) == "pilosa.setBit:3|c"


def test_gauge_and_histogram(agent):
    c = make_client(agent)
    c.gauge("maxSlice", 42)
    assert recv(agent) == "pilosa.maxSlice:42|g"
    c.histogram("snapshotDurationSeconds", 1.5)
    assert recv(agent) == "pilosa.snapshotDurationSeconds:1.5|h"


def test_set_and_timing_ns_to_ms(agent):
    c = make_client(agent)
    c.set("indexes", "i0")
    assert recv(agent) == "pilosa.indexes:i0|s"
    c.timing("importDuration", 2_500_000)     # 2.5e6 ns == 2.5 ms
    assert recv(agent) == "pilosa.importDuration:2.5|ms"


def test_with_tags_appends_datadog_tags(agent):
    c = make_client(agent).with_tags("index:i0")
    c.count("setBit")
    assert recv(agent) == "pilosa.setBit:1|c|#index:i0"


def test_with_tags_hierarchical_merge_sorted_deduped(agent):
    c = make_client(agent).with_tags("index:i0")
    child = c.with_tags("frame:f0", "index:i0")
    child.count("clearBit", 2)
    assert recv(agent) == "pilosa.clearBit:2|c|#frame:f0,index:i0"
    # Parent unchanged by the child's tags.
    c.count("clearBit")
    assert recv(agent) == "pilosa.clearBit:1|c|#index:i0"


def test_agent_down_drops_silently():
    c = StatsDStatsClient("127.0.0.1:1")   # nothing listens on port 1
    c.count("whatever")                     # must not raise or block
    c.close()
