"""Executor tests (reference executor_test.go).

Multi-node behavior is tested the same way the reference does: a real
local Executor plus a cluster whose other node is reached through a
scripted fake client asserting the forwarded query and returning canned
results (executor_test.go:473-692).
"""

import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.errors import PilosaError
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.models.frame import FrameOptions
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.storage.bitmap import Bitmap
from pilosa_tpu.storage.cache import Pair


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def executor(holder):
    return Executor(holder, host="local")


def must_set(holder, index, frame, row, col, view="standard"):
    idx = holder.create_index_if_not_exists(index)
    f = idx.create_frame_if_not_exists(frame)
    f.set_bit(view, row, col)


class TestBitmapCalls:
    def test_bitmap(self, holder, executor):
        must_set(holder, "i", "general", 10, 3)
        must_set(holder, "i", "general", 10, SLICE_WIDTH + 1)
        res = executor.execute("i", "Bitmap(rowID=10, frame=general)")
        assert list(res[0].bits()) == [3, SLICE_WIDTH + 1]

    def test_bitmap_attaches_row_attrs(self, holder, executor):
        must_set(holder, "i", "general", 10, 3)
        holder.frame("i", "general").row_attr_store.set_attrs(
            10, {"category": "x"})
        res = executor.execute("i", "Bitmap(rowID=10, frame=general)")
        assert res[0].attrs == {"category": "x"}

    def test_inverse_bitmap(self, holder, executor):
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists(
            "f", FrameOptions(inverse_enabled=True))
        f.set_bit("standard", 5, 100)
        f.set_bit("inverse", 100, 5)
        res = executor.execute("i", "Bitmap(columnID=100, frame=f)")
        assert list(res[0].bits()) == [5]

    def test_inverse_bitmap_remote_leg_keeps_slices(self, holder, executor):
        # A forwarded inverse query arrives with explicit slice ids; they
        # must not be replaced by the (empty) locally-computed inverse
        # list.
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists(
            "f", FrameOptions(inverse_enabled=True))
        f.set_bit("inverse", 100, 5)
        res = executor.execute("i", "Bitmap(columnID=100, frame=f)",
                               slices=[0], opt=ExecOptions(remote=True))
        assert list(res[0].bits()) == [5]

    def test_inverse_requires_flag(self, holder, executor):
        must_set(holder, "i", "f", 1, 2)
        with pytest.raises(PilosaError, match="inverse"):
            executor.execute("i", "Bitmap(columnID=2, frame=f)")

    def test_intersect(self, holder, executor):
        for col in (3, 5, SLICE_WIDTH + 2):
            must_set(holder, "i", "general", 1, col)
        for col in (5, SLICE_WIDTH + 2, SLICE_WIDTH + 9):
            must_set(holder, "i", "general", 2, col)
        res = executor.execute(
            "i", "Intersect(Bitmap(rowID=1), Bitmap(rowID=2))")
        assert list(res[0].bits()) == [5, SLICE_WIDTH + 2]

    def test_union(self, holder, executor):
        must_set(holder, "i", "general", 1, 3)
        must_set(holder, "i", "general", 2, 5)
        res = executor.execute("i", "Union(Bitmap(rowID=1), Bitmap(rowID=2))")
        assert list(res[0].bits()) == [3, 5]

    def test_difference(self, holder, executor):
        for col in (1, 2, 3):
            must_set(holder, "i", "general", 1, col)
        must_set(holder, "i", "general", 2, 2)
        res = executor.execute(
            "i", "Difference(Bitmap(rowID=1), Bitmap(rowID=2))")
        assert list(res[0].bits()) == [1, 3]

    def test_empty_intersect_errors(self, holder, executor):
        must_set(holder, "i", "general", 1, 1)
        with pytest.raises(PilosaError, match="empty Intersect"):
            executor.execute("i", "Intersect()")

    def test_count(self, holder, executor):
        must_set(holder, "i", "general", 10, 3)
        must_set(holder, "i", "general", 10, SLICE_WIDTH + 1)
        must_set(holder, "i", "general", 10, SLICE_WIDTH + 2)
        res = executor.execute("i", "Count(Bitmap(rowID=10))")
        assert res[0] == 3


class TestSetBit:
    def test_set_and_clear(self, holder, executor):
        holder.create_index_if_not_exists("i").create_frame_if_not_exists(
            "f")
        res = executor.execute("i", "SetBit(rowID=11, frame=f, columnID=2)")
        assert res[0] is True
        res = executor.execute("i", "SetBit(rowID=11, frame=f, columnID=2)")
        assert res[0] is False  # no change
        assert executor.execute("i", "Count(Bitmap(rowID=11, frame=f))") \
            == [1]
        assert executor.execute(
            "i", "ClearBit(rowID=11, frame=f, columnID=2)") == [True]
        assert executor.execute(
            "i", "ClearBit(rowID=11, frame=f, columnID=2)") == [False]

    def test_set_with_timestamp_creates_time_views(self, holder, executor):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists("f", FrameOptions(time_quantum="Y"))
        executor.execute(
            "i",
            'SetBit(rowID=1, frame=f, columnID=2,'
            ' timestamp="2017-03-04T10:30")')
        assert set(holder.frame("i", "f").views) == {"standard",
                                                     "standard_2017"}

    def test_set_inverse_pair(self, holder, executor):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "f", FrameOptions(inverse_enabled=True))
        executor.execute("i", "SetBit(rowID=3, frame=f, columnID=9)")
        # Inverse view holds the transpose.
        res = executor.execute("i", "Bitmap(columnID=9, frame=f)")
        assert list(res[0].bits()) == [3]

    def test_missing_frame_errors(self, holder, executor):
        holder.create_index_if_not_exists("i")
        with pytest.raises(PilosaError):
            executor.execute("i", "SetBit(rowID=1, frame=nope, columnID=2)")


class TestRange:
    def test_range_unions_time_views(self, holder, executor):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists("f", FrameOptions(time_quantum="YMDH"))
        q = ('SetBit(rowID=1, frame=f, columnID={col},'
             ' timestamp="{ts}")')
        executor.execute("i", q.format(col=1, ts="2017-01-01T00:00"))
        executor.execute("i", q.format(col=2, ts="2017-01-02T00:00"))
        executor.execute("i", q.format(col=3, ts="2017-02-01T00:00"))
        res = executor.execute(
            "i", 'Range(rowID=1, frame=f, start="2017-01-01T00:00",'
                 ' end="2017-01-31T00:00")')
        assert list(res[0].bits()) == [1, 2]

    def test_range_requires_row_field(self, holder, executor):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists("f", FrameOptions(time_quantum="Y"))
        with pytest.raises(PilosaError, match="row field"):
            executor.execute(
                "i", 'Range(frame=f, start="2017-01-01T00:00",'
                     ' end="2017-01-31T00:00")')

    def test_range_no_quantum_empty(self, holder, executor):
        must_set(holder, "i", "f", 1, 2)
        res = executor.execute(
            "i", 'Range(rowID=1, frame=f, start="2017-01-01T00:00",'
                 ' end="2017-01-31T00:00")')
        assert res[0].count() == 0


class TestTopN:
    def test_top_n(self, holder, executor):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "f", FrameOptions(cache_type="ranked"))
        f = holder.frame("i", "f")
        for col in range(5):
            f.set_bit("standard", 0, col)
        for col in range(3):
            f.set_bit("standard", 10, col)
        for col in range(4):
            f.set_bit("standard", 2, SLICE_WIDTH + col)
        for frag in f.view("standard").fragments.values():
            frag.recalculate_cache()
        res = executor.execute("i", "TopN(frame=f, n=2)")
        assert res[0] == [Pair(0, 5), Pair(2, 4)]

    def test_top_n_with_src(self, holder, executor):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "f", FrameOptions(cache_type="ranked"))
        f = holder.frame("i", "f")
        for col in (1, 2, 3, 4):
            f.set_bit("standard", 0, col)
        for col in (1, 2):
            f.set_bit("standard", 5, col)
        f.set_bit("standard", 7, 1)
        f.view("standard").fragment(0).recalculate_cache()
        # src = row 0's bits; ranked intersection counts.
        res = executor.execute(
            "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=2)")
        assert res[0] == [Pair(0, 4), Pair(5, 2)]
        # Staleness regression (round 5: src-cols memo + count-map
        # cache): mutating a CANDIDATE row must refresh its count on
        # the next query...
        f.set_bit("standard", 5, 3)
        f.view("standard").fragment(0).recalculate_cache()
        res = executor.execute(
            "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=2)")
        assert res[0] == [Pair(0, 4), Pair(5, 3)]
        # ...and mutating the SRC row must invalidate the memoized
        # src key (fresh row object) and the map.
        f.set_bit("standard", 0, 9)
        f.set_bit("standard", 7, 9)
        f.view("standard").fragment(0).recalculate_cache()
        res = executor.execute(
            "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=3)")
        assert res[0] == [Pair(0, 5), Pair(5, 3), Pair(7, 2)]

    def test_top_n_fill(self, holder, executor):
        """executor_test.go:300-322: the global winner's count must
        aggregate across slices even when the per-slice tops differ —
        the exact phase re-queries every candidate everywhere."""
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "f", FrameOptions(cache_type="ranked"))
        f = holder.frame("i", "f")
        for col in (0, 1, 2):
            f.set_bit("standard", 0, col)
        f.set_bit("standard", 0, SLICE_WIDTH)
        f.set_bit("standard", 1, SLICE_WIDTH + 2)
        f.set_bit("standard", 1, SLICE_WIDTH)
        for frag in f.view("standard").fragments.values():
            frag.recalculate_cache()
        res = executor.execute("i", "TopN(frame=f, n=1)")
        assert res[0] == [Pair(0, 4)]

    def test_top_n_fill_small(self, holder, executor):
        """executor_test.go:324-356: row 0 is never any slice's sole
        standout (1 bit/slice over 5 slices vs 2-bit rows per slice)
        yet must win globally with count 5."""
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "f", FrameOptions(cache_type="ranked"))
        f = holder.frame("i", "f")
        for s in range(5):
            f.set_bit("standard", 0, s * SLICE_WIDTH)
        f.set_bit("standard", 1, 0)
        f.set_bit("standard", 1, 1)
        f.set_bit("standard", 2, SLICE_WIDTH)
        f.set_bit("standard", 2, SLICE_WIDTH + 1)
        f.set_bit("standard", 3, 2 * SLICE_WIDTH)
        f.set_bit("standard", 3, 2 * SLICE_WIDTH + 1)
        f.set_bit("standard", 4, 3 * SLICE_WIDTH)
        f.set_bit("standard", 4, 3 * SLICE_WIDTH + 1)
        for frag in f.view("standard").fragments.values():
            frag.recalculate_cache()
        res = executor.execute("i", "TopN(frame=f, n=1)")
        assert res[0] == [Pair(0, 5)]

    def test_top_n_int_attr_filter(self, holder, executor):
        """executor_test.go:391-435: attribute filters with INT values
        (filters=[123] against an int64-typed attr), with and without a
        source bitmap, across two slices."""
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "f", FrameOptions(cache_type="ranked"))
        f = holder.frame("i", "f")
        f.set_bit("standard", 0, 0)
        f.set_bit("standard", 0, 1)
        f.set_bit("standard", 10, SLICE_WIDTH)
        f.row_attr_store.set_attrs(10, {"category": 123})
        for view in f.views.values():
            for frag in view.fragments.values():
                frag.recalculate_cache()
        res = executor.execute(
            "i", 'TopN(frame="f", n=1, field="category", filters=[123])')
        assert res[0] == [Pair(10, 1)]
        res = executor.execute(
            "i", 'TopN(Bitmap(rowID=10, frame=f), frame="f", n=1,'
                 ' field="category", filters=[123])')
        assert res[0] == [Pair(10, 1)]

    def test_top_n_ids(self, holder, executor):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "f", FrameOptions(cache_type="ranked"))
        f = holder.frame("i", "f")
        for col in range(5):
            f.set_bit("standard", 0, col)
        for col in range(3):
            f.set_bit("standard", 1, col)
        res = executor.execute("i", "TopN(frame=f, ids=[1])")
        assert res[0] == [Pair(1, 3)]


class TestAttrs:
    def test_set_row_attrs(self, holder, executor):
        must_set(holder, "i", "f", 10, 1)
        executor.execute("i", 'SetRowAttrs(rowID=10, frame=f, foo="bar")')
        assert holder.frame("i", "f").row_attr_store.attrs(10) == \
            {"foo": "bar"}

    def test_bulk_set_row_attrs(self, holder, executor):
        must_set(holder, "i", "f", 1, 1)
        res = executor.execute(
            "i",
            'SetRowAttrs(rowID=1, frame=f, a=1)\n'
            'SetRowAttrs(rowID=2, frame=f, b=true)')
        assert res == [None, None]
        store = holder.frame("i", "f").row_attr_store
        assert store.attrs(1) == {"a": 1}
        assert store.attrs(2) == {"b": True}

    def test_set_column_attrs(self, holder, executor):
        must_set(holder, "i", "f", 1, 10)
        executor.execute("i", 'SetColumnAttrs(columnID=10, foo="baz")')
        assert holder.index("i").column_attr_store.attrs(10) == \
            {"foo": "baz"}

    def test_typed_attrs_persist_across_reopen(self, holder, executor):
        """All four reference attr types (attr.go:34-40) through PQL,
        surviving a holder reopen byte-typed (protobuf AttrMap)."""
        must_set(holder, "i", "f", 1, 1)
        executor.execute(
            "i", 'SetRowAttrs(frame="f", rowID=1, active=true,'
                 ' weight=1.5, name="x", rank=9)')
        want = {"active": True, "weight": 1.5, "name": "x", "rank": 9}
        assert holder.frame("i", "f").row_attr_store.attrs(1) == want
        path = holder.path
        holder.close()
        h2 = Holder(path)
        h2.open()
        try:
            got = h2.frame("i", "f").row_attr_store.attrs(1)
            assert got == want
            assert isinstance(got["active"], bool)
            assert isinstance(got["weight"], float)
            assert isinstance(got["rank"], int)
        finally:
            h2.close()
            holder.open()  # fixture teardown closes it again


class FakeClient:
    """Scripted remote transport (reference executor_test.go mock server)."""

    def __init__(self, fn):
        self.fn = fn
        self.calls = []

    def execute_query(self, node, index, query, slices, remote):
        self.calls.append((node.host, index, query, slices, remote))
        return self.fn(node, index, query, slices)


class TestDistributed:
    def _two_node(self, holder, fn, replica_n=1):
        cluster = new_cluster(["local", "remotehost"], replica_n=replica_n)
        client = FakeClient(fn)
        e = Executor(holder, host="local", cluster=cluster, client=client)
        return e, client, cluster

    def test_remote_count_merges(self, holder):
        must_set(holder, "i", "general", 10, 3)  # slice 0 data

        def fn(node, index, query, slices):
            assert query == "Count(Bitmap(frame=\"general\", rowID=10))"
            return [7]

        e, client, cluster = self._two_node(holder, fn)
        # Force 3 slices; remote node owns some of them.
        holder.index("i").set_remote_max_slice(2)
        res = e.execute("i", "Count(Bitmap(rowID=10, frame=general))")
        slice0_local = cluster.fragment_nodes("i", 0)[0].host == "local"
        remote_slices = [s for s in range(3)
                         if cluster.fragment_nodes("i", s)[0].host
                         == "remotehost"]
        # All remote slices arrive grouped into ONE exec call.
        assert len(client.calls) == (1 if remote_slices else 0)
        expected = (1 if slice0_local else 0) + \
            (7 if remote_slices else 0)
        assert res[0] == expected

    def test_remote_bitmap_merges(self, holder):
        must_set(holder, "i", "general", 10, 3)
        holder.index("i").set_remote_max_slice(2)

        def fn(node, index, query, slices):
            bm = Bitmap()
            for s in slices:
                bm.set_bit(s * SLICE_WIDTH + 42)
            return [bm]

        e, client, cluster = self._two_node(holder, fn)
        res = e.execute("i", "Bitmap(rowID=10, frame=general)")
        bits = set(res[0].bits())
        if cluster.fragment_nodes("i", 0)[0].host == "local":
            assert 3 in bits
        for host, index, query, slices, remote in client.calls:
            assert remote is True
            for s in slices:
                assert s * SLICE_WIDTH + 42 in bits

    def test_remote_topn_two_phase(self, holder):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "f", FrameOptions(cache_type="ranked"))
        f = holder.frame("i", "f")
        for col in range(4):
            f.set_bit("standard", 0, col)
        f.view("standard").fragment(0).recalculate_cache()
        idx.set_remote_max_slice(2)

        def fn(node, index, query, slices):
            if "ids=" in query:
                return [[Pair(0, 1), Pair(30, 5)]]  # exact-count phase
            return [[Pair(30, 5)]]

        e, client, cluster = self._two_node(holder, fn)
        res = e.execute("i", "TopN(frame=f, n=2)")
        has_remote = any(cluster.fragment_nodes("i", s)[0].host
                         == "remotehost" for s in range(3))
        slice0_local = cluster.fragment_nodes("i", 0)[0].host == "local"
        assert has_remote  # 3 slices over 2 nodes: some leg is remote
        # Second phase re-queried with the candidate ids.
        assert any("ids=" in c[2] for c in client.calls)
        if slice0_local:
            # local Pair(0,4) + remote phase-2 Pair(0,1) merge to 5.
            assert res[0] == [Pair(0, 5), Pair(30, 5)]
        else:
            # local fragment not owned → only remote results survive.
            assert res[0] == [Pair(30, 5), Pair(0, 1)]

    def test_setbit_forwards_to_owner(self, holder):
        holder.create_index_if_not_exists("i").create_frame_if_not_exists(
            "f")

        def fn(node, index, query, slices):
            assert query.startswith("SetBit(")
            return [True]

        e, client, cluster = self._two_node(holder, fn, replica_n=2)
        res = e.execute("i", "SetBit(rowID=1, frame=f, columnID=3)")
        assert res[0] is True
        # replica_n=2 on 2 nodes → both own slice 0; remote got the call.
        assert len(client.calls) == 1
        # Local write also landed.
        assert holder.fragment("i", "f", "standard", 0).row(1).count() == 1

    def test_remote_flag_stops_forwarding(self, holder):
        holder.create_index_if_not_exists("i").create_frame_if_not_exists(
            "f")

        def fn(node, index, query, slices):
            raise AssertionError("must not forward when remote=True")

        e, client, cluster = self._two_node(holder, fn, replica_n=2)
        res = e.execute("i", "SetBit(rowID=1, frame=f, columnID=3)",
                        opt=ExecOptions(remote=True))
        assert res[0] is True
        assert client.calls == []

    def test_failed_node_retries_on_replica(self, holder):
        must_set(holder, "i", "general", 10, 3)
        holder.index("i").set_remote_max_slice(2)
        attempts = []

        def fn(node, index, query, slices):
            attempts.append(list(slices))
            raise ConnectionError("node down")

        # replica_n=2 on 2 nodes → every slice is owned by both; when the
        # remote leg fails its slices re-map onto the local node.
        e, client, cluster = self._two_node(holder, fn, replica_n=2)
        res = e.execute("i", "Count(Bitmap(rowID=10, frame=general))")
        assert res[0] == 1  # all slices eventually served locally

    def test_attr_write_broadcasts(self, holder):
        must_set(holder, "i", "f", 10, 1)

        def fn(node, index, query, slices):
            assert query == 'SetRowAttrs(foo="bar", frame="f", rowID=10)'
            return [None]

        e, client, cluster = self._two_node(holder, fn)
        e.execute("i", 'SetRowAttrs(rowID=10, frame=f, foo="bar")')
        assert len(client.calls) == 1  # forwarded to the one other node


class TestDeviceCountPath:
    """The mesh-batched Count fast path must agree exactly with the
    per-slice host path on randomized data (and engage when eligible)."""

    def _fill(self, holder, rng, frame="f", rows=(1, 2, 3), slices=3):
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists(frame)
        for row in rows:
            cols = rng.choice(slices * SLICE_WIDTH,
                              size=rng.integers(50, 200), replace=False)
            for col in cols:
                f.set_bit("standard", int(row), int(col))

    def test_matches_host_path(self, holder):
        import numpy as np
        rng = np.random.default_rng(7)
        self._fill(holder, rng)
        queries = [
            'Count(Bitmap(rowID=1, frame=f))',
            'Count(Intersect(Bitmap(rowID=1, frame=f),'
            ' Bitmap(rowID=2, frame=f)))',
            'Count(Union(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f),'
            ' Bitmap(rowID=3, frame=f)))',
            'Count(Difference(Bitmap(rowID=1, frame=f),'
            ' Bitmap(rowID=2, frame=f), Bitmap(rowID=3, frame=f)))',
            'Count(Union(Intersect(Bitmap(rowID=1, frame=f),'
            ' Bitmap(rowID=2, frame=f)), Bitmap(rowID=3, frame=f)))',
            'Count(Bitmap(rowID=99, frame=f))',  # absent row
        ]
        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        slow = Executor(holder, host="local", use_mesh=False)
        for q in queries:
            assert fast.execute("i", q) == slow.execute("i", q), q

    def test_fast_path_engages(self, holder, monkeypatch):
        import numpy as np
        rng = np.random.default_rng(8)
        self._fill(holder, rng)
        f = holder.frame("i", "f")
        for col in (7, SLICE_WIDTH + 9, 2 * SLICE_WIDTH + 11):
            f.set_bit("standard", 1, col)
            f.set_bit("standard", 2, col)
        ex = Executor(holder, host="local", use_mesh=True,
                      mesh_min_slices=1)
        called = {}
        from pilosa_tpu.parallel import mesh as mesh_mod
        orig = mesh_mod.count_expr_sharded

        def spy(mesh, expr, arrs):
            called["expr"] = expr
            called["n_leaves"] = len(arrs)
            return orig(mesh, expr, arrs)

        monkeypatch.setattr(mesh_mod, "count_expr_sharded", spy)
        res = ex.execute("i", 'Count(Intersect(Bitmap(rowID=1, frame=f),'
                              ' Bitmap(rowID=2, frame=f)))')
        assert called["expr"] == ("and", ("leaf", 0), ("leaf", 1))
        assert called["n_leaves"] == 2
        assert res[0] >= 3  # the three overlap columns, one per slice

    def test_range_on_device_matches_host(self, holder, monkeypatch):
        """Range compiles to an or-fold over its time-view cover
        (executor.go:490-546 semantics on the mesh path)."""
        import numpy as np
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists(
            "tq", FrameOptions(time_quantum="YMD"))
        rng = np.random.default_rng(13)
        write = Executor(holder, host="local", use_mesh=False)
        for day in (2, 3, 9, 28):
            for col in rng.choice(3 * SLICE_WIDTH, size=40, replace=False):
                write.execute(
                    "i", f'SetBit(rowID=1, frame=tq, columnID={int(col)},'
                         f' timestamp="2017-01-{day:02d}T00:00")')
        queries = [
            'Count(Range(rowID=1, frame=tq,'
            ' start="2017-01-01T00:00", end="2017-02-01T00:00"))',
            'Count(Range(rowID=1, frame=tq,'
            ' start="2017-01-03T00:00", end="2017-01-10T00:00"))',
            # Range composed with a plain Bitmap leaf
            'Count(Intersect(Range(rowID=1, frame=tq,'
            ' start="2017-01-01T00:00", end="2018-01-01T00:00"),'
            ' Bitmap(rowID=1, frame=tq)))',
            # empty cover window
            'Count(Range(rowID=1, frame=tq,'
            ' start="2016-01-01T00:00", end="2016-02-01T00:00"))',
        ]
        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        slow = Executor(holder, host="local", use_mesh=False)
        # Prove the device path actually executes the Range form — a
        # compile regression to None would make fast == slow trivially.
        engaged = []
        from pilosa_tpu.parallel import mesh as mesh_mod
        orig = mesh_mod.count_expr_sharded

        def spy(mesh, expr, arrs):
            engaged.append(len(arrs))
            return orig(mesh, expr, arrs)

        monkeypatch.setattr(mesh_mod, "count_expr_sharded", spy)
        for q in queries:
            assert fast.execute("i", q) == slow.execute("i", q), q
        assert fast.device_fallbacks == 0
        # All 4 engage — the time cover is by WINDOW, not data, so the
        # out-of-data 2016 window still compiles (absent fragments pack
        # as zeros). Jan 3→10 covers exactly 7 day views.
        assert engaged == [1, 7, 2, 1], engaged

    def test_range_without_quantum_falls_back(self, holder):
        """Range on a quantum-less frame isn't device-eligible — must
        still answer through the host path (which owns the semantics:
        empty bitmap)."""
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists("plain")
        ex = Executor(holder, host="local", use_mesh=True,
                      mesh_min_slices=1)
        ex.execute("i", 'SetBit(rowID=1, frame=plain, columnID=5)')
        res = ex.execute(
            "i", 'Count(Range(rowID=1, frame=plain,'
                 ' start="2017-01-01T00:00", end="2017-02-01T00:00"))')
        assert res[0] == 0


class TestDeviceTopNPath:
    """Mesh-batched TopN exact-count phase must agree with the per-slice
    host path and engage for the eligible form."""

    def _fill(self, holder, slices=3):
        import numpy as np
        rng = np.random.default_rng(11)
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")
        for row in range(6):
            cols = rng.choice(slices * SLICE_WIDTH, size=120, replace=False)
            for col in cols:
                f.set_bit("standard", row, int(col))
        # deterministic overlaps so intersections are non-trivial
        for col in range(0, slices * SLICE_WIDTH, SLICE_WIDTH // 2):
            for row in range(6):
                f.set_bit("standard", row, col)

    def test_topn_matches_host_path(self, holder):
        self._fill(holder)
        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        slow = Executor(holder, host="local", use_mesh=False)
        queries = [
            'TopN(frame=f, n=3)',
            'TopN(frame=f, n=4, ids=[0,1,2,3,4,5])',
            'TopN(Bitmap(rowID=0, frame=f), frame=f, n=4)',
            'TopN(Intersect(Bitmap(rowID=0, frame=f),'
            ' Bitmap(rowID=1, frame=f)), frame=f, n=3)',
        ]
        for q in queries:
            assert fast.execute("i", q) == slow.execute("i", q), q

    def test_topn_all_option_combinations_match_host(self, holder):
        """VERDICT r1 item 7: threshold>1, Tanimoto, and attr filters
        must run the device path with per-slice pruning semantics
        identical to the per-slice host path, at ≥8 slices."""
        self._fill(holder, slices=8)
        store = holder.frame("i", "f").row_attr_store
        for rid in range(6):
            store.set_attrs(rid, {"cat": "x" if rid % 2 == 0 else "y"})
        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        slow = Executor(holder, host="local", use_mesh=False)
        ids = "ids=[0,1,2,3,4,5]"
        src = "Bitmap(rowID=0, frame=f)"
        queries = [
            f'TopN({src}, frame=f, {ids}, threshold=2)',
            f'TopN({src}, frame=f, {ids}, threshold=40)',
            f'TopN({src}, frame=f, {ids}, tanimotoThreshold=5)',
            f'TopN({src}, frame=f, {ids}, tanimotoThreshold=60)',
            f'TopN({src}, frame=f, {ids}, field="cat", filters=["x"])',
            f'TopN({src}, frame=f, {ids}, field="cat", filters=["y"],'
            ' threshold=2)',
            f'TopN({src}, frame=f, {ids}, field="cat", filters=["x"],'
            ' tanimotoThreshold=10)',
            f'TopN({src}, frame=f, {ids}, field="cat", filters=["z"])',
            # no-ids phase with options still goes per-slice, then the
            # refetch phase engages the device with the options cloned
            f'TopN({src}, frame=f, n=3, threshold=2)',
            f'TopN({src}, frame=f, n=3, field="cat", filters=["x"])',
        ]
        for q in queries:
            f_res = fast.execute("i", q)
            s_res = slow.execute("i", q)
            assert [(p.id, p.count) for p in f_res[0]] == \
                [(p.id, p.count) for p in s_res[0]], q
        assert fast.device_fallbacks == 0

    def test_topn_filtered_streaming_matches_host(self, holder,
                                                  monkeypatch):
        """Filtered forms past the resident block budget must stream
        through the chunked filtered program, staying exact."""
        self._fill(holder, slices=8)
        from pilosa_tpu.parallel import mesh as mesh_mod
        # Shrink the device-block budget so the 8-slice candidate block
        # exceeds it → the executor takes the streaming branch, and the
        # stream itself row-chunks.
        monkeypatch.setattr(mesh_mod, "TOPN_BLOCK_BYTES", 1 << 20)
        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        slow = Executor(holder, host="local", use_mesh=False)
        src = "Bitmap(rowID=0, frame=f)"
        for q in (f'TopN({src}, frame=f, ids=[0,1,2,3,4,5], threshold=2)',
                  f'TopN({src}, frame=f, ids=[0,1,2,3,4,5],'
                  ' tanimotoThreshold=20)'):
            f_res = fast.execute("i", q)
            s_res = slow.execute("i", q)
            assert [(p.id, p.count) for p in f_res[0]] == \
                [(p.id, p.count) for p in s_res[0]], q
        assert fast.device_fallbacks == 0

    def test_exact_phase_engages(self, holder, monkeypatch):
        self._fill(holder)
        ex = Executor(holder, host="local", use_mesh=True,
                      mesh_min_slices=1)
        calls = []
        from pilosa_tpu.parallel import mesh as mesh_mod
        orig = mesh_mod.topn_exact_sharded

        def spy(mesh, expr, rows, leaves):
            calls.append((expr, rows.shape))
            return orig(mesh, expr, rows, leaves)

        monkeypatch.setattr(mesh_mod, "topn_exact_sharded", spy)
        res = ex.execute("i", 'TopN(Bitmap(rowID=0, frame=f), frame=f, n=3)')
        assert calls, "TopN exact phase did not use the mesh path"
        assert calls[-1][0] == ("leaf", 0)
        assert len(res[0]) == 3

    def test_filters_fall_back(self, holder, monkeypatch):
        self._fill(holder)
        holder.frame("i", "f").row_attr_store.set_attrs(0, {"cat": "x"})
        ex = Executor(holder, host="local", use_mesh=True,
                      mesh_min_slices=1)
        from pilosa_tpu.parallel import mesh as mesh_mod

        def boom(*a, **kw):
            raise AssertionError("device path must not engage with filters")

        monkeypatch.setattr(mesh_mod, "topn_exact", boom)
        monkeypatch.setattr(mesh_mod, "topn_exact_sharded", boom)
        res = ex.execute(
            "i", 'TopN(frame=f, n=2, field="cat", filters=["x"],'
                 ' ids=[0,1,2])')
        assert all(p.id == 0 for p in res[0])


class TestBatchedCounts:
    """Consecutive Count calls in one PQL query fuse into ONE mesh
    program (one device dispatch) with shared, deduplicated leaves."""

    def _fill(self, holder, slices=8):
        import numpy as np
        rng = np.random.default_rng(55)
        f = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        for row in range(4):
            for col in rng.choice(slices * SLICE_WIDTH, size=150,
                                  replace=False):
                f.set_bit("standard", row, int(col))

    QUERY = ("Count(Bitmap(rowID=0, frame=f))"
             " Count(Intersect(Bitmap(rowID=0, frame=f),"
             " Bitmap(rowID=1, frame=f)))"
             " Count(Union(Bitmap(rowID=2, frame=f),"
             " Bitmap(rowID=3, frame=f)))")

    def test_batch_matches_sequential(self, holder):
        self._fill(holder)
        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        slow = Executor(holder, host="local", use_mesh=False)
        assert fast.execute("i", self.QUERY) == \
            slow.execute("i", self.QUERY)
        assert fast.device_fallbacks == 0

    def test_single_dispatch_with_shared_leaves(self, holder,
                                                monkeypatch):
        self._fill(holder)
        ex = Executor(holder, host="local", use_mesh=True,
                      mesh_min_slices=1)
        calls = []
        from pilosa_tpu.parallel import mesh as mesh_mod
        orig = mesh_mod.count_exprs_sharded

        def spy(mesh, exprs, arrs):
            calls.append((exprs, len(arrs)))
            return orig(mesh, exprs, arrs)

        monkeypatch.setattr(mesh_mod, "count_exprs_sharded", spy)
        ex.execute("i", self.QUERY)
        assert len(calls) == 1  # three Counts, one program
        exprs, n_leaves = calls[0]
        assert len(exprs) == 3
        assert n_leaves == 4  # rowID 0 shared between calls 1 and 2
        assert exprs[1] == ("and", ("leaf", 0), ("leaf", 1))

    def test_mixed_calls_batch_only_runs(self, holder, monkeypatch):
        self._fill(holder)
        ex = Executor(holder, host="local", use_mesh=True,
                      mesh_min_slices=1)
        calls = []
        from pilosa_tpu.parallel import mesh as mesh_mod
        orig = mesh_mod.count_exprs_sharded

        def spy(mesh, exprs, arrs):
            calls.append(len(exprs))
            return orig(mesh, exprs, arrs)

        monkeypatch.setattr(mesh_mod, "count_exprs_sharded", spy)
        q = ("Count(Bitmap(rowID=0, frame=f))"
             " Count(Bitmap(rowID=1, frame=f))"
             " SetBit(rowID=9, frame=f, columnID=3)"
             " Count(Bitmap(rowID=2, frame=f))")
        res = ex.execute("i", q)
        # The leading run of 2 fuses; the trailing lone Count runs as
        # the K=1 form through the same program builder.
        assert calls == [2, 1]
        assert res[2] is True and len(res) == 4
        slow = Executor(holder, host="local", use_mesh=False)
        assert res[:2] == slow.execute(
            "i", "Count(Bitmap(rowID=0, frame=f))"
                 " Count(Bitmap(rowID=1, frame=f))")

    def test_cluster_does_not_batch(self, holder, monkeypatch):
        """Batching would bypass remote legs — multi-node clusters
        must keep per-call map-reduce."""
        self._fill(holder, slices=2)
        cluster = new_cluster(["local", "other"])
        ex = Executor(holder, host="local", cluster=cluster,
                      use_mesh=True, mesh_min_slices=1,
                      client=type("C", (), {
                          "execute_query":
                          lambda self, node, index, q, s, remote:
                          [0]})())
        from pilosa_tpu.parallel import mesh as mesh_mod

        def boom(*a, **kw):
            raise AssertionError("batched on a multi-node cluster")

        monkeypatch.setattr(mesh_mod, "count_exprs_sharded", boom)
        ex.execute("i", "Count(Bitmap(rowID=0, frame=f))"
                        " Count(Bitmap(rowID=1, frame=f))")


class TestDeviceMaterializePath:
    """Materializing Union/Intersect/Difference on device (BASELINE
    config 2) must agree bit-for-bit with the per-slice roaring path
    and engage only on wide fan-outs."""

    N_ROWS = 10

    def _fill(self, holder, slices=8):
        import numpy as np
        rng = np.random.default_rng(77)
        f = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        for row in range(self.N_ROWS):
            cols = rng.choice(slices * SLICE_WIDTH, size=300,
                              replace=False)
            for col in cols:
                f.set_bit("standard", row, int(col))

    def _wide(self, name, rows=None):
        rows = rows if rows is not None else range(self.N_ROWS)
        children = ", ".join(f"Bitmap(rowID={r}, frame=f)" for r in rows)
        return f"{name}({children})"

    def test_wide_calls_match_host(self, holder):
        self._fill(holder)
        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        slow = Executor(holder, host="local", use_mesh=False)
        for q in (self._wide("Union"), self._wide("Intersect"),
                  self._wide("Difference"),
                  self._wide("Union", range(0, self.N_ROWS, 2))):
            f_bits = list(fast.execute("i", q)[0].bits())
            s_bits = list(slow.execute("i", q)[0].bits())
            assert f_bits == s_bits, q
        assert fast.device_fallbacks == 0

    def test_engages_wide_not_narrow(self, holder, monkeypatch):
        self._fill(holder)
        ex = Executor(holder, host="local", use_mesh=True,
                      mesh_min_slices=1)
        calls = []
        from pilosa_tpu.parallel import mesh as mesh_mod
        orig = mesh_mod.materialize_expr_sharded

        def spy(mesh, expr, arrs):
            calls.append(len(arrs))
            return orig(mesh, expr, arrs)

        monkeypatch.setattr(mesh_mod, "materialize_expr_sharded", spy)
        ex.execute("i", self._wide("Union"))
        assert calls == [self.N_ROWS]
        ex.execute("i", "Union(Bitmap(rowID=0, frame=f),"
                        " Bitmap(rowID=1, frame=f))")
        assert calls == [self.N_ROWS]  # narrow fold stayed host-side

    def test_count_over_wide_union_uses_reduce(self, holder):
        """The 3+-leaf fold goes through _eval_expr's lax.reduce path —
        counts must stay exact."""
        self._fill(holder, slices=4)
        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        slow = Executor(holder, host="local", use_mesh=False)
        q = f"Count({self._wide('Union')})"
        assert fast.execute("i", q) == slow.execute("i", q)
        q = f"Count({self._wide('Difference')})"
        assert fast.execute("i", q) == slow.execute("i", q)


class TestDevicePathFuzz:
    """Randomized parity: device mesh Count/TopN vs the host roaring
    path over random expression trees and bit distributions (the
    reference's quick-check style, applied to the TPU fast paths)."""

    def test_random_expressions_agree(self, holder):
        import numpy as np
        rng = np.random.default_rng(1234)
        slices = 4
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")
        n_rows = 5
        for row in range(n_rows):
            # mixed densities: some rows dense in one slice, sparse rest
            dense_slice = int(rng.integers(slices))
            cols = rng.choice(SLICE_WIDTH // 64, size=300, replace=False)
            for col in cols:
                f.set_bit("standard", row,
                          int(dense_slice * SLICE_WIDTH + col))
            cols = rng.choice(slices * SLICE_WIDTH, size=60, replace=False)
            for col in cols:
                f.set_bit("standard", row, int(col))

        # A time-quantum frame so random leaves can also be Range calls
        # (compiled as or-folds over their time-view covers).
        tqf = idx.create_frame_if_not_exists(
            "tqf", FrameOptions(time_quantum="YMD"))
        slow = Executor(holder, host="local", use_mesh=False)
        for day in (1, 5, 14, 27):
            for col in rng.choice(slices * SLICE_WIDTH, size=30,
                                  replace=False):
                slow.execute(
                    "i", f'SetBit(rowID=1, frame=tqf, columnID={int(col)},'
                         f' timestamp="2017-06-{day:02d}T00:00")')

        def rand_leaf():
            if rng.random() < 0.25:
                d0, d1 = sorted(rng.integers(1, 29, size=2).tolist())
                return (f'Range(rowID=1, frame=tqf,'
                        f' start="2017-06-{d0:02d}T00:00",'
                        f' end="2017-06-{d1 + 1:02d}T00:00")')
            return f'Bitmap(rowID={int(rng.integers(n_rows + 1))}, frame=f)'

        def rand_expr(depth):
            if depth == 0 or rng.random() < 0.4:
                return rand_leaf()
            op = rng.choice(["Intersect", "Union", "Difference"])
            k = int(rng.integers(2, 4))
            return f"{op}({', '.join(rand_expr(depth - 1) for _ in range(k))})"

        fast = Executor(holder, host="local", use_mesh=True,
                        mesh_min_slices=1)
        for _ in range(25):
            q = f"Count({rand_expr(2)})"
            assert fast.execute("i", q) == slow.execute("i", q), q
        for _ in range(10):
            ids = sorted(set(int(x) for x in rng.integers(n_rows + 1,
                                                          size=3)))
            q = (f"TopN({rand_expr(1)}, frame=f, n=4,"
                 f" ids={list(ids)})")
            assert fast.execute("i", q) == slow.execute("i", q), q
        # Multi-Count queries fuse into one batched program — parity
        # must hold for random run lengths and shared leaves.
        for _ in range(10):
            k = int(rng.integers(2, 6))
            q = " ".join(f"Count({rand_expr(1)})" for _ in range(k))
            assert fast.execute("i", q) == slow.execute("i", q), q
        assert fast.device_fallbacks == 0


class TestMeshBackendRecovery:
    def test_backend_failure_backs_off_then_recovers(self, holder,
                                                     monkeypatch):
        """A server started during a TPU outage serves host-side, then
        picks the device back up after the backoff window — no restart
        (round-2 pool outages motivated this)."""
        import numpy as np
        rng = np.random.default_rng(3)
        f = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        for col in rng.choice(8 * SLICE_WIDTH, size=64, replace=False):
            f.set_bit("standard", 1, int(col))
        ex = Executor(holder, host="local", use_mesh=True,
                      mesh_min_slices=1)
        from pilosa_tpu.parallel import mesh as mesh_mod
        orig_make = mesh_mod.make_mesh

        def broken(*a, **kw):
            raise RuntimeError("backend unavailable")

        monkeypatch.setattr(mesh_mod, "make_mesh", broken)
        q = "Count(Bitmap(frame=f, rowID=1))"
        assert ex.execute("i", q)[0] == 64  # host path, correct
        assert ex.device_fallbacks == 1
        assert ex._mesh is None
        # Within the backoff window: no re-probe (make_mesh would raise).
        assert ex.execute("i", q)[0] == 64
        assert ex.device_fallbacks == 1
        # Outage ends + backoff expires → device path resumes.
        monkeypatch.setattr(mesh_mod, "make_mesh", orig_make)
        ex._mesh_failed_until = 0.0
        assert ex.execute("i", q)[0] == 64
        assert ex._mesh is not None


class TestSparseUploadPath:
    """Cold device blocks may ship as bucketed sparse words + device
    densify (PILOSA_TPU_SPARSE_UPLOAD; round-4 cold-path work). Forced
    interpret mode must produce byte-identical results to the dense
    upload on both the Count-leaf and TopN-candidate builders."""

    def _fill(self, holder, slices=3):
        import numpy as np
        rng = np.random.default_rng(21)
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")
        for row in range(5):
            cols = rng.choice(slices * SLICE_WIDTH, size=150,
                              replace=False)
            for col in cols:
                f.set_bit("standard", row, int(col))

    def test_sparse_and_dense_uploads_agree(self, holder, monkeypatch):
        self._fill(holder)
        queries = [
            'Count(Intersect(Bitmap(rowID=0, frame=f),'
            ' Bitmap(rowID=1, frame=f)))',
            'TopN(frame=f, n=3)',
            'TopN(Bitmap(rowID=0, frame=f), frame=f, n=4)',
        ]
        host = Executor(holder, host="local", use_mesh=False)
        want = [host.execute("i", q) for q in queries]

        from pilosa_tpu.parallel.residency import device_cache
        monkeypatch.setenv("PILOSA_TPU_SPARSE_UPLOAD", "interpret")
        device_cache().clear()
        sparse_ex = Executor(holder, host="local", use_mesh=True,
                             mesh_min_slices=1)
        got_sparse = [sparse_ex.execute("i", q) for q in queries]

        monkeypatch.setenv("PILOSA_TPU_SPARSE_UPLOAD", "0")
        device_cache().clear()
        dense_ex = Executor(holder, host="local", use_mesh=True,
                            mesh_min_slices=1)
        got_dense = [dense_ex.execute("i", q) for q in queries]
        assert got_sparse == want
        assert got_dense == want

    def test_gate_rejects_dense_blocks(self):
        """A block with a dense row must take the dense path (the
        measured 0.5x sparse LOSS at G=128, benchmarks/DENSIFY.json)."""
        import numpy as np
        from pilosa_tpu.ops import packed
        dense_row = (np.arange(0, 32768, dtype=np.int32),
                     np.full(32768, 7, dtype=np.uint32))
        sparse_row = (np.array([5, 300], dtype=np.int32),
                      np.array([1, 2], dtype=np.uint32))
        use, plan = packed.sparse_gate([dense_row, sparse_row], 32768)
        assert not use and plan[0] > 32
        use2, plan2 = packed.sparse_gate([sparse_row, None], 32768)
        assert use2 and plan2[0] == 1


class TestVectorizedHostTopN:
    def test_matches_per_slice_path(self, holder, monkeypatch):
        """The rank-array host leg (one dict per local batch) must
        reproduce the per-slice map path exactly, for plain,
        thresholded, and ids forms."""
        import numpy as np
        rng = np.random.default_rng(31)
        idx = holder.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")
        for row in range(30):
            cols = rng.choice(6 * SLICE_WIDTH,
                              size=int(rng.integers(5, 120)),
                              replace=False)
            for col in cols:
                f.set_bit("standard", row, int(col))
        fast = Executor(holder, host="local", use_mesh=False)
        slow = Executor(holder, host="local", use_mesh=False)
        monkeypatch.setattr(slow, "_topn_local_host_fn",
                            lambda *a, **k: None)
        queries = [
            'TopN(frame=f, n=5)',
            'TopN(frame=f, n=31)',
            'TopN(frame=f)',
            'TopN(frame=f, n=6, threshold=40)',
            'TopN(frame=f, n=4, ids=[0,3,7,29])',
            'TopN(frame=f, ids=[1,2,99], threshold=10)',
        ]
        for q in queries:
            assert fast.execute("i", q) == slow.execute("i", q), q

    def test_ranked_cache_falls_back_to_fresh_counts(self, holder,
                                                     monkeypatch):
        """RankCache rankings are rate-limited; the ids-form fast path
        must defer to the per-slice cache.get path there (round-4
        review: stale ranked counts)."""
        import numpy as np
        idx = holder.create_index_if_not_exists("r")
        f = idx.create_frame_if_not_exists(
            "rf", FrameOptions(cache_type="ranked"))
        for col in range(5):
            f.set_bit("standard", 0, col)
        ex = Executor(holder, host="local", use_mesh=False)
        got = ex.execute("r", 'TopN(frame=rf, n=5, ids=[0])')
        assert [(p.id, p.count) for p in got[0]] == [(0, 5)]
        # mutate within the rank-limiter window; counts must be fresh
        for col in range(5, 9):
            f.set_bit("standard", 0, col)
        got = ex.execute("r", 'TopN(frame=rf, n=5, ids=[0])')
        assert [(p.id, p.count) for p in got[0]] == [(0, 9)]

    def test_ids_form_survives_empty_cache(self, holder):
        """A lost .cache sidecar (empty rank cache) must take the
        recount fallback, not IndexError (round-4 review)."""
        idx = holder.create_index_if_not_exists("e")
        f = idx.create_frame_if_not_exists("ef")
        for col in range(4):
            f.set_bit("standard", 2, col)
        frag = holder.fragment("e", "ef", "standard", 0)
        frag.cache._od.clear()           # simulate lost sidecar
        frag.cache._ranked = None
        ex = Executor(holder, host="local", use_mesh=False)
        got = ex.execute("e", 'TopN(frame=ef, n=5, ids=[2, 7])')
        assert [(p.id, p.count) for p in got[0]] == [(2, 4)]
