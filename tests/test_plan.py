"""Cost-based planner + observability plane (ISSUE 18).

The load-bearing property is the differential one: for ANY read query,
planned execution must be bit-for-bit identical to unplanned — the
planner may only reorder, skip proven-empty work, serve cached
subresults, and re-place subtrees, never change an answer. Randomized
PQL trees run both ways on the host path and on the virtual device
mesh, with writes interleaved between queries so the generation-token
subresult keys must invalidate (a stale hit would show up as a wrong
bit). The observability half is contract-tested: fingerprint
normalization stability, ?plan=1 / ?profile=1 wire shapes, the
/debug/plans store, and the slow-log planFingerprint cross-link."""

import io
import json
import os

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.plan import record as plan_record
from pilosa_tpu.plan.planner import Planner, SubresultCache
from pilosa_tpu.plan.record import (PlanNode, PlanRecord,
                                    fingerprint_calls, normalize_call)
from pilosa_tpu.plan.store import PlanStore
from pilosa_tpu.pql import parser as pql

N_ROWS = 8
N_SLICES = 3


def _norm(results):
    out = []
    for r in results:
        if hasattr(r, "bits"):
            out.append(list(r.bits()))
        elif isinstance(r, list):
            out.append([(p.id, p.count) for p in r])
        else:
            out.append(r)
    return out


def _rand_tree(rng, depth, n_rows=N_ROWS):
    if depth == 0 or rng.random() < 0.4:
        # +2 headroom: absent rows are exactly the short-circuit food.
        return f"Bitmap(rowID={int(rng.integers(n_rows + 2))}, frame=f)"
    op = rng.choice(["Intersect", "Union", "Difference"])
    k = int(rng.integers(2, 5))
    return (f"{op}("
            + ", ".join(_rand_tree(rng, depth - 1, n_rows)
                        for _ in range(k)) + ")")


def _rand_query(rng):
    tree = _rand_tree(rng, int(rng.integers(1, 4)))
    wrap = rng.random()
    if wrap < 0.5:
        return f"Count({tree})"
    if wrap < 0.7:
        return f"TopN({tree}, frame=f, n=4)"
    return tree


# -- fingerprint contract ------------------------------------------------------


class TestFingerprint:
    def test_literals_normalize_away(self):
        a = pql.parse("Count(Bitmap(rowID=1, frame=f))").calls
        b = pql.parse("Count(Bitmap(rowID=999, frame=f))").calls
        assert fingerprint_calls(a) == fingerprint_calls(b)

    def test_commutative_operand_order_normalizes_away(self):
        a = pql.parse("Intersect(Bitmap(rowID=1, frame=f),"
                      " Bitmap(rowID=2, frame=g))").calls
        b = pql.parse("Intersect(Bitmap(rowID=7, frame=g),"
                      " Bitmap(rowID=3, frame=f))").calls
        assert fingerprint_calls(a) == fingerprint_calls(b)

    def test_difference_order_is_semantic(self):
        a = pql.parse("Difference(Bitmap(rowID=1, frame=f),"
                      " Bitmap(rowID=2, frame=g))").calls
        b = pql.parse("Difference(Bitmap(rowID=1, frame=g),"
                      " Bitmap(rowID=2, frame=f))").calls
        assert fingerprint_calls(a) != fingerprint_calls(b)

    def test_frame_names_distinguish(self):
        a = pql.parse("Count(Bitmap(rowID=1, frame=f))").calls
        b = pql.parse("Count(Bitmap(rowID=1, frame=g))").calls
        assert fingerprint_calls(a) != fingerprint_calls(b)

    def test_shape_distinguishes(self):
        a = pql.parse("Count(Bitmap(rowID=1, frame=f))").calls
        b = pql.parse("Count(Intersect(Bitmap(rowID=1, frame=f),"
                      " Bitmap(rowID=2, frame=f)))").calls
        assert fingerprint_calls(a) != fingerprint_calls(b)

    def test_normalize_call_masks_numbers_keeps_names(self):
        c = pql.parse("TopN(Bitmap(rowID=5, frame=f), frame=f,"
                      " n=10)").calls[0]
        text = normalize_call(c)
        assert "5" not in text and "10" not in text
        assert "f" in text and "TopN" in text


# -- plan record / wire shape --------------------------------------------------


class TestPlanRecord:
    def test_wire_json_roundtrips_and_stitches(self):
        rec = PlanRecord("abc123def456", node="n1")
        root = PlanNode("Count")
        root.est_rows = 10
        root.children.append(PlanNode("Bitmap", "f/1"))
        rec.roots.append(root)
        rec.note("reordered")
        leg = PlanRecord("abc123def456", node="n2")
        leg.roots.append(PlanNode("Count"))
        rec.add_remote_json(leg.wire_json())
        tree = rec.to_tree()
        assert tree["fingerprint"] == "abc123def456"
        assert tree["calls"][0]["op"] == "Count"
        assert tree["calls"][0]["children"][0]["detail"] == "f/1"
        assert tree["decisions"] == {"reordered": 1}
        assert tree["legs"][0]["node"] == "n2"
        # wire form parses back
        assert json.loads(rec.wire_json())["fingerprint"] == \
            "abc123def456"

    def test_wire_json_respects_budget(self):
        rec = PlanRecord("ff", node="n1")
        for i in range(40):
            n = PlanNode("Count", "x" * 200)
            rec.roots.append(n)
        payload = rec.wire_json(max_bytes=2000)
        assert len(payload) <= 2000
        assert json.loads(payload)["fingerprint"] == "ff"

    def test_remote_json_garbage_ignored(self):
        rec = PlanRecord("ff")
        rec.add_remote_json("{not json")
        rec.add_remote_json("[1,2]")
        assert rec.to_tree().get("legs") is None


class TestSubresultCache:
    def test_lru_entry_bound(self):
        c = SubresultCache(max_entries=4, max_bits=1 << 30)
        for i in range(8):
            c.put(("k", i), object(), 1)
        assert c.stats()["entries"] == 4
        assert c.get(("k", 0)) is None
        assert c.get(("k", 7)) is not None

    def test_bit_budget_bound(self):
        c = SubresultCache(max_entries=100, max_bits=10)
        c.put(("a",), object(), 6)
        c.put(("b",), object(), 6)  # 12 bits > 10: "a" evicts
        assert c.get(("a",)) is None
        assert c.get(("b",)) is not None

    def test_clear(self):
        c = SubresultCache()
        c.put(("a",), object(), 1)
        c.clear()
        assert c.stats() == {"entries": 0, "bits": 0}


class TestPlanStore:
    def test_aggregates_per_fingerprint(self):
        s = PlanStore()
        for i in range(5):
            s.record("fp1", {"op": "Count"}, 0.01 * (i + 1),
                     pql="Count(...)", est_rows=100, actual_rows=120)
        s.record("fp2", {"op": "TopN"}, 0.5)
        snap = s.snapshot()
        assert snap["fingerprints"] == 2
        top = snap["plans"][0]
        assert top["fingerprint"] == "fp1" and top["count"] == 5
        assert top["p50Ms"] > 0 and top["p99Ms"] >= top["p50Ms"]
        assert top["examplePql"] == "Count(...)"
        assert top["lastPlan"] == {"op": "Count"}
        assert abs(top["estActualDrift"]["median"] - 121 / 101) < 1e-3

    def test_fingerprint_lru_bound(self):
        s = PlanStore(max_fingerprints=3)
        for i in range(6):
            s.record(f"fp{i}", {}, 0.01)
        assert s.snapshot()["fingerprints"] == 3


# -- planner decisions ---------------------------------------------------------


@pytest.fixture
def planned_holder(tmp_path):
    holder = Holder(str(tmp_path / "data"))
    holder.open()
    idx = holder.create_index("p")
    f = idx.create_frame("f")
    rng = np.random.default_rng(7)
    # Skewed rows: row 0 huge, row counts decay; rows >= N_ROWS empty.
    for row in range(N_ROWS):
        k = max(4, 4000 >> row)
        cols = rng.choice(N_SLICES * SLICE_WIDTH, size=k,
                          replace=False)
        f.import_bits(np.full(k, row, dtype=np.uint64),
                      cols.astype(np.uint64))
    yield holder
    holder.close()


class TestPlannerDecisions:
    def test_reorders_intersect_smallest_first(self, planned_holder):
        ex = Executor(planned_holder, host="local", use_mesh=False)
        tree = ex.explain(
            "p", "Count(Intersect(Bitmap(rowID=0, frame=f),"
                 " Bitmap(rowID=5, frame=f)))")
        node = tree["calls"][0]["children"][0]
        assert "reordered" in node.get("decisions", [])
        ests = [c["estRows"] for c in node["children"]]
        assert ests == sorted(ests)

    def test_short_circuits_empty_intersect(self, planned_holder):
        ex = Executor(planned_holder, host="local", use_mesh=False)
        tree = ex.explain(
            "p", f"Count(Intersect(Bitmap(rowID=0, frame=f),"
                 f" Bitmap(rowID={N_ROWS + 1}, frame=f)))")
        root = tree["calls"][0]
        assert root["estRows"] == 0 and root["exact"]
        assert "short_circuit" in root["decisions"]

    def test_estimates_are_exact_on_local_slices(self, planned_holder):
        ex = Executor(planned_holder, host="local", use_mesh=False)
        tree = ex.explain("p", "Bitmap(rowID=3, frame=f)")
        leaf = tree["calls"][0]
        want = ex.execute("p", "Count(Bitmap(rowID=3, frame=f))")[0]
        assert leaf["estRows"] == want and leaf["exact"]

    def test_explain_does_not_execute(self, planned_holder):
        ex = Executor(planned_holder, host="local", use_mesh=False)
        tree = ex.explain("p", "Count(Bitmap(rowID=0, frame=f))")
        assert tree["calls"][0]["op"] == "Count"
        assert "actualS" not in tree["calls"][0]
        with pytest.raises(Exception):
            ex.explain("p", "SetBit(frame=f, rowID=1, columnID=2)")

    def test_subresult_cache_hits_across_queries(self, planned_holder):
        ex = Executor(planned_holder, host="local", use_mesh=False)
        q = ("Count(Union(Bitmap(rowID=1, frame=f),"
             " Bitmap(rowID=2, frame=f)))")
        want = ex.execute("p", q)[0]
        before = ex.planner.subresults.stats()["entries"]
        for _ in range(3):
            ex._bitmap_results.clear()  # force past whole-result cache
            assert ex.execute("p", q)[0] == want
        assert ex.planner.subresults.stats()["entries"] > before

    def test_disabled_planner_attaches_nothing(self, planned_holder):
        ex = Executor(planned_holder, host="local", use_mesh=False)
        ex.planner_enabled = False
        from pilosa_tpu.executor import ExecOptions
        from pilosa_tpu.sched.context import QueryContext
        ctx = QueryContext(pql="x", index="p")
        ex.execute("p", "Count(Bitmap(rowID=0, frame=f))",
                   opt=ExecOptions(ctx=ctx))
        assert ctx.plan is None


# -- plan memo: reuse, validity sweep, sampling --------------------------------


class TestPlanMemo:
    def test_hit_reuses_finished_plan(self, planned_holder):
        ex = Executor(planned_holder, host="local", use_mesh=False)
        q = "Count(Bitmap(rowID=1, frame=f))"
        want = ex.execute("p", q)[0]
        assert len(ex.planner._plans) == 1
        ent = next(iter(ex.planner._plans.values()))
        assert ent["hits"] == 0
        for _ in range(3):
            ex._bitmap_results.clear()
            assert ex.execute("p", q)[0] == want
        assert len(ex.planner._plans) == 1
        assert ent["hits"] == 3

    def test_write_invalidates_memoized_plan(self, planned_holder):
        ex = Executor(planned_holder, host="local", use_mesh=False)
        q = "Count(Bitmap(rowID=1, frame=f))"
        before = ex.execute("p", q)[0]
        ex._bitmap_results.clear()
        ex.execute("p", q)  # memoized now
        free_col = N_SLICES * SLICE_WIDTH - 1
        ex.execute("p", f"SetBit(frame=f, rowID=1, columnID={free_col})")
        ex._bitmap_results.clear()
        assert ex.execute("p", q)[0] == before + 1

    def test_view_appearing_voids_short_circuit_proof(self,
                                                      planned_holder):
        # An empty frame's missing standard view is an exact-0 proof;
        # the first write creates the view and MUST void the memoized
        # short-circuit, or the cached plan would keep answering 0.
        planned_holder.index("p").create_frame("g")
        ex = Executor(planned_holder, host="local", use_mesh=False)
        bits = list(ex.execute("p", "Bitmap(rowID=0, frame=f)")[0].bits())
        col = bits[0]
        q = (f"Count(Intersect(Bitmap(rowID=0, frame=f),"
             f" Bitmap(rowID=0, frame=g)))")
        for _ in range(2):  # second run serves from the memo
            ex._bitmap_results.clear()
            assert ex.execute("p", q)[0] == 0
        ex.execute("p", f"SetBit(frame=g, rowID=0, columnID={col})")
        ex._bitmap_results.clear()
        assert ex.execute("p", q)[0] == 1

    def test_memo_is_lru_bounded(self, planned_holder):
        from pilosa_tpu.plan.planner import _PLAN_MEMO_ENTRIES
        ex = Executor(planned_holder, host="local", use_mesh=False)
        for i in range(_PLAN_MEMO_ENTRIES + 20):
            ex.execute("p", f"Count(Bitmap(rowID={i}, frame=f))")
        assert len(ex.planner._plans) <= _PLAN_MEMO_ENTRIES

    def test_fresh_plans_sample_and_hits_sample_1_in_16(self,
                                                        planned_holder):
        from pilosa_tpu.executor import ExecOptions
        ex = Executor(planned_holder, host="local", use_mesh=False)
        query = pql.parse("Count(Bitmap(rowID=2, frame=f))")
        slices = list(range(N_SLICES))
        _, rec = ex._maybe_plan("p", query, slices, ExecOptions())
        assert rec.sample  # fresh plan: full fidelity
        samples = []
        for _ in range(16):
            _, rec = ex._maybe_plan("p", query, slices, ExecOptions())
            samples.append(rec.sample)
        assert samples.count(True) == 1 and samples[-1]


# -- randomized differential: planned == unplanned (host) ----------------------


class TestPlannedVsUnplannedDifferential:
    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_random_trees_with_writes_between(self, tmp_path, seed):
        """The acceptance leg: random PQL trees, planned and unplanned
        executors over the SAME holder, bit-for-bit equality — with
        writes interleaved so every cached subresult's generation
        token must invalidate (a stale hit diverges the executors)."""
        rng = np.random.default_rng(seed)
        holder = Holder(str(tmp_path / "d"))
        holder.open()
        try:
            idx = holder.create_index("q")
            f = idx.create_frame("f")
            n_cols = N_SLICES * SLICE_WIDTH
            for row in range(N_ROWS):
                k = max(2, 2000 >> row)
                cols = rng.choice(n_cols, size=k, replace=False)
                f.import_bits(np.full(k, row, dtype=np.uint64),
                              cols.astype(np.uint64))
            planned = Executor(holder, host="local", use_mesh=False)
            unplanned = Executor(holder, host="local", use_mesh=False)
            unplanned.planner_enabled = False
            for step in range(60):
                if rng.random() < 0.3:
                    # Write between queries: the token-keyed
                    # invalidation leg. Writes go through the PLANNED
                    # executor (they bypass planning by contract).
                    r = int(rng.integers(N_ROWS))
                    c = int(rng.integers(n_cols))
                    verb = ("SetBit" if rng.random() < 0.7
                            else "ClearBit")
                    planned.execute(
                        "q", f"{verb}(frame=f, rowID={r},"
                             f" columnID={c})")
                    continue
                q = _rand_query(rng)
                got = _norm(planned.execute("q", q))
                want = _norm(unplanned.execute("q", q))
                assert got == want, (seed, step, q)
            # The run must actually have exercised the machinery.
            totals = planned.planner.decision_totals
            assert totals.get("planned", 0) > 0
        finally:
            holder.close()

    def test_repeated_query_after_write_is_fresh(self, tmp_path):
        """Directed token-invalidation check: prime the subresult
        cache hard (same interior subtree many times), then write one
        bit inside it — the next answer must include the new bit."""
        holder = Holder(str(tmp_path / "d"))
        holder.open()
        try:
            idx = holder.create_index("q")
            f = idx.create_frame("f")
            f.import_bits(np.zeros(50, dtype=np.uint64),
                          np.arange(50, dtype=np.uint64))
            f.import_bits(np.ones(50, dtype=np.uint64),
                          np.arange(25, 75, dtype=np.uint64))
            ex = Executor(holder, host="local", use_mesh=False)
            q = ("Count(Union(Bitmap(rowID=0, frame=f),"
                 " Bitmap(rowID=1, frame=f)))")
            for _ in range(4):
                ex._bitmap_results.clear()
                assert ex.execute("q", q)[0] == 75
            ex.execute("q", "SetBit(frame=f, rowID=0, columnID=1000)")
            ex._bitmap_results.clear()
            assert ex.execute("q", q)[0] == 76
        finally:
            holder.close()


# -- randomized differential: device leg ---------------------------------------


class TestPlannedDeviceDifferential:
    @pytest.mark.parametrize("seed", [21, 22])
    def test_planned_device_matches_unplanned_host(self, tmp_path,
                                                   seed):
        """Planned execution on the virtual device mesh vs unplanned
        host execution: the placement hints and short-circuits must
        compose with the device lowering without changing a bit."""
        rng = np.random.default_rng(seed)
        holder = Holder(str(tmp_path / "d"))
        holder.open()
        try:
            idx = holder.create_index("q")
            f = idx.create_frame("f")
            n_cols = N_SLICES * SLICE_WIDTH
            for row in range(N_ROWS):
                k = max(8, 3000 >> row)
                cols = rng.choice(n_cols, size=k, replace=False)
                f.import_bits(np.full(k, row, dtype=np.uint64),
                              cols.astype(np.uint64))
            device = Executor(holder, host="local", use_mesh=True,
                              mesh_min_slices=1)
            host = Executor(holder, host="local", use_mesh=False)
            host.planner_enabled = False
            for step in range(15):
                q = f"Count({_rand_tree(rng, 2)})"
                got = device.execute("q", q)
                want = host.execute("q", q)
                assert got == want, (seed, step, q)
            device.close()
            host.close()
        finally:
            holder.close()


# -- the serving surface -------------------------------------------------------


def _call(app, method, path, body=b""):
    if "?" in path:
        path, _, qs = path.partition("?")
    else:
        qs = ""
    environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
               "QUERY_STRING": qs,
               "CONTENT_LENGTH": str(len(body)),
               "wsgi.input": io.BytesIO(body)}
    out = {}

    def start_response(status, headers):
        out["status"] = int(status.split()[0])
        out["headers"] = dict(headers)

    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


@pytest.fixture
def served(planned_holder):
    from pilosa_tpu.sched import QueryRegistry
    from pilosa_tpu.server.handler import Handler
    ex = Executor(planned_holder, host="local", use_mesh=False)
    registry = QueryRegistry(slow_threshold_s=1e-9)
    h = Handler(planned_holder, ex, host="local", registry=registry)
    yield h, ex, registry


class TestServingSurface:
    def test_plan_flag_returns_explain_only(self, served):
        h, ex, _reg = served
        st, _hd, body = _call(
            h, "POST", "/index/p/query?plan=1",
            b"Count(Bitmap(rowID=0, frame=f))")
        assert st == 200
        doc = json.loads(body)
        assert doc["results"] == []
        assert doc["plan"]["calls"][0]["op"] == "Count"
        assert "actualS" not in doc["plan"]["calls"][0]
        # EXPLAIN of a write is a 400, and nothing executed either way.
        st, _hd, body = _call(
            h, "POST", "/index/p/query?plan=1",
            b"SetBit(frame=f, rowID=0, columnID=99999999)")
        assert st == 400

    def test_profile_embeds_analyzed_plan(self, served):
        h, _ex, _reg = served
        st, _hd, body = _call(
            h, "POST", "/index/p/query?profile=1",
            b"Count(Intersect(Bitmap(rowID=0, frame=f),"
            b" Bitmap(rowID=1, frame=f)))")
        assert st == 200
        doc = json.loads(body)
        plan = doc["plan"]
        assert plan["fingerprint"]
        root = plan["calls"][0]
        assert root["op"] == "Count"
        assert "actualS" in root        # ANALYZE: wall time recorded
        assert root["actualRows"] == doc["results"][0]

    def test_debug_plans_aggregates(self, served):
        h, _ex, _reg = served
        for row in (0, 1, 2):   # same shape, different literal
            _call(h, "POST", "/index/p/query",
                  f"Count(Bitmap(rowID={row}, frame=f))".encode())
        st, _hd, body = _call(h, "GET", "/debug/plans")
        assert st == 200
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert doc["fingerprints"] >= 1
        top = doc["plans"][0]
        assert top["count"] >= 3     # three literals, ONE fingerprint
        assert top["lastPlan"]["calls"][0]["op"] == "Count"
        assert doc["planner"]["decisions"].get("planned", 0) >= 3

    def test_slow_log_cross_links_fingerprint(self, served):
        h, _ex, reg = served
        _call(h, "POST", "/index/p/query",
              b"Count(Bitmap(rowID=0, frame=f))")
        slow = reg.slow_queries()
        assert slow, "threshold 1e-9 must catch every query"
        entry = slow[-1]
        assert entry["planFingerprint"]
        st, _hd, body = _call(h, "GET", "/debug/plans")
        fps = [p["fingerprint"]
               for p in json.loads(body)["plans"]]
        assert entry["planFingerprint"] in fps

    def test_planner_off_still_serves(self, served):
        h, ex, _reg = served
        ex.planner_enabled = False
        st, _hd, body = _call(h, "POST", "/index/p/query",
                              b"Count(Bitmap(rowID=0, frame=f))")
        assert st == 200
        doc = json.loads(body)
        assert isinstance(doc["results"][0], int)
        st, _hd, body = _call(h, "POST", "/index/p/query?profile=1",
                              b"Count(Bitmap(rowID=0, frame=f))")
        assert "plan" not in json.loads(body)

    def test_plan_disabled_globally(self, served):
        h, _ex, _reg = served
        plan_record.set_enabled(False)
        try:
            st, _hd, body = _call(h, "POST", "/index/p/query",
                                  b"Count(Bitmap(rowID=0, frame=f))")
            assert st == 200
        finally:
            plan_record.set_enabled(True)


# -- real 2-node cluster: stitched plans + differential ------------------------


def test_two_node_cluster_plans_stitch_and_match_model(tmp_path):
    """Spawn a REAL 2-node gossip cluster with replicas=1 so slices
    split across nodes and every fan-out query has a genuine remote
    leg. Asserts (a) planned answers stay model-exact over the wire,
    including after writes (cluster-wide token invalidation), and
    (b) ?profile=1 returns ONE plan tree with the remote node's leg
    stitched in via the X-Pilosa-Plan header."""
    import signal
    import subprocess
    import sys as _sys
    import urllib.request

    _here = os.path.dirname(os.path.abspath(__file__))
    _sys.path.insert(0, _here)
    from podenv import cpu_env, free_port, wait_up

    def post(host, path, body):
        req = urllib.request.Request(f"http://{host}{path}",
                                     data=body, method="POST")
        return urllib.request.urlopen(req, timeout=30).read()

    def query(host, body, extra=""):
        return json.loads(post(host, f"/index/cp/query{extra}",
                               body.encode()))

    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs, logs = [], []

    def spawn(name, port, internal, seed=""):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env["PILOSA_TPU_WARMUP"] = "0"
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        argv = [_sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--cluster.type", "gossip",
                "--cluster.hosts", hosts,
                "--cluster.replicas", "1",
                "--cluster.internal-port", str(internal),
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_here))
        procs.append(p)
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    try:
        host_a = spawn("a", pa, ga)
        host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
        post(host_a, "/index/cp", b"{}")
        post(host_a, "/index/cp/frame/f", b"{}")

        rng = np.random.default_rng(42)
        bits: dict[int, set[int]] = {}
        n_rows, n_cols = 10, 3 * SLICE_WIDTH

        # Seed every slice so ownership splits matter from query one.
        from pilosa_tpu.cluster.client import Client
        client = Client(host_a)
        k = 1500
        rows = rng.integers(0, n_rows, k).astype(np.uint64)
        cols = rng.integers(0, n_cols, k).astype(np.uint64)
        client.import_arrays("cp", "f", rows, cols)
        for r, c in zip(rows.tolist(), cols.tolist()):
            bits.setdefault(r, set()).add(c)

        # The CreateSlice broadcast is async: wait until BOTH nodes
        # know the cluster-wide max slice, or queries routed through
        # the node that did not take the import see a partial range.
        import time as _time
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            ms = [json.loads(urllib.request.urlopen(
                      f"http://{n}/slices/max", timeout=30).read())
                  ["maxSlices"].get("cp") for n in (host_a, host_b)]
            if ms == [2, 2]:
                break
            _time.sleep(0.2)
        else:
            raise AssertionError(f"max-slice never converged: {ms}")

        def check(node, q, want):
            assert query(node, q)["results"][0] == want, q

        for step in range(30):
            node = (host_a, host_b)[int(rng.integers(0, 2))]
            kind = int(rng.integers(0, 4))
            if kind == 0:  # write between queries: invalidation leg
                r = int(rng.integers(0, n_rows))
                c = int(rng.integers(0, n_cols))
                query(node, f"SetBit(frame=f, rowID={r},"
                            f" columnID={c})")
                bits.setdefault(r, set()).add(c)
            elif kind == 1:
                a, b = rng.integers(0, n_rows, 2).tolist()
                check(node,
                      f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
                      f" Bitmap(rowID={b}, frame=f)))",
                      len(bits.get(a, set()) & bits.get(b, set())))
            elif kind == 2:
                ids = rng.integers(0, n_rows, 3).tolist()
                want = len(set().union(
                    *(bits.get(r, set()) for r in ids)))
                check(node, "Count(Union(" + ", ".join(
                    f"Bitmap(rowID={r}, frame=f)"
                    for r in ids) + "))", want)
            else:  # empty-row short-circuit still exact over the wire
                a = int(rng.integers(0, n_rows))
                check(node,
                      f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
                      f" Bitmap(rowID={n_rows + 3}, frame=f)))", 0)

        # The observability acceptance check: one profiled query,
        # one plan tree, remote leg(s) stitched under "legs".
        doc = query(host_a,
                    "Count(Union(Bitmap(rowID=0, frame=f),"
                    " Bitmap(rowID=1, frame=f)))", "?profile=1")
        want = len(bits.get(0, set()) | bits.get(1, set()))
        assert doc["results"][0] == want
        plan = doc.get("plan")
        assert plan is not None and plan["fingerprint"]
        assert plan["calls"][0]["op"] == "Count"
        legs = plan.get("legs") or []
        assert legs, "replicas=1 over 3 slices must produce a remote leg"
        assert all(leg["fingerprint"] == plan["fingerprint"]
                   for leg in legs)
        assert any(leg.get("calls") for leg in legs)

        # Both nodes' /debug/plans carry the fingerprint store.
        for node in (host_a, host_b):
            with urllib.request.urlopen(
                    f"http://{node}/debug/plans", timeout=30) as resp:
                dbg = json.loads(resp.read())
            assert dbg["enabled"] is True
            assert dbg["fingerprints"] >= 1
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGINT)
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
