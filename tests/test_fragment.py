"""Fragment tests — temp-file-backed wrapper with Reopen(), mirroring the
reference's test strategy (fragment_test.go:628-735): persistence across
close/open, snapshot behavior, TopN semantics, block checksums, MergeBlock
consensus, import."""

import os

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.storage.bitmap import Bitmap
from pilosa_tpu.storage.cache import Pair
from pilosa_tpu.storage.fragment import (Fragment, PairSet, TopOptions,
                                         HASH_BLOCK_SIZE, MAX_OP_N)


class AttrStoreStub:
    """In-memory row attr store (fragment_test.go:700-735)."""

    def __init__(self):
        self._m = {}

    def set_attrs(self, id, attrs):
        self._m[id] = attrs

    def attrs(self, id):
        return self._m.get(id)


@pytest.fixture
def frag(tmp_path):
    f = make_fragment(tmp_path)
    yield f
    f.close()


def make_fragment(tmp_path, slice=0, cache_type="ranked", name="frag"):
    f = Fragment(str(tmp_path / name), "i", "f", "standard", slice,
                 cache_type=cache_type, row_attr_store=AttrStoreStub())
    f.open()
    return f


def reopen(f):
    path, slice = f.path, f.slice
    f.close()
    f2 = Fragment(path, f.index, f.frame, f.view, slice,
                  cache_type=f.cache_type, row_attr_store=f.row_attr_store)
    f2.open()
    return f2


class TestSetClear:
    def test_set_bit_and_row(self, frag):
        assert frag.set_bit(120, 1)
        assert frag.set_bit(120, 6)
        assert frag.set_bit(121, 0)
        assert not frag.set_bit(120, 1)  # idempotent
        assert list(map(int, frag.row(120).bits())) == [1, 6]
        assert frag.row(120).count() == 2
        assert frag.row_count(121) == 1

    def test_clear_bit(self, frag):
        frag.set_bit(1000, 1)
        frag.set_bit(1000, 2)
        assert frag.clear_bit(1000, 1)
        assert not frag.clear_bit(1000, 1)
        assert list(map(int, frag.row(1000).bits())) == [2]

    def test_column_bounds(self, tmp_path):
        f = make_fragment(tmp_path, slice=2)
        try:
            with pytest.raises(ValueError):
                f.set_bit(0, 0)  # slice 2 owns cols [2*2^20, 3*2^20)
            base = 2 * SLICE_WIDTH
            assert f.set_bit(0, base + 5)
            assert list(map(int, f.row(0).bits())) == [base + 5]
        finally:
            f.close()

    def test_persistence_across_reopen(self, tmp_path):
        f = make_fragment(tmp_path)
        f.set_bit(5, 10)
        f.set_bit(5, 20)
        f.clear_bit(5, 10)
        f = reopen(f)
        try:
            assert list(map(int, f.row(5).bits())) == [20]
        finally:
            f.close()

    def test_snapshot_after_max_opn(self, tmp_path):
        f = make_fragment(tmp_path)
        try:
            for i in range(MAX_OP_N + 2):
                f.set_bit(i % 3, i % SLICE_WIDTH)
            # op-log must fold into a snapshot (async since round 4:
            # wait for the background worker before asserting)
            f._join_snapshot()
            assert f.storage.op_n <= MAX_OP_N
            size_after = os.path.getsize(f.path)
            f2 = reopen(f)
            f = f2
            assert f.row_count(0) > 0
            assert os.path.getsize(f.path) == size_after
        finally:
            f.close()


class TestCrashRecovery:
    def test_torn_wal_tail_is_trimmed(self, tmp_path):
        f = make_fragment(tmp_path)
        for i in range(10):
            f.set_bit(i, i)
        f.close()
        size = os.path.getsize(f.path)
        with open(f.path, "ab") as fh:
            fh.write(b"\x01\x02\x03")  # partial op record from a crash
        f = reopen(f)
        try:
            assert f.storage.count() == 10
            assert os.path.getsize(f.path) == size  # tail trimmed
            assert f.set_bit(99, 99)  # still writable
        finally:
            f.close()

    def test_double_open_blocked_by_flock(self, tmp_path):
        f = make_fragment(tmp_path)
        try:
            g = Fragment(f.path, "i", "f", "standard", 0)
            with pytest.raises(BlockingIOError):
                g.open()
        finally:
            f.close()


class TestTopN:
    def fill(self, f, rows):
        # rows: {row_id: n_bits}
        for rid, n in rows.items():
            cols = np.arange(n, dtype=np.uint64)
            f.import_bits(np.full(n, rid, dtype=np.uint64), cols)

    def test_top_basic(self, frag):
        self.fill(frag, {1: 10, 2: 30, 3: 20})
        pairs = frag.top(TopOptions(n=2))
        assert pairs == [Pair(2, 30), Pair(3, 20)]

    def test_top_all(self, frag):
        self.fill(frag, {1: 10, 2: 30, 3: 20})
        pairs = frag.top()
        assert pairs == [Pair(2, 30), Pair(3, 20), Pair(1, 10)]

    def test_top_with_src(self, frag):
        self.fill(frag, {0: 100, 1: 50, 2: 10})
        # src covers columns 0..24 → intersections: row0=25, row1=25, row2=10
        src = Bitmap(*range(25))
        pairs = frag.top(TopOptions(n=3, src=src))
        assert {p.id: p.count for p in pairs} == {0: 25, 1: 25, 2: 10}

    def test_top_row_ids(self, frag):
        self.fill(frag, {1: 10, 2: 30, 3: 20})
        pairs = frag.top(TopOptions(row_ids=[1, 3]))
        assert pairs == [Pair(3, 20), Pair(1, 10)]

    def test_top_min_threshold(self, frag):
        self.fill(frag, {1: 10, 2: 30, 3: 20})
        pairs = frag.top(TopOptions(n=5, min_threshold=15))
        assert pairs == [Pair(2, 30), Pair(3, 20)]

    def test_top_attr_filter(self, frag):
        self.fill(frag, {1: 10, 2: 30, 3: 20})
        frag.row_attr_store.set_attrs(1, {"x": "foo"})
        frag.row_attr_store.set_attrs(2, {"x": "bar"})
        pairs = frag.top(TopOptions(n=5, filter_field="x",
                                    filter_values=["foo"]))
        assert pairs == [Pair(1, 10)]

    def test_top_tanimoto(self, frag):
        # reference fragment_test.go TopN Tanimoto case
        self.fill(frag, {100: 10, 101: 6, 102: 4})
        src = Bitmap(*range(6))
        pairs = frag.top(TopOptions(tanimoto_threshold=50, src=src))
        got = {p.id: p.count for p in pairs}
        # row100: count=6, tan=ceil(600/(10+6-6))=60 > 50 ✓
        # row101: cnt=6 passes min/max window, count=6, tan=ceil(600/6)=100 ✓
        # row102: cnt=4 <= min_tan(3)? min_tan = 6*50/100 = 3 → 4 > 3 ok;
        #          count=4, tan=ceil(400/(4+6-4))=67 > 50 ✓
        assert got == {100: 6, 101: 6, 102: 4}

    def test_topn_intersect_large(self, tmp_path):
        """fragment_test.go:233-272 verbatim: rows 0..999 where row i
        holds bits 0..i-1, src = {980..999}; the top-10 by intersection
        must be rows 999..990 with counts 19..10 — exercises threshold
        pruning where rank-cache counts and src counts diverge."""
        frag = make_fragment(tmp_path, name="toplarge")
        try:
            rows = np.repeat(np.arange(1000, dtype=np.uint64),
                             np.arange(1000))
            cols = np.concatenate([np.arange(i, dtype=np.uint64)
                                   for i in range(1000)])
            frag.import_bits(rows, cols)
            src = Bitmap(*range(980, 1000))
            pairs = frag.top(TopOptions(n=10, src=src))
            assert [(p.id, p.count) for p in pairs] == \
                [(999 - k, 19 - k) for k in range(10)]
        finally:
            frag.close()

    def test_topn_cache_size_bounds_result(self, tmp_path):
        """fragment_test.go:295-358: a ranked cache of size 3 caps the
        candidate set — TopN(5) returns exactly the 3 cached rows."""
        frag = make_fragment(tmp_path, name="topsize")
        frag.cache_size = 3
        from pilosa_tpu.storage import cache as cache_mod
        frag.cache = cache_mod.RankCache(3)
        try:
            self.fill(frag, {100: 3, 101: 4, 102: 5, 103: 6, 104: 7})
            frag.set_bit(105, 10)
            frag.set_bit(105, 11)
            frag.recalculate_cache()
            pairs = frag.top(TopOptions(n=5))
            assert len(pairs) <= 3
            assert [(p.id, p.count) for p in pairs] == \
                [(104, 7), (103, 6), (102, 5)]
        finally:
            frag.close()

    def test_src_topn_paths_match_bruteforce(self, tmp_path):
        """Randomized parity for TopN with a source bitmap: the
        vectorized count-map path must reproduce a brute-force
        (count desc, id asc) model at several candidate-set sizes."""
        rng = np.random.default_rng(17)
        for trial, n_rows in enumerate((8, 40, 300, 3000)):
            frag = make_fragment(tmp_path, name=f"srctop{trial}")
            try:
                rows = rng.integers(0, n_rows, 2000).astype(np.uint64)
                cols = rng.integers(0, 5000, 2000).astype(np.uint64)
                frag.import_bits(rows, cols)
                src_cols = np.unique(
                    rng.integers(0, 5000, 400)).astype(np.uint64)
                src = Bitmap()
                from pilosa_tpu.storage import roaring
                src.add_segment(roaring.Bitmap.from_sorted(src_cols), 0,
                                writable=True)

                model = {}
                bits = {}
                for r, c in zip(rows.tolist(), cols.tolist()):
                    bits.setdefault(r, set()).add(c)
                srcset = set(src_cols.tolist())
                for r, s in bits.items():
                    cnt = len(s & srcset)
                    if cnt > 0:
                        model[r] = cnt
                want = sorted(model.items(),
                              key=lambda kv: (-kv[1], kv[0]))[:10]

                got = frag.top(TopOptions(n=10, src=src))
                assert [(p.id, p.count) for p in got[:10]] == want, \
                    (trial, got[:10], want)
            finally:
                frag.close()

    def test_fold_rows_matches_sequential_set_ops(self, tmp_path):
        """fold_rows (one-pass vectorized or/and/andnot over many rows)
        must match per-row Python set folds, including duplicate row
        ids and rows with no bits."""
        rng = np.random.default_rng(5)
        frag = make_fragment(tmp_path, name="fold")
        rows = rng.integers(0, 30, 4000).astype(np.uint64)
        cols = rng.integers(0, 3000, 4000).astype(np.uint64)
        frag.import_bits(rows, cols)
        bits: dict[int, set[int]] = {}
        for r, c in zip(rows.tolist(), cols.tolist()):
            bits.setdefault(r, set()).add(c)
        for trial in range(20):
            k = rng.integers(2, 12)
            ids = [int(x) for x in rng.integers(0, 35, k)]  # incl. empty
            sets = [bits.get(r, set()) for r in ids]
            want_or = set().union(*sets)
            want_and = set(sets[0])
            for s in sets[1:]:
                want_and &= s
            want_andnot = set(sets[0])
            for s in sets[1:]:
                want_andnot -= s
            for op, want in (("or", want_or), ("and", want_and),
                             ("andnot", want_andnot)):
                got = frag.fold_rows(op, ids)
                assert sorted(int(x) for x in got) == sorted(want), \
                    (trial, op, ids)
        frag.close()

    def test_src_count_map_matches_per_row_intersections(self, tmp_path):
        # The one-pass vectorized count map must agree with per-row
        # roaring intersection counts (the reference's per-row walk).
        rng = np.random.default_rng(0)
        rows = rng.integers(0, 64, 20000).astype(np.uint64)
        cols = rng.integers(0, SLICE_WIDTH, 20000).astype(np.uint64)
        src = Bitmap(*np.unique(rng.integers(0, SLICE_WIDTH, 5000)).tolist())
        f1 = make_fragment(tmp_path, name="dev")
        f1.import_bits(rows, cols)
        ids, counts = f1._host_src_count_map(src)
        lookup = dict(zip(ids.tolist(), counts.tolist()))
        for rid in range(64):
            want = src.intersection_count(f1.row(rid))
            assert lookup.get(rid, 0) == want, (rid, want)
        f1.close()

    def test_src_count_map_handles_huge_row_ids(self, tmp_path):
        # A bit at a huge row id must not allocate a row-id-sized
        # count array (the map is (ids, counts), not a bincount).
        frag = make_fragment(tmp_path, name="hugerow")
        big = 10**12
        frag.set_bit(big, 5)
        frag.set_bit(3, 5)
        frag.recalculate_cache()  # skip the 10 s rank re-sort limiter
        src = Bitmap(5)
        got = frag.top(TopOptions(n=10, src=src))
        assert [(p.id, p.count) for p in got] == [(3, 1), (big, 1)]
        frag.close()


class TestNoCopyClose:
    def test_escaped_results_survive_snapshot_and_reopen(self, tmp_path):
        """Close/snapshot drop the old mapping WITHOUT copying container
        data out; escaped query results must stay valid (their views
        pin the map), and the flock must release so the same path
        reopens immediately even while those results are alive."""
        frag = make_fragment(tmp_path, name="nocopy")
        cols = list(range(0, 3000, 3))
        frag.import_bits([7] * len(cols), cols)
        row_before = frag.row(7)          # zero-copy views of map #1
        bits_before = row_before.bits().copy()

        frag.set_bit(7, 1)                # mutate + snapshot new file
        frag.snapshot()
        # Old result still reads map-#1 data, unchanged.
        assert np.array_equal(row_before.bits(), bits_before)

        frag2 = reopen(frag)              # flock must not be held
        try:
            got = sorted(int(b) for b in frag2.row(7).bits())
            assert got == sorted(cols + [1])
            # The pre-snapshot escaped result STILL reads its snapshot.
            assert np.array_equal(row_before.bits(), bits_before)
        finally:
            frag2.close()


class TestImport:
    def test_import_and_counts(self, frag):
        rows = np.array([0, 0, 1, 1, 1], dtype=np.uint64)
        cols = np.array([1, 2, 1, 5, 9], dtype=np.uint64)
        frag.import_bits(rows, cols)
        assert frag.row_count(0) == 2
        assert frag.row_count(1) == 3
        # WAL-first import contract: the bits ride the op-log (one
        # group-committed blob, durable before import_bits returned)
        # instead of forcing a synchronous snapshot; the MAX_OP_N
        # cadence snapshots in the background. The blob counts toward
        # op_n at 1/16th per position (fragment._BLOB_OP_WEIGHT — blob
        # replay is the vectorized lane), so 5 positions weigh 1.
        assert frag.storage.op_n == 1
        assert frag._wal is None or frag._wal.pending_bytes() == 0

    def test_import_out_of_bounds(self, frag):
        with pytest.raises(ValueError):
            frag.import_bits([0], [SLICE_WIDTH])  # belongs to slice 1


class TestBlocks:
    def test_blocks_and_invalidation(self, frag):
        frag.set_bit(0, 0)
        frag.set_bit(HASH_BLOCK_SIZE, 0)      # second block
        blocks = frag.blocks()
        assert [b[0] for b in blocks] == [0, 1]
        chk0 = blocks[0][1]
        frag.set_bit(1, 5)                     # mutate block 0
        blocks2 = frag.blocks()
        assert blocks2[0][1] != chk0
        assert blocks2[1][1] == blocks[1][1]   # block 1 untouched

    def test_checksum_equality_means_same_data(self, tmp_path):
        a = make_fragment(tmp_path, name="a")
        b = make_fragment(tmp_path, name="b")
        try:
            for f in (a, b):
                f.set_bit(3, 100)
                f.set_bit(204, 500)
            assert a.checksum() == b.checksum()
            b.set_bit(5, 5)
            assert a.checksum() != b.checksum()
        finally:
            a.close()
            b.close()

    def test_block_data_roundtrip(self, frag):
        frag.set_bit(1, 10)
        frag.set_bit(99, 20)
        frag.set_bit(100, 30)  # next block
        ps = frag.block_data(0)
        assert list(map(int, ps.row_ids)) == [1, 99]
        assert list(map(int, ps.column_ids)) == [10, 20]


class TestMergeBlock:
    def test_majority_consensus(self, frag):
        # local has {r0c0, r0c1}; peer1 has {r0c0}; peer2 has {r0c0, r0c2}
        frag.set_bit(0, 0)
        frag.set_bit(0, 1)
        u = lambda *v: np.array(v, dtype=np.uint64)
        peer1 = PairSet(u(0), u(0))
        peer2 = PairSet(u(0, 0), u(0, 2))
        sets, clears = frag.merge_block(0, [peer1, peer2])
        # consensus (majority of 3 ≥ 2): c0 (3 votes) set, c1 (1) clear,
        # c2 (1) clear
        assert list(map(int, frag.row(0).bits())) == [0]
        # peer1 needs no sets, no clears beyond what it has
        assert len(sets[0].row_ids) == 0 and len(clears[0].row_ids) == 0
        # peer2 must clear c2
        assert list(map(int, clears[1].column_ids)) == [2]

    def test_even_split_sets(self, frag):
        # 2 copies, 1 vote each → majority = (2+1)//2 = 1 → bit stays set
        frag.set_bit(0, 7)
        peer = PairSet(np.array([], dtype=np.uint64),
                       np.array([], dtype=np.uint64))
        sets, clears = frag.merge_block(0, [peer])
        assert frag.row(0).count() == 1          # local keeps the bit
        assert list(map(int, sets[0].column_ids)) == [7]  # peer must set it

    def test_bulk_divergence_repairs_fast_and_correct(self, tmp_path):
        """A 10k-bit divergence must bulk-apply (one snapshot, no per-bit
        WAL loop) and repair in about a second — the anti-entropy crawl
        guard. Two replicated peers agree against a diverged local."""
        import time
        frag = make_fragment(tmp_path)
        try:
            rng = np.random.default_rng(7)
            rows = rng.integers(0, HASH_BLOCK_SIZE, 5000).astype(np.uint64)
            cols = rng.integers(0, 200000, 5000).astype(np.uint64)
            # Local-only bits: majority (2 peers without them vs local)
            # says clear all 5000.
            frag.import_bits(rows, cols)
            # Peer-only bits: majority says set all of these locally.
            peer_rows = rng.integers(0, HASH_BLOCK_SIZE,
                                     5000).astype(np.uint64)
            peer_cols = (rng.integers(0, 200000, 5000).astype(np.uint64)
                         + np.uint64(300000))
            peer = PairSet(peer_rows, peer_cols)
            peer2 = PairSet(peer_rows.copy(), peer_cols.copy())

            start = time.perf_counter()
            sets, clears = frag.merge_block(0, [peer, peer2])
            elapsed = time.perf_counter() - start

            want = {(int(r), int(c)) for r, c in zip(peer_rows, peer_cols)}
            got = {(r, c) for r, c in frag.for_each_bit()}
            assert got == want
            # Peers need the sets/clears that bring them to consensus:
            # nothing to set (they have all consensus bits), and they
            # must clear nothing (local-only bits lost the vote and
            # peers never had them).
            for ps in sets + clears:
                assert len(ps.column_ids) == 0
            assert elapsed < 1.5, f"bulk merge took {elapsed:.2f}s"
            # Survives a reopen (the bulk path snapshotted).
            frag = reopen(frag)
            assert {(r, c) for r, c in frag.for_each_bit()} == want
        finally:
            frag.close()


class TestCachePersistence:
    def test_cache_flush_and_reload(self, tmp_path):
        f = make_fragment(tmp_path, cache_type="ranked")
        for rid, n in {1: 5, 2: 9}.items():
            for c in range(n):
                f.set_bit(rid, c)
        f.flush_cache()
        assert os.path.exists(f.cache_path)
        f = reopen(f)
        try:
            assert f.top(TopOptions(n=2)) == [Pair(2, 9), Pair(1, 5)]
        finally:
            f.close()

    def test_for_each_bit(self, tmp_path):
        f = make_fragment(tmp_path, slice=1)
        try:
            base = SLICE_WIDTH
            f.set_bit(0, base + 1)
            f.set_bit(2, base + 3)
            assert list(f.for_each_bit()) == [(0, base + 1), (2, base + 3)]
        finally:
            f.close()


class TestReviewRegressions:
    def test_duplicate_peer_pairs_get_one_vote(self, frag):
        # peer repeating a pair on the wire must not double-vote
        u = lambda *v: np.array(v, dtype=np.uint64)
        peerA = PairSet(u(0, 0), u(5, 5))   # same pair twice
        peerB = PairSet(u(), u())
        sets, clears = frag.merge_block(0, [peerA, peerB])
        # 1 real vote of 3 → cleared everywhere
        assert frag.row(0).count() == 0
        assert list(map(int, clears[0].column_ids)) == [5]

    def test_corrupt_cache_sidecar_ignored(self, tmp_path):
        f = make_fragment(tmp_path)
        f.set_bit(1, 2)
        f.close()
        with open(f.path + ".cache", "wb") as fh:
            fh.write(b"\xff\xfe garbage")
        f = reopen(f)
        try:
            assert f.row_count(1) == 1  # opens fine, cache rebuilt lazily
        finally:
            f.close()


class TestPackedRowCache:
    def test_pack_row_caches_and_invalidates(self, tmp_path):
        import numpy as np
        from pilosa_tpu.ops.packed import WORDS_PER_SLICE
        from pilosa_tpu.storage.fragment import Fragment
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            f.set_bit(1, 5)
            f.set_bit(1, 65)
            out = np.zeros(WORDS_PER_SLICE, dtype=np.uint32)
            f.pack_row(1, out)
            assert out[0] == 1 << 5 and out[2] == 1 << 1
            # second pack comes from the host cache (same contents)
            out2 = np.zeros(WORDS_PER_SLICE, dtype=np.uint32)
            f.pack_row(1, out2)
            assert (out == out2).all()
            assert 1 in f.device._host_rows
            # a write invalidates the cached packed row
            f.set_bit(1, 6)
            assert 1 not in f.device._host_rows
            f.pack_row(1, out)
            assert out[0] == (1 << 5) | (1 << 6)
        finally:
            f.close()


class TestSrcCountPartials:
    def test_multi_partial_merge_matches_single_pass(self, tmp_path,
                                                     monkeypatch):
        """A broad src folds matched positions into bounded partial
        (ids, counts) maps (ADVICE r3: peak memory must scale with
        distinct rows, not matched bits); shrinking the fold budget
        must not change the result."""
        import numpy as np
        from pilosa_tpu.storage import fragment as fragment_mod
        from pilosa_tpu.storage.bitmap import Bitmap as QB
        from pilosa_tpu.storage.fragment import Fragment
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            rng = np.random.default_rng(7)
            rows = rng.integers(0, 40, 8000)
            cols = rng.integers(0, 60000, 8000)
            for r, c in zip(rows, cols):
                f.set_bit(int(r), int(c))
            src = QB(*range(0, 60000, 2))
            want_ids, want_counts = f._host_src_count_map(src)
            # force many partial folds and bust the per-src memo
            monkeypatch.setattr(fragment_mod, "_SRC_FOLD_POSITIONS", 64)
            f._src_counts.clear()
            got_ids, got_counts = f._host_src_count_map(src)
            assert (want_ids == got_ids).all()
            assert (np.asarray(want_counts).astype(np.int64)
                    == np.asarray(got_counts).astype(np.int64)).all()
        finally:
            f.close()


class TestFastSnapshotAndIncrementalCounts:
    def test_many_snapshots_swap_and_remap_durable(self, tmp_path,
                                                   monkeypatch):
        """Drive enough snapshots through the fast fd-swap path to cross
        the _REMAP_EVERY full-reopen boundary, interleaving set/clear;
        row counts (incremental +-1 bookkeeping) must match recounts at
        every step and the file must replay identically on reopen."""
        import numpy as np
        from pilosa_tpu.storage import fragment as fragment_mod
        from pilosa_tpu.storage.fragment import Fragment
        monkeypatch.setattr(fragment_mod, "MAX_OP_N", 20)
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        rng = np.random.default_rng(3)
        try:
            live = set()
            for step in range(900):
                r = int(rng.integers(0, 7))
                c = int(rng.integers(0, 3000))
                if live and step % 5 == 4:
                    r, c = next(iter(live))
                    f.clear_bit(r, c)
                    live.discard((r, c))
                else:
                    f.set_bit(r, c)
                    live.add((r, c))
            f._join_snapshot()  # snapshots are async since round 4
            assert f._snapshot_n > 0  # workers coalesce: >=1 ran
            # incremental counts == ground truth per row
            for row in range(7):
                want = sum(1 for (r, _) in live if r == row)
                assert f.row_count(row) == want, row
                assert f.cache.get(row) == want, row
        finally:
            f.close()
        # reopen: snapshot + WAL replay reproduce the same state
        g = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        g.open()
        try:
            assert g.storage.count() == len(live)
            for row in range(7):
                assert g.row_count(row) == sum(
                    1 for (r, _) in live if r == row)
        finally:
            g.close()

    def test_snapshot_swap_releases_old_lock(self, tmp_path, monkeypatch):
        """After a fast-path snapshot the old fd's flock must be gone:
        closing the fragment then reopening the path must not raise
        (a leaked lock would EWOULDBLOCK the flock in open())."""
        from pilosa_tpu.storage import fragment as fragment_mod
        from pilosa_tpu.storage.fragment import Fragment
        monkeypatch.setattr(fragment_mod, "MAX_OP_N", 5)
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        for i in range(40):
            f.set_bit(1, i)
        f._join_snapshot()  # async since round 4
        assert f._snapshot_n >= 1
        f.close()
        g = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        g.open()  # would raise BlockingIOError if a lock leaked
        assert g.row_count(1) == 40
        g.close()


class TestAsyncSnapshot:
    def test_writes_during_serialization_splice_into_tail(self, tmp_path,
                                                          monkeypatch):
        """Ops appended WHILE the background worker serializes must
        land in the new file via the WAL-tail splice; a reopen replays
        them identically."""
        import threading as th

        import numpy as np
        from pilosa_tpu.storage import fragment as fragment_mod
        from pilosa_tpu.storage import roaring as roaring_mod
        from pilosa_tpu.storage.fragment import Fragment

        gate = th.Event()
        entered = th.Event()
        orig = roaring_mod.write_frozen

        def slow_write(live, w, footer=False):
            entered.set()
            gate.wait(10)  # hold serialization open
            return orig(live, w, footer=footer)

        monkeypatch.setattr(roaring_mod, "write_frozen", slow_write)
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            for i in range(300):
                f.set_bit(1, i)
            f.snapshot(sync=False)
            assert entered.wait(10)
            # these land only in the OLD file's WAL during the worker
            for i in range(300, 420):
                f.set_bit(2, i - 300)
            gate.set()
            f._join_snapshot()
            assert f.row_count(1) == 300 and f.row_count(2) == 120
        finally:
            f.close()
        g = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        g.open()
        try:
            assert g.row_count(1) == 300
            assert g.row_count(2) == 120  # spliced tail replayed
        finally:
            g.close()

    def test_remap_cycle_reached_with_sequential_snapshots(self, tmp_path,
                                                           monkeypatch):
        """Crossing _REMAP_EVERY sequential async snapshots exercises
        the full close/reopen branch and stays durable."""
        from pilosa_tpu.storage import fragment as fragment_mod
        from pilosa_tpu.storage.fragment import Fragment
        monkeypatch.setattr(fragment_mod, "_REMAP_EVERY", 3)
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            for k in range(5):
                f.set_bit(1, 1000 + k)
                f.snapshot(sync=False)
                f._join_snapshot()
            assert f._snapshot_n >= 5
            assert f.row_count(1) == 5
        finally:
            f.close()
        g = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        g.open()
        try:
            assert g.row_count(1) == 5
        finally:
            g.close()

    def test_sync_snapshot_while_worker_inflight_no_deadlock(
            self, tmp_path, monkeypatch):
        """import_bits (sync snapshot) arriving while a background
        worker is serializing must wait it out and complete — the
        round-4 review deadlock: joining the worker while holding the
        fragment lock the worker itself needs."""
        import threading as th

        import numpy as np
        from pilosa_tpu.storage import roaring as roaring_mod
        from pilosa_tpu.storage.fragment import Fragment

        gate = th.Event()
        entered = th.Event()
        orig = roaring_mod.write_frozen

        def slow_write(live, w, footer=False):
            entered.set()
            gate.wait(10)
            return orig(live, w, footer=footer)

        monkeypatch.setattr(roaring_mod, "write_frozen", slow_write)
        # Pin the import to the vintage detach-then-SYNC-snapshot lane
        # (the WAL-first lane never takes _snap_mu, so it would finish
        # while the worker is still serializing — by design).
        import pilosa_tpu.storage.fragment as fragmod
        monkeypatch.setattr(fragmod, "_WAL_IMPORT_MAX_BYTES", -1)
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            for i in range(50):
                f.set_bit(1, i)
            f.snapshot(sync=False)
            assert entered.wait(10)
            done = th.Event()

            def importer():
                f.import_bits(np.array([5] * 30, np.uint64),
                              np.arange(30, dtype=np.uint64))
                done.set()

            t = th.Thread(target=importer, daemon=True)
            t.start()
            # the import must be blocked behind the worker, not done
            assert not done.wait(0.5)
            gate.set()
            assert done.wait(20), "import deadlocked behind the worker"
            assert f.row_count(5) == 30 and f.row_count(1) == 50
        finally:
            f.close()


class TestTopSrcVectorizedParity:
    """Randomized parity between Fragment._top_src_vectorized and a
    verbatim port of the heap-walk it replaces (round-5 src-TopN fast
    path): visit-order semantics, the phase-A threshold, the
    break-on-cache-count, and the cross-slice fill SUPERSET must all
    match bit for bit."""

    @staticmethod
    def _loop_reference(cand_ids, cand_counts, scnt_map, n,
                        min_threshold):
        import heapq
        results, out = [], []
        for i, (rid, cnt) in enumerate(zip(cand_ids.tolist(),
                                           cand_counts.tolist())):
            if cnt <= 0:
                continue
            if cnt < min_threshold:
                continue
            if len(results) < n:
                count = int(scnt_map[i])
                if count == 0:
                    continue
                if count < min_threshold:
                    continue
                heapq.heappush(results, (count, -rid))
                continue
            threshold = results[0][0]
            if threshold < min_threshold or cnt < threshold:
                break
            count = int(scnt_map[i])
            if count < threshold:
                continue
            heapq.heappush(results, (count, -rid))
        while results:
            cnt, neg_id = heapq.heappop(results)
            out.append((-neg_id, cnt))
        out.reverse()
        return out

    def test_randomized_parity(self):
        rng = np.random.default_rng(321)
        for trial in range(2000):
            n_cand = int(rng.integers(1, 60))
            cand_counts = np.sort(
                rng.integers(0, 50, n_cand))[::-1].astype(np.int64)
            cand_ids = rng.permutation(1000)[:n_cand].astype(np.int64)
            # src counts <= cache counts (|row ∩ src| <= |row|),
            # including zeros (candidates absent from the src)
            scnt = np.array(
                [rng.integers(0, c + 1) for c in cand_counts],
                dtype=np.int64)
            n = int(rng.integers(1, 12))
            min_th = int(rng.integers(0, 6))
            want = self._loop_reference(cand_ids, cand_counts, scnt,
                                        n, min_th)
            got = Fragment._top_src_vectorized(cand_ids, cand_counts,
                                               scnt, n, min_th)
            assert [(p.id, p.count) for p in got] == want, trial
