"""Tail sampling on a REAL 2-node gossip cluster — the acceptance
path of the always-on observability PR: a deadline-exceeded query
(with tracing OFF — tail sampling is the default) yields a kept,
stitched, disk-persisted trace with keep reason ``deadline``,
retrievable via ``/debug/traces?source=disk`` after the coordinator is
SIGKILLed and restarted."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402


def _post(host, path, body=b"", timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=timeout)


def _get_json(host, path, timeout=10):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture
def cluster(tmp_path):
    """Two gossip-joined nodes, 4 slices of data, tracing NOT enabled
    — the tail sampler (default-on) is what must catch the incident.
    The coordinator's spawn closure is yielded so the test can SIGKILL
    and resurrect it on the same data dir."""
    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs, logs = {}, []

    def spawn(name, port, internal, seed=""):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env["PILOSA_TPU_WARMUP"] = "0"
        # Force real fan-out every time (the hot-query and result-
        # residency caches would serve repeats without remote legs to
        # stitch — the convergence loop primes both).
        env["PILOSA_QUERY_CLUSTER_CACHE_ENTRIES"] = "0"
        env["PILOSA_QUERY_RESULT_CACHE_ENTRIES"] = "0"
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--cluster.type", "gossip",
                "--cluster.hosts", hosts,
                "--cluster.replicas", "1",
                "--cluster.internal-port", str(internal),
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        procs[name] = p
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    host_a = spawn("a", pa, ga)
    host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
    _post(host_a, "/index/tl", b"{}")
    _post(host_a, "/index/tl/frame/f", b"{}")

    import numpy as np

    from pilosa_tpu.cluster.client import Client
    client = Client(host_a)
    cols = np.arange(0, 4 * SLICE_WIDTH,
                     SLICE_WIDTH // 8).astype(np.uint64)
    client.import_arrays("tl", "f", np.ones(len(cols), np.uint64),
                         cols)

    deadline = time.time() + 30
    got = None
    while time.time() < deadline:
        with _post(host_a, "/index/tl/query",
                   b'Count(Bitmap(frame="f", rowID=1))') as r:
            got = json.loads(r.read())["results"][0]
        if got == len(cols):
            break
        time.sleep(0.3)
    assert got == len(cols), got

    yield {"a": host_a, "b": host_b, "procs": procs,
           "respawn_a": lambda: spawn("a", pa, ga,
                                      seed=f"127.0.0.1:{gb}"),
           "n_bits": len(cols)}

    for p in procs.values():
        try:
            p.send_signal(signal.SIGINT)
        except OSError:
            pass
    for p in procs.values():
        try:
            p.wait(timeout=20)
        except subprocess.TimeoutExpired:
            p.kill()
    for log in logs:
        log.close()


def test_deadline_exceeded_query_persists_stitched_trace_across_restart(
        cluster):
    host_a, host_b = cluster["a"], cluster["b"]

    # Slow every fan-out RPC leg by 350 ms (rpc.recv delay — the
    # response arrives, the delay burns budget, then the spans
    # stitch), and give a two-call query a 600 ms budget: call 1
    # completes and stitches the remote leg's spans (~355 ms), call 2
    # is still mid-RPC when the fan-out loop's deadline poll fires
    # past 600 ms → QueryDeadlineError → 504. The delay must exceed
    # the executor's 250 ms poll tick so a check lands while the leg
    # is pending (a leg that completes between checks still answers).
    with _post(host_a, "/debug/failpoints",
               json.dumps({"site": "rpc.recv",
                           "spec": "delay(350ms)"}).encode()):
        pass
    qid = None
    try:
        q = (b'Count(Bitmap(frame="f", rowID=1))'
             b'Count(Bitmap(frame="f", rowID=1))')
        try:
            with _post(host_a,
                       "/index/tl/query?timeout=600ms", q) as r:
                qid = r.headers["X-Pilosa-Query-Id"]
                status = r.status
        except urllib.error.HTTPError as e:
            qid = e.headers["X-Pilosa-Query-Id"]
            status = e.code
            e.read()
        assert status == 504, status
        assert qid
    finally:
        with _post(host_a, "/debug/failpoints",
                   json.dumps({"site": "rpc.recv",
                               "spec": "off"}).encode()):
            pass

    # Kept in the ring with the deadline reason, remote spans stitched.
    listing = _get_json(host_a, "/debug/traces?reason=deadline")
    entry = next(t for t in listing["traces"] if t["id"] == qid)
    assert entry["reason"] == "deadline"
    assert host_b in entry["nodes"], entry

    # Persisted to disk with the same shape.
    disk = _get_json(host_a,
                     "/debug/traces?source=disk&reason=deadline")
    assert any(t["id"] == qid for t in disk["traces"]), disk

    # SIGKILL the coordinator (no orderly close — the disk ring's
    # crash-safety is part of the contract) and resurrect it.
    proc_a = cluster["procs"]["a"]
    proc_a.kill()
    proc_a.wait(timeout=20)
    host_a = cluster["respawn_a"]()

    # The in-memory ring is gone; the disk ring survived the restart.
    disk = _get_json(host_a,
                     "/debug/traces?source=disk&reason=deadline")
    entry = next(t for t in disk["traces"] if t["id"] == qid)
    assert entry["reason"] == "deadline"
    assert host_b in entry["nodes"], entry

    # The full trace is still addressable by id (disk fallback) and
    # exports as perfetto-loadable Chrome JSON with BOTH nodes.
    chrome = _get_json(host_a, f"/debug/traces/{qid}?source=disk")
    assert chrome["otherData"]["traceId"] == qid
    pid_names = {e["args"]["name"] for e in chrome["traceEvents"]
                 if e["name"] == "process_name"}
    assert {host_a, host_b} <= pid_names, pid_names

    # The restarted node records fresh disk writes under the new
    # family — the persisted-trace counter survives as a contract.
    with urllib.request.urlopen(f"http://{host_a}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    assert "pilosa_trace_disk_records_total" in text


def test_build_info_served_and_status_block(cluster):
    host_a = cluster["a"]
    with urllib.request.urlopen(f"http://{host_a}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("pilosa_build_info{"))
    assert 'version="' in line and 'python="' in line \
        and 'jax="' in line and 'backend="' in line
    assert line.rstrip().endswith(" 1")
    status = _get_json(host_a, "/status")
    build = status["build"]
    assert build["version"] and build["python"]
    assert build["jax"] not in ("", "unloaded")
