"""1 B-column scale smoke: 1024 slices (1024 × 2^20 = 2^30 columns)
through the mesh programs and the executor, asserting the chunk guards
actually execute and results stay exact (VERDICT r1 item 9 — so the
first real pod run is not the first time the chunking runs at scale).

The real constants trigger for TopN at this size: a 1024-slice
candidate block is 128 MB per row, so TOPN_BLOCK_BYTES (256 MB) forces
row-chunking at 2 rows per call. The 2^15 slice bound needs 4 GB+ of
leaves to trigger naturally; the seam logic is exercised by shrinking
the bound (monkeypatch) over the same data and requiring identical
results.
"""

import numpy as np
import pytest

from pilosa_tpu.ops.packed import WORDS_PER_SLICE
from pilosa_tpu.parallel import mesh as mesh_mod

N_SLICES = 1024  # × 2^20 columns per slice = 2^30 columns


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh(8)


@pytest.fixture(scope="module")
def leaves():
    rng = np.random.default_rng(30)
    # Sparse-ish leaves: dense random words in 1/8 of the slices, zero
    # elsewhere — 256 MB total, popcount reference stays cheap.
    out = np.zeros((2, N_SLICES, WORDS_PER_SLICE), dtype=np.uint32)
    idx = rng.choice(N_SLICES, size=N_SLICES // 8, replace=False)
    out[:, idx] = rng.integers(0, 2**32,
                               size=(2, len(idx), WORDS_PER_SLICE),
                               dtype=np.uint32)
    return out


def test_count_expr_1b_columns(mesh, leaves):
    expr = ("and", ("leaf", 0), ("leaf", 1))
    want = int(np.bitwise_count(leaves[0] & leaves[1]).sum())
    assert mesh_mod.count_expr(mesh, expr, leaves) == want


def test_count_expr_chunk_seams_exact(mesh, leaves, monkeypatch):
    """Force the slice-chunk loop to run many times (the 2^15 bound
    needs 4 GB to trigger naturally) — seams must not change the sum."""
    expr = ("or", ("leaf", 0), ("leaf", 1))
    want = int(np.bitwise_count(leaves[0] | leaves[1]).sum())
    monkeypatch.setattr(mesh_mod, "slice_chunk_bound", lambda n: 100)
    assert mesh_mod.count_expr(mesh, expr, leaves) == want


def test_topn_exact_1b_columns_row_chunk_triggers(mesh, leaves):
    """1024-slice candidate blocks exceed TOPN_BLOCK_BYTES per 2 rows —
    the REAL row-chunk guard must fire, and counts must stay exact."""
    rng = np.random.default_rng(31)
    n_rows = 5  # 5 × 128 MB per-row block → 3 chunks of ≤2 rows
    rows = np.zeros((N_SLICES, n_rows, WORDS_PER_SLICE), dtype=np.uint32)
    idx = rng.choice(N_SLICES, size=64, replace=False)
    rows[idx] = rng.integers(0, 2**32,
                             size=(len(idx), n_rows, WORDS_PER_SLICE),
                             dtype=np.uint32)

    row_chunk = max(1, mesh_mod.TOPN_BLOCK_BYTES
                    // (N_SLICES * WORDS_PER_SLICE * 4))
    assert row_chunk == 2  # the guard is live at this scale

    calls = []
    orig = mesh_mod.topn_exact_fn

    def spy(mesh_, expr_):
        fn = orig(mesh_, expr_)

        def wrapped(*a):
            calls.append(1)
            return fn(*a)
        return wrapped

    expr = ("leaf", 0)
    src = leaves[:1]
    want = np.bitwise_count(
        rows & leaves[0][:, None, :]).sum(axis=(0, 2)).tolist()
    import unittest.mock as mock
    with mock.patch.object(mesh_mod, "topn_exact_fn", spy):
        got = mesh_mod.topn_exact(mesh, expr, rows, src)
    assert got == want
    assert len(calls) == -(-n_rows // row_chunk)  # 3 chunked programs


def test_executor_1b_column_index(tmp_path):
    """A real 1024-slice index served through the executor: Count and
    the streamed TopN exact phase (resident path exceeds its block
    budget at this scale and must hand off to the chunked stream)."""
    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    holder = Holder(str(tmp_path))
    holder.open()
    try:
        frame = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        rng = np.random.default_rng(32)
        # 3 bits per slice per row, deterministic counts.
        for row in (1, 2, 3):
            cols = (rng.integers(0, SLICE_WIDTH, size=N_SLICES)
                    + np.arange(N_SLICES, dtype=np.uint64) * SLICE_WIDTH)
            frame.import_bits([row] * N_SLICES, cols)
        ex = Executor(holder, host="local", mesh_min_slices=1)
        got = ex.execute("i", "Count(Bitmap(frame=f, rowID=1))")[0]
        assert got == N_SLICES
        # TopN exact phase across all 1024 slices: 3 candidates × 1024
        # slices = 384 MB block > the 256 MB resident budget, so the
        # executor must hand off to the chunked streaming path — and
        # counts must stay exact against the host path.
        q = "TopN(Bitmap(frame=f, rowID=1), frame=f, ids=[1, 2, 3])"
        res = ex.execute("i", q)
        assert ex.device_fallbacks == 0
        got = {p.id: p.count for p in res[0]}
        slow = Executor(holder, host="local", use_mesh=False)
        sres = slow.execute("i", q)
        assert got == {p.id: p.count for p in sres[0]}
        assert got[1] == N_SLICES  # row ∩ itself = every slice's bit
        # Plain TopN (both phases: 1024 rank-cache walks + the exact
        # re-query across every slice) — BASELINE config 5's shape at
        # the full 1 B-column axis.
        res = ex.execute("i", "TopN(frame=f, n=2)")[0]
        assert [(p.id, p.count) for p in res] == \
            [(1, N_SLICES), (2, N_SLICES)]
    finally:
        holder.close()
