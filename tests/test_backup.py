"""Disaster-recovery tests (ISSUE 20): archive object pool, WAL
segments, the journaled coordinator, retention/GC, the WAL archiver,
and the full backup → destroy-every-data-dir → restore → verified
point-in-time legs.

The e2e class is the acceptance test: a 2-node cluster is backed up
while serving, post-backup writes travel via the WAL archive, every
data dir is destroyed, and a DIFFERENT-size (1-node) cluster restored
from the archive serves digest-identical answers (the PR-19 replay
contract); ``--to-timestamp`` provably excludes the post-cut write.
"""

import io
import json
import os
import shutil
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.backup import archive as archive_mod
from pilosa_tpu.backup import coordinator as coord_mod
from pilosa_tpu.backup import restore as restore_mod
from pilosa_tpu.backup import retention as retention_mod
from pilosa_tpu.backup import verify as verify_mod
from pilosa_tpu.backup.walarchive import WalArchiver
from pilosa_tpu.cli.commands import main as cli_main
from pilosa_tpu.cluster.client import Client
from pilosa_tpu.cluster.topology import Node
from pilosa_tpu.fault import failpoints
from pilosa_tpu.obs import replay as replay_mod
from pilosa_tpu.server.server import Server
from pilosa_tpu.storage import integrity as integrity_mod
from pilosa_tpu.storage import roaring
from pilosa_tpu.tier import blob as blob_mod
from pilosa_tpu.utils.config import BackupConfig

pytestmark = pytest.mark.backup


def _footered(b: "roaring.Bitmap") -> bytes:
    buf = io.BytesIO()
    b.write_to(buf, footer=True)
    return buf.getvalue()


def _bitmap(values) -> "roaring.Bitmap":
    b = roaring.Bitmap()
    b.add_many(np.asarray(sorted(values), dtype=np.uint64))
    return b


def _store(tmp_path, name="archive"):
    return blob_mod.LocalDirBlobStore(str(tmp_path / name))


def _fake_backup(store, bid, kind, t, parent=None, rows=(1,),
                 wal_start=None, index="i", frame="f", slice=0):
    """A committed backup manifest whose single fragment really lives
    in the store's object pool — enough for retention/CLI tests."""
    body = _footered(_bitmap(rows))
    prefix = archive_mod.fragment_prefix(index, frame, "standard",
                                         slice)
    fm, digest, _pushed, _nbytes = archive_mod.push_fragment_bytes(
        store, prefix, body)
    manifest = {
        "version": archive_mod.MANIFEST_VERSION, "id": bid,
        "kind": kind, "parent": parent, "t": t, "coordinator": "n0",
        "epoch": 0, "hosts": ["n0"], "schema": [],
        "maxSlices": {index: slice},
        "walStart": dict(wal_start or {}),
        "fragments": [{"index": index, "frame": frame,
                       "view": "standard", "slice": slice,
                       "prefix": prefix, "bodyDigest": digest,
                       "manifest": fm}],
    }
    archive_mod.write_backup_manifest(store, manifest)
    return manifest


# -- archive object pool ------------------------------------------------------


class TestArchiveObjects:
    def test_fragment_roundtrip(self, tmp_path):
        store = _store(tmp_path)
        body = _footered(_bitmap(range(0, 5000, 3)))
        prefix = archive_mod.fragment_prefix("i", "f", "standard", 0)
        fm, digest, pushed, nbytes = archive_mod.push_fragment_bytes(
            store, prefix, body)
        assert pushed == 2 + int(fm["blockN"])
        assert nbytes == len(body)
        back = archive_mod.fetch_fragment_bytes(store, prefix, fm,
                                                digest)
        assert bytes(back) == body

    def test_push_skips_pool_resident_objects(self, tmp_path):
        store = _store(tmp_path)
        body = _footered(_bitmap(range(0, 5000, 3)))
        prefix = archive_mod.fragment_prefix("i", "f", "standard", 0)
        archive_mod.push_fragment_bytes(store, prefix, body)
        _fm, _d, pushed, nbytes = archive_mod.push_fragment_bytes(
            store, prefix, body)
        assert pushed == 0 and nbytes == 0

    def test_incremental_ships_only_changed_blocks(self, tmp_path):
        store = _store(tmp_path)
        vals = set(range(0, 200000, 7))
        prefix = archive_mod.fragment_prefix("i", "f", "standard", 0)
        fm1, _d, full_pushed, _n = archive_mod.push_fragment_bytes(
            store, prefix, _footered(_bitmap(vals)))
        assert int(fm1["blockN"]) > 1, "need a multi-block body"
        vals.add(3)  # dirty one block
        _fm2, _d2, delta_pushed, _n2 = \
            archive_mod.push_fragment_bytes(store, prefix,
                                            _footered(_bitmap(vals)))
        assert 0 < delta_pushed < full_pushed

    def test_tail_objects_are_content_distinct(self, tmp_path):
        """Regression: a footer ends with its own crc32, and
        crc32(data || crc32(data)) is the constant CRC residue — a
        crc-named tail aliased EVERY fragment's footer to one pool
        object, so a shared pool served stale footers."""
        store = _store(tmp_path)
        prefix = archive_mod.fragment_prefix("i", "f", "standard", 0)
        fm1, d1, _p, _n = archive_mod.push_fragment_bytes(
            store, prefix, _footered(_bitmap([1, 2])))
        fm2, d2, _p2, _n2 = archive_mod.push_fragment_bytes(
            store, prefix, _footered(_bitmap([3, 4])))
        assert fm1["tail"] != fm2["tail"]
        for fm, d in ((fm1, d1), (fm2, d2)):
            archive_mod.fetch_fragment_bytes(store, prefix, fm, d)

    def test_digest_mismatch_rejected(self, tmp_path):
        store = _store(tmp_path)
        body = _footered(_bitmap([1, 2, 3]))
        prefix = archive_mod.fragment_prefix("i", "f", "standard", 0)
        fm, _digest, _p, _n = archive_mod.push_fragment_bytes(
            store, prefix, body)
        with pytest.raises(integrity_mod.CorruptionError):
            archive_mod.fetch_fragment_bytes(store, prefix, fm,
                                             "0" * 32)

    def test_corrupt_stored_object_detected(self, tmp_path):
        store = _store(tmp_path)
        body = _footered(_bitmap(range(0, 5000, 3)))
        prefix = archive_mod.fragment_prefix("i", "f", "standard", 0)
        fm, digest, _p, _n = archive_mod.push_fragment_bytes(
            store, prefix, body)
        key = sorted(store.list(prefix + "/"))[0]
        path = store._path(key)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(path, "wb") as f:
            f.write(raw)
        with pytest.raises(integrity_mod.CorruptionError):
            archive_mod.fetch_fragment_bytes(store, prefix, fm,
                                             digest)

    def test_torn_stored_object_detected(self, tmp_path):
        store = _store(tmp_path)
        body = _footered(_bitmap(range(0, 5000, 3)))
        prefix = archive_mod.fragment_prefix("i", "f", "standard", 0)
        fm, digest, _p, _n = archive_mod.push_fragment_bytes(
            store, prefix, body)
        key = sorted(store.list(prefix + "/"))[0]
        path = store._path(key)
        raw = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(raw[:max(1, len(raw) // 2)])
        with pytest.raises(integrity_mod.CorruptionError):
            archive_mod.fetch_fragment_bytes(store, prefix, fm,
                                             digest)

    def test_unfootered_body_never_enters_archive(self, tmp_path):
        store = _store(tmp_path)
        buf = io.BytesIO()
        _bitmap([1, 2]).write_to(buf, footer=False)
        with pytest.raises(integrity_mod.CorruptionError):
            archive_mod.push_fragment_bytes(
                store, archive_mod.fragment_prefix("i", "f",
                                                   "standard", 0),
                buf.getvalue())


# -- WAL segments -------------------------------------------------------------


class TestWalSegments:
    def test_roundtrip(self, tmp_path):
        store = _store(tmp_path)
        batches = [{"frag": "i/f/standard/0", "t": 12.5,
                    "ops": b"\x01" * 26},
                   {"frag": "i/f/standard/1", "t": 13.0,
                    "ops": b"\x02" * 13}]
        body = archive_mod.encode_wal_segment("127.0.0.1:1", 0,
                                              batches)
        key = archive_mod.wal_segment_key("127.0.0.1:1", 0, body)
        store.put(key, body)
        seg = archive_mod.read_wal_segment(store, key)
        assert seg["seq"] == 0
        assert [b["frag"] for b in seg["batches"]] == \
            ["i/f/standard/0", "i/f/standard/1"]
        assert seg["batches"][0]["ops"] == b"\x01" * 26

    def test_crc_tamper_detected(self, tmp_path):
        store = _store(tmp_path)
        body = archive_mod.encode_wal_segment(
            "n1", 3, [{"frag": "i/f/standard/0", "t": 1.0,
                       "ops": b"x" * 13}])
        key = archive_mod.wal_segment_key("n1", 3, body)
        store.put(key, body + b" ")
        with pytest.raises(integrity_mod.CorruptionError):
            archive_mod.read_wal_segment(store, key)

    def test_list_order_and_next_seq(self, tmp_path):
        store = _store(tmp_path)
        for node, seq in (("b", 1), ("a", 2), ("a", 0), ("b", 0)):
            body = archive_mod.encode_wal_segment(node, seq, [])
            store.put(archive_mod.wal_segment_key(node, seq, body),
                      body)
        store.put("wal/a/garbage", b"nope")  # unparseable: ignored
        segs = [(n, s) for _k, n, s in
                archive_mod.list_wal_segments(store)]
        assert segs == [("a", 0), ("a", 2), ("b", 0), ("b", 1)]
        assert archive_mod.next_wal_seq(store, "a") == 3
        assert archive_mod.next_wal_seq(store, "c") == 0

    def test_sanitized_node_names(self):
        key = archive_mod.wal_segment_key("127.0.0.1:10101", 0, b"")
        assert ":" not in key.split("/", 1)[1]
        assert archive_mod.parse_wal_key(key) is not None
        assert archive_mod.parse_wal_key("wal/n/short") is None
        assert archive_mod.parse_wal_key("data/i/f/s/0/head-0") is None


# -- the crash journal --------------------------------------------------------


class TestBackupJournal:
    def test_write_load_clear(self, tmp_path):
        j = coord_mod.BackupJournal.for_data_dir(str(tmp_path))
        assert j.load() is None and not j.in_flight()
        j.write(phase=coord_mod.PHASE_SNAPSHOT, id="abc",
                kind="full")
        j2 = coord_mod.BackupJournal.for_data_dir(str(tmp_path))
        state = j2.load()
        assert state["id"] == "abc" and j2.in_flight()
        j2.write(phase=coord_mod.PHASE_DONE)
        assert not j2.in_flight()
        j2.clear()
        assert coord_mod.BackupJournal.for_data_dir(
            str(tmp_path)).load() is None

    def test_version_mismatch_ignored(self, tmp_path):
        path = os.path.join(str(tmp_path), coord_mod.JOURNAL_FILE)
        with open(path, "w") as f:
            json.dump({"version": 99, "phase": "snapshot"}, f)
        assert coord_mod.BackupJournal(path).load() is None


# -- retention + GC -----------------------------------------------------------


class TestRetention:
    def _wal(self, store, node, seq):
        body = archive_mod.encode_wal_segment(node, seq, [])
        key = archive_mod.wal_segment_key(node, seq, body)
        store.put(key, body)
        return key

    def test_plan_keeps_last_n_fulls_and_wal_floor(self, tmp_path):
        store = _store(tmp_path)
        _fake_backup(store, "f1", "full", 100.0, rows=(1, 2),
                     wal_start={"n": 0})
        _fake_backup(store, "i1", "incremental", 150.0, parent="f1",
                     rows=(1, 2, 3), wal_start={"n": 2})
        _fake_backup(store, "f2", "full", 200.0, rows=(4,),
                     wal_start={"n": 5})
        _fake_backup(store, "f3", "full", 300.0, rows=(5,),
                     wal_start={"n": 7})
        keys = [self._wal(store, "n", seq) for seq in range(9)]
        plan = retention_mod.plan_gc(store, keep_fulls=2)
        assert plan["kept"] == ["f2", "f3"]
        assert plan["newestFull"] == "f3"
        assert sorted(plan["dropBackups"]) == ["f1", "i1"]
        # WAL floor = min walStart across kept (5): seqs 0..4 drop.
        assert plan["dropWalSegments"] == sorted(keys[:5])

    def test_shared_pool_objects_survive_a_drop(self, tmp_path):
        store = _store(tmp_path)
        _fake_backup(store, "a", "full", 100.0, rows=(9,))
        _fake_backup(store, "b", "full", 200.0, rows=(9,))
        plan = retention_mod.plan_gc(store, keep_fulls=1)
        assert plan["dropBackups"] == ["a"]
        assert plan["dropObjects"] == []  # pool shared with "b"

    def test_incremental_chain_keeps_ancestors(self, tmp_path):
        store = _store(tmp_path)
        _fake_backup(store, "f1", "full", 100.0, rows=(1,))
        _fake_backup(store, "i1", "incremental", 150.0, parent="f1",
                     rows=(2,))
        _fake_backup(store, "f2", "full", 200.0, rows=(3,))
        _fake_backup(store, "i2", "incremental", 250.0, parent="i1",
                     rows=(4,))
        plan = retention_mod.plan_gc(store, keep_fulls=1)
        # i2 rides the window; its parent chain (i1 -> f1) must
        # survive even though both predate the kept full.
        assert set(plan["kept"]) == {"f1", "i1", "f2", "i2"}
        assert plan["dropBackups"] == []

    def test_orphan_sweep_is_opt_in_and_dry_run_deletes_nothing(
            self, tmp_path):
        store = _store(tmp_path)
        _fake_backup(store, "f1", "full", 100.0, rows=(1,))
        stray = "data/i/f/standard/0/stray-deadbeef"
        store.put(stray, b"debris")
        plan = retention_mod.plan_gc(store, keep_fulls=1)
        assert stray in plan["orphanObjects"]
        out = retention_mod.run_gc(store, keep_fulls=1, dry_run=True,
                                   sweep_orphans=True)
        assert out["deleted"] == 0 and store.exists(stray)
        out = retention_mod.run_gc(store, keep_fulls=1)
        assert not out["orphanObjects"] and store.exists(stray)
        out = retention_mod.run_gc(store, keep_fulls=1,
                                   sweep_orphans=True)
        assert stray in out["orphanObjects"]
        assert not store.exists(stray)

    def test_gc_drops_old_full_but_archive_stays_restorable(
            self, tmp_path):
        store = _store(tmp_path)
        _fake_backup(store, "f1", "full", 100.0, rows=(1, 2))
        keep = _fake_backup(store, "f2", "full", 200.0, rows=(3, 4))
        out = retention_mod.run_gc(store, keep_fulls=1)
        assert out["dropBackups"] == ["f1"]
        assert archive_mod.read_backup(store, "f1") is None
        for name, verdict in archive_mod.verify_backup(store, keep):
            assert not verdict["corrupt"], (name, verdict)

    def test_run_gc_refuses_to_break_newest_chain(self, tmp_path,
                                                  monkeypatch):
        store = _store(tmp_path)
        m = _fake_backup(store, "f1", "full", 100.0, rows=(1,))
        evil = retention_mod.plan_gc(store, 1)
        evil["dropObjects"] = sorted(
            archive_mod.manifest_object_keys(m))
        monkeypatch.setattr(retention_mod, "plan_gc",
                            lambda *a, **k: dict(evil))
        with pytest.raises(retention_mod.GCError):
            retention_mod.run_gc(store, 1)
        assert archive_mod.read_backup(store, "f1") is not None
        for key in evil["dropObjects"]:
            assert store.exists(key)

    def test_run_gc_refuses_wal_the_newest_full_replays(
            self, tmp_path, monkeypatch):
        store = _store(tmp_path)
        _fake_backup(store, "f1", "full", 100.0, rows=(1,),
                     wal_start={"n": 3})
        key = self._wal(store, "n", 5)  # >= floor: still replayed
        evil = retention_mod.plan_gc(store, 1)
        evil["dropWalSegments"] = [key]
        monkeypatch.setattr(retention_mod, "plan_gc",
                            lambda *a, **k: dict(evil))
        with pytest.raises(retention_mod.GCError):
            retention_mod.run_gc(store, 1)
        assert store.exists(key)


# -- the WAL archiver ---------------------------------------------------------


class _FlakyStore:
    """Delegating store whose next ``fail`` puts raise OSError."""

    def __init__(self, inner, fail=0):
        self.inner = inner
        self.fail = fail

    def put(self, key, data):
        if self.fail > 0:
            self.fail -= 1
            raise OSError("injected archive outage")
        self.inner.put(key, data)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class TestWalArchiver:
    def _frag_path(self, root, slice=0):
        return os.path.join(str(root), "i", "f", "views", "standard",
                            "fragments", str(slice))

    def test_frag_key_mapping(self, tmp_path):
        a = WalArchiver(_store(tmp_path), str(tmp_path), "n1")
        assert a._frag_key(self._frag_path(tmp_path, 7)) == \
            "i/f/standard/7"
        assert a._frag_key(os.path.join(str(tmp_path), "i", "f",
                                        "somewhere")) is None
        assert a._frag_key(os.path.join(str(tmp_path),
                                        "backup.json")) is None

    def test_buffer_flush_and_replayable_order(self, tmp_path):
        store = _store(tmp_path)
        a = WalArchiver(store, str(tmp_path), "127.0.0.1:7")
        path = self._frag_path(tmp_path)
        a._on_batch(path, b"\x01" * 13)
        a._on_batch(path, b"\x02" * 26)
        a._on_batch(os.path.join(str(tmp_path), "junk"), b"zz")
        assert a.flush() == 2
        assert a.flush() == 0  # drained
        segs = archive_mod.list_wal_segments(store)
        assert len(segs) == 1
        seg = archive_mod.read_wal_segment(store, segs[0][0])
        assert [b["ops"] for b in seg["batches"]] == \
            [b"\x01" * 13, b"\x02" * 26]

    def test_store_outage_requeues_in_commit_order(self, tmp_path):
        store = _store(tmp_path)
        flaky = _FlakyStore(store, fail=1)
        a = WalArchiver(flaky, str(tmp_path), "n1")
        path = self._frag_path(tmp_path)
        a._on_batch(path, b"\x01" * 13)
        a._on_batch(path, b"\x02" * 13)
        with pytest.raises(OSError):
            a.flush()
        assert a.errors == 1
        a._on_batch(path, b"\x03" * 13)
        assert a.flush() == 3
        seg = archive_mod.read_wal_segment(
            store, archive_mod.list_wal_segments(store)[0][0])
        assert [b["ops"][:1] for b in seg["batches"]] == \
            [b"\x01", b"\x02", b"\x03"]

    def test_seq_resumes_from_store(self, tmp_path):
        store = _store(tmp_path)
        for seq in (0, 1):
            body = archive_mod.encode_wal_segment("n1", seq, [])
            store.put(archive_mod.wal_segment_key("n1", seq, body),
                      body)
        a = WalArchiver(store, str(tmp_path), "n1")
        a._on_batch(self._frag_path(tmp_path), b"\x01" * 13)
        a.flush()
        assert archive_mod.next_wal_seq(store, "n1") == 3


# -- live-cluster legs --------------------------------------------------------


def _post(host, path, body=b""):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.loads(r.read() or b"{}")


def _get(host, path):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=15) as r:
        return json.loads(r.read())


def _query(host, index, q):
    return _post(host, f"/index/{index}/query", q.encode())["results"]


def _wait_backup(host, timeout_s=90.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        op = _get(host, "/backup")["op"]
        if op and op["phase"] in (coord_mod.PHASE_DONE,
                                  coord_mod.PHASE_FAILED):
            return op
        time.sleep(0.05)
    raise AssertionError("backup did not finish in time")


@pytest.fixture
def env(tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_MESH", "0")
    ns = SimpleNamespace(tmp=tmp_path, servers=[])

    def make(name, backup=None):
        s = Server(str(tmp_path / name), host="127.0.0.1:0",
                   anti_entropy_interval=0, polling_interval=0,
                   backup_config=backup)
        s.open()
        ns.servers.append(s)
        return s

    ns.make = make
    yield ns
    failpoints.disarm_all()
    for s in ns.servers:
        try:
            s.close()
        except Exception:  # noqa: BLE001 - already closed mid-test
            pass


def _setup_index(hosts, index="bk", frame="f"):
    for h in hosts:
        _post(h, f"/index/{index}")
        _post(h, f"/index/{index}/frame/{frame}")


class TestBackupRestoreE2E:
    """Full disaster: consistent backup under live writes, incremental
    on top, every data dir destroyed, restore into a different-size
    cluster, workload-replay digest verification, exact PITR cut."""

    def test_backup_destroy_restore_pitr_verified(self, env):
        arch = str(env.tmp / "archive")
        bc = BackupConfig(archive=f"dir:{arch}", wal_interval=60.0)
        s1 = env.make("n1", backup=bc)
        s2 = env.make("n2", backup=bc)
        for s in (s1, s2):
            s.cluster.nodes = [Node(s1.host), Node(s2.host)]
        _setup_index((s1.host, s2.host))
        rng = np.random.default_rng(7)
        n_bits = 1200
        rows = rng.integers(0, 6, n_bits).astype(np.uint64)
        cols = rng.choice(3 * SLICE_WIDTH, size=n_bits,
                          replace=False).astype(np.uint64)
        Client(s1.host).import_arrays("bk", "f", rows, cols)
        for s in (s1, s2):
            s.holder.index("bk").set_remote_max_slice(2)
        model = {}
        for r, c in zip(rows.tolist(), cols.tolist()):
            model.setdefault(int(r), set()).add(int(c))

        out = _post(s1.host, "/backup",
                    json.dumps({"kind": "full"}).encode())
        assert out["op"]["kind"] == "full"
        full_op = _wait_backup(s1.host)
        assert full_op["phase"] == coord_mod.PHASE_DONE, full_op
        assert full_op["fragments"] > 0

        # Post-backup writes: only the WAL archive can carry these.
        _query(s1.host, "bk", 'SetBit(frame="f", rowID=50,'
                              ' columnID=123)')
        _query(s2.host, "bk", 'SetBit(frame="f", rowID=50,'
                              ' columnID=456)')
        model[50] = {123, 456}
        for s in (s1, s2):
            s.wal_archiver.flush()
        time.sleep(0.02)
        cut = time.time()
        time.sleep(0.02)
        _query(s1.host, "bk", 'SetBit(frame="f", rowID=51,'
                              ' columnID=789)')
        model[51] = {789}
        for s in (s1, s2):
            s.wal_archiver.flush()

        # An incremental rides the shared pool: far fewer objects.
        _post(s1.host, "/backup",
              json.dumps({"kind": "incremental"}).encode())
        incr_op = _wait_backup(s1.host)
        assert incr_op["phase"] == coord_mod.PHASE_DONE, incr_op
        assert incr_op["objectsPushed"] < full_op["objectsPushed"]
        dbg = _get(s1.host, "/debug/backup")
        assert [b["kind"] for b in dbg["backups"]] == \
            ["full", "incremental"]
        assert dbg["backups"][1]["parent"] == full_op["id"]
        assert dbg["walSegments"], "no WAL segments archived"

        # Capture the workload verdicts on the SOURCE cluster.
        records = []
        for row in sorted(model):
            rec = {"index": "bk",
                   "pql": f'Bitmap(frame="f", rowID={row})'}
            got = replay_mod._issue(s1.host, rec)
            assert got["status"] == 200 and got["digest"]
            rec.update(status=200, digest=got["digest"])
            records.append(rec)
        records.append({"index": "bk",
                        "pql": 'SetBit(frame="f", rowID=1,'
                               ' columnID=1)'})  # write: never replayed
        recpath = str(env.tmp / "records.json")
        with open(recpath, "w") as f:
            json.dump({"records": records}, f)

        # Destroy EVERY data dir.
        for s in (s1, s2):
            s.close()
        env.servers.clear()
        shutil.rmtree(str(env.tmp / "n1"))
        shutil.rmtree(str(env.tmp / "n2"))

        # Restore into a DIFFERENT-size (1-node) cluster via the CLI,
        # with workload-replay verification: zero digest mismatches.
        r1 = env.make("r1")
        out1, err1 = io.StringIO(), io.StringIO()
        rc = cli_main(["restore", "--host", r1.host,
                       "--archive", f"dir:{arch}",
                       "--verify", recpath], out1, err1)
        assert rc == 0, (out1.getvalue(), err1.getvalue())
        summary = json.loads(out1.getvalue())
        assert summary["verify"]["compared"] == len(model)
        assert summary["verify"]["mismatches"] == 0
        assert summary["verify"]["skipped"] == 1  # the write record
        for row, want in model.items():
            got = _query(r1.host, "bk",
                         f'Count(Bitmap(frame="f", rowID={row}))')[0]
            assert got == len(want), (row, got, len(want))

        r1.close()
        env.servers.clear()
        shutil.rmtree(str(env.tmp / "r1"))

        # PITR to the cut: the post-cut write provably excluded, and
        # the verifier SEES the drift (row 51's digest mismatches).
        r2 = env.make("r2")
        store = archive_mod.open_archive(f"dir:{arch}", "")
        summary = restore_mod.run_restore(r2.host, store,
                                          to_timestamp=cut)
        assert summary["id"] == full_op["id"]  # incremental post-cut
        assert _query(r2.host, "bk",
                      'Count(Bitmap(frame="f", rowID=50))')[0] == 2
        assert _query(r2.host, "bk",
                      'Count(Bitmap(frame="f", rowID=51))')[0] == 0
        verdict = verify_mod.verify_restore(r2.host, records)
        assert verdict["mismatches"] >= 1


class TestCrashResume:
    """A coordinator killed mid-push resumes idempotently under the
    same backup id (the journal + pool exists-check contract)."""

    def _seed(self, env, name, archive_spec=None):
        bc = BackupConfig(archive=archive_spec) if archive_spec \
            else None
        s = env.make(name, backup=bc)
        _setup_index((s.host,))
        rng = np.random.default_rng(3)
        rows = rng.integers(0, 4, 400).astype(np.uint64)
        cols = rng.choice(2 * SLICE_WIDTH, size=400,
                          replace=False).astype(np.uint64)
        Client(s.host).import_arrays("bk", "f", rows, cols)
        s.holder.index("bk").set_remote_max_slice(1)
        return s

    def test_failed_push_resumes_same_id(self, env):
        arch = str(env.tmp / "archive")
        s = self._seed(env, "n1", archive_spec=f"dir:{arch}")
        store = s.backup_store
        # Drain the WAL archiver first: its (retried, error-tolerant)
        # segment push must not consume the one-shot injection meant
        # for the coordinator's first data object.
        s.wal_archiver.flush()
        coord = coord_mod.BackupCoordinator(s, store, kind="full")
        with failpoints.injected("backup.push", "error*1"):
            # The failpoint fires AFTER the store write: the crash
            # leaves the first object durable but unjournaled.
            with pytest.raises(OSError):
                coord._run()
        journal = coord_mod.BackupJournal.for_data_dir(s.holder.path)
        assert journal.load() is not None and journal.in_flight()
        assert archive_mod.read_backup(store, coord.id) is None

        out = coord_mod.recover(s)
        assert out is not None and out["id"] == coord.id
        assert out["phase"] == coord_mod.PHASE_DONE, out
        manifest = archive_mod.read_backup(store, coord.id)
        assert manifest is not None
        total = len(archive_mod.manifest_object_keys(manifest))
        # The durable object from the crashed attempt was skipped.
        assert out["objectsPushed"] < total
        for name, verdict in archive_mod.verify_backup(store,
                                                       manifest):
            assert not verdict["corrupt"], (name, verdict)

    def test_journaled_fragments_reused_on_recover(self, env):
        arch = str(env.tmp / "archive")
        s = self._seed(env, "n1", archive_spec=f"dir:{arch}")
        store = s.backup_store
        first = coord_mod.BackupCoordinator(s, store, kind="full")
        first._run()
        m1 = archive_mod.read_backup(store, first.id)
        frag = m1["fragments"][0]
        key = (f"{frag['index']}/{frag['frame']}/{frag['view']}"
               f"/{frag['slice']}")
        # Simulate a crash that had journaled exactly one fragment.
        journal = coord_mod.BackupJournal.for_data_dir(s.holder.path)
        journal.write(phase=coord_mod.PHASE_SNAPSHOT, id="resume01",
                      kind="full", coordinator=s.host,
                      startedAt=time.time(),
                      walStart=m1.get("walStart") or {}, parent=None,
                      fragments={key: frag})
        out = coord_mod.recover(s)
        assert out["id"] == "resume01"
        assert out["phase"] == coord_mod.PHASE_DONE, out
        assert out["fragmentsSkipped"] >= 1
        m2 = archive_mod.read_backup(store, "resume01")
        assert frag in m2["fragments"]

    def test_recover_noop_without_in_flight_journal(self, env):
        arch = str(env.tmp / "archive")
        s = self._seed(env, "n1", archive_spec=f"dir:{arch}")
        assert coord_mod.recover(s) is None


class TestRestoreAdmission:
    """Torn/corrupt archive objects are detected at restore admission
    and never served (the PR-15 contract, extended offline)."""

    def _backed_up_store(self, env, rows=(1, 2, 3)):
        """A closed-and-destroyed 1-node cluster's archive, plus the
        row -> column model it held."""
        s = env.make("src")
        _setup_index((s.host,))
        for r in rows:
            _query(s.host, "bk",
                   f'SetBit(frame="f", rowID={r}, columnID={r})')
        store = archive_mod.open_archive(
            f"dir:{env.tmp / 'archive'}", "")
        coord = coord_mod.BackupCoordinator(s, store, kind="full")
        coord._run()
        assert coord.phase == coord_mod.PHASE_DONE
        s.close()
        env.servers.remove(s)
        shutil.rmtree(str(env.tmp / "src"))
        return store

    def test_corrupt_object_rejected_never_served(self, env):
        store = self._backed_up_store(env)
        key = sorted(store.list("data/"))[0]
        path = store._path(key)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x40
        with open(path, "wb") as f:
            f.write(raw)
        target = env.make("dst")
        with pytest.raises(restore_mod.RestoreError) as ei:
            restore_mod.run_restore(target.host, store)
        assert "NOT admitted" in str(ei.value)
        # Schema came back but the rotten fragment never did: the
        # restored cluster serves nothing rather than wrong bits.
        assert _query(target.host, "bk",
                      'Count(Bitmap(frame="f", rowID=1))')[0] == 0

    def test_fetch_failpoint_corrupt_rejected(self, env):
        store = self._backed_up_store(env)
        target = env.make("dst")
        with failpoints.injected("restore.fetch", "corrupt*1"):
            with pytest.raises(restore_mod.RestoreError):
                restore_mod.run_restore(target.host, store)

    def test_fetch_failpoint_error_surfaces(self, env):
        store = self._backed_up_store(env)
        target = env.make("dst")
        with failpoints.injected("restore.fetch", "error*1"):
            with pytest.raises(restore_mod.RestoreError):
                restore_mod.run_restore(target.host, store)

    def test_torn_object_rejected(self, env):
        store = self._backed_up_store(env)
        key = sorted(store.list("data/"))[-1]
        path = store._path(key)
        raw = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(raw[:max(1, len(raw) // 3)])
        target = env.make("dst")
        with pytest.raises(restore_mod.RestoreError):
            restore_mod.run_restore(target.host, store)


# -- the CLI surface ----------------------------------------------------------


class TestBackupCLI:
    def test_list_gc_and_check_deep(self, tmp_path):
        arch = str(tmp_path / "archive")
        store = blob_mod.LocalDirBlobStore(arch)
        _fake_backup(store, "f1", "full", 100.0, rows=(1, 2))
        _fake_backup(store, "f2", "full", 200.0, rows=(3, 4))
        body = archive_mod.encode_wal_segment(
            "n1", 0, [{"frag": "i/f/standard/0", "t": 1.0,
                       "ops": b"x" * 13}])
        store.put(archive_mod.wal_segment_key("n1", 0, body), body)

        out = io.StringIO()
        rc = cli_main(["backup", "--archive", f"dir:{arch}",
                       "--list"], out, io.StringIO())
        assert rc == 0
        assert "f1" in out.getvalue() and "f2" in out.getvalue()

        out = io.StringIO()
        rc = cli_main(["backup", "--archive", f"dir:{arch}", "--gc",
                       "--keep", "1", "--dry-run"], out,
                      io.StringIO())
        assert rc == 0
        plan = json.loads(out.getvalue())
        assert plan["dryRun"] and plan["dropBackups"] == ["f1"]
        assert archive_mod.read_backup(store, "f1") is not None

        out = io.StringIO()
        rc = cli_main(["check", "--deep", "--archive",
                       f"dir:{arch}"], out, io.StringIO())
        assert rc == 0, out.getvalue()
        assert "0 corrupt" in out.getvalue()

        # Rot one pool object: same walk must fail with rc 1.
        key = sorted(store.list("data/"))[0]
        path = store._path(key)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x01
        with open(path, "wb") as f:
            f.write(raw)
        out = io.StringIO()
        rc = cli_main(["check", "--deep", "--archive",
                       f"dir:{arch}"], out, io.StringIO())
        assert rc == 1
        assert "CORRUPT" in out.getvalue()

    def test_archive_flags_require_explicit_path(self, tmp_path):
        err = io.StringIO()
        rc = cli_main(["backup", "--archive", "dir", "--list"],
                      io.StringIO(), err)
        assert rc == 1
        rc = cli_main(["restore", "--archive", "dir", "--host",
                       "localhost:1"], io.StringIO(), err)
        assert rc == 1
