"""PQL parser tests (reference pql/parser_test.go cases) plus canonical
String() round-trip, which the executor relies on for query forwarding."""

import pytest

from pilosa_tpu.pql import parser as pql
from pilosa_tpu.pql.ast import Call


def parse1(s):
    q = pql.parse(s)
    assert len(q.calls) == 1
    return q.calls[0]


class TestParser:
    def test_empty(self):
        assert pql.parse("").calls == []

    def test_simple_call(self):
        c = parse1("Bitmap(rowID=1, frame='f')")
        assert c.name == "Bitmap"
        assert c.args == {"rowID": 1, "frame": "f"}

    def test_children_before_args(self):
        c = parse1('Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))')
        assert c.name == "Count"
        inner = c.children[0]
        assert inner.name == "Intersect"
        assert [ch.args["rowID"] for ch in inner.children] == [1, 2]

    def test_child_paren_must_be_adjacent(self):
        """A child call needs LPAREN immediately after the ident — the
        reference checks IDENT+LPAREN with a raw scan (parser.go:
        119-126), so "Bitmap (" is not a child and the ident falls
        through to argument parsing, which then fails on '('."""
        with pytest.raises(pql.ParseError):
            pql.parse('Count(Bitmap (rowID=1))')
        # whitespace before a TOP-LEVEL call's paren stays legal
        c = parse1('Count (Bitmap(rowID=1))')
        assert c.name == "Count" and c.children[0].name == "Bitmap"

    def test_int64_bounds(self):
        """Integers parse as int64 like the reference (parser.go:186):
        out-of-range ids fail at parse, which also keeps a stray huge
        columnID from exploding max_slice."""
        assert parse1(f"X(a={2**63 - 1})").args["a"] == 2**63 - 1
        assert parse1(f"X(a={-2**63})").args["a"] == -(2**63)
        with pytest.raises(pql.ParseError):
            pql.parse(f"X(a={2**63})")
        with pytest.raises(pql.ParseError):
            pql.parse(f"SetBit(columnID={2**70})")
        with pytest.raises(pql.ParseError):
            pql.parse(f"X(a=[1, {2**64}])")

    def test_unicode_digits_rejected(self):
        """Number tokens are ASCII-only like the reference's isDigit —
        a Unicode digit must not silently extend an integer (int()
        would convert it)."""
        with pytest.raises(pql.ParseError):
            pql.parse('SetBit(rowID=5٥)')
        with pytest.raises(pql.ParseError):
            pql.parse('SetBit(rowID=-٥)')

    def test_children_and_args(self):
        c = parse1('TopN(Bitmap(rowID=1), frame="f", n=5)')
        assert len(c.children) == 1
        assert c.args == {"frame": "f", "n": 5}

    def test_value_kinds(self):
        c = parse1('X(a=1, b=-2, c=3.5, d=true, e=false, f=null, '
                   'g="str", h=bareword, i=[1,2,"x"])')
        assert c.args == {"a": 1, "b": -2, "c": 3.5, "d": True, "e": False,
                          "f": None, "g": "str", "h": "bareword",
                          "i": [1, 2, "x"]}

    def test_ident_with_special_chars(self):
        c = parse1("Range(frame=f, start=x2010-01)")
        assert c.args["start"] == "x2010-01"

    def test_string_escapes(self):
        c = parse1(r'X(a="q\"uote", b=\'sin\ngle\')'.replace("\\'", "'"))
        assert c.args["a"] == 'q"uote'

    def test_duplicate_key_rejected(self):
        with pytest.raises(pql.ParseError, match="already used"):
            pql.parse("X(a=1, a=2)")

    def test_errors(self):
        for bad in ["X(", "X)", "X(a=)", "X(a", "X(1)", "X(a=1 b=2)"]:
            with pytest.raises(pql.ParseError):
                pql.parse(bad)

    def test_multiple_calls(self):
        q = pql.parse('SetBit(id=1, frame="f", col=2)\n'
                      'Count(Bitmap(id=1, frame="f"))')
        assert [c.name for c in q.calls] == ["SetBit", "Count"]
        assert [c.name for c in q.write_calls()] == ["SetBit"]


class TestCanonicalString:
    @pytest.mark.parametrize("src", [
        'Bitmap(frame="f", rowID=1)',
        'Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))',
        'TopN(Bitmap(rowID=1), field="x", filters=[1,2,"a",true], n=5)',
        'SetBit(col=3, frame="f", row=1)',
        'X(neg=-5, pi=3.5, t=true)',
    ])
    def test_roundtrip(self, src):
        q = pql.parse(src)
        assert str(pql.parse(str(q))) == str(q)

    def test_sorted_keys(self):
        c = parse1("X(b=2, a=1)")
        assert str(c) == "X(a=1, b=2)"

    def test_child_and_args_order(self):
        c = parse1('TopN(Bitmap(rowID=1), n=2, frame="f")')
        assert str(c) == 'TopN(Bitmap(rowID=1), frame="f", n=2)'


class TestCallHelpers:
    def test_uint_arg(self):
        c = Call("X", {"n": 5, "s": "x"})
        assert c.uint_arg("n") == (5, True)
        assert c.uint_arg("missing") == (0, False)
        with pytest.raises(ValueError):
            c.uint_arg("s")

    def test_uint_slice_arg(self):
        c = Call("X", {"ids": [1, 2, 3]})
        assert c.uint_slice_arg("ids") == ([1, 2, 3], True)
        assert c.uint_slice_arg("nope") == ([], False)

    def test_is_inverse(self):
        c = Call("Bitmap", {"columnID": 3})
        assert c.is_inverse("rowID", "columnID")
        c2 = Call("Bitmap", {"rowID": 3})
        assert not c2.is_inverse("rowID", "columnID")
        assert not Call("Range", {"columnID": 3}).is_inverse(
            "rowID", "columnID")

    def test_clone_independent(self):
        c = parse1("TopN(Bitmap(rowID=1), n=5)")
        d = c.clone()
        d.args["n"] = 9
        d.children[0].args["rowID"] = 2
        assert c.args["n"] == 5
        assert c.children[0].args["rowID"] == 1


class TestReviewRegressions:
    def test_malformed_numbers_raise_parse_error(self):
        for bad in ["f(x=-)", "f(x=-.)", "f(x=[1,-])"]:
            with pytest.raises(pql.ParseError):
                pql.parse(bad)
