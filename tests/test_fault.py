"""Fault subsystem: failpoints, breakers, health, failover, hedging.

Tier-1 chaos tests (the ``chaos`` marker, FAST — the multi-process
SIGKILL legs live in test_fault_cluster.py under ``slow``): every
failpoint site is exercised at least once, the disarmed path is proven
free (the ctx.trace-style nop guard), the breaker state machine is
driven through closed→open→half-open→closed with a fake clock, and
the executor-level failover/partial/hedging contracts run against
scripted fake clients exactly like test_executor's distributed legs.
"""

import http.client
import io
import json
import random
import threading
import time
from types import SimpleNamespace

import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.cluster.client import (CircuitOpenError, Client,
                                       ClientError)
from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.errors import SliceUnavailableError
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.fault import FaultManager, breaker as breaker_mod
from pilosa_tpu.fault import failpoints
from pilosa_tpu.fault.breaker import (STATE_CLOSED, STATE_HALF_OPEN,
                                      STATE_OPEN, BreakerBoard)
from pilosa_tpu.fault.failpoints import FailpointError, Failpoints
from pilosa_tpu.fault.health import PeerHealth
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.server.handler import Handler
from pilosa_tpu.server.syncer import FragmentSyncer, HolderSyncer
from pilosa_tpu.storage.fragment import Fragment

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_failpoints():
    """Failpoints are process-global by design; no test may leak an
    armed schedule into the rest of the suite."""
    yield
    failpoints.disarm_all()
    failpoints.ACTIVE = None


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def must_set(holder, index, frame, row, col):
    idx = holder.create_index_if_not_exists(index)
    f = idx.create_frame_if_not_exists(frame)
    f.set_bit("standard", row, col)


# -- failpoint spec parsing + determinism -------------------------------------


class TestFailpointSpecs:
    def test_modes_parse(self):
        for spec in ("error", "error(0.5)", "delay(50ms)",
                     "delay(1ms,0.5)", "torn(7)", "partition(hostB)",
                     "error*3", "torn(7,0.5)*2"):
            fp = failpoints.parse_spec("rpc.send", spec)
            assert fp is not None and fp.spec == spec

    def test_off_and_empty_disarm(self):
        assert failpoints.parse_spec("rpc.send", "off") is None
        assert failpoints.parse_spec("rpc.send", "") is None

    def test_malformed_specs_raise(self):
        for spec in ("boom", "error(2.0)", "delay()", "torn()",
                     "partition()", "error(0.5)(0.5)", "delay(xyz)"):
            with pytest.raises(ValueError):
                failpoints.parse_spec("rpc.send", spec)

    def test_unknown_site_rejected(self):
        reg = Failpoints(seed=1)
        with pytest.raises(ValueError, match="unknown failpoint site"):
            reg.arm("no.such.site", "error")

    def test_count_auto_disarms(self):
        reg = Failpoints(seed=1)
        reg.arm("rpc.send", "error*2")
        for _ in range(2):
            with pytest.raises(FailpointError):
                reg.hit("rpc.send")
        reg.hit("rpc.send")  # third hit: disarmed, no raise
        assert reg.snapshot()["armed"] == {}

    def test_probability_replays_from_seed(self):
        def schedule(seed):
            reg = Failpoints(seed=seed)
            reg.arm("rpc.send", "error(0.5)")
            out = []
            for _ in range(64):
                try:
                    reg.hit("rpc.send")
                    out.append(0)
                except FailpointError:
                    out.append(1)
            reg.disarm_all()
            return out

        a, b = schedule(42), schedule(42)
        assert a == b, "same seed must replay the same schedule"
        assert 0 < sum(a) < 64, "p=0.5 over 64 draws hit both outcomes"
        assert schedule(43) != a, "a different seed reshuffles"

    def test_partition_scopes_by_host(self):
        reg = Failpoints(seed=1)
        reg.arm("rpc.send", "partition(hostB)")
        reg.hit("rpc.send", host="hostA:10101")  # no match, no raise
        with pytest.raises(FailpointError):
            reg.hit("rpc.send", host="hostB:10101")

    def test_delay_sleeps(self):
        reg = Failpoints(seed=1)
        reg.arm("rpc.send", "delay(30ms)")
        t0 = time.perf_counter()
        reg.hit("rpc.send")
        assert time.perf_counter() - t0 >= 0.025

    def test_torn_writes_prefix_then_fails(self):
        reg = Failpoints(seed=1)
        reg.arm("wal.append", "torn(3)")
        buf = io.BytesIO()
        with pytest.raises(FailpointError):
            reg.hit("wal.append", writer=buf, data=b"abcdef")
        assert buf.getvalue() == b"abc"

    def test_arm_from_env(self):
        reg_sites = failpoints.arm_from_env(
            {"PILOSA_FAULT_GOSSIP_DELIVER": "error",
             "PILOSA_FAULT_SEED": "7",        # reserved: not a site
             "PILOSA_FAULT_UNRELATED": "x"})  # unknown: ignored
        assert reg_sites == ["gossip.deliver"]
        assert "gossip.deliver" in \
            failpoints.default().snapshot()["armed"]
        failpoints.disarm_all()

    def test_private_registry_never_touches_global_active(self):
        """Only the DEFAULT registry publishes to the process-global
        ACTIVE hook: a test-local registry must neither hijack the
        production injection sites nor clear the default's schedule."""
        failpoints.ACTIVE = None
        reg = Failpoints(seed=1)
        reg.arm("rpc.send", "error")
        assert failpoints.ACTIVE is None, \
            "a private registry must not arm the global sites"
        failpoints.arm("rpc.recv", "error")
        assert failpoints.ACTIVE is failpoints.default()
        reg.disarm_all()
        assert failpoints.ACTIVE is failpoints.default(), \
            "a private disarm must not clear the default's schedule"
        failpoints.disarm_all()
        assert failpoints.ACTIVE is None

    def test_trigger_counter(self):
        before = obs_metrics.FAILPOINT_TRIGGERS.labels(
            "mesh.dispatch").value
        reg = Failpoints(seed=1)
        reg.arm("mesh.dispatch", "error*1")
        with pytest.raises(FailpointError):
            reg.hit("mesh.dispatch")
        after = obs_metrics.FAILPOINT_TRIGGERS.labels(
            "mesh.dispatch").value
        assert after == before + 1


# -- every injection site, through its real call path -------------------------


class _FakeResp:
    status = 200
    will_close = False

    def read(self):
        return b"{}"

    def getheaders(self):
        return []

    def close(self):
        pass


class _GoodConn:
    """Minimal http.client.HTTPConnection stand-in."""

    def __init__(self, host, timeout=None):
        self.host = host
        self.timeout = timeout
        self.sock = None
        self.closed = False

    def request(self, method, path, body=None, headers=None):
        pass

    def getresponse(self):
        return _FakeResp()

    def close(self):
        self.closed = True


class TestFailpointSites:
    def test_rpc_send_injects_transport_error(self, monkeypatch):
        monkeypatch.setattr(http.client, "HTTPConnection", _GoodConn)
        c = Client("peer:1")
        with failpoints.injected("rpc.send", "error"):
            with pytest.raises(ClientError, match="failpoint rpc.send"):
                c._do("GET", "/schema")
        status, _ = c._do("GET", "/schema")  # disarmed: flows again
        assert status == 200

    def test_rpc_send_single_shot_is_retried(self, monkeypatch):
        # error*1: the first attempt fails, the transparent retry on a
        # fresh connection succeeds — the injection exercises exactly
        # the stale-keep-alive recovery path.
        monkeypatch.setattr(http.client, "HTTPConnection", _GoodConn)
        c = Client("peer:1")
        c._conn_put("peer:1", _GoodConn("peer:1"))  # pooled socket
        with failpoints.injected("rpc.send", "error*1"):
            status, _ = c._do("GET", "/schema")
        assert status == 200

    def test_rpc_recv_injects_response_loss(self, monkeypatch):
        monkeypatch.setattr(http.client, "HTTPConnection", _GoodConn)
        c = Client("peer:1")
        with failpoints.injected("rpc.recv", "error"):
            with pytest.raises(ClientError, match="failpoint rpc.recv"):
                c._do("GET", "/schema")

    def test_rpc_partition_mode_scopes_to_one_peer(self, monkeypatch):
        monkeypatch.setattr(http.client, "HTTPConnection", _GoodConn)
        c = Client("peerA:1")
        with failpoints.injected("rpc.send", "partition(peerB)"):
            status, _ = c._do("GET", "/schema")          # A unaffected
            assert status == 200
            with pytest.raises(ClientError):
                c._do("GET", "/schema", host="peerB:1")  # B partitioned

    def test_wal_append_error(self, tmp_path):
        """The wal.append site now lives at the group-commit LEADER
        write (storage.wal): point ops append in memory, and the
        injected fault surfaces at the commit barrier. A failed write
        leaves the batch pending and retryable — after disarm the next
        barrier lands it plus later writes."""
        from pilosa_tpu.storage.wal import WalError
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            f.set_bit(1, 5)
            f.wal_barrier()
            with failpoints.injected("wal.append", "error"):
                f.set_bit(1, 6)  # appends fine; the flush fails
                with pytest.raises((FailpointError, WalError)):
                    f.wal_barrier()
            assert f.set_bit(1, 7)  # disarmed: writes flow again
            f.wal_barrier()  # retries the failed batch + the new op
            assert f._wal.pending_bytes() == 0
        finally:
            f.close()

    def test_snapshot_write_error_keeps_old_file_of_record(self,
                                                           tmp_path):
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            f.set_bit(1, 5)
            with failpoints.injected("snapshot.write", "error*1"):
                with pytest.raises(FailpointError):
                    f.snapshot()
            # The failed snapshot never swapped: WAL intact, a retry
            # succeeds, and the data survives a reopen.
            f.snapshot()
        finally:
            f.close()
        f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f2.open()
        try:
            assert list(f2.row(1).bits()) == [5]
        finally:
            f2.close()

    def test_gossip_deliver_drop_and_restore(self):
        from pilosa_tpu.cluster.broadcast import (CancelQueryMessage,
                                                  marshal_message)
        from pilosa_tpu.cluster.gossip import GossipNodeSet
        got = []
        gs = GossipNodeSet("n1")
        gs.start(SimpleNamespace(receive_message=got.append))
        data = marshal_message(CancelQueryMessage("q1"))
        with failpoints.injected("gossip.deliver", "error"):
            gs._handle_envelope(data)
        assert got == [], "armed drop must swallow the envelope"
        gs._handle_envelope(data)
        assert len(got) == 1 and got[0].id == "q1"

    def test_mesh_dispatch_gate(self):
        from pilosa_tpu.parallel import mesh
        with failpoints.injected("mesh.dispatch", "error"):
            with pytest.raises(FailpointError):
                mesh._dispatch_gate()
        mesh._dispatch_gate()  # disarmed: no-op


class TestDisarmedOverheadGuard:
    def test_disarmed_sites_never_enter_the_registry(self, tmp_path,
                                                     monkeypatch):
        """The nop-path contract (same pattern as the PR 3 trace
        guard): with nothing armed, NO injection site may call into
        the registry at all — the cost is the ACTIVE None-check."""
        failpoints.disarm_all()
        failpoints.ACTIVE = None
        calls = []
        monkeypatch.setattr(
            Failpoints, "hit",
            lambda self, *a, **kw: calls.append((a, kw)))
        # wal.append site: a write storm through the batch engine.
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            for i in range(100):
                f.set_bit(i % 4, i)
        finally:
            f.close()
        # rpc.send / rpc.recv sites.
        monkeypatch.setattr(http.client, "HTTPConnection", _GoodConn)
        c = Client("peer:1")
        c._do("GET", "/schema")
        # gossip.deliver site.
        from pilosa_tpu.cluster.broadcast import (CancelQueryMessage,
                                                  marshal_message)
        from pilosa_tpu.cluster.gossip import GossipNodeSet
        gs = GossipNodeSet("n1")
        gs.start(SimpleNamespace(receive_message=lambda m: None))
        gs._handle_envelope(marshal_message(CancelQueryMessage("q")))
        # mesh.dispatch site.
        from pilosa_tpu.parallel import mesh
        mesh._dispatch_gate()
        assert calls == [], (
            "disarmed failpoints must be zero-cost: no registry calls")


# -- circuit breaker state machine --------------------------------------------


def _board(**kw):
    clk = [0.0]
    kw.setdefault("rng", random.Random(0))
    board = BreakerBoard(clock=lambda: clk[0], **kw)
    return board, clk


class TestBreaker:
    def test_threshold_consecutive_failures_open(self):
        board, _ = _board(threshold=3)
        for _ in range(2):
            board.record_failure("b")
        assert board.state("b") == STATE_CLOSED
        assert board.allow("b")
        board.record_failure("b")
        assert board.state("b") == STATE_OPEN
        assert not board.allow("b")

    def test_success_resets_the_consecutive_count(self):
        board, _ = _board(threshold=3)
        board.record_failure("b")
        board.record_failure("b")
        board.record_success("b")
        board.record_failure("b")
        board.record_failure("b")
        assert board.state("b") == STATE_CLOSED

    def test_half_open_single_probe_then_close(self):
        board, clk = _board(threshold=1, backoff_base_s=1.0)
        board.record_failure("b")
        assert not board.allow("b")
        clk[0] = 1.5  # past any jittered window <= base
        assert board.allow("b"), "lapsed window grants THE probe"
        assert board.state("b") == STATE_HALF_OPEN
        assert not board.allow("b"), "only one probe in flight"
        board.record_success("b")
        assert board.state("b") == STATE_CLOSED
        assert board.allow("b")

    def test_probe_failure_reopens_with_doubled_window(self):
        board, clk = _board(threshold=1, backoff_base_s=1.0,
                            backoff_cap_s=64.0)
        board.record_failure("b")
        first = board._peers["b"].open_until
        assert first <= 1.0, "full jitter: uniform(0, base)"
        clk[0] = 1.5
        assert board.allow("b")  # probe
        board.record_failure("b")
        assert board.state("b") == STATE_OPEN
        second = board._peers["b"].open_until - clk[0]
        assert second <= 2.0, "second opening: uniform(0, 2*base)"

    def test_backoff_caps(self):
        board, clk = _board(threshold=1, backoff_base_s=1.0,
                            backoff_cap_s=4.0)
        for i in range(8):
            clk[0] += 100.0
            board.allow("b")  # grant the probe when open
            board.record_failure("b")
            window = board._peers["b"].open_until - clk[0]
            assert window <= 4.0, f"opening {i}: window {window} > cap"

    def test_force_open_and_probe_ready(self):
        board, clk = _board(threshold=5)
        board.force_open("b", reason="gossip dead")
        assert board.state("b") == STATE_OPEN
        assert not board.allow("b")
        board.note_probe_ready("b")  # gossip: alive again
        assert board.allow("b"), "collapsed window grants the probe"
        board.record_success("b")
        assert board.state("b") == STATE_CLOSED

    def test_would_allow_has_no_side_effects(self):
        board, clk = _board(threshold=1)
        board.record_failure("b")
        clk[0] = 100.0
        assert board.would_allow("b")
        assert board.state("b") == STATE_OPEN, \
            "would_allow must not transition to half-open"

    def test_abandoned_probe_expires(self):
        """A granted probe whose caller died without reporting must
        not blacklist the peer forever: past PROBE_EXPIRY_S the slot
        is reclaimed and a new probe is granted."""
        board, clk = _board(threshold=1)
        board.record_failure("b")
        clk[0] = 10.0
        assert board.allow("b")  # probe granted ... and abandoned
        assert not board.allow("b")
        assert not board.would_allow("b")
        clk[0] = 10.0 + BreakerBoard.PROBE_EXPIRY_S + 1.0
        assert board.would_allow("b")
        assert board.allow("b"), "expired slot: a fresh probe"
        board.record_success("b")
        assert board.state("b") == STATE_CLOSED

    def test_gossip_alive_rescues_a_lost_half_open_probe(self):
        board, clk = _board(threshold=1)
        board.record_failure("b")
        clk[0] = 10.0
        assert board.allow("b")  # probe granted, then lost
        board.note_probe_ready("b")  # gossip: the peer IS alive
        assert board.allow("b"), \
            "liveness evidence outranks a lost probe slot"

    def test_state_gauge_and_transition_counter(self):
        board, _ = _board(threshold=1)
        before = obs_metrics.BREAKER_TRANSITIONS.labels(
            "gauge-peer", "open").value
        board.record_failure("gauge-peer")
        assert obs_metrics.BREAKER_STATE.labels(
            "gauge-peer").value == 2
        assert obs_metrics.BREAKER_TRANSITIONS.labels(
            "gauge-peer", "open").value == before + 1


# -- peer health EWMA ---------------------------------------------------------


class TestPeerHealth:
    def test_unknown_peer_scores_innocent(self):
        h = PeerHealth()
        assert h.score("nobody") == 1.0

    def test_failures_decay_the_score(self):
        h = PeerHealth()
        h.record("b", True, 0.01)
        assert h.score("b") > 0.9
        for _ in range(10):
            h.record("b", False)
        assert h.score("b") < 0.2
        for _ in range(20):
            h.record("b", True, 0.01)
        assert h.score("b") > 0.8, "recovery decays back up"

    def test_gossip_states_scale_the_score(self):
        h = PeerHealth()
        h.record("b", True, 0.01)
        h.note_gossip("b", "suspect")
        assert 0.4 < h.score("b") < 0.6
        h.note_gossip("b", "dead")
        assert h.score("b") == 0.0
        h.note_gossip("b", "alive")
        assert h.score("b") >= 0.5

    def test_latency_tail_tracks_mean_plus_deviation(self):
        h = PeerHealth()
        for _ in range(50):
            h.record("b", True, 0.010)
        tail = h.latency_tail("b")
        assert 0.009 < tail < 0.015, tail
        for _ in range(10):
            h.record("b", True, 0.100)  # a slow burst widens the tail
        assert h.latency_tail("b") > tail

    def test_snapshot_shape(self):
        h = PeerHealth()
        h.record("b", True, 0.01)
        snap = h.snapshot()["b"]
        for key in ("score", "okEwma", "latencyMs", "latencyTailMs",
                    "gossip", "samples", "failures", "successes"):
            assert key in snap


# -- FaultManager placement ordering ------------------------------------------


class TestFaultManagerOrdering:
    def test_equal_health_keeps_stable_order(self):
        fm = FaultManager(node="local")
        nodes = new_cluster(["a", "b", "c"]).nodes
        assert [n.host for n in fm.order_nodes(nodes)] == ["a", "b",
                                                          "c"]

    def test_local_node_first(self):
        fm = FaultManager(node="local")
        nodes = new_cluster(["a", "local", "b"]).nodes
        assert fm.order_nodes(nodes)[0].host == "local"

    def test_open_circuit_sinks_to_last_but_stays(self):
        fm = FaultManager(node="local")
        fm.breakers.force_open("a")
        nodes = new_cluster(["a", "b"]).nodes
        ordered = fm.order_nodes(nodes)
        assert [n.host for n in ordered] == ["b", "a"], \
            "open circuit sinks but is NOT dropped"

    def test_unhealthy_peer_ranks_below_healthy(self):
        fm = FaultManager(breaker_threshold=100, node="local")
        for _ in range(10):
            fm.record_rpc("a", False)
        nodes = new_cluster(["a", "b"]).nodes
        assert [n.host for n in fm.order_nodes(nodes)] == ["b", "a"]

    def test_gossip_dead_opens_breaker_immediately(self):
        fm = FaultManager(node="local")
        fm.note_gossip("b", "dead")
        assert not fm.allow("b")
        fm.note_gossip("b", "alive")
        assert fm.allow("b"), "alive refutation re-arms the probe"

    def test_hedge_delay_uses_latency_tail_above_floor(self):
        fm = FaultManager(hedge_s=0.01, node="local")
        assert fm.hedge_delay_s("b") == 0.01  # unobserved: the floor
        for _ in range(50):
            fm.record_rpc("b", True, 0.2)
        assert fm.hedge_delay_s("b") > 0.1
        assert FaultManager(node="local").hedge_delay_s("b") is None


# -- client integration -------------------------------------------------------


class _BrokenConn:
    sock = None
    timeout = None

    def __init__(self):
        self.closed = False

    def request(self, *a, **kw):
        raise OSError("broken socket")

    def close(self):
        self.closed = True


class TestClientFaultIntegration:
    def test_broken_pooled_conn_never_poisons_the_pool(self,
                                                       monkeypatch):
        """Satellite: a failed leg must drop its connection — the next
        _conn_get must never hand out the broken socket."""
        monkeypatch.setattr(http.client, "HTTPConnection", _GoodConn)
        c = Client("peer:1")
        broken = _BrokenConn()
        c._conn_put("peer:1", broken)
        status, _ = c._do("GET", "/schema")  # retries on a fresh conn
        assert status == 200
        assert broken.closed, "the broken socket must be closed"
        pooled = c._pool.get("peer:1", [])
        assert broken not in pooled
        assert all(isinstance(p, _GoodConn) for p in pooled)

    def test_any_exception_drops_the_conn(self, monkeypatch):
        """BaseException hygiene: an error escaping mid-request (not
        just HTTPException/OSError) must close the socket, not pool
        it."""
        monkeypatch.setattr(http.client, "HTTPConnection", _GoodConn)
        c = Client("peer:1")

        class Boom(BaseException):
            pass

        conn = _GoodConn("peer:1")

        def explode(*a, **kw):
            raise Boom()

        conn.request = explode
        c._conn_put("peer:1", conn)
        with pytest.raises(Boom):
            c._do("GET", "/schema")
        assert conn.closed
        assert conn not in c._pool.get("peer:1", [])

    def test_open_breaker_fails_fast(self):
        fm = FaultManager(node="me")
        fm.breakers.force_open("peer:1")
        c = Client("peer:1", fault=fm)
        t0 = time.perf_counter()
        with pytest.raises(CircuitOpenError):
            c._do("GET", "/schema")
        assert time.perf_counter() - t0 < 0.1, \
            "an open circuit must not pay any socket time"

    def test_outcomes_feed_health_and_breaker(self, monkeypatch):
        fm = FaultManager(breaker_threshold=2, node="me")
        monkeypatch.setattr(http.client, "HTTPConnection", _GoodConn)
        c = Client("peer:1", fault=fm)
        c._do("GET", "/schema")
        assert fm.health.snapshot()["peer:1"]["successes"] >= 1

        def refuse(host, timeout=None):
            conn = _GoodConn(host, timeout)
            conn.request = _BrokenConn().request
            return conn

        monkeypatch.setattr(http.client, "HTTPConnection", refuse)
        c._pool.clear()  # the pooled good socket would still answer
        for _ in range(2):  # threshold 2 consecutive failures
            with pytest.raises(ClientError):
                c._do("GET", "/schema")
        assert fm.breakers.state("peer:1") == STATE_OPEN
        with pytest.raises(CircuitOpenError):
            c._do("GET", "/schema")

    def test_budget_clamped_timeout_does_not_feed_breaker(self,
                                                          monkeypatch):
        """A healthy-but-80ms peer serving 50ms-deadline queries must
        not trip its breaker: a TIMEOUT that coincides with budget
        exhaustion blames the budget, not the peer."""
        fm = FaultManager(breaker_threshold=1, node="me")

        def hang(host, timeout=None):
            conn = _GoodConn(host, timeout)

            def slow_request(*a, **kw):
                time.sleep((timeout or 0.05) + 0.01)
                raise TimeoutError("timed out")

            conn.request = slow_request
            return conn

        monkeypatch.setattr(http.client, "HTTPConnection", hang)
        c = Client("peer:1", fault=fm, timeout=0.05)
        from pilosa_tpu.errors import QueryDeadlineError
        with pytest.raises(QueryDeadlineError):
            c._do("GET", "/schema", deadline_s=0.05)
        assert fm.breakers.state("peer:1") == STATE_CLOSED, \
            "deadline-clamped timeouts must not open the breaker"
        # The same timeout WITHOUT a deadline is the peer's fault.
        with pytest.raises(ClientError):
            c._do("GET", "/schema")
        assert fm.breakers.state("peer:1") == STATE_OPEN

    def test_import_retries_429_with_retry_after(self, monkeypatch):
        """Satellite: imports honor admission control's 429 +
        Retry-After with capped backoff instead of surfacing the
        first rejection."""
        c = Client("peer:1")
        script = [(429, b"busy", [("Retry-After", "0.01")]),
                  (429, b"busy", [("Retry-After", "0.01")]),
                  (200, b"", [])]
        calls = []

        def fake_do(method, path, body=None, headers=None, host=None,
                    idempotent=None, deadline_s=None, headers_out=None):
            status, raw, hs = script[len(calls)]
            calls.append((method, path))
            if headers_out is not None:
                headers_out.extend(hs)
            return status, raw

        sleeps = []
        monkeypatch.setattr(c, "_do", fake_do)
        monkeypatch.setattr(time, "sleep", sleeps.append)
        status, _ = c._do_429("POST", "/import", b"x", {}, None)
        assert status == 200
        assert len(calls) == 3
        assert len(sleeps) == 2
        assert all(s >= 0.01 for s in sleeps), \
            "waits are floored at the server's Retry-After"
        assert all(s <= Client._RETRY_429_CAP for s in sleeps)

    def test_429_retry_bounded_by_budget(self, monkeypatch):
        c = Client("peer:1", timeout=0.05)

        def always_429(method, path, body=None, headers=None,
                       host=None, idempotent=None, deadline_s=None,
                       headers_out=None):
            if headers_out is not None:
                headers_out.append(("Retry-After", "100"))
            return 429, b"busy"

        monkeypatch.setattr(c, "_do", always_429)
        t0 = time.perf_counter()
        status, _ = c._do_429("POST", "/import", b"x", {}, None)
        assert status == 429, "out of budget: the rejection surfaces"
        assert time.perf_counter() - t0 < 1.0


# -- anti-entropy skips dead peers --------------------------------------------


class TestSyncerBreakerSkip:
    def test_holder_syncer_peers_skip_open_circuits(self, holder):
        fm = FaultManager(node="local")
        fm.breakers.force_open("b")
        cluster = new_cluster(["local", "b", "c"])
        syncer = HolderSyncer(holder, "local", cluster, fault=fm)
        assert [n.host for n in syncer._peers()] == ["c"]

    def test_fragment_syncer_skips_open_circuit_replicas(self,
                                                         tmp_path):
        fm = FaultManager(node="local")
        fm.breakers.force_open("b")
        cluster = new_cluster(["local", "b", "c"], replica_n=3)
        f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        f.open()
        try:
            fs = FragmentSyncer(f, "local", cluster, fault=fm)
            peers = fs._replica_peers(cluster.fragment_nodes("i", 0))
            assert "b" not in [n.host for n in peers]
            assert "local" in [n.host for n in peers]
        finally:
            f.close()

    def test_peer_filter_does_not_consume_the_probe(self, holder):
        """_peers must use the side-effect-free consult: if the filter
        itself took the half-open probe slot, the syncer's own client
        would find it gone and skip the peer it just included —
        permanently wedging recovery."""
        fm = FaultManager(breaker_threshold=1, node="local")
        fm.record_rpc("b", False)  # open
        fm.breakers.note_probe_ready("b")  # window collapsed
        cluster = new_cluster(["local", "b"])
        syncer = HolderSyncer(holder, "local", cluster, fault=fm)
        assert [n.host for n in syncer._peers()] == ["b"]
        assert fm.breakers.state("b") == STATE_OPEN, \
            "the filter must not transition the breaker"
        assert fm.allow("b"), \
            "the probe slot is still there for the actual RPC"

    def test_attr_sync_survives_a_dead_peer(self, holder):
        """A ClientError from one peer must not abort the pass — the
        remaining peers still get consulted."""
        consulted = []

        def fetch_diff(client, blocks):
            consulted.append(client.host)
            if client.host == "b":
                raise ClientError("connection refused")
            return {}

        cluster = new_cluster(["local", "b", "c"])
        syncer = HolderSyncer(
            holder, "local", cluster,
            client_factory=lambda h: SimpleNamespace(host=h))
        store = SimpleNamespace(blocks=lambda: [],
                                set_bulk_attrs=lambda m: None)
        syncer._sync_attr_store(store, fetch_diff)  # must not raise
        assert consulted == ["b", "c"]


# -- executor: failover, breaker skip, partial, hedging -----------------------


class _FaultyClient:
    """Scripted transport that mimics the REAL client's fault-feed
    contract: failures against a down host raise ClientError AND
    record into the fault manager (cluster.client._do does both)."""

    def __init__(self, fault, down=(), slow=(), slow_s=0.0,
                 result_fn=None):
        self.fault = fault
        self.down = set(down)
        self.slow = set(slow)
        self.slow_s = slow_s
        self.calls = []

    def execute_query(self, node, index, query, slices, remote):
        self.calls.append((node.host, list(slices or [])))
        if node.host in self.down:
            if self.fault is not None:
                self.fault.record_rpc(node.host, False)
            raise ClientError(f"{node.host}: connection refused")
        if node.host in self.slow:
            time.sleep(self.slow_s)
        if self.fault is not None:
            self.fault.record_rpc(node.host, True, 0.001)
        return [len(slices or [])]


class TestExecutorFailover:
    def _cluster_executor(self, holder, hosts, replica_n, fault,
                          client, n_slices=8):
        cluster = new_cluster(hosts, replica_n=replica_n)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client, fault=fault)
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("general")
        holder.index("i").set_remote_max_slice(n_slices - 1)
        return e, cluster

    def test_first_failure_pays_next_query_skips(self, holder):
        """The ISSUE contract: the first query after a node dies pays
        the discovery; subsequent queries never touch the open
        circuit."""
        fm = FaultManager(breaker_threshold=1, node="local")
        client = _FaultyClient(fm, down={"b"})
        e, cluster = self._cluster_executor(
            holder, ["local", "b", "c"], 2, fm, client)
        down_slices = [
            s for s in range(8)
            if "b" in [n.host for n in cluster.fragment_nodes("i", s)]
            and "local" not in [n.host
                                for n in cluster.fragment_nodes("i", s)]]
        if not down_slices:
            pytest.skip("hash layout gave b no exclusive-remote slices")
        res = e.execute("i", "Count(Bitmap(rowID=1, frame=general))")
        assert res[0] >= 0  # failover produced a full answer
        b_calls_first = sum(1 for h, _ in client.calls if h == "b")
        assert b_calls_first >= 1, "the FIRST query discovers the death"
        assert fm.breakers.state("b") == STATE_OPEN

        client.calls.clear()
        res2 = e.execute("i", "Count(Bitmap(rowID=1, frame=general))")
        assert res2[0] == res[0]
        assert all(h != "b" for h, _ in client.calls), \
            "after the breaker opens, no query touches the dead peer"
        failover = obs_metrics.FAILOVER_SLICES.labels("b").value
        assert failover >= 1, "re-mapped slices are counted"

    def test_partial_skips_unreachable_slices(self, holder):
        must_set(holder, "i", "general", 1, 3)  # slice 0, local data
        client = _FaultyClient(None, down={"remotehost"})
        cluster = new_cluster(["local", "remotehost"], replica_n=1)
        e = Executor(holder, host="local", cluster=cluster,
                     client=client)
        holder.index("i").set_remote_max_slice(3)
        remote_slices = [
            s for s in range(4)
            if cluster.fragment_nodes("i", s)[0].host == "remotehost"]
        if not remote_slices:
            pytest.skip("hash layout put every slice on local")

        # Strict (default): the dead replica fails the query.
        with pytest.raises(ClientError):
            e.execute("i", "Count(Bitmap(rowID=1, frame=general))")

        # Degraded (?partial=1): local slices answer, missing
        # reported.
        opt = ExecOptions(partial=True, missing_slices=[])
        res = e.execute("i", "Count(Bitmap(rowID=1, frame=general))",
                        opt=opt)
        want = 0 if 0 in remote_slices else 1  # the bit lives in slice 0
        assert res[0] == want, "reachable slices still answer"
        assert sorted(opt.missing_slices) == remote_slices

    def test_partial_with_no_owner_at_all(self, holder):
        must_set(holder, "i", "general", 1, 3)
        cluster = new_cluster(["local", "gone"], replica_n=1)
        e = Executor(holder, host="local", cluster=cluster, client=None)
        holder.index("i").set_remote_max_slice(3)
        # Drop the remote node entirely: its slices have NO owner in
        # the surviving node list.
        cluster.nodes = [n for n in cluster.nodes if n.host == "local"]
        opt = ExecOptions(partial=True, missing_slices=[])
        res = e.execute("i", "Count(Bitmap(rowID=1, frame=general))",
                        opt=opt)
        assert res[0] == 1

    def test_hedged_read_beats_a_slow_primary(self, holder):
        fm = FaultManager(hedge_s=0.05, node="local")
        client = _FaultyClient(fm, slow={"b"}, slow_s=1.5)
        e, cluster = self._cluster_executor(
            holder, ["local", "b", "c"], 2, fm, client)
        hedgeable = [
            s for s in range(8)
            if cluster.fragment_nodes("i", s)[0].host == "b"
            and "local" not in [n.host
                                for n in cluster.fragment_nodes("i", s)]]
        if not hedgeable:
            pytest.skip("hash layout gave b no primary-remote slices")
        before = obs_metrics.HEDGED_REQUESTS.labels("fired").value
        t0 = time.perf_counter()
        res = e.execute("i", "Count(Bitmap(rowID=1, frame=general))")
        elapsed = time.perf_counter() - t0
        assert res[0] >= len(hedgeable)
        assert elapsed < 1.0, (
            f"hedge must beat the 1.5s primary, took {elapsed:.2f}s")
        assert obs_metrics.HEDGED_REQUESTS.labels("fired").value \
            > before

    def test_slices_by_node_orders_by_health(self, holder):
        fm = FaultManager(breaker_threshold=100, node="local")
        for _ in range(10):
            fm.record_rpc("b", False)  # unhealthy but not open
        client = _FaultyClient(fm)
        e, cluster = self._cluster_executor(
            holder, ["local", "b", "c"], 2, fm, client)
        for s in range(8):
            owners = [n.host
                      for n in cluster.fragment_nodes("i", s)]
            if set(owners) == {"b", "c"}:
                groups = e._slices_by_node(cluster.nodes, "i", [s])
                assert groups[0][0].host == "c", \
                    "healthy replica outranks the failing one"
                return
        pytest.skip("hash layout gave no {b,c} slice")


# -- /debug/failpoints over HTTP ----------------------------------------------


def call(app, method, path, body=b"", content_type=""):
    if "?" in path:
        path, _, qs = path.partition("?")
    else:
        qs = ""
    environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
               "QUERY_STRING": qs, "CONTENT_LENGTH": str(len(body)),
               "wsgi.input": io.BytesIO(body)}
    if content_type:
        environ["CONTENT_TYPE"] = content_type
    out = {}

    def start_response(status, headers):
        out["status"] = int(status.split()[0])
        out["headers"] = dict(headers)

    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


class TestFailpointHTTP:
    def test_get_lists_schedule_and_seed(self):
        h = Handler(None, None)
        status, _, body = call(h, "GET", "/debug/failpoints")
        assert status == 200
        got = json.loads(body)
        assert "seed" in got and "armed" in got
        assert set(got["sites"]) == set(failpoints.SITES)

    def test_post_arms_and_off_disarms(self):
        h = Handler(None, None)
        status, _, body = call(
            h, "POST", "/debug/failpoints",
            json.dumps({"site": "rpc.send",
                        "spec": "error(0.5)*3"}).encode())
        assert status == 200
        assert "rpc.send" in json.loads(body)["armed"]
        assert failpoints.ACTIVE is not None
        status, _, body = call(
            h, "POST", "/debug/failpoints",
            json.dumps({"failpoints": {"rpc.send": "off"}}).encode())
        assert status == 200
        assert json.loads(body)["armed"] == {}

    def test_post_validates_before_arming_anything(self):
        h = Handler(None, None)
        status, _, _ = call(
            h, "POST", "/debug/failpoints",
            json.dumps({"failpoints": {"rpc.send": "error",
                                       "bogus.site": "error"}}).encode())
        assert status == 400
        assert failpoints.default().snapshot()["armed"] == {}, \
            "a bulk update must not half-apply"
        status, _, _ = call(
            h, "POST", "/debug/failpoints",
            json.dumps({"site": "rpc.send", "spec": "nope"}).encode())
        assert status == 400
        status, _, _ = call(h, "POST", "/debug/failpoints", b"{}")
        assert status == 400

    def test_partial_header_rides_the_response(self, holder):
        class StubExecutor:
            def execute(self, index, query, slices=None, opt=None):
                if opt is not None and opt.partial:
                    opt.missing_slices.extend([3, 1])
                return [0]

        h = Handler(holder, StubExecutor(), host="local")
        status, headers, _ = call(
            h, "POST", "/index/i/query?partial=1",
            b'Count(Bitmap(rowID=1, frame="general"))')
        assert status == 200
        assert headers.get("X-Pilosa-Partial") == "1,3"
        status, headers, _ = call(
            h, "POST", "/index/i/query",
            b'Count(Bitmap(rowID=1, frame="general"))')
        assert status == 200
        assert "X-Pilosa-Partial" not in headers

    def test_status_carries_the_fault_block(self, holder):
        fm = FaultManager(node="local")
        fm.record_rpc("b", True, 0.01)
        fm.breakers.force_open("c")
        h = Handler(holder, None, host="local",
                    cluster=new_cluster(["local", "b", "c"]), fault=fm)
        status, _, body = call(h, "GET", "/status")
        assert status == 200
        fault = json.loads(body)["fault"]
        assert fault["peers"]["b"]["successes"] == 1
        assert fault["breakers"]["c"]["state"] == STATE_OPEN
