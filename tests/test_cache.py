"""Cache tests (reference cache_test.go semantics)."""

from pilosa_tpu.storage.cache import (LRUCache, Pair, RankCache, SimpleCache,
                                      pairs_add, pairs_sort, top_n_heap_merge)


class TestRankCache:
    def test_add_get_top(self):
        c = RankCache(max_entries=10)
        for i, n in [(1, 5), (2, 9), (3, 1)]:
            c.add(i, n)
        c.recalculate()
        assert [p.id for p in c.top()] == [2, 1, 3]
        assert c.get(2) == 9

    def test_threshold_trims_overflow(self):
        c = RankCache(max_entries=5)
        for i in range(20):
            c.bulk_add(i, i + 1)
        c.recalculate()
        top = c.top()
        assert len(top) == 5
        assert [p.count for p in top] == [20, 19, 18, 17, 16]
        # adds below the new threshold are ignored
        before = len(c)
        c.add(99, 1)
        assert len(c) == before

    def test_ids_sorted(self):
        c = RankCache()
        for i in (5, 1, 9):
            c.bulk_add(i, 10)
        assert c.ids() == [1, 5, 9]


class TestLRUCache:
    def test_eviction(self):
        c = LRUCache(max_entries=2)
        c.add(1, 10)
        c.add(2, 20)
        c.get(1)        # refresh 1
        c.add(3, 30)    # evicts 2
        assert c.get(2) == 0
        assert c.get(1) == 10 and c.get(3) == 30


class TestPairs:
    def test_pairs_add_merges_counts(self):
        a = [Pair(1, 5), Pair(2, 3)]
        b = [Pair(2, 4), Pair(3, 1)]
        merged = {p.id: p.count for p in pairs_add(a, b)}
        assert merged == {1: 5, 2: 7, 3: 1}

    def test_sort_ties_by_id(self):
        got = pairs_sort([Pair(3, 5), Pair(1, 5), Pair(2, 9)])
        assert [p.id for p in got] == [2, 1, 3]

    def test_top_n_heap_merge(self):
        got = top_n_heap_merge([[Pair(1, 5)], [Pair(1, 2), Pair(2, 6)]], 1)
        assert got == [Pair(1, 7)]


class TestSimpleCache:
    def test_fetch_invalidate(self):
        c = SimpleCache()
        c.add(1, "bm")
        assert c.fetch(1) == "bm"
        c.invalidate(1)
        assert c.fetch(1) is None
