"""Bit-sliced integer fields (BSI): engine, schema, PQL, executor,
HTTP, and device legs.

The engine test is differential against a brute-force dict-of-ints
model over every operator and every predicate in (and beyond) the
domain; the executor test drives the full PQL → executor → storage
stack single-node; the generative test interleaves random value
writes/imports with Range/Sum/Min/Max queries against the model; the
kernel tests pin the XLA circuit to its numpy twin. The 2-node cluster
merge proof lives in test_bsi_cluster.py.
"""

import io
import json
import random

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.errors import PilosaError
from pilosa_tpu.executor import Executor
from pilosa_tpu.models.frame import Field, Frame, FrameOptions
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.pql.ast import Condition
from pilosa_tpu.pql.parser import parse
from pilosa_tpu.storage import bsi
from pilosa_tpu.storage.bitmap import Bitmap


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def executor(holder):
    ex = Executor(holder, host="local", use_mesh=False)
    yield ex
    ex.close()


def field_frame(holder, min_v=0, max_v=100, name="v"):
    idx = holder.create_index_if_not_exists("i")
    frame = idx.create_frame_if_not_exists("f")
    frame.create_field(Field(name, min_v, max_v))
    return frame


# -- engine vs brute force ----------------------------------------------------


class TestEngine:
    @pytest.mark.parametrize("mn,mx", [(0, 100), (-50, 37), (10, 10),
                                       (5, 6)])
    def test_all_ops_all_predicates_match_brute_force(self, mn, mx):
        rng = random.Random(7)
        depth = bsi.bit_depth(mn, mx)
        vals = {c: rng.randint(mn, mx) for c in range(80)
                if rng.random() < 0.7}
        planes = {bsi.EXISTS_PLANE: Bitmap(*vals.keys())}
        for i in range(depth):
            planes[i] = Bitmap(*[c for c, v in vals.items()
                                 if ((v - mn) >> i) & 1])

        def row(i):
            return planes[i]

        ops = {"<": lambda v, p: v < p, "<=": lambda v, p: v <= p,
               ">": lambda v, p: v > p, ">=": lambda v, p: v >= p,
               "==": lambda v, p: v == p, "!=": lambda v, p: v != p}
        for op, fn in ops.items():
            for p in range(mn - 3, mx + 4):
                got = bsi.range_bitmap(op, p, mn, mx, row)
                got_set = (set() if got is None
                           else set(got.bits().tolist()))
                want = {c for c, v in vals.items() if fn(v, p)}
                assert got_set == want, (op, p)
        for lo in range(mn - 2, mx + 3, 3):
            for hi in range(lo - 1, mx + 3, 3):
                got = bsi.range_bitmap("><", (lo, hi), mn, mx, row)
                got_set = (set() if got is None
                           else set(got.bits().tolist()))
                assert got_set == {c for c, v in vals.items()
                                   if lo <= v <= hi}, (lo, hi)

        sc = bsi.sum_count(mn, mx, row)
        assert (sc.value, sc.count) == (sum(vals.values()), len(vals))
        if vals:
            m = bsi.min_max(mn, mx, row, want_min=True)
            assert m.value == min(vals.values())
            assert m.count == sum(1 for v in vals.values()
                                  if v == m.value)
            m = bsi.min_max(mn, mx, row, want_min=False)
            assert m.value == max(vals.values())

    def test_combine_min_max_merge(self):
        a = bsi.ValCount(5, 2)
        b = bsi.ValCount(5, 3)
        assert bsi.combine_min_max(a, b).count == 5
        assert bsi.combine_min_max(a, bsi.ValCount(4, 1)).value == 4
        assert bsi.combine_min_max(
            a, bsi.ValCount(9, 1), want_min=False).value == 9
        # empty sides are identity
        assert bsi.combine_min_max(bsi.ValCount(0, 0), a) == a
        assert bsi.combine_min_max(a, bsi.ValCount(0, 0)) == a

    def test_depth_and_validation(self):
        assert bsi.bit_depth(0, 0) == 0
        assert bsi.bit_depth(0, 1) == 1
        assert bsi.bit_depth(-10, 100) == 7
        with pytest.raises(PilosaError):
            bsi.bit_depth(5, 4)
        with pytest.raises(PilosaError):
            Field("v", 0, 1 << 63)


# -- PQL conditions -----------------------------------------------------------


class TestConditionSyntax:
    @pytest.mark.parametrize("op", ["==", "!=", "<", "<=", ">", ">="])
    def test_roundtrip(self, op):
        q = parse(f'Range(frame="f", age {op} -7)')
        c = q.calls[0]
        assert c.args["age"] == Condition(op, -7)
        assert parse(str(c)).calls[0] == c

    def test_between_roundtrip(self):
        c = parse('Range(frame="f", v >< [3, 9])').calls[0]
        assert c.args["v"] == Condition("><", [3, 9])
        assert parse(str(c)).calls[0] == c

    def test_condition_arg_helper(self):
        c = parse('Range(frame="f", v > 2)').calls[0]
        assert c.condition_arg() == ("v", Condition(">", 2))
        assert parse('Bitmap(rowID=1)').calls[0].condition_arg() is None

    @pytest.mark.parametrize("bad", [
        'Range(frame="f", v >< 5)',
        'Range(frame="f", v >< [1])',
        'Range(frame="f", v > "x")',
        'Range(frame="f", v > 1.5)',
    ])
    def test_rejects_malformed(self, bad):
        with pytest.raises(PilosaError):
            parse(bad)

    def test_sum_form_parses(self):
        c = parse('Sum(Bitmap(rowID=1, frame="g"), frame="f",'
                  ' field="v")').calls[0]
        assert c.name == "Sum" and len(c.children) == 1
        assert c.args["field"] == "v"


# -- frame schema / writes ----------------------------------------------------


class TestFrameFields:
    def test_create_persist_reopen(self, tmp_path):
        f = Frame(str(tmp_path / "f"), "i", "f")
        f.open()
        f.create_field(Field("age", -10, 100))
        f.set_field_value("age", 5, 42)
        f.close()
        f2 = Frame(str(tmp_path / "f"), "i", "f")
        f2.open()
        assert f2.field("age") == Field("age", -10, 100)
        assert f2.field_value("age", 5) == (42, True)
        f2.close()

    def test_create_conflicting_range_rejected(self, tmp_path):
        f = Frame(str(tmp_path / "f"), "i", "f")
        f.open()
        f.create_field(Field("age", 0, 10))
        f.create_field(Field("age", 0, 10))  # idempotent
        with pytest.raises(PilosaError, match="different range"):
            f.create_field(Field("age", 0, 11))
        f.close()

    def test_set_value_overwrites_planes(self, tmp_path):
        f = Frame(str(tmp_path / "f"), "i", "f")
        f.open()
        f.create_field(Field("v", 0, 127))
        assert f.set_field_value("v", 1, 127)
        assert f.set_field_value("v", 1, 0)  # clears every 1-plane
        assert f.field_value("v", 1) == (0, True)
        assert not f.set_field_value("v", 1, 0)  # idempotent
        with pytest.raises(PilosaError, match="out of range"):
            f.set_field_value("v", 1, 128)
        f.close()

    def test_bulk_import_last_wins_and_overwrites(self, tmp_path):
        f = Frame(str(tmp_path / "f"), "i", "f")
        f.open()
        f.create_field(Field("v", -5, 50))
        f.import_field_values(
            "v", np.array([1, 2, 1, SLICE_WIDTH + 3], dtype=np.uint64),
            np.array([7, -5, 50, 12], dtype=np.int64))
        assert f.field_value("v", 1) == (50, True)  # last wins
        assert f.field_value("v", 2) == (-5, True)
        assert f.field_value("v", SLICE_WIDTH + 3) == (12, True)
        f.import_field_values("v", [1], [0])  # stale planes cleared
        assert f.field_value("v", 1) == (0, True)
        assert f.max_slice() == 1  # field views drive slice discovery
        with pytest.raises(PilosaError, match="out of range"):
            f.import_field_values("v", [9], [51])
        f.close()


# -- executor, single node ----------------------------------------------------


class TestExecutorBSI:
    def test_range_sum_min_max_end_to_end(self, holder, executor):
        field_frame(holder, 0, 100)
        vals = {3: 10, 5: 42, SLICE_WIDTH + 7: 42,
                2 * SLICE_WIDTH + 1: 99, 8: 0}
        for c, v in vals.items():
            r = executor.execute(
                "i", f'SetFieldValue(frame="f", columnID={c}, v={v})')
            assert r[0] is True
        assert executor.execute(
            "i", 'SetFieldValue(frame="f", columnID=3, v=10)')[0] is False

        res = executor.execute("i", 'Range(frame="f", v > 30)')[0]
        assert sorted(res.bits().tolist()) == sorted(
            c for c, v in vals.items() if v > 30)
        res = executor.execute("i", 'Range(frame="f", v == 42)')[0]
        assert sorted(res.bits().tolist()) == [5, SLICE_WIDTH + 7]
        res = executor.execute("i", 'Range(frame="f", v >< [10, 42])')[0]
        assert sorted(res.bits().tolist()) == [3, 5, SLICE_WIDTH + 7]
        assert executor.execute(
            "i", 'Count(Range(frame="f", v <= 10))')[0] == 2

        s = executor.execute("i", 'Sum(frame="f", field="v")')[0]
        assert (s.value, s.count) == (sum(vals.values()), len(vals))
        m = executor.execute("i", 'Min(frame="f", field="v")')[0]
        assert (m.value, m.count) == (0, 1)
        m = executor.execute("i", 'Max(frame="f", field="v")')[0]
        assert (m.value, m.count) == (99, 1)

    def test_filtered_aggregates_and_compose(self, holder, executor):
        frame = field_frame(holder, 0, 100)
        for c, v in {3: 10, 5: 42, 8: 0, 9: 77}.items():
            frame.set_field_value("v", c, v)
        for c in (3, 5, 8):
            executor.execute(
                "i", f'SetBit(frame="f", rowID=1, columnID={c})')
        s = executor.execute(
            "i", 'Sum(Bitmap(frame="f", rowID=1), frame="f",'
                 ' field="v")')[0]
        assert (s.value, s.count) == (52, 3)
        m = executor.execute(
            "i", 'Max(Bitmap(frame="f", rowID=1), frame="f",'
                 ' field="v")')[0]
        assert (m.value, m.count) == (42, 1)
        res = executor.execute(
            "i", 'Intersect(Range(frame="f", v >= 10),'
                 ' Bitmap(frame="f", rowID=1))')[0]
        assert sorted(res.bits().tolist()) == [3, 5]
        # a field Range inside Count inside Union
        n = executor.execute(
            "i", 'Count(Union(Range(frame="f", v == 0),'
                 ' Range(frame="f", v >= 77)))')[0]
        assert n == 2

    def test_errors(self, holder, executor):
        field_frame(holder, 0, 100)
        for bad, msg in [
            ('Range(frame="f", nope > 3)', "field not found"),
            ('Sum(frame="f", field="nope")', "field not found"),
            ('Sum(frame="f")', "field required"),
            ('SetFieldValue(frame="f", columnID=1, v=101)',
             "out of range"),
            ('SetFieldValue(frame="f", columnID=1)',
             "exactly one field"),
            ('SetFieldValue(columnID=1, v=3)', "frame required"),
        ]:
            with pytest.raises(PilosaError, match=msg):
                executor.execute("i", bad)

    def test_empty_and_all_clamps(self, holder, executor):
        frame = field_frame(holder, 10, 20)
        frame.set_field_value("v", 1, 15)
        assert executor.execute(
            "i", 'Count(Range(frame="f", v < 5))')[0] == 0
        assert executor.execute(
            "i", 'Count(Range(frame="f", v < 100))')[0] == 1
        assert executor.execute(
            "i", 'Count(Range(frame="f", v != 999))')[0] == 1
        s = executor.execute("i", 'Min(frame="f", field="v")')[0]
        assert (s.value, s.count) == (15, 1)

    def test_aggregate_on_empty_field(self, holder, executor):
        field_frame(holder, 0, 100)
        s = executor.execute("i", 'Sum(frame="f", field="v")')[0]
        assert (s.value, s.count) == (0, 0)
        m = executor.execute("i", 'Min(frame="f", field="v")')[0]
        assert m.count == 0


# -- generative differential vs dict-of-ints model ---------------------------


def test_differential_random_ops_match_model(holder):
    """Random SetFieldValue / bulk imports / overwrites interleaved
    with Range/Sum/Min/Max on a 3-slice domain must match a plain
    dict-of-ints model exactly at every step (satellite: BSI engine
    differential)."""
    ex = Executor(holder, host="local", use_mesh=False)
    mn, mx = -20, 200
    frame = field_frame(holder, mn, mx)
    rng = np.random.default_rng(42)
    model: dict[int, int] = {}
    n_cols = 3 * SLICE_WIDTH

    import operator
    op_fns = {"<": operator.lt, "<=": operator.le, ">": operator.gt,
              ">=": operator.ge, "==": operator.eq, "!=": operator.ne}

    def check(step):
        op = ("<", "<=", ">", ">=", "==", "!=")[
            int(rng.integers(0, 6))]
        p = int(rng.integers(mn - 5, mx + 6))
        got = ex.execute("i", f'Range(frame="f", v {op} {p})')[0]
        want = {c for c, v in model.items() if op_fns[op](v, p)}
        assert set(got.bits().tolist()) == want, (step, op, p)
        s = ex.execute("i", 'Sum(frame="f", field="v")')[0]
        assert (s.value, s.count) == (sum(model.values()), len(model)), step
        if model:
            m = ex.execute("i", 'Min(frame="f", field="v")')[0]
            assert m.value == min(model.values()), step
            m = ex.execute("i", 'Max(frame="f", field="v")')[0]
            assert m.value == max(model.values()), step

    for step in range(60):
        kind = int(rng.integers(0, 3))
        if kind == 0:  # point write (often overwriting)
            c = int(rng.integers(0, n_cols))
            v = int(rng.integers(mn, mx + 1))
            ex.execute(
                "i", f'SetFieldValue(frame="f", columnID={c}, v={v})')
            model[c] = v
        elif kind == 1:  # bulk import
            k = int(rng.integers(1, 120))
            cols = rng.integers(0, n_cols, k).astype(np.uint64)
            vals = rng.integers(mn, mx + 1, k).astype(np.int64)
            frame.import_field_values("v", cols, vals)
            for c, v in zip(cols.tolist(), vals.tolist()):
                model[c] = v
        else:
            check(step)
    check("final")
    ex.close()


# -- wire codec ---------------------------------------------------------------


class TestWire:
    def test_valcount_proto_roundtrip(self):
        from pilosa_tpu.server import codec
        resp = codec.encode_query_response(
            [bsi.ValCount(-7, 3), True, 5])
        from pilosa_tpu.proto import internal_pb2 as pb
        back = pb.QueryResponse.FromString(resp.SerializeToString())
        out = codec.decode_query_results(
            back, ["Sum", "SetFieldValue", "Count"])
        assert out == [bsi.ValCount(-7, 3), True, 5]

    def test_valcount_json(self):
        from pilosa_tpu.server import codec
        assert codec.result_to_json(bsi.ValCount(9, 2)) == {
            "value": 9, "count": 2}


# -- HTTP handler -------------------------------------------------------------


def wsgi_call(app, method, path, body=b"", content_type="", accept=""):
    qs = ""
    if "?" in path:
        path, _, qs = path.partition("?")
    environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
               "QUERY_STRING": qs, "CONTENT_LENGTH": str(len(body)),
               "wsgi.input": io.BytesIO(body)}
    if content_type:
        environ["CONTENT_TYPE"] = content_type
    if accept:
        environ["HTTP_ACCEPT"] = accept
    out = {}

    def start_response(status, headers):
        out["status"] = int(status.split()[0])
    chunks = app(environ, start_response)
    return out["status"], b"".join(chunks)


class TestHandlerFields:
    @pytest.fixture
    def app(self, holder):
        from pilosa_tpu.server.handler import Handler
        ex = Executor(holder, host="local", use_mesh=False)
        yield Handler(holder, ex, host="local")
        ex.close()

    def test_field_lifecycle_over_http(self, app):
        assert wsgi_call(app, "POST", "/index/i", b"{}")[0] == 200
        body = json.dumps({"options": {"fields": [
            {"name": "qty", "min": 0, "max": 1000}]}}).encode()
        assert wsgi_call(app, "POST", "/index/i/frame/f", body)[0] == 200
        s, _ = wsgi_call(app, "POST", "/index/i/frame/f/field/price",
                         json.dumps({"min": -100, "max": 100}).encode())
        assert s == 200
        s, b = wsgi_call(app, "GET", "/index/i/frame/f/fields")
        assert json.loads(b)["fields"] == [
            {"name": "qty", "min": 0, "max": 1000},
            {"name": "price", "min": -100, "max": 100}]

        # JSON value import → query back over HTTP
        s, b = wsgi_call(
            app, "POST", "/index/i/frame/f/field/price/import",
            json.dumps({"columns": [1, 2, SLICE_WIDTH + 3],
                        "values": [-50, 10, 99]}).encode())
        assert s == 200, b
        s, b = wsgi_call(app, "POST", "/index/i/query",
                         b'Range(frame="f", price > 0)')
        assert json.loads(b)["results"][0]["bits"] == [2, SLICE_WIDTH + 3]
        s, b = wsgi_call(app, "POST", "/index/i/query",
                         b'Sum(frame="f", field="price")')
        assert json.loads(b)["results"][0] == {"value": 59, "count": 3}

        # protobuf import + protobuf query response
        from pilosa_tpu.proto import internal_pb2 as pb
        req = pb.ImportValueRequest(Index="i", Frame="f", Field="qty",
                                    Slice=0, ColumnIDs=[1, 2],
                                    Values=[5, 7])
        s, b = wsgi_call(app, "POST",
                         "/index/i/frame/f/field/qty/import",
                         req.SerializeToString(),
                         content_type="application/x-protobuf",
                         accept="application/x-protobuf")
        assert s == 200, b
        s, b = wsgi_call(app, "POST", "/index/i/query",
                         b'Max(frame="f", field="qty")',
                         accept="application/x-protobuf")
        resp = pb.QueryResponse.FromString(b)
        assert (resp.Results[0].ValCount.Val,
                resp.Results[0].ValCount.Count) == (7, 1)

        # schema surfaces the fields
        s, b = wsgi_call(app, "GET", "/schema")
        frames = json.loads(b)["indexes"][0]["frames"]
        assert {f["name"] for f in frames[0]["fields"]} == \
            {"qty", "price"}

    def test_field_error_statuses(self, app):
        wsgi_call(app, "POST", "/index/i", b"{}")
        wsgi_call(app, "POST", "/index/i/frame/f", b"{}")
        s, _ = wsgi_call(app, "POST", "/index/i/frame/f/field/b",
                         json.dumps({"min": 5, "max": 1}).encode())
        assert s == 400
        s, _ = wsgi_call(app, "POST", "/index/i/frame/f/field/b",
                         json.dumps({"bogus": 1}).encode())
        assert s == 400
        s, _ = wsgi_call(app, "POST",
                         "/index/i/frame/nope/field/x/import", b"{}")
        assert s == 404
        s, _ = wsgi_call(app, "POST",
                         "/index/i/frame/f/field/nope/import",
                         json.dumps({"columns": [1],
                                     "values": [1]}).encode())
        assert s == 404


# -- device kernels / mesh ----------------------------------------------------


class TestDeviceCircuit:
    def test_xla_circuit_matches_numpy_twin(self):
        import jax.numpy as jnp

        from pilosa_tpu.ops import kernels
        rng = np.random.default_rng(0)
        depth = 7
        planes = rng.integers(0, 2**32, size=(depth + 1, 2, 64),
                              dtype=np.uint32)
        planes[0] |= planes[1:].max(axis=0)  # exists ⊇ every plane
        for op in kernels.BSI_OPS:
            for upred in (0, 1, 37, 127):
                want = kernels.bsi_compare_words_host(op, upred, planes)
                got = np.asarray(kernels.bsi_compare_words(
                    op, kernels.bsi_predicate_bits(upred, depth),
                    jnp.asarray(planes)))
                assert (got == want).all(), (op, upred)

    def test_circuit_semantics_against_decoded_values(self):
        from pilosa_tpu.ops import kernels
        rng = np.random.default_rng(3)
        depth = 6
        planes = rng.integers(0, 2**32, size=(depth + 1, 1, 32),
                              dtype=np.uint32)
        planes[0] = 0xFFFFFFFF
        vals = np.zeros(32 * 32, dtype=np.int64)
        for i in range(depth):
            bits = np.unpackbits(planes[1 + i].view(np.uint8),
                                 bitorder="little")
            vals += bits.astype(np.int64) << i
        for op, fn in (("<", np.less), (">=", np.greater_equal),
                       ("==", np.equal)):
            got = kernels.bsi_compare_words_host(op, 21, planes)
            gotbits = np.unpackbits(got.view(np.uint8),
                                    bitorder="little").astype(bool)
            assert (gotbits == fn(vals, 21)).all(), op


def _has_shard_map() -> bool:
    import jax
    if hasattr(jax, "shard_map"):
        return True
    try:
        from jax.experimental.shard_map import shard_map  # noqa: F401
        return True
    except ImportError:
        return False


@pytest.mark.skipif(not _has_shard_map(),
                    reason="no shard_map in this jax")
class TestMeshBSI:
    def test_bsi_range_sharded_matches_host(self):
        from pilosa_tpu.ops import kernels
        from pilosa_tpu.parallel import mesh as mesh_mod
        mesh = mesh_mod.make_mesh(1)
        rng = np.random.default_rng(1)
        depth = 5
        n_slices, words = 4, 256
        planes = rng.integers(0, 2**32,
                              size=(depth + 1, n_slices, words),
                              dtype=np.uint32)
        planes[0] |= planes[1:].max(axis=0)
        arrs = [mesh_mod.shard_slices(mesh, planes[i])
                for i in range(depth + 1)]
        for op in ("<", ">=", "==", "!="):
            got = mesh_mod.bsi_range_sharded(mesh, op, 11, depth, arrs)
            want = kernels.bsi_compare_words_host(op, 11, planes)
            assert (got == want).all(), op
        got = mesh_mod.bsi_range_sharded(mesh, "><", (3, 19), depth,
                                         arrs)
        want = (kernels.bsi_compare_words_host(">=", 3, planes)
                & kernels.bsi_compare_words_host("<=", 19, planes))
        assert (got == want).all()

    def test_executor_device_legs_match_host(self, holder):
        """Acceptance (c): Range/Count/Sum through the mesh leg agree
        with the host path on the same data."""
        frame = field_frame(holder, -10, 50)
        rng = np.random.default_rng(5)
        cols = np.arange(0, 3 * SLICE_WIDTH, 401, dtype=np.uint64)
        vals = rng.integers(-10, 51, len(cols)).astype(np.int64)
        frame.import_field_values("v", cols, vals)
        host = Executor(holder, host="local", use_mesh=False)
        dev = Executor(holder, host="local", use_mesh=True,
                       mesh_min_slices=1)
        dev._cost_model_enabled = False
        try:
            for q in ('Range(frame="f", v > 17)',
                      'Count(Range(frame="f", v <= 0))',
                      'Sum(frame="f", field="v")',
                      'Sum(Range(frame="f", v >= 25), frame="f",'
                      ' field="v")'):
                got = dev.execute("i", q)[0]
                want = host.execute("i", q)[0]
                if hasattr(got, "bits"):
                    assert got.bits().tolist() == want.bits().tolist(), q
                else:
                    assert got == want, q
            assert dev.device_fallbacks == 0
        finally:
            host.close()
            dev.close()
