"""Many-node SWIM convergence under injected datagram loss.

The regime SWIM exists for (memberlist gets this hardening free,
reference gossip/gossip.go:48-54): with real packet loss and asymmetry,
indirect probes + the suspicion window must prevent false deaths, a
real death must still be detected in bounded time, and a wrong
suspicion must clear via refutation. Deterministic seeds, loopback
sockets, HMAC (with replay binding) on across the whole harness.
"""

import random
import time

from test_gossip import wait_until

from pilosa_tpu.cluster.gossip import (GossipNodeSet, Member,
                                       STATE_ALIVE, STATE_SUSPECT)

KEY = b"convergence-harness-key"


def make_cluster(n: int, loss: float, seed: int, probe: float = 0.08,
                 **kw):
    """n gossip nodes on loopback, each datagram dropped with
    probability ``loss`` (deterministic per-node RNG)."""
    nodes: list[GossipNodeSet] = []
    first_addr = None
    for i in range(n):
        g = GossipNodeSet(
            f"host{i:02d}:10101", gossip_host="127.0.0.1:0",
            seeds=[first_addr] if first_addr else [],
            probe_interval=probe, probe_timeout=probe * 2,
            push_pull_interval=0.5, suspect_after=2,
            secret_key=KEY, replay_window=30.0, **kw)
        rng = random.Random(seed * 1000 + i)
        g.loss_filter = (lambda addr, pkt, _rng=rng:
                         _rng.random() < loss)
        g.open()
        if first_addr is None:
            first_addr = g.gossip_host
        nodes.append(g)
    return nodes


def alive_view(g: GossipNodeSet) -> set[str]:
    return {n.host for n in g.nodes()}


def test_no_false_deaths_at_20pct_loss_then_real_death_converges():
    """Phase A: 12 nodes at 20% symmetric loss — nobody may be declared
    dead while everybody is alive (indirect probes + suspicion window
    doing their job). Phase B: one node actually dies; every survivor
    must converge on its absence in bounded time despite the loss."""
    nodes = make_cluster(12, loss=0.20, seed=7)
    try:
        want = {g.host for g in nodes}
        assert wait_until(
            lambda: all(alive_view(g) == want for g in nodes),
            timeout=20.0), "full membership did not converge"

        # Phase A: hold for ~50 probe periods, sampling continuously.
        deadline = time.monotonic() + 4.0
        while time.monotonic() < deadline:
            for g in nodes:
                missing = want - alive_view(g)
                assert not missing, (
                    f"{g.host} falsely declared {missing} dead at 20%"
                    " loss")
            time.sleep(0.2)

        # Phase B: node 11 really dies.
        victim = nodes[-1]
        victim_name = victim.host
        victim.close()
        survivors = nodes[:-1]
        want_b = want - {victim_name}
        assert wait_until(
            lambda: all(alive_view(g) == want_b for g in survivors),
            timeout=20.0), (
            "survivors did not converge on the real death: " + repr(
                [sorted(alive_view(g)) for g in survivors
                 if alive_view(g) != want_b][:3]))
    finally:
        for g in nodes:
            g.close()


def test_wrong_suspicion_refuted_under_loss():
    """A live node wrongly suspected (rumor injected at several peers)
    must clear via refutation — never progressing to dead — even at 20%
    loss. The refutation is visible as an incarnation bump."""
    nodes = make_cluster(6, loss=0.20, seed=11)
    try:
        want = {g.host for g in nodes}
        assert wait_until(
            lambda: all(alive_view(g) == want for g in nodes),
            timeout=20.0)
        target = nodes[3]
        inc0 = target._member_snapshot(target.host).incarnation
        rumor = Member(target.host, target.gossip_host, inc0,
                       STATE_SUSPECT)
        for accuser in (nodes[0], nodes[1], nodes[5]):
            accuser._merge_member(Member(rumor.name, rumor.addr,
                                         rumor.incarnation,
                                         rumor.state))
        # Refutation: the target re-announces alive with a bumped
        # incarnation and every accuser flips it back.
        assert wait_until(
            lambda: all(
                g._member_snapshot(target.host).state == STATE_ALIVE
                for g in nodes), timeout=15.0), (
            "wrong suspicion did not clear")
        assert target._member_snapshot(target.host).incarnation > inc0
        # And nobody ever dropped it from membership.
        for g in nodes:
            assert target.host in alive_view(g)
    finally:
        for g in nodes:
            g.close()


def test_asymmetric_partition_does_not_kill_at_scale():
    """One node's DIRECT outbound probes are fully cut to half the
    cluster; ping-req relays through the unaffected half must keep
    everyone alive (no false deaths) for many probe periods."""
    nodes = make_cluster(8, loss=0.0, seed=3)
    try:
        want = {g.host for g in nodes}
        assert wait_until(
            lambda: all(alive_view(g) == want for g in nodes),
            timeout=20.0)
        cut_addrs = {g.gossip_host for g in nodes[4:]}
        base_filter = nodes[0].loss_filter

        def asym(addr, pkt, _base=base_filter):
            if addr in cut_addrs and pkt.get("t") == "ping":
                return True  # direct pings dropped; pingreq flows
            return _base(addr, pkt)

        nodes[0].loss_filter = asym
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            assert alive_view(nodes[0]) == want, (
                "asymmetric direct loss killed a reachable node")
            time.sleep(0.2)
    finally:
        for g in nodes:
            g.close()
