"""Multi-tenant QoS on a REAL 2-node gossip cluster (ISSUE 14):
the tenant principal must ride fan-out legs (X-Pilosa-Tenant), a
cost-policy kill must propagate cluster-wide via the cancel
broadcast, and a STORM of concurrent cost-policy kills must drain
both nodes' registries with zero admission-slot or penalty-box
leaks (the PR-2 staggered-deadline storm, extended to the kill
path)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402

pytestmark = pytest.mark.tenant

# tc's wall ceiling: generous against healthy-cluster latency (a
# fan-out read is ~ms), tiny against a stalled peer.
_TENANTS_SPEC = ("default:weight=1;"
                 "tc:max-wall=600ms;"
                 "alpha:weight=2")


def _post(host, path, body=b"", headers=None, timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST", headers=headers or {})
    return urllib.request.urlopen(req, timeout=timeout).read()


def _get_json(host, path, timeout=10):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


def _get_text(host, path, timeout=10):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return r.read().decode()


@pytest.fixture
def cluster(tmp_path):
    """Two gossip-joined nodes (replicas=1 → fan-out is mandatory),
    both carrying the same [tenants] table, with data in indexes
    ``tc`` (kill-ceiling tenant) and ``q`` (quiet tenant) spanning 4
    slices."""
    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs, logs = [], []

    def spawn(name, port, internal, seed=""):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env["PILOSA_TPU_WARMUP"] = "0"
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--cluster.type", "gossip",
                "--cluster.hosts", hosts,
                "--cluster.replicas", "1",
                "--cluster.internal-port", str(internal),
                "--tenants", _TENANTS_SPEC,
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        procs.append(p)
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    host_a = spawn("a", pa, ga)
    host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
    from pilosa_tpu.cluster.client import Client
    import numpy as np
    client = Client(host_a)
    cols = np.arange(0, 4 * SLICE_WIDTH,
                     SLICE_WIDTH // 8).astype(np.uint64)
    for index in ("tc", "q"):
        _post(host_a, f"/index/{index}", b"{}")
        _post(host_a, f"/index/{index}/frame/f", b"{}")
        client.import_arrays(index, "f",
                             np.ones(len(cols), np.uint64), cols)
        client.import_arrays(index, "f",
                             np.full(len(cols), 2, np.uint64), cols)
    deadline = time.time() + 30
    while time.time() < deadline:
        got = json.loads(_post(
            host_a, "/index/q/query",
            b'Count(Bitmap(frame="f", rowID=1))'))["results"][0]
        if got == len(cols):
            break
        time.sleep(0.3)
    assert got == len(cols), got

    yield {"a": host_a, "b": host_b, "procs": procs,
           "n_bits": len(cols)}

    for p in procs:
        try:
            os.kill(p.pid, signal.SIGCONT)
        except OSError:
            pass
        try:
            p.send_signal(signal.SIGINT)
        except OSError:
            pass
    for p in procs:
        try:
            p.wait(timeout=20)
        except subprocess.TimeoutExpired:
            p.kill()
    for log in logs:
        log.close()


def test_tenant_principal_rides_fanout_legs(cluster):
    """An EXPLICIT X-Pilosa-Tenant header (≠ index) on a
    fan-out-requiring read must reach the peer's leg: node B's
    per-tenant chargeback counters record the coordinator's
    principal, not the index fallback — the header crossed the wire
    end to end (client → A → B)."""
    host_a, host_b = cluster["a"], cluster["b"]
    out = json.loads(_post(
        host_a, "/index/q/query",
        b'Count(Intersect(Bitmap(frame="f", rowID=1),'
        b' Bitmap(frame="f", rowID=2)))',
        headers={"X-Pilosa-Tenant": "alpha"}))
    assert out["results"][0] == cluster["n_bits"]
    deadline = time.time() + 10
    seen = ""
    while time.time() < deadline:
        seen = _get_text(host_b, "/metrics")
        if 'pilosa_tenant_cost_units_total{tenant="alpha"' in seen:
            break
        time.sleep(0.2)
    assert 'pilosa_tenant_cost_units_total{tenant="alpha"' in seen, (
        "peer never accounted the propagated tenant principal")
    # And the default path (no header): the index IS the principal.
    _post(host_a, "/index/q/query",
          b'Count(Intersect(Bitmap(frame="f", rowID=1),'
          b' Bitmap(frame="f", rowID=2)))')
    assert 'pilosa_tenant_cost_units_total{tenant="q"' in _get_text(
        host_b, "/metrics")


def test_cost_policy_kill_propagates_cluster_wide(cluster):
    """SIGSTOP node B: a query on the wall-ceilinged tenant stalls on
    its remote leg, the coordinator's cost policy kills it at a stage
    boundary (402 + X-Pilosa-Killed-By), the kill broadcast reaches B
    (buffered while stopped), and after B resumes BOTH registries are
    drained."""
    host_a, host_b, procs = cluster["a"], cluster["b"], cluster["procs"]
    os.kill(procs[1].pid, signal.SIGSTOP)
    try:
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(host_a, "/index/tc/query?timeout=60s",
                  b'Count(Bitmap(frame="f", rowID=1))', timeout=90)
        elapsed = time.monotonic() - t0
        assert ei.value.code == 402, ei.value.code
        assert ei.value.headers["X-Pilosa-Killed-By"] == "cost-policy"
        assert b"cost-policy" in ei.value.read().lower()
        # Killed at ~the 600ms ceiling, not the 60s client budget.
        assert elapsed < 15, elapsed
        dbg = _get_json(host_a, "/debug/tenants")["tenants"]["tc"]
        assert dbg["killed"] >= 1 and dbg["inPenaltyBox"]
        assert dbg["effectiveWeight"] < dbg["policy"]["weight"]
        # Coordinator drained (slot + registry) without waiting out
        # the stalled leg.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not _get_json(host_a, "/debug/queries")["queries"]:
                break
            time.sleep(0.2)
        assert _get_json(host_a, "/debug/queries")["queries"] == []
    finally:
        os.kill(procs[1].pid, signal.SIGCONT)
    # B drains its buffered leg (the kill broadcast or the leg's own
    # completion) without leaking a registry entry.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if not _get_json(host_b, "/debug/queries")["queries"]:
            break
        time.sleep(0.3)
    assert _get_json(host_b, "/debug/queries")["queries"] == []
    # The healthy cluster still serves the penalized tenant (demoted,
    # not banned).
    got = json.loads(_post(
        host_a, "/index/tc/query?timeout=10s",
        b'Count(Bitmap(frame="f", rowID=1))'))["results"][0]
    assert got == cluster["n_bits"]


def test_cost_kill_storm_drains_both_registries(cluster):
    """The PR-2 staggered-deadline storm on the KILL path: N
    concurrent queries all breach the tenant's wall ceiling against a
    stalled peer — every one answers 402, and afterwards both nodes'
    registries are empty, the coordinator's admission has zero
    in-flight slots, and the penalty box holds exactly the storm's
    kills (no leaked slots, entries, or scores)."""
    host_a, host_b, procs = cluster["a"], cluster["b"], cluster["procs"]
    n = 8
    kills_before = _get_json(
        host_a, "/debug/tenants")["tenants"].get("tc", {}).get(
        "killed", 0)
    os.kill(procs[1].pid, signal.SIGSTOP)
    codes = []
    mu = threading.Lock()

    def one(i):
        try:
            _post(host_a, "/index/tc/query?timeout=60s",
                  b'Count(Bitmap(frame="f", rowID=1))', timeout=90)
            code = 200
        except urllib.error.HTTPError as e:
            code = e.code
        with mu:
            codes.append(code)

    try:
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(codes) == n
        assert all(c == 402 for c in codes), codes
        dbg = _get_json(host_a, "/debug/tenants")["tenants"]["tc"]
        assert dbg["killed"] == kills_before + n, dbg
        # Zero admission-slot leaks: every killed query released its
        # slot (and its registry entry) on the way out.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            adm = _get_json(host_a, "/debug/queries")
            if (not adm["queries"]
                    and adm["admission"]["inFlight"] == 0):
                break
            time.sleep(0.2)
        adm = _get_json(host_a, "/debug/queries")
        assert adm["queries"] == []
        assert adm["admission"]["inFlight"] == 0
        assert adm["admission"]["queued"] == {}
    finally:
        os.kill(procs[1].pid, signal.SIGCONT)
    # Both registries drain after the peer resumes.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if not _get_json(host_b, "/debug/queries")["queries"]:
            break
        time.sleep(0.3)
    assert _get_json(host_b, "/debug/queries")["queries"] == []
    # No penalty-box leak: the score decays back toward zero (no
    # stuck demotion) — observable as a strictly shrinking score.
    s1 = _get_json(host_a,
                   "/debug/tenants")["tenants"]["tc"]["penaltyScore"]
    time.sleep(2.0)
    s2 = _get_json(host_a,
                   "/debug/tenants")["tenants"]["tc"]["penaltyScore"]
    assert s2 < s1
