"""Generative differential test: a random stream of mutations and
queries runs against the full executor AND a plain Python set model;
every answer must match exactly. Complements the targeted suites by
exploring operator/lane interleavings nobody wrote down — the round-5
bulk/batch/vectorized paths all sit under these queries (deterministic
seeds; reference semantics per executor.go).
"""

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder


class Model:
    """bits[frame][row] = set of column ids (the executor's ground
    truth, reference semantics)."""

    def __init__(self):
        self.bits: dict[int, set[int]] = {}

    def set_bit(self, row: int, col: int) -> bool:
        s = self.bits.setdefault(row, set())
        if col in s:
            return False
        s.add(col)
        return True

    def clear_bit(self, row: int, col: int) -> bool:
        s = self.bits.get(row)
        if s is None or col not in s:
            return False
        s.discard(col)
        return True

    def row(self, row: int) -> set[int]:
        return self.bits.get(row, set())


def _pairs(result) -> list[tuple[int, int]]:
    return [(p.id, p.count) for p in result]


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_random_stream_matches_model(tmp_path, seed):
    rng = np.random.default_rng(seed)
    holder = Holder(str(tmp_path))
    holder.open()
    try:
        idx = holder.create_index("d")
        idx.create_frame("f")
        idx.create_frame("g")  # single-slice twin: TopN is EXACT there
        ex = Executor(holder, host="local", use_mesh=False)
        model = Model()
        gmodel = Model()
        n_rows, n_cols = 40, 3 * SLICE_WIDTH  # 3 slices

        def rand_rows(k):
            return rng.integers(0, n_rows, k).tolist()

        def recalc(frame_name):
            view = holder.frame("d", frame_name).view("standard")
            if view is not None:
                for fr in view.fragments.values():
                    fr.recalculate_cache()

        for step in range(250):
            kind = int(rng.integers(0, 10))
            if kind < 3:  # point set
                r, c = int(rng.integers(0, n_rows)), int(
                    rng.integers(0, n_cols))
                got = ex.execute(
                    "d", f"SetBit(frame=f, rowID={r}, columnID={c})")[0]
                assert got == model.set_bit(r, c), ("set", step)
                gc = c % SLICE_WIDTH
                got = ex.execute(
                    "d", f"SetBit(frame=g, rowID={r}, columnID={gc})")[0]
                assert got == gmodel.set_bit(r, gc)
            elif kind == 3:  # point clear
                r, c = int(rng.integers(0, n_rows)), int(
                    rng.integers(0, n_cols))
                got = ex.execute(
                    "d",
                    f"ClearBit(frame=f, rowID={r}, columnID={c})")[0]
                assert got == model.clear_bit(r, c), ("clear", step)
            elif kind == 4:  # bulk import (the packed-sort lanes)
                k = int(rng.integers(1, 400))
                rows = rng.integers(0, n_rows, k).astype(np.uint64)
                cols = rng.integers(0, n_cols, k).astype(np.uint64)
                holder.frame("d", "f").import_bits(rows, cols)
                for r, c in zip(rows.tolist(), cols.tolist()):
                    model.set_bit(r, c)
            elif kind == 5:  # Count(Bitmap)
                r = int(rng.integers(0, n_rows))
                got = ex.execute(
                    "d", f"Count(Bitmap(frame=f, rowID={r}))")[0]
                assert got == len(model.row(r)), ("count", step)
            elif kind == 6:  # Count(Union(...)) wide
                ids = rand_rows(int(rng.integers(2, 12)))
                q = "Count(Union(" + ", ".join(
                    f"Bitmap(frame=f, rowID={r})" for r in ids) + "))"
                want = len(set().union(*(model.row(r) for r in ids)))
                assert ex.execute("d", q)[0] == want, ("union", step)
            elif kind == 7:  # Count(Intersect/Difference)
                a, b = rand_rows(2)
                got_i = ex.execute(
                    "d", f"Count(Intersect(Bitmap(frame=f, rowID={a}),"
                         f" Bitmap(frame=f, rowID={b})))")[0]
                assert got_i == len(model.row(a) & model.row(b))
                got_d = ex.execute(
                    "d", f"Count(Difference(Bitmap(frame=f, rowID={a}),"
                         f" Bitmap(frame=f, rowID={b})))")[0]
                assert got_d == len(model.row(a) - model.row(b))
            elif kind == 8:  # TopN totals
                # The rank cache re-sorts at most every 10 s (reference
                # cache.go semantics): exact assertions require the
                # explicit recalculation the reference's own tests use.
                # Multi-slice TopN is approximate BY REFERENCE DESIGN
                # (candidates = union of per-slice tops, so a row
                # spread thin across slices can miss), so the exact
                # assertion holds only for returned pairs' counts and
                # ordering; full exactness is asserted on the
                # single-slice frame below.
                recalc("f")
                n = int(rng.integers(1, 6))
                got = _pairs(ex.execute("d", f"TopN(frame=f, n={n})")[0])
                assert len(got) <= n
                assert got == sorted(got, key=lambda kv: (-kv[1],
                                                          kv[0]))
                for rid, cnt in got:
                    assert cnt == len(model.row(rid)), ("topn-cnt",
                                                        step, rid)
                # single-slice frame: full exactness
                recalc("g")
                gg = _pairs(ex.execute("d", f"TopN(frame=g, n={n})")[0])
                gw = sorted(((r, len(sv)) for r, sv in
                             gmodel.bits.items() if sv),
                            key=lambda kv: (-kv[1], kv[0]))[:n]
                assert gg == gw, ("topn-g", step, gg, gw)
            else:  # src TopN (the vectorized replay + count maps)
                recalc("f")
                src = int(rng.integers(0, n_rows))
                got = _pairs(ex.execute(
                    "d", f"TopN(Bitmap(frame=f, rowID={src}),"
                         f" frame=f, n=5)")[0])
                # Same per-slice candidate approximation as plain
                # TopN: returned counts must be the EXACT model
                # intersections, in (count desc, id asc) order.
                assert got == sorted(got, key=lambda kv: (-kv[1],
                                                          kv[0]))
                for rid, cnt in got:
                    assert cnt == len(model.row(rid)
                                      & model.row(src)), ("src-cnt",
                                                          step, rid)
    finally:
        holder.close()


@pytest.mark.parametrize("seed", [7, 8])
def test_range_stream_matches_model(tmp_path, seed):
    """Differential Range/time-quantum coverage: timestamped sets fan
    out to Y/M/D time views; Range(start, end) must equal the model's
    exact [start, end) timestamp filter for the row (reference
    executor.go Range over views_by_time_range covers)."""
    import datetime as dt

    from pilosa_tpu.models.frame import FrameOptions

    rng = np.random.default_rng(seed)
    holder = Holder(str(tmp_path))
    holder.open()
    try:
        idx = holder.create_index("t")
        idx.create_frame("f", options=FrameOptions(time_quantum="YMD"))
        ex = Executor(holder, host="local", use_mesh=False)
        frame = holder.frame("t", "f")
        # (row, col) -> timestamp of the LAST set (sets overwrite the
        # time-view placement only additively; the standard view keeps
        # the bit either way)
        events: list[tuple[int, int, dt.datetime]] = []
        base = dt.datetime(2026, 1, 1)
        for step in range(120):
            r = int(rng.integers(0, 8))
            c = int(rng.integers(0, 2 * SLICE_WIDTH))
            t = base + dt.timedelta(days=int(rng.integers(0, 200)),
                                    hours=int(rng.integers(0, 24)))
            ts = t.strftime("%Y-%m-%dT%H:%M")
            ex.execute("t", f"SetBit(frame=f, rowID={r}, columnID={c},"
                            f" timestamp=\"{ts}\")")
            events.append((r, c, t))
            if step % 15 != 14:
                continue
            row = int(rng.integers(0, 8))
            lo = base + dt.timedelta(days=int(rng.integers(0, 100)))
            hi = lo + dt.timedelta(days=int(rng.integers(1, 120)))
            got = ex.execute(
                "t", f'Count(Range(rowID={row}, frame=f,'
                     f' start="{lo.strftime("%Y-%m-%dT%H:%M")}",'
                     f' end="{hi.strftime("%Y-%m-%dT%H:%M")}"))')[0]
            # Model: a column matches if ANY set of (row, col) fell in
            # [lo, hi) — time views are additive (a bit lives in every
            # quantum view its sets touched), per reference frame.go
            # SetBit time fan-out.
            want_cols = {c2 for (r2, c2, t2) in events
                         if r2 == row and lo <= t2 < hi}
            # Quantum granularity: YMD views cover whole days, so the
            # executor's cover rounds to day boundaries exactly like
            # views_by_time_range; both ends here are midnight-aligned
            # starts plus day deltas, so no partial-day mismatch.
            assert got == len(want_cols), (step, got, len(want_cols))
    finally:
        holder.close()


@pytest.mark.parametrize("seed", [11, 12])
def test_attrs_stream_matches_model(tmp_path, seed):
    """Differential row-attribute coverage: random SetRowAttrs streams
    (typed values, null deletion) against a dict model, checked through
    Bitmap(...)'s attrs payload and TopN attribute filters (reference
    executor.go SetRowAttrs / fragment.go Top filter semantics)."""
    rng = np.random.default_rng(seed)
    holder = Holder(str(tmp_path))
    holder.open()
    try:
        idx = holder.create_index("a")
        from pilosa_tpu.models.frame import FrameOptions
        idx.create_frame("f", options=FrameOptions(cache_type="ranked"))
        ex = Executor(holder, host="local", use_mesh=False)
        frame = holder.frame("a", "f")
        attrs_model: dict[int, dict] = {}
        n_rows = 12
        # seed bits so TopN has candidates; counts descend by row
        for r in range(n_rows):
            for c in range(2 * (n_rows - r)):
                frame.set_bit("standard", r, c)
        for fr in frame.view("standard").fragments.values():
            fr.recalculate_cache()

        cats = [100, 200, 300]
        for step in range(60):
            r = int(rng.integers(0, n_rows))
            kind = int(rng.integers(0, 4))
            if kind == 0:  # int attr
                v = int(cats[int(rng.integers(0, 3))])
                ex.execute("a", f"SetRowAttrs(rowID={r}, frame=f,"
                                f" category={v})")
                attrs_model.setdefault(r, {})["category"] = v
            elif kind == 1:  # string attr
                v = f"s{int(rng.integers(0, 3))}"
                ex.execute("a", f'SetRowAttrs(rowID={r}, frame=f,'
                                f' tag="{v}")')
                attrs_model.setdefault(r, {})["tag"] = v
            elif kind == 2:  # null deletes
                ex.execute("a", f"SetRowAttrs(rowID={r}, frame=f,"
                                f" category=null)")
                attrs_model.setdefault(r, {}).pop("category", None)
            else:  # read attrs through Bitmap
                got = ex.execute(
                    "a", f"Bitmap(frame=f, rowID={r})")[0]
                want = {k: v for k, v in
                        attrs_model.get(r, {}).items()}
                assert got.attrs == want, (step, r, got.attrs, want)
            if step % 10 == 9:
                # TopN filtered by category: exact per reference
                # semantics (candidates from the rank cache; all rows
                # cached here, counts descend by row id)
                v = cats[int(rng.integers(0, 3))]
                got = ex.execute(
                    "a", f"TopN(frame=f, n={n_rows},"
                         f' field="category", filters=[{v}])')[0]
                want_rows = sorted(
                    (r for r, a in attrs_model.items()
                     if a.get("category") == v))
                got_rows = sorted(p.id for p in got)
                assert got_rows == want_rows, (step, got_rows,
                                               want_rows)
    finally:
        holder.close()
