"""Tail-sampled tracing: the keep-reason decision, the crash-safe
on-disk segment ring, and the handler integration (every query buffers
spans; the interesting ones persist and the slow log cross-links
them). docs/OBSERVABILITY.md is the operator-facing contract."""

import io
import json
import os

import pytest

from pilosa_tpu.errors import QueryCancelledError, QueryDeadlineError
from pilosa_tpu.executor import Executor
from pilosa_tpu.fault import failpoints
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.obs.diskring import SegmentRing
from pilosa_tpu.obs.sampler import (TailSampler, record_to_trace,
                                    trace_record)
from pilosa_tpu.obs.trace import Trace, Tracer
from pilosa_tpu.sched import AdmissionController, QueryContext
from pilosa_tpu.server.handler import Handler


def call(app, method, path, body=b"", content_type="", accept="",
         headers=None):
    if "?" in path:
        path, _, qs = path.partition("?")
    else:
        qs = ""
    environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
               "QUERY_STRING": qs, "CONTENT_LENGTH": str(len(body)),
               "wsgi.input": io.BytesIO(body)}
    if content_type:
        environ["CONTENT_TYPE"] = content_type
    if accept:
        environ["HTTP_ACCEPT"] = accept
    for k, v in (headers or {}).items():
        environ["HTTP_" + k.upper().replace("-", "_")] = v
    out = {}

    def start_response(status, hs):
        out["status"] = int(status.split()[0])
        out["headers"] = dict(hs)

    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


# -- the disk segment ring -----------------------------------------------------


class TestSegmentRing:
    def test_round_trip_and_rotation(self, tmp_path):
        ring = SegmentRing(str(tmp_path / "r"), segment_bytes=4096,
                           max_segments=3)
        for i in range(200):
            assert ring.append({"i": i, "pad": "x" * 64})
        got = [r["i"] for r in ring.scan()]
        # Newest first, oldest rotated away, disk bounded.
        assert got[0] == 199
        assert got == sorted(got, reverse=True)
        assert len(got) < 200
        stats = ring.stats()
        assert stats["segments"] <= 3
        assert stats["bytes"] <= 3 * 4096 + 4096
        assert stats["written"] == 200
        ring.close()

    def test_reopen_serves_persisted_records(self, tmp_path):
        d = str(tmp_path / "r")
        ring = SegmentRing(d)
        for i in range(5):
            ring.append({"i": i})
        ring.close()
        reopened = SegmentRing(d)
        assert [r["i"] for r in reopened.scan()] == [4, 3, 2, 1, 0]
        # New appends land in a FRESH segment past the old ones.
        reopened.append({"i": 5})
        assert [r["i"] for r in reopened.scan()][0] == 5
        reopened.close()

    def test_torn_write_skips_bad_segment_serves_rest(self, tmp_path):
        """The crash-safety contract: a torn segment write (the
        ring.write failpoint tears mid-record, as SIGKILL would) ends
        that segment's scan at the tear; whole records before it and
        every other segment still serve after reopen."""
        d = str(tmp_path / "r")
        ring = SegmentRing(d, segment_bytes=1 << 16)
        ring.append({"i": 0})
        ring.append({"i": 1})
        with failpoints.injected("ring.write", "torn(7)*1"):
            assert ring.append({"i": 2}) is False
        assert ring.dropped == 1
        # Post-tear appends open a fresh segment and serve.
        ring.append({"i": 3})
        got = [r["i"] for r in ring.scan()]
        assert got == [3, 1, 0], got  # 2 is gone, nothing else is
        assert ring.skipped >= 1
        ring.close()
        # Reopen (the restart path): same records, same skip.
        reopened = SegmentRing(d)
        assert [r["i"] for r in reopened.scan()] == [3, 1, 0]
        reopened.close()

    def test_sigkill_mid_write_torn_tail_trimmed(self, tmp_path):
        """A raw torn tail on disk (process killed mid-write(2), no
        exception ever raised in-process): reopen serves every whole
        record and stops at the tear."""
        d = str(tmp_path / "r")
        ring = SegmentRing(d)
        ring.append({"i": 0})
        ring.append({"i": 1})
        ring.close()
        segs = sorted(os.listdir(d))
        path = os.path.join(d, segs[-1])
        with open(path, "ab") as f:  # half a record, as SIGKILL leaves
            f.write(b"deadbeef {\"i\": 2, \"trunca")
        reopened = SegmentRing(d)
        assert [r["i"] for r in reopened.scan()] == [1, 0]
        assert reopened.skipped == 1
        # Corrupt a MIDDLE byte of the first record of a fresh
        # segment: crc catches silent corruption, not just length.
        reopened.append({"i": 3})
        reopened.close()
        segs2 = sorted(os.listdir(d))
        assert len(segs2) == 2
        with open(os.path.join(d, segs2[-1]), "r+b") as f:
            f.seek(12)
            f.write(b"X")
        again = SegmentRing(d)
        assert [r["i"] for r in again.scan()] == [1, 0]
        again.close()


# -- the keep decision ---------------------------------------------------------


class TestKeepDecision:
    def _sampler(self, **kw):
        kw.setdefault("head_n", 0)
        kw.setdefault("histogram", obs_metrics.Histogram(
            "pilosa_test_decide_latency_seconds", buckets=(0.1, 1.0)))
        return TailSampler(**kw)

    def test_outcome_reasons(self):
        s = self._sampler()
        ctx = QueryContext(pql="q")
        assert s.decide(ctx, err=QueryDeadlineError("x")) == "deadline"
        assert s.decide(ctx, err=QueryCancelledError("x")) == "cancelled"
        assert s.decide(ctx, err=RuntimeError("x")) == "error"
        assert s.decide(ctx, status=504) == "deadline"
        assert s.decide(ctx, status=429) == "shed"
        assert s.decide(ctx, status=500) == "error"
        assert s.decide(ctx, partial=True) == "partial"
        assert s.decide(ctx) is None

    def test_fault_flags(self):
        s = self._sampler()
        for flag, reason in (("breaker", "breaker"),
                             ("failover", "breaker"),
                             ("failpoint", "failpoint"),
                             ("partial", "partial")):
            ctx = QueryContext(pql="q")
            ctx.note_flag(flag)
            assert s.decide(ctx) == reason, flag

    def test_shed_lane_window(self):
        adm = AdmissionController(concurrency=1, queue_depth=0)
        s = self._sampler(admission=adm)
        ctx = QueryContext(pql="q", lane="read")
        assert s.decide(ctx) is None
        slot = adm.acquire("read")
        with pytest.raises(Exception):
            adm.acquire("read")  # queue_depth=0 -> immediate 429
        assert s.decide(ctx) == "shed"
        slot.release()

    def test_dynamic_slow_threshold_tracks_histogram(self):
        hist = obs_metrics.Histogram(
            "pilosa_test_slowthresh_latency_seconds",
            buckets=(0.01, 0.1, 1.0))
        s = self._sampler(histogram=hist, slow_floor_s=0.001)
        # Cold: too few observations -> conservative fixed threshold.
        assert s.slow_threshold_s() == 0.5
        for _ in range(200):
            hist.observe(0.005)
        s._threshold = (0.0, 0.0)  # expire the cache
        # p99 of an all-fast workload: the first bucket bound.
        assert s.slow_threshold_s() == 0.01
        ctx = QueryContext(pql="q", timeout_s=None)
        ctx.started -= 0.05  # elapsed ~50ms > 10ms threshold
        assert s.decide(ctx) == "slow"

    def test_head_sample_one_in_n(self):
        s = TailSampler(head_n=10, histogram=obs_metrics.Histogram(
            "pilosa_test_head_latency_seconds", buckets=(0.1,)))
        ctx = QueryContext(pql="q")
        kept = [s.decide(ctx) for _ in range(30)]
        assert kept.count("head") == 3
        assert kept[0] == "head"  # the first query of a process keeps

    def test_persist_round_trip(self, tmp_path):
        ring = SegmentRing(str(tmp_path / "t"))
        s = self._sampler(disk=ring)
        trace = Trace("qid1", node="n1", pql="Count(...)")
        with trace.span("execute"):
            pass
        ctx = QueryContext(pql="Count(...)", index="i")
        s.persist(trace, "slow", ctx=ctx)
        rec = next(ring.scan())
        assert rec["id"] == "qid1" and rec["reason"] == "slow"
        assert rec["index"] == "i"
        rebuilt = record_to_trace(rec)
        assert rebuilt.keep_reason == "slow"
        assert [sp.name for sp in rebuilt.spans()] == ["execute"]
        chrome = rebuilt.to_chrome()
        assert chrome["otherData"]["traceId"] == "qid1"
        ring.close()


# -- handler integration -------------------------------------------------------


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def tail_handler(holder, tmp_path):
    """A bare handler with tail sampling wired, over a real executor
    (the server wires the same objects in open())."""
    tracer = Tracer(enabled=False)
    sampler = TailSampler(
        disk=SegmentRing(str(tmp_path / "traces")),
        head_n=0, slow_floor_s=30.0,
        histogram=obs_metrics.Histogram(
            "pilosa_test_tailhandler_latency_seconds", buckets=(64.0,)))
    h = Handler(holder, Executor(holder, host="local"), host="local",
                tracer=tracer, sampler=sampler)
    return h


class TestHandlerTailSampling:
    def _seed(self, app):
        status, _, _ = call(app, "POST", "/index/ti", b"{}")
        assert status == 200
        status, _, _ = call(app, "POST", "/index/ti/frame/f", b"{}")
        assert status == 200
        status, _, body = call(
            app, "POST", "/index/ti/query",
            b'SetBit(frame="f", rowID=1, columnID=1)')
        assert status == 200, body

    def test_healthy_fast_query_not_kept(self, tail_handler):
        self._seed(tail_handler)
        status, headers, _ = call(tail_handler, "POST",
                                  "/index/ti/query",
                                  b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200
        qid = headers["X-Pilosa-Query-Id"]
        _, _, body = call(tail_handler, "GET", "/debug/traces")
        listing = json.loads(body)
        assert listing["tail"] is True
        assert not any(t["id"] == qid for t in listing["traces"])
        assert list(tail_handler.sampler.disk.scan()) == []

    def test_error_query_kept_with_reason_and_persisted(
            self, tail_handler):
        self._seed(tail_handler)
        status, headers, _ = call(
            tail_handler, "POST", "/index/ti/query",
            b'Plugin(frame="f")')  # parses, fails in the executor
        assert status == 400
        qid = headers["X-Pilosa-Query-Id"]
        _, _, body = call(tail_handler, "GET", "/debug/traces")
        entry = next(t for t in json.loads(body)["traces"]
                     if t["id"] == qid)
        assert entry["reason"] == "error"
        # Persisted: the disk listing filters by reason, and the
        # by-id route falls back to disk.
        _, _, body = call(tail_handler, "GET",
                          "/debug/traces?source=disk&reason=error")
        disk = json.loads(body)
        assert disk["source"] == "disk"
        assert any(t["id"] == qid for t in disk["traces"])
        _, _, body = call(tail_handler, "GET",
                          f"/debug/traces/{qid}?source=disk")
        assert json.loads(body)["otherData"]["traceId"] == qid

    def test_failpoint_hit_keeps_trace(self, tail_handler):
        """A query whose commit barrier hits an armed wal.append
        failpoint (delay mode — the injection fires, the write
        proceeds) is kept with reason "failpoint"."""
        self._seed(tail_handler)
        kept_ids = []
        with failpoints.injected("wal.append", "delay(1ms)"):
            for i in range(3):
                status, headers, _ = call(
                    tail_handler, "POST", "/index/ti/query",
                    f'SetBit(frame="f", rowID=2, columnID={i})'
                    .encode())
                assert status == 200
                kept_ids.append(headers["X-Pilosa-Query-Id"])
        _, _, body = call(tail_handler, "GET",
                          "/debug/traces?reason=failpoint")
        traces = json.loads(body)["traces"]
        assert any(t["id"] in kept_ids for t in traces), traces

    def test_slow_log_cross_links_kept_trace(self, holder, tmp_path):
        from pilosa_tpu.sched import QueryRegistry
        registry = QueryRegistry(slow_threshold_s=1e-9)
        sampler = TailSampler(
            disk=None, head_n=0, slow_floor_s=30.0,
            histogram=obs_metrics.Histogram(
                "pilosa_test_crosslink_latency_seconds",
                buckets=(64.0,)))
        h = Handler(holder, Executor(holder, host="local"),
                    host="local", registry=registry, sampler=sampler)
        call(h, "POST", "/index/tj", b"{}")
        call(h, "POST", "/index/tj/frame/f", b"{}")
        # An erroring query: kept (reason "error") + slow-logged.
        status, headers, _ = call(h, "POST", "/index/tj/query",
                                  b'Plugin(frame="f")')
        assert status == 400
        qid = headers["X-Pilosa-Query-Id"]
        _, _, body = call(h, "GET", "/debug/queries/slow")
        entry = next(e for e in json.loads(body)["slow"]
                     if e["id"] == qid)
        assert entry["traceKept"] is True
        assert entry["traceKeepReason"] == "error"
        # A healthy query's slow entry records the negative too.
        status, headers, _ = call(
            h, "POST", "/index/tj/query",
            b'SetBit(frame="f", rowID=1, columnID=1)')
        assert status == 200
        qid2 = headers["X-Pilosa-Query-Id"]
        _, _, body = call(h, "GET", "/debug/queries/slow")
        entry2 = next(e for e in json.loads(body)["slow"]
                      if e["id"] == qid2)
        assert entry2["traceKept"] is False
        assert "traceKeepReason" not in entry2

    def test_explicit_trace_still_kept_as_requested(self, tail_handler):
        self._seed(tail_handler)
        status, headers, _ = call(
            tail_handler, "POST", "/index/ti/query?trace=1",
            b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200
        qid = headers["X-Pilosa-Query-Id"]
        _, _, body = call(tail_handler, "GET", "/debug/traces")
        entry = next(t for t in json.loads(body)["traces"]
                     if t["id"] == qid)
        assert entry["reason"] == "requested"


class TestTraceRecordShape:
    def test_record_carries_cost_and_stages(self):
        from pilosa_tpu.obs import accounting
        ctx = QueryContext(pql="q", index="i")
        accounting.attach(ctx, node="n1")
        ctx.stages["execute"] = 0.5
        trace = Trace("qid2", node="n1", pql="q")
        rec = trace_record(trace, "deadline", ctx=ctx)
        assert rec["reason"] == "deadline"
        assert rec["stages"]["execute"] == 0.5
        assert "cost" in rec
