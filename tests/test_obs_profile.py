"""Per-query cost accounting (obs.accounting), the continuous profiler
(obs.profile), and SLO health (obs.slo): ledger units, profile-ring
bounds, the ?profile=1 cost tree over HTTP, /health readiness, the
wire-import stage breakdown, and the overhead guard proving
accounting + the default-rate profiler cost <5% on the query p50."""

import io
import json
import threading
import time

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.obs import accounting
from pilosa_tpu.obs.profile import ContinuousProfiler
from pilosa_tpu.obs.slo import HealthChecker, SLOTracker
from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.sched import QueryContext
from pilosa_tpu.sched import context as sched_context
from pilosa_tpu.server.handler import Handler


def call(app, method, path, body=b"", content_type="", headers=None):
    if "?" in path:
        path, _, qs = path.partition("?")
    else:
        qs = ""
    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": path,
        "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(body)),
        "wsgi.input": io.BytesIO(body),
    }
    if content_type:
        environ["CONTENT_TYPE"] = content_type
    for k, v in (headers or {}).items():
        environ["HTTP_" + k.upper().replace("-", "_")] = v
    out = {}

    def start_response(status, hs):
        out["status"] = int(status.split()[0])
        out["headers"] = dict(hs)

    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def handler(holder):
    ex = Executor(holder, host="local", use_mesh=False)
    yield Handler(holder, ex, host="local")
    ex.close()


def _two_row_frame(holder, n=400):
    frame = holder.create_index_if_not_exists("i") \
        .create_frame_if_not_exists("f")
    rows = np.concatenate([np.zeros(n, np.uint64),
                           np.ones(n, np.uint64)])
    cols = np.concatenate([np.arange(n, dtype=np.uint64),
                           np.arange(n // 2, n + n // 2,
                                     dtype=np.uint64)])
    frame.import_bits(rows, cols)
    return frame


# -- ledger units -------------------------------------------------------------

class TestQueryCostLedger:
    def test_note_sites_accumulate(self):
        cost = accounting.QueryCost(node="n1")
        cost.note_container_op("intersect", "array_array", words=8)
        cost.note_container_op("intersect", "array_array", words=8)
        cost.note_container_op("union", "bitmap_bitmap", words=2048)
        cost.note_bits_written(5)
        cost.note_device_dispatch(1 << 20)
        cost.note_compile(0.25)
        cost.note_rpc("peer:1", 100, 900)
        cost.note_rpc("peer:1", 50, 450)
        tree = cost.to_tree({"execute": 0.5, "admission": 0.001})
        assert tree["containerOps"] == {"intersect:array_array": 2,
                                        "union:bitmap_bitmap": 1}
        assert tree["wordsScanned"] == 8 + 8 + 2048
        assert tree["bitsWritten"] == 5
        assert tree["devicePrograms"] == 1
        assert tree["deviceBytes"] == 1 << 20
        assert tree["compileMs"] == 250.0
        assert tree["rpc"]["peer:1"] == {"bytesOut": 150,
                                         "bytesIn": 1350, "calls": 2}
        assert tree["queueWaitMs"] == 1.0
        summary = cost.summary()
        assert summary["containerOps"] == 3
        assert summary["rpcBytesOut"] == 150
        assert summary["rpcBytesIn"] == 1350

    def test_current_cost_requires_bound_ctx(self):
        assert accounting.current_cost() is None
        ctx = QueryContext(pql="q")
        assert accounting.attach(ctx) is not None
        with sched_context.use(ctx):
            assert accounting.current_cost() is ctx.cost
        assert accounting.current_cost() is None

    def test_attach_respects_switch(self):
        accounting.set_enabled(False)
        try:
            ctx = QueryContext(pql="q")
            assert accounting.attach(ctx) is None
            assert ctx.cost is None
        finally:
            accounting.set_enabled(True)

    def test_remote_stitch_and_child_cap(self):
        cost = accounting.QueryCost(node="coord")
        child = accounting.QueryCost(node="peer")
        child.note_container_op("intersect", "bitmap_bitmap", 2048)
        cost.add_remote_json(child.wire_json())
        cost.add_remote_json("not json")       # ignored
        cost.add_remote_json("[1, 2, 3]")      # wrong shape, ignored
        tree = cost.to_tree()
        assert len(tree["children"]) == 1
        assert tree["children"][0]["node"] == "peer"
        assert tree["children"][0]["containerOps"] == {
            "intersect:bitmap_bitmap": 1}
        for i in range(2 * accounting.MAX_CHILDREN):
            cost.add_remote_json(json.dumps({"node": f"p{i}"}))
        assert len(cost.to_tree()["children"]) \
            == accounting.MAX_CHILDREN

    def test_wire_json_respects_header_budget(self):
        cost = accounting.QueryCost(node="n" * 40)
        for i in range(4000):
            cost.note_container_op(f"op{i}", "array_array", 1)
        wire = cost.wire_json()
        assert len(wire) <= accounting.QueryCost._WIRE_BYTES
        tree = json.loads(wire)
        # Over budget the mix collapses to its total — never dropped.
        assert tree["containerOps"] == {"total": 4000}

    def test_wide_fanout_attributes_reduce_side_ops(self, holder):
        """The chunked slice fan-out pre-reduces inside pool tasks;
        the ctx binding must cover map AND reduce there — a wide query
        whose merges went unattributed would undercount exactly the
        queries the ledger exists to explain."""
        from pilosa_tpu.executor import ExecOptions, Executor
        frame = holder.create_index_if_not_exists("w") \
            .create_frame_if_not_exists("f")
        rng = np.random.default_rng(3)
        n_slices = 64  # >> 4 * max_workers → chunk > 1
        from pilosa_tpu import SLICE_WIDTH
        for row in (0, 1):
            cols = (rng.integers(0, SLICE_WIDTH, size=20 * n_slices)
                    + np.repeat(np.arange(n_slices), 20) * SLICE_WIDTH)
            frame.import_bits(np.full(len(cols), row, np.uint64),
                              cols.astype(np.uint64))
        ex = Executor(holder, host="local", use_mesh=False)
        q = ('Intersect(Bitmap(frame=f, rowID=0),'
             ' Bitmap(frame=f, rowID=1))')
        ex.execute("w", q)  # warm
        ex._bitmap_results.clear()
        ctx = QueryContext(pql=q)
        accounting.attach(ctx)
        ex.execute("w", q, opt=ExecOptions(ctx=ctx))
        # At least one container op per slice leg reached the ledger.
        assert sum(ctx.cost.container_ops.values()) >= n_slices
        ex.close()

    def test_roaring_ops_attribute_to_bound_query(self):
        from pilosa_tpu.storage import roaring
        ctx = QueryContext(pql="q")
        accounting.attach(ctx)
        a = roaring.Bitmap(*range(0, 130000, 2))   # bitmap container
        b = roaring.Bitmap(1, 2, 3)                # array container
        with sched_context.use(ctx):
            a.intersect(b)
        key = "intersect:array_bitmap"
        assert ctx.cost.container_ops.get(key) == 1
        assert ctx.cost.words_scanned >= 1024  # the bitmap operand


# -- continuous profiler ------------------------------------------------------

class TestContinuousProfiler:
    def test_ring_is_bounded(self):
        prof = ContinuousProfiler(hz=100, ring=32)
        stop = threading.Event()

        def busy_loop_for_profiler():
            while not stop.is_set():
                sum(i * i for i in range(200))

        t = threading.Thread(target=busy_loop_for_profiler, daemon=True)
        t.start()
        try:
            for _ in range(100):
                prof.sample_once()
        finally:
            stop.set()
            t.join()
        snap = prof.snapshot()
        assert snap["ringSamples"] <= 32
        assert snap["ticks"] == 100
        assert not prof.running  # sample_once() never started a thread

    def test_query_id_tagged_and_filterable(self):
        prof = ContinuousProfiler(hz=100, ring=1024)
        ctx = QueryContext(pql="q")
        stop = threading.Event()

        def busy_named_query_leg():
            with sched_context.use(ctx):
                while not stop.is_set():
                    sum(i * i for i in range(200))

        t = threading.Thread(target=busy_named_query_leg, daemon=True)
        t.start()
        try:
            time.sleep(0.02)
            for _ in range(20):
                prof.sample_once()
                time.sleep(0.002)
        finally:
            stop.set()
            t.join()
        mine = prof.flame(query=ctx.id)
        assert "busy_named_query_leg" in mine
        # Collapsed-stack format: every non-header line ends in a count.
        for line in mine.splitlines()[1:]:
            stack, _, count = line.rpartition(" ")
            assert stack and count.isdigit()
        # A bogus query id matches nothing.
        none = prof.flame(query="nope")
        assert "busy_named_query_leg" not in none
        assert none.splitlines()[0].startswith(
            "# continuous profile: 0 samples")

    def test_background_thread_start_stop(self):
        prof = ContinuousProfiler(hz=100, ring=64)
        prof.start()
        assert prof.running
        time.sleep(0.08)
        prof.stop()
        assert not prof.running
        assert prof.samples_taken >= 1

    def test_flame_endpoint(self, handler):
        status, _, body = call(handler, "GET", "/debug/pprof/flame")
        assert status == 200
        assert body.decode().startswith("# continuous profile:")
        status, _, _ = call(handler, "GET",
                            "/debug/pprof/flame?since=bogus")
        assert status == 400


# -- ?profile=1 cost tree over HTTP -------------------------------------------

class TestProfileTreeHTTP:
    def test_profile_tree_shape(self, handler, holder):
        _two_row_frame(holder)
        status, headers, body = call(
            handler, "POST", "/index/i/query?profile=1",
            b'Intersect(Bitmap(frame="f", rowID=0),'
            b' Bitmap(frame="f", rowID=1))')
        assert status == 200
        resp = json.loads(body)
        tree = resp["profile"]
        assert tree["node"] == "local"
        assert sum(tree["containerOps"].values()) >= 1
        assert tree["wordsScanned"] > 0
        assert {"parse", "admission", "execute"} <= set(tree["stages"])
        assert "queueWaitMs" in tree
        # The compact roll-up rides EVERY response as X-Pilosa-Stats.
        stats = json.loads(headers["X-Pilosa-Stats"])
        assert stats["containerOps"] \
            == sum(tree["containerOps"].values())

    def test_without_profile_param_no_tree_but_header(self, handler,
                                                      holder):
        _two_row_frame(holder)
        status, headers, body = call(
            handler, "POST", "/index/i/query",
            b'Count(Bitmap(frame="f", rowID=0))')
        assert status == 200
        assert "profile" not in json.loads(body)
        assert "X-Pilosa-Stats" in headers

    def test_debug_queries_slow_log_carries_cost(self, holder):
        from pilosa_tpu.sched import QueryRegistry
        ex = Executor(holder, host="local", use_mesh=False)
        registry = QueryRegistry(slow_threshold_s=1e-9)
        h = Handler(holder, ex, host="local", registry=registry)
        _two_row_frame(holder)
        status, headers, _ = call(
            h, "POST", "/index/i/query",
            b'Intersect(Bitmap(frame="f", rowID=0),'
            b' Bitmap(frame="f", rowID=1))')
        assert status == 200
        qid = headers["X-Pilosa-Query-Id"]
        status, _, body = call(h, "GET", "/debug/queries/slow")
        entry = [e for e in json.loads(body)["slow"]
                 if e["id"] == qid][-1]
        assert entry["cost"]["containerOps"] >= 1
        ex.close()

    def test_write_query_counts_bits_written(self, handler, holder):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        status, headers, _ = call(
            handler, "POST", "/index/i/query",
            b'SetBit(frame="f", rowID=7, columnID=3)')
        assert status == 200
        stats = json.loads(headers["X-Pilosa-Stats"])
        assert stats["bitsWritten"] == 1


# -- wire-import stage breakdown ----------------------------------------------

class TestImportStageTiming:
    def test_decode_apply_recorded(self, handler, holder):
        from pilosa_tpu.proto import internal_pb2 as pb
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")

        def stage_count(stage):
            fam = obs_metrics.IMPORT_STAGE_SECONDS
            _counts, _sum, n = fam.labels(stage).snapshot()
            return n

        before_d, before_a = stage_count("decode"), stage_count("apply")
        req = pb.ImportRequest(Index="i", Frame="f", Slice=0,
                               RowIDs=[1, 1], ColumnIDs=[3, 4])
        status, headers, _ = call(
            handler, "POST", "/import", req.SerializeToString(),
            content_type="application/x-protobuf",
            headers={"Accept": "application/x-protobuf"})
        assert status == 200
        assert stage_count("decode") == before_d + 1
        assert stage_count("apply") == before_a + 1
        stats = json.loads(headers["X-Pilosa-Stats"])
        assert stats["bits"] == 2
        assert stats["wireBytes"] > 0
        assert stats["decodeMs"] >= 0 and stats["applyMs"] >= 0


# -- SLO + health -------------------------------------------------------------

class TestSLOAndHealth:
    def test_burn_rate_from_histogram(self):
        reg = obs_metrics.Registry()
        hist = reg.histogram("pilosa_test_slo_seconds",
                             labels=("status",))
        tracker = SLOTracker(histogram=hist, objective_s=0.25,
                             target=0.9)
        # 10 fast, 10 slow → 50% bad; budget 10% → burn rate 5x.
        for _ in range(10):
            hist.labels("200").observe(0.01)
        for _ in range(10):
            hist.labels("200").observe(2.0)
        out = tracker.record()
        assert out["requestsTotal"] == 20
        assert out["goodTotal"] == 10
        assert out["burnRates"]["5m"] == pytest.approx(5.0)
        # All-good traffic decays the rolling burn toward zero.
        for _ in range(980):
            hist.labels("200").observe(0.01)
        out = tracker.record()
        assert out["burnRates"]["5m"] < 0.6

    def test_health_ready_and_unready(self, handler):
        status, _, body = call(handler, "GET", "/health")
        assert status == 200
        out = json.loads(body)
        assert out["status"] == "ok"
        assert set(out["checks"]) == {"holder", "gossip", "admission",
                                      "disk", "writeReady", "storage"}
        assert out["checks"]["storage"]["ok"] is True
        # A handler with no holder is NOT ready (and says why).
        bare = Handler(None, None)
        status, _, body = call(bare, "GET", "/health")
        assert status == 503
        out = json.loads(body)
        assert out["status"] == "unhealthy"
        assert out["checks"]["holder"]["ok"] is False

    def test_static_membership_stays_ready(self, holder):
        """Static/HTTP clusters have no failure detector —
        node_states() reports peers DOWN by construction, and /health
        must NOT let that drain a healthy cluster behind a load
        balancer."""
        from pilosa_tpu.cluster.topology import Cluster, Node
        cl = Cluster(nodes=[Node("a:1"), Node("b:2"), Node("c:3")])
        assert cl.node_set is None
        ready, checks = HealthChecker(holder=holder,
                                      cluster=cl).check()
        assert ready and checks["gossip"]["ok"]
        assert "static" in checks["gossip"]["detail"]

    def test_admission_saturation_unready(self, holder):
        from pilosa_tpu.sched import AdmissionController
        adm = AdmissionController(concurrency=1, queue_depth=1)
        checker = HealthChecker(holder=holder, admission=adm)
        ready, checks = checker.check()
        assert ready
        # Fill the slot AND the queue: the next arrival would be
        # rejected — the node must stop advertising ready.
        slot = adm.acquire("read")
        t = threading.Thread(target=lambda: adm.acquire("read").release(),
                             daemon=True)
        t.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            snap = adm.snapshot()
            if sum((snap.get("queued") or {}).values()) >= 1:
                break
            time.sleep(0.01)
        ready, checks = checker.check()
        assert not ready and checks["admission"]["ok"] is False
        slot.release()
        t.join(timeout=5)

    def test_status_carries_slo_and_profiler(self, holder):
        from pilosa_tpu.obs.runtime import RuntimeCollector
        prof = ContinuousProfiler(hz=50, ring=64)
        tracker = SLOTracker()
        rc = RuntimeCollector(holder=holder, slo=tracker,
                              profiler=prof)
        snap = rc.collect()
        assert "burnRates" in snap["slo"]
        assert snap["profiler"]["running"] is False


# -- overhead guard -----------------------------------------------------------

class TestOverheadGuard:
    def test_accounting_and_profiler_under_5pct_p50(self, handler,
                                                    holder):
        """Accounting ON + the continuous profiler at its default rate
        must cost <5% on the bench query leg's p50. The profiler runs
        for the WHOLE measurement (its sampling load hits both modes;
        its per-query serving cost is zero by construction) and the
        accounting switch alternates in small interleaved groups, so
        shared-CI scheduler noise lands on both modes equally — the
        p50s then differ only by the increments under test."""
        # A bench-leg-weight query (the suite's config-2 shape scaled
        # down): materializing Union over many rows — real container
        # algebra per query, so the fixed per-query ledger cost is
        # measured against realistic work, not an empty-frame no-op.
        frame = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        rng = np.random.default_rng(7)
        n_rows = 24
        for row in range(n_rows):
            cols = rng.choice(1 << 16, size=2000, replace=False)
            frame.import_bits(np.full(2000, row, np.uint64),
                              cols.astype(np.uint64))
        children = ", ".join(f"Bitmap(rowID={r}, frame=f)"
                             for r in range(n_rows))
        q = f"Union({children})".encode()

        def run_group(samples, n=25):
            for _ in range(n):
                t0 = time.perf_counter()
                status, _, _ = call(handler, "POST", "/index/i/query",
                                    q)
                samples.append(time.perf_counter() - t0)
                assert status == 200

        prof = ContinuousProfiler()  # default rate
        warm: list = []
        run_group(warm, 50)  # warm caches/pools for both modes
        on_samples: list = []
        off_samples: list = []
        prof.start()
        try:
            for _ in range(12):
                accounting.set_enabled(False)
                run_group(off_samples)
                accounting.set_enabled(True)
                run_group(on_samples)
        finally:
            accounting.set_enabled(True)
            prof.stop()
        assert prof.samples_taken >= 1  # it really ran alongside
        on_p50 = sorted(on_samples)[len(on_samples) // 2]
        off_p50 = sorted(off_samples)[len(off_samples) // 2]
        ratio = on_p50 / off_p50
        assert ratio < 1.05, (
            f"accounting+profiler overhead {ratio:.3f}x "
            f"(on p50={on_p50 * 1e3:.3f}ms"
            f" off p50={off_p50 * 1e3:.3f}ms)")
