"""Parity tests for the Pallas serving-path kernels (interpret mode off
TPU) and the mesh dispatch that selects them.

The serving path (mesh.count_expr_fn / topn_exact_fn) runs these fused
kernels on TPU; forcing PILOSA_TPU_PALLAS=interpret exercises the same
dispatch + kernels on the CPU test mesh, proving the Pallas path answers
queries identically to the XLA fusion path (the reference bar:
roaring/assembly_test.go asm-vs-Go parity).
"""

import numpy as np
import pytest

from pilosa_tpu.ops import pallas_kernels as pk
from pilosa_tpu.parallel import mesh as mesh_mod

EXPR = ("or", ("and", ("leaf", 0), ("leaf", 1)),
        ("andnot", ("leaf", 2), ("leaf", 0)))


def _eval(expr, leaves):
    if expr[0] == "leaf":
        return leaves[expr[1]]
    f = {"and": np.bitwise_and, "or": np.bitwise_or,
         "xor": np.bitwise_xor,
         "andnot": lambda a, b: a & ~b}[expr[0]]
    return f(_eval(expr[1], leaves), _eval(expr[2], leaves))


@pytest.fixture
def data():
    rng = np.random.default_rng(7)
    L, S, R, W = 3, 16, 9, 384
    leaves = rng.integers(0, 2**32, size=(L, S, W), dtype=np.uint32)
    rows = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)
    return leaves, rows


class TestExprCountPallas:
    def test_parity(self, data):
        leaves, _ = data
        want = np.bitwise_count(_eval(EXPR, leaves)).sum(axis=-1)
        got = np.asarray(pk.expr_count_rows_pallas(EXPR, leaves,
                                                   interpret=True))
        assert got.tolist() == want.tolist()

    def test_single_leaf(self, data):
        leaves, _ = data
        got = np.asarray(pk.expr_count_rows_pallas(("leaf", 2), leaves,
                                                   interpret=True))
        want = np.bitwise_count(leaves[2]).sum(axis=-1)
        assert got.tolist() == want.tolist()

    def test_unaligned_shapes(self):
        # Rows and words that don't divide the tile sizes must pad
        # losslessly.
        rng = np.random.default_rng(8)
        leaves = rng.integers(0, 2**32, size=(2, 5, 130), dtype=np.uint32)
        expr = ("xor", ("leaf", 0), ("leaf", 1))
        got = np.asarray(pk.expr_count_rows_pallas(expr, leaves,
                                                   interpret=True))
        want = np.bitwise_count(leaves[0] ^ leaves[1]).sum(axis=-1)
        assert got.tolist() == want.tolist()


class TestTopNBlockPallas:
    def test_with_expr(self, data):
        leaves, rows = data
        src = _eval(EXPR, leaves)
        want = np.bitwise_count(rows & src[:, None, :]).sum(axis=-1)
        got = np.asarray(pk.topn_block_count_pallas(EXPR, rows, leaves,
                                                    interpret=True))
        assert got.tolist() == want.tolist()

    def test_plain_popcount(self, data):
        _, rows = data
        S = rows.shape[0]
        got = np.asarray(pk.topn_block_count_pallas(
            None, rows, np.zeros((0, S, 1), np.uint32), interpret=True))
        want = np.bitwise_count(rows).sum(axis=-1)
        assert got.tolist() == want.tolist()


class TestMeshPallasDispatch:
    def test_count_expr_via_pallas(self, data, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "interpret")
        leaves, _ = data
        m = mesh_mod.make_mesh(8)
        want = int(np.bitwise_count(_eval(EXPR, leaves)).sum())
        assert mesh_mod.count_expr(m, EXPR, leaves) == want

    def test_topn_exact_via_pallas(self, data, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "interpret")
        leaves, rows = data
        m = mesh_mod.make_mesh(8)
        src = _eval(EXPR, leaves)
        want = np.bitwise_count(rows & src[:, None, :]) \
            .sum(axis=(0, 2)).tolist()
        assert mesh_mod.topn_exact(m, EXPR, rows, leaves) == want

    def test_topn_filtered_via_pallas(self, data, monkeypatch):
        """The per-slice threshold/Tanimoto pruning program must agree
        with a per-slice host reference when its counts come from the
        Pallas kernels (interpret mode — the compiled-TPU branch)."""
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "interpret")
        leaves, rows = data
        m = mesh_mod.make_mesh(8)
        src = _eval(EXPR, leaves)
        inter = np.bitwise_count(rows & src[:, None, :]).sum(axis=-1)
        rowc = np.bitwise_count(rows).sum(axis=-1)
        srcc = np.bitwise_count(src).sum(axis=-1)[:, None]
        d_rows = mesh_mod.shard_slices(m, rows)
        d_leaves = [mesh_mod.shard_slices(m, leaves[i])
                    for i in range(leaves.shape[0])]
        for threshold, tanimoto in ((1, 0), (3, 0), (10**6, 0),
                                    (1, 5), (1, 50), (1, 99)):
            if tanimoto:
                keep = ((100 * rowc > srcc * tanimoto)
                        & (rowc * tanimoto < srcc * 100)
                        & (inter > 0)
                        & (100 * inter
                           > tanimoto * (rowc + srcc - inter)))
            else:
                keep = (rowc >= threshold) & (inter >= threshold)
            want = np.where(keep, inter, 0).sum(axis=0).tolist()
            got = mesh_mod.topn_filtered_sharded(
                m, EXPR, d_rows, d_leaves,
                threshold=threshold, tanimoto=tanimoto)
            assert got == want, (threshold, tanimoto)

    def test_mode_selection(self, monkeypatch):
        # Default (and "auto", and "0") = XLA: the recorded round-4 A/B
        # (benchmarks/PALLAS_AB.json) has XLA equal-or-faster on 5/6
        # serving shapes; Pallas is an explicit opt-in now.
        monkeypatch.delenv("PILOSA_TPU_PALLAS", raising=False)
        assert pk.pallas_mode("tpu") is None
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
        assert pk.pallas_mode("tpu") is None
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "auto")
        assert pk.pallas_mode("tpu") is None
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "interpret")
        assert pk.pallas_mode("cpu") == "interpret"
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
        assert pk.pallas_mode("tpu") == "compiled"
        assert pk.pallas_mode("cpu") is None
