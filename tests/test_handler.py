"""HTTP handler tests, in-process WSGI with a real or mock executor
(reference handler_test.go: mock Executor seam at handler.go:60-62)."""

import io
import json

import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.proto import internal_pb2 as pb
from pilosa_tpu.server.handler import Handler
from pilosa_tpu.storage.bitmap import Bitmap
from pilosa_tpu.storage.cache import Pair

_PROTOBUF = "application/x-protobuf"


def call(app, method, path, body=b"", content_type="", accept=""):
    """Invoke a WSGI app in-process; returns (status_int, headers, body)."""
    if "?" in path:
        path, _, qs = path.partition("?")
    else:
        qs = ""
    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": path,
        "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(body)),
        "wsgi.input": io.BytesIO(body),
    }
    if content_type:
        environ["CONTENT_TYPE"] = content_type
    if accept:
        environ["HTTP_ACCEPT"] = accept
    out = {}

    def start_response(status, headers):
        out["status"] = int(status.split()[0])
        out["headers"] = dict(headers)

    chunks = app(environ, start_response)
    return out["status"], out["headers"], b"".join(chunks)


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def handler(holder):
    return Handler(holder, Executor(holder, host="local"), host="local")


class MockExecutor:
    def __init__(self, fn):
        self.fn = fn

    def execute(self, index, query, slices, opt):
        return self.fn(index, query, slices, opt)


class TestMeta:
    def test_version(self, handler):
        status, _, body = call(handler, "GET", "/version")
        assert status == 200
        assert "version" in json.loads(body)

    def test_404(self, handler):
        status, _, _ = call(handler, "GET", "/nope")
        assert status == 404

    def test_webui_console(self, handler):
        status, headers, body = call(handler, "GET", "/")
        assert status == 200
        assert headers["Content-Type"].startswith("text/html")
        page = body.decode()
        assert "textarea" in page and "/assets/main.js" in page
        # The console logic (now a static asset) drives the same public
        # API surface as the reference's webui.
        _, _, js = call(handler, "GET", "/assets/main.js")
        script = js.decode()
        for needle in ("/index/", "/query", "/schema", "/status",
                       "/version"):
            assert needle in script, needle

    def test_method_not_allowed(self, handler):
        status, _, _ = call(handler, "GET", "/index/i/query")
        assert status == 405

    def test_schema(self, holder, handler):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        status, _, body = call(handler, "GET", "/schema")
        assert status == 200
        schema = json.loads(body)["indexes"]
        assert schema[0]["name"] == "i"
        assert schema[0]["frames"][0]["name"] == "f"

    def test_slice_max(self, holder, handler):
        holder.create_index_if_not_exists("i")
        status, _, body = call(handler, "GET", "/slices/max")
        assert json.loads(body) == {"maxSlices": {"i": 0}}
        # protobuf negotiation
        status, _, body = call(handler, "GET", "/slices/max",
                               accept=_PROTOBUF)
        assert pb.MaxSlicesResponse.FromString(body).MaxSlices["i"] == 0


class TestIndexCRUD:
    def test_create_get_delete(self, handler):
        status, _, _ = call(handler, "POST", "/index/idx",
                            json.dumps({}).encode())
        assert status == 200
        status, _, body = call(handler, "GET", "/index/idx")
        assert json.loads(body) == {"index": {"name": "idx"}}
        status, _, _ = call(handler, "POST", "/index/idx", b"{}")
        assert status == 409  # conflict
        status, _, _ = call(handler, "DELETE", "/index/idx")
        assert status == 200
        status, _, _ = call(handler, "GET", "/index/idx")
        assert status == 404

    def test_unknown_option_key_rejected(self, handler):
        body = json.dumps({"options": {"bogus": 1}}).encode()
        status, _, resp = call(handler, "POST", "/index/idx", body)
        assert status == 400
        assert b"Unknown key" in resp
        body = json.dumps({"bogus": {}}).encode()
        assert call(handler, "POST", "/index/idx", body)[0] == 400

    def test_create_with_options(self, holder, handler):
        body = json.dumps(
            {"options": {"columnLabel": "cid", "timeQuantum": "YM"}}
        ).encode()
        assert call(handler, "POST", "/index/idx", body)[0] == 200
        idx = holder.index("idx")
        assert idx.column_label == "cid"
        assert idx.time_quantum() == "YM"

    def test_time_quantum_patch(self, holder, handler):
        holder.create_index_if_not_exists("i")
        body = json.dumps({"timeQuantum": "YMD"}).encode()
        assert call(handler, "PATCH", "/index/i/time-quantum",
                    body)[0] == 200
        assert holder.index("i").time_quantum() == "YMD"


class TestFrameCRUD:
    def test_create_delete(self, holder, handler):
        holder.create_index_if_not_exists("i")
        body = json.dumps({"options": {"rowLabel": "rl",
                                       "inverseEnabled": True,
                                       "cacheType": "ranked"}}).encode()
        assert call(handler, "POST", "/index/i/frame/f", body)[0] == 200
        f = holder.frame("i", "f")
        assert f.row_label == "rl" and f.inverse_enabled
        assert call(handler, "POST", "/index/i/frame/f", b"{}")[0] == 409
        assert call(handler, "DELETE", "/index/i/frame/f")[0] == 200
        assert holder.frame("i", "f") is None

    def test_views(self, holder, handler):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f").set_bit("standard", 1, 2)
        status, _, body = call(handler, "GET", "/index/i/frame/f/views")
        assert json.loads(body) == {"views": ["standard"]}


class TestQuery:
    def test_json_query_roundtrip(self, holder, handler):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        status, _, body = call(
            handler, "POST", "/index/i/query",
            b'SetBit(frame="f", rowID=1, columnID=2)')
        assert status == 200
        assert json.loads(body) == {"results": [True]}
        status, _, body = call(handler, "POST", "/index/i/query",
                               b"Bitmap(frame=\"f\", rowID=1)")
        assert json.loads(body) == {
            "results": [{"attrs": {}, "bits": [2]}]}
        status, _, body = call(handler, "POST", "/index/i/query",
                               b"Count(Bitmap(frame=\"f\", rowID=1))")
        assert json.loads(body) == {"results": [1]}

    def test_parse_error_400(self, holder, handler):
        holder.create_index_if_not_exists("i")
        status, _, body = call(handler, "POST", "/index/i/query", b"((")
        assert status == 400
        assert "error" in json.loads(body)

    def test_protobuf_query(self, holder, handler):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f").set_bit("standard", 7, 9)
        req = pb.QueryRequest(Query='Bitmap(frame="f", rowID=7)')
        status, _, body = call(handler, "POST", "/index/i/query",
                               req.SerializeToString(),
                               content_type=_PROTOBUF, accept=_PROTOBUF)
        assert status == 200
        resp = pb.QueryResponse.FromString(body)
        assert list(resp.Results[0].Bitmap.Bits) == [9]

    def test_mock_executor_seam(self, holder):
        seen = {}

        def fn(index, query, slices, opt):
            seen["args"] = (index, [c.name for c in query.calls], slices,
                            opt.remote)
            return [[Pair(5, 10)]]

        h = Handler(holder, MockExecutor(fn), host="local")
        req = pb.QueryRequest(Query="TopN(frame=\"f\", n=2)",
                              Slices=[0, 1], Remote=True)
        status, _, body = call(h, "POST", "/index/i/query",
                               req.SerializeToString(),
                               content_type=_PROTOBUF, accept=_PROTOBUF)
        assert status == 200
        assert seen["args"] == ("i", ["TopN"], [0, 1], True)
        resp = pb.QueryResponse.FromString(body)
        assert resp.Results[0].Pairs[0].Key == 5

    def test_invalid_slice_argument(self, holder, handler):
        # handler_test.go:203-212: ?slices=a,b → 400 JSON error object.
        holder.create_index_if_not_exists("i")
        status, _, body = call(handler, "POST",
                               "/index/i/query?slices=a,b",
                               b'Bitmap(frame="f", rowID=1)')
        assert status == 400
        assert json.loads(body) == {"error": "invalid slice argument"}

    def test_executor_error_json_and_protobuf(self, holder):
        # handler_test.go:447-484: executor failures surface as 500
        # with {"error": msg} JSON, or QueryResponse.Err as protobuf.
        def boom(index, query, slices, opt):
            raise RuntimeError("marker")

        h = Handler(holder, MockExecutor(boom), host="local")
        holder.create_index_if_not_exists("i")
        status, _, body = call(h, "POST", "/index/i/query",
                               b'Bitmap(frame="f", rowID=1)')
        assert status == 500
        assert json.loads(body) == {"error": "marker"}
        status, _, body = call(h, "POST", "/index/i/query",
                               b'TopN(frame="f", n=2)',
                               accept=_PROTOBUF)
        assert status == 500
        assert pb.QueryResponse.FromString(body).Err == "marker"

    def test_query_method_not_allowed(self, holder, handler):
        # handler_test.go:486-493.
        holder.create_index_if_not_exists("i")
        status, _, _ = call(handler, "GET", "/index/i/query")
        assert status == 405

    def test_column_attrs_join(self, holder, handler):
        idx = holder.create_index_if_not_exists("i")
        idx.create_frame_if_not_exists("f").set_bit("standard", 1, 3)
        idx.column_attr_store.set_attrs(3, {"name": "three"})
        status, _, body = call(
            handler, "POST", "/index/i/query?columnAttrs=true",
            b"Bitmap(frame=\"f\", rowID=1)")
        out = json.loads(body)
        assert out["columnAttrs"] == [{"id": 3,
                                       "attrs": {"name": "three"}}]


class TestImportExport:
    def test_import_requires_protobuf(self, handler):
        assert call(handler, "POST", "/import", b"x")[0] == 415

    def test_import_and_export(self, holder, handler):
        holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        req = pb.ImportRequest(Index="i", Frame="f", Slice=0,
                               RowIDs=[1, 1, 2], ColumnIDs=[3, 4, 5])
        status, _, _ = call(handler, "POST", "/import",
                            req.SerializeToString(),
                            content_type=_PROTOBUF, accept=_PROTOBUF)
        assert status == 200
        status, _, body = call(
            handler, "GET",
            "/export?index=i&frame=f&view=standard&slice=0",
            accept="text/csv")
        assert status == 200
        assert body.decode().splitlines() == ["1,3", "1,4", "2,5"]


class TestFragmentEndpoints:
    def _setup(self, holder):
        f = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        f.set_bit("standard", 1, 2)
        f.set_bit("standard", 250, 9)
        return f

    def test_blocks(self, holder, handler):
        self._setup(holder)
        status, _, body = call(
            handler, "GET",
            "/fragment/blocks?index=i&frame=f&view=standard&slice=0")
        blocks = json.loads(body)["blocks"]
        assert [b["id"] for b in blocks] == [0, 2]

    def test_block_data(self, holder, handler):
        self._setup(holder)
        req = pb.BlockDataRequest(Index="i", Frame="f", View="standard",
                                  Slice=0, Block=2)
        status, _, body = call(handler, "GET", "/fragment/block/data",
                               req.SerializeToString(),
                               content_type=_PROTOBUF)
        resp = pb.BlockDataResponse.FromString(body)
        assert list(resp.RowIDs) == [250]
        assert list(resp.ColumnIDs) == [9]

    def test_backup_restore_roundtrip(self, holder, handler, tmp_path):
        self._setup(holder)
        status, _, tarball = call(
            handler, "GET",
            "/fragment/data?index=i&frame=f&view=standard&slice=0")
        assert status == 200

        h2 = Holder(str(tmp_path / "data2"))
        h2.open()
        try:
            h2.create_index_if_not_exists("i").create_frame_if_not_exists(
                "f")
            handler2 = Handler(h2, Executor(h2, host="x"), host="x")
            status, _, _ = call(
                handler2, "POST",
                "/fragment/data?index=i&frame=f&view=standard&slice=0",
                tarball)
            assert status == 200
            frag = h2.fragment("i", "f", "standard", 0)
            assert frag.row(1).count() == 1
            assert frag.row(250).count() == 1
        finally:
            h2.close()

    def test_attr_diff(self, holder, handler):
        idx = holder.create_index_if_not_exists("i")
        idx.column_attr_store.set_attrs(5, {"x": 1})
        status, _, body = call(handler, "POST", "/index/i/attr/diff",
                               json.dumps({"blocks": []}).encode())
        assert status == 200
        assert json.loads(body)["attrs"] == {"5": {"x": 1}}


class TestExpvar:
    def test_device_observability_counters(self, handler):
        status, _, body = call(handler, "GET", "/debug/vars")
        assert status == 200
        snap = json.loads(body)
        cache = snap["deviceBlockCache"]
        assert {"entries", "usedBytes", "budgetBytes", "hits",
                "misses", "evictions"} <= set(cache)
        assert snap["deviceFallback"] == 0


class TestWebUIAssets:
    def test_assets_served_with_content_types(self, handler):
        for name, ctype, marker in (
                ("main.js", "application/javascript", b"refreshStatus"),
                ("style.css", "text/css", b"--accent"),
                ("index.html", "text/html", b"pane-schema")):
            status, headers, body = call(handler, "GET",
                                         f"/assets/{name}")
            assert status == 200, name
            assert ctype in headers["Content-Type"], name
            assert marker in body, name

    def test_assets_unknown_and_traversal_404(self, handler):
        for path in ("/assets/nope.js", "/assets/.hidden"):
            assert call(handler, "GET", path)[0] == 404, path
        # a literal ../ segment cannot even match the route pattern
        from pilosa_tpu.server.webui import asset
        assert asset("../webui.py") is None
        assert asset("..\\webui.py") is None
