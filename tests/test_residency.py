"""Budgeted HBM residency: the process-wide device block cache.

SURVEY §7 hard part 2: 50k cached rows × many fragments exceed HBM, so
device blocks live in one budgeted LRU (parallel.residency) keyed by
fragment (uid, generation) — repeat queries reuse uploads, writes
invalidate by key, the byte budget bounds total HBM.
"""

import numpy as np
import pytest

from pilosa_tpu.parallel import residency
from pilosa_tpu.parallel.residency import DeviceBlockCache


def _arr(n_bytes: int):
    import jax
    return jax.device_put(np.zeros(n_bytes // 4, dtype=np.uint32))


class TestDeviceBlockCache:
    def test_hit_returns_same_array(self):
        c = DeviceBlockCache(budget_bytes=1 << 20)
        a = c.get_or_build(("k",), lambda: _arr(1024))
        b = c.get_or_build(("k",), lambda: pytest.fail("rebuilt on hit"))
        assert a is b
        assert c.hits == 1 and c.misses == 1

    def test_budget_evicts_lru(self):
        c = DeviceBlockCache(budget_bytes=4096)
        c.get_or_build(("a",), lambda: _arr(2048))
        c.get_or_build(("b",), lambda: _arr(2048))
        c.get_or_build(("a",), lambda: pytest.fail("a evicted early"))
        c.get_or_build(("c",), lambda: _arr(2048))  # evicts b (LRU)
        assert c.evictions == 1
        assert c.used_bytes <= 4096
        rebuilt = []
        c.get_or_build(("b",), lambda: rebuilt.append(1) or _arr(2048))
        assert rebuilt  # b was the evicted one

    def test_oversize_entry_not_cached(self):
        c = DeviceBlockCache(budget_bytes=1024)
        c.get_or_build(("small",), lambda: _arr(512))
        c.get_or_build(("big",), lambda: _arr(4096))
        assert c.used_bytes == 512  # big stayed one-shot
        c.get_or_build(("small",), lambda: pytest.fail("small evicted"))

    def test_snapshot(self):
        c = DeviceBlockCache(budget_bytes=1 << 20)
        c.get_or_build(("k",), lambda: _arr(1024))
        snap = c.snapshot()
        assert snap["entries"] == 1 and snap["usedBytes"] == 1024
        assert snap["misses"] == 1


class TestFragmentResidency:
    def test_block_cached_and_generation_invalidates(self, tmp_path):
        from pilosa_tpu.storage.fragment import Fragment
        frag = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        frag.open()
        try:
            for r in range(4):
                for col in range(r + 1):
                    frag.set_bit(r, col)
            cache = residency.device_cache()
            m0 = cache.misses
            b1 = frag.device.block(frag.storage, (0, 1, 2, 3))
            b2 = frag.device.block(frag.storage, (0, 1, 2, 3))
            assert b1 is b2
            assert cache.misses == m0 + 1
            frag.set_bit(0, 100)  # bumps generation
            b3 = frag.device.block(frag.storage, (0, 1, 2, 3))
            assert b3 is not b1
            assert np.asarray(b3)[0].sum() != np.asarray(b1)[0].sum()
        finally:
            frag.close()

    def test_uid_unique_across_reopen(self, tmp_path):
        from pilosa_tpu.storage.fragment import Fragment
        path = str(tmp_path / "frag")
        frag = Fragment(path, "i", "f", "standard", 0)
        frag.open()
        uid1 = frag.device.uid
        frag.close()
        frag = Fragment(path, "i", "f", "standard", 0)
        frag.open()
        assert frag.device.uid != uid1
        frag.close()


class TestExecutorResidency:
    @pytest.fixture
    def holder_exec(self, tmp_path):
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        holder = Holder(str(tmp_path))
        holder.open()
        idx = holder.create_index_if_not_exists("i")
        frame = idx.create_frame_if_not_exists("f")
        from pilosa_tpu import SLICE_WIDTH
        for s in range(8):
            for r in (1, 2):
                for j in range(3 - r + 1):
                    frame.set_bit("standard", r, s * SLICE_WIDTH + j)
        ex = Executor(holder, host="h", mesh_min_slices=1)
        yield holder, ex
        holder.close()

    def test_repeat_count_reuses_device_blocks(self, holder_exec):
        holder, ex = holder_exec
        cache = residency.device_cache()
        q = "Count(Intersect(Bitmap(frame=f, rowID=1)," \
            " Bitmap(frame=f, rowID=2)))"
        first = ex.execute("i", q)[0]
        misses_after_first = cache.misses
        again = ex.execute("i", q)[0]
        assert again == first == 8 * 2  # rows 1∩2 share 2 cols/slice
        assert cache.misses == misses_after_first  # no re-upload
        assert ex.device_fallbacks == 0

    def test_repeat_topn_reuses_device_blocks(self, holder_exec):
        holder, ex = holder_exec
        cache = residency.device_cache()
        q = "TopN(Bitmap(frame=f, rowID=1), frame=f, ids=[1, 2])"
        first = ex.execute("i", q)[0]
        misses_after_first = cache.misses
        again = ex.execute("i", q)[0]
        assert [(p.id, p.count) for p in first] == \
            [(p.id, p.count) for p in again] == [(1, 24), (2, 16)]
        assert cache.misses == misses_after_first
        assert ex.device_fallbacks == 0

    def test_write_invalidates_leaf_entry(self, holder_exec):
        holder, ex = holder_exec
        q = "Count(Bitmap(frame=f, rowID=1))"
        assert ex.execute("i", q)[0] == 24
        ex.execute("i", "SetBit(frame=f, rowID=1, columnID=500)")
        assert ex.execute("i", q)[0] == 25  # fresh generation → re-pack
        assert ex.device_fallbacks == 0
