"""Distributed tracing on a REAL 2-node gossip cluster (replicas=1, so
a cluster-spanning query MUST fan out): one query id yields, via
``GET /debug/traces/{id}`` on the coordinator, a single Chrome
trace-event JSON whose spans cover parse → admission → fan-out RPC →
the REMOTE node's executor leg → merge — i.e. the peer's child spans
were piggybacked on the internal response and stitched under the
coordinator's trace id."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from podenv import cpu_env, free_port, wait_up  # noqa: E402

from pilosa_tpu import SLICE_WIDTH  # noqa: E402


def _post(host, path, body=b"", timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=timeout)


def _get_json(host, path, timeout=10):
    with urllib.request.urlopen(f"http://{host}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture
def cluster(tmp_path):
    """Two gossip-joined nodes with bits spanning 4 slices and
    tracing ENABLED via env (PILOSA_TRACE_ENABLED — the config-load
    path the server actually ships with)."""
    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    hosts = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    procs, logs = [], []

    def spawn(name, port, internal, seed=""):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"
        env["PILOSA_TPU_WARMUP"] = "0"
        env["PILOSA_TRACE_ENABLED"] = "1"
        # These tests assert on the SPANS OF A FAN-OUT (stitched
        # coordinator + remote legs); the coordinator hot-query
        # result cache would serve the repeated convergence query
        # from cache — correct results, no remote legs to stitch —
        # so pin it off (distributed fast paths have their own
        # suite, test_distributed_fastpath.py).
        env["PILOSA_QUERY_CLUSTER_CACHE_ENTRIES"] = "0"
        # Slow log at ~0: every finished query's ledger is retained,
        # so the cost-tree test can read the REMOTE node's own ledger
        # after the fact and compare it to the stitched child.
        env["PILOSA_QUERY_SLOW_THRESHOLD"] = "1us"
        log = open(tmp_path / f"{name}.log", "a")
        logs.append(log)
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", str(d), "-b", f"127.0.0.1:{port}",
                "--cluster.type", "gossip",
                "--cluster.hosts", hosts,
                "--cluster.replicas", "1",
                "--cluster.internal-port", str(internal),
                "--anti-entropy.interval", "300s"]
        if seed:
            argv += ["--cluster.gossip-seed", seed]
        p = subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                             cwd=os.path.dirname(_HERE))
        procs.append(p)
        wait_up(f"127.0.0.1:{port}")
        return f"127.0.0.1:{port}"

    host_a = spawn("a", pa, ga)
    host_b = spawn("b", pb, gb, seed=f"127.0.0.1:{ga}")
    _post(host_a, "/index/tr", b"{}")
    _post(host_a, "/index/tr/frame/f", b"{}")

    import numpy as np

    from pilosa_tpu.cluster.client import Client
    client = Client(host_a)
    cols = np.arange(0, 4 * SLICE_WIDTH,
                     SLICE_WIDTH // 8).astype(np.uint64)
    client.import_arrays("tr", "f", np.ones(len(cols), np.uint64),
                         cols)

    # Wait until A answers the full count (slice knowledge of B's
    # slices arrives via broadcast/gossip) — the query that warms
    # this also proves fan-out works.
    deadline = time.time() + 30
    got = None
    while time.time() < deadline:
        with _post(host_a, "/index/tr/query",
                   b'Count(Bitmap(frame="f", rowID=1))') as r:
            got = json.loads(r.read())["results"][0]
        if got == len(cols):
            break
        time.sleep(0.3)
    assert got == len(cols), got

    yield {"a": host_a, "b": host_b, "procs": procs,
           "n_bits": len(cols)}

    for p in procs:
        try:
            p.send_signal(signal.SIGINT)
        except OSError:
            pass
    for p in procs:
        try:
            p.wait(timeout=20)
        except subprocess.TimeoutExpired:
            p.kill()
    for log in logs:
        log.close()


def test_one_trace_id_spans_coordinator_and_remote_legs(cluster):
    host_a, host_b = cluster["a"], cluster["b"]

    with _post(host_a, "/index/tr/query",
               b'Count(Bitmap(frame="f", rowID=1))') as r:
        qid = r.headers["X-Pilosa-Query-Id"]
        assert json.loads(r.read())["results"][0] == cluster["n_bits"]
    assert qid

    # The coordinator's ring lists the trace under the query id.
    listing = _get_json(host_a, "/debug/traces")
    assert listing["enabled"] is True
    entry = next(t for t in listing["traces"] if t["id"] == qid)
    # Stitched: spans from BOTH nodes under one trace id.
    assert set(entry["nodes"]) == {host_a, host_b}, entry

    chrome = _get_json(host_a, f"/debug/traces/{qid}")
    assert chrome["otherData"]["traceId"] == qid
    events = chrome["traceEvents"]
    names = {e["name"] for e in events if e["name"] != "process_name"}
    # The acceptance chain: parse → admission → fan-out rpc → remote
    # executor leg → merge (all under ONE trace id).
    assert {"parse", "admission", "execute", "map_reduce", "rpc",
            "leg", "merge"} <= names, names

    # Each node renders as its own perfetto process; the remote leg's
    # spans carry the peer's pid.
    pid_names = {e["pid"]: e["args"]["name"] for e in events
                 if e["name"] == "process_name"}
    assert set(pid_names.values()) == {host_a, host_b}
    pid_of = {v: k for k, v in pid_names.items()}
    remote_spans = {e["name"] for e in events
                    if e["name"] != "process_name"
                    and e["pid"] == pid_of[host_b]}
    # The peer executed its leg: its own execute/map_reduce spans
    # arrived via the piggyback header.
    assert {"execute", "map_reduce"} <= remote_spans, remote_spans
    # And every event is a well-formed complete event.
    for e in events:
        if e["name"] != "process_name":
            assert e["ph"] == "X" and e["dur"] >= 1 and e["ts"] > 0

    # The remote node also kept its own child trace locally.
    listing_b = _get_json(host_b, "/debug/traces")
    assert any(t["id"] == qid for t in listing_b["traces"])


def test_profile_cost_tree_includes_remote_ledger(cluster):
    """?profile=1 on the coordinator returns ONE merged cost tree
    whose remote-leg child is the REMOTE node's own ledger: its
    container-op counts must equal what that node recorded for its leg
    (read back from its slow log, armed at ~0 threshold), and the
    root must carry the RPC bytes of the fan-out leg to that peer."""
    host_a, host_b = cluster["a"], cluster["b"]

    # Materializing Intersect: every slice leg does real roaring
    # container algebra on whichever node owns it.
    q = (b'Intersect(Bitmap(frame="f", rowID=1),'
         b' Bitmap(frame="f", rowID=1))')
    with _post(host_a, "/index/tr/query?profile=1", q) as r:
        qid = r.headers["X-Pilosa-Query-Id"]
        stats_hdr = r.headers["X-Pilosa-Stats"]
        resp = json.loads(r.read())
    assert qid

    tree = resp["profile"]
    assert tree["node"] == host_a
    assert {"parse", "admission", "execute"} <= set(tree["stages"])
    # The coordinator recorded the RPC leg to the peer: request and
    # response bytes, per peer host.
    assert host_b in tree["rpc"], tree
    assert tree["rpc"][host_b]["bytesOut"] > 0
    assert tree["rpc"][host_b]["bytesIn"] > 0
    assert tree["rpc"][host_b]["calls"] >= 1
    # The remote leg's ledger arrived as a stitched child.
    children = [c for c in tree.get("children", [])
                if c["node"] == host_b]
    assert children, tree
    child = children[0]
    child_ops = child["containerOps"]
    assert sum(child_ops.values()) >= 1, child
    # The child IS the remote node's own accounting: node B's slow log
    # retained its leg's ledger under the same query id — totals must
    # match exactly.
    slow_b = _get_json(host_b, "/debug/queries/slow")["slow"]
    leg = [e for e in slow_b if e["id"] == qid and e["remote"]]
    assert leg, slow_b
    assert leg[-1]["cost"]["containerOps"] == sum(child_ops.values())
    assert leg[-1]["cost"]["wordsScanned"] == child["wordsScanned"]

    # The compact roll-up header agrees with the inline tree.
    stats = json.loads(stats_hdr)
    assert stats["rpcBytesOut"] == tree["rpc"][host_b]["bytesOut"]
    assert stats["remoteLegs"] == len(tree["children"])

    # And the coordinator's own slow-log entry carries the roll-up
    # (cost visibility without ?profile=1).
    slow_a = _get_json(host_a, "/debug/queries/slow")["slow"]
    entry = [e for e in slow_a if e["id"] == qid and not e["remote"]]
    assert entry and "cost" in entry[-1]
