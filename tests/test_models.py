"""Schema hierarchy + attr store tests (reference index_test.go,
frame_test.go, view_test.go, holder_test.go, attr_test.go)."""

import datetime as dt
import os

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.errors import (FrameExistsError, IndexExistsError,
                               PilosaError)
from pilosa_tpu.models.frame import Frame, FrameOptions
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.models.index import Index, IndexOptions
from pilosa_tpu.models.view import VIEW_INVERSE, VIEW_STANDARD
from pilosa_tpu.storage.attrs import AttrStore, diff_blocks


class TestAttrStore:
    @pytest.fixture
    def store(self, tmp_path):
        s = AttrStore(str(tmp_path / "attrs"))
        s.open()
        yield s
        s.close()

    def test_set_get_merge(self, store):
        store.set_attrs(1, {"a": "x", "n": 5})
        store.set_attrs(1, {"b": True, "f": 1.5})
        assert store.attrs(1) == {"a": "x", "n": 5, "b": True, "f": 1.5}
        store.set_attrs(1, {"a": None})      # delete key
        assert store.attrs(1) == {"n": 5, "b": True, "f": 1.5}
        assert store.attrs(999) == {}

    def test_persistence(self, tmp_path):
        s = AttrStore(str(tmp_path / "a"))
        s.open()
        s.set_attrs(7, {"k": "v"})
        s.close()
        s2 = AttrStore(str(tmp_path / "a"))
        s2.open()
        assert s2.attrs(7) == {"k": "v"}
        s2.close()

    def test_bulk_and_blocks(self, store):
        store.set_bulk_attrs({1: {"x": 1}, 150: {"y": 2}, 101: {"z": 3}})
        blocks = store.blocks()
        assert [b[0] for b in blocks] == [0, 1]
        assert store.block_data(1) == {150: {"y": 2}, 101: {"z": 3}}

    def test_blocks_diff(self, store):
        store.set_attrs(1, {"a": 1})
        store.set_attrs(100, {"b": 2})
        other = AttrStore(store.path + "2")
        other.open()
        other.set_attrs(1, {"a": 1})
        try:
            ids = diff_blocks(store.blocks(), other.blocks())
            assert ids == [1]  # block 0 same, block 1 missing in other
        finally:
            other.close()


class TestFrame:
    @pytest.fixture
    def frame(self, tmp_path):
        f = Frame(str(tmp_path / "i" / "f"), "i", "f")
        f.open()
        yield f
        f.close()

    def test_set_get_bit(self, frame):
        assert frame.set_bit(VIEW_STANDARD, 3, 10)
        v = frame.view(VIEW_STANDARD)
        assert v.fragment(0).row(3).count() == 1
        assert frame.clear_bit(VIEW_STANDARD, 3, 10)

    def test_meta_persists(self, tmp_path):
        opts = FrameOptions(row_label="rl", inverse_enabled=True,
                            cache_type="ranked", cache_size=123,
                            time_quantum="YM")
        f = Frame(str(tmp_path / "i" / "f"), "i", "f", options=opts)
        f.open()
        f.close()
        f2 = Frame(str(tmp_path / "i" / "f"), "i", "f")
        f2.open()
        try:
            assert f2.options == opts
        finally:
            f2.close()

    def test_time_views_fan_out(self, tmp_path):
        f = Frame(str(tmp_path / "i" / "f"), "i", "f",
                  options=FrameOptions(time_quantum="YMDH"))
        f.open()
        try:
            t = dt.datetime(2017, 1, 2, 3)
            f.set_bit(VIEW_STANDARD, 1, 2, t)
            names = set(f.views)
            assert names == {"standard", "standard_2017", "standard_201701",
                             "standard_20170102", "standard_2017010203"}
            for n in names:
                assert f.view(n).fragment(0).row(1).count() == 1
        finally:
            f.close()

    def test_inverse_requires_flag(self, frame):
        with pytest.raises(PilosaError):
            frame.set_bit(VIEW_INVERSE, 1, 2)

    def test_import_with_inverse_and_time(self, tmp_path):
        f = Frame(str(tmp_path / "i" / "f"), "i", "f",
                  options=FrameOptions(inverse_enabled=True,
                                       time_quantum="Y"))
        f.open()
        try:
            t = dt.datetime(2018, 6, 1)
            f.import_bits([5], [9], [t])
            assert f.view("standard").fragment(0).row(5).count() == 1
            assert f.view("standard_2018").fragment(0).row(5).count() == 1
            # inverse transposed: row 9, col 5
            assert list(map(int, f.view("inverse").fragment(0)
                            .row(9).bits())) == [5]
        finally:
            f.close()

    def test_import_mixed_timestamps_multislice(self, tmp_path):
        """The vectorized no-timestamp lane and the per-bit time-view
        lane must compose: one import with plain and timestamped bits
        across slices, inverse enabled (frame.go:538-573)."""
        f = Frame(str(tmp_path / "i" / "f"), "i", "f",
                  options=FrameOptions(inverse_enabled=True,
                                       time_quantum="YM"))
        f.open()
        try:
            f.import_bits(
                [1, 2, 3], [5, SLICE_WIDTH, 7],
                [None, dt.datetime(2017, 3, 4, 10, 30),
                 dt.datetime(2018, 1, 1)])
            assert f.views.keys() >= {
                "standard", "inverse", "standard_2017",
                "standard_201703", "inverse_2017", "inverse_2018"}
            std = f.view("standard")
            assert std.fragment(0).row(1).count() == 1
            assert std.fragment(0).row(3).count() == 1
            assert std.fragment(1).row(2).count() == 1  # plain view too
            inv = f.view("inverse")
            assert inv.fragment(0).row(5).count() == 1
            assert inv.fragment(0).row(SLICE_WIDTH).count() == 1
            assert f.view("standard_201703").fragment(1) \
                    .row(2).count() == 1
            assert f.view("inverse_2018").fragment(0).row(7).count() == 1
        finally:
            f.close()

    def test_max_slice(self, frame):
        frame.set_bit(VIEW_STANDARD, 0, 3 * SLICE_WIDTH + 1)
        assert frame.max_slice() == 3


class TestIndex:
    def test_create_frame_defaults_quantum(self, tmp_path):
        idx = Index(str(tmp_path / "i"), "i",
                    options=IndexOptions(time_quantum="YM"))
        idx.open()
        try:
            f = idx.create_frame("f")
            assert f.time_quantum() == "YM"
            with pytest.raises(FrameExistsError):
                idx.create_frame("f")
        finally:
            idx.close()

    def test_invalid_names(self, tmp_path):
        with pytest.raises(PilosaError):
            Index(str(tmp_path / "X"), "UPPER")
        idx = Index(str(tmp_path / "i"), "i")
        idx.open()
        try:
            with pytest.raises(PilosaError):
                idx.create_frame("Bad Name")
        finally:
            idx.close()

    def test_remote_max_slice(self, tmp_path):
        idx = Index(str(tmp_path / "i"), "i")
        idx.open()
        try:
            assert idx.max_slice() == 0
            idx.set_remote_max_slice(7)
            assert idx.max_slice() == 7
        finally:
            idx.close()


class TestHolder:
    def test_open_scans_and_navigates(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("myidx")
        f = idx.create_frame("myframe")
        f.set_bit(VIEW_STANDARD, 1, 2)
        h.flush_caches()
        h.close()

        h2 = Holder(str(tmp_path / "data"))
        h2.open()
        try:
            frag = h2.fragment("myidx", "myframe", VIEW_STANDARD, 0)
            assert frag is not None
            assert frag.row(1).count() == 1
            assert h2.schema() == [{
                "name": "myidx",
                "frames": [{"name": "myframe",
                            "views": [{"name": "standard"}]}],
            }]
            assert h2.max_slices() == {"myidx": 0}
        finally:
            h2.close()

    def test_index_exists(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        try:
            h.create_index("a")
            with pytest.raises(IndexExistsError):
                h.create_index("a")
            h.delete_index("a")
            assert h.index("a") is None
            assert not os.path.exists(h.index_path("a"))
        finally:
            h.close()

    def test_create_slice_announcements(self, tmp_path):
        events = []
        h = Holder(str(tmp_path / "data"),
                   on_create_slice=lambda i, s, inv: events.append(
                       (i, s, inv)))
        h.open()
        try:
            idx = h.create_index("i")
            f = idx.create_frame("f")
            f.set_bit(VIEW_STANDARD, 0, 1)              # slice 0: no announce
            f.set_bit(VIEW_STANDARD, 0, SLICE_WIDTH)    # slice 1: announce
            assert events == [("i", 1, False)]
        finally:
            h.close()
